"""Checkpoint tests: per-shard save/load, filename convention, retention,
resume state, and mesh-independence (save at TP=4, load for TP=2).

Reference behaviours mirrored: filename metadata + regex discovery
(`/root/reference/train.py:123,129`, `test.py:94-95`), retention pruning
(`train.py:127-132`); fixed here: optimizer/step state is saved so training
can resume (the reference cannot — SURVEY §5.4).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np

from distributed_pytorch_from_scratch_tpu.config import MeshConfig, ModelConfig
from distributed_pytorch_from_scratch_tpu.models.transformer import Transformer
from distributed_pytorch_from_scratch_tpu.runtime.mesh import make_mesh
from distributed_pytorch_from_scratch_tpu.training.checkpoint import (
    latest_step, list_checkpoints, load_checkpoint, save_checkpoint,
    validate_checkpoint)
from distributed_pytorch_from_scratch_tpu.training.optim import init_adam_state

CFG = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=8, num_layers=2,
                  vocab_size=64, maxlen=16)


def _tree_equal(a, b):
    jax.tree.map(lambda x, y: np.testing.assert_array_equal(
        np.asarray(x), np.asarray(y)), a, b)


def test_save_load_roundtrip(tmp_path):
    model = Transformer(CFG, tp_size=4)
    params = model.init(jax.random.key(0))
    opt = init_adam_state(params)
    opt = opt._replace(step=jnp.asarray(123, jnp.int32),
                       mu=jax.tree.map(lambda p: p + 1.0, opt.mu))

    paths = save_checkpoint(str(tmp_path), 123, 2.5, params, model.specs(),
                            tp_size=4, opt_state=opt)
    assert len(paths) == 4
    assert os.path.basename(paths[0]) == "tprank-0_iter-123_loss-2.5000.npz"

    loaded, opt_loaded, step = load_checkpoint(str(tmp_path), 123, params,
                                               model.specs(), with_opt=True)
    assert step == 123
    _tree_equal(loaded, params)
    _tree_equal(opt_loaded.mu, opt.mu)
    assert int(opt_loaded.step) == 123


def test_shards_are_actual_slices(tmp_path):
    """Each rank file must hold only its slice (not the full weight) — the
    same per-rank layout as the reference's per-process state_dicts."""
    model = Transformer(CFG, tp_size=4)
    params = model.init(jax.random.key(1))
    save_checkpoint(str(tmp_path), 1, 1.0, params, model.specs(), tp_size=4)
    shard0 = np.load(os.path.join(tmp_path, "tprank-0_iter-1_loss-1.0000.npz"))
    # embedding is P('tp', None): vocab 64 / 4 = 16 rows per shard
    emb = shard0["param/embedding/weight"]
    assert emb.shape == (16, CFG.attn_dim)
    np.testing.assert_array_equal(emb, np.asarray(params["embedding"]["weight"])[:16])
    # norm scale is replicated: full size in every shard
    assert shard0["param/norm/scale"].shape == (CFG.attn_dim,)


def test_async_save_matches_sync_and_survives_donation(tmp_path):
    """async_write=True must produce byte-identical files to the sync path,
    and the on-device snapshot must keep the write valid even when the
    caller's buffers are donated away immediately after scheduling (the
    train loop's donate_argnums pattern, training/train_step.py)."""
    model = Transformer(CFG, tp_size=2)
    params = model.init(jax.random.key(4))
    opt = init_adam_state(params)

    sync_dir, async_dir = str(tmp_path / "sync"), str(tmp_path / "async")
    save_checkpoint(sync_dir, 7, 1.5, params, model.specs(), tp_size=2,
                    opt_state=opt)
    handle = save_checkpoint(async_dir, 7, 1.5, params, model.specs(),
                             tp_size=2, opt_state=opt, async_write=True)
    # donate the original buffers away while the write may still be running
    bump = jax.jit(lambda t: jax.tree.map(lambda x: x + 1.0, t),
                   donate_argnums=(0,))
    params = bump(params)

    paths = handle.join()
    assert handle.step == 7
    assert [os.path.basename(p) for p in paths] == [
        "tprank-0_iter-7_loss-1.5000.npz", "tprank-1_iter-7_loss-1.5000.npz"]
    for rank in range(2):
        a = np.load(os.path.join(async_dir, f"tprank-{rank}_iter-7_loss-1.5000.npz"))
        s = np.load(os.path.join(sync_dir, f"tprank-{rank}_iter-7_loss-1.5000.npz"))
        assert sorted(a.files) == sorted(s.files)
        for key in a.files:
            np.testing.assert_array_equal(a[key], s[key])


def test_missing_rank_shard_refused_early(tmp_path):
    """An incomplete shard set (one rank file lost in transfer) must fail
    BEFORE assembly with the missing-rank list — it used to surface as a
    cryptic KeyError mid-assemble in find_rank_shards consumers. The
    serving loader (serving/serve.py) and interop validate through the
    same `validate_checkpoint`."""
    import pytest

    model = Transformer(CFG, tp_size=4)
    params = model.init(jax.random.key(6))
    save_checkpoint(str(tmp_path), 9, 1.0, params, model.specs(), tp_size=4)

    # a complete set validates and reports its tp_size
    tp_size, rank_files = validate_checkpoint(str(tmp_path), 9)
    assert tp_size == 4 and sorted(rank_files) == [0, 1, 2, 3]

    os.remove(os.path.join(tmp_path, "tprank-2_iter-9_loss-1.0000.npz"))
    with pytest.raises(FileNotFoundError, match=r"rank\(s\) \[2\]"):
        validate_checkpoint(str(tmp_path), 9)
    with pytest.raises(FileNotFoundError, match=r"rank\(s\) \[2\]"):
        load_checkpoint(str(tmp_path), 9, params, model.specs())

    # rank 0 missing too: the metadata is read from ANY surviving shard
    os.remove(os.path.join(tmp_path, "tprank-0_iter-9_loss-1.0000.npz"))
    with pytest.raises(FileNotFoundError, match=r"rank\(s\) \[0, 2\]"):
        validate_checkpoint(str(tmp_path), 9)

    # pth (interop) fallback: no metadata, rank span catches the hole
    for r in (0, 1, 3):
        open(os.path.join(tmp_path, f"tprank-{r}_iter-3_loss-1.0.pth"),
             "wb").close()
    with pytest.raises(FileNotFoundError, match=r"rank\(s\) \[2\]"):
        validate_checkpoint(str(tmp_path), 3, ext="pth")


def test_retention_pruning(tmp_path):
    model = Transformer(CFG, tp_size=2)
    params = model.init(jax.random.key(2))
    for it in (10, 20, 30, 40):
        save_checkpoint(str(tmp_path), it, 1.0, params, model.specs(),
                        tp_size=2, reserve_last_n=2)
    kept = [it for it, _ in list_checkpoints(str(tmp_path), rank=0)]
    assert kept == [30, 40]
    assert latest_step(str(tmp_path)) == 40


def test_mesh_independent_reload(tmp_path):
    """Save at TP=4, reassemble, and use for a TP=2 (or TP=1) model: global
    arrays identical — checkpoints are not tied to the mesh they were written
    from (unlike the reference, where rank files only load at the same
    tp_size)."""
    m4 = Transformer(CFG, tp_size=4)
    params = m4.init(jax.random.key(3))
    save_checkpoint(str(tmp_path), 5, 1.0, params, m4.specs(), tp_size=4)

    m2 = Transformer(CFG, tp_size=2)
    loaded, _, _ = load_checkpoint(str(tmp_path), 5, params, m4.specs())
    _tree_equal(loaded, params)
    # and it actually runs on a tp=2 mesh
    mesh = make_mesh(MeshConfig(dp=1, tp=2))
    sharded = jax.device_put(loaded, m2.shardings(mesh))
    ids = jnp.zeros((2, 8), jnp.int32)
    pos = jnp.tile(jnp.arange(8)[None, :], (2, 1))
    logits = m2.make_forward(mesh)(sharded, ids, pos)
    assert np.isfinite(np.asarray(logits)).all()
