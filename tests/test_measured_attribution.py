"""obs v4 (ISSUE 15): measured attribution.

The acceptance criteria pinned here:
* the COMMITTED fixture capture (a synthetic trace.json.gz with a known
  event set — tests/profparse_fixtures/) parses into a measured_phases
  report whose per-phase ms match hand arithmetic exactly, and
  reconciles against a hand analytic report with hand-checkable drift
  numbers (the round-trip pin, backend-proof);
* a REAL CPU-backend jax.profiler capture from a tiny serve run parses
  end-to-end: capture -> parse -> versioned profile_attribution event
  -> summarize_run "Measured vs analytic" render, in one test;
* duty-cycle laws: windows open every N ticks, the disk budget stops
  sampling BETWEEN windows (never mid-window) with a counted skip, and
  the off state is exactly zero-cost (no capture dirs, no events);
* the silent-zero HBM fix: a statless backend reports None/'unavailable'
  loudly — never a fake 0-GiB watermark — through device_memory_gib,
  the exporter gauges, the hbm_watermark events, the fleet rollup, and
  the obs_top column;
* schema v4 (profile_attribution / hbm_watermark) validates and drifts
  loudly; the regression gate treats measured ms directionally.
"""

import glob
import importlib.util
import json
import os

import jax
import jax.numpy as jnp
import pytest

from distributed_pytorch_from_scratch_tpu.obs import profparse
from distributed_pytorch_from_scratch_tpu.obs.collector import (
    FleetCollector)
from distributed_pytorch_from_scratch_tpu.obs.schema import (
    EVENT_SCHEMA_VERSION, validate_record)
from distributed_pytorch_from_scratch_tpu.training.metrics import (
    DutyCycleProfiler, MetricsWriter, device_memory_gib,
    device_memory_stats, hbm_watermarks, publish_hbm)

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
FIXTURE_CAPTURE = os.path.join(HERE, "profparse_fixtures", "capture")

# the hand analytic report the fixture reconciles against (2 profiled
# steps): compute 5 ms/step, all-reduce 1 ms/step, cp 0.5 ms/step
HAND_ANALYTIC = {
    "phases": [{"name": "compute", "ms": 5.0},
               {"name": "all-reduce", "ms": 1.0},
               {"name": "collective-permute", "ms": 0.5}],
    "total_ms": 6.5,
}


def _load_script(name):
    path = os.path.join(REPO, "scripts", name + ".py")
    spec = importlib.util.spec_from_file_location(f"_ma_{name}", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ------------------------------------------------- the fixture round-trip

def test_fixture_capture_parses_to_hand_checked_phases():
    """The committed trace.json.gz holds 18 ms of device ops on a 20 ms
    lane; every per-phase total is pinned against hand arithmetic (see
    profparse_fixtures/gen_fixture.py for the authored event set)."""
    r = profparse.parse_capture(FIXTURE_CAPTURE)
    assert r["files"] == 1 and r["events"] == 8
    assert r["devices"] == ["/device:TPU:0"]
    ms = profparse.phase_ms_map(r)
    assert ms == {"fusion": 10.0, "dot": 2.0, "all-reduce": 3.0,
                  "collective-permute": 1.0, "copy": 0.5,
                  "transpose": 0.5, "convert": 1.0, "host_gap": 2.0}
    assert r["device_busy_ms"] == pytest.approx(18.0)
    assert r["host_gap_ms"] == pytest.approx(2.0)
    assert r["total_ms"] == pytest.approx(20.0)
    # the python host-callstack event was ignored (no hlo args)
    counts = {p["name"]: p["count"] for p in r["phases"]}
    assert counts["fusion"] == 2


def test_fixture_reconcile_drift_hand_math():
    """The round-trip pin: measured (per 2 steps) vs the hand analytic
    report — compute folds fusion+dot+convert = 13/2 = 6.5 vs 5.0 =
    +30%; all-reduce 1.5 vs 1.0 = +50%; cp exact; copy/transpose/
    host_gap unpriced (drift None); comm 2.0 ms/step; total +53.8%."""
    measured = profparse.parse_capture(FIXTURE_CAPTURE)
    rec = profparse.reconcile(measured, HAND_ANALYTIC, steps=2)
    assert rec["steps"] == 2
    assert rec["phases"] == {
        "compute": 6.5, "all-reduce": 1.5, "collective-permute": 0.5,
        "copy": 0.25, "transpose": 0.25, "host_gap": 1.0}
    by = {r["phase"]: r for r in rec["rows"]}
    assert by["compute"]["drift_pct"] == pytest.approx(30.0)
    assert by["all-reduce"]["drift_pct"] == pytest.approx(50.0)
    assert by["collective-permute"]["drift_pct"] == pytest.approx(0.0)
    assert by["copy"]["drift_pct"] is None          # unpriced: the find
    assert rec["measured_step_ms"] == pytest.approx(10.0)
    assert rec["analytic_step_ms"] == pytest.approx(6.5)
    assert rec["comm_ms"] == pytest.approx(2.0)
    assert rec["total_drift_pct"] == pytest.approx(53.8)
    # worst suspect = the largest absolute gap (compute, 1.5 ms)
    assert rec["suspects"][0]["phase"] == "compute"
    text = profparse.format_reconcile(rec)
    assert "+30.0%" in text and "host_gap" in text


def test_classify_op_taxonomy():
    assert profparse.classify_op("fusion.2047") == "fusion"
    assert profparse.classify_op("%all-reduce-start.1") == "all-reduce"
    assert profparse.classify_op("all_gather.3") == "all-gather"
    assert profparse.classify_op("reduce-scatter.12") == "reduce-scatter"
    assert profparse.classify_op("collective-permute-done.2") == \
        "collective-permute"
    assert profparse.classify_op("dot.2") == "dot"
    assert profparse.classify_op("dynamic-update-slice.9") == "copy"
    assert profparse.classify_op("bitcast-convert.1") == "convert"
    assert profparse.classify_op("wat.77") == "other"


def test_parse_refuses_non_capture_dirs(tmp_path):
    with pytest.raises(ValueError, match="no .*trace.json"):
        profparse.parse_capture(str(tmp_path))


def test_analytic_phase_report_folds_attribution():
    """The analytic fold: compute == the roofline step; each collective
    kind == its records' serialized ms summed — so the analytic side
    lands in the measured taxonomy, joinable by name."""
    from distributed_pytorch_from_scratch_tpu.config import ModelConfig
    from distributed_pytorch_from_scratch_tpu.obs.attribution import (
        attribution)
    cfg = ModelConfig(attn_dim=64, ffn_dim=128, num_heads=4, num_layers=2,
                      vocab_size=256, maxlen=128)
    attr = attribution(cfg, batch=4, t=128, tp=2, sp=True, world=2)
    rep = profparse.analytic_phase_report(attr)
    ms = profparse.phase_ms_map(rep)
    assert ms["compute"] == pytest.approx(attr["analytic_step_ms"],
                                          abs=5.1e-5)  # report rounds to 4dp
    by_kind = {}
    for r in attr["comm"]["records"]:
        by_kind[r["kind"]] = by_kind.get(r["kind"], 0.0) \
            + r["serialized_ms"]
    for kind, total in by_kind.items():
        assert ms[kind] == pytest.approx(total, abs=1e-3)
    assert rep["comm_exposed_ms"] == pytest.approx(
        attr["comm"]["comm_exposed_ms"], abs=1e-3)


# ---------------------------------------- real capture end-to-end (pin)

def test_real_cpu_capture_end_to_end(tmp_path, capsys):
    """The acceptance pin: a REAL jax.profiler capture from a tiny serve
    run on the CPU backend parses end-to-end — capture dir on disk ->
    obs/profparse -> schema-valid profile_attribution event in the
    metrics chain -> summarize_run renders the 'Measured vs analytic'
    section."""
    from distributed_pytorch_from_scratch_tpu.serving import serve as srv
    log_dir = str(tmp_path / "logs")
    srv.main(["--dry_run", "--paged", "--profile_every", "3",
              "--profile_window", "2", "--log_dir", log_dir])
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["profile_captures"], "duty profiler captured nothing"
    assert rec["profile_attributions"] >= 1
    recs = [json.loads(l)
            for l in open(os.path.join(log_dir, "metrics.jsonl"))]
    pa = [r for r in recs if r["tag"] == "profile_attribution"]
    assert pa, "no profile_attribution events landed"
    assert not any(p for r in pa for p in validate_record(r))
    parsed = [r for r in pa if not r.get("error")]
    assert parsed, "every capture failed to parse"
    first = parsed[0]
    assert first["trigger"] == "duty" and first["steps"] == 2
    assert first["phases"], "parsed capture classified no device events"
    assert os.path.isdir(first["capture"])
    assert profparse.find_trace_files(first["capture"])
    # the post-hoc render: summarize_run picks the events up
    sr = _load_script("summarize_run")
    text = sr.summarize(str(tmp_path))
    assert "Measured vs analytic" in text
    assert "duty" in text


# --------------------------------------------------- duty-cycle laws

def _tick_with_device_work(duty, steps, size=64):
    f = jax.jit(lambda a: a @ a)
    x = jnp.ones((size, size))
    for step in range(steps):
        y = f(x)
        jax.block_until_ready(y)
        duty.tick(step, sync=y)


def test_duty_cycle_budget_stops_between_windows(tmp_path):
    """Budget law: a tiny budget exhausts after the FIRST finished
    window; later due windows are skipped (counted), never started, and
    the finished capture is complete (stopped by window mechanics, not
    truncated by the budget)."""
    with MetricsWriter(str(tmp_path), process_index=0) as w:
        duty = DutyCycleProfiler(str(tmp_path), every=3, window=1,
                                 budget_mb=1e-6, writer=w)
        _tick_with_device_work(duty, 14)
        duty.close()
    assert len(duty.captures) == 1          # one window, then exhausted
    assert duty.exhausted
    # due windows at ticks 6, 9, 12 were skipped (3 of them)
    assert duty.windows_skipped >= 2
    assert os.path.isdir(duty.captures[0])
    assert profparse.find_trace_files(duty.captures[0])
    recs = [json.loads(l) for l in open(tmp_path / "metrics.jsonl")]
    pa = [r for r in recs if r["tag"] == "profile_attribution"]
    assert len(pa) == 1
    assert not validate_record(pa[0])


def test_duty_cycle_opens_windows_on_period(tmp_path):
    # generous budget: a CPU capture's size scales with the host
    # callstack (tens of MiB inside the full suite) — this test pins the
    # PERIOD law, the budget law has its own test above
    with MetricsWriter(str(tmp_path), process_index=0) as w:
        duty = DutyCycleProfiler(str(tmp_path), every=4, window=2,
                                 budget_mb=4096.0, writer=w)
        _tick_with_device_work(duty, 13)
        duty.close()
    # windows open at ticks 4, 8, 12 -> 3 captures (last closed early)
    assert len(duty.captures) == 3
    assert duty.windows_skipped == 0 and not duty.exhausted


def test_duty_cycle_counts_dispatches_not_step_numbers(tmp_path):
    """steps_per_dispatch regression pin: the caller's step numbers jump
    by N per dispatch (train.py's spd mode) — the window must still span
    `window` DISPATCHES, not close Nx early in the step-number domain."""
    f = jax.jit(lambda a: a @ a)
    x = jnp.ones((32, 32))
    opened_at = closed_at = None
    with MetricsWriter(str(tmp_path), process_index=0) as w:
        duty = DutyCycleProfiler(str(tmp_path), every=4, window=2,
                                 budget_mb=4096.0, writer=w)
        for i in range(10):
            y = f(x)
            jax.block_until_ready(y)
            duty.tick(i * 8, sync=y)       # spd=8-style step numbers
            if duty._trace is not None and opened_at is None:
                opened_at = i
            if (opened_at is not None and closed_at is None
                    and i > opened_at and duty._trace is None):
                closed_at = i
        duty.close()
    assert opened_at == 4                  # the every-th dispatch
    assert closed_at == 6                  # exactly `window`=2 dispatches


def test_duty_cycle_truncated_window_reports_actual_steps(tmp_path):
    """A close()-forced window covers fewer dispatches than `window`;
    attributing it at the full count would deflate measured_step_ms (the
    number the regression gate checks directionally)."""
    with MetricsWriter(str(tmp_path), process_index=0) as w:
        duty = DutyCycleProfiler(str(tmp_path), every=3, window=3,
                                 budget_mb=4096.0, writer=w)
        _tick_with_device_work(duty, 5)    # window opens at tick 3
        duty.close()                       # ... 1 dispatch (tick 4) in
    assert duty.capture_steps == [1]
    recs = [json.loads(l) for l in open(tmp_path / "metrics.jsonl")]
    pa = [r for r in recs if r["tag"] == "profile_attribution"]
    assert pa and pa[0]["steps"] == 1


def test_duty_cycle_back_to_back_when_window_equals_every(tmp_path):
    """W == N means continuous back-to-back capture: a window finishing
    on a duty boundary must not swallow the window due at that tick
    (that would silently halve the documented cadence)."""
    with MetricsWriter(str(tmp_path), process_index=0) as w:
        duty = DutyCycleProfiler(str(tmp_path), every=2, window=2,
                                 budget_mb=4096.0, writer=w)
        _tick_with_device_work(duty, 9)
        duty.close()
    # windows open at ticks 2, 4, 6, 8 — every boundary, no gaps
    assert len(duty.captures) == 4
    assert duty.windows_skipped == 0


def test_duty_profiler_refusals(tmp_path):
    with MetricsWriter(str(tmp_path), process_index=0) as w:
        with pytest.raises(ValueError, match="profile window"):
            DutyCycleProfiler(str(tmp_path), every=2, window=3, writer=w)
        with pytest.raises(ValueError, match="budget"):
            DutyCycleProfiler(str(tmp_path), every=4, window=2,
                              budget_mb=0, writer=w)
    with pytest.raises(ValueError, match="MetricsWriter"):
        DutyCycleProfiler(str(tmp_path), every=4, window=2, writer=None)


def test_profiler_off_state_is_zero_cost(tmp_path, capsys):
    """Off state: a serve run WITHOUT profile flags writes no capture
    dirs, no profile_attribution events, and the summary record carries
    no profile fields."""
    from distributed_pytorch_from_scratch_tpu.serving import serve as srv
    log_dir = str(tmp_path / "logs")
    srv.main(["--dry_run", "--paged", "--log_dir", log_dir])
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert "profile_captures" not in rec
    assert not glob.glob(os.path.join(log_dir, "profile_duty_*"))
    assert not glob.glob(os.path.join(log_dir, "plugins"))
    recs = [json.loads(l)
            for l in open(os.path.join(log_dir, "metrics.jsonl"))]
    assert not any(r["tag"] == "profile_attribution" for r in recs)


# --------------------------------------------- silent-zero HBM fix

def test_device_memory_unavailable_is_none_not_zero():
    """The CPU backend has no memory_stats: every reader must see the
    DISTINCT unavailable value, never 0 (the fake 0-GiB watermark)."""
    assert device_memory_stats() is None
    assert device_memory_gib() is None
    assert hbm_watermarks() is None


def test_publish_hbm_exports_unavailable_loudly(tmp_path):
    from distributed_pytorch_from_scratch_tpu.obs import TelemetryExporter
    tel = TelemetryExporter()
    with MetricsWriter(str(tmp_path), process_index=0) as w:
        marks = publish_hbm(telemetry=tel, writer=w, step=7, event=True,
                            pool_accounted_bytes=4096)
    assert marks is None
    g = tel.snapshot()["gauges"]
    assert g["hbm/available"] == 0.0
    assert "hbm/bytes_in_use" not in g          # no fake zeros
    assert g["hbm/kv_accounted_bytes"] == 4096
    recs = [json.loads(l) for l in open(tmp_path / "metrics.jsonl")]
    hw = [r for r in recs if r["tag"] == "hbm_watermark"]
    assert len(hw) == 1
    assert hw[0]["available"] is False and hw[0]["devices"] == []
    assert not validate_record(hw[0])


def test_train_scalar_never_fakes_zero_memory(tmp_path):
    """memory.py's budget fallback warns loudly too (one-time note)."""
    from distributed_pytorch_from_scratch_tpu.training import memory
    memory._warned_assumed_budget.clear()
    import io
    import sys
    err = io.StringIO()
    old = sys.stderr
    sys.stderr = err
    try:
        v = memory.hbm_budget_gib()
        memory.hbm_budget_gib()     # second call stays quiet
    finally:
        sys.stderr = old
    assert v == 16.0
    assert err.getvalue().count("UNAVAILABLE") == 1


# -------------------------------- schema v4 + collector + obs_top

def test_schema_v4_profile_attribution_and_hbm_watermark():
    ok = {"tag": "profile_attribution", "schema_version":
          EVENT_SCHEMA_VERSION, "capture": "/x", "trigger": "duty",
          "phases": {"fusion": 1.0}}
    assert validate_record(ok) == []
    missing = dict(ok)
    missing.pop("phases")
    assert any("phases" in p for p in validate_record(missing))
    hbm = {"tag": "hbm_watermark", "schema_version": EVENT_SCHEMA_VERSION,
           "devices": [], "available": False}
    assert validate_record(hbm) == []
    newer = dict(ok, schema_version=EVENT_SCHEMA_VERSION + 1)
    assert any("NEWER" in p for p in validate_record(newer))


def test_fleet_rollup_folds_hbm_and_keeps_unavailable_distinct(tmp_path):
    """2 fake procs: one exports real watermark gauges, one exports
    available=0 — the rollup sums only the real one and counts the
    unavailable proc LOUDLY instead of folding a zero."""
    d0, d1 = tmp_path / "p0", tmp_path / "p1"
    with MetricsWriter(str(d0), process_index=0) as w:
        w.event("telemetry_snapshot", process=0,
                gauges={"serve/tokens_per_sec": 10.0,
                        "hbm/available": 1.0,
                        "hbm/bytes_in_use": 3 * 2**30,
                        "hbm/peak_bytes": 5 * 2**30},
                counters={})
    with MetricsWriter(str(d1), process_index=1) as w:
        w.event("telemetry_snapshot", process=1,
                gauges={"serve/tokens_per_sec": 5.0,
                        "hbm/available": 0.0},
                counters={})
    c = FleetCollector([str(d0), str(d1)])
    assert c.poll() == 2
    r = c.rollup()
    assert r["hbm"] == {"bytes_in_use_total": 3 * 2**30,
                        "peak_bytes_max": 5 * 2**30,
                        "procs_reporting": 1,
                        "procs_unavailable": 1}


def test_collector_folds_hbm_watermark_events(tmp_path):
    with MetricsWriter(str(tmp_path), process_index=0) as w:
        w.event("hbm_watermark", available=True,
                devices=[{"device": "tpu:0", "bytes_in_use": 100,
                          "peak_bytes": 200, "limit_bytes": 400}])
    c = FleetCollector([str(tmp_path)])
    c.poll()
    r = c.rollup()
    assert r["hbm"]["bytes_in_use_total"] == 100
    assert r["hbm"]["peak_bytes_max"] == 200


def test_obs_top_once_renders_hbm_column(tmp_path, capsys):
    d0, d1 = tmp_path / "p0", tmp_path / "p1"
    with MetricsWriter(str(d0), process_index=0) as w:
        w.event("telemetry_snapshot", process=0,
                gauges={"serve/tokens_per_sec": 42.0,
                        "hbm/available": 1.0,
                        "hbm/bytes_in_use": 2 * 2**30,
                        "hbm/peak_bytes": 3 * 2**30},
                counters={})
    with MetricsWriter(str(d1), process_index=1) as w:
        w.event("telemetry_snapshot", process=1,
                gauges={"serve/tokens_per_sec": 7.0,
                        "hbm/available": 0.0},
                counters={})
    top = _load_script("obs_top")
    assert top.main([str(d0), str(d1), "--once"]) == 0
    out = capsys.readouterr().out
    assert "| hbm |" in out
    assert "2.00/3.00G" in out              # the available proc's column
    assert "n/a" in out                     # the statless proc, loudly
    assert "report NO" in out or "HBM:" in out


def test_summarize_renders_hbm_watermarks(tmp_path):
    with MetricsWriter(str(tmp_path), process_index=0) as w:
        w.event("hbm_watermark", available=False, devices=[])
    sr = _load_script("summarize_run")
    text = sr.summarize(str(tmp_path))
    assert "HBM watermarks" in text and "UNAVAILABLE" in text


# ------------------------------------------- the regression gate

def _serving_record(measured_step_ms, comm_ms, phases):
    return {"metric": "serving tokens/sec (x)", "value": 100.0,
            "unit": "tokens/sec (serving)",
            "measured_vs_analytic": {
                "capture": "/x", "steps": 2,
                "measured_step_ms": measured_step_ms,
                "comm_ms": comm_ms, "phases": phases}}


def test_gate_measured_ms_directional(tmp_path):
    gate = _load_script("check_bench_regression")
    base = tmp_path / "base.json"
    base.write_text(json.dumps(
        _serving_record(10.0, 1.0, {"compute": 8.0, "all-reduce": 1.0})))
    ok = tmp_path / "ok.json"
    ok.write_text(json.dumps(
        _serving_record(9.5, 0.9, {"compute": 7.8, "all-reduce": 0.9})))
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps(
        _serving_record(20.0, 4.0, {"compute": 17.0, "all-reduce": 4.0})))
    assert gate.main(["--fresh", str(ok), "--baseline", str(base)]) == 0
    rc = gate.main(["--fresh", str(bad), "--baseline", str(base)])
    assert rc == 1
    # the dynamic per-phase checks actually fired
    checks, _ = gate.metric_checks(json.loads(bad.read_text()),
                                   json.loads(base.read_text()),
                                   tol_pct=10.0, tol_latency_pct=25.0)
    fields = {c["field"] for c in checks}
    assert "measured_vs_analytic.measured_step_ms" in fields
    assert "measured_vs_analytic.phases.compute" in fields
    assert any(not c["ok"] for c in checks)


# --------------------------------------------------- CLI refusals

def test_serve_cli_profile_refusals():
    from distributed_pytorch_from_scratch_tpu.serving import serve as srv
    with pytest.raises(SystemExit):       # duty + anomaly collide
        srv.get_serve_args(["--dry_run", "--paged", "--flight_records",
                            "--profile_every", "4",
                            "--profile_on_anomaly", "2"])
    with pytest.raises(SystemExit):       # window > every
        srv.get_serve_args(["--dry_run", "--profile_every", "2",
                            "--profile_window", "4"])
    with pytest.raises(SystemExit):       # no metrics dir
        srv.get_serve_args(["--dry_run", "--profile_every", "4",
                            "--log_dir", ""])
    with pytest.raises(SystemExit):       # budget
        srv.get_serve_args(["--dry_run", "--profile_every", "4",
                            "--profile_window", "2",
                            "--profile_budget_mb", "0"])


def test_bench_cli_profile_refusals():
    import bench
    with pytest.raises(SystemExit):       # --serving gate
        bench.parse_args(["--profile_every", "4"])
    with pytest.raises(SystemExit):       # window > every
        bench.parse_args(["--serving", "--profile_every", "2",
                          "--profile_window", "4"])
    with pytest.raises(SystemExit):       # no metrics dir
        bench.parse_args(["--serving", "--profile_every", "4",
                          "--obs_dir", ""])
    with pytest.raises(SystemExit):       # breakdown-only knob
        bench.parse_args(["--capture_profile"])
    with pytest.raises(SystemExit):       # capture needs device timing
        bench.parse_args(["--breakdown", "--analytic", "--remat", "dots",
                          "--capture_profile"])
    args = bench.parse_args(["--serving", "--profile_every", "6",
                             "--profile_window", "2"])
    assert args.profile_every == 6 and args.profile_window == 2


def test_train_cli_profile_refusals():
    from distributed_pytorch_from_scratch_tpu.train import get_train_args
    with pytest.raises(SystemExit):       # duty excludes fixed window
        get_train_args(["--data_path", "x", "--profile_every", "4",
                        "--profile_steps", "2"])
    with pytest.raises(SystemExit):       # duty excludes anomaly arm
        get_train_args(["--data_path", "x", "--profile_every", "4",
                        "--profile_on_anomaly", "2"])
    with pytest.raises(SystemExit):       # window > every
        get_train_args(["--data_path", "x", "--profile_every", "2",
                        "--profile_window", "8"])
    args = get_train_args(["--data_path", "x", "--profile_every", "8",
                           "--profile_window", "2"])
    assert args.profile_every == 8
