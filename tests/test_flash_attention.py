"""Pallas flash-attention kernel vs the naive XLA oracle.

The reference has no fused attention at all (naive O(T^2) masked softmax,
`/root/reference/models/model.py:73-77`); the oracle here is our XLA
mirror of that math, so equivalence to it is equivalence to the reference.
Runs in Pallas interpreter mode on CPU (same kernel code compiles on TPU).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributed_pytorch_from_scratch_tpu import (MeshConfig, ModelConfig,
                                                  Transformer, make_mesh)
from distributed_pytorch_from_scratch_tpu.ops.attention import (
    causal_attention_xla)
from distributed_pytorch_from_scratch_tpu.ops.pallas.flash_attention import (
    flash_attention)


@pytest.mark.parametrize("shape", [(2, 4, 128, 64), (1, 2, 300, 64),
                                   (2, 2, 513, 32), (1, 8, 1000, 64)])
def test_forward_matches_oracle_f32(shape):
    b, h, t, d = shape
    kq, kk, kv = jax.random.split(jax.random.key(t), 3)
    q = jax.random.normal(kq, shape, jnp.float32)
    k = jax.random.normal(kk, shape, jnp.float32)
    v = jax.random.normal(kv, shape, jnp.float32)
    ref = causal_attention_xla(q, k, v)
    out = flash_attention(q, k, v)
    assert jnp.abs(ref - out).max() < 1e-5


def test_forward_matches_oracle_bf16():
    shape = (2, 4, 256, 64)
    kq, kk, kv = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(kq, shape, jnp.bfloat16)
    k = jax.random.normal(kk, shape, jnp.bfloat16)
    v = jax.random.normal(kv, shape, jnp.bfloat16)
    ref = causal_attention_xla(q, k, v).astype(jnp.float32)
    out = flash_attention(q, k, v).astype(jnp.float32)
    # bf16 storage + f32-vs-bf16 score accumulation: ~1e-2 quantisation
    assert jnp.abs(ref - out).max() < 3e-2


def test_gradients_match_oracle():
    shape = (2, 2, 320, 64)
    kq, kk, kv, kg = jax.random.split(jax.random.key(1), 4)
    q = jax.random.normal(kq, shape, jnp.float32)
    k = jax.random.normal(kk, shape, jnp.float32)
    v = jax.random.normal(kv, shape, jnp.float32)
    g = jax.random.normal(kg, shape, jnp.float32)

    gr = jax.grad(lambda *a: jnp.vdot(causal_attention_xla(*a), g), (0, 1, 2))(q, k, v)
    gf = jax.grad(lambda *a: jnp.vdot(flash_attention(*a), g), (0, 1, 2))(q, k, v)
    for a, b in zip(gr, gf):
        assert jnp.abs(a - b).max() < 1e-4


def test_gradients_match_oracle_multiblock():
    """Small explicit block sizes force the split dq/dkv backward kernels —
    the fused single-block backward handles every default-sized case, so
    without this the multi-block path would lose coverage."""
    shape = (1, 2, 320, 64)
    kq, kk, kv, kg = jax.random.split(jax.random.key(5), 4)
    q = jax.random.normal(kq, shape, jnp.float32)
    k = jax.random.normal(kk, shape, jnp.float32)
    v = jax.random.normal(kv, shape, jnp.float32)
    g = jax.random.normal(kg, shape, jnp.float32)

    def fl(*a):
        return flash_attention(*a, block_q=128, block_k=128,
                               bwd_block_q=128, bwd_block_k=128)

    gr = jax.grad(lambda *a: jnp.vdot(causal_attention_xla(*a), g), (0, 1, 2))(q, k, v)
    gf = jax.grad(lambda *a: jnp.vdot(fl(*a), g), (0, 1, 2))(q, k, v)
    for a, b in zip(gr, gf):
        assert jnp.abs(a - b).max() < 1e-4


def test_flash_under_shard_map():
    """The kernel runs per-shard inside shard_map (local heads), like in
    the TP transformer."""
    mesh = make_mesh(MeshConfig(dp=1, tp=4))
    shape = (2, 8, 256, 32)
    kq, kk, kv = jax.random.split(jax.random.key(2), 3)
    q = jax.random.normal(kq, shape, jnp.float32)
    k = jax.random.normal(kk, shape, jnp.float32)
    v = jax.random.normal(kv, shape, jnp.float32)

    fn = jax.jit(jax.shard_map(
        lambda q, k, v: flash_attention(q, k, v),
        mesh=mesh, in_specs=(P(None, "tp"),) * 3, out_specs=P(None, "tp")))
    out = fn(q, k, v)
    ref = causal_attention_xla(q, k, v)
    assert jnp.abs(ref - out).max() < 1e-5

    # backward under shard_map too (exercises the vma tags on the dq/dk/dv
    # pallas_call out_shapes, which only fail at trace time on TPU otherwise)
    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)

    g_fl = jax.jit(jax.grad(loss(
        jax.shard_map(flash_attention, mesh=mesh,
                      in_specs=(P(None, "tp"),) * 3,
                      out_specs=P(None, "tp"))), argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(loss(causal_attention_xla), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_fl):
        assert jnp.abs(a - b).max() < 1e-4


def test_transformer_attn_impl_flash_matches_xla():
    """Full TP model forward with attn_impl='flash' == attn_impl='xla'."""
    cfg = ModelConfig(attn_dim=64, ffn_dim=128, num_heads=4, num_layers=2,
                      vocab_size=128, maxlen=160, compute_dtype="float32")
    mesh = make_mesh(MeshConfig(dp=2, tp=4))
    m_xla = Transformer(cfg, tp_size=4, attn_impl="xla")
    m_fla = Transformer(cfg, tp_size=4, attn_impl="flash")
    params = m_xla.init(jax.random.key(0))
    params = jax.device_put(params, m_xla.shardings(mesh))

    b, t = 4, 160
    ids = jax.random.randint(jax.random.key(3), (b, t), 0, cfg.vocab_size)
    pos = jnp.tile(jnp.arange(t, dtype=jnp.int32)[None, :], (b, 1))

    lo_x = m_xla.make_forward(mesh)(params, ids, pos)
    lo_f = m_fla.make_forward(mesh)(params, ids, pos)
    assert jnp.abs(lo_x - lo_f).max() < 1e-4


# ---- grouped-query (GQA) kernel routing: no K/V repeat in HBM ----


@pytest.mark.parametrize("t,block", [(64, 128), (200, 128)])
def test_gqa_kernel_matches_repeat_oracle(t, block):
    """hkv < hq routed inside the kernels (fused single-block at t=64,
    split dq/dkv kernels at t=200) vs the repeat+dense oracle."""
    from distributed_pytorch_from_scratch_tpu.ops.attention import (
        causal_attention_xla)

    key = jax.random.key(5)
    b, hq, hkv, d = 2, 8, 2, 16
    q = jax.random.normal(jax.random.fold_in(key, 1), (b, hq, t, d))
    k = jax.random.normal(jax.random.fold_in(key, 2), (b, hkv, t, d))
    v = jax.random.normal(jax.random.fold_in(key, 3), (b, hkv, t, d))
    ref = causal_attention_xla(q, k, v)
    out = flash_attention(q, k, v, block_q=block, block_k=block)
    np.testing.assert_allclose(out, ref, atol=2e-5)

    loss = lambda fn: lambda *a: jnp.sum(fn(*a) ** 2)
    g_ref = jax.grad(loss(causal_attention_xla), argnums=(0, 1, 2))(q, k, v)
    g_out = jax.grad(
        loss(lambda q, k, v: flash_attention(q, k, v, block_q=block,
                                             block_k=block)),
        argnums=(0, 1, 2))(q, k, v)
    for name, a, b_ in zip("qkv", g_ref, g_out):
        np.testing.assert_allclose(b_, a, atol=5e-5, err_msg=f"d{name}")
        # dk/dv stay at the kv head count — nothing materialised the repeat
    assert g_out[1].shape == k.shape and g_out[2].shape == v.shape


def test_gqa_rejects_nondivisible_heads():
    q = jnp.zeros((1, 6, 64, 16))
    kv = jnp.zeros((1, 4, 64, 16))
    with pytest.raises(ValueError, match="multiple of kv heads"):
        flash_attention(q, kv, kv)


# ---- positional block kernel (ring attention building block) ----


def test_block_attention_matches_xla_block():
    """Pallas positional kernel vs the dense XLA block math, including an
    all-dead query row (position earlier than every kv) and GQA heads."""
    from distributed_pytorch_from_scratch_tpu.ops.pallas.flash_attention import (
        block_attention)
    from distributed_pytorch_from_scratch_tpu.ops.ring_attention import (
        _BIG_NEG, _block_attn_xla)

    key = jax.random.key(7)
    b, hq, hkv, tq, tk, d = 2, 4, 2, 96, 160, 16
    q = jax.random.normal(jax.random.fold_in(key, 1), (b, hq, tq, d))
    k = jax.random.normal(jax.random.fold_in(key, 2), (b, hkv, tk, d))
    v = jax.random.normal(jax.random.fold_in(key, 3), (b, hkv, tk, d))
    qp = jax.random.randint(jax.random.fold_in(key, 4), (b, tq), 100, 500)
    qp = qp.at[:, 0].set(0)  # row 0: sees nothing (all kv_pos >= 100)
    kp = jax.random.randint(jax.random.fold_in(key, 5), (b, tk), 100, 500)
    scale = 1.0 / np.sqrt(d)

    o_ref, lse_ref = _block_attn_xla(q, k, v, qp, kp, scale)
    o_k, lse_k = block_attention(q, k, v, qp, kp)
    assert bool((lse_ref[:, :, 0] <= _BIG_NEG / 2).all()), "dead row expected"
    np.testing.assert_allclose(o_k, o_ref, atol=2e-5)
    alive = lse_ref > _BIG_NEG / 2
    np.testing.assert_allclose(jnp.where(alive, lse_k, 0.0),
                               jnp.where(alive, lse_ref, 0.0), atol=2e-5)

    def loss(fn):
        def inner(q, k, v):
            o, lse = fn(q, k, v)
            keep = lse > _BIG_NEG / 2
            return jnp.sum(o.astype(jnp.float32) ** 2) + jnp.sum(
                jnp.where(keep, lse, 0.0) ** 2)
        return inner

    g_ref = jax.grad(loss(lambda q, k, v: _block_attn_xla(q, k, v, qp, kp,
                                                          scale)),
                     argnums=(0, 1, 2))(q, k, v)
    g_k = jax.grad(loss(lambda q, k, v: block_attention(q, k, v, qp, kp)),
                   argnums=(0, 1, 2))(q, k, v)
    for name, a, b_ in zip("qkv", g_ref, g_k):
        np.testing.assert_allclose(b_, a, atol=5e-5, err_msg=f"d{name}")


# ---- pad-aware t_real path (sequence bucketing) ----


def test_t_real_matches_sliced_oracle():
    """t_real < t: rows below t_real match the oracle on the SLICED inputs
    exactly; rows at/after t_real are hard zeros (the bucketing contract —
    flash_attention docstring)."""
    b, h, t, d, tr = 1, 2, 320, 32, 300
    kq, kk, kv = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(kq, (b, h, t, d))
    k = jax.random.normal(kk, (b, h, t, d))
    v = jax.random.normal(kv, (b, h, t, d))
    ref = causal_attention_xla(q[:, :, :tr], k[:, :, :tr], v[:, :, :tr])
    for blocks in ({}, dict(block_q=128, block_k=128,
                            bwd_block_q=128, bwd_block_k=128)):
        out = flash_attention(q, k, v, t_real=tr, **blocks)
        assert jnp.abs(out[:, :, :tr] - ref).max() < 1e-5
        assert jnp.abs(out[:, :, tr:]).max() == 0.0


def test_t_real_grads_exact_even_with_tail_cotangent():
    """Gradients through the t_real path equal the sliced oracle's, and a
    NONZERO cotangent on the pad rows contributes exactly zero (the pad
    outputs are constants) — the invariant that keeps bucketing exact
    under losses that touch every row (e.g. MoE aux sums)."""
    b, h, t, d, tr = 1, 2, 320, 32, 300
    keys = jax.random.split(jax.random.key(1), 4)
    q, k, v, g = (jax.random.normal(kk, (b, h, t, d)) for kk in keys)

    gr = jax.grad(
        lambda *a: jnp.vdot(causal_attention_xla(*a), g[:, :, :tr]),
        (0, 1, 2))(q[:, :, :tr], k[:, :, :tr], v[:, :, :tr])
    # g carries nonzero values on rows >= tr on purpose
    gf = jax.grad(
        lambda *a: jnp.vdot(flash_attention(*a, t_real=tr), g),
        (0, 1, 2))(q, k, v)
    for a, b_ in zip(gr, gf):
        assert jnp.abs(a - b_[:, :, :tr]).max() < 1e-4
        assert jnp.abs(b_[:, :, tr:]).max() == 0.0


def test_t_real_validation():
    q = jnp.zeros((1, 2, 128, 16))
    with pytest.raises(ValueError, match="t_real"):
        flash_attention(q, q, q, t_real=0)
    with pytest.raises(ValueError, match="t_real"):
        flash_attention(q, q, q, t_real=129)


@pytest.mark.slow
def test_t_real_parity_reference_shape():
    """The acceptance case: t=1000 real tokens in a t=1024 bucket equals
    the plain t=1000 path and the vanilla oracle, at the reference head
    shape (fwd; CPU interpreter)."""
    b, h, t_pad, d, tr = 1, 8, 1024, 64, 1000
    kq, kk, kv = jax.random.split(jax.random.key(2), 3)
    q = jax.random.normal(kq, (b, h, t_pad, d))
    k = jax.random.normal(kk, (b, h, t_pad, d))
    v = jax.random.normal(kv, (b, h, t_pad, d))
    ref = causal_attention_xla(q[:, :, :tr], k[:, :, :tr], v[:, :, :tr])
    plain = flash_attention(q[:, :, :tr], k[:, :, :tr], v[:, :, :tr])
    bucketed = flash_attention(q, k, v, t_real=tr,
                               block_q=256, block_k=256)
    assert jnp.abs(plain - ref).max() < 1e-5
    assert jnp.abs(bucketed[:, :, :tr] - ref).max() < 1e-5
    assert jnp.abs(bucketed[:, :, tr:]).max() == 0.0


# ---- block-shape autotuner table + cache ----


@pytest.fixture
def block_table():
    """Snapshot/restore the module-global tuned-block table around a test."""
    from distributed_pytorch_from_scratch_tpu.ops.pallas import (
        flash_attention as fa)

    saved, saved_loaded = dict(fa._BLOCK_TABLE), fa._cache_loaded
    fa._cache_loaded = True  # keep tests off the real user cache file
    yield fa
    fa._BLOCK_TABLE.clear()
    fa._BLOCK_TABLE.update(saved)
    fa._cache_loaded = saved_loaded


def test_block_config_defaults_and_override(block_table):
    fa = block_table
    cfg = fa.get_block_config(333, 64, jnp.float32)
    assert cfg == fa.BlockConfig()  # no entry -> the swept defaults
    fa.set_block_config(333, 64, jnp.float32, fa.BlockConfig(128, 256,
                                                             128, 128))
    # t buckets by the padded pow2: 333 and 500 share the 512 entry
    assert fa.get_block_config(500, 64, jnp.float32).block_k == 256
    assert fa.get_block_config(600, 64, jnp.float32) == fa.BlockConfig()


def test_block_cache_roundtrip(block_table, tmp_path):
    fa = block_table
    path = str(tmp_path / "blocks.json")
    fa.set_block_config(256, 32, jnp.bfloat16, fa.BlockConfig(256, 128,
                                                              128, 128))
    fa.save_block_cache(path)
    fa._BLOCK_TABLE.clear()
    assert fa.get_block_config(256, 32, jnp.bfloat16) == fa.BlockConfig()
    assert fa.load_block_cache(path) >= 1
    assert fa.get_block_config(256, 32, jnp.bfloat16).block_q == 256
    # a garbled cache is ignored, not fatal
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    assert fa.load_block_cache(str(bad)) == 0


def test_tuned_blocks_drive_the_kernel(block_table):
    """flash_attention with no explicit blocks must consult the table —
    and stay correct with a deliberately odd tuned entry."""
    fa = block_table
    b, h, t, d = 1, 2, 300, 32
    kq, kk, kv = jax.random.split(jax.random.key(3), 3)
    q = jax.random.normal(kq, (b, h, t, d))
    k = jax.random.normal(kk, (b, h, t, d))
    v = jax.random.normal(kv, (b, h, t, d))
    fa.set_block_config(t, d, q.dtype, fa.BlockConfig(128, 256, 128, 128))
    out = flash_attention(q, k, v)  # blocks=None -> table entry
    ref = causal_attention_xla(q, k, v)
    assert jnp.abs(out - ref).max() < 1e-5


def test_autotune_caches_winner(block_table, tmp_path, monkeypatch):
    """autotune_block_config sweeps, records the winner in the table, and
    persists it through the JSON cache when asked."""
    fa = block_table
    monkeypatch.setenv("FLASH_BLOCKS_CACHE", str(tmp_path / "fb.json"))
    best = fa.autotune_block_config(128, 16, jnp.float32, batch_heads=2,
                                    sweep=(128,), iters=1, warmup=0,
                                    write_cache=True)
    assert best == fa.BlockConfig(128, 128, 128, 128)
    assert fa.get_block_config(128, 16, jnp.float32) == best
    fa._BLOCK_TABLE.clear()
    assert fa.load_block_cache() >= 1  # reads FLASH_BLOCKS_CACHE
    assert fa.get_block_config(128, 16, jnp.float32) == best


# ---- model-level sequence bucketing (attn_t_real) ----


@pytest.mark.parametrize("attn_impl", ["xla", "flash"])
def test_model_seq_bucket_matches_unbucketed(attn_impl):
    """A bucket-padded batch (t=200 real in a t=256 buffer, IGNORE_INDEX
    pad targets) through a model with attn_t_real must reproduce the plain
    model's loss AND grads exactly — the pad-aware bucketing acceptance
    bar at model level."""
    from distributed_pytorch_from_scratch_tpu.config import IGNORE_INDEX

    cfg = ModelConfig(attn_dim=64, ffn_dim=128, num_heads=4, num_layers=2,
                      vocab_size=128, maxlen=200, compute_dtype="float32")
    mesh = make_mesh(MeshConfig(dp=1, tp=2))
    tr, tp_ = 200, 256
    m_plain = Transformer(cfg, tp_size=2, attn_impl=attn_impl, remat=False)
    m_buck = Transformer(cfg, tp_size=2, attn_impl=attn_impl, remat=False,
                         attn_t_real=tr)
    params = jax.device_put(m_plain.init(jax.random.key(0)),
                            m_plain.shardings(mesh))
    b = 4
    ids = jax.random.randint(jax.random.key(3), (b, tr), 0, cfg.vocab_size)
    tgt = jnp.roll(ids, -1, axis=1)
    pos = jnp.tile(jnp.arange(tr, dtype=jnp.int32)[None], (b, 1))
    ids_p = jnp.pad(ids, ((0, 0), (0, tp_ - tr)))
    tgt_p = jnp.pad(tgt, ((0, 0), (0, tp_ - tr)),
                    constant_values=IGNORE_INDEX)
    pos_p = jnp.pad(pos, ((0, 0), (0, tp_ - tr)), mode="edge")

    l0 = m_plain.make_loss(mesh)(params, ids, tgt, pos)
    l1 = m_buck.make_loss(mesh)(params, ids_p, tgt_p, pos_p)
    np.testing.assert_allclose(float(l1), float(l0), atol=1e-6)
    g0 = jax.grad(lambda p: m_plain.make_loss(mesh)(p, ids, tgt, pos))(
        params)
    g1 = jax.grad(lambda p: m_buck.make_loss(mesh)(p, ids_p, tgt_p,
                                                   pos_p))(params)
    jax.tree.map(lambda a, b_: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b_), atol=1e-5), g0, g1)


def test_model_t_real_requires_cp1():
    cfg = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=4, num_layers=2,
                      vocab_size=64, maxlen=64)
    with pytest.raises(ValueError, match="cp_size"):
        Transformer(cfg, cp_size=2, attn_t_real=48)
    with pytest.raises(ValueError, match="attn_t_real"):
        Transformer(cfg, attn_t_real=0)
    # MoE: the router sees every position — pad tokens would claim expert
    # capacity and inflate the aux losses, so bucketing must refuse
    import dataclasses
    moe_cfg = dataclasses.replace(cfg, num_experts=4)
    with pytest.raises(ValueError, match="MoE"):
        Transformer(moe_cfg, attn_t_real=48)


@pytest.mark.slow
@pytest.mark.parametrize("group", [1, 2, 4])
@pytest.mark.parametrize("t", [96, 256])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_gqa_kernel_shape_sweep(group, t, dtype):
    """Broader (group, t, dtype) sweep of the GQA-routed kernels ahead of
    hardware: forward vs the repeat+dense oracle at both the fused
    (t<=128) and split block paths."""
    from distributed_pytorch_from_scratch_tpu.ops.attention import (
        causal_attention_xla)

    key = jax.random.key(group * 1000 + t)
    b, hkv, d = 2, 2, 32
    hq = hkv * group
    q = jax.random.normal(jax.random.fold_in(key, 1), (b, hq, t, d), dtype)
    k = jax.random.normal(jax.random.fold_in(key, 2), (b, hkv, t, d), dtype)
    v = jax.random.normal(jax.random.fold_in(key, 3), (b, hkv, t, d), dtype)
    ref = causal_attention_xla(q, k, v)
    out = flash_attention(q, k, v, block_q=128, block_k=128)
    atol = 3e-2 if dtype == jnp.bfloat16 else 2e-5
    np.testing.assert_allclose(out.astype(jnp.float32),
                               ref.astype(jnp.float32), atol=atol)
