"""Pallas flash-attention kernel vs the naive XLA oracle.

The reference has no fused attention at all (naive O(T^2) masked softmax,
`/root/reference/models/model.py:73-77`); the oracle here is our XLA
mirror of that math, so equivalence to it is equivalence to the reference.
Runs in Pallas interpreter mode on CPU (same kernel code compiles on TPU).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributed_pytorch_from_scratch_tpu import (MeshConfig, ModelConfig,
                                                  Transformer, make_mesh)
from distributed_pytorch_from_scratch_tpu.ops.attention import (
    causal_attention_xla)
from distributed_pytorch_from_scratch_tpu.ops.pallas.flash_attention import (
    flash_attention)


@pytest.mark.parametrize("shape", [(2, 4, 128, 64), (1, 2, 300, 64),
                                   (2, 2, 513, 32), (1, 8, 1000, 64)])
def test_forward_matches_oracle_f32(shape):
    b, h, t, d = shape
    kq, kk, kv = jax.random.split(jax.random.key(t), 3)
    q = jax.random.normal(kq, shape, jnp.float32)
    k = jax.random.normal(kk, shape, jnp.float32)
    v = jax.random.normal(kv, shape, jnp.float32)
    ref = causal_attention_xla(q, k, v)
    out = flash_attention(q, k, v)
    assert jnp.abs(ref - out).max() < 1e-5


def test_forward_matches_oracle_bf16():
    shape = (2, 4, 256, 64)
    kq, kk, kv = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(kq, shape, jnp.bfloat16)
    k = jax.random.normal(kk, shape, jnp.bfloat16)
    v = jax.random.normal(kv, shape, jnp.bfloat16)
    ref = causal_attention_xla(q, k, v).astype(jnp.float32)
    out = flash_attention(q, k, v).astype(jnp.float32)
    # bf16 storage + f32-vs-bf16 score accumulation: ~1e-2 quantisation
    assert jnp.abs(ref - out).max() < 3e-2


def test_gradients_match_oracle():
    shape = (2, 2, 320, 64)
    kq, kk, kv, kg = jax.random.split(jax.random.key(1), 4)
    q = jax.random.normal(kq, shape, jnp.float32)
    k = jax.random.normal(kk, shape, jnp.float32)
    v = jax.random.normal(kv, shape, jnp.float32)
    g = jax.random.normal(kg, shape, jnp.float32)

    gr = jax.grad(lambda *a: jnp.vdot(causal_attention_xla(*a), g), (0, 1, 2))(q, k, v)
    gf = jax.grad(lambda *a: jnp.vdot(flash_attention(*a), g), (0, 1, 2))(q, k, v)
    for a, b in zip(gr, gf):
        assert jnp.abs(a - b).max() < 1e-4


def test_gradients_match_oracle_multiblock():
    """Small explicit block sizes force the split dq/dkv backward kernels —
    the fused single-block backward handles every default-sized case, so
    without this the multi-block path would lose coverage."""
    shape = (1, 2, 320, 64)
    kq, kk, kv, kg = jax.random.split(jax.random.key(5), 4)
    q = jax.random.normal(kq, shape, jnp.float32)
    k = jax.random.normal(kk, shape, jnp.float32)
    v = jax.random.normal(kv, shape, jnp.float32)
    g = jax.random.normal(kg, shape, jnp.float32)

    def fl(*a):
        return flash_attention(*a, block_q=128, block_k=128,
                               bwd_block_q=128, bwd_block_k=128)

    gr = jax.grad(lambda *a: jnp.vdot(causal_attention_xla(*a), g), (0, 1, 2))(q, k, v)
    gf = jax.grad(lambda *a: jnp.vdot(fl(*a), g), (0, 1, 2))(q, k, v)
    for a, b in zip(gr, gf):
        assert jnp.abs(a - b).max() < 1e-4


def test_flash_under_shard_map():
    """The kernel runs per-shard inside shard_map (local heads), like in
    the TP transformer."""
    mesh = make_mesh(MeshConfig(dp=1, tp=4))
    shape = (2, 8, 256, 32)
    kq, kk, kv = jax.random.split(jax.random.key(2), 3)
    q = jax.random.normal(kq, shape, jnp.float32)
    k = jax.random.normal(kk, shape, jnp.float32)
    v = jax.random.normal(kv, shape, jnp.float32)

    fn = jax.jit(jax.shard_map(
        lambda q, k, v: flash_attention(q, k, v),
        mesh=mesh, in_specs=(P(None, "tp"),) * 3, out_specs=P(None, "tp")))
    out = fn(q, k, v)
    ref = causal_attention_xla(q, k, v)
    assert jnp.abs(ref - out).max() < 1e-5

    # backward under shard_map too (exercises the vma tags on the dq/dk/dv
    # pallas_call out_shapes, which only fail at trace time on TPU otherwise)
    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)

    g_fl = jax.jit(jax.grad(loss(
        jax.shard_map(flash_attention, mesh=mesh,
                      in_specs=(P(None, "tp"),) * 3,
                      out_specs=P(None, "tp"))), argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(loss(causal_attention_xla), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_fl):
        assert jnp.abs(a - b).max() < 1e-4


def test_transformer_attn_impl_flash_matches_xla():
    """Full TP model forward with attn_impl='flash' == attn_impl='xla'."""
    cfg = ModelConfig(attn_dim=64, ffn_dim=128, num_heads=4, num_layers=2,
                      vocab_size=128, maxlen=160, compute_dtype="float32")
    mesh = make_mesh(MeshConfig(dp=2, tp=4))
    m_xla = Transformer(cfg, tp_size=4, attn_impl="xla")
    m_fla = Transformer(cfg, tp_size=4, attn_impl="flash")
    params = m_xla.init(jax.random.key(0))
    params = jax.device_put(params, m_xla.shardings(mesh))

    b, t = 4, 160
    ids = jax.random.randint(jax.random.key(3), (b, t), 0, cfg.vocab_size)
    pos = jnp.tile(jnp.arange(t, dtype=jnp.int32)[None, :], (b, 1))

    lo_x = m_xla.make_forward(mesh)(params, ids, pos)
    lo_f = m_fla.make_forward(mesh)(params, ids, pos)
    assert jnp.abs(lo_x - lo_f).max() < 1e-4


# ---- grouped-query (GQA) kernel routing: no K/V repeat in HBM ----


@pytest.mark.parametrize("t,block", [(64, 128), (200, 128)])
def test_gqa_kernel_matches_repeat_oracle(t, block):
    """hkv < hq routed inside the kernels (fused single-block at t=64,
    split dq/dkv kernels at t=200) vs the repeat+dense oracle."""
    from distributed_pytorch_from_scratch_tpu.ops.attention import (
        causal_attention_xla)

    key = jax.random.key(5)
    b, hq, hkv, d = 2, 8, 2, 16
    q = jax.random.normal(jax.random.fold_in(key, 1), (b, hq, t, d))
    k = jax.random.normal(jax.random.fold_in(key, 2), (b, hkv, t, d))
    v = jax.random.normal(jax.random.fold_in(key, 3), (b, hkv, t, d))
    ref = causal_attention_xla(q, k, v)
    out = flash_attention(q, k, v, block_q=block, block_k=block)
    np.testing.assert_allclose(out, ref, atol=2e-5)

    loss = lambda fn: lambda *a: jnp.sum(fn(*a) ** 2)
    g_ref = jax.grad(loss(causal_attention_xla), argnums=(0, 1, 2))(q, k, v)
    g_out = jax.grad(
        loss(lambda q, k, v: flash_attention(q, k, v, block_q=block,
                                             block_k=block)),
        argnums=(0, 1, 2))(q, k, v)
    for name, a, b_ in zip("qkv", g_ref, g_out):
        np.testing.assert_allclose(b_, a, atol=5e-5, err_msg=f"d{name}")
        # dk/dv stay at the kv head count — nothing materialised the repeat
    assert g_out[1].shape == k.shape and g_out[2].shape == v.shape


def test_gqa_rejects_nondivisible_heads():
    q = jnp.zeros((1, 6, 64, 16))
    kv = jnp.zeros((1, 4, 64, 16))
    with pytest.raises(ValueError, match="multiple of kv heads"):
        flash_attention(q, kv, kv)


# ---- positional block kernel (ring attention building block) ----


def test_block_attention_matches_xla_block():
    """Pallas positional kernel vs the dense XLA block math, including an
    all-dead query row (position earlier than every kv) and GQA heads."""
    from distributed_pytorch_from_scratch_tpu.ops.pallas.flash_attention import (
        block_attention)
    from distributed_pytorch_from_scratch_tpu.ops.ring_attention import (
        _BIG_NEG, _block_attn_xla)

    key = jax.random.key(7)
    b, hq, hkv, tq, tk, d = 2, 4, 2, 96, 160, 16
    q = jax.random.normal(jax.random.fold_in(key, 1), (b, hq, tq, d))
    k = jax.random.normal(jax.random.fold_in(key, 2), (b, hkv, tk, d))
    v = jax.random.normal(jax.random.fold_in(key, 3), (b, hkv, tk, d))
    qp = jax.random.randint(jax.random.fold_in(key, 4), (b, tq), 100, 500)
    qp = qp.at[:, 0].set(0)  # row 0: sees nothing (all kv_pos >= 100)
    kp = jax.random.randint(jax.random.fold_in(key, 5), (b, tk), 100, 500)
    scale = 1.0 / np.sqrt(d)

    o_ref, lse_ref = _block_attn_xla(q, k, v, qp, kp, scale)
    o_k, lse_k = block_attention(q, k, v, qp, kp)
    assert bool((lse_ref[:, :, 0] <= _BIG_NEG / 2).all()), "dead row expected"
    np.testing.assert_allclose(o_k, o_ref, atol=2e-5)
    alive = lse_ref > _BIG_NEG / 2
    np.testing.assert_allclose(jnp.where(alive, lse_k, 0.0),
                               jnp.where(alive, lse_ref, 0.0), atol=2e-5)

    def loss(fn):
        def inner(q, k, v):
            o, lse = fn(q, k, v)
            keep = lse > _BIG_NEG / 2
            return jnp.sum(o.astype(jnp.float32) ** 2) + jnp.sum(
                jnp.where(keep, lse, 0.0) ** 2)
        return inner

    g_ref = jax.grad(loss(lambda q, k, v: _block_attn_xla(q, k, v, qp, kp,
                                                          scale)),
                     argnums=(0, 1, 2))(q, k, v)
    g_k = jax.grad(loss(lambda q, k, v: block_attention(q, k, v, qp, kp)),
                   argnums=(0, 1, 2))(q, k, v)
    for name, a, b_ in zip("qkv", g_ref, g_k):
        np.testing.assert_allclose(b_, a, atol=5e-5, err_msg=f"d{name}")


@pytest.mark.slow
@pytest.mark.parametrize("group", [1, 2, 4])
@pytest.mark.parametrize("t", [96, 256])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_gqa_kernel_shape_sweep(group, t, dtype):
    """Broader (group, t, dtype) sweep of the GQA-routed kernels ahead of
    hardware: forward vs the repeat+dense oracle at both the fused
    (t<=128) and split block paths."""
    from distributed_pytorch_from_scratch_tpu.ops.attention import (
        causal_attention_xla)

    key = jax.random.key(group * 1000 + t)
    b, hkv, d = 2, 2, 32
    hq = hkv * group
    q = jax.random.normal(jax.random.fold_in(key, 1), (b, hq, t, d), dtype)
    k = jax.random.normal(jax.random.fold_in(key, 2), (b, hkv, t, d), dtype)
    v = jax.random.normal(jax.random.fold_in(key, 3), (b, hkv, t, d), dtype)
    ref = causal_attention_xla(q, k, v)
    out = flash_attention(q, k, v, block_q=128, block_k=128)
    atol = 3e-2 if dtype == jnp.bfloat16 else 2e-5
    np.testing.assert_allclose(out.astype(jnp.float32),
                               ref.astype(jnp.float32), atol=atol)
