"""Mesh-elastic checkpoints + any-layout→any-layout redistribution (ISSUE 20).

The acceptance contract: a checkpoint saved under ANY (mesh, PartitionSpec,
ZeRO-stage) layout reloads under any other through `reshard/` with

* BIT-identical global param AND optimizer-moment trees (the redistribution
  is data movement, never arithmetic),
* peak host bytes == ONE leaf, not the tree — `HostMeter`-asserted, the
  streamed-executor law the `host-gather-in-reshard` lint enforces
  statically,
* the planner's minimal-transfer claim pinned by op counts and
  `bytes_moved` (a pure zero-stage change moves ZERO bytes),
* the elastic `train.py --resume` trajectory matching a same-mesh resume,
  with a versioned `reshard_event` in the metrics stream,
* a fleet replica restarted at a DIFFERENT tp width serving token-identical
  greedy output (`reshard_params` device→device + `replace_replica`),
* inexpressible targets and spec-less legacy sources refusing LOUDLY.

The reference cannot do any of this: its rank pickles only reload at the
same tp_size (SURVEY §5.4); a mesh change means retraining or a hand-rolled
conversion script.
"""

import json
import os
import re
import shutil
import subprocess
import sys

import jax
import numpy as np
import pytest

from distributed_pytorch_from_scratch_tpu.config import MeshConfig, ModelConfig
from distributed_pytorch_from_scratch_tpu.models.transformer import Transformer
from distributed_pytorch_from_scratch_tpu.reshard import (
    HostMeter, ReshardError, layouts_equal, make_layout, plan_checkpoint,
    plan_reshard, read_stamp, reshard_checkpoint, reshard_params,
    resolve_source_layout, stream_load)
from distributed_pytorch_from_scratch_tpu.runtime.mesh import make_mesh
from distributed_pytorch_from_scratch_tpu.training.checkpoint import (
    _flatten, latest_step, load_checkpoint, save_checkpoint,
    validate_checkpoint)
from distributed_pytorch_from_scratch_tpu.training.optim import init_adam_state

CFG = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=8, num_layers=2,
                  vocab_size=64, maxlen=16)


def _tree_equal(a, b):
    jax.tree.map(lambda x, y: np.testing.assert_array_equal(
        np.asarray(x), np.asarray(y)), a, b)


def _max_leaf_bytes(params, with_opt):
    n = max(np.asarray(v).nbytes for v in jax.tree.leaves(params))
    return n  # moments shard like params, so the max is the same


def _save_src(tmp, step=3, tp=4, dp=1, zero=0, seed=0, with_opt=True):
    """A stamped source checkpoint with non-trivial optimizer moments."""
    model = Transformer(CFG, tp_size=tp)
    params = model.init(jax.random.key(seed))
    opt = None
    if with_opt:
        opt = init_adam_state(params)
        opt = opt._replace(mu=jax.tree.map(lambda p: p + 0.25, opt.mu),
                           nu=jax.tree.map(lambda p: p * 0.0 + 0.5, opt.nu))
    save_checkpoint(str(tmp), step, 1.0, params, model.specs(), tp_size=tp,
                    opt_state=opt, zero_stage=zero,
                    mesh_axes=(("dp", dp), ("tp", tp)))
    return model, params, opt


# ------------------------------------------------ layout stamping (files) --

def test_save_stamps_layout_and_resolves_exactly(tmp_path):
    model, _, _ = _save_src(tmp_path, tp=4, dp=2, zero=3)
    lay, legacy = resolve_source_layout(str(tmp_path), 3,
                                        echo=lambda *a: None)
    assert not legacy
    want = make_layout((("dp", 2), ("tp", 4)), model.specs(), zero_stage=3)
    assert layouts_equal(lay, want)
    assert lay.describe() == "dp2xtp4 zero3"
    # the stamp is json inside every shard, skipped by pre-stamp readers
    with np.load(os.path.join(
            tmp_path, "tprank-0_iter-3_loss-1.0000.npz")) as npz:
        assert layouts_equal(read_stamp(npz), want)


def test_legacy_unstamped_source_is_loud_never_a_crash(tmp_path):
    model, params, opt = _save_src(tmp_path, tp=4)
    for rank in range(4):
        p = os.path.join(tmp_path, f"tprank-{rank}_iter-3_loss-1.0000.npz")
        d = dict(np.load(p))
        del d["__layout__"]
        np.savez(p, **d)

    # spec-less legacy: refuse, naming the fix
    with pytest.raises(ValueError, match="legacy checkpoint.*canonical_specs"):
        resolve_source_layout(str(tmp_path), 3)

    notes = []
    lay, legacy = resolve_source_layout(
        str(tmp_path), 3, specs=model.specs(),
        echo=lambda *a: notes.append(" ".join(map(str, a))))
    assert legacy and lay.tp == 4
    assert any("layout inferred from filenames" in n for n in notes)

    # and the legacy source still reshards bit-identically, re-stamped
    dst = make_layout((("tp", 2),), model.specs())
    paths, _, info = reshard_checkpoint(
        str(tmp_path), 3, str(tmp_path / "dst"), dst, specs=model.specs(),
        echo=lambda *a: None)
    assert info["legacy"] is True
    with np.load(paths[0]) as npz:
        assert layouts_equal(read_stamp(npz), dst)
    loaded, lopt, _ = load_checkpoint(str(tmp_path / "dst"), 3, params,
                                      model.specs(), with_opt=True)
    _tree_equal(loaded, params)
    _tree_equal(lopt.mu, opt.mu)


# ------------------------------------- file→file matrix, bit-identical ----

MATRIX = {
    # src (mesh, zero) -> dst (mesh, zero): the ISSUE-20 acceptance pairs
    "tp4_to_tp2": (dict(tp=4), dict(tp=2, dp=1, zero=0)),
    "tp4_to_tp1": (dict(tp=4), dict(tp=1, dp=1, zero=0)),
    "z3_train_to_serving": (dict(tp=4, dp=2, zero=3),
                            dict(tp=2, dp=1, zero=0)),
    "z2_to_z0": (dict(tp=2, dp=2, zero=2), dict(tp=2, dp=2, zero=0)),
}


@pytest.mark.parametrize("case", sorted(MATRIX), ids=sorted(MATRIX))
def test_reshard_checkpoint_bit_identical(tmp_path, case):
    src_kw, dst_kw = MATRIX[case]
    model, params, opt = _save_src(tmp_path / "src", **src_kw)
    dst_lay = make_layout((("dp", dst_kw["dp"]), ("tp", dst_kw["tp"])),
                          model.specs(), zero_stage=dst_kw["zero"])
    meter = HostMeter()
    paths, plan, info = reshard_checkpoint(
        str(tmp_path / "src"), 3, str(tmp_path / "dst"), dst_lay,
        meter=meter, echo=lambda *a: None)

    # the output is a first-class checkpoint at the target width
    tp_out, _ = validate_checkpoint(str(tmp_path / "dst"), 3)
    assert tp_out == dst_kw["tp"] == len(paths)
    with np.load(paths[0]) as npz:
        assert layouts_equal(read_stamp(npz), dst_lay)
        assert int(npz["__zero_stage__"]) == dst_kw["zero"]

    # bit-identical global params AND moments
    loaded, lopt, step = load_checkpoint(str(tmp_path / "dst"), 3, params,
                                         model.specs(), with_opt=True)
    assert step == 3
    _tree_equal(loaded, params)
    _tree_equal(lopt.mu, opt.mu)
    _tree_equal(lopt.nu, opt.nu)

    # peak host == one leaf, never the tree (the streamed-executor law)
    assert meter.peak <= info["max_leaf_bytes"]
    assert info["max_leaf_bytes"] == _max_leaf_bytes(params, True)
    assert meter.live == 0

    # a pure zero-stage change re-slices NOTHING: files already identical
    if src_kw.get("tp") == dst_kw["tp"]:
        assert info["bytes_moved"] == 0


def test_plan_op_pins_and_minimal_bytes(tmp_path):
    """The planner's schedule, pinned: op inventory per acceptance pair and
    the bytes_moved minimality facts (same-tp == 0; the graftcheck trace
    contract pins the lowered collective count against these same
    numbers)."""
    _save_src(tmp_path, tp=4, dp=2, zero=3)
    model = Transformer(CFG, tp_size=4)

    plan, src_lay, legacy = plan_checkpoint(
        str(tmp_path), 3, make_layout((("tp", 2),), model.specs()),
        echo=lambda *a: None)
    assert not legacy and src_lay.describe() == "dp2xtp4 zero3"
    s = plan.summary()
    # every leaf coarsens (dp-extension dropped AND tp halved): all gather
    assert s["ops"] == {"gather": 60}
    assert s["n_leaves"] == 60 and s["max_leaf_bytes"] == 16384
    assert s["bytes_moved"] == 307968

    # same mesh, zero3 -> zero3 at half width: params/moments that were
    # replicated across tp stay copies, tp-sharded leaves gather
    plan2, _, _ = plan_checkpoint(
        str(tmp_path), 3,
        make_layout((("dp", 2), ("tp", 2)), model.specs(), zero_stage=3),
        echo=lambda *a: None)
    assert plan2.summary()["ops"] == {"gather": 45, "copy": 15}

    # identity reshard: every leaf a copy, zero bytes
    plan3, _, _ = plan_checkpoint(
        str(tmp_path), 3,
        make_layout((("dp", 2), ("tp", 4)), model.specs(), zero_stage=3),
        echo=lambda *a: None)
    assert plan3.summary() == {
        "src": "dp2xtp4 zero3", "dst": "dp2xtp4 zero3",
        "ops": {"copy": 60}, "bytes_moved": 0, "n_leaves": 60,
        "max_leaf_bytes": 16384}


def test_inexpressible_layout_refuses_loudly(tmp_path):
    _save_src(tmp_path, tp=4)
    model = Transformer(CFG, tp_size=4)
    # vocab 64 does not divide 3 ways: the embedding leaf is inexpressible
    with pytest.raises(ReshardError, match="inexpressible"):
        plan_checkpoint(str(tmp_path), 3,
                        make_layout((("tp", 3),), model.specs()),
                        echo=lambda *a: None)


# --------------------------------------------- file→device (stream_load) --

def test_stream_load_elastic_zero3_bit_identical_and_bounded(tmp_path):
    """dp2xtp4 ZeRO-3 checkpoint lands on a dp2xtp2 ZeRO-3 mesh: one leaf
    on the host at a time, each device_put straight against its TARGET
    sharding — params and both moments bit-identical."""
    from distributed_pytorch_from_scratch_tpu.training.zero import (
        zero3_shardings)

    m4, params, opt = _save_src(tmp_path, step=11, tp=4, dp=2, zero=3)
    m2 = Transformer(CFG, tp_size=2)
    mesh = make_mesh(MeshConfig(dp=2, tp=2))
    p_sh = zero3_shardings(m2, mesh)
    dst_lay = make_layout(mesh, m2.canonical_specs(), zero_stage=3)
    meter = HostMeter()
    out_p, out_o, step, info = stream_load(
        str(tmp_path), 11, params, m2.canonical_specs(), dst_lay, p_sh,
        moment_shardings=p_sh, with_opt=True, meter=meter,
        echo=lambda *a: None)
    assert step == 11
    _tree_equal(out_p, params)
    _tree_equal(out_o.mu, opt.mu)
    _tree_equal(out_o.nu, opt.nu)
    # the leaves actually live under the target sharding
    for got, want in zip(jax.tree.leaves(out_p), jax.tree.leaves(p_sh)):
        assert got.sharding.is_equivalent_to(want, got.ndim)
    assert meter.peak <= info["max_leaf_bytes"] == _max_leaf_bytes(params,
                                                                   True)
    assert info["ops"] == {"gather": 45, "copy": 15}
    assert meter.live == 0


def test_stream_load_refuses_moments_without_shardings(tmp_path):
    _save_src(tmp_path, step=2, tp=2)
    m2 = Transformer(CFG, tp_size=2)
    mesh = make_mesh(MeshConfig(dp=1, tp=2))
    with pytest.raises(ReshardError, match="moment_shardings"):
        stream_load(str(tmp_path), 2, m2.init(jax.random.key(0)),
                    m2.canonical_specs(),
                    make_layout(mesh, m2.canonical_specs()),
                    m2.shardings(mesh), with_opt=True,
                    echo=lambda *a: None)


# ------------------------------------------------- legacy .pth rank span --

def test_pth_span_reshards_through_interop(tmp_path):
    """The reference's torch pickles bridge through interop (loud note,
    documented host-cost exemption) and come out as a stamped npz set at
    the new width — values identical."""
    torch = pytest.importorskip("torch")  # noqa: F841
    from distributed_pytorch_from_scratch_tpu import interop

    model = Transformer(CFG, tp_size=4)
    params = model.init(jax.random.key(5))
    interop.export_reference_checkpoint(params, CFG, 4, str(tmp_path / "pth"),
                                        7, loss=1.0)
    notes = []
    dst_lay = make_layout((("tp", 2),), model.specs())
    paths, _, info = reshard_checkpoint(
        str(tmp_path / "pth"), 7, str(tmp_path / "dst"), dst_lay,
        specs=model.specs(), ext="pth", cfg=CFG,
        echo=lambda *a: notes.append(" ".join(map(str, a))))
    assert info["legacy"] is True
    assert any("not streamable" in n for n in notes)
    tp_out, _ = validate_checkpoint(str(tmp_path / "dst"), 7)
    assert tp_out == 2
    with np.load(paths[0]) as npz:
        assert layouts_equal(read_stamp(npz), dst_lay)
    loaded, _, _ = load_checkpoint(str(tmp_path / "dst"), 7, params,
                                   model.specs())
    _tree_equal(loaded, params)


# ------------------------------------- fleet replica restart at new width --

SCFG = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=8, num_layers=2,
                   vocab_size=96, maxlen=64)
_BASE = [0, 5, 17, 33, 60, 2, 4, 6]
SPROMPTS = [_BASE + [7], _BASE + [9, 11], _BASE + [3, 5, 7, 11],
            _BASE + [13]]


def _sengine(tp=1, seed=7, params=None):
    from distributed_pytorch_from_scratch_tpu.serving.engine import PagedEngine
    mesh = make_mesh(MeshConfig(dp=1, tp=tp))
    model = Transformer(SCFG, tp_size=tp)
    if params is None:
        params = jax.device_put(model.init(jax.random.key(seed)),
                                model.shardings(mesh))
    return PagedEngine(model, mesh, params, buf_len=32, eos_id=1,
                       num_slots=4, page_size=8, prefill_chunk=8)


def _sreqs(rid0):
    from distributed_pytorch_from_scratch_tpu.serving.engine import Request
    return [Request(rid=rid0 + i, prompt=list(p), max_new=6)
            for i, p in enumerate(SPROMPTS)]


def test_fleet_width_restart_token_identical(tmp_path):
    """A live tp1 replica restarts at tp2 mid-traffic (`reshard_params`
    device→device, `replace_replica` under the old name): the second wave
    is greedy token-identical to a single never-restarted engine, and the
    `replica_restart` event carries the reshard plan summary."""
    from distributed_pytorch_from_scratch_tpu.serving.router import (
        FleetRouter)
    from distributed_pytorch_from_scratch_tpu.training.metrics import (
        MetricsWriter)

    single = _sengine(tp=1)
    refs = {}
    for rid0 in (0, 100):
        for r in _sreqs(rid0):
            single.submit(r)
        for r in single.run_to_completion():
            refs[r.rid] = list(r.tokens)
    assert len(refs) == 8 and any(refs.values())

    w = MetricsWriter(str(tmp_path), process_index=0)
    # prefix_weight off so the shared-prefix burst actually spreads and
    # the restarted replica serves wave-B requests
    router = FleetRouter([_sengine(tp=1), _sengine(tp=1)],
                         prefix_weight=0.0, writer=w)
    got = {}
    for r in _sreqs(0):
        router.submit(r)
    for r in router.run_to_completion():
        got[r.rid] = list(r.tokens)

    # restart r1 at DOUBLE width: plan the layout change, re-lay the live
    # params per leaf, attach the new engine under the old name
    old = dict(router.replicas)["r1"]
    assert SCFG.padded_vocab_size(1) == SCFG.padded_vocab_size(2)
    m2 = Transformer(SCFG, tp_size=2)
    flat = _flatten(old._params_in, "param")
    plan = plan_reshard(sorted(flat),
                        {k: tuple(v.shape) for k, v in flat.items()},
                        {k: v.dtype.itemsize for k, v in flat.items()},
                        make_layout((("tp", 1),), old.model.specs()),
                        make_layout((("tp", 2),), m2.specs()))
    # widening is pure slicing: local, no wire collective
    assert set(plan.summary()["ops"]) <= {"slice", "copy"}
    mesh2 = make_mesh(MeshConfig(dp=1, tp=2))
    params2 = reshard_params(old._params_in, mesh2, m2.specs())
    jax.block_until_ready(params2)
    router.replace_replica("r1", _sengine(tp=2, params=params2),
                           reshard=plan.summary())

    before = dict(router.dispatched)
    for r in _sreqs(100):
        router.submit(r)
    for r in router.run_to_completion():
        got[r.rid] = list(r.tokens)
    assert router.dispatched["r1"] > before["r1"], \
        "the restarted tp2 replica never served — the identity claim is vacuous"
    assert got == refs

    w.close()
    evs = [json.loads(l) for l in open(tmp_path / "metrics.jsonl")]
    restart = [e for e in evs if e.get("tag") == "replica_restart"]
    assert len(restart) == 1 and restart[0]["replica"] == "r1"
    assert restart[0]["reshard"]["src"] == "single zero0"
    assert restart[0]["reshard"]["dst"] == "tp2 zero0"


# ------------------------------------------- elastic train.py --resume ----

TEXTS = ["the king rode out at dawn with his men",
         "a quiet morning on the river bank",
         "she sold sea shells by the sea shore",
         "to be or not to be that is the question"] * 4

STEP_RE = re.compile(r"^step (\d+)/\d+ -> avg loss ([0-9.]+)", re.M)


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    from distributed_pytorch_from_scratch_tpu.data.tokenizer import (
        pre_tokenize, train_bpe)
    d = tmp_path_factory.mktemp("reshard_corpus")
    text_json = d / "texts.json"
    with open(text_json, "w") as f:
        json.dump({"train": TEXTS, "validation": TEXTS[:2]}, f)
    tok = d / "tokenizer.json"
    # vocab divisible by 4 AND 2: padded_vocab_size must agree across the
    # two widths or the elastic trees would have different shapes
    train_bpe(str(text_json), str(tok), vocab_size=272)
    tokens = d / "tokens.json"
    pre_tokenize(str(text_json), str(tokens), str(tok))
    return tokens


def _train(args, env):
    return subprocess.run(
        [sys.executable, "-m", "distributed_pytorch_from_scratch_tpu.train"]
        + args, capture_output=True, text=True, timeout=900, env=env)


@pytest.mark.slow
def test_elastic_resume_matches_offline_reshard_resume(corpus, tmp_path):
    """train --resume on a DIFFERENT mesh (dp2xtp4 -> dp2xtp2) routes the
    checkpoint through the in-process reshard plan and continues with
    EXACTLY the loss trajectory of the offline path (scripts/
    reshard_ckpt.py to tp2 files, then a normal same-mesh resume): both
    arms run identical dp2xtp2 math from bit-identical state, so the
    printed losses must agree to every digit. The elastic arm also leaves
    a schema-valid `reshard_event` in the metrics stream.

    (A tp4-arm trajectory is NOT pinned here: Adam's rsqrt(nu) amplifies
    the ~1e-4 cross-width reassociation noise the single-step equivalence
    tests allow into per-mille loss drift within 3 steps — a float fact,
    not a reshard one.)"""
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONUNBUFFERED": "1"}
    env.pop("PALLAS_AXON_POOL_IPS", None)
    if "xla_force_host_platform_device_count" not in env.get("XLA_FLAGS", ""):
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                            + " --xla_force_host_platform_device_count=8")
    common = ["--data_path", str(corpus),
              "--attn_dim", "32", "--ffn_dim", "64", "--num_heads", "4",
              "--num_layers", "2", "--maxlen", "32", "--batch_size", "4",
              "--log_interval", "1", "--warmup_steps", "2", "--lr", "1e-3",
              "--dp_size", "2"]
    base_dir = str(tmp_path / "base")
    base = _train(common + ["--save_dir", base_dir, "--tp_size", "4",
                            "--max_steps", "3", "--save_interval", "3"], env)
    assert base.returncode == 0, base.stdout + base.stderr
    assert latest_step(base_dir) == 3

    # arm A: the offline CLI reshards the files to dp2xtp2, then a plain
    # same-mesh resume picks them up (no elastic path involved)
    a_dir, b_dir = str(tmp_path / "a"), str(tmp_path / "b")
    shutil.copytree(base_dir, b_dir)
    cli = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(__file__), os.pardir,
                                      "scripts", "reshard_ckpt.py"),
         "--src", base_dir, "--dst", a_dir, "--tp", "2", "--dp", "2"],
        capture_output=True, text=True, timeout=300, env=env)
    assert cli.returncode == 0, cli.stdout + cli.stderr
    rec = json.loads(cli.stdout.strip().splitlines()[-1])
    assert rec["src"] == "dp2xtp4 zero0" and rec["dst"] == "dp2xtp2 zero0"
    assert rec["peak_host_bytes"] <= rec["max_leaf_bytes"]

    resume = ["--tp_size", "2", "--max_steps", "6", "--save_interval",
              "1000", "--resume"]
    same = _train(common + ["--save_dir", a_dir] + resume, env)
    assert same.returncode == 0, same.stdout + same.stderr
    assert "resumed from iter 3" in same.stdout
    assert "elastic resume" not in same.stdout

    # arm B: the in-process elastic path, straight off the tp4 files
    elastic = _train(common + ["--save_dir", b_dir] + resume, env)
    assert elastic.returncode == 0, elastic.stdout + elastic.stderr
    assert "elastic resume: iter 3" in elastic.stdout
    assert "resharded dp2xtp4 zero0 -> dp2xtp2 zero0" in elastic.stdout

    traj_a = {int(s): float(l) for s, l in STEP_RE.findall(same.stdout)}
    traj_b = {int(s): float(l) for s, l in STEP_RE.findall(elastic.stdout)}
    assert sorted(traj_a) == sorted(traj_b) == [4, 5, 6]
    assert [traj_a[s] for s in (4, 5, 6)] == [traj_b[s] for s in (4, 5, 6)]

    # the lineage record forensics joins on (schema v7)
    evs = []
    logs = os.path.join(b_dir, "logs")
    for name in sorted(os.listdir(logs)):
        if name.endswith(".jsonl"):
            evs += [json.loads(l) for l in open(os.path.join(logs, name))]
    rev = [e for e in evs if e.get("tag") == "reshard_event"]
    assert len(rev) == 1, [e.get("tag") for e in evs]
    assert rev[0]["src_layout"] == "dp2xtp4 zero0"
    assert rev[0]["dst_layout"] == "dp2xtp2 zero0"
    assert rev[0]["bytes_moved"] > 0
    assert rev[0]["plan_ops"] and rev[0]["wall_ms"] >= 0
    assert rev[0]["peak_host_bytes"] > 0


def test_gate_treats_reshard_record_as_latency():
    """The reshard record's headline `value` IS a wall latency (unit
    "ms"): a FASTER second run must pass the gate and a slower-past-band
    one must fail, and reshard_bytes_moved stays must-not-grow — the
    drive that caught `value` riding the throughput branch."""
    import importlib.util

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "check_bench_regression.py")
    spec = importlib.util.spec_from_file_location("_rs_gate", path)
    gate = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(gate)

    base = {"metric": "reshard wall ms (tiny, dp2xtp4 zero3 -> tp2 zero0,"
                      " moments included, streamed leaf-at-a-time)",
            "value": 150.0, "unit": "ms", "reshard_ms": 150.0,
            "reshard_bytes_moved": 9_510_912}
    faster = dict(base, value=90.0, reshard_ms=90.0)
    slower = dict(base, value=300.0, reshard_ms=300.0)
    mover = dict(base, reshard_bytes_moved=19_021_824)

    by = {c["field"]: c for c in gate.metric_checks(faster, base,
                                                    10.0, 25.0)[0]}
    assert by["value"]["direction"] == "down" and by["value"]["ok"]
    assert by["reshard_ms"]["ok"]
    assert by["reshard_bytes_moved"]["direction"] == "down"
    assert by["reshard_bytes_moved"]["ok"]

    by = {c["field"]: c for c in gate.metric_checks(slower, base,
                                                    10.0, 25.0)[0]}
    assert not by["value"]["ok"] and not by["reshard_ms"]["ok"]

    by = {c["field"]: c for c in gate.metric_checks(mover, base,
                                                    10.0, 25.0)[0]}
    assert not by["reshard_bytes_moved"]["ok"]
