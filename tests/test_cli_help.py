"""Every CLI's --help must render (a stray % in an argparse help string
raises at format time — caught here once, kept caught)."""

import os
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.parametrize("target", [
    ["-m", "distributed_pytorch_from_scratch_tpu.train"],
    ["-m", "distributed_pytorch_from_scratch_tpu.evaluate"],
    ["bench.py"],
])
def test_help_renders(target):
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("PALLAS_AXON_POOL_IPS", None)
    p = subprocess.run([sys.executable, *target, "--help"],
                       capture_output=True, text=True, timeout=240,
                       cwd=REPO_ROOT, env=env)
    assert p.returncode == 0, p.stderr[-1500:]
    assert "usage:" in p.stdout
