"""Optimizer equivalence: our hand-rolled Adam + OneCycle vs torch.optim.

The framework never imports torch; here torch-CPU serves as the oracle for
the exact semantics the reference trained with
(`/root/reference/train.py:83-84`): `optim.Adam` + `OneCycleLR` including
torch's default beta1 cycling (cycle_momentum=True).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")

from distributed_pytorch_from_scratch_tpu.config import OptimizerConfig
from distributed_pytorch_from_scratch_tpu.training.optim import (
    adam_update, init_adam_state, onecycle_lr)


def test_onecycle_lr_matches_torch():
    cfg = OptimizerConfig(lr=3e-4, warmup_steps=20, max_steps=100)
    p = torch.nn.Parameter(torch.zeros(1))
    opt = torch.optim.Adam([p], lr=cfg.lr)
    sched = torch.optim.lr_scheduler.OneCycleLR(
        opt, cfg.lr, cfg.max_steps, pct_start=cfg.warmup_steps / cfg.max_steps)

    ours_lr, ours_b1, torch_lr, torch_b1 = [], [], [], []
    for step in range(cfg.max_steps):
        torch_lr.append(opt.param_groups[0]["lr"])
        torch_b1.append(opt.param_groups[0]["betas"][0])
        lr, b1 = onecycle_lr(cfg, jnp.asarray(step))
        ours_lr.append(float(lr))
        ours_b1.append(float(b1))
        opt.step()
        sched.step()

    # f32 vs f64 schedule computation: tiny absolute differences are fine
    np.testing.assert_allclose(ours_lr, torch_lr, rtol=1e-4, atol=1e-10)
    np.testing.assert_allclose(ours_b1, torch_b1, rtol=1e-4)


def test_adam_onecycle_training_matches_torch():
    """Full loop: 150 steps of Adam+OneCycle on a quadratic, params must track
    torch to float32 precision."""
    cfg = OptimizerConfig(lr=1e-2, warmup_steps=30, max_steps=150)
    rng = np.random.RandomState(0)
    w0 = rng.randn(8, 4).astype(np.float32)
    tgt = rng.randn(8, 4).astype(np.float32)

    # torch side
    wt = torch.nn.Parameter(torch.tensor(w0.copy()))
    opt = torch.optim.Adam([wt], lr=cfg.lr)
    sched = torch.optim.lr_scheduler.OneCycleLR(
        opt, cfg.lr, cfg.max_steps, pct_start=cfg.warmup_steps / cfg.max_steps)
    tgt_t = torch.tensor(tgt)

    # ours
    params = {"w": jnp.asarray(w0.copy())}
    state = init_adam_state(params)

    @jax.jit
    def step_fn(params, state):
        def loss_fn(p):
            return jnp.sum((p["w"] - jnp.asarray(tgt)) ** 2)
        grads = jax.grad(loss_fn)(params)
        return adam_update(cfg, params, grads, state)

    for i in range(cfg.max_steps):
        loss = torch.sum((wt - tgt_t) ** 2)
        opt.zero_grad(); loss.backward(); opt.step(); sched.step()
        params, state = step_fn(params, state)

    # f32 accumulation over 150 steps vs torch's f64 schedule internals
    np.testing.assert_allclose(np.asarray(params["w"]), wt.detach().numpy(),
                               rtol=1e-3, atol=1e-5)


def test_adam_state_pytree_matches_params():
    params = {"a": jnp.ones((3, 2)), "b": {"c": jnp.zeros((5,))}}
    st = init_adam_state(params)
    assert jax.tree.structure(st.mu) == jax.tree.structure(params)
    assert int(st.step) == 0
    new_p, new_st = adam_update(OptimizerConfig(max_steps=10, warmup_steps=2),
                                params, jax.tree.map(jnp.ones_like, params), st)
    assert int(new_st.step) == 1
    assert jax.tree.structure(new_p) == jax.tree.structure(params)


def test_clip_grad_norm_matches_torch():
    """clip_by_global_norm == torch.nn.utils.clip_grad_norm_: one global L2
    norm over every leaf, scale only when it exceeds max_norm."""
    from distributed_pytorch_from_scratch_tpu.training.optim import (
        clip_by_global_norm)

    rng = np.random.RandomState(1)
    g1 = rng.randn(8, 4).astype(np.float32) * 3.0
    g2 = rng.randn(16).astype(np.float32) * 0.1

    for max_norm in (0.5, 5.0, 1e6):  # clipped, clipped, no-op
        pt = [torch.nn.Parameter(torch.zeros(8, 4)),
              torch.nn.Parameter(torch.zeros(16))]
        pt[0].grad = torch.tensor(g1.copy())
        pt[1].grad = torch.tensor(g2.copy())
        torch.nn.utils.clip_grad_norm_(pt, max_norm)

        ours = clip_by_global_norm({"a": jnp.asarray(g1),
                                    "b": jnp.asarray(g2)}, max_norm)
        np.testing.assert_allclose(np.asarray(ours["a"]),
                                   pt[0].grad.numpy(), rtol=1e-6, atol=1e-7)
        np.testing.assert_allclose(np.asarray(ours["b"]),
                                   pt[1].grad.numpy(), rtol=1e-6, atol=1e-7)


def test_clip_grad_norm_in_adam_update():
    """OptimizerConfig.clip_grad_norm=NORM routes through adam_update: a
    huge gradient must produce the same update as its pre-clipped version."""
    cfg = OptimizerConfig(lr=1e-2, warmup_steps=2, max_steps=10,
                          clip_grad_norm=1.0)
    params = {"w": jnp.ones((4,))}
    big = {"w": jnp.full((4,), 100.0)}
    clipped = {"w": big["w"] * (1.0 / (jnp.linalg.norm(big["w"]) + 1e-6))}

    p1, _ = adam_update(cfg, params, big, init_adam_state(params))
    cfg_off = OptimizerConfig(lr=1e-2, warmup_steps=2, max_steps=10)
    p2, _ = adam_update(cfg_off, params, clipped, init_adam_state(params))
    np.testing.assert_allclose(np.asarray(p1["w"]), np.asarray(p2["w"]),
                               rtol=1e-6)


def test_adamw_cosine_training_matches_torch():
    """AdamW (decoupled weight decay) + warmup/cosine schedule vs
    torch.optim.AdamW + LambdaLR implementing the identical schedule
    formula — 150 steps on a quadratic, params track to f32 precision."""
    import math
    cfg = OptimizerConfig(lr=1e-2, warmup_steps=30, max_steps=150,
                          weight_decay=0.1, lr_schedule="cosine")
    rng = np.random.RandomState(1)
    w0 = rng.randn(8, 4).astype(np.float32)
    tgt = rng.randn(8, 4).astype(np.float32)

    def lam(step):  # lr multiplier at 0-based step (cosine_lr's formula)
        if step < cfg.warmup_steps:
            return min(1.0, (step + 1) / cfg.warmup_steps)
        pct = min(1.0, (step - cfg.warmup_steps)
                  / max(cfg.max_steps - cfg.warmup_steps, 1))
        lo = cfg.cosine_min_ratio
        return lo + (1.0 - lo) / 2.0 * (1.0 + math.cos(math.pi * pct))

    wt = torch.nn.Parameter(torch.tensor(w0.copy()))
    opt = torch.optim.AdamW([wt], lr=cfg.lr, weight_decay=cfg.weight_decay)
    sched = torch.optim.lr_scheduler.LambdaLR(opt, lam)
    tgt_t = torch.tensor(tgt)

    params = {"w": jnp.asarray(w0.copy())}
    state = init_adam_state(params)

    @jax.jit
    def step_fn(params, state):
        def loss_fn(p):
            return jnp.sum((p["w"] - jnp.asarray(tgt)) ** 2)
        grads = jax.grad(loss_fn)(params)
        return adam_update(cfg, params, grads, state)

    for _ in range(cfg.max_steps):
        loss = torch.sum((wt - tgt_t) ** 2)
        opt.zero_grad(); loss.backward(); opt.step(); sched.step()
        params, state = step_fn(params, state)

    np.testing.assert_allclose(np.asarray(params["w"]), wt.detach().numpy(),
                               rtol=1e-3, atol=1e-5)


def test_cosine_schedule_values():
    from distributed_pytorch_from_scratch_tpu.training.optim import cosine_lr
    cfg = OptimizerConfig(lr=1e-3, warmup_steps=10, max_steps=110,
                          lr_schedule="cosine")
    lr0, b1 = cosine_lr(cfg, jnp.asarray(0))
    assert abs(float(lr0) - 1e-4) < 1e-9          # (0+1)/10 of lr
    assert float(b1) == pytest.approx(0.9)        # beta1 NOT cycled
    lr_peak, _ = cosine_lr(cfg, jnp.asarray(9))
    assert float(lr_peak) == pytest.approx(1e-3)  # end of warmup
    lr_end, _ = cosine_lr(cfg, jnp.asarray(cfg.max_steps))
    assert float(lr_end) == pytest.approx(1e-4, rel=1e-5)  # min ratio 0.1
