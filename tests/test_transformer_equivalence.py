"""Full-model numerical equivalence: sharded Transformer vs vanilla oracle.

The working version of the reference's `tests/test_transformers.py` (which
imports a `VallinaTransformer` that doesn't exist — SURVEY quirk #1): the
tensor-parallel model must match the independent unsharded implementation on
forward logits, loss, gradients, and multi-step training loss history, on
TP-only and TPxDP meshes, in both loss modes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_from_scratch_tpu.config import (
    IGNORE_INDEX, MeshConfig, ModelConfig)
from distributed_pytorch_from_scratch_tpu.models.transformer import Transformer
from distributed_pytorch_from_scratch_tpu.models.vanilla import VanillaTransformer
from distributed_pytorch_from_scratch_tpu.runtime.mesh import make_mesh

CFG = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=8, num_layers=2,
                  vocab_size=96, maxlen=32)


def make_batch(key, batch=4, t=16, vocab=96):
    k1, k2 = jax.random.split(key)
    input_ids = jax.random.randint(k1, (batch, t), 0, vocab)
    target_ids = jax.random.randint(k2, (batch, t), 0, vocab)
    # sprinkle IGNORE_INDEX like padded positions
    mask = jax.random.bernoulli(jax.random.fold_in(key, 9), 0.2, (batch, t))
    target_ids = jnp.where(mask, IGNORE_INDEX, target_ids)
    position_ids = jnp.tile(jnp.arange(t)[None, :], (batch, 1))
    return input_ids, target_ids, position_ids


@pytest.mark.parametrize("dp,tp", [(1, 4), (2, 4), (1, 8), (2, 1)])
def test_forward_logits_match(dp, tp):
    mesh = make_mesh(MeshConfig(dp=dp, tp=tp))
    model = Transformer(CFG, tp_size=tp)
    oracle = VanillaTransformer(CFG)
    params = model.init(jax.random.key(0))
    ids, _, pos = make_batch(jax.random.key(1), batch=4, t=16)

    logits_sh = model.make_forward(mesh)(params, ids, pos)
    logits_ref = oracle.forward(params, ids, pos)
    np.testing.assert_allclose(np.asarray(logits_sh), np.asarray(logits_ref),
                               rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("mode", ["vocab_parallel", "gather"])
@pytest.mark.parametrize("dp,tp", [(1, 4), (2, 4)])
def test_loss_and_grads_match(mode, dp, tp):
    mesh = make_mesh(MeshConfig(dp=dp, tp=tp))
    model = Transformer(CFG, tp_size=tp)
    oracle = VanillaTransformer(CFG)
    params = model.init(jax.random.key(0))
    ids, tgt, pos = make_batch(jax.random.key(2))

    loss_fn = model.make_loss(mesh, mode=mode)
    l_sh, g_sh = jax.value_and_grad(loss_fn)(params, ids, tgt, pos)
    l_ref, g_ref = jax.value_and_grad(oracle.loss)(params, ids, tgt, pos)

    np.testing.assert_allclose(l_sh, l_ref, rtol=1e-5)
    flat_sh, _ = jax.tree.flatten(g_sh)
    flat_ref, _ = jax.tree.flatten(g_ref)
    for a, b in zip(flat_sh, flat_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_non_divisible_vocab_padding():
    """vocab 100 over tp 8 -> padded to 104; the reference instead gives the
    last rank a ragged partition (`layers.py:126-131`). Losses must agree."""
    cfg = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=8, num_layers=1,
                      vocab_size=100, maxlen=16)
    tp = 8
    mesh = make_mesh(MeshConfig(dp=1, tp=tp))
    model = Transformer(cfg, tp_size=tp)
    oracle = VanillaTransformer(cfg)
    assert model.vocab_padded == 104
    params = model.init(jax.random.key(0))
    ids, tgt, pos = make_batch(jax.random.key(3), batch=2, t=8, vocab=100)

    for mode in ("vocab_parallel", "gather"):
        l_sh = model.make_loss(mesh, mode=mode)(params, ids, tgt, pos)
        l_ref = oracle.loss(params, ids, tgt, pos)
        np.testing.assert_allclose(l_sh, l_ref, rtol=1e-5)


def test_multi_step_training_equivalence():
    """Reference check #3 at full-model scale: train sharded (TP=4, DP=2) and
    vanilla side by side with SGD; loss histories and final params match."""
    mesh = make_mesh(MeshConfig(dp=2, tp=4))
    model = Transformer(CFG, tp_size=4)
    oracle = VanillaTransformer(CFG)
    key = jax.random.key(5)
    params_sh = model.init(key)
    params_ref = jax.tree.map(jnp.copy, params_sh)
    lr = 1e-2

    sh_fn = jax.jit(jax.value_and_grad(model.make_loss(mesh)))
    ref_fn = jax.jit(jax.value_and_grad(oracle.loss))

    hist_sh, hist_ref = [], []
    for step in range(50):
        ids, tgt, pos = make_batch(jax.random.fold_in(key, step))
        l_sh, g_sh = sh_fn(params_sh, ids, tgt, pos)
        l_ref, g_ref = ref_fn(params_ref, ids, tgt, pos)
        params_sh = jax.tree.map(lambda p, g: p - lr * g, params_sh, g_sh)
        params_ref = jax.tree.map(lambda p, g: p - lr * g, params_ref, g_ref)
        hist_sh.append(float(l_sh))
        hist_ref.append(float(l_ref))

    np.testing.assert_allclose(hist_sh, hist_ref, atol=1e-4)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), atol=1e-4), params_sh, params_ref)


def test_overfit_fixed_batch():
    """Sharded model can actually learn: overfitting one batch must drive the
    loss down substantially."""
    mesh = make_mesh(MeshConfig(dp=2, tp=4))
    model = Transformer(CFG, tp_size=4)
    params = model.init(jax.random.key(8))
    ids, tgt, pos = make_batch(jax.random.key(9))
    fn = jax.jit(jax.value_and_grad(model.make_loss(mesh)))
    first = None
    for _ in range(100):
        loss, grads = fn(params, ids, tgt, pos)
        if first is None:
            first = float(loss)
        params = jax.tree.map(lambda p, g: p - 5e-2 * g, params, grads)
    assert float(loss) < first * 0.4, (first, float(loss))


def test_bf16_compute_dtype_runs():
    """bf16 path compiles and produces finite loss close to the f32 one
    (the reference's --bf16 autocast analogue, `train.py:99-104`)."""
    cfg_bf16 = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=8, num_layers=2,
                           vocab_size=96, maxlen=32, compute_dtype="bfloat16")
    mesh = make_mesh(MeshConfig(dp=1, tp=4))
    model = Transformer(cfg_bf16, tp_size=4)
    params = model.init(jax.random.key(0))
    ids, tgt, pos = make_batch(jax.random.key(6))
    loss = model.make_loss(mesh)(params, ids, tgt, pos)
    assert np.isfinite(float(loss))

    f32_loss = VanillaTransformer(CFG).loss(params, ids, tgt, pos)
    assert abs(float(loss) - float(f32_loss)) < 0.1


@pytest.mark.slow
def test_long_horizon_training_history_matches_vanilla():
    """400 Adam steps with per-step randomized batches: the full loss
    history matches the unsharded oracle — the closest port of the
    reference's 1000-step drift check (`tests/test_*_parallel_*.py:111-135`;
    the fast suite runs 20-step variants, this is the long-horizon lane)."""
    from distributed_pytorch_from_scratch_tpu.config import OptimizerConfig
    from distributed_pytorch_from_scratch_tpu.training.optim import (
        adam_update, init_adam_state)
    from distributed_pytorch_from_scratch_tpu.training.train_step import (
        build_train_step)

    cfg = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=4, num_layers=2,
                      vocab_size=96, maxlen=32)
    mesh = make_mesh(MeshConfig(dp=2, tp=2))
    model = Transformer(cfg, tp_size=2)
    oracle = VanillaTransformer(cfg)
    ocfg = OptimizerConfig(lr=1e-3, warmup_steps=20, max_steps=500)

    p_sh = jax.device_put(model.init(jax.random.key(0)),
                          model.shardings(mesh))
    o_sh = init_adam_state(p_sh)
    step_sh = build_train_step(model, mesh, ocfg)

    p_v = model.init(jax.random.key(0))
    o_v = init_adam_state(p_v)
    grad_v = jax.jit(jax.value_and_grad(oracle.loss))

    @jax.jit
    def step_v(p, o, ids, tgt, pos):
        loss, g = grad_v(p, ids, tgt, pos)
        p, o = adam_update(ocfg, p, g, o)
        return p, o, loss

    hist_sh, hist_v = [], []
    for s in range(400):
        k = jax.random.key(1000 + s)
        ids = jax.random.randint(jax.random.fold_in(k, 0), (4, 32), 0, 96)
        tgt = jax.random.randint(jax.random.fold_in(k, 1), (4, 32), 0, 96)
        pos = jnp.tile(jnp.arange(32)[None, :], (4, 1))
        p_sh, o_sh, l1 = step_sh(p_sh, o_sh, ids, tgt, pos)
        p_v, o_v, l2 = step_v(p_v, o_v, ids, tgt, pos)
        hist_sh.append(float(l1))
        hist_v.append(float(l2))
    np.testing.assert_allclose(hist_sh, hist_v, rtol=0, atol=2e-4)
