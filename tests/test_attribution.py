"""Roofline attribution (obs/attribution) + the remat memory selector
(training/memory): pure host math, so these pin the numbers the perf work
leans on — the flash tile accounting (mirrors the kernel's block_live
predicate), the suspect ranking, and the policy the 45m/gpt2 presets are
known to need.
"""

import pytest

from distributed_pytorch_from_scratch_tpu.config import model_preset
from distributed_pytorch_from_scratch_tpu.obs.attribution import (
    attribution, flash_tile_stats, format_attribution)
from distributed_pytorch_from_scratch_tpu.training.memory import (
    estimate_step_gib, hbm_budget_gib, select_remat)


# ------------------------------------------------------ flash tile stats


def test_tile_stats_single_block_counts_full_square():
    """t=1000 at the shipped 1024x1024 default: ONE live tile covering the
    whole padded square — 1024^2 score elements where causal-real needs
    1000*1001/2, the quantified 2.1x flagship suspect."""
    s = flash_tile_stats(1000, 1024, 1024)
    assert s["t_pad"] == 1024
    assert (s["live_tiles"], s["total_tiles"]) == (1, 1)
    assert s["work_elems"] == 1024 * 1024
    assert s["ideal_elems"] == 1000 * 1001 / 2
    assert 2.0 < s["waste_ratio"] < 2.2


def test_tile_stats_small_blocks_skip_dead_tiles():
    """128-blocks at t=1024: the causal grid guard skips the upper
    triangle — 36 of 64 tiles live (sum of min(i+1, 8))."""
    s = flash_tile_stats(1024, 128, 128)
    assert (s["live_tiles"], s["total_tiles"]) == (36, 64)
    assert s["waste_ratio"] < 1.2


def test_tile_stats_brute_force_agreement():
    """The tile counter must agree with brute-force evaluation of the
    kernel's block_live predicate at a non-square block shape."""
    t, bq, bk = 700, 128, 256
    s = flash_tile_stats(t, bq, bk)
    t_pad = s["t_pad"]
    live = sum(1
               for qi in range(t_pad // bq)
               for ki in range(t_pad // bk)
               if ki * bk <= qi * bq + bq - 1
               and ki * bk < t and qi * bq < t)
    assert s["live_tiles"] == live
    assert s["work_elems"] == live * bq * bk


def test_tile_stats_t_real_cuts_pad_rows():
    """Bucketed accounting: a t=1024 buffer holding 1000 real tokens prices
    exactly like t=1000 at the same blocks (pad tiles are skipped, the
    ideal is the real causal triangle)."""
    bucketed = flash_tile_stats(1024, 256, 256, t_real=1000)
    plain = flash_tile_stats(1000, 256, 256)
    assert bucketed["work_elems"] == plain["work_elems"]
    assert bucketed["ideal_elems"] == plain["ideal_elems"]


# ------------------------------------------------------ attribution report


@pytest.fixture
def cfg45m():
    return model_preset("45m", compute_dtype="bfloat16")


def test_attribution_ranks_suspects_descending(cfg45m):
    rep = attribution(cfg45m, 32, 1000, remat="dots", spd=8,
                      block_q=1024, block_k=1024)
    est = [s["est_ms"] for s in rep["suspects"]]
    assert est == sorted(est, reverse=True)
    assert [s["rank"] for s in rep["suspects"]] == list(
        range(1, len(est) + 1))
    assert rep["analytic_step_ms"] > 0
    # at the flagship shape the tile waste must register as a real suspect
    tile = next(s for s in rep["suspects"]
                if "tile/pad waste" in s["name"])
    assert tile["est_ms"] > 1.0  # > 1 ms of the step


def test_attribution_measured_mode_computes_dispatch_and_gap(cfg45m):
    """With the round-4 measured step, the report must (a) quote shares
    against the measured basis, (b) derive the dispatch gap from
    step - amortised, and (c) surface the roofline gap — the share the
    itemised suspects cannot explain, which IS the 45m finding."""
    measured = {"step_ms": 200.0, "step_ms_spd8": 184.5}
    rep = attribution(cfg45m, 32, 1000, remat="dots", spd=8,
                      measured=measured, block_q=1024, block_k=1024)
    assert rep["step_ms_basis"] == 184.5
    assert abs(rep["dispatch_ms"] - 15.5) < 1e-9
    gap = next(s for s in rep["suspects"] if "roofline gap" in s["name"])
    assert gap["est_ms"] > 50  # most of the flagship's missing MFU
    assert gap["rank"] == 1
    total_share = sum(s["share"] for s in rep["suspects"])
    assert total_share <= 1.01  # suspects never over-explain the step


def test_gpt2_family_prices_two_matmul_ffn(cfg45m):
    """gpt2's gelu MLP is fc+proj (2 matmuls) vs llama's SwiGLU (3): at
    identical dims the gpt2 FFN phase must price exactly 2/3 of llama's."""
    from distributed_pytorch_from_scratch_tpu.obs.attribution import (
        analytic_phases)

    llama = {p.name: p for p in analytic_phases(cfg45m, 32, 1000, "dots")}
    gpt2 = {p.name: p for p in analytic_phases(cfg45m, 32, 1000, "dots",
                                               family="gpt2")}
    assert gpt2["ffn"].flops == pytest.approx(llama["ffn"].flops * 2 / 3)
    assert gpt2["qkv_proj"].flops == llama["qkv_proj"].flops


def test_attribution_remat_ordering(cfg45m):
    """remat=true must price strictly more recompute than dots, and dots
    more than false."""
    ms = {r: attribution(cfg45m, 32, 1000, remat=r)["analytic_step_ms"]
          for r in ("false", "dots", "true")}
    assert ms["false"] < ms["dots"] < ms["true"]


def test_format_attribution_renders_table(cfg45m):
    measured = {"fwd_ms": 50.0, "fwdbwd_ms": 150.0, "step_ms": 200.0,
                "step_ms_spd8": 184.5}
    rep = attribution(cfg45m, 32, 1000, remat="dots", spd=8,
                      measured=measured)
    text = format_attribution(rep, measured)
    assert "rank" in text and "suspect" in text
    assert "measured" in text  # the basis line names its source
    # analytic-vs-measured bucket rows render the measured numbers
    assert "50.00" in text and "100.00" in text


def test_attribution_bucketed_beats_padded(cfg45m):
    """The fix direction must actually price better: bucketed t_real=1000
    in a 1024 buffer with tuned 256-blocks < plain t=1000 at the 1024
    default."""
    before = attribution(cfg45m, 32, 1000, remat="dots",
                         block_q=1024, block_k=1024)
    after = attribution(cfg45m, 32, 1024, remat="false", t_real=1000,
                        block_q=256, block_k=256)
    assert after["analytic_step_ms"] < before["analytic_step_ms"]
    assert (after["tile_stats"]["waste_ratio"]
            < before["tile_stats"]["waste_ratio"])


# ------------------------------------------------------ memory selector


def test_estimate_monotone_in_remat_policy():
    cfg = model_preset("45m")
    est = {p: estimate_step_gib(cfg, 32, 1000, p)
           for p in ("false", "dots", "true")}
    assert est["false"] > est["dots"] > est["true"] > 0


def test_select_remat_matches_validated_configs():
    """The selector must reproduce the empirically validated picks: 45m
    b32xt1000 and gpt2-124m b8xt1024 fit a 16G chip without remat
    (bench.py's defaults, proven in round 4)."""
    assert select_remat(model_preset("45m"), 32, 1000,
                        budget_gib=16.0, verbose=False) == "false"
    assert select_remat(model_preset("gpt2-124m"), 8, 1024,
                        budget_gib=16.0, verbose=False) == "false"


def test_select_remat_steps_down_when_tight():
    """A small budget must force the ladder down — and a hopeless one
    still returns 'true' (the ladder's floor, never an exception)."""
    cfg = model_preset("45m")
    assert select_remat(cfg, 32, 1000, budget_gib=10.0,
                        verbose=False) in ("dots", "true")
    assert select_remat(cfg, 32, 1000, budget_gib=0.1,
                        verbose=False) == "true"


def test_estimate_rejects_unknown_policy():
    with pytest.raises(ValueError, match="remat must be one of"):
        estimate_step_gib(model_preset("45m"), 32, 1000, "sometimes")


def test_hbm_budget_falls_back_on_cpu():
    # the CPU test mesh reports no bytes_limit -> the v5e default
    assert hbm_budget_gib(default=16.0) > 0


def test_moe_estimate_exceeds_dense():
    dense = estimate_step_gib(model_preset("45m"), 32, 1000, "false")
    moe = estimate_step_gib(model_preset("45m-moe8"), 32, 1000, "false")
    assert moe > dense


# ------------------------------------------------------ ZeRO ladder (r12)


def _dp_records(zero_stage, dp_reduce_dtype="f32", dp_bucket_mb=25.0,
                dp=4):
    from distributed_pytorch_from_scratch_tpu.obs.attribution import (
        comm_attribution)
    comm = comm_attribution(model_preset("45m"), 32, 1000, tp=1, dp=dp,
                            dp_bucket_mb=dp_bucket_mb,
                            dp_reduce_dtype=dp_reduce_dtype,
                            zero_stage=zero_stage)
    return {r["name"]: r for r in comm["records"]}, comm


def test_zero2_reduce_scatter_priced_at_half_allreduce_bytes():
    """ISSUE 9 acceptance: comm_attribution prices the stage-2 grad
    reduce-scatter at exactly HALF the stage-1 all-reduce wire bytes, at
    every wire dtype — the halved wire is shown, not asserted."""
    for wire in ("f32", "bf16", "int8"):
        ar, _ = _dp_records(1, wire)
        rs, _ = _dp_records(2, wire)
        assert rs["DP grad reduce-scatter"]["bytes_each"] * 2 == \
            ar["DP grad reduce"]["bytes_each"], wire
    # the schedule's other half: stage 2 adds the f32 param all-gather
    rs, comm = _dp_records(2)
    assert "ZeRO-2 param all-gather" in rs
    assert comm["config"]["zero_stage"] == 2
    # bucketed RS hides under the backward; the param gather is exposed
    assert rs["DP grad reduce-scatter"]["hidden_ms"] > 0
    assert rs["ZeRO-2 param all-gather"]["exposed_ms"] > 0


def test_zero3_schedule_priced_as_per_layer_gathers():
    """Stage 3 prices NO standalone grad collective: two param all-gathers
    (fwd + the remat replay) and the gather-transpose reduce-scatter, all
    f32 and all hidden up to the adjacent compute budgets."""
    recs, comm = _dp_records(3)
    names = set(recs)
    assert "ZeRO-3 param all-gather (fwd)" in names
    assert "ZeRO-3 param all-gather (bwd remat)" in names
    assert "ZeRO-3 grad reduce-scatter (bwd)" in names
    assert not any(n.startswith("DP grad reduce") for n in names)
    # the wire dtype the DP schedule actually carries under stage 3 is f32
    assert comm["config"]["wire_dtype"] == "f32"
    # per-element the RS matches stage 2's f32 bytes (same shard walks the
    # ring), while the gathers pay f32 regardless of --dp_reduce_dtype
    rs2, _ = _dp_records(2)
    assert recs["ZeRO-3 grad reduce-scatter (bwd)"]["bytes_each"] == \
        rs2["DP grad reduce-scatter"]["bytes_each"]


def test_zero_estimate_matches_perf_doc_table():
    """The per-stage resident-state model equals the docs/PERF.md "ZeRO
    ladder" table's bytes/param column (the satellite's validation): the
    doc and the estimator must not drift apart."""
    from distributed_pytorch_from_scratch_tpu.training.memory import (
        zero_state_bytes_per_param)
    dp = 8
    assert zero_state_bytes_per_param(0, dp) == 16.0
    assert zero_state_bytes_per_param(1, dp) == 8.0 + 8.0 / dp      # 9.0
    assert zero_state_bytes_per_param(2, dp) == 4.0 + 12.0 / dp     # 5.5
    # stage 3: 16/dp resident + the gathered working set (one layer +
    # embed/head), charged at 4 bytes per gathered param
    cfg = model_preset("45m")
    P = cfg.num_params()
    nonlayer = 2 * cfg.vocab_size * cfg.attn_dim + cfg.vocab_size \
        + cfg.attn_dim
    per_layer = (P - nonlayer) / cfg.num_layers
    expect = 16.0 / dp + 4.0 * (per_layer + nonlayer) / P
    assert abs(zero_state_bytes_per_param(3, dp, cfg) - expect) < 1e-9
    # dp=1 collapses every stage to the plain 16 bytes/param
    for stage in (0, 1, 2, 3):
        assert zero_state_bytes_per_param(stage, 1, cfg) == 16.0


def test_zero1_estimate_fix_shrinks_pre_existing_overestimate():
    """The satellite's bugfix: estimate_step_gib used to ignore optimizer
    sharding entirely, so a --zero1 dp8 run was overestimated by
    8 x P x (1 - 1/dp) bytes. The stage-aware estimate must be smaller
    and the delta must be exactly the sharded-moment savings."""
    cfg = model_preset("45m")
    base = estimate_step_gib(cfg, 32, 1000, "dots")
    z1 = estimate_step_gib(cfg, 32, 1000, "dots", zero_stage=1, dp=8)
    saved = (base - z1) * 1024 ** 3
    expect = cfg.num_params() * 8.0 * (1 - 1 / 8) * 1.10  # x the tp fudge
    assert abs(saved - expect) / expect < 1e-6


def test_select_remat_zero3_never_picks_false():
    """Stage 3 + remat 'false' would save every gathered layer as a
    backward residual; the selector must skip it even under an infinite
    budget."""
    cfg = model_preset("45m")
    assert select_remat(cfg, 32, 1000, budget_gib=1e9, verbose=False,
                        zero_stage=3, dp=8) == "dots"
    assert select_remat(cfg, 32, 1000, budget_gib=1e9,
                        verbose=False) == "false"
