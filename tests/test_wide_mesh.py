"""16-virtual-device mesh sweep (VERDICT r1 #9).

Runs tests/_wide_mesh_main.py in a subprocess with 16 forced CPU devices:
transformer-vs-oracle equivalence (incl. the 3-D dp2xcp2xtp4 mesh and a
non-divisible vocab) and ZeRO-1-vs-plain-Adam parity at dp4xtp4 / dp8xtp2 —
shapes an 8-device mesh cannot express.
"""

import os
import subprocess
import sys
import pytest

pytestmark = pytest.mark.slow


def test_wide_mesh_16_devices():
    script = os.path.join(os.path.dirname(__file__), "_wide_mesh_main.py")
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=16"
    r = subprocess.run([sys.executable, script], env=env,
                       capture_output=True, text=True, timeout=850,
                       cwd=os.path.dirname(os.path.dirname(script)))
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    assert "ALL OK" in r.stdout
