"""obs v3 (ISSUE 12): the live telemetry plane.

The acceptance criteria pinned here:
* the exporter endpoint serves the registry as JSON and Prometheus text
  for train + both serving engines, refusing a busy port loudly;
* fleet rollup math equals hand-computed completion-weighted attainment
  across 2 fake procs;
* a request whose trace BEGAN in another process merges into ONE
  contiguous waterfall (span sum == measured wall) after clock-offset
  translation — with a deliberately skewed clock;
* an anomaly flight dump cross-links a `jax.profiler` capture that
  actually exists on disk;
* MetricsWriter size rotation chains through schema-valid `rotated`
  events that the collector tailer follows, and a torn trailing line is
  held + resynced (never dropped, never double-counted);
* exporter+collector overhead on a traced loadgen run stays within
  budget of the obs-off run (the 2% pin is asserted on-chip by the
  staged session; CPU CI pins a generous bound against pathology).
"""

import glob
import importlib.util
import json
import os
import socket
import time
import urllib.request

import jax
import pytest

from distributed_pytorch_from_scratch_tpu.config import (MeshConfig,
                                                         ModelConfig)
from distributed_pytorch_from_scratch_tpu.models.transformer import (
    Transformer)
from distributed_pytorch_from_scratch_tpu.obs import (
    EVENT_SCHEMA_VERSION, FleetCollector, FlightRecorder, JsonlTailer,
    RequestTracer, TelemetryExporter, TraceContext, fleet_slo_attainment,
    merge_traces, validate_jsonl, validate_record)
from distributed_pytorch_from_scratch_tpu.runtime.mesh import make_mesh
from distributed_pytorch_from_scratch_tpu.serving.engine import (
    ContinuousBatchingEngine, PagedEngine, Request)
from distributed_pytorch_from_scratch_tpu.serving.loadgen import (
    run_loadgen, synthetic_requests)
from distributed_pytorch_from_scratch_tpu.training.metrics import (
    AnomalyProfiler, MetricsWriter)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CFG = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=8, num_layers=2,
                  vocab_size=96, maxlen=64)
BUF = 32
EOS = 1


def _setup(tp=1, seed=3):
    mesh = make_mesh(MeshConfig(dp=1, tp=tp))
    model = Transformer(CFG, tp_size=tp)
    params = jax.device_put(model.init(jax.random.key(seed)),
                            model.shardings(mesh))
    return mesh, model, params


def _load_script(name):
    path = os.path.join(REPO, "scripts", name + ".py")
    spec = importlib.util.spec_from_file_location(f"_tel_{name}", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=5.0) as r:
        return r.read().decode()


# ------------------------------------------------------ exporter endpoint

def test_exporter_endpoint_json_and_prometheus(tmp_path):
    with MetricsWriter(str(tmp_path), process_index=0) as w:
        tel = TelemetryExporter(writer=w, process_index=0,
                                rollup_interval=0.05)
        port = tel.start(0)
        tel.gauge("serve/kv_util", 0.75)
        tel.counter("slo/interactive/completed", 8)
        tel.count("serve/errors")
        snap = json.loads(_get(port, "/metrics.json"))
        assert snap["gauges"]["serve/kv_util"] == 0.75
        assert snap["counters"]["slo/interactive/completed"] == 8
        assert snap["counters"]["serve/errors"] == 1
        prom = _get(port, "/metrics")
        # names sanitized, process label attached, both metric types
        assert '# TYPE serve_kv_util gauge' in prom
        assert 'serve_kv_util{process="0"} 0.75' in prom
        assert '# TYPE slo_interactive_completed counter' in prom
        # the snapshot thread mirrors into metrics.jsonl
        deadline = time.monotonic() + 5.0
        while tel.snapshots == 0 and time.monotonic() < deadline:
            time.sleep(0.02)
        tel.close()
    recs = [json.loads(l) for l in open(tmp_path / "metrics.jsonl")]
    snaps = [r for r in recs if r["tag"] == "telemetry_snapshot"]
    assert snaps, "no telemetry_snapshot events mirrored"
    assert not any(p for r in snaps for p in validate_record(r))
    assert snaps[-1]["gauges"]["serve/kv_util"] == 0.75


def test_exporter_rate_smooths_counter_into_per_second_gauge():
    clock = [0.0]
    tel = TelemetryExporter(clock=lambda: clock[0])
    tel.rate("serve/tokens_per_sec", 0)
    clock[0] = 1.0
    tel.rate("serve/tokens_per_sec", 100)     # 100 tok/s instantaneous
    snap = tel.snapshot()
    assert snap["gauges"]["serve/tokens_per_sec"] == pytest.approx(100.0)
    assert snap["counters"]["serve/tokens_per_sec_total"] == 100
    clock[0] = 2.0
    tel.rate("serve/tokens_per_sec", 400)     # 300 tok/s -> EWMA between
    v = tel.snapshot()["gauges"]["serve/tokens_per_sec"]
    assert 100.0 < v < 300.0


def test_exporter_busy_port_refuses_loudly():
    blocker = socket.socket()
    try:
        blocker.bind(("127.0.0.1", 0))
        port = blocker.getsockname()[1]
        tel = TelemetryExporter()
        with pytest.raises(SystemExit) as ei:
            tel.start(port)
        assert "cannot bind" in str(ei.value)
    finally:
        blocker.close()


# ------------------------------------------- rotation + the tailer chain

def test_metrics_rotation_chains_through_schema_valid_events(tmp_path):
    with MetricsWriter(str(tmp_path), process_index=0, max_bytes=512) as w:
        for i in range(40):
            w.event("serve_request", rid=i, generated=2)
    gens = sorted(glob.glob(str(tmp_path / "metrics*.jsonl")))
    assert len(gens) > 2, gens                     # it actually rotated
    # every generation validates (the rotated event is schema-valid)
    for g in gens:
        assert validate_jsonl(g) == [], g
    # the chain visits every record exactly once, in order
    t = JsonlTailer(str(tmp_path / "metrics.jsonl"))
    recs = t.poll()
    assert [r["rid"] for r in recs] == list(range(40))
    assert t.rotations == len(gens) - 1
    # the base file's last line is the rotated event naming generation 1
    base_last = json.loads(
        open(tmp_path / "metrics.jsonl").read().splitlines()[-1])
    assert base_last["tag"] == "rotated"
    assert base_last["next"] == "metrics.001.jsonl"


def test_tailer_holds_torn_line_and_resyncs(tmp_path):
    """The satellite pin: a torn trailing jsonl line mid-tail is HELD and
    completed by the next flush — not dropped, not double-counted."""
    p = tmp_path / "metrics.jsonl"
    l1 = json.dumps({"tag": "serve_request", "rid": 0, "generated": 1,
                     "schema_version": EVENT_SCHEMA_VERSION})
    l2 = json.dumps({"tag": "serve_request", "rid": 1, "generated": 2,
                     "schema_version": EVENT_SCHEMA_VERSION})
    with open(p, "w") as f:
        f.write(l1 + "\n" + l2[:17])          # torn mid-record
    t = JsonlTailer(str(p))
    first = t.poll()
    assert [r["rid"] for r in first] == [0]   # the whole record only
    assert t.torn_holds == 1
    assert t.poll() == []                     # still torn: nothing new
    with open(p, "a") as f:
        f.write(l2[17:] + "\n")               # the flush completes it
    second = t.poll()
    assert [r["rid"] for r in second] == [1]  # exactly once
    assert t.poll() == []
    assert t.invalid == 0


def test_tailer_refuses_rotation_cycle(tmp_path):
    """A corrupt/hand-edited chain whose `rotated` event points back at
    an already-read file must terminate the poll (counted as drift), not
    spin it forever."""
    p = tmp_path / "metrics.jsonl"
    p.write_text(json.dumps(
        {"tag": "rotated", "ts": 0.0,
         "schema_version": EVENT_SCHEMA_VERSION,
         "next": "metrics.jsonl", "generation": 1}) + "\n")
    t = JsonlTailer(str(p))
    assert t.poll() == []
    assert t.invalid == 1 and t.rotations == 0


def test_merge_keeps_span_durations_on_overlap():
    """The one-way handshake cannot separate transfer latency from clock
    skew, so an origin's post-export residual can land ON TOP of the
    adopter's first activity: the merge must shift the later span
    forward with its measured duration intact, never trim it."""
    clockA, clockB = [0.0], [0.0]
    rtA = RequestTracer(clock=lambda: clockA[0],
                        wall=lambda: 100.0 + clockA[0], process_index=0)
    rtB = RequestTracer(clock=lambda: clockB[0],
                        wall=lambda: 100.0 + clockB[0], process_index=1)
    ra = _FakeReq(1)
    ra.submit_t = 0.0
    rtA.begin(ra)
    clockA[0] = 0.050
    rtA.mark(ra, "prefill_chunk")
    ctx = rtA.export_context(ra)
    clockA[0] = 0.060                  # 10ms of post-export bookkeeping
    recA = rtA.retire(ra, t=clockA[0])
    rb = _FakeReq(1)
    rb.submit_t = 0.0
    rtB.begin(rb, ctx=ctx)             # adoption pinned to the export stamp
    clockB[0] = 0.040
    rtB.mark(rb, "decode")
    rb.finish_t = 0.040
    recB = rtB.retire(rb)
    m = merge_traces([recA, recB])
    decode = [s for s in m["spans"] if s["name"] == "decode"]
    assert decode and decode[0]["dur_ms"] == pytest.approx(40.0, abs=0.1)
    assert sum(s["dur_ms"] for s in m["spans"]) == pytest.approx(
        m["total_ms"], abs=0.01)
    # total = every process's measured activity: 60ms in A + 40ms in B
    assert m["total_ms"] == pytest.approx(100.0, abs=0.5)


def test_train_and_bench_refuse_bad_rollup_interval():
    from distributed_pytorch_from_scratch_tpu.train import get_train_args
    with pytest.raises(SystemExit):
        get_train_args(["--data_path", "x", "--metrics_port", "0",
                        "--rollup_interval", "0"])
    import bench
    with pytest.raises(SystemExit):
        bench.parse_args(["--serving", "--metrics_port", "0",
                          "--rollup_interval", "0"])


# --------------------------------------------------- fleet rollup math

def test_fleet_rollup_matches_hand_computed_attainment(tmp_path):
    """2 fake procs: completion-weighted fleet attainment, summed
    tokens/s, aggregated pool — against hand math."""
    d0, d1 = tmp_path / "p0", tmp_path / "p1"
    for d, proc, tps, cls_counts, pages in (
            (d0, 0, 120.0, {"interactive": (10, 9), "batch": (4, 4)},
             (6, 16)),
            (d1, 1, 80.0, {"interactive": (40, 10)}, (10, 16))):
        with MetricsWriter(str(d), process_index=proc) as w:
            counters = {}
            for cls, (c, h) in cls_counts.items():
                counters[f"slo/{cls}/completed"] = c
                counters[f"slo/{cls}/hit"] = h
            w.event("telemetry_snapshot", process=proc,
                    gauges={"serve/tokens_per_sec": tps,
                            "serve/pages_in_use": pages[0],
                            "serve/num_pages": pages[1]},
                    counters=counters)
    c = FleetCollector([str(d0), str(d1)],
                       out_path=str(tmp_path / "fleet_rollup.jsonl"))
    assert c.poll() == 2
    r = c.emit()
    assert r["procs"] == 2
    assert r["tokens_per_sec"] == pytest.approx(200.0)
    # hand-computed: interactive (10+40 completed, 9+10 hit) = 19/50
    assert r["slo_attainment"]["interactive"] == {
        "completed": 50, "attained": pytest.approx(0.38)}
    assert r["slo_attainment"]["batch"] == {
        "completed": 4, "attained": 1.0}
    assert r["pool"]["pages_in_use"] == 16 and r["pool"]["num_pages"] == 32
    # the emitted event is schema-valid and lands in the rollup file
    recs = [json.loads(l)
            for l in open(tmp_path / "fleet_rollup.jsonl")]
    assert recs[-1]["tag"] == "fleet_rollup"
    assert not validate_record(recs[-1])


def test_fleet_slo_attainment_pure_math():
    out = fleet_slo_attainment([{"a": (10, 9)}, {"a": (40, 10), "b": (2, 1)}])
    assert out == {"a": {"completed": 50, "attained": 0.38},
                   "b": {"completed": 2, "attained": 0.5}}
    assert fleet_slo_attainment([]) == {}


def test_collector_online_rank_skew(tmp_path):
    """rank_phase_stats from 2 procs surface as the rollup's rank_skew."""
    for proc, dw in ((0, 1.0), (1, 6.0)):
        with MetricsWriter(str(tmp_path), process_index=proc) as w:
            w.event("rank_phase_stats", process=proc,
                    phases_s={"data_wait": dw, "step": 10.0}, steps=50,
                    tokens=500, wall_s=12.0)
    c = FleetCollector([str(tmp_path)])
    c.poll()
    r = c.rollup()
    assert r["rank_skew"]["suspects"][0]["process"] == 1
    assert r["rank_skew"]["suspects"][0]["phase"] == "data_wait"


def test_obs_top_once_renders_and_emits(tmp_path, capsys):
    with MetricsWriter(str(tmp_path), process_index=0) as w:
        w.event("telemetry_snapshot", process=0,
                gauges={"serve/tokens_per_sec": 42.0},
                counters={"slo/interactive/completed": 4,
                          "slo/interactive/hit": 2})
    top = _load_script("obs_top")
    assert top.main([str(tmp_path), "--once"]) == 0
    out = capsys.readouterr().out
    assert "fleet: 1 proc(s)" in out
    assert "interactive 50% of 4" in out
    assert os.path.exists(tmp_path / "fleet_rollup.jsonl")


def test_collector_bounds_hung_endpoint_and_counts_it(tmp_path):
    """Scrape liveness (ISSUE 19): an endpoint that ACCEPTS but never
    responds must not hang the poll loop — the scrape is bounded by
    `scrape_timeout` and the proc counts as unresponsive (mirroring the
    hbm rollup's procs_unavailable: loud, never a folded zero)."""
    import threading

    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]
    held = []

    def hold_open():
        try:
            conn, _ = srv.accept()
            held.append(conn)               # never respond, never close
        except OSError:
            pass

    threading.Thread(target=hold_open, daemon=True).start()
    try:
        c = FleetCollector(
            [str(tmp_path)],
            endpoints=[f"http://127.0.0.1:{port}/metrics.json"],
            scrape_timeout=0.2)
        t0 = time.monotonic()
        c.poll()
        assert time.monotonic() - t0 < 2.0  # bounded, not hung
        assert c.procs_unresponsive == 1
        assert c.unresponsive_scrapes == 1
        assert c.rollup()["procs_unresponsive"] == 1
        c.poll()                            # still hung: cumulative grows
        assert c.procs_unresponsive == 1
        assert c.unresponsive_scrapes == 2
    finally:
        for conn in held:
            conn.close()
        srv.close()
    with pytest.raises(ValueError):
        FleetCollector([str(tmp_path)], endpoints=["http://x"],
                       scrape_timeout=0.0)


# ------------------------------------- cross-process waterfall (tentpole)

class _FakeReq:
    def __init__(self, rid):
        self.rid = rid
        self.trace_id = None
        self.prompt = [3, 4, 5]
        self.prompt_len = 3
        self.tokens = []
        self.submit_t = None
        self.first_token_t = None
        self.finish_t = None
        self.ttft_s = None
        self.tpot_s = None
        self.preemptions = 0
        self.tenant = "t0"
        self.slo_class = None


def test_crossproc_waterfall_merges_with_deliberate_clock_offset(tmp_path):
    """The acceptance pin: a request whose trace BEGAN in process 0 and
    finished in process 1 — whose wall clock is deliberately 1007.3s
    ahead — renders as ONE contiguous waterfall whose span sum equals
    the measured cross-process wall after offset translation."""
    skew = 1007.3
    clockA, clockB = [0.0], [0.0]
    rtA = RequestTracer(clock=lambda: clockA[0],
                        wall=lambda: 1000.0 + clockA[0], process_index=0)
    rtB = RequestTracer(clock=lambda: clockB[0],
                        wall=lambda: 1000.0 + skew + clockB[0],
                        process_index=1)
    # process 0: submit -> queued -> prefill_chunk -> handoff
    ra = _FakeReq(5)
    ra.submit_t = 0.0
    rtA.begin(ra)
    clockA[0] = 0.010
    rtA.mark(ra, "queued")
    clockA[0] = 0.050
    rtA.mark(ra, "prefill_chunk", positions=3)
    ctx = rtA.export_context(ra)
    recA = rtA.retire(ra, t=clockA[0])
    wire = ctx.to_wire()                       # serializable contract
    assert json.loads(json.dumps(wire)) == wire
    # process 1 adopts 5ms of transfer later (on ITS skewed clock)
    clockB[0] = 0.0
    rb = _FakeReq(5)
    rb.submit_t = 0.0
    rtB.begin(rb, ctx=TraceContext.from_wire(wire))
    assert rb.trace_id == ra.trace_id
    clockB[0] = 0.020
    rtB.mark(rb, "decode")
    clockB[0] = 0.040
    rtB.mark(rb, "decode")
    rb.finish_t = 0.040
    rb.tokens = [7, 8]
    recB = rtB.retire(rb)
    # the raw records carry the handshake: B's offset cancels the skew
    # (modulo the 50ms of genuine elapsed time the fake clocks encode —
    # B's clock was still at 0 when A exported at 0.050)
    assert recB["clock_offset_ms"] == pytest.approx(-(skew - 0.050) * 1e3,
                                                    abs=1.0)
    m = merge_traces([recA, recB])
    # contiguous: spans chain with no gap/overlap, sum == total EXACTLY
    cursor = 0.0
    for s in m["spans"]:
        assert s["start_ms"] == pytest.approx(cursor, abs=0.01)
        cursor += s["dur_ms"]
    assert cursor == pytest.approx(m["total_ms"], abs=0.01)
    # total == measured wall in the ROOT timebase: 50ms in A + 40ms in B
    assert m["total_ms"] == pytest.approx(90.0, abs=0.5)
    assert m["processes"] == [0, 1]
    names = [s["name"] for s in m["spans"]]
    assert names[0] == "queued" and "decode" in names
    assert m["generated"] == 2


def test_summarize_renders_crossproc_waterfall(tmp_path):
    """The two processes' request_trace events land in (proc-tagged)
    metrics files; summarize_run merges + renders them as one line."""
    clockA, clockB = [0.0], [0.0]
    wA = MetricsWriter(str(tmp_path), process_index=0)
    wB = MetricsWriter(str(tmp_path), process_index=1)
    rtA = RequestTracer(writer=wA, clock=lambda: clockA[0],
                        wall=lambda: 500.0 + clockA[0], process_index=0)
    rtB = RequestTracer(writer=wB, clock=lambda: clockB[0],
                        wall=lambda: 777.0 + clockB[0], process_index=1)
    ra = _FakeReq(3)
    ra.submit_t = 0.0
    rtA.begin(ra)
    clockA[0] = 0.030
    rtA.mark(ra, "prefill_chunk")
    ctx = rtA.export_context(ra)
    rtA.retire(ra, t=clockA[0])
    rb = _FakeReq(3)
    rb.submit_t = 0.0
    rtB.begin(rb, ctx=ctx)
    clockB[0] = 0.025
    rtB.mark(rb, "decode")
    rb.finish_t = 0.025
    rtB.retire(rb)
    wA.close()
    wB.close()
    sr = _load_script("summarize_run")
    text = sr.summarize(str(tmp_path))
    assert "Cross-process request waterfalls" in text
    assert "across p0 -> p1" in text
    assert "prefill_chunk" in text and "decode" in text


def test_engine_adopts_wire_context_on_submit(tmp_path):
    """The engine-side contract the router PR will use: a Request
    carrying `trace_ctx` CONTINUES the origin trace instead of opening a
    new one, and the retired record links back to the origin."""
    mesh, model, params = _setup(seed=3)
    rt = RequestTracer(process_index=1)
    eng = PagedEngine(model, mesh, params, num_slots=2, buf_len=BUF,
                      eos_id=EOS, page_size=8, prefill_chunk=8,
                      request_tracer=rt)
    ctx = TraceContext(trace_id="r7.1", rid=7, parent_span="route",
                       origin_process=0, handoff_wall=time.time())
    req = Request(rid=7, prompt=[3, 5, 9], max_new=4,
                  trace_ctx=ctx.to_wire())
    eng.submit(req)
    eng.run_to_completion()
    rec = rt.timeline(7)
    assert rec["trace_id"] == "r7.1" and req.trace_id == "r7.1"
    assert rec["origin"] == {"parent_span": "route", "origin_process": 0}
    assert rec["process"] == 1
    assert abs(rec["clock_offset_ms"]) < 5_000  # same host: near zero


# ------------------------------- anomaly -> profiler window (tentpole)

def test_anomaly_dump_cross_links_profiler_capture(tmp_path):
    """The acceptance pin: a forced PoolExhausted preemption (and the
    online SLO-collapse path) produces a flight dump whose `profile`
    field names a jax.profiler capture that EXISTS on disk."""
    mesh, model, params = _setup(seed=3)
    prof = AnomalyProfiler(str(tmp_path), window_steps=2)
    fl = FlightRecorder(str(tmp_path), maxlen=128, profiler=prof)
    eng = PagedEngine(model, mesh, params, num_slots=3, buf_len=BUF,
                      eos_id=EOS, page_size=8, num_pages=4,
                      prefill_chunk=8, flight=fl)
    for i, p in enumerate([[0, 5, 9, 60, 2, 8, 33],
                           [0, 11, 4, 7, 21, 35, 2],
                           [0, 44, 17, 8, 52, 3, 71]]):
        eng.submit(Request(rid=i, prompt=p, max_new=12))
    eng.run_to_completion()
    prof.close()
    assert eng.preemptions >= 1
    dumps = sorted(glob.glob(str(tmp_path / "flightdump_pool_exhausted_*")))
    assert dumps
    doc = json.load(open(dumps[0]))
    assert doc["profile"], "dump did not cross-link a profile path"
    assert prof.captures and doc["profile"] == prof.captures[0]
    assert os.path.isdir(doc["profile"]), doc["profile"]
    assert os.listdir(doc["profile"]), "profile capture dir is empty"
    # the capture budget: an anomaly storm profiles once, not per dump
    assert len(prof.captures) == 1


def test_online_slo_collapse_dumps_mid_run(tmp_path):
    """PagedEngine detects attainment collapse DURING the run (not only
    in loadgen's post-run check): an impossible deadline collapses the
    class, the flight freezes once per class, and loadgen does not
    double-dump it."""
    mesh, model, params = _setup(seed=4)
    fl = FlightRecorder(str(tmp_path), maxlen=64)
    eng = PagedEngine(model, mesh, params, num_slots=3, buf_len=BUF,
                      eos_id=EOS, page_size=8, prefill_chunk=8,
                      slo_classes={"interactive": 1e-9, "batch": 60.0},
                      default_class="interactive", flight=fl)
    reqs = synthetic_requests(6, 4, 8, 6, CFG.vocab_size, seed=1,
                              arrival="burst",
                              class_mix={"interactive": 1})
    run_loadgen(eng, reqs, sleep=lambda s: None)
    assert "interactive" in eng.slo_collapsed
    dumps = glob.glob(str(tmp_path / "flightdump_slo_collapse_*"))
    assert len(dumps) == 1, dumps              # once, not once per path
    doc = json.load(open(dumps[0]))
    assert doc["trigger"]["slo_class"] == "interactive"
    assert doc["trigger"]["attained"] < 0.5


# -------------------------------------------- engine + CLI exporter smoke

def _scrape_during_run(eng, reqs, port):
    """Drive the engine inline and scrape the endpoint mid-run (after the
    first decode steps), returning the mid-run snapshot."""
    for r in reqs:
        r.submit_t = time.monotonic()
        eng.submit(r)
    snap = None
    while eng.has_work():
        eng.step()
        if snap is None and eng.decode_steps >= 2:
            snap = json.loads(_get(port, "/metrics.json"))
    return snap


def test_paged_engine_publishes_live_gauges(tmp_path):
    mesh, model, params = _setup(seed=5)
    tel = TelemetryExporter()
    port = tel.start(0)
    eng = PagedEngine(model, mesh, params, num_slots=3, buf_len=BUF,
                      eos_id=EOS, page_size=8, prefill_chunk=8,
                      slo_classes={"standard": 10.0}, telemetry=tel)
    reqs = [Request(rid=i, prompt=[0, 3 + i, 7, 11], max_new=6)
            for i in range(3)]
    snap = _scrape_during_run(eng, reqs, port)
    tel.close()
    assert snap is not None
    g = snap["gauges"]
    assert g["serve/live"] >= 1
    assert g["serve/num_pages"] == eng.pool.num_pages
    assert "serve/pages_in_use" in g and "serve/queue_depth" in g
    assert snap["counters"]["serve/decode_steps"] >= 2
    # completions flow into per-class SLO counters
    final = tel.snapshot()
    assert final["counters"]["slo/standard/completed"] == 3


def test_slot_engine_publishes_live_gauges(tmp_path):
    mesh, model, params = _setup(seed=6)
    tel = TelemetryExporter()
    port = tel.start(0)
    eng = ContinuousBatchingEngine(model, mesh, params, num_slots=2,
                                   buf_len=BUF, eos_id=EOS,
                                   prefill_bucket=8, telemetry=tel)
    reqs = [Request(rid=i, prompt=[0, 5 + i, 9], max_new=6)
            for i in range(3)]
    snap = _scrape_during_run(eng, reqs, port)
    tel.close()
    assert snap is not None
    assert snap["gauges"]["serve/live"] >= 1
    assert snap["counters"]["serve/decode_steps"] >= 2


def test_serve_dry_run_with_telemetry_and_profiler(tmp_path, capsys):
    """--dry_run --paged with the full ISSUE-12 flag set: the CLI smoke
    that keeps the flags from rotting on chip-less images. Snapshot
    events land versioned in metrics.jsonl; the record carries the bound
    port; the SLO collapse (dry-run deadlines are tight) cross-links a
    capture."""
    from distributed_pytorch_from_scratch_tpu.serving import serve as srv
    log_dir = str(tmp_path / "logs")
    srv.main(["--dry_run", "--paged", "--trace_requests",
              "--flight_records", "--metrics_port", "0",
              "--rollup_interval", "0.2", "--profile_on_anomaly", "2",
              "--log_dir", log_dir])
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["metrics_port"] > 0
    assert rec["telemetry_snapshots"] >= 1
    recs = [json.loads(l)
            for l in open(os.path.join(log_dir, "metrics.jsonl"))]
    snaps = [r for r in recs if r["tag"] == "telemetry_snapshot"]
    assert snaps and not any(p for r in snaps for p in validate_record(r))
    assert any("serve/tokens_per_sec" in r["gauges"] for r in snaps)
    if rec.get("flight_dumps"):
        assert rec["anomaly_profiles"], rec
        assert os.path.isdir(rec["anomaly_profiles"][0])


def test_serve_dry_run_slot_engine_with_telemetry(tmp_path, capsys):
    from distributed_pytorch_from_scratch_tpu.serving import serve as srv
    log_dir = str(tmp_path / "logs")
    srv.main(["--dry_run", "--metrics_port", "0", "--rollup_interval",
              "0.2", "--log_dir", log_dir])
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["metrics_port"] > 0
    recs = [json.loads(l)
            for l in open(os.path.join(log_dir, "metrics.jsonl"))]
    assert any(r["tag"] == "telemetry_snapshot" for r in recs)


def test_serve_refuses_profiler_without_flight():
    from distributed_pytorch_from_scratch_tpu.serving import serve as srv
    with pytest.raises(SystemExit):
        srv.get_serve_args(["--dry_run", "--paged",
                            "--profile_on_anomaly", "2"])


def test_bench_telemetry_flags_gated_on_serving():
    import bench
    with pytest.raises(SystemExit):
        bench.parse_args(["--metrics_port", "0"])
    with pytest.raises(SystemExit):
        bench.parse_args(["--serving", "--profile_on_anomaly", "2"])
    args = bench.parse_args(["--serving", "--flight_records",
                             "--metrics_port", "0",
                             "--profile_on_anomaly", "2"])
    assert args.metrics_port == 0 and args.profile_on_anomaly == 2


@pytest.mark.slow
def test_train_run_exports_telemetry(tmp_path):
    """Train exporter smoke (slow lane: pays a compile): snapshots carry
    the train gauges the log line prints."""
    import random

    from distributed_pytorch_from_scratch_tpu import train as train_mod
    from distributed_pytorch_from_scratch_tpu.config import (
        BOS_TOKEN, EOS_TOKEN, UNK_TOKEN)
    rng = random.Random(0)
    corpus = {
        "train": [[rng.randint(4, 63) for _ in range(20)]
                  for _ in range(64)],
        "validation": [[rng.randint(4, 63) for _ in range(12)]
                       for _ in range(8)],
        "special_ids": {BOS_TOKEN: 1, EOS_TOKEN: 2, UNK_TOKEN: 3},
        "vocab_size": 64,
    }
    tokens = tmp_path / "tokens.json"
    tokens.write_text(json.dumps(corpus))
    save = str(tmp_path / "ckpts")
    train_mod.main(["--data_path", str(tokens), "--save_dir", save,
                    "--batch_size", "4", "--max_steps", "10",
                    "--log_interval", "2", "--save_interval", "100",
                    "--warmup_steps", "2", "--metrics_port", "0",
                    "--rollup_interval", "0.2",
                    "--attn_dim", "32", "--ffn_dim", "64",
                    "--num_heads", "4", "--num_layers", "2",
                    "--maxlen", "32"])
    recs = [json.loads(l)
            for l in open(os.path.join(save, "logs", "metrics.jsonl"))]
    snaps = [r for r in recs if r["tag"] == "telemetry_snapshot"]
    assert snaps, "train run mirrored no telemetry snapshots"
    last = snaps[-1]
    assert last["gauges"]["train/tokens_per_sec"] > 0
    assert "train/goodput" in last["gauges"]
    assert last["counters"]["train/step"] == 10
    # ISSUE 15 silent-zero pin: the CPU backend has no memory_stats, so
    # the run must export 'unavailable' loudly — no device_memory_gib
    # scalar (previously a fake 0), hbm/available gauge at 0, and
    # hbm_watermark events saying available=false
    assert not any(r.get("tag") == "device_memory_gib" for r in recs)
    assert last["gauges"].get("hbm/available") == 0.0
    hw = [r for r in recs if r["tag"] == "hbm_watermark"]
    assert hw and all(r["available"] is False for r in hw)
    # the collector reads a train fleet too
    c = FleetCollector([os.path.join(save, "logs")])
    c.poll()
    assert c.rollup()["tokens_per_sec"] > 0


# ------------------------------------------------------- overhead pin

def test_exported_traced_overhead_within_budget(tmp_path):
    """The overhead pin for the NEW subsystem: adding the live exporter
    (per-step gauges/rates + snapshot thread) to an already traced +
    flight-recorded loadgen run must not cost the hot path. The full
    obs-vs-off <= 2% budget is asserted on-chip by the staged r14
    session (where a decode step is ms-scale and the jsonl writes
    amortize); CPU CI pins the exporter's MARGINAL cost with a generous
    1.3x bound that still catches a pathological regression (I/O or
    lock contention per decode step). Both arms reuse warmed engines
    (identical compiled programs) and take best-of-3 — min is the
    standard noise-robust timing estimator on a busy CI box."""
    mesh, model, params = _setup(seed=7)

    def build(exported: bool):
        w = MetricsWriter(str(tmp_path / ("on" if exported else "off")),
                          process_index=0)
        fl = FlightRecorder(str(tmp_path), maxlen=256)
        rt = RequestTracer(writer=w, flight=fl)
        tel = None
        if exported:
            tel = TelemetryExporter(writer=w, rollup_interval=0.5)
            tel.start(0)
        eng = PagedEngine(model, mesh, params, num_slots=4, buf_len=BUF,
                          eos_id=EOS, page_size=8, prefill_chunk=8,
                          request_tracer=rt, flight=fl, writer=w,
                          telemetry=tel)
        return eng, tel, w

    def drive(eng, base_rid):
        for i in range(8):
            r = Request(rid=base_rid + i, prompt=[0, 3 + i, 7, 11, 2],
                        max_new=10, seed=i)
            r.submit_t = time.monotonic()
            eng.submit(r)
        eng.run_to_completion()

    times, steps = {}, {}
    for exported in (False, True):
        eng, tel, w = build(exported)
        drive(eng, 0)                      # warm: compiles amortized
        best = float("inf")
        s0 = eng.decode_steps
        for round_ in range(1, 4):
            t0 = time.perf_counter()
            drive(eng, 100 * round_)
            best = min(best, time.perf_counter() - t0)
        times[exported] = best
        steps[exported] = max((eng.decode_steps - s0) // 3, 1)
        if tel is not None:
            # ISSUE 15: the watermark gauges ride the same publish path,
            # so this pin now also bounds THEIR marginal cost — and on
            # the statless CPU backend they must export 'unavailable',
            # never a fake 0-byte gauge
            g = tel.snapshot()["gauges"]
            assert g.get("hbm/available") == 0.0
            assert "hbm/bytes_in_use" not in g
            tel.close()
        w.close()
    ratio = times[True] / times[False]
    # two ways to pass, one way to fail: either the ratio is clean OR the
    # absolute marginal cost per decode step is sub-millisecond (a busy
    # box can skew a 30ms round by scheduler jitter alone; a REAL
    # regression — per-step I/O or lock contention — fails both bounds)
    per_step_ms = (times[True] - times[False]) * 1e3 / steps[True]
    assert ratio < 1.3 or per_step_ms < 1.0, (
        f"exported {times[True]:.3f}s vs traced-only {times[False]:.3f}s "
        f"= x{ratio:.2f} and +{per_step_ms:.2f}ms/decode-step — the live "
        f"exporter is costing the hot path")
