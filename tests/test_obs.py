"""The obs/ subsystem: span tracer, goodput meter, health sentinel, hang
watchdog, compiled-program introspection — unit level plus the tier-1
end-to-end smoke: a tiny CPU train run must emit a valid Chrome trace, a
goodput summary whose buckets sum to wall time, and a cost-analysis FLOPs
number within 2x of the hand-rolled estimate; an injected NaN loss must
halt training with a state dump."""

import glob
import importlib.util
import json
import os
import random
import threading
import time

import jax
import jax.numpy as jnp
import pytest

from distributed_pytorch_from_scratch_tpu.obs import (
    GoodputMeter, HangWatchdog, HealthSentinel, SpanTracer,
    TrainingHealthError, analyze_compiled, parse_collectives)
from distributed_pytorch_from_scratch_tpu.obs.introspect import _shape_bytes
from distributed_pytorch_from_scratch_tpu.training.metrics import (
    MetricsWriter)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------- tracer

def test_tracer_emits_valid_chrome_trace(tmp_path):
    tr = SpanTracer(str(tmp_path), pid=7, process_name="unit")
    with tr.span("compile", cat="compile", step=0):
        with tr.span("inner", cat="compile"):
            pass
    tr.instant("marker", step=3)
    tr.counter("loss", 4.5)
    done = threading.Event()

    def producer():
        t0 = tr.now()
        tr.complete("prefetch_window", t0, cat="data_prep")
        done.set()

    threading.Thread(target=producer).start()
    assert done.wait(5)
    path = tr.close()
    assert path is not None and os.path.exists(path)
    doc = json.load(open(path))
    evs = doc["traceEvents"]
    names = {e["name"] for e in evs}
    assert {"compile", "inner", "marker", "loss", "prefetch_window",
            "process_name"} <= names
    # timestamps sorted (close() sorts) and non-negative; durations >= 0
    ts = [e["ts"] for e in evs if "ts" in e]
    assert ts == sorted(ts) and all(t >= 0 for t in ts)
    assert all(e.get("dur", 0) >= 0 for e in evs)
    # the producer thread shows up as its own tid
    main_tids = {e["tid"] for e in evs if e["name"] == "compile"}
    prod_tids = {e["tid"] for e in evs if e["name"] == "prefetch_window"}
    assert main_tids and prod_tids and main_tids != prod_tids
    # crash-safe jsonl mirror: one parseable object per line
    for line in open(tmp_path / "trace.jsonl"):
        json.loads(line)
    # idempotent close
    assert tr.close() == path


def test_tracer_disabled_is_noop(tmp_path):
    tr = SpanTracer(str(tmp_path / "sub"), enabled=False)
    with tr.span("x"):
        pass
    tr.instant("y")
    assert tr.close() is None
    assert not os.path.exists(tmp_path / "sub")


# --------------------------------------------------------------- goodput

def test_goodput_buckets_sum_to_wall():
    t = [0.0]
    m = GoodputMeter(clock=lambda: t[0])
    m.account("compile", 2.0)
    m.account("step", 5.0)
    m.account("data_wait", 1.0)
    m.add_progress(tokens=1000, steps=10)
    t[0] = 10.0
    s = m.summary()
    assert s["wall_s"] == pytest.approx(10.0)
    assert sum(s["buckets_s"].values()) == pytest.approx(10.0)
    assert s["buckets_s"]["other"] == pytest.approx(2.0)
    assert s["goodput"] == pytest.approx(0.5)
    assert s["tokens"] == 1000 and s["steps"] == 10
    line = GoodputMeter.format_summary(s)
    assert "goodput 50.0%" in line and "step" in line


def test_goodput_other_clamps_at_zero():
    t = [0.0]
    m = GoodputMeter(clock=lambda: t[0])
    m.account("step", 5.0)  # over-account past wall
    t[0] = 4.0
    s = m.summary()
    assert s["buckets_s"]["other"] == 0.0


# -------------------------------------------------------------- sentinel

def test_sentinel_healthy_run_is_quiet(tmp_path):
    s = HealthSentinel(str(tmp_path))
    for i, loss in enumerate([4.0, 3.5, 3.2, 3.0]):
        s.check(i, loss, grad_norm=1.0)
    assert s.spikes == 0
    assert not glob.glob(str(tmp_path / "sentinel_dump_*"))


def test_sentinel_flags_spike_but_does_not_halt(tmp_path):
    s = HealthSentinel(str(tmp_path), spike_factor=3.0)
    s.check(0, 2.0)
    s.check(1, 2.0)
    s.check(2, 50.0)  # > 3 x EMA
    assert s.spikes == 1
    assert not glob.glob(str(tmp_path / "sentinel_dump_*"))  # no dump


def test_sentinel_nan_halts_with_dump(tmp_path):
    s = HealthSentinel(str(tmp_path))
    s.check(0, 2.0)
    with pytest.raises(TrainingHealthError) as ei:
        s.check(5, float("nan"))
    dump = ei.value.dump_path
    assert dump and os.path.exists(dump)
    rec = json.load(open(dump))
    assert "non-finite" in rec["reason"] and rec["step"] == 5
    assert len(rec["history"]) == 2  # the healthy check + the fatal one


def test_sentinel_nonfinite_grad_norm_halts(tmp_path):
    s = HealthSentinel(str(tmp_path))
    with pytest.raises(TrainingHealthError):
        s.check(1, 2.0, grad_norm=float("inf"))


def test_sentinel_halt_optout(tmp_path):
    s = HealthSentinel(str(tmp_path), halt_on_nonfinite=False)
    s.check(1, float("nan"))  # dumps but does not raise
    assert glob.glob(str(tmp_path / "sentinel_dump_*"))


# -------------------------------------------------------------- watchdog

def test_watchdog_detects_stall_and_recovery():
    stalls = []
    wd = HangWatchdog(timeout_s=0.08, poll_s=0.02,
                      on_stall=lambda rec: stalls.append(rec))
    try:
        wd.beat(step=7)
        deadline = time.monotonic() + 5.0
        while not stalls and time.monotonic() < deadline:
            time.sleep(0.02)
        assert stalls and stalls[0]["last_step"] == 7
        wd.beat(step=8)  # recovery
        assert wd.stall_count >= 1
    finally:
        wd.close()


def test_watchdog_quiet_while_beating():
    stalls = []
    wd = HangWatchdog(timeout_s=0.2, poll_s=0.02,
                      on_stall=lambda rec: stalls.append(rec))
    try:
        for _ in range(10):
            wd.beat(step=1)
            time.sleep(0.02)
        assert not stalls
    finally:
        wd.close()


# ------------------------------------------------------------ introspect

CANNED_HLO = """
  %ar = f32[8,128]{1,0} all-reduce(f32[8,128]{1,0} %p), replica_groups={}
  %ag.1 = bf16[4,256]{1,0} all-gather(bf16[4,64]{1,0} %q), dimensions={1}
  %aas = (f32[16]{0}, f32[16]{0}) all-to-all-start(f32[16]{0} %r)
  %done = f32[8,128]{1,0} all-reduce-done(f32[8,128]{1,0} %ar)
"""


def test_parse_collectives_counts_and_bytes():
    colls = parse_collectives(CANNED_HLO)
    assert colls["all-reduce"] == {"count": 1, "bytes": 8 * 128 * 4}
    assert colls["all-gather"] == {"count": 1, "bytes": 4 * 256 * 2}
    assert colls["all-to-all"]["count"] == 1
    # async -start tuple = (operand, result): only the result counts, so
    # sync and async lowerings of the same op report the same bytes
    assert colls["all-to-all"]["bytes"] == 16 * 4
    assert _shape_bytes("f32[2,3]{1,0}") == 24
    assert _shape_bytes("pred[]") == 1


def test_analyze_compiled_on_real_program():
    from jax.sharding import PartitionSpec as P
    from distributed_pytorch_from_scratch_tpu import MeshConfig, make_mesh

    mesh = make_mesh(MeshConfig(dp=1, tp=2))
    f = jax.jit(jax.shard_map(lambda x: jax.lax.psum(x @ x.T, "tp"),
                              mesh=mesh, in_specs=(P(None, "tp"),),
                              out_specs=P()))
    compiled = f.lower(jnp.ones((16, 64))).compile()
    a = analyze_compiled(compiled)
    assert a["flops"] is None or a["flops"] > 0
    assert "all-reduce" in a["collectives"]
    assert a["comm_bytes"] >= a["collectives"]["all-reduce"]["bytes"]


# --------------------------------------------------------- MetricsWriter

def test_metrics_writer_context_manager_and_events(tmp_path):
    with MetricsWriter(str(tmp_path), process_index=0) as w:
        w.scalar("train/x", 1.5, 3)
        w.event("goodput_summary", wall_s=10.0, goodput=0.5)
    recs = [json.loads(l) for l in open(tmp_path / "metrics.jsonl")]
    assert recs[0] == pytest.approx(
        {"tag": "train/x", "value": 1.5, "step": 3, "ts": recs[0]["ts"]})
    assert recs[1]["tag"] == "goodput_summary"
    w.scalar("after/close", 1.0, 4)  # silently dropped, no ValueError
    assert len(open(tmp_path / "metrics.jsonl").readlines()) == 2


def test_metrics_writer_tags_nonzero_process(tmp_path):
    with MetricsWriter(str(tmp_path), process_index=2) as w:
        w.scalar("a", 1.0, 0)
    assert os.path.exists(tmp_path / "metrics.proc2.jsonl")
    assert not os.path.exists(tmp_path / "metrics.jsonl")


# ------------------------------------------------- end-to-end train smoke

@pytest.fixture(scope="module")
def token_corpus(tmp_path_factory):
    from distributed_pytorch_from_scratch_tpu.config import (
        BOS_TOKEN, EOS_TOKEN, UNK_TOKEN)
    rng = random.Random(0)
    d = tmp_path_factory.mktemp("obs_corpus")
    data = {
        "train": [[rng.randint(4, 63) for _ in range(rng.randint(8, 30))]
                  for _ in range(64)],
        "validation": [[rng.randint(4, 63) for _ in range(12)]
                       for _ in range(8)],
        "special_ids": {BOS_TOKEN: 1, EOS_TOKEN: 2, UNK_TOKEN: 3},
        "vocab_size": 64,
    }
    path = d / "tokens.json"
    with open(path, "w") as f:
        json.dump(data, f)
    return str(path)


MODEL_FLAGS = ["--attn_dim", "32", "--ffn_dim", "64", "--num_heads", "4",
               "--num_layers", "2", "--maxlen", "32"]


def test_train_run_emits_trace_goodput_and_cost_analysis(token_corpus,
                                                         tmp_path):
    from distributed_pytorch_from_scratch_tpu import train as train_mod

    save = str(tmp_path / "ckpts")
    train_mod.main(["--data_path", token_corpus, "--save_dir", save,
                    "--batch_size", "4", "--max_steps", "30",
                    "--log_interval", "5", "--save_interval", "10",
                    "--warmup_steps", "2", *MODEL_FLAGS])

    # -- trace.json: valid Chrome trace-event format, monotonic timestamps
    doc = json.load(open(os.path.join(save, "logs", "trace.json")))
    evs = doc["traceEvents"]
    ts = [e["ts"] for e in evs if "ts" in e]
    assert ts == sorted(ts) and all(t >= 0 for t in ts)
    cats = {e.get("cat") for e in evs}
    assert {"compile", "data_wait", "h2d", "step", "checkpoint",
            "data_prep"} <= cats
    # the async checkpoint writer traced on its own thread
    assert any(e["name"] == "checkpoint_write" for e in evs)

    # -- metrics.jsonl: goodput summary + cost analysis + grad-norm scalars
    recs = [json.loads(l)
            for l in open(os.path.join(save, "logs", "metrics.jsonl"))]
    tags = {r["tag"] for r in recs}
    assert "train/grad_norm" in tags

    (good,) = [r for r in recs if r["tag"] == "goodput_summary"]
    total = sum(good["buckets_s"].values())
    assert total == pytest.approx(good["wall_s"], rel=0.05)
    assert good["steps"] == 30 and good["tokens"] == 30 * 4 * 32
    assert 0 < good["goodput"] <= 1

    (cost,) = [r for r in recs if r["tag"] == "cost_analysis"]
    assert cost["flops"] and cost["expected_program_flops"]
    ratio = cost["flops"] / cost["expected_program_flops"]
    assert 0.5 <= ratio <= 2.0, f"XLA vs hand-rolled FLOPs ratio {ratio}"
    assert cost["collectives"], "expected at least one collective parsed"

    # -- summarize_run integration: the goodput/health reader finds it
    spec = importlib.util.spec_from_file_location(
        "_summarize_run", os.path.join(REPO, "scripts", "summarize_run.py"))
    sr = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(sr)
    goodput_rows, health_rows = sr.obs_lines(save)
    assert any("goodput" in r for r in goodput_rows)
    assert any("GFLOPs/program" in r for r in goodput_rows)


def test_nan_loss_halts_training_with_state_dump(token_corpus, tmp_path,
                                                 monkeypatch):
    from distributed_pytorch_from_scratch_tpu import train as train_mod

    real_builder = train_mod.build_train_step

    def nan_builder(*a, **kw):
        fn = real_builder(*a, **kw)
        calls = [0]

        def wrapped(p, o, ids, tgt, pos):
            p, o, (loss, g) = fn(p, o, ids, tgt, pos)
            calls[0] += 1
            if calls[0] >= 6:  # blow up mid-run, after healthy intervals
                loss = loss * jnp.float32("nan")
            return p, o, (loss, g)

        return wrapped

    monkeypatch.setattr(train_mod, "build_train_step", nan_builder)
    save = str(tmp_path / "ckpts_nan")
    with pytest.raises(TrainingHealthError) as ei:
        train_mod.main(["--data_path", token_corpus, "--save_dir", save,
                        "--batch_size", "4", "--max_steps", "30",
                        "--log_interval", "5", "--save_interval", "100",
                        "--warmup_steps", "2", *MODEL_FLAGS])
    dump = ei.value.dump_path
    assert dump and os.path.exists(dump)
    rec = json.load(open(dump))
    assert "non-finite" in rec["reason"]
    # the halt still leaves a complete trace + goodput summary behind
    assert os.path.exists(os.path.join(save, "logs", "trace.json"))
    recs = [json.loads(l)
            for l in open(os.path.join(save, "logs", "metrics.jsonl"))]
    assert any(r["tag"] == "sentinel/nonfinite" for r in recs)
    assert any(r["tag"] == "goodput_summary" for r in recs)


def test_sentinel_can_be_disabled(token_corpus, tmp_path, monkeypatch):
    """--no_sentinel: the same NaN injection runs to completion (the
    pre-obs behaviour, for when dying is worse than diverging)."""
    from distributed_pytorch_from_scratch_tpu import train as train_mod

    real_builder = train_mod.build_train_step

    def nan_builder(*a, **kw):
        fn = real_builder(*a, **kw)

        def wrapped(p, o, ids, tgt, pos):
            p, o, (loss, g) = fn(p, o, ids, tgt, pos)
            return p, o, (loss * jnp.float32("nan"), g)

        return wrapped

    monkeypatch.setattr(train_mod, "build_train_step", nan_builder)
    save = str(tmp_path / "ckpts_nosent")
    train_mod.main(["--data_path", token_corpus, "--save_dir", save,
                    "--batch_size", "4", "--max_steps", "6",
                    "--log_interval", "3", "--save_interval", "100",
                    "--warmup_steps", "2", "--no_sentinel", *MODEL_FLAGS])
    assert not glob.glob(os.path.join(save, "logs", "sentinel_dump_*"))
