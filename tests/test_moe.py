"""Mixture-of-Experts + expert parallelism (parallel/moe.py).

The reference has no MoE/EP of any kind (SURVEY §2.4 "EP ❌"), so the oracle
is the framework itself on a single-device mesh — the same parallel-vs-
unsharded equivalence idiom as the reference's tests (SURVEY §4), applied
across mesh shapes:

* op level: MoEFFN with 1 expert == the dense SwiGLU math; routing one-hot
  algebra (dispatch/combine) is internally consistent; capacity drops occur
  iff capacity is insufficient.
* model level: the SAME params + batch produce identical losses, logits and
  gradients on 1-device, ep-only, ep x tp and dp x ep x tp meshes (exact
  while nothing drops — ample capacity_factor makes routing
  sharding-invariant in value, not just expectation).
* training level: multi-step loss histories match across meshes (the
  backward all_to_all / einsum transposes drift-free over steps).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributed_pytorch_from_scratch_tpu.config import (IGNORE_INDEX,
                                                         MeshConfig,
                                                         ModelConfig,
                                                         OptimizerConfig)
from distributed_pytorch_from_scratch_tpu.models.transformer import Transformer
from distributed_pytorch_from_scratch_tpu.parallel.moe import (MoEFFN,
                                                               aux_losses)
from distributed_pytorch_from_scratch_tpu.runtime.mesh import make_mesh
from distributed_pytorch_from_scratch_tpu.training.optim import (
    init_adam_state)
from distributed_pytorch_from_scratch_tpu.training.train_step import (
    build_train_step)

CFG = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=4, num_layers=2,
                  vocab_size=96, maxlen=64, num_experts=4, moe_top_k=2,
                  moe_capacity_factor=8.0)  # ample: zero drops -> exactness


def make_batch(key, batch=4, t=32, vocab=96):
    k1, k2 = jax.random.split(key)
    input_ids = jax.random.randint(k1, (batch, t), 0, vocab)
    target_ids = jax.random.randint(k2, (batch, t), 0, vocab)
    mask = jax.random.bernoulli(jax.random.fold_in(key, 9), 0.2, (batch, t))
    target_ids = jnp.where(mask, IGNORE_INDEX, target_ids)
    position_ids = jnp.tile(jnp.arange(t)[None, :], (batch, 1))
    return input_ids, target_ids, position_ids


def run_moe_single(moe: MoEFFN, params, x):
    """Run MoEFFN.apply on a 1-device mesh (every axis size 1)."""
    from distributed_pytorch_from_scratch_tpu.parallel.moe import aux_zeros
    mesh = make_mesh(MeshConfig())
    aux_specs = jax.tree.map(lambda _: P(), aux_zeros(moe.num_experts))

    def run(p, x):
        y, aux = moe.apply(p, x)
        # expert weights are ep-sharded, so y carries an ep-varying vma tag;
        # on this size-1 axis psum is the identity and clears it.
        return jax.lax.psum(y, "ep"), aux

    fn = jax.shard_map(run, mesh=mesh, in_specs=(moe.specs(), P()),
                       out_specs=(P(), aux_specs))
    return jax.jit(fn)(params, x)


# ---- op level ----

def test_single_expert_equals_dense():
    """E=1, k=1 MoE is exactly silu-gated dense FFN with expert 0's weights
    (router prob softmax over one logit == 1)."""
    d, f = 16, 32
    moe = MoEFFN(d, f, num_experts=1, top_k=1, capacity_factor=4.0)
    params = moe.init(jax.random.key(0))
    x = jax.random.normal(jax.random.key(1), (2, 8, d), jnp.float32)
    y, aux = run_moe_single(moe, params, x)
    g = jnp.einsum("btd,df->btf", x, params["gate"][0])
    u = jnp.einsum("btd,df->btf", x, params["up"][0])
    ref = jnp.einsum("btf,fd->btd", jax.nn.silu(g) * u, params["down"][0])
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    assert float(aux["dropped"]) == 0.0


def test_capacity_drops():
    """With capacity below the routed load, tokens drop (and are counted);
    with ample capacity nothing drops."""
    d, f, E = 8, 16, 4
    x = jax.random.normal(jax.random.key(2), (1, 64, d), jnp.float32)

    tight = MoEFFN(d, f, E, top_k=2, capacity_factor=0.25)
    params = tight.init(jax.random.key(0))
    _, aux = run_moe_single(tight, params, x)
    assert float(aux["dropped"]) > 0

    ample = MoEFFN(d, f, E, top_k=2, capacity_factor=8.0)
    _, aux = run_moe_single(ample, params, x)
    assert float(aux["dropped"]) == 0.0


def test_aux_losses_uniform_routing_is_minimal():
    """A zero-init router routes uniformly: the Switch load-balance loss sits
    at its minimum value 1.0 exactly."""
    d, f, E = 8, 16, 4
    moe = MoEFFN(d, f, E, top_k=2, capacity_factor=8.0)
    params = moe.init(jax.random.key(0))
    x = jax.random.normal(jax.random.key(3), (2, 32, d), jnp.float32)
    _, aux = run_moe_single(moe, params, x)
    lb, z = aux_losses(aux, E, 2)
    # prob mass uniform (zero logits) -> P_e = 1/E, sum_e f_e = 1
    np.testing.assert_allclose(float(lb), 1.0, rtol=1e-5)
    assert float(z) >= 0.0


def test_validation_errors():
    with pytest.raises(ValueError, match="divisible"):
        MoEFFN(8, 16, num_experts=3, ep_size=2)
    with pytest.raises(ValueError, match="divisible"):
        MoEFFN(8, 15, num_experts=4, tp_size=2)
    with pytest.raises(ValueError, match="top_k"):
        MoEFFN(8, 16, num_experts=4, top_k=5)
    with pytest.raises(ValueError, match="ep_size"):
        Transformer(ModelConfig(num_experts=0), ep_size=2)
    # sequence_parallel + MoE is SUPPORTED since round 3 (VERDICT r2 #4)
    Transformer(CFG, sequence_parallel=True)


# ---- model level: mesh-shape equivalence ----

MESHES = [
    ("ep2", dict(dp=1, ep=2, tp=1)),
    pytest.param("ep4", dict(dp=1, ep=4, tp=1), marks=pytest.mark.slow),
    ("ep2tp2", dict(dp=1, ep=2, tp=2)),
    pytest.param("dp2ep2tp2", dict(dp=2, ep=2, tp=2),
                 marks=pytest.mark.slow),
]


@pytest.mark.parametrize("name,shape", MESHES)
def test_model_loss_logits_grads_match_single_device(name, shape):
    """Loss, full logits and every gradient leaf match the 1-device run of
    the SAME model/params — expert parallelism is semantically invisible."""
    key = jax.random.key(0)
    ids, tgt, pos = make_batch(jax.random.key(2))

    ref_model = Transformer(CFG)
    ref_mesh = make_mesh(MeshConfig())
    params = ref_model.init(key)
    l_ref, g_ref = jax.value_and_grad(ref_model.make_loss(ref_mesh))(
        params, ids, tgt, pos)
    logits_ref = ref_model.make_forward(ref_mesh)(params, ids, pos)

    model = Transformer(CFG, tp_size=shape["tp"], ep_size=shape["ep"])
    mesh = make_mesh(MeshConfig(**shape))
    sh_params = jax.device_put(params, model.shardings(mesh))
    l_sh, g_sh = jax.value_and_grad(model.make_loss(mesh))(
        sh_params, ids, tgt, pos)
    logits_sh = model.make_forward(mesh)(sh_params, ids, pos)

    np.testing.assert_allclose(float(l_sh), float(l_ref), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(logits_sh), np.asarray(logits_ref),
                               rtol=1e-4, atol=1e-4)
    for a, b in zip(jax.tree.leaves(g_sh), jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


@pytest.mark.slow  # heaviest of its family; shorter siblings stay fast
def test_multi_step_history_matches_across_meshes():
    """20 Adam steps: the loss history on dp2 x ep2 x tp2 matches the
    1-device history — no drift from the all_to_all/einsum transposes
    (the reference's 1000-step idiom, SURVEY §4 check 3, at CI scale)."""
    ocfg = OptimizerConfig(lr=1e-3, warmup_steps=5, max_steps=30)
    histories = {}
    for name, shape in [("single", dict()), ("dp2ep2tp2",
                                             dict(dp=2, ep=2, tp=2))]:
        model = Transformer(CFG, tp_size=shape.get("tp", 1),
                            ep_size=shape.get("ep", 1))
        mesh = make_mesh(MeshConfig(**shape))
        params = jax.device_put(model.init(jax.random.key(0)),
                                model.shardings(mesh))
        opt = init_adam_state(params)
        step = build_train_step(model, mesh, ocfg)
        losses = []
        for i in range(20):
            ids, tgt, pos = make_batch(jax.random.key(100 + i))
            params, opt, loss = step(params, opt, ids, tgt, pos)
            losses.append(float(loss))
        histories[name] = losses
    np.testing.assert_allclose(histories["single"], histories["dp2ep2tp2"],
                               rtol=2e-4)


def test_moe_checkpoint_zero1_resume(tmp_path):
    """MoE params flow through the existing save/load + ZeRO-1 machinery:
    train 3 steps with dp-sharded Adam moments on a dp2 x ep2 x tp2 mesh,
    checkpoint, reload, and continue — the continued loss matches a
    straight-through run exactly."""
    from distributed_pytorch_from_scratch_tpu.training.checkpoint import (
        load_checkpoint, save_checkpoint)
    from distributed_pytorch_from_scratch_tpu.training.zero import (
        zero1_moment_shardings)

    ocfg = OptimizerConfig(lr=1e-3, warmup_steps=2, max_steps=20)
    shape = dict(dp=2, ep=2, tp=2)
    model = Transformer(CFG, tp_size=2, ep_size=2)
    mesh = make_mesh(MeshConfig(**shape))
    moment_sh = zero1_moment_shardings(model, mesh)
    params = jax.device_put(model.init(jax.random.key(0)),
                            model.shardings(mesh))
    opt = init_adam_state(params)
    step = build_train_step(model, mesh, ocfg, zero1=True,
                            moment_shardings=moment_sh)

    losses = []
    for i in range(3):
        ids, tgt, pos = make_batch(jax.random.key(200 + i))
        params, opt, loss = step(params, opt, ids, tgt, pos)
        losses.append(float(loss))
    save_checkpoint(str(tmp_path), 3, losses[-1], params, model.specs(),
                    tp_size=2, opt_state=opt)

    # straight-through continuation
    ids, tgt, pos = make_batch(jax.random.key(203))
    _, _, loss_cont = step(params, opt, ids, tgt, pos)

    # reload into fresh buffers and take the same 4th step
    template = model.init(jax.random.key(7))  # different values, same tree
    p2, o2, st = load_checkpoint(str(tmp_path), 3, template, model.specs(),
                                 with_opt=True)
    assert st == 3
    p2 = jax.device_put(p2, model.shardings(mesh))
    o2 = jax.device_put(o2, o2.__class__(
        step=jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec()),
        mu=moment_sh, nu=moment_sh))
    _, _, loss_resume = step(p2, o2, ids, tgt, pos)
    np.testing.assert_allclose(float(loss_resume), float(loss_cont),
                               rtol=1e-6)


def test_moe_decode_matches_forward():
    """Greedy KV-cache decode runs the MoE FFN per step; its chosen tokens
    must match argmax over the full-forward logits."""
    from distributed_pytorch_from_scratch_tpu.models.decode import (
        GreedyDecoder)
    cfg = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=4, num_layers=2,
                      vocab_size=96, maxlen=64, num_experts=4,
                      moe_capacity_factor=8.0, compute_dtype="float32")
    mesh = make_mesh(MeshConfig(dp=1, ep=2, tp=2))
    model = Transformer(cfg, tp_size=2, ep_size=2)
    params = jax.device_put(model.init(jax.random.key(0)),
                            model.shardings(mesh))
    dec = GreedyDecoder(model, mesh, buf_len=32)
    prompts = [[5, 6, 7], [1, 2, 3, 4]]
    eos = cfg.vocab_size - 1
    outs = dec.decode_batch(params, prompts, eos_id=eos, max_total_len=10)
    # oracle: step-by-step argmax over the full forward on the same mesh
    fwd = model.make_forward(mesh)
    for p, out in zip(prompts, outs):
        seq = list(p)
        while len(seq) < 10:
            # batch of 2 identical rows: the ep axis shards the batch, so a
            # single row would not divide dp*ep=2
            ids = jnp.asarray([seq, seq], jnp.int32)
            pos = jnp.tile(jnp.arange(len(seq), dtype=jnp.int32)[None, :],
                           (2, 1))
            logits = fwd(params, ids, pos)
            nxt = int(jnp.argmax(logits[0, -1, :cfg.vocab_size]))
            # the decoder's contract excludes EOS from the returned ids
            # (models/decode.decode_batch) — the oracle must too, or an
            # early-EOS init makes the lists differ by the terminator
            if nxt == eos:
                break
            seq.append(nxt)
        assert out == seq[len(p):], (out, seq[len(p):])


def test_moe_sequence_parallel_matches_dense_mesh():
    """SP + MoE (VERDICT r2 #4): the router sees the tp-gathered tokens and
    each rank keeps its sequence slice of the expert output."""
    from distributed_pytorch_from_scratch_tpu.models.transformer import (
        Transformer)

    cfg = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=4, num_layers=2,
                      vocab_size=96, maxlen=64, num_experts=4, moe_top_k=2,
                      moe_capacity_factor=8.0)
    ids, tgt, pos = make_batch(jax.random.key(11))

    ref = Transformer(cfg)
    mesh1 = make_mesh(MeshConfig())
    params = ref.init(jax.random.key(0))
    l_ref, g_ref = jax.value_and_grad(ref.make_loss(mesh1))(
        params, ids, tgt, pos)

    model = Transformer(cfg, tp_size=2, ep_size=2, sequence_parallel=True)
    mesh = make_mesh(MeshConfig(ep=2, tp=2))
    sp = jax.device_put(params, model.shardings(mesh))
    l_sh, g_sh = jax.value_and_grad(model.make_loss(mesh))(sp, ids, tgt, pos)

    np.testing.assert_allclose(float(l_sh), float(l_ref), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(g_sh), jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)
