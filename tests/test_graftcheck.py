"""graftcheck (analysis/ + scripts/graftcheck.py) — ISSUE 11.

Three layers of pinning:

* **fixture corpus** — every lint rule has a known-bad snippet that must
  trigger EXACTLY that rule and a known-good sibling that must stay
  clean (tests/graftcheck_fixtures/); plus the pragma escape hatch.
* **clean-repo gate** — the layer-1 sweep over this repo returns zero
  violations. Every future PR inherits the contract: new dead imports,
  compat bypasses, donation misuse etc. fail HERE, not on a chip.
* **trace contracts** — the acceptance pins: the compiled train step's
  collective inventory matches `obs/attribution.expected_collectives`
  for zero ∈ {1,2,3} at dp2 x tp2 + SP; the int8-wire step provably
  carries no wide dp payload; ZeRO-3 contains no whole-tree dp gather
  (and refuses int8 loudly); the paged decode step's donation actually
  aliases and its lowering is stable across host states.
"""

import glob
import json
import os
import subprocess
import sys

import pytest

from distributed_pytorch_from_scratch_tpu.analysis import (
    GRAFTCHECK_SCHEMA_VERSION, RULES, build_report, format_report,
    lint_file, lint_paths, validate_report)
from distributed_pytorch_from_scratch_tpu.analysis.report import (
    write_report)

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
FIXTURES = os.path.join(HERE, "graftcheck_fixtures")

ALL_RULES = sorted(RULES)


# ------------------------------------------------------------ fixtures --

def _fixture(name):
    return os.path.join(FIXTURES, name + ".py")


@pytest.mark.parametrize("rule", ALL_RULES)
def test_bad_fixture_triggers_exactly_its_rule(rule):
    """Positive fixture: the known-bad snippet fires its rule (and ONLY
    its rule — cross-talk would make every pragma suppress too much)."""
    path = _fixture("bad_" + rule.replace("-", "_"))
    assert os.path.exists(path), f"no bad fixture for rule {rule}"
    vios = lint_file(path)
    hit = sorted({v.rule for v in vios})
    assert hit == [rule], (rule, [v.format() for v in vios])
    assert all(v.line > 0 for v in vios)


@pytest.mark.parametrize("rule", ALL_RULES)
def test_good_fixture_stays_clean(rule):
    """Negative fixture: the corrected idiom produces no violations at
    all (any rule firing here is a false positive)."""
    path = _fixture("good_" + rule.replace("-", "_"))
    assert os.path.exists(path), f"no good fixture for rule {rule}"
    vios = lint_file(path)
    assert vios == [], [v.format() for v in vios]


def test_rule_count_meets_acceptance_floor():
    """ISSUE 11 acceptance: >= 8 rules, each with both fixture polarities
    (the two tests above parametrize over exactly these)."""
    assert len(ALL_RULES) >= 8, ALL_RULES


def test_pragma_suppresses_on_line_and_file():
    bad = open(_fixture("bad_unused_import")).read()
    # line pragma on the flagged import
    patched = bad.replace(
        "import json",
        "import json  # graftcheck: disable=unused-import", 1)
    vios = lint_file(_fixture("bad_unused_import"), text=patched)
    assert all("json" not in v.message for v in vios)
    assert any(v.rule == "unused-import" for v in vios)  # other import
    # file pragma kills the whole rule
    patched = "# graftcheck: disable-file=unused-import\n" + bad
    vios = lint_file(_fixture("bad_unused_import"), text=patched)
    assert vios == []


def test_report_path_override_names_snippets():
    vios = lint_file(_fixture("bad_unreachable_code"),
                     report_path="<snippet>")
    assert vios and all(v.path == "<snippet>" for v in vios)


# -------------------------------------------------------- clean-repo gate --

@pytest.fixture(scope="module")
def repo_sweep():
    return lint_paths([REPO], root=REPO)


def test_repo_sweep_is_clean(repo_sweep):
    """THE gate: the layer-1 sweep over this repo is violation-free.
    When this fails, either fix the finding or (for a justified
    exception) add an inline `# graftcheck: disable=<rule>` pragma —
    see docs/ANALYSIS.md."""
    vios, files = repo_sweep
    assert files > 100, f"sweep saw only {files} files — wrong root?"
    assert vios == [], "\n".join(v.format() for v in vios)


def test_sweep_excludes_the_fixture_corpus(repo_sweep):
    """The deliberately-bad fixtures must NOT be swept (they would turn
    the clean-repo gate permanently red) — but sweeping the corpus
    directly does find them."""
    vios, _ = repo_sweep
    assert not any("graftcheck_fixtures" in v.path for v in vios)
    vios, files = lint_paths(glob.glob(os.path.join(FIXTURES, "bad_*.py")),
                             root=REPO)
    assert files >= 8 and vios


# ---------------------------------------------------------------- report --

def test_report_schema_roundtrip(tmp_path):
    vios = lint_file(_fixture("bad_unused_import"))
    doc = build_report(vios, files_scanned=1,
                       contracts=[{"name": "x", "ok": True, "detail": ""}],
                       duration_s=0.1)
    assert doc["schema_version"] == GRAFTCHECK_SCHEMA_VERSION
    assert doc["ok"] is False
    assert doc["violation_counts"] == {"unused-import": len(vios)}
    assert validate_report(doc) == []
    p = tmp_path / "graftcheck.json"
    write_report(doc, str(p))
    loaded = json.loads(p.read_text())
    assert validate_report(loaded) == []
    text = format_report(loaded)
    assert "unused-import" in text and "graftcheck:" in text


def test_report_validator_fails_loudly_on_drift():
    doc = build_report([], 0, [])
    doc["schema_version"] = GRAFTCHECK_SCHEMA_VERSION + 1
    assert any("NEWER" in p for p in validate_report(doc))
    assert any("missing field" in p
               for p in validate_report({"tool": "graftcheck"}))


def test_clean_report_is_ok_and_failed_contract_is_not():
    assert build_report([], 5, [])["ok"] is True
    doc = build_report([], 5, [{"name": "c", "ok": False, "detail": "d"}])
    assert doc["ok"] is False
    assert "FAIL" in format_report(doc)


# ------------------------------------------------------------------- CLI --

def _run_cli(args):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "graftcheck.py")]
        + args, capture_output=True, text=True, cwd=REPO, timeout=120)


@pytest.mark.parametrize("rule", ALL_RULES)
def test_cli_exits_1_on_each_fixture_violation(rule):
    """ISSUE 11 acceptance, literally: the CLI exits 1 on EACH rule's
    fixture violation (jax-free --no-trace path, ~1 s per run)."""
    out = _run_cli(["--no-trace", _fixture("bad_" + rule.replace("-", "_"))])
    assert out.returncode == 1, (rule, out.stdout, out.stderr)
    assert rule in out.stdout


def test_cli_no_trace_exits_by_verdict(tmp_path):
    """Exit 1 on each fixture violation, 0 on a clean file — without ever
    importing jax (--no-trace must stay chip-image-independent)."""
    bad = _run_cli(["--no-trace", _fixture("bad_use_after_donate")])
    assert bad.returncode == 1, bad.stdout + bad.stderr
    assert "use-after-donate" in bad.stdout
    good = _run_cli(["--no-trace", _fixture("good_use_after_donate"),
                     "--json", str(tmp_path / "r.json")])
    assert good.returncode == 0, good.stdout + good.stderr
    doc = json.loads((tmp_path / "r.json").read_text())
    assert validate_report(doc) == [] and doc["ok"] is True
    # the skipped trace layer is recorded as "no contracts", not "clean"
    assert doc["contracts"] == []


def test_summarize_run_renders_graftcheck_section(tmp_path):
    """scripts/summarize_run.py renders a 'Static contracts' section when
    a graftcheck report is present in the run dir (the CI/tooling
    satellite), including the failing contract's detail."""
    import importlib.util
    from distributed_pytorch_from_scratch_tpu.analysis.rules import (
        Violation)
    doc = build_report(
        [Violation("unused-import", "x.py", 3, "'json' never used")], 3,
        [{"name": "donation-aliased", "ok": False,
          "detail": "2 leaves un-aliased", "program": "paged_decode"}])
    write_report(doc, str(tmp_path / "graftcheck.json"))
    spec = importlib.util.spec_from_file_location(
        "_gc_summarize", os.path.join(REPO, "scripts", "summarize_run.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    text = mod.summarize(str(tmp_path))
    assert "Static contracts" in text
    assert "VIOLATIONS" in text and "unused-import" in text
    assert "FAIL donation-aliased" in text and "paged_decode" in text
    # and a future-versioned report warns instead of rendering garbage
    doc["schema_version"] += 10
    write_report(doc, str(tmp_path / "graftcheck.json"))
    assert "SCHEMA DRIFT" in mod.summarize(str(tmp_path))


def test_cli_list_rules():
    out = _run_cli(["--list-rules"])
    assert out.returncode == 0
    for rule in ALL_RULES:
        assert rule in out.stdout


def test_cli_rejects_unknown_rule_ids():
    """A typo'd --rules must exit 2, not filter every finding and report
    a false 'clean'."""
    out = _run_cli(["--no-trace", "--rules", "use_after_donate",
                    _fixture("bad_use_after_donate")])
    assert out.returncode == 2, (out.stdout, out.stderr)
    assert "unknown rule id" in out.stderr
    # the kebab-case id works and still fails the file
    out = _run_cli(["--no-trace", "--rules", "use-after-donate",
                    _fixture("bad_use_after_donate")])
    assert out.returncode == 1


# ------------------------------------------------- trace contracts (L2) --

@pytest.fixture(scope="module")
def contracts_mod():
    from distributed_pytorch_from_scratch_tpu.analysis import contracts
    return contracts


@pytest.fixture(scope="module")
def programs_mod():
    from distributed_pytorch_from_scratch_tpu.analysis import programs
    return programs


@pytest.mark.parametrize("stage,wire", [(0, "f32"), (1, "f32"),
                                        (2, "f32"), (2, "int8"),
                                        (3, "f32")])
def test_collective_inventory_matches_priced_schedule(
        contracts_mod, programs_mod, stage, wire):
    """ISSUE 11 acceptance + the satellite pin: the compiled train step's
    per-axis collective inventory at dp2 x tp2 + SP equals what
    `expected_collectives` derives from the priced schedule, for zero
    stages 0-3 (and the int8 stage-2 wire). Attribution drift — a new
    collective, a vanished one, a dtype change — fails here. Stage 0's
    donation leg is the regression pin for the out_shardings fix this
    checker found in training/train_step.py."""
    from distributed_pytorch_from_scratch_tpu.obs.attribution import (
        expected_collectives)
    prog = programs_mod.train_step_program(stage, wire)
    res = contracts_mod.check_collective_inventory(
        prog, expected_collectives(**prog.config))
    assert res["ok"], res["detail"]
    # and the donation contract rides along on every lowered step
    res = contracts_mod.check_donation_aliased(prog)
    assert res["ok"], res["detail"]


def test_stage2_inventory_actually_detects_drift(contracts_mod,
                                                 programs_mod):
    """The inventory check must FAIL when the schedule and the program
    disagree — pin it against a deliberately wrong expectation."""
    from distributed_pytorch_from_scratch_tpu.obs.attribution import (
        expected_collectives)
    prog = programs_mod.train_step_program(2, "f32")
    wrong = expected_collectives(**dict(prog.config, zero_stage=3))
    res = contracts_mod.check_collective_inventory(prog, wrong)
    assert not res["ok"]
    assert "all-gather" in res["detail"]  # stage 3 forbids the dp gather


def test_int8_wire_carries_no_wide_dp_payload(contracts_mod,
                                              programs_mod):
    """ISSUE 11 acceptance: the int8-wire train step provably contains no
    f32 dp-axis collective beyond the documented param all-gather — the
    'int8 silently falls back to f32' hazard, checked statically."""
    prog = programs_mod.train_step_program(2, "int8")
    res = contracts_mod.check_no_wide_dp_wire(
        prog, allowed_ops=("all-gather",))
    assert res["ok"], res["detail"]
    # the f32-wire sibling must FAIL the same check (the contract has
    # teeth: it distinguishes the wires, not just passes everything)
    prog32 = programs_mod.train_step_program(2, "f32")
    res32 = contracts_mod.check_no_wide_dp_wire(
        prog32, allowed_ops=("all-gather",))
    assert not res32["ok"]


def test_zero3_has_no_whole_tree_gather_and_refuses_int8(
        contracts_mod, programs_mod):
    prog = programs_mod.train_step_program(3, "f32")
    res = contracts_mod.check_zero3_no_whole_tree_gather(prog)
    assert res["ok"], res["detail"]
    msg = programs_mod.train_step_refuses(3, "int8")
    assert msg is not None and "stage 2" in msg


def test_paged_decode_donation_aliased_and_lowering_stable(
        contracts_mod, programs_mod):
    """ISSUE 11 acceptance: the paged decode step's donated KV pool
    halves alias in the executable (in-place page writes survive
    compile), and the lowering is byte-identical across host states
    (cursors, step index, table contents) — no per-step recompiles."""
    prog = programs_mod.paged_decode_program()
    res = contracts_mod.check_donation_aliased(prog)
    assert res["ok"], res["detail"]
    assert prog.donated_leaves == 2  # pool ks + vs
    res = contracts_mod.check_stable_lowering(
        "paged_decode", contracts_mod._decode_lowerings())
    assert res["ok"], res["detail"]


def test_pallas_decode_same_schedule_and_stable_lowering(
        contracts_mod, programs_mod):
    """ISSUE 14 layer-2 satellite: the PALLAS decode dispatch (the
    kernel lowered through the interpreter on the contract mesh) must
    (a) satisfy the SAME expected_collectives schedule as the gather
    impl — the kernel changes HBM traffic, never the wire, so any new
    collective is a contract failure, (b) keep the donated pool halves
    aliased, and (c) lower byte-identically from 3 host states — the
    scalar-prefetched page table must never bake values into the
    program."""
    from distributed_pytorch_from_scratch_tpu.obs.attribution import (
        expected_collectives)
    prog = programs_mod.paged_decode_program(paged_attn="pallas")
    res = contracts_mod.check_collective_inventory(
        prog, expected_collectives(**prog.config))
    assert res["ok"], res["detail"]
    res = contracts_mod.check_donation_aliased(prog)
    assert res["ok"], res["detail"]
    res = contracts_mod.check_stable_lowering(
        "paged_decode_pallas",
        contracts_mod._decode_lowerings(paged_attn="pallas"))
    assert res["ok"], res["detail"]
    # the gather and pallas programs carry the same (axis, op) inventory
    gather = programs_mod.paged_decode_program()
    inv = lambda p: {k: v["count"] for k, v in contracts_mod.inventory(
        contracts_mod.parse_collectives_by_axis(p.compiled_text,
                                                p.mesh)).items()}
    assert inv(prog) == inv(gather), (inv(prog), inv(gather))


def test_axis_classification_on_the_test_mesh(contracts_mod):
    """The HLO group classifier must map both replica_groups formats and
    permute pairs onto the right mesh axes (everything else rests on
    this)."""
    from distributed_pytorch_from_scratch_tpu.config import MeshConfig
    from distributed_pytorch_from_scratch_tpu.runtime.mesh import make_mesh
    mesh = make_mesh(MeshConfig(dp=2, tp=2))
    ag = contracts_mod._axis_groups(mesh)
    assert set(ag) == {"dp", "tp", "all"}
    # braced + iota formats, pairs, singletons
    assert contracts_mod._classify([(0, 1), (2, 3)], ag) == "tp"
    assert contracts_mod._classify([(0, 2), (1, 3)], ag) == "dp"
    assert contracts_mod._classify([(0, 1, 2, 3)], ag) == "all"
    assert contracts_mod._classify([(0,), (1,)], ag) == "local"
    assert contracts_mod._parse_iota_groups("[2,2]<=[4]") == [
        (0, 1), (2, 3)]
    assert contracts_mod._parse_iota_groups("[2,2]<=[2,2]T(1,0)") == [
        (0, 2), (1, 3)]
    assert contracts_mod._classify_pairs([(0, 2), (2, 0)], ag) == "dp"
    assert contracts_mod._classify_pairs([(0, 1), (1, 0)], ag) == "tp"
