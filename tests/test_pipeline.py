"""Pipeline parallelism (GPipe schedule over the 'pp' mesh axis).

The reference has no pipeline parallelism (SURVEY §2.4 "PP ❌" — its layers
run in a single-device Python loop, `/root/reference/models/model.py:132-135`).
The oracle is therefore the framework itself on a single-device mesh, the
same idiom as the MoE/CP suites:

* loss, full logits and every gradient leaf match the 1-device run exactly
  (the pipeline is semantically invisible — including the subtle last-stage
  loss masking that keeps replicated embedding/lm_head cotangents from
  being psum-multiplied by pp);
* multi-step training histories match (the transposed reverse-time
  backward pipeline is drift-free over optimizer steps);
* composition with dp and tp on one mesh;
* static validation errors.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_from_scratch_tpu.config import (IGNORE_INDEX,
                                                         MeshConfig,
                                                         ModelConfig,
                                                         OptimizerConfig)
from distributed_pytorch_from_scratch_tpu.models.transformer import Transformer
from distributed_pytorch_from_scratch_tpu.runtime.mesh import make_mesh
from distributed_pytorch_from_scratch_tpu.training.optim import (
    init_adam_state)
from distributed_pytorch_from_scratch_tpu.training.train_step import (
    build_train_step)

CFG = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=4, num_layers=4,
                  vocab_size=96, maxlen=64)


def make_batch(key, batch=8, t=16, vocab=96):
    k1, k2 = jax.random.split(key)
    input_ids = jax.random.randint(k1, (batch, t), 0, vocab)
    target_ids = jax.random.randint(k2, (batch, t), 0, vocab)
    mask = jax.random.bernoulli(jax.random.fold_in(key, 9), 0.2, (batch, t))
    target_ids = jnp.where(mask, IGNORE_INDEX, target_ids)
    position_ids = jnp.tile(jnp.arange(t)[None, :], (batch, 1))
    return input_ids, target_ids, position_ids


MESHES = [
    # (dp, pp, tp, microbatches); 0 microbatches -> pp (minimum schedule)
    ("pp2", 1, 2, 1, 0),
    ("pp4", 1, 4, 1, 0),
    pytest.param("pp2_m8", 1, 2, 1, 8,
                 marks=pytest.mark.slow),  # deep pipe: 8 microbatches of 1
    ("pp2tp2", 1, 2, 2, 0),
    pytest.param("dp2pp2tp2", 2, 2, 2, 4, marks=pytest.mark.slow),
]


@pytest.mark.parametrize("name,dp,pp,tp,m", MESHES)
def test_loss_logits_grads_match_single_device(name, dp, pp, tp, m):
    key = jax.random.key(0)
    ids, tgt, pos = make_batch(jax.random.key(2))

    ref = Transformer(CFG)
    mesh1 = make_mesh(MeshConfig())
    params = ref.init(key)
    l_ref, g_ref = jax.value_and_grad(ref.make_loss(mesh1))(
        params, ids, tgt, pos)
    logits_ref = ref.make_forward(mesh1)(params, ids, pos)

    model = Transformer(CFG, tp_size=tp, pp_size=pp, pp_microbatches=m)
    mesh = make_mesh(MeshConfig(dp=dp, pp=pp, tp=tp))
    sp = jax.device_put(params, model.shardings(mesh))
    l_sh, g_sh = jax.value_and_grad(model.make_loss(mesh))(sp, ids, tgt, pos)
    logits_sh = model.make_forward(mesh)(sp, ids, pos)

    np.testing.assert_allclose(float(l_sh), float(l_ref), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(logits_sh), np.asarray(logits_ref),
                               rtol=1e-4, atol=1e-4)
    for a, b in zip(jax.tree.leaves(g_sh), jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


@pytest.mark.slow  # heaviest of its family; shorter siblings stay fast
def test_multi_step_history_matches_single_device():
    """20 Adam steps on dp2 x pp2 x tp2 reproduce the 1-device loss history
    (the reference's multi-step equivalence idiom, SURVEY §4 check 3)."""
    ocfg = OptimizerConfig(lr=1e-3, warmup_steps=5, max_steps=30)
    histories = {}
    for name, shape, kw in [
            ("single", dict(), dict()),
            ("dp2pp2tp2", dict(dp=2, pp=2, tp=2),
             dict(tp_size=2, pp_size=2, pp_microbatches=2))]:
        model = Transformer(CFG, **kw)
        mesh = make_mesh(MeshConfig(**shape))
        params = jax.device_put(model.init(jax.random.key(0)),
                                model.shardings(mesh))
        opt = init_adam_state(params)
        step = build_train_step(model, mesh, ocfg)
        losses = []
        for i in range(20):
            ids, tgt, pos = make_batch(jax.random.key(100 + i))
            params, opt, loss = step(params, opt, ids, tgt, pos)
            losses.append(float(loss))
        histories[name] = losses
    np.testing.assert_allclose(histories["single"], histories["dp2pp2tp2"],
                               rtol=2e-4)


def test_pp_composes_with_cp():
    """pp x cp on one mesh: the ring-attention sequence sharding runs inside
    each pipeline stage."""
    ids, tgt, pos = make_batch(jax.random.key(3), batch=4, t=32)
    ref = Transformer(CFG)
    params = ref.init(jax.random.key(0))
    l_ref = ref.make_loss(make_mesh(MeshConfig()))(params, ids, tgt, pos)

    model = Transformer(CFG, pp_size=2, cp_size=2, tp_size=2)
    mesh = make_mesh(MeshConfig(pp=2, cp=2, tp=2))
    sp = jax.device_put(params, model.shardings(mesh))
    l_sh = model.make_loss(mesh)(sp, ids, tgt, pos)
    np.testing.assert_allclose(float(l_sh), float(l_ref), rtol=1e-5)


def test_pp_checkpoint_resume(tmp_path):
    """pp-sharded layer stacks round-trip through save/load: resume-step
    loss equals the straight-through loss."""
    from distributed_pytorch_from_scratch_tpu.training.checkpoint import (
        load_checkpoint, save_checkpoint)

    ocfg = OptimizerConfig(lr=1e-3, warmup_steps=2, max_steps=20)
    model = Transformer(CFG, tp_size=2, pp_size=2, pp_microbatches=2)
    mesh = make_mesh(MeshConfig(pp=2, tp=2))
    params = jax.device_put(model.init(jax.random.key(0)),
                            model.shardings(mesh))
    opt = init_adam_state(params)
    step = build_train_step(model, mesh, ocfg)
    for i in range(2):
        ids, tgt, pos = make_batch(jax.random.key(300 + i))
        params, opt, loss = step(params, opt, ids, tgt, pos)
    save_checkpoint(str(tmp_path), 2, float(loss), params, model.specs(),
                    tp_size=2, opt_state=opt)

    ids, tgt, pos = make_batch(jax.random.key(302))
    _, _, loss_cont = step(params, opt, ids, tgt, pos)

    template = model.init(jax.random.key(7))
    p2, o2, st = load_checkpoint(str(tmp_path), 2, template, model.specs(),
                                 with_opt=True)
    p2 = jax.device_put(p2, model.shardings(mesh))
    o2 = jax.device_put(o2, o2.__class__(
        step=jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec()),
        mu=model.shardings(mesh), nu=model.shardings(mesh)))
    _, _, loss_resume = step(p2, o2, ids, tgt, pos)
    np.testing.assert_allclose(float(loss_resume), float(loss_cont),
                               rtol=1e-6)


def test_validation_errors():
    with pytest.raises(ValueError, match="divisible"):
        Transformer(CFG, pp_size=3)  # 4 layers % 3 != 0
    # pp + MoE and pp + sequence_parallel are SUPPORTED since round 3
    # (VERDICT r2 #4) — construction must succeed
    Transformer(ModelConfig(num_layers=4, num_experts=4), pp_size=2)
    Transformer(CFG, pp_size=2, sequence_parallel=True)
    with pytest.raises(ValueError, match="bubbles"):
        Transformer(CFG, pp_size=4, pp_microbatches=2)
    # local batch not divisible by microbatches -> runtime error
    model = Transformer(CFG, pp_size=2, pp_microbatches=3)
    mesh = make_mesh(MeshConfig(pp=2))
    params = jax.device_put(model.init(jax.random.key(0)),
                            model.shardings(mesh))
    ids, tgt, pos = make_batch(jax.random.key(1), batch=4)
    with pytest.raises(ValueError, match="not divisible"):
        model.make_loss(mesh)(params, ids, tgt, pos)


# ---- composability matrix closure (VERDICT r2 #4): pp x {MoE, SP} ----

MOE_CFG = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=4, num_layers=4,
                      vocab_size=96, maxlen=64, num_experts=4, moe_top_k=2,
                      moe_capacity_factor=8.0)  # generous: zero drops


@pytest.mark.parametrize("name,axes,kw", [
    ("pp2_moe", dict(pp=2), dict(pp_size=2)),
    ("pp2ep2tp2_moe", dict(pp=2, ep=2, tp=2),
     dict(pp_size=2, ep_size=2, tp_size=2, pp_microbatches=2)),
    # pp x ring-CP x MoE: the live-gated schedule (VERDICT r3 #3) with
    # router aux riding the skip branches' zeroed leaves
    ("pp2cp2_moe_ring", dict(pp=2, cp=2),
     dict(pp_size=2, cp_size=2, pp_microbatches=2)),
])
def test_pipeline_moe_matches_single_device(name, axes, kw):
    """MoE models pipeline: router aux sums ride the schedule carry and the
    aux losses match the 1-device run exactly (no drops at cf=8)."""
    key = jax.random.key(0)
    ids, tgt, pos = make_batch(jax.random.key(2))

    ref = Transformer(MOE_CFG)
    mesh1 = make_mesh(MeshConfig())
    params = ref.init(key)
    l_ref, g_ref = jax.value_and_grad(ref.make_loss(mesh1))(
        params, ids, tgt, pos)

    model = Transformer(MOE_CFG, **kw)
    mesh = make_mesh(MeshConfig(**axes))
    sp = jax.device_put(params, model.shardings(mesh))
    l_sh, g_sh = jax.value_and_grad(model.make_loss(mesh))(sp, ids, tgt, pos)

    np.testing.assert_allclose(float(l_sh), float(l_ref), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(g_sh), jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("name,axes,kw", [
    ("pp2_sp", dict(pp=2, tp=2),
     dict(pp_size=2, tp_size=2, sequence_parallel=True)),
    ("dp2pp2tp2_sp", dict(dp=2, pp=2, tp=2),
     dict(pp_size=2, tp_size=2, sequence_parallel=True, pp_microbatches=4)),
])
def test_pipeline_sequence_parallel_matches_single_device(name, axes, kw):
    """Megatron SP composes with the pipeline: the step carry is the
    (mb, t/tp, d) seq-sharded activation (tp-varying vma)."""
    key = jax.random.key(0)
    ids, tgt, pos = make_batch(jax.random.key(3))

    ref = Transformer(CFG)
    mesh1 = make_mesh(MeshConfig())
    params = ref.init(key)
    l_ref, g_ref = jax.value_and_grad(ref.make_loss(mesh1))(
        params, ids, tgt, pos)

    model = Transformer(CFG, **kw)
    mesh = make_mesh(MeshConfig(**axes))
    sp = jax.device_put(params, model.shardings(mesh))
    l_sh, g_sh = jax.value_and_grad(model.make_loss(mesh))(sp, ids, tgt, pos)

    np.testing.assert_allclose(float(l_sh), float(l_ref), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(g_sh), jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_pipeline_nondivisible_batch_falls_back_to_masked_head():
    """batch 6 with pp 2, microbatches 3: b % pp == 0 here would be 0 —
    use b=6, M=3, pp=2 -> b%pp=0... pick M=3, pp=3, b=6 -> chunks of 2;
    instead force the fallback with b=10, pp=4, M=5 (10 % 4 != 0)."""
    ids, tgt, pos = make_batch(jax.random.key(4), batch=10)
    ref = Transformer(CFG)
    mesh1 = make_mesh(MeshConfig())
    params = ref.init(jax.random.key(0))
    l_ref = ref.make_loss(mesh1)(params, ids, tgt, pos)

    model = Transformer(CFG, pp_size=4, pp_microbatches=5)
    mesh = make_mesh(MeshConfig(pp=4))
    sp = jax.device_put(params, model.shardings(mesh))
    l_sh = model.make_loss(mesh)(sp, ids, tgt, pos)
    np.testing.assert_allclose(float(l_sh), float(l_ref), rtol=1e-5)


def test_pipeline_remat_steps_matches():
    """pp_remat_steps=True (the 1F1B-style memory option) is numerically
    invisible."""
    ids, tgt, pos = make_batch(jax.random.key(5))
    ref = Transformer(CFG)
    mesh1 = make_mesh(MeshConfig())
    params = ref.init(jax.random.key(0))
    l_ref, g_ref = jax.value_and_grad(ref.make_loss(mesh1))(
        params, ids, tgt, pos)

    model = Transformer(CFG, pp_size=2, pp_microbatches=4,
                        pp_remat_steps=True)
    mesh = make_mesh(MeshConfig(pp=2))
    sp = jax.device_put(params, model.shardings(mesh))
    l_sh, g_sh = jax.value_and_grad(model.make_loss(mesh))(sp, ids, tgt, pos)
    np.testing.assert_allclose(float(l_sh), float(l_ref), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(g_sh), jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_pp_microbatches_without_pp_raises():
    with pytest.raises(ValueError, match="pp_microbatches requires"):
        Transformer(CFG, pp_microbatches=4)


# ---- interleaved (virtual-stage) schedule (VERDICT r3 #7) ----

@pytest.mark.parametrize("name,axes,kw", [
    ("pp2_V2", dict(pp=2), dict(pp_size=2)),
    pytest.param("pp2_V2_m4", dict(pp=2),
                 dict(pp_size=2, pp_microbatches=4),
                 marks=pytest.mark.slow),
    pytest.param("pp2tp2_V2_remat", dict(pp=2, tp=2),
                 dict(pp_size=2, tp_size=2, pp_remat_steps=True),
                 marks=pytest.mark.slow),
    ("pp4_V2", dict(pp=4), dict(pp_size=4, pp_microbatches=4)),
    ("pp2_V2_cp2_ring", dict(pp=2, cp=2), dict(pp_size=2, cp_size=2)),
])
def test_interleaved_matches_single_device(name, axes, kw):
    """The interleaved schedule (each device owns pp_virtual round-robin
    layer blocks; microbatches circulate the ring pp_virtual times) is
    semantically invisible: loss + every gradient leaf (canonicalised back
    to the (L, ...) stack) match the 1-device oracle, including composed
    with tp, per-step remat, and the live-gated ring-CP path."""
    cfg = (ModelConfig(attn_dim=32, ffn_dim=64, num_heads=4, num_layers=8,
                       vocab_size=96, maxlen=64)
           if axes.get("pp") == 4 else CFG)
    ids, tgt, pos = make_batch(jax.random.key(11))
    ref = Transformer(cfg)
    params = ref.init(jax.random.key(0))
    l_ref, g_ref = jax.value_and_grad(ref.make_loss(make_mesh(MeshConfig())))(
        params, ids, tgt, pos)

    model = Transformer(cfg, pp_schedule="interleaved", **kw)
    mesh = make_mesh(MeshConfig(**axes))
    sp = jax.device_put(model.from_canonical(params), model.shardings(mesh))
    l_sh, g_sh = jax.value_and_grad(model.make_loss(mesh))(sp, ids, tgt, pos)
    np.testing.assert_allclose(float(l_sh), float(l_ref), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(model.to_canonical(g_sh)),
                    jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_interleaved_moe_matches_single_device():
    """MoE through the interleaved schedule: router aux sums accumulate
    across V circulations x M microbatches per device."""
    mcfg = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=4, num_layers=4,
                       vocab_size=96, maxlen=64, num_experts=4, moe_top_k=2,
                       moe_capacity_factor=8.0)
    ids, tgt, pos = make_batch(jax.random.key(12))
    ref = Transformer(mcfg)
    params = ref.init(jax.random.key(0))
    l_ref, g_ref = jax.value_and_grad(ref.make_loss(make_mesh(MeshConfig())))(
        params, ids, tgt, pos)

    model = Transformer(mcfg, pp_size=2, ep_size=2,
                        pp_schedule="interleaved", pp_microbatches=2)
    mesh = make_mesh(MeshConfig(pp=2, ep=2))
    sp = jax.device_put(model.from_canonical(params), model.shardings(mesh))
    l_sh, g_sh = jax.value_and_grad(model.make_loss(mesh))(sp, ids, tgt, pos)
    np.testing.assert_allclose(float(l_sh), float(l_ref), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(model.to_canonical(g_sh)),
                    jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_interleaved_gpt2_matches_vanilla():
    """The second family through the interleaved schedule (tied head,
    learned positions) vs the unsharded oracle."""
    from distributed_pytorch_from_scratch_tpu.models.gpt2 import (
        GPT2Transformer)
    from distributed_pytorch_from_scratch_tpu.models.vanilla import (
        VanillaGPT2)

    gcfg = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=4, num_layers=4,
                       vocab_size=96, maxlen=64)
    ids, tgt, pos = make_batch(jax.random.key(13))
    oracle = VanillaGPT2(gcfg)
    model = GPT2Transformer(gcfg, pp_size=2, tp_size=2,
                            pp_schedule="interleaved", pp_microbatches=2)
    mesh = make_mesh(MeshConfig(pp=2, tp=2))
    params = oracle_params = GPT2Transformer(gcfg).init(jax.random.key(0))
    sp = jax.device_put(model.from_canonical(params), model.shardings(mesh))
    l_sh, g_sh = jax.value_and_grad(model.make_loss(mesh))(sp, ids, tgt, pos)
    l_ref, g_ref = jax.value_and_grad(oracle.loss)(oracle_params, ids, tgt,
                                                   pos)
    np.testing.assert_allclose(float(l_sh), float(l_ref), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(model.to_canonical(g_sh)),
                    jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_interleaved_validation_errors():
    with pytest.raises(ValueError, match="pp_size > 1"):
        Transformer(CFG, pp_schedule="interleaved")
    with pytest.raises(ValueError, match="pp_virtual"):
        Transformer(CFG, pp_size=2, pp_schedule="interleaved", pp_virtual=1)
    with pytest.raises(ValueError, match="pp_size\\*pp_virtual"):
        # 4 layers cannot split into 2 devices x 4 virtual blocks
        Transformer(CFG, pp_size=2, pp_schedule="interleaved", pp_virtual=4)
    with pytest.raises(ValueError, match="divisible"):
        Transformer(ModelConfig(attn_dim=32, ffn_dim=64, num_heads=4,
                                num_layers=8, vocab_size=96, maxlen=64),
                    pp_size=2, pp_schedule="interleaved", pp_microbatches=3)
    with pytest.raises(ValueError, match="gpipe"):
        Transformer(CFG, pp_size=2, pp_schedule="1f1b")
