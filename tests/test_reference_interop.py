"""Interop with the reference's ACTUAL shipped artifacts.

The schemas here are documented as byte-compatible with the reference
(`data/tokenizer.py`, `data/dataset.py`); this suite proves it against the
real files instead of self-produced fixtures:

* `/root/reference/tokenizer/tokenizer.json` — the reference's trained BPE
  (vocab 1024, BOS=0/EOS=1/UNK=2, verified by SURVEY §2.1) must load, encode
  through both the HF and the native C++ backends identically, and feed a
  real training step through the reference-schema token JSON.
* this repo's own shipped `tokenizer/tokenizer.json` (recipe step 3 output,
  trained offline on the repo-docs corpus) must satisfy the same contract.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_from_scratch_tpu.config import (BOS_TOKEN, EOS_TOKEN,
                                                         MeshConfig,
                                                         ModelConfig,
                                                         OptimizerConfig,
                                                         UNK_TOKEN)
from distributed_pytorch_from_scratch_tpu.data.dataset import get_dataloader
from distributed_pytorch_from_scratch_tpu.models.transformer import Transformer
from distributed_pytorch_from_scratch_tpu.runtime.mesh import make_mesh
from distributed_pytorch_from_scratch_tpu.training.optim import (
    init_adam_state)
from distributed_pytorch_from_scratch_tpu.training.train_step import (
    build_train_step)

REF_TOKENIZER = "/root/reference/tokenizer/tokenizer.json"
OUR_TOKENIZER = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tokenizer", "tokenizer.json")

SAMPLES = [
    "First Citizen:\nBefore we proceed any further, hear me speak.",
    "Nice to meet you, it's a test",
    "the quick brown fox jumps over the lazy dog 0123456789",
    "O Romeo, Romeo! wherefore art thou Romeo?",
]


def _require(path):
    if not os.path.exists(path):
        pytest.skip(f"{path} not present")
    return path


@pytest.fixture(scope="module", params=["reference", "shipped"])
def tokenizer_path(request):
    return _require(REF_TOKENIZER if request.param == "reference"
                    else OUR_TOKENIZER)


def test_tokenizer_loads_with_expected_specials(tokenizer_path):
    from tokenizers import Tokenizer
    tok = Tokenizer.from_file(tokenizer_path)
    assert tok.get_vocab_size() == 1024
    assert tok.token_to_id(BOS_TOKEN) == 0
    assert tok.token_to_id(EOS_TOKEN) == 1
    assert tok.token_to_id(UNK_TOKEN) == 2
    for text in SAMPLES:
        ids = tok.encode(text).ids
        assert ids and all(0 <= i < 1024 for i in ids)


def test_native_bpe_parity_on_artifact(tokenizer_path):
    """The C++ encoder must reproduce HF token-for-token on the artifact
    (NativeBPE's constructor self-check plus an explicit sample sweep)."""
    from tokenizers import Tokenizer
    from distributed_pytorch_from_scratch_tpu.data.native import (
        NativeBPE, native_available)
    if not native_available():
        pytest.skip("native library unavailable")
    native = NativeBPE(tokenizer_path)  # raises on probe mismatch
    hf = Tokenizer.from_file(tokenizer_path)
    for text in SAMPLES:
        assert native.encode(text) == hf.encode(text).ids, text


def test_train_steps_from_reference_schema_token_json(tmp_path):
    """pre_tokenize-schema JSON built with the REFERENCE tokenizer (same
    schema as `/root/reference/pre_tokenize.py:43-48`) drives the real
    dataloader + sharded train step: finite, decreasing loss."""
    from tokenizers import Tokenizer
    tok = Tokenizer.from_file(_require(REF_TOKENIZER))
    texts = SAMPLES * 8
    token_json = {
        "train": [tok.encode(t).ids for t in texts],
        "validation": [tok.encode(t).ids for t in texts[:4]],
        "special_ids": {BOS_TOKEN: tok.token_to_id(BOS_TOKEN),
                        EOS_TOKEN: tok.token_to_id(EOS_TOKEN),
                        UNK_TOKEN: tok.token_to_id(UNK_TOKEN)},
        "vocab_size": tok.get_vocab_size(),
    }
    data_path = tmp_path / "tokens.json"
    data_path.write_text(json.dumps(token_json))

    maxlen = 64
    loader = get_dataloader(str(data_path), batch_size=4, split="train",
                            maxlen=maxlen, seed=0)
    cfg = ModelConfig(attn_dim=64, ffn_dim=128, num_heads=8, num_layers=2,
                      vocab_size=token_json["vocab_size"], maxlen=maxlen)
    tp = 4
    mesh = make_mesh(MeshConfig(dp=2, tp=tp))
    model = Transformer(cfg, tp_size=tp)
    params = jax.device_put(model.init(jax.random.key(0)),
                            model.shardings(mesh))
    opt_state = init_adam_state(params)
    step_fn = build_train_step(
        model, mesh, OptimizerConfig(lr=1e-3, warmup_steps=2, max_steps=50))

    losses = []
    for step, batch in enumerate(loader.epoch(0)):
        if step >= 8:
            break
        params, opt_state, loss = step_fn(
            params, opt_state,
            jnp.asarray(batch["input_ids"]), jnp.asarray(batch["target_ids"]),
            jnp.asarray(batch["position_ids"]))
        losses.append(float(loss))
    assert len(losses) == 8
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0], losses
