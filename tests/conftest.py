"""Test harness: a virtual 8-device CPU mesh, no TPU required.

The reference's tests spawn real NCCL processes on >=2 physical GPUs
(`mp.spawn` in each `tests/*.py` `__main__`; SURVEY §4 — there are no
cluster-free tests at all). JAX makes distributed testing cheap: we force the
host platform to expose 8 virtual CPU devices and every sharding/collective
path runs in-process. The same test code runs unchanged on real TPU chips.

NOTE: this image injects an `axon` PJRT plugin via sitecustomize that forces
the TPU platform regardless of JAX_PLATFORMS, so we must override the
platform *after* importing jax, before any backend is initialised.
"""

import os

# Must be set before the first XLA CPU client is created.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def _check_devices():
    assert jax.device_count() >= 8, (
        f"expected 8 virtual CPU devices, got {jax.device_count()}")
    yield
