"""Test harness: a virtual 8-device CPU mesh, no TPU required.

The reference's tests spawn real NCCL processes on >=2 physical GPUs
(`mp.spawn` in each `tests/*.py` `__main__`; SURVEY §4 — there are no
cluster-free tests at all). JAX makes distributed testing cheap: we force the
host platform to expose 8 virtual CPU devices and every sharding/collective
path runs in-process. The same test code runs unchanged on real TPU chips.

NOTE: this image injects an `axon` PJRT plugin via sitecustomize that forces
the TPU platform regardless of JAX_PLATFORMS, so we must override the
platform *after* importing jax, before any backend is initialised.
"""

import os

# Must be set before the first XLA CPU client is created.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def _check_devices():
    assert jax.device_count() >= 8, (
        f"expected 8 virtual CPU devices, got {jax.device_count()}"
    )
    yield


# --- the `core` lane (VERDICT r4 #7: default loop < 5 min on a 1-core box)
#
# One curated representative per parallelism axis / feature, selected from
# measured durations (the full "not slow" lane is ~32 min on the build box;
# this list sums to ~4 min including session setup). Subprocess-harness and
# sweep files (multihost/preemption/wide-mesh/e2e/bench) are deliberately
# NOT represented — they live in the slow/fast lanes. Maintained centrally
# here instead of per-file markers so the budget is auditable in one place.
# An entry is either a whole file ("test_x.py": None) or a list of test-name
# prefixes (parametrized ids match by prefix).
CORE_LANE = {
    # foundations: comm ops + parallel layers + preflight (always run whole)
    "test_collectives.py": None,
    "test_parallel_layers.py": None,
    "test_staged_session.py": None,
    "test_interop_ckpt.py": None,
    "test_optim.py": None,
    "test_prefetch.py": None,
    "test_native_data.py": None,
    # one representative per axis/feature
    "test_transformer_equivalence.py": [
        "test_loss_and_grads_match[1-4-vocab_parallel]",
        "test_forward_logits_match[2-4]",
    ],
    "test_pipeline.py": ["test_loss_logits_grads_match_single_device[pp2-"],
    "test_moe.py": ["test_model_loss_logits_grads_match_single_device[ep2-"],
    "test_ring_attention.py": ["test_ring_forward_matches_dense[2-1]",
                               "test_grads_match_dense[ring]"],
    "test_flash_attention.py": ["test_forward_matches_oracle_bf16",
                                "test_gradients_match_oracle"],
    "test_gqa.py": ["test_gqa_matches_vanilla[2-1]"],
    "test_gpt2_model.py": ["test_forward_logits_match_vanilla"],
    "test_kv_decode.py": ["test_kv_matches_nocache[0-prompt0-1]",
                          "TestContextParallelDecode::"
                          "test_cp_decode_matches_cp1[2-1]"],
    # serving: the continuous-batching token-identity anchor (tp=2 covers
    # the tp=1 lowering modulo collectives), the pure-host scheduler
    # properties, and the serve CLI smoke (the chip-less-image rot guard)
    "test_serving.py": ["test_engine_matches_greedy_decoder[2]",
                        "test_scheduler_fifo_bucket_groups",
                        "test_scheduler_backpressure_and_validation",
                        "test_serve_dry_run_smoke"],
    # serving v2 (paged): the paged-vs-slot-vs-greedy identity anchor at
    # tp=2, COW sharing + refcount drain, the chunked-prefill stall bound,
    # the equal-HBM capacity win (both ISSUE 6 acceptance criteria), the
    # pure-host SLO scheduler laws, and the --paged CLI rot guard
    "test_serving_paged.py": [
        "test_paged_matches_slot_and_greedy[2-8]",
        "test_cow_shared_prefix_identity_and_drain",
        "test_chunked_vs_whole_prefill_identity_and_stall_bound",
        "test_capacity_win_at_equal_hbm",
        "test_interleaved_prefill_no_stale_row_scribble",
        "test_slo_scheduler_class_ordering_and_fairness",
        "test_paged_serve_dry_run_smoke",
    ],
    # speculative decoding (ISSUE 7): the greedy token-identity anchor at
    # tp=2 with a disagreeing drafter, the all-accept page-boundary case,
    # the fused-vs-host sampler pin (the bugfix satellite), the config
    # refusals, and the --speculate CLI rot guard; the chi-square
    # distribution test runs in the default lane but not core (~16 s)
    "test_speculative.py": [
        "test_spec_matches_paged_and_greedy[2-2-8]",
        "test_spec_acceptance_boundary_at_page_boundary[7]",
        "test_host_sampler_matches_fused[paged]",
        "test_spec_refuses_invalid_configs",
        "test_spec_serve_dry_run_smoke",
    ],
    # paged-attention kernel (ISSUE 14): the block-level oracle (decode +
    # int8 chunk), the engine token-identity anchor at tp=2 (native +
    # int8 fused dequant), the CPU fallback warning + the CLI scope
    # refusal, the gather-copy pricing pin, and the pallas dry-run rot
    # guard; the full family/GQA/speculative/preempt matrix runs in the
    # default lane
    "test_paged_kernel.py": [
        "test_kernel_decode_matches_dense_oracle[8-2-4]",
        "test_kernel_chunk_matches_dense_oracle[True]",
        "test_pallas_matches_gather_greedy[2-8]",
        "test_pallas_matches_gather_int8_kv[2]",
        "test_pallas_falls_back_to_gather_on_cpu_with_warning",
        "test_serve_cli_refuses_paged_attn_without_paged",
        "test_paged_decode_hbm_bytes_drops_gather_copy",
        "test_paged_serve_dry_run_pallas_smoke",
    ],
    # quantized wires + caches (ISSUE 8): the shared-rule round-trip
    # oracles, the int8 DP-wire error pin (the bf16 canary's sibling),
    # one ring_q kernel bound, the int8-KV greedy-quality pin + the
    # equal-HBM capacity criterion, the CLI scope refusals, and the
    # int8 serve dry-run rot guard
    "test_quant.py": [
        "test_quantize_roundtrip_oracles",
        "test_bucketed_reduce_int8_wire_tolerance",
        "test_ring_q_kernels_match_oracles_within_bound[2]",
        "test_int8_kv_greedy_pin[1]",
        "test_int8_kv_capacity_win_at_equal_hbm",
        "test_ring_q_refusals",
        "test_quant_serve_dry_run_smoke",
    ],
    "test_sequence_parallel.py": ["test_model_sp_matches_vanilla[1-1-4]"],
    "test_overlap.py": ["test_ag_matmul_matches_gather_dot_oracle[1-2]",
                        "test_matmul_rs_matches_dot_scatter_oracle[2]",
                        "test_model_ring_overlap_matches_monolithic"
                        "[llama-2]",
                        "test_bucketed_reduce_matches_whole_tree_psum"
                        "[8-1-1-False]"],
    # the ZeRO ladder (ISSUE 9): the stage-1 layout pin, the stage-2
    # reduce-scatter value-parity acceptance pin, and the stage-3
    # gather-on-demand trajectory pin
    "test_zero.py": ["test_moments_are_dp_sharded",
                     "test_zero2_grads_match_whole_tree_reducer",
                     "test_zero3_loss_trajectory_matches_zero1"],
    "test_multi_step.py": ["test_cli_steps_per_dispatch_matches"],
    "test_grad_accum.py": ["test_accum_matches_concatenated_batch[1-1]"],
    "test_checkpoint.py": ["test_save_load_roundtrip"],
    "test_cli_help.py": ["test_help_renders[target0]"],
    "test_run_step.py": ["test_failure_records_real_rc_and_stderr_tail"],
    "test_session_shell.py": [
        "test_bench_line_failure_removes_artifact_and_records_rc"],
    "test_data_pipeline.py": ["test_collate_semantics",
                              "test_token_json_schema",
                              "test_reference_shipped_tokenizer_loads"],
    # graftcheck (ISSUE 11): every rule's positive + negative fixture pin
    # and the clean-repo gate — the contract every future PR inherits.
    # The trace contracts stay in the default lane (they pay compiles).
    "test_graftcheck.py": [
        "test_bad_fixture_triggers_exactly_its_rule[",
        "test_good_fixture_stays_clean[",
        "test_rule_count_meets_acceptance_floor",
        "test_repo_sweep_is_clean",
    ],
    # obs: cheap unit coverage of every component; the train-run smoke
    # stays in the fast lane (it costs a full compile)
    "test_profiler_trace.py": None,
    "test_obs.py": ["test_tracer_emits_valid_chrome_trace",
                    "test_goodput_buckets_sum_to_wall",
                    "test_sentinel_nan_halts_with_dump",
                    "test_watchdog_detects_stall_and_recovery",
                    "test_parse_collectives_counts_and_bytes"],
    # obs v2 (ISSUE 10): the contiguous-timeline acceptance pin (one tiny
    # compile), the flight ring bound + PoolExhausted dump pin, the
    # regression-gate trio, the schema-drift guard, the rank-skew unit,
    # and the traced-serve CLI rot guard
    # obs v3 (ISSUE 12): the exporter endpoint + busy-port refusal, the
    # rotation chain + torn-line resync (the collector's correctness
    # core), fleet rollup math vs hand computation, the cross-process
    # waterfall acceptance pin, the anomaly->profiler cross-link, and the
    # telemetry serve CLI rot guard; the train smoke (slow lane) and the
    # overhead pin (timing-sensitive) stay out of core
    "test_telemetry.py": [
        "test_exporter_endpoint_json_and_prometheus",
        "test_exporter_busy_port_refuses_loudly",
        "test_metrics_rotation_chains_through_schema_valid_events",
        "test_tailer_holds_torn_line_and_resyncs",
        "test_fleet_rollup_matches_hand_computed_attainment",
        "test_crossproc_waterfall_merges_with_deliberate_clock_offset",
        "test_anomaly_dump_cross_links_profiler_capture",
        "test_serve_dry_run_with_telemetry_and_profiler",
        "test_bench_telemetry_flags_gated_on_serving",
    ],
    # obs v4 (ISSUE 15): the committed-fixture round-trip pin (parse +
    # hand-math reconcile), the taxonomy, the silent-zero HBM pins, the
    # schema-v4/collector/obs_top coverage, the gate's measured
    # direction, and the CLI refusals — all pure host, no compiles; the
    # real-capture end-to-end + duty-cycle-law tests (tiny compiles /
    # a dry-run serve) stay in the default lane
    "test_measured_attribution.py": [
        "test_fixture_capture_parses_to_hand_checked_phases",
        "test_fixture_reconcile_drift_hand_math",
        "test_classify_op_taxonomy",
        "test_device_memory_unavailable_is_none_not_zero",
        "test_publish_hbm_exports_unavailable_loudly",
        "test_schema_v4_profile_attribution_and_hbm_watermark",
        "test_fleet_rollup_folds_hbm_and_keeps_unavailable_distinct",
        "test_obs_top_once_renders_hbm_column",
        "test_gate_measured_ms_directional",
        "test_serve_cli_profile_refusals",
        "test_bench_cli_profile_refusals",
        "test_train_cli_profile_refusals",
    ],
    "test_obs_v2.py": [
        "test_paged_request_timelines_contiguous_and_sum_to_wall",
        "test_flight_ring_bound_holds_under_sustained_load",
        "test_pool_exhausted_preemption_dumps_flight",
        "test_gate_passes_on_committed_trajectory_vs_itself",
        "test_gate_fails_on_degraded_record",
        "test_gate_skips_on_backend_unavailable",
        "test_metrics_events_carry_schema_version_and_validate",
        "test_schema_validator_fails_loudly_on_drift",
        "test_rank_skew_ranks_stragglers",
        "test_serve_dry_run_with_tracing_and_flight",
    ],
    # obs v5 (ISSUE 16): the control plane — the committed-reconcile
    # pinned decision, the advise/act ladder laws (advise never mutates,
    # act only at safe points), the loadgen-replay adaptation + ledger
    # reconstruction end-to-end, the zero-cost off pin, the schema-v5
    # ledger contracts, and the --controller window gate (whole file:
    # one tiny dry serve + one tiny replay serve, ~8 s)
    "test_control.py": None,
    # obs v6 (ISSUE 17): run forensics — the fixture RunCard pins, the
    # shared outage classifier + the real-r02 never-a-baseline pin, THE
    # ranked-suspect acceptance pin (pages_per_block -> copy), the
    # committed-trajectory changepoint pin, the schema-v6 contracts, and
    # the --explain gate pair — all pure host, no compiles; the obs_diff
    # CLI matrix + the serve stamp e2e stay in the default lane
    # reshard (ISSUE 20): the stamp round-trip, the file->file layout
    # matrix (bit-identity + the peak-host-one-leaf bound), the planner's
    # op/bytes pins, the loud inexpressible refusal, and the elastic
    # file->device ZeRO-3 stream — all tiny-model; the subprocess elastic
    # resume arm (slow) and the fleet width restart stay out of core
    "test_reshard.py": [
        "test_save_stamps_layout_and_resolves_exactly",
        "test_reshard_checkpoint_bit_identical[",
        "test_plan_op_pins_and_minimal_bytes",
        "test_inexpressible_layout_refuses_loudly",
        "test_stream_load_elastic_zero3_bit_identical_and_bounded",
        "test_gate_treats_reshard_record_as_latency",
    ],
    "test_forensics.py": [
        "test_run_card_pins_fixture_run_a",
        "test_outage_classifier_is_shared_with_gate",
        "test_bench_r02_outage_never_baseline",
        "test_pinned_ranked_suspect_pages_per_block_to_copy",
        "test_changepoint_flags_pinned_trajectory_step",
        "test_schema_v6_forensics_contracts",
        "test_gate_explain_attaches_forensics_on_failure",
        "test_gate_explain_silent_on_pass",
    ],
}


def _core_match(name: str, pattern: str) -> bool:
    """Exact test id, or a prefix that ends at a parametrize bracket / a
    partial param id (pattern ending in '[', '-' or ':'). A bare function
    name must NOT prefix-match longer siblings (test_x must not pull in
    test_x_multiblock) — that would silently grow the audited budget."""
    if name == pattern:
        return True
    if pattern.endswith(("[", "-", ":")):
        return name.startswith(pattern)
    return name.startswith(pattern + "[")


def pytest_collection_modifyitems(config, items):
    # Every CORE_LANE file must exist ON DISK, unconditionally (ADVICE r5):
    # the dead-pattern audit below only runs on full-suite collections, so
    # a renamed/deleted file would otherwise drop its whole axis out of the
    # core lane silently — the exact regression the lane guards against.
    here = os.path.dirname(os.path.abspath(__file__))
    missing = [f for f in CORE_LANE
               if not os.path.exists(os.path.join(here, f))]
    assert not missing, (
        f"CORE_LANE lists test files that no longer exist on disk: "
        f"{missing} — update CORE_LANE in tests/conftest.py to match the "
        f"rename/deletion")

    core = pytest.mark.core
    matched = {}  # (file, pattern) -> hit count
    collected_files = set()
    for item in items:
        fname = os.path.basename(str(item.fspath))
        collected_files.add(fname)
        sel = CORE_LANE.get(fname, False)
        if sel is None:
            item.add_marker(core)
        elif sel:
            name = item.nodeid.split("::", 1)[1] if "::" in item.nodeid else ""
            for p in sel:
                if _core_match(name, p):
                    item.add_marker(core)
                    matched[(fname, p)] = matched.get((fname, p), 0) + 1
                    break
    # The curated lane must not silently shrink: when the whole suite is
    # collected, every pattern must still select at least one test (a
    # rename/param change would otherwise drop an axis from the inner loop
    # while -m core stays green). Partial collections (single-file runs)
    # skip the check.
    if collected_files.issuperset(CORE_LANE):
        dead = [(f, p) for f, sel in CORE_LANE.items() if sel
                for p in sel if (f, p) not in matched]
        assert not dead, f"CORE_LANE patterns match no test: {dead}"
