"""KV-cache decoding equals full-recompute decoding, token for token.

The reference decodes with a full growing-sequence forward per token and no
cache (`/root/reference/test.py:141-161`). Our oracle here is the
fixed-buffer full-recompute decoder (evaluate.make_greedy_decoder — the
reference-parity path); the KV-cache prefill+step decoder must generate the
identical token sequence on the same params.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_from_scratch_tpu.config import MeshConfig, ModelConfig
from distributed_pytorch_from_scratch_tpu.evaluate import make_greedy_decoder
from distributed_pytorch_from_scratch_tpu.models.decode import (
    GreedyDecoder, make_generate)
from distributed_pytorch_from_scratch_tpu.models.transformer import Transformer
from distributed_pytorch_from_scratch_tpu.runtime.mesh import make_mesh

CFG = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=8, num_layers=2,
                  vocab_size=96, maxlen=64)
BUF = 32
EOS = 1


def nocache_decode(model, mesh, params, prompt, max_new):
    step = make_greedy_decoder(model, mesh, BUF)
    buf = np.full((1, BUF), EOS, np.int32)
    buf[0, : len(prompt)] = prompt
    cur, out = len(prompt), []
    while cur < BUF and len(out) < max_new:
        nxt = int(step(params, jnp.asarray(buf), cur))
        if nxt == EOS:
            break
        out.append(nxt)
        buf[0, cur] = nxt
        cur += 1
    return out


@pytest.mark.parametrize("tp", [1, 4, 8])
@pytest.mark.parametrize("seed,prompt", [
    (0, [0, 5, 17, 33, 60]),
    (3, [0, 95]),                      # boundary vocab id
    (7, [0, 2, 4, 6, 8, 10, 12, 14]),  # longer prompt
])
def test_kv_matches_nocache(tp, seed, prompt):
    mesh = make_mesh(MeshConfig(dp=1, tp=tp))
    model = Transformer(CFG, tp_size=tp)
    params = jax.device_put(model.init(jax.random.key(seed)),
                            model.shardings(mesh))
    ref = nocache_decode(model, mesh, params, prompt, max_new=20)
    got = GreedyDecoder(model, mesh, BUF).decode(
        params, prompt, EOS, max_total_len=len(prompt) + 20)
    assert got == ref, f"tp={tp} seed={seed}: {got} != {ref}"


def test_kv_respects_buffer_and_limits():
    mesh = make_mesh(MeshConfig(dp=1, tp=2))
    model = Transformer(CFG, tp_size=2)
    params = jax.device_put(model.init(jax.random.key(1)),
                            model.shardings(mesh))
    dec = GreedyDecoder(model, mesh, BUF)
    prompt = [0, 5, 9]
    got = dec.decode(params, prompt, EOS, max_total_len=len(prompt) + 4)
    assert len(got) <= 4
    # never exceeds the buffer even with a huge limit
    got = dec.decode(params, prompt, EOS, max_total_len=10_000)
    assert len(prompt) + len(got) <= BUF


def test_kv_cp_model_accepted_ring_contiguous_only():
    # cp decode is supported for ring+contiguous (TestContextParallelDecode);
    # other cp configs still reject with a clear error
    mesh = make_mesh(MeshConfig(dp=1, cp=2, tp=2))
    GreedyDecoder(Transformer(CFG, tp_size=2, cp_size=2), mesh, BUF)  # ok
    with pytest.raises(ValueError, match="ring"):
        GreedyDecoder(Transformer(CFG, tp_size=2, cp_size=2,
                                  cp_impl="ulysses"), mesh, BUF)


@pytest.mark.parametrize("tp", [1, 4])
def test_batched_mixed_length_prompts(tp):
    """decode_batch over prompts of DIFFERENT lengths (the evaluate.py
    production path) must reproduce each prompt's single-row decode exactly —
    the teacher-forced catch-up must not perturb any row."""
    mesh = make_mesh(MeshConfig(dp=1, tp=tp))
    model = Transformer(CFG, tp_size=tp)
    params = jax.device_put(model.init(jax.random.key(11)),
                            model.shardings(mesh))
    dec = GreedyDecoder(model, mesh, BUF)
    prompts = [
        [0, 5, 17, 33, 60],
        [0, 95],
        [0, 2, 4, 6, 8, 10, 12, 14],
        [0, 7],
    ]
    refs = [dec.decode(params, p, EOS, max_total_len=24) for p in prompts]
    got = dec.decode_batch(params, prompts, EOS, max_total_len=24)
    assert got == refs


def test_decode_buffer_longer_than_maxlen():
    """ADVICE r1: buf_len > cfg.maxlen used to clip RoPE positions to the
    last table row; tables are now sized to the buffer."""
    mesh = make_mesh(MeshConfig(dp=1, tp=2))
    model = Transformer(CFG, tp_size=2)
    params = jax.device_put(model.init(jax.random.key(2)),
                            model.shardings(mesh))
    big = CFG.maxlen + 16
    dec = GreedyDecoder(model, mesh, big)
    out = dec.decode(params, [0, 5, 9], EOS, max_total_len=big)
    assert len(out) + 3 <= big


def test_batched_generate_per_row_lengths():
    """Batch of 2 prompts through one generate call: each row's reported
    length must match its own single-prompt decode (early-EOS rows must not
    absorb the longer row's padding)."""
    tp = 2
    mesh = make_mesh(MeshConfig(dp=1, tp=tp))
    model = Transformer(CFG, tp_size=tp)
    params = jax.device_put(model.init(jax.random.key(5)),
                            model.shardings(mesh))
    dec = GreedyDecoder(model, mesh, BUF)
    p = [0, 5, 17, 33, 60]  # same length so one padded buffer fits both rows
    q = [0, 11, 2, 44, 9]
    ref_p = dec.decode(params, p, EOS, max_total_len=len(p) + 10)
    ref_q = dec.decode(params, q, EOS, max_total_len=len(q) + 10)

    gen = make_generate(model, mesh, BUF)
    buf = np.full((2, BUF), EOS, np.int32)
    buf[0, : len(p)] = p
    buf[1, : len(q)] = q
    out, flen = gen(params, jnp.asarray(buf),
                    jnp.asarray(len(p), jnp.int32),
                    jnp.asarray(EOS, jnp.int32),
                    jnp.asarray(len(p) + 10, jnp.int32),
                    jax.random.key(0))
    out = np.asarray(out)
    flen = np.asarray(flen)
    assert out[0, len(p): flen[0]].tolist() == ref_p
    assert out[1, len(q): flen[1]].tolist() == ref_q


# ---- sampled decoding (temperature / top-k; the reference is greedy-only,
# test.py:149) ----


def test_sampled_decode_deterministic_per_seed_and_in_vocab():
    mesh = make_mesh(MeshConfig(dp=1, tp=2))
    model = Transformer(CFG, tp_size=2)
    params = jax.device_put(model.init(jax.random.key(0)),
                            model.shardings(mesh))
    dec = GreedyDecoder(model, mesh, BUF, temperature=1.0, top_k=8)
    prompt = [0, 5, 17]
    a = dec.decode_batch(params, [prompt], eos_id=EOS, max_total_len=BUF,
                         seed=11)[0]
    b = dec.decode_batch(params, [prompt], eos_id=EOS, max_total_len=BUF,
                         seed=11)[0]
    c = dec.decode_batch(params, [prompt], eos_id=EOS, max_total_len=BUF,
                         seed=12)[0]
    assert a == b, "same seed must reproduce"
    assert all(0 <= t < CFG.vocab_size for t in a)
    assert a != c or len(a) <= 2, "different seeds should usually diverge"


def test_low_temperature_matches_greedy():
    mesh = make_mesh(MeshConfig(dp=1, tp=2))
    model = Transformer(CFG, tp_size=2)
    params = jax.device_put(model.init(jax.random.key(0)),
                            model.shardings(mesh))
    greedy = GreedyDecoder(model, mesh, BUF)
    cold = GreedyDecoder(model, mesh, BUF, temperature=1e-4)
    prompt = [0, 5, 17, 33]
    g = greedy.decode_batch(params, [prompt], eos_id=EOS, max_total_len=16)[0]
    s = cold.decode_batch(params, [prompt], eos_id=EOS, max_total_len=16)[0]
    assert g == s, (g, s)


def test_sampling_validation():
    mesh = make_mesh(MeshConfig(dp=1, tp=1))
    model = Transformer(CFG)
    with pytest.raises(ValueError, match="temperature"):
        make_generate(model, mesh, BUF, temperature=-1.0)
    with pytest.raises(ValueError, match="top_k"):
        make_generate(model, mesh, BUF, top_k=CFG.vocab_size + 1)


def test_top_p_tiny_nucleus_matches_greedy():
    """top_p -> 0+ keeps only the argmax token in the nucleus, so sampling
    at any temperature reduces to the greedy decode; top_p=1.0 is a no-op
    filter (same draw as the unfiltered sampler at the same seed)."""
    mesh = make_mesh(MeshConfig(dp=1, tp=2))
    model = Transformer(CFG, tp_size=2)
    params = jax.device_put(model.init(jax.random.key(0)),
                            model.shardings(mesh))
    prompt = [0, 5, 17, 33]

    greedy = GreedyDecoder(model, mesh, BUF)
    tiny = GreedyDecoder(model, mesh, BUF, temperature=1.0, top_p=1e-6)
    g = greedy.decode_batch(params, [prompt], eos_id=EOS, max_total_len=16)[0]
    t = tiny.decode_batch(params, [prompt], eos_id=EOS, max_total_len=16)[0]
    assert g == t, (g, t)

    full = GreedyDecoder(model, mesh, BUF, temperature=1.0)
    noop = GreedyDecoder(model, mesh, BUF, temperature=1.0, top_p=1.0)
    a = full.decode_batch(params, [prompt], eos_id=EOS, max_total_len=16,
                          seed=3)[0]
    b = noop.decode_batch(params, [prompt], eos_id=EOS, max_total_len=16,
                          seed=3)[0]
    assert a == b, (a, b)


def test_top_p_deterministic_and_in_vocab():
    mesh = make_mesh(MeshConfig(dp=1, tp=2))
    model = Transformer(CFG, tp_size=2)
    params = jax.device_put(model.init(jax.random.key(0)),
                            model.shardings(mesh))
    dec = GreedyDecoder(model, mesh, BUF, temperature=1.0, top_p=0.9,
                        top_k=16)  # composed filters
    prompt = [0, 5, 17]
    a = dec.decode_batch(params, [prompt], eos_id=EOS, max_total_len=BUF,
                         seed=5)[0]
    b = dec.decode_batch(params, [prompt], eos_id=EOS, max_total_len=BUF,
                         seed=5)[0]
    assert a == b
    assert all(0 <= t < CFG.vocab_size for t in a)


def test_top_p_validation():
    mesh = make_mesh(MeshConfig(dp=1, tp=1))
    model = Transformer(CFG)
    with pytest.raises(ValueError, match="top_p"):
        make_generate(model, mesh, BUF, top_p=1.5)


def test_per_row_total_length_limits():
    """max_total_len as a (b,) vector: each row stops at ITS limit — a
    short prompt in a mixed batch must not generate until the longest
    row's limit (the generate CLI's per-prompt --max_new_tokens)."""
    import numpy as np

    mesh = make_mesh(MeshConfig(dp=1, tp=2))
    model = Transformer(CFG, tp_size=2)
    params = jax.device_put(model.init(jax.random.key(0)),
                            model.shardings(mesh))
    dec = GreedyDecoder(model, mesh, BUF)
    short, long = [0, 5], [0, 5, 17, 33, 2, 9, 11, 21]
    # per-row budget: 4 new tokens each
    limits = np.asarray([len(short) + 4, len(long) + 4], np.int32)
    gens = dec.decode_batch(params, [short, long], eos_id=-1,
                            max_total_len=limits)
    assert len(gens[0]) == 4, gens[0]
    assert len(gens[1]) == 4, gens[1]
    # and each row's tokens equal its solo decode (limits don't couple rows)
    solo = dec.decode_batch(params, [short], eos_id=-1,
                            max_total_len=len(short) + 4)[0]
    assert gens[0] == solo, (gens[0], solo)


class TestContextParallelDecode:
    """Long-context decode: the prefill shards the prompt over 'cp' and runs
    ring attention (the training long-context path); the decode loop runs on
    the gathered caches. Token-for-token equal to the cp=1 decoder."""

    @pytest.mark.parametrize("cp,tp", [(2, 1), (2, 2), (4, 2)])
    def test_cp_decode_matches_cp1(self, cp, tp):
        mesh = make_mesh(MeshConfig(cp=cp, tp=tp))
        base = Transformer(CFG, tp_size=tp)
        cp_model = Transformer(CFG, tp_size=tp, cp_size=cp)
        params = jax.device_put(base.init(jax.random.key(11)),
                                base.shardings(mesh))
        prompts = [[0, 5, 17, 33, 60], [0, 7, 9]]
        want = GreedyDecoder(base, mesh, BUF).decode_batch(
            params, prompts, EOS, max_total_len=24)
        got = GreedyDecoder(cp_model, mesh, BUF).decode_batch(
            params, prompts, EOS, max_total_len=24)
        assert got == want, (cp, tp, got, want)

    def test_cp_decode_gqa(self):
        mesh = make_mesh(MeshConfig(cp=2, tp=2))
        cfg = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=8,
                          num_kv_heads=2, num_layers=2, vocab_size=96,
                          maxlen=64)
        base = Transformer(cfg, tp_size=2)
        cp_model = Transformer(cfg, tp_size=2, cp_size=2)
        params = jax.device_put(base.init(jax.random.key(5)),
                                base.shardings(mesh))
        prompt = [0, 3, 5, 7, 11, 13]
        want = GreedyDecoder(base, mesh, BUF).decode(
            params, prompt, EOS, max_total_len=20)
        got = GreedyDecoder(cp_model, mesh, BUF).decode(
            params, prompt, EOS, max_total_len=20)
        assert got == want

    def test_cp_decode_gpt2(self):
        from distributed_pytorch_from_scratch_tpu.models.gpt2 import (
            GPT2Transformer)
        cfg = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=4,
                          num_layers=2, vocab_size=96, maxlen=64)
        mesh = make_mesh(MeshConfig(cp=2, tp=2))
        base = GPT2Transformer(cfg, tp_size=2)
        cp_model = GPT2Transformer(cfg, tp_size=2, cp_size=2)
        params = jax.device_put(base.init(jax.random.key(9)),
                                base.shardings(mesh))
        prompt = [0, 4, 8, 15, 16, 23, 42]
        want = GreedyDecoder(base, mesh, BUF).decode(
            params, prompt, EOS, max_total_len=20)
        got = GreedyDecoder(cp_model, mesh, BUF).decode(
            params, prompt, EOS, max_total_len=20)
        assert got == want

    def test_cp_decode_rejects_bad_configs(self):
        cp_model = Transformer(CFG, tp_size=1, cp_size=2, cp_impl="ulysses")
        mesh = make_mesh(MeshConfig(cp=2))
        with pytest.raises(ValueError, match="ring"):
            GreedyDecoder(cp_model, mesh, BUF)
        with pytest.raises(ValueError, match="divisible"):
            GreedyDecoder(Transformer(CFG, tp_size=1, cp_size=2), mesh, 31)
