"""Context-parallel attention: ring + Ulysses vs the dense causal oracle.

The reference has no long-context machinery (SURVEY §5.7) so the oracle is
our own dense causal attention / vanilla transformer. Checks at two levels:

* op level: ring/ulysses attention over a sequence-sharded ('cp') mesh axis
  reproduces dense causal attention — forward and gradients.
* model level: a Transformer with cp_size>1 matches the vanilla oracle on
  loss and gradients, on a full 3-D dp x cp x tp mesh.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributed_pytorch_from_scratch_tpu.config import (
    IGNORE_INDEX, MeshConfig, ModelConfig)
from distributed_pytorch_from_scratch_tpu.models.transformer import Transformer
from distributed_pytorch_from_scratch_tpu.models.vanilla import VanillaTransformer
from distributed_pytorch_from_scratch_tpu.ops.attention import causal_attention_xla
from distributed_pytorch_from_scratch_tpu.ops.ring_attention import (
    ring_attention, ulysses_attention)
from distributed_pytorch_from_scratch_tpu.runtime.mesh import make_mesh


def make_qkv(key, b=2, h=4, t=32, d=8):
    kq, kk, kv = jax.random.split(key, 3)
    shape = (b, h, t, d)
    q = jax.random.normal(kq, shape, jnp.float32)
    k = jax.random.normal(kk, shape, jnp.float32)
    v = jax.random.normal(kv, shape, jnp.float32)
    pos = jnp.tile(jnp.arange(t, dtype=jnp.int32)[None, :], (b, 1))
    return q, k, v, pos


def sharded_ring(mesh):
    """Global (b,h,t,d) -> (b,h,t,d): heads over 'tp', seq over 'cp'."""
    fn = functools.partial(ring_attention, axis="cp")
    return jax.jit(jax.shard_map(
        fn, mesh=mesh,
        in_specs=(P(None, "tp", "cp", None),) * 3 + (P(None, "cp"),),
        out_specs=P(None, "tp", "cp", None)))


def sharded_ulysses(mesh):
    fn = functools.partial(ulysses_attention, axis="cp", impl="xla")
    return jax.jit(jax.shard_map(
        fn, mesh=mesh,
        in_specs=(P(None, "tp", "cp", None),) * 3,
        out_specs=P(None, "tp", "cp", None)))


@pytest.mark.parametrize("cp,tp", [(2, 1), (4, 2), (8, 1), (2, 4)])
def test_ring_forward_matches_dense(cp, tp):
    mesh = make_mesh(MeshConfig(dp=1, cp=cp, tp=tp))
    q, k, v, pos = make_qkv(jax.random.key(0))
    out = sharded_ring(mesh)(q, k, v, pos)
    ref = causal_attention_xla(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("cp,tp", [(4, 2), (2, 1)])
def test_ulysses_forward_matches_dense(cp, tp):
    mesh = make_mesh(MeshConfig(dp=1, cp=cp, tp=tp))
    q, k, v, _ = make_qkv(jax.random.key(1), h=8)
    out = sharded_ulysses(mesh)(q, k, v)
    ref = causal_attention_xla(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_grads_match_dense(impl):
    """The scan/ppermute (or all_to_all) transpose must reproduce the dense
    kernel's gradients — the conjugate-communication property at the heart of
    context parallelism."""
    mesh = make_mesh(MeshConfig(dp=1, cp=4, tp=2))
    q, k, v, pos = make_qkv(jax.random.key(2), h=8)
    w = jax.random.normal(jax.random.key(3), q.shape, jnp.float32)

    sharded = sharded_ring(mesh) if impl == "ring" else sharded_ulysses(mesh)

    def loss_sh(q, k, v):
        args = (q, k, v, pos) if impl == "ring" else (q, k, v)
        return jnp.sum(sharded(*args) * w)

    def loss_ref(q, k, v):
        return jnp.sum(causal_attention_xla(q, k, v) * w)

    g_sh = jax.grad(loss_sh, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_sh, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_ring_nonstandard_positions():
    """Positions carried around the ring, not inferred from rank order: a
    shifted position layout must still mask causally by global position."""
    mesh = make_mesh(MeshConfig(dp=1, cp=4, tp=1))
    q, k, v, pos = make_qkv(jax.random.key(4), t=16)
    pos = pos + 7  # uniform shift: same relative order, bigger offsets
    out = sharded_ring(mesh)(q, k, v, pos)
    ref = causal_attention_xla(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


# ---- model level ----

CFG = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=8, num_layers=2,
                  vocab_size=96, maxlen=64)


def make_batch(key, batch=4, t=32, vocab=96):
    k1, k2 = jax.random.split(key)
    input_ids = jax.random.randint(k1, (batch, t), 0, vocab)
    target_ids = jax.random.randint(k2, (batch, t), 0, vocab)
    mask = jax.random.bernoulli(jax.random.fold_in(key, 9), 0.2, (batch, t))
    target_ids = jnp.where(mask, IGNORE_INDEX, target_ids)
    position_ids = jnp.tile(jnp.arange(t)[None, :], (batch, 1))
    return input_ids, target_ids, position_ids


@pytest.mark.parametrize("dp,cp,tp,impl", [
    (1, 4, 2, "ring"),
    (2, 2, 2, "ring"),
    (1, 2, 4, "ring"),
    (1, 4, 2, "ulysses"),
    (2, 2, 2, "ulysses"),
])
def test_model_loss_and_grads_vs_vanilla(dp, cp, tp, impl):
    mesh = make_mesh(MeshConfig(dp=dp, cp=cp, tp=tp))
    model = Transformer(CFG, tp_size=tp, cp_size=cp, cp_impl=impl)
    oracle = VanillaTransformer(CFG)
    params = model.init(jax.random.key(0))
    ids, tgt, pos = make_batch(jax.random.key(2))

    loss_fn = model.make_loss(mesh)
    l_sh, g_sh = jax.value_and_grad(loss_fn)(params, ids, tgt, pos)
    l_ref, g_ref = jax.value_and_grad(oracle.loss)(params, ids, tgt, pos)

    np.testing.assert_allclose(l_sh, l_ref, rtol=1e-5)
    flat_sh, _ = jax.tree.flatten(g_sh)
    flat_ref, _ = jax.tree.flatten(g_ref)
    for a, b in zip(flat_sh, flat_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_model_forward_logits_cp():
    mesh = make_mesh(MeshConfig(dp=1, cp=4, tp=2))
    model = Transformer(CFG, tp_size=2, cp_size=4)
    oracle = VanillaTransformer(CFG)
    params = model.init(jax.random.key(0))
    ids, _, pos = make_batch(jax.random.key(1))
    logits_sh = model.make_forward(mesh)(params, ids, pos)
    logits_ref = oracle.forward(params, ids, pos)
    np.testing.assert_allclose(np.asarray(logits_sh), np.asarray(logits_ref),
                               rtol=1e-4, atol=1e-4)


def test_ulysses_rejects_bad_head_split():
    with pytest.raises(ValueError, match="ulysses"):
        Transformer(CFG, tp_size=4, cp_size=4, cp_impl="ulysses")


# ---- zig-zag layout ----


def test_zigzag_perm_properties():
    from distributed_pytorch_from_scratch_tpu.ops.ring_attention import (
        zigzag_perm)
    perm = zigzag_perm(16, 4)
    # a permutation of range(t)
    assert sorted(perm.tolist()) == list(range(16))
    # shard r (chunk of 4) holds sub-chunks r and 2n-1-r
    assert perm.tolist()[:4] == [0, 1, 14, 15]
    assert perm.tolist()[4:8] == [2, 3, 12, 13]
    with pytest.raises(ValueError, match="divisible"):
        zigzag_perm(10, 4)


def test_zigzag_rejects_ulysses():
    with pytest.raises(ValueError, match="zigzag"):
        Transformer(CFG, tp_size=2, cp_size=2, cp_impl="ulysses",
                    cp_layout="zigzag")


@pytest.mark.parametrize("dp,cp,tp", [(1, 4, 2), (2, 2, 2)])
def test_zigzag_model_matches_vanilla(dp, cp, tp):
    """zig-zag layout is invisible to the caller: loss AND grads match the
    unsharded oracle on naturally-ordered inputs, and the forward's logits
    come back in natural token order."""
    mesh = make_mesh(MeshConfig(dp=dp, cp=cp, tp=tp))
    model = Transformer(CFG, tp_size=tp, cp_size=cp, cp_layout="zigzag")
    oracle = VanillaTransformer(CFG)
    params = model.init(jax.random.key(0))
    ids, tgt, pos = make_batch(jax.random.key(3))

    l_sh, g_sh = jax.value_and_grad(model.make_loss(mesh))(params, ids, tgt, pos)
    l_ref, g_ref = jax.value_and_grad(oracle.loss)(params, ids, tgt, pos)
    np.testing.assert_allclose(l_sh, l_ref, rtol=1e-5)
    for a, b in zip(jax.tree.leaves(g_sh), jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)

    logits_zz = model.make_forward(mesh)(params, ids, pos)
    logits_ref = oracle.forward(params, ids, pos)
    np.testing.assert_allclose(np.asarray(logits_zz), np.asarray(logits_ref),
                               rtol=1e-4, atol=1e-4)


def test_doc_loss_zigzag_matches_single_device():
    """Per-document eval loss through the zig-zag cp layout: token
    permutation must not change any document's mean CE."""
    cfg = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=4, num_layers=2,
                      vocab_size=96, maxlen=64)
    ids, tgt, pos = make_batch(jax.random.key(21), batch=4, t=32)

    ref = Transformer(cfg)
    means_ref, real_ref = ref.make_doc_loss(make_mesh(MeshConfig()))(
        ref.init(jax.random.key(0)), ids, tgt, pos)

    model = Transformer(cfg, cp_size=2, cp_layout="zigzag")
    mesh = make_mesh(MeshConfig(cp=2))
    params = jax.device_put(ref.init(jax.random.key(0)),
                            model.shardings(mesh))
    means, real = model.make_doc_loss(mesh)(params, ids, tgt, pos)
    np.testing.assert_array_equal(np.asarray(real), np.asarray(real_ref))
    np.testing.assert_allclose(np.asarray(means), np.asarray(means_ref),
                               rtol=1e-5, atol=1e-6)


# ---- ring + flash kernel composition (VERDICT r3 #2) ----
#
# In product code, `_block_attn` falls back to dense XLA whenever the
# interpreted Pallas kernel would run inside a vma-checked shard_map (the
# discharged kernel jaxpr fails the varying-manual-axes check), so the
# composed ring+flash path — the Pallas positional block kernel driven by
# the online-softmax combine with real ppermutes — never executed in any
# CPU test. `check_vma=False` removes the tags entirely: the gate at
# ops/ring_attention.py::_block_attn sees no vma, takes the kernel path,
# and the FULL composition runs interpreted inside a cp>1 mesh. These
# tests pin its forward and backward against the dense oracle.


def flash_ring(mesh, layout_pos=None):
    fn = functools.partial(ring_attention, axis="cp", impl="flash")
    return jax.jit(jax.shard_map(
        fn, mesh=mesh,
        in_specs=(P(None, "tp", "cp", None),) * 3 + (P(None, "cp"),),
        out_specs=P(None, "tp", "cp", None), check_vma=False))


@pytest.mark.parametrize("cp,tp", [(2, 1), (2, 2)])
def test_flash_blocks_execute_inside_cp_mesh(cp, tp):
    """impl='flash' blocks run INSIDE a cp>1 shard_map (interpreted kernel,
    real ppermutes, online-softmax combine) and match the dense oracle."""
    mesh = make_mesh(MeshConfig(dp=1, cp=cp, tp=tp))
    q, k, v, pos = make_qkv(jax.random.key(11), h=2 * tp, t=128, d=64)
    out = flash_ring(mesh)(q, k, v, pos)
    ref = causal_attention_xla(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)


@pytest.mark.slow
def test_flash_ring_cp4_gqa_matches_dense():
    """cp=4 ring with GROUPED k/v (hkv < hq): the BlockSpec head routing
    composes with the ring's half-chunk skipping."""
    mesh = make_mesh(MeshConfig(dp=1, cp=4, tp=1))
    b, hq, hkv, t, d = 1, 4, 2, 256, 64
    kq, kk, kv = jax.random.split(jax.random.key(12), 3)
    q = jax.random.normal(kq, (b, hq, t, d), jnp.float32)
    k = jax.random.normal(kk, (b, hkv, t, d), jnp.float32)
    v = jax.random.normal(kv, (b, hkv, t, d), jnp.float32)
    pos = jnp.tile(jnp.arange(t, dtype=jnp.int32)[None, :], (b, 1))
    out = flash_ring(mesh)(q, k, v, pos)
    ref = causal_attention_xla(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)


@pytest.mark.slow
def test_flash_ring_grads_match_dense():
    """Backward through the composition: the kernel's custom VJP consumes
    the combine's (do, dlse) cotangents and the scan/ppermute transpose
    rebuilds the reverse ring — gradients must match the dense kernel's."""
    mesh = make_mesh(MeshConfig(dp=1, cp=2, tp=1))
    q, k, v, pos = make_qkv(jax.random.key(13), h=2, t=128, d=64)
    w = jax.random.normal(jax.random.key(14), q.shape, jnp.float32)

    ring = flash_ring(mesh)
    g_ring = jax.grad(lambda q, k, v: jnp.sum(ring(q, k, v, pos) * w),
                      argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(
        lambda q, k, v: jnp.sum(causal_attention_xla(q, k, v) * w),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-3)


# ---- long context at LONG context (VERDICT r3 #8) ----


@pytest.mark.slow
def test_long_context_8k_cross_impl_agreement():
    """t=8192 — 8x the reference's hard maxlen=1000 cap
    (`/root/reference/constants.py:17`, SURVEY §5.7: it has no long-context
    story at all). Four independent shardings of the same model must agree
    on the loss: ring cp2, ring cp2 zig-zag, ring cp2 x tp2, and Ulysses
    cp2 — the Ulysses path all-to-alls to the FULL 8k sequence and runs
    dense attention, so it doubles as the oracle for the ring's online
    softmax at this length."""
    t = 8192
    cfg = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=2, num_layers=2,
                      vocab_size=96, maxlen=t)
    ids = jax.random.randint(jax.random.key(40), (1, t), 0, 96)
    tgt = jax.random.randint(jax.random.key(41), (1, t), 0, 96)
    pos = jnp.tile(jnp.arange(t)[None, :], (1, 1))

    losses = {}
    for name, axes, kw in [
        ("ring_cp2", dict(cp=2), dict(cp_size=2)),
        ("ring_cp2_zz", dict(cp=2), dict(cp_size=2, cp_layout="zigzag")),
        ("ring_cp2tp2", dict(cp=2, tp=2), dict(cp_size=2, tp_size=2)),
        ("ulysses_cp2", dict(cp=2), dict(cp_size=2, cp_impl="ulysses")),
    ]:
        model = Transformer(cfg, **kw)
        mesh = make_mesh(MeshConfig(**axes))
        params = jax.device_put(model.init(jax.random.key(0)),
                                model.shardings(mesh))
        losses[name] = float(model.make_loss(mesh)(params, ids, tgt, pos))
        assert np.isfinite(losses[name]), (name, losses[name])
    ref = losses["ulysses_cp2"]
    for name, v in losses.items():
        np.testing.assert_allclose(v, ref, rtol=2e-5, err_msg=name)
