"""Long-context cp serving correctness — ISSUE 18.

The paged KV pool shards over the 'cp' mesh axis (each rank owns a
disjoint slab of physical pages plus one scratch page), chunked prefill
rings the query chunk around cp over each rank's LOCAL pages, and decode
attends cp-locally then combines per-rank (out, lse) partials with one
exact online-softmax merge. None of that may move a token: the anchor
contract here is GREEDY TOKEN IDENTITY between cp=2 and the cp=1 oracle
across page sizes, KV storage dtypes, and both attend impls (gather and
the Pallas kernel in interpreter mode) — sharding changes per-chip BYTES
(~1/cp at equal context, asserted via pages_per_rank), never tokens.

Plus the cp-specific invariants: COW prefix sharing and preempt-resume
work across cp shards (ownership is positional, so a resumed request
re-lands its pages on the same ranks), ring prefill is chunk-boundary
invariant (including a chunk width the engine must round UP to a cp
multiple), the slot engine / speculative drafter refuse cp>1 models
loudly naming the supported shape, and the capacity win the sharding
exists for: at EQUAL per-chip page bytes, cp=2 admits and completes a
request whose page demand the cp=1 pool refuses up front.
"""

import jax
import numpy as np
import pytest

from distributed_pytorch_from_scratch_tpu.config import MeshConfig, ModelConfig
from distributed_pytorch_from_scratch_tpu.models.decode import GreedyDecoder
from distributed_pytorch_from_scratch_tpu.models.transformer import Transformer
from distributed_pytorch_from_scratch_tpu.runtime.mesh import make_mesh
from distributed_pytorch_from_scratch_tpu.serving.engine import (
    ContinuousBatchingEngine, PagedEngine, Request)

CFG = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=8, num_layers=2,
                  vocab_size=96, maxlen=64)
BUF = 32
EOS = 1

PROMPTS = [
    [0, 5, 17, 33, 60],
    [0, 95],                        # boundary vocab id
    [0, 2, 4, 6, 8, 10, 12, 14],    # page-boundary prompt at ps=8
    [0, 3, 5, 7, 11, 13, 17],
]


def _setup(cp, tp=2, seed=7):
    """cp x tp mesh + model. Same seed => bit-identical init values at
    every cp (cp_size changes sharding and lowering, never weights), so
    a cp=1 build IS the oracle for a cp=2 build."""
    mesh = make_mesh(MeshConfig(dp=1, cp=cp, tp=tp))
    model = Transformer(CFG, tp_size=tp, cp_size=cp)
    params = jax.device_put(model.init(jax.random.key(seed)),
                            model.shardings(mesh))
    return mesh, model, params


def _assert_drained(eng):
    """No page leak across the cp slabs: every page back on its owner's
    free list, refcounts at zero, prefix index empty."""
    assert eng.pool.free_pages == eng.pool.num_pages, (
        eng.pool.free_pages, eng.pool.num_pages)
    assert (eng.pool.refcount == 0).all(), eng.pool.refcount
    assert not eng.pool._children and not eng.pool._page_keys


def _drive(eng, prompts, max_new=8):
    """Staggered admissions (two live + late arrivals reversed) so the
    cp decode/prefill programs run INTERLEAVED, not one clean phase."""
    reqs = [Request(rid=i, prompt=list(p), max_new=max_new)
            for i, p in enumerate(prompts)]
    eng.submit(reqs[0])
    eng.submit(reqs[1])
    for _ in range(3):
        eng.step()
    for r in reversed(reqs[2:]):
        eng.submit(r)
    eng.run_to_completion()
    return {r.rid: r.tokens for r in eng.completed}


_MATRIX_SETUPS = {}   # cp -> the one tp=1 (mesh, model, params) build
_ORACLE = {}          # (ps, kv_dtype) -> cp=1 greedy tokens (gather impl)


def _matrix_setup(cp):
    """The identity matrix runs at tp=1: cp is what's under test here,
    and cp x tp composition is covered by the other tests in this file
    (all tp=2). Params are read-only to the engines, so one build per cp
    serves every combo."""
    if cp not in _MATRIX_SETUPS:
        _MATRIX_SETUPS[cp] = _setup(cp, tp=1)
    return _MATRIX_SETUPS[cp]


def _oracle(ps, kv_dtype):
    """cp=1 oracle tokens, computed ONCE per (ps, kv_dtype) with the
    gather impl: gather==pallas token identity at cp=1 is already pinned
    by test_paged_kernel (native and int8 pools), so one oracle serves
    both impl arms — what's under test is the cp sharding, not the
    kernel."""
    key = (ps, kv_dtype)
    if key not in _ORACLE:
        mesh1, model1, params1 = _matrix_setup(1)
        eng = PagedEngine(model1, mesh1, params1, num_slots=2,
                          buf_len=BUF, eos_id=EOS, page_size=ps,
                          prefill_chunk=4, kv_dtype=kv_dtype,
                          paged_attn_impl="gather")
        _ORACLE[key] = _drive(eng, PROMPTS)
        _assert_drained(eng)
    return _ORACLE[key]


@pytest.mark.parametrize("ps", [8, 16])
@pytest.mark.parametrize("kv_dtype", [None, "int8"])
@pytest.mark.parametrize("impl", ["gather", "pallas"])
def test_cp2_token_identical_to_cp1_oracle(ps, kv_dtype, impl):
    """The tentpole contract: cp=2 greedy == the cp=1 oracle at the SAME
    page size and KV dtype (pallas runs the real kernel in interpreter
    mode — cp hands it pos_offset per shard and merges lse). The int8
    arms compare int8-to-int8: quantisation moves tokens vs native,
    sharding must not move them vs cp=1. The native-gather arm
    additionally anchors to the fused GreedyDecoder."""
    oracle = _oracle(ps, kv_dtype)

    mesh2, model2, params2 = _matrix_setup(2)
    eng = PagedEngine(model2, mesh2, params2, num_slots=2, buf_len=BUF,
                      eos_id=EOS, page_size=ps, prefill_chunk=4,
                      kv_dtype=kv_dtype, paged_attn_impl=impl,
                      paged_attn_interpret=impl == "pallas")
    assert eng.paged_attn_impl == impl   # interpret opt-in: no fallback
    assert eng.cp == 2 and eng.pool.cp == 2
    # the bytes claim behind the whole exercise: each rank's slab is
    # 1/cp of the real pages (plus its one scratch page)
    assert eng.pool.pages_per_rank == eng.pool.num_pages // 2
    got = _drive(eng, PROMPTS)

    assert len(got) == len(PROMPTS)
    for i in range(len(PROMPTS)):
        assert got[i] == oracle[i], (ps, kv_dtype, impl, i,
                                     got[i], oracle[i])
    if kv_dtype is None and impl == "gather":
        mesh1, model1, params1 = _matrix_setup(1)
        dec = GreedyDecoder(model1, mesh1, BUF)
        for i, p in enumerate(PROMPTS):
            ref = dec.decode(params1, p, EOS, max_total_len=len(p) + 8)
            assert got[i] == ref, (i, got[i], ref)
    _assert_drained(eng)


def test_cp_cow_shared_prefix_identity_and_drain():
    """COW prefix sharing across cp shards: ownership is positional
    (page-table column j -> rank j // mpp), so three requests sharing an
    18-token prefix (two full ps=8 pages + a partial tail) share pages
    that live on BOTH ranks' slabs, and the copy-on-write of the shared
    tail pairs source and destination on the SAME owner. Outputs must
    equal unshared solo decodes; the cache must actually hit; at least
    one COW copy must happen; everything drains."""
    mesh1, model1, params1 = _setup(1, seed=3)
    dec = GreedyDecoder(model1, mesh1, BUF)
    pre = [0, 7, 3, 9, 22, 41, 5, 13, 28, 31, 6, 44, 2, 19, 55, 8, 60, 12]
    prompts = [pre + [70], pre + [80], pre + [90, 33]]
    refs = [dec.decode(params1, p, EOS, max_total_len=len(p) + 8)
            for p in prompts]

    mesh, model, params = _setup(2, seed=3)
    eng = PagedEngine(model, mesh, params, num_slots=3, buf_len=BUF,
                      eos_id=EOS, page_size=8, prefill_chunk=32)
    for i, p in enumerate(prompts):
        eng.submit(Request(rid=i, prompt=p, max_new=8))
    eng.run_to_completion()
    got = {r.rid: r.tokens for r in eng.completed}
    for i, ref in enumerate(refs):
        assert got[i] == ref, (i, got[i], ref)
    st = eng.stats()
    assert st["prefix_hit_tokens"] >= 16, st   # both full shared pages
    assert st["cow_copies"] >= 1, st
    assert st["cp"] == 2 and st["pages_per_rank"] == st["num_pages"] // 2
    _assert_drained(eng)


def test_cp_preempt_resume_token_identity():
    """Decode-time pool exhaustion at cp=2: three growing requests
    through slabs too small for their combined growth must preempt a
    victim (its pages freed on their OWNING ranks), then resume it
    through the cp ring-prefill path — token-identical to uninterrupted
    solo decodes."""
    mesh1, model1, params1 = _setup(1, seed=3)
    dec = GreedyDecoder(model1, mesh1, BUF)
    prompts = [[0, 5, 9, 60, 2, 8, 33], [0, 11, 4, 7, 21, 35, 2],
               [0, 44, 17, 8, 52, 3, 71]]
    refs = [dec.decode(params1, p, EOS, max_total_len=len(p) + 12)
            for p in prompts]

    mesh, model, params = _setup(2, seed=3)
    eng = PagedEngine(model, mesh, params, num_slots=3, buf_len=BUF,
                      eos_id=EOS, page_size=8, num_pages=4,
                      prefill_chunk=8)
    for i, p in enumerate(prompts):
        eng.submit(Request(rid=i, prompt=p, max_new=12))
    eng.run_to_completion()
    got = {r.rid: r.tokens for r in eng.completed}
    for i, ref in enumerate(refs):
        assert got[i] == ref, (i, got[i], ref)
    assert eng.stats()["preemptions"] >= 1
    _assert_drained(eng)


def test_cp_ring_prefill_chunk_boundary_invariance():
    """The query ring must be chunk-boundary invariant: a 40-token prompt
    prefilled at chunk 4, at chunk 5 (NOT a cp multiple — the engine must
    round the compiled width up to 6 and mask the pad), and at chunk 64
    (whole prompt in one ring) all produce the cp=1 oracle's tokens, with
    a short live stream decoding throughout so ring hops interleave with
    cp-combined decode steps."""
    buf = 48
    rng = np.random.default_rng(5)
    long = [0] + [int(t) for t in rng.integers(3, CFG.vocab_size, size=39)]
    short = [0, 5, 9]

    mesh1, model1, params1 = _setup(1)
    dec = GreedyDecoder(model1, mesh1, buf)
    ref_long = dec.decode(params1, long, EOS, max_total_len=len(long) + 5)
    ref_short = dec.decode(params1, short, EOS,
                           max_total_len=len(short) + 6)

    mesh, model, params = _setup(2)
    for chunk in (4, 5, 64):
        eng = PagedEngine(model, mesh, params, num_slots=2, buf_len=buf,
                          eos_id=EOS, page_size=8, prefill_chunk=chunk)
        eng.submit(Request(rid=0, prompt=short, max_new=6))
        eng.step()
        eng.submit(Request(rid=1, prompt=long, max_new=5))
        eng.run_to_completion()
        got = {r.rid: r.tokens for r in eng.completed}
        assert got[0] == ref_short, (chunk, got[0], ref_short)
        assert got[1] == ref_long, (chunk, got[1], ref_long)
        _assert_drained(eng)


def test_cp2_equal_per_chip_hbm_admits_what_cp1_refuses():
    """The capacity win the sharding exists for: at EQUAL per-chip page
    bytes (cp=1 pool of 4 pages vs cp=2 pool of 8 = 4 per rank), a
    5-page request is refused up front by cp=1 ('needs up to N pages')
    but admitted AND completed token-identically by cp=2 — the long
    context fits because each chip holds 1/cp of it."""
    buf = 48
    rng = np.random.default_rng(9)
    prompt = [0] + [int(t) for t in
                    rng.integers(3, CFG.vocab_size, size=34)]
    req = lambda: Request(rid=0, prompt=list(prompt), max_new=5)
    # need = ceil((35 + 5) / 8) = 5 pages > the cp=1 pool's 4
    mesh1, model1, params1 = _setup(1)
    small = PagedEngine(model1, mesh1, params1, num_slots=1, buf_len=buf,
                        eos_id=EOS, page_size=8, num_pages=4,
                        prefill_chunk=8)
    with pytest.raises(ValueError, match="pages"):
        small.submit(req())

    mesh, model, params = _setup(2)
    eng = PagedEngine(model, mesh, params, num_slots=1, buf_len=buf,
                      eos_id=EOS, page_size=8, num_pages=8,
                      prefill_chunk=8)
    assert eng.pool.pages_per_rank == 4   # = the cp=1 pool: equal HBM
    eng.submit(req())
    eng.run_to_completion()
    ref = GreedyDecoder(model1, mesh1, buf).decode(
        params1, prompt, EOS, max_total_len=len(prompt) + 5)
    assert eng.completed[0].tokens == ref
    _assert_drained(eng)


def test_cp_record_fields_flow_through_loadgen():
    """serve.py's record copies cp/pages_per_rank/num_pages from the
    loadgen summary ('if k in summary' — a key loadgen forgets to lift
    from engine.stats() silently un-records the resolved cp), so pin
    the lift here at cp=2."""
    from distributed_pytorch_from_scratch_tpu.serving.loadgen import (
        run_loadgen)
    mesh, model, params = _matrix_setup(2)
    eng = PagedEngine(model, mesh, params, num_slots=2, buf_len=BUF,
                      eos_id=EOS, page_size=8, prefill_chunk=4)
    summary = run_loadgen(eng, [Request(rid=i, prompt=list(p), max_new=4)
                                for i, p in enumerate(PROMPTS[:2])])
    assert summary["completed"] == 2
    assert summary["cp"] == 2
    assert summary["pages_per_rank"] == summary["num_pages"] // 2
    _assert_drained(eng)


def test_slot_engine_refuses_cp_model():
    """The slot engine replicates per-slot caches — a cp>1 model must be
    refused at construction, pointing at the paged engine."""
    mesh, model, params = _setup(2)
    with pytest.raises(ValueError, match="PAGED"):
        ContinuousBatchingEngine(model, mesh, params, num_slots=2,
                                 buf_len=BUF, eos_id=EOS)


def test_speculative_refuses_cp_drafter():
    """SpeculativeEngine's supported shape is target cp>=1, drafter cp=1
    (the drafter pool is small enough to replicate); a cp>1 drafter is a
    loud construction-time refusal naming that shape."""
    from distributed_pytorch_from_scratch_tpu.serving.speculative import (
        SpeculativeEngine)
    mesh, model, params = _setup(2)
    drafter = Transformer(CFG, tp_size=2, cp_size=2)
    with pytest.raises(ValueError, match="drafter cp=1"):
        SpeculativeEngine(model, mesh, params, drafter, params,
                          num_slots=2, buf_len=BUF, eos_id=EOS,
                          speculate_k=2, page_size=8)
