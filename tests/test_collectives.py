"""Conjugate-pair tests for the four comm ops (SURVEY §7 step 2).

The reference has no direct tests for `models/comm_ops.py` — its layer tests
exercise them indirectly. Here each op's forward semantics and its
conjugate-gradient (the forward of its pair) are asserted directly:

    Copy   fwd = identity      Copy   bwd = Reduce fwd (all-reduce)
    Reduce fwd = all-reduce    Reduce bwd = Copy   fwd (identity)
    Split  fwd = local slice   Split  bwd = Gather fwd (all-gather)
    Gather fwd = all-gather    Gather bwd = Split  fwd (slice)

(`/root/reference/models/comm_ops.py:7-83`.)
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributed_pytorch_from_scratch_tpu.config import MeshConfig
from distributed_pytorch_from_scratch_tpu.ops import collectives as C
from distributed_pytorch_from_scratch_tpu.runtime.mesh import make_mesh

TP = 4


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(MeshConfig(dp=2, tp=TP))


def shmap(fn, mesh, in_specs, out_specs):
    return jax.shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs)


def test_copy_forward_identity(mesh):
    x = jnp.arange(16.0).reshape(2, 8)
    # per-shard output is the full replicated x; declaring the output sharded
    # over tp stitches one copy per shard -> a horizontal tiling of x.
    f = shmap(lambda x: C.copy_to(x, "tp"), mesh, (P(),), P(None, "tp"))
    assert np.allclose(f(x), np.tile(np.asarray(x), (1, TP)))


def test_copy_reduce_conjugate_grads(mesh):
    """grad through Copy must all-reduce: d/dx sum_r f_r(copy(x)) = sum_r f_r'."""
    x = jnp.arange(8.0)

    def per_shard(x):
        xc = C.copy_to(x, "tp")
        # shard-dependent linear function: weight = (rank+1)
        w = (C.axis_index("tp") + 1).astype(jnp.float32)
        return C.reduce_from(jnp.sum(xc) * w, "tp")

    f = shmap(per_shard, mesh, (P(),), P())
    g = jax.grad(f)(x)
    expected = sum(r + 1 for r in range(TP))  # all-reduce of per-rank grads
    assert np.allclose(g, expected)


def test_reduce_forward_sums(mesh):
    x = jnp.ones((TP * 2,))

    def per_shard(x_local):
        return C.reduce_from(jnp.sum(x_local), "tp")

    f = shmap(per_shard, mesh, (P("tp"),), P())
    # x sharded over tp: each shard sums its 2 elements -> 2; psum -> 2*TP
    assert np.allclose(f(x), 2 * TP)


def test_reduce_backward_identity(mesh):
    x = jnp.arange(4.0)

    def per_shard(x):
        return C.reduce_from(jnp.sum(x * x), "tp") / TP

    f = shmap(per_shard, mesh, (P(),), P())
    g = jax.grad(f)(x)
    # loss = psum(sum(x^2))/TP = sum(x^2); grad = 2x (identity bwd, no double count)
    assert np.allclose(g, 2 * x)


def test_split_forward_slices(mesh):
    x = jnp.arange(TP * 3.0).reshape(1, TP * 3)

    def per_shard(x):
        local = C.split_to(x, "tp")       # (1, 3)
        return local

    f = shmap(per_shard, mesh, (P(),), P(None, "tp"))
    out = f(x)
    # stitching the per-shard slices reassembles x
    assert np.allclose(out, x)


def test_split_backward_gathers(mesh):
    """Split bwd must reassemble the full cotangent (reference all-gathers,
    comm_ops.py:22-28)."""
    x = jnp.arange(TP * 2.0)

    def per_shard(x):
        local = C.split_to(x, "tp")
        w = (C.axis_index("tp") + 1).astype(jnp.float32)
        return C.reduce_from(jnp.sum(local) * w, "tp")

    f = shmap(per_shard, mesh, (P(),), P())
    g = jax.grad(f)(x)
    expected = np.repeat(np.arange(1, TP + 1, dtype=np.float32), 2)
    assert np.allclose(g, expected)


def test_gather_forward_concats(mesh):
    x = jnp.arange(TP * 2.0)

    def per_shard(x_local):
        full = C.gather_from(x_local, "tp")
        return jnp.sum(full) / 1.0  # varying-free value? keep per-shard
    f = shmap(lambda x: C.reduce_from(jnp.sum(C.gather_from(x, "tp")), "tp") / TP,
              mesh, (P("tp"),), P())
    assert np.allclose(f(x), jnp.sum(x))


def test_gather_backward_slices(mesh):
    """Gather bwd: each shard's weight grad only sees its own slice of the
    cotangent (reference slices, comm_ops.py:78-83; JAX transposes to
    psum_scatter which equals the slice for the tp-mean loss)."""
    w = jnp.arange(TP * 2.0)  # sharded over tp, 2 per shard

    def per_shard(w_local):
        full = C.gather_from(w_local, "tp")          # (TP*2,)
        coef = jnp.arange(TP * 2.0) + 1.0            # distinct cotangent per col
        loss = jnp.sum(full * coef)
        return C.reduce_from(loss, "tp") / TP        # mean of identical copies

    f = shmap(per_shard, mesh, (P("tp"),), P())
    g = jax.grad(f)(w)
    assert np.allclose(g, jnp.arange(TP * 2.0) + 1.0)


def test_reduce_scatter_matches_reduce_then_split(mesh):
    x = jax.random.normal(jax.random.key(0), (TP, TP * 4))

    def via_rs(x_local):
        return C.reduce_scatter(x_local, "tp", scatter_axis=-1)

    def via_reduce_split(x_local):
        return C.split_to(C.reduce_from(x_local, "tp"), "tp")

    f1 = shmap(via_rs, mesh, (P("tp"),), P("tp", "tp"))
    # note: out last dim sharded; compare summed values instead to avoid
    # double-sharded spec complexity
    f1 = shmap(lambda x: C.reduce_from(jnp.sum(via_rs(x)), "tp") / TP, mesh, (P("tp"),), P())
    f2 = shmap(lambda x: C.reduce_from(jnp.sum(via_reduce_split(x)), "tp") / TP, mesh, (P("tp"),), P())
    assert np.allclose(f1(x), f2(x), atol=1e-5)


def test_all_to_all_roundtrip(mesh):
    x = jax.random.normal(jax.random.key(1), (TP * 2, TP * 3))

    def per_shard(x_local):  # x sharded on dim 0
        swapped = C.all_to_all(x_local, "tp", split_axis=1, concat_axis=0)
        back = C.all_to_all(swapped, "tp", split_axis=0, concat_axis=1)
        return back

    f = shmap(per_shard, mesh, (P("tp"),), P("tp", None))
    assert np.allclose(f(x), x)


def test_ring_permute(mesh):
    x = jnp.arange(float(TP))

    def per_shard(x_local):
        return C.ring_permute(x_local, "tp", shift=1)

    f = shmap(per_shard, mesh, (P("tp"),), P("tp"))
    out = f(x)
    assert np.allclose(out, np.roll(np.arange(float(TP)), 1))
