"""Ring-decomposed collective matmul + bucketed gradient reduction
(ops/overlap.py, ISSUE 4).

Three invariants pinned on the virtual 8-device CPU mesh:

1. `ag_matmul` / `matmul_rs` equal their monolithic oracles
   (`all_gather`+dot, dot+`psum_scatter`) on values AND gradients (jacrev),
   for tp in {2, 4} — the ring is a pure re-scheduling of the same math,
   up to float summation order.
2. The model-level `tp_overlap='ring'` path matches the monolithic SP path
   fwd + grads, for both families, INSIDE the pipeline's live-gating (the
   ring's ppermutes run unconditionally on bubble steps — the acceptance
   bar of ISSUE 4).
3. The bucketed DP grad reduce equals the whole-tree transpose-derived
   reduction exactly (f32 wire), and within pinned tolerance on a bf16
   wire. A jax upgrade that changes shard_map's psum-transpose semantics
   breaks parity here LOUDLY (training/zero.build_bucketed_grad_fn
   normalises a trace-time-measured inflation factor).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributed_pytorch_from_scratch_tpu.config import (
    IGNORE_INDEX, MeshConfig, ModelConfig)
from distributed_pytorch_from_scratch_tpu.models.gpt2 import GPT2Transformer
from distributed_pytorch_from_scratch_tpu.models.transformer import Transformer
from distributed_pytorch_from_scratch_tpu.ops.collectives import (
    gather_from, reduce_scatter, split_to)
from distributed_pytorch_from_scratch_tpu.ops.overlap import (
    ag_matmul, bucket_partition, matmul_rs)
from distributed_pytorch_from_scratch_tpu.runtime.mesh import make_mesh
from distributed_pytorch_from_scratch_tpu.training.zero import (
    build_bucketed_grad_fn)

CFG = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=8, num_layers=2,
                  vocab_size=96, maxlen=64)


def make_batch(key, batch=4, t=32, vocab=96):
    k1, k2 = jax.random.split(key)
    input_ids = jax.random.randint(k1, (batch, t), 0, vocab)
    target_ids = jax.random.randint(k2, (batch, t), 0, vocab)
    mask = jax.random.bernoulli(jax.random.fold_in(key, 9), 0.2, (batch, t))
    target_ids = jnp.where(mask, IGNORE_INDEX, target_ids)
    position_ids = jnp.tile(jnp.arange(t)[None, :], (batch, 1))
    return input_ids, target_ids, position_ids


def assert_trees_close(a, b, rtol=1e-4, atol=1e-5):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=rtol, atol=atol)


# ------------------------------------------------ kernel-level vs oracles ----

@pytest.mark.parametrize("tp", [2, 4])
@pytest.mark.parametrize("nw", [1, 3])
def test_ag_matmul_matches_gather_dot_oracle(tp, nw):
    """ag_matmul == all_gather(x, seq) @ w, values and jacrev grads, for a
    single weight and for the fused multi-weight ring (wq/wk/wv shape)."""
    mesh = make_mesh(MeshConfig(dp=1, tp=tp))
    b, t, d = 2, 8, 6
    key = jax.random.key(0)
    x = jax.random.normal(key, (b, t, d))
    ws = tuple(jax.random.normal(jax.random.fold_in(key, j), (d, 4 + 2 * j))
               for j in range(nw))
    coefs = tuple(jax.random.normal(jax.random.fold_in(key, 50 + j),
                                    (b, t, 4 + 2 * j)) for j in range(nw))

    def ring_loss(x, ws):
        ys = ag_matmul(x, ws, "tp")
        return sum(jnp.sum(y * c) for y, c in zip(ys, coefs))

    def mono_loss(x, ws):
        xf = gather_from(x, "tp", tiled_axis=-2)
        return sum(jnp.sum((xf @ w) * c) for w, c in zip(ws, coefs))

    specs = (P(None, "tp", None), P())
    run = lambda fn: jax.jit(jax.shard_map(
        fn, mesh=mesh, in_specs=specs, out_specs=P()))
    np.testing.assert_allclose(run(ring_loss)(x, ws), run(mono_loss)(x, ws),
                               rtol=1e-5)
    g_ring = jax.jit(jax.jacrev(jax.shard_map(
        ring_loss, mesh=mesh, in_specs=specs, out_specs=P()),
        argnums=(0, 1)))(x, ws)
    g_mono = jax.jit(jax.jacrev(jax.shard_map(
        mono_loss, mesh=mesh, in_specs=specs, out_specs=P()),
        argnums=(0, 1)))(x, ws)
    assert_trees_close(g_ring, g_mono)


@pytest.mark.parametrize("tp", [2, 4])
def test_matmul_rs_matches_dot_scatter_oracle(tp):
    """matmul_rs == psum_scatter(x @ w, seq), values and jacrev grads (the
    row-parallel seq_sharded pattern: split input, partial dot, reduce)."""
    mesh = make_mesh(MeshConfig(dp=1, tp=tp))
    b, t, f, o = 2, 8, 8, 10
    key = jax.random.key(1)
    x = jax.random.normal(key, (b, t, f))
    w = jax.random.normal(jax.random.fold_in(key, 1), (f, o))
    tgt = jax.random.normal(jax.random.fold_in(key, 2), (b, t, o))

    def ring_loss(x, w, tgt):
        y = matmul_rs(split_to(x, "tp"), w, "tp")
        return jax.lax.psum(jnp.sum((y - tgt) ** 2), "tp")

    def mono_loss(x, w, tgt):
        y = reduce_scatter(split_to(x, "tp") @ w, "tp", scatter_axis=-2)
        return jax.lax.psum(jnp.sum((y - tgt) ** 2), "tp")

    specs = (P(), P("tp", None), P(None, "tp", None))
    run = lambda fn: jax.jit(jax.shard_map(
        fn, mesh=mesh, in_specs=specs, out_specs=P()))
    np.testing.assert_allclose(run(ring_loss)(x, w, tgt),
                               run(mono_loss)(x, w, tgt), rtol=1e-5)
    g_ring = jax.jit(jax.jacrev(jax.shard_map(
        ring_loss, mesh=mesh, in_specs=specs, out_specs=P()),
        argnums=(0, 1)))(x, w, tgt)
    g_mono = jax.jit(jax.jacrev(jax.shard_map(
        mono_loss, mesh=mesh, in_specs=specs, out_specs=P()),
        argnums=(0, 1)))(x, w, tgt)
    assert_trees_close(g_ring, g_mono)


def test_uneven_seq_chunks_refused_loudly():
    """matmul_rs must refuse a sequence the ring cannot chunk evenly, and
    both ops must refuse shape-incompatible weights — at TRACE time, not
    as a wrong answer on the chip."""
    mesh = make_mesh(MeshConfig(dp=1, tp=4))
    x = jnp.ones((2, 6, 8))   # t=6, tp=4: uneven
    with pytest.raises(ValueError, match="not divisible"):
        jax.jit(jax.shard_map(
            lambda x, w: matmul_rs(x, w, "tp"), mesh=mesh,
            in_specs=(P(), P()), out_specs=P(None, "tp", None)))(
                x, jnp.ones((8, 4)))
    with pytest.raises(ValueError, match="does not contract"):
        jax.jit(jax.shard_map(
            lambda x, w: ag_matmul(x, (w,), "tp")[0], mesh=mesh,
            in_specs=(P(None, "tp", None), P()),
            out_specs=P(None, None, None)))(jnp.ones((2, 8, 6)),
                                            jnp.ones((5, 4)))
    with pytest.raises(ValueError, match="non-empty tuple"):
        jax.jit(jax.shard_map(
            lambda x: ag_matmul(x, (), "tp"), mesh=mesh,
            in_specs=(P(None, "tp", None),),
            out_specs=P()))(jnp.ones((2, 8, 6)))


# ---------------------------------------------- model-level ring overlap ----

@pytest.mark.parametrize("family,tp", [
    ("llama", 2), ("llama", 4), ("gpt2", 4),
    # covered by the three above (family x tp both exercised); slow lane
    # keeps the full matrix without costing the tier-1 870s window
    pytest.param("gpt2", 2, marks=pytest.mark.slow),
])
def test_model_ring_overlap_matches_monolithic(family, tp):
    """tp_overlap='ring' == 'off' on loss and every grad leaf, with SP on —
    the ISSUE 4 acceptance pin (tp in {2, 4})."""
    cls = GPT2Transformer if family == "gpt2" else Transformer
    mesh = make_mesh(MeshConfig(dp=1, tp=tp))
    mono = cls(CFG, tp_size=tp, sequence_parallel=True)
    ring = cls(CFG, tp_size=tp, sequence_parallel=True, tp_overlap="ring")
    params = mono.init(jax.random.key(0))
    ids, tgt, pos = make_batch(jax.random.key(2))
    l0, g0 = jax.value_and_grad(mono.make_loss(mesh))(params, ids, tgt, pos)
    l1, g1 = jax.value_and_grad(ring.make_loss(mesh))(params, ids, tgt, pos)
    np.testing.assert_allclose(float(l1), float(l0), rtol=1e-5)
    assert_trees_close(g1, g0)


@pytest.mark.parametrize("pp,tp", [
    (2, 2), pytest.param(2, 4, marks=pytest.mark.slow)])
def test_model_ring_overlap_matches_inside_pipeline(pp, tp):
    """The ring path inside the pipeline's live-gating: the tp rings run
    unconditionally on bubble steps (a stage-divergent cond around a
    ppermute deadlocks), garbage flows only into garbage — loss and grads
    still match the monolithic pipelined path."""
    mesh = make_mesh(MeshConfig(pp=pp, tp=tp))
    kw = dict(tp_size=tp, pp_size=pp, pp_microbatches=4,
              sequence_parallel=True)
    mono = Transformer(CFG, **kw)
    ring = Transformer(CFG, tp_overlap="ring", **kw)
    params = mono.init(jax.random.key(0))
    ids, tgt, pos = make_batch(jax.random.key(2))
    l0, g0 = jax.value_and_grad(mono.make_loss(mesh))(params, ids, tgt, pos)
    l1, g1 = jax.value_and_grad(ring.make_loss(mesh))(params, ids, tgt, pos)
    np.testing.assert_allclose(float(l1), float(l0), rtol=1e-5)
    assert_trees_close(g1, g0)


@pytest.mark.slow
def test_model_ring_overlap_matches_inside_ring_cp_pipeline():
    """The deepest composition: pp x cp(ring) x tp with SP + tp_overlap —
    BOTH ring families (cp attention ring, tp collective-matmul rings)
    execute their ppermutes on every pipeline step."""
    mesh = make_mesh(MeshConfig(pp=2, cp=2, tp=2))
    kw = dict(tp_size=2, cp_size=2, pp_size=2, pp_microbatches=4,
              sequence_parallel=True)
    mono = Transformer(CFG, **kw)
    ring = Transformer(CFG, tp_overlap="ring", **kw)
    params = mono.init(jax.random.key(0))
    ids, tgt, pos = make_batch(jax.random.key(3))
    l0, g0 = jax.value_and_grad(mono.make_loss(mesh))(params, ids, tgt, pos)
    l1, g1 = jax.value_and_grad(ring.make_loss(mesh))(params, ids, tgt, pos)
    np.testing.assert_allclose(float(l1), float(l0), rtol=1e-5)
    assert_trees_close(g1, g0, rtol=2e-4, atol=2e-5)


def test_tp_overlap_validation():
    with pytest.raises(ValueError, match="requires sequence_parallel"):
        Transformer(CFG, tp_size=2, tp_overlap="ring")
    with pytest.raises(ValueError, match="'off', 'ring' or 'ring_q'"):
        Transformer(CFG, tp_size=2, sequence_parallel=True,
                    tp_overlap="mesh")
    moe_cfg = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=8, num_layers=2,
                          vocab_size=96, maxlen=64, num_experts=4)
    with pytest.raises(ValueError, match="MoE"):
        Transformer(moe_cfg, tp_size=2, sequence_parallel=True,
                    tp_overlap="ring")


# ------------------------------------------------- bucketed grad reduce ----

def test_bucket_partition_bounds_and_covers():
    sizes = [10, 10, 100, 1, 1, 1, 50]
    buckets = bucket_partition(sizes, bucket_bytes=80, itemsize=4)
    flat = [i for b in buckets for i in b]
    assert flat == list(range(len(sizes)))          # covers, in order
    for b in buckets:
        if len(b) > 1:                              # multi-leaf buckets fit
            assert sum(sizes[i] * 4 for i in b) <= 80
    assert [2] in buckets                           # oversize leaf: own bucket


@pytest.mark.parametrize("dp,cp,tp,sp", [
    (8, 1, 1, False), (2, 1, 2, True),
    # the cp and tp4 compositions ride the slow lane (the two defaults
    # already pin the pure-dp and the SP tp-replicated-leaf rules)
    pytest.param(2, 2, 2, True, marks=pytest.mark.slow),
    pytest.param(2, 1, 4, True, marks=pytest.mark.slow)])
def test_bucketed_reduce_matches_whole_tree_psum(dp, cp, tp, sp):
    """build_bucketed_grad_fn == value_and_grad(make_loss) on loss and every
    grad leaf — tiny buckets force many psums, so the schedule itself is
    exercised. This is also the canary for the psum-transpose semantics the
    reducer normalises (see its docstring): a jax upgrade that changes them
    fails HERE, not silently in training."""
    mesh = make_mesh(MeshConfig(dp=dp, cp=cp, tp=tp))
    model = Transformer(CFG, tp_size=tp, cp_size=cp, sequence_parallel=sp)
    params = model.init(jax.random.key(0))
    ids, tgt, pos = make_batch(jax.random.key(2), batch=8)
    l0, g0 = jax.jit(jax.value_and_grad(
        model.make_loss(mesh)))(params, ids, tgt, pos)
    l1, g1 = jax.jit(build_bucketed_grad_fn(
        model, mesh, bucket_mb=0.001))(params, ids, tgt, pos)
    np.testing.assert_allclose(float(l1), float(l0), rtol=1e-6)
    assert_trees_close(g1, g0, rtol=1e-5, atol=1e-6)


def test_bucketed_reduce_bf16_wire_tolerance():
    """The EQuARX-style bf16 wire: grads stay f32 OUTSIDE the collective
    and land within bf16 rounding of the f32 reduction — |err| bounded by
    ~2^-8 relative (bf16 has 8 mantissa bits) plus the dp-deep reduced-
    precision accumulation. The bound is pinned so a silent dtype leak
    (f32 master accumulate lost) fails the suite."""
    mesh = make_mesh(MeshConfig(dp=4, tp=2))
    model = Transformer(CFG, tp_size=2, sequence_parallel=True)
    params = model.init(jax.random.key(0))
    ids, tgt, pos = make_batch(jax.random.key(2), batch=8)
    _, g32 = jax.jit(build_bucketed_grad_fn(
        model, mesh, bucket_mb=1.0))(params, ids, tgt, pos)
    _, g16 = jax.jit(build_bucketed_grad_fn(
        model, mesh, bucket_mb=1.0,
        reduce_dtype=jnp.bfloat16))(params, ids, tgt, pos)
    for a, b in zip(jax.tree.leaves(g16), jax.tree.leaves(g32)):
        assert a.dtype == jnp.float32  # wire-only compression
        scale = max(float(jnp.max(jnp.abs(b))), 1e-8)
        err = float(jnp.max(jnp.abs(a - b))) / scale
        assert err < 2.0 ** -7, f"bf16 wire error {err} out of bounds"


def test_bucketed_reduce_scope_refusals():
    mesh = make_mesh(MeshConfig(dp=2, tp=2))
    with pytest.raises(ValueError, match="sequence_parallel"):
        build_bucketed_grad_fn(Transformer(CFG, tp_size=2), mesh)
    mesh_pp = make_mesh(MeshConfig(pp=2, tp=2))
    with pytest.raises(ValueError, match="pp_size"):
        build_bucketed_grad_fn(
            Transformer(CFG, tp_size=2, pp_size=2, sequence_parallel=True),
            mesh_pp)
    moe_cfg = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=8, num_layers=2,
                          vocab_size=96, maxlen=64, num_experts=4)
    mesh_ep = make_mesh(MeshConfig(dp=2, ep=2, tp=2))
    with pytest.raises(ValueError, match="MoE"):
        build_bucketed_grad_fn(
            Transformer(moe_cfg, tp_size=2, ep_size=2), mesh_ep)
