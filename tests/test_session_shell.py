"""Integration tests for the hardware-session shell helpers
(runs/r5/session_lib.sh): rc propagation, artifact guards, error-payload
cleanup — exercised with stub commands in a sandbox, so the shell plumbing
that gates the real chip window is proven on CPU in CI.

Complements tests/test_staged_session.py (which validates WHAT is staged —
flags against argparsers) by validating HOW it runs (the helpers' shell
semantics).
"""

import json
import os
import subprocess
import sys
import textwrap


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LIB = os.path.join(REPO, "runs", "r5", "session_lib.sh")


def run_snippet(tmp_path, body, fake_bench=None):
    """Run a bash snippet with $R/$M sandboxed and `python bench.py`
    replaced by a stub (a bench.py in a scratch cwd shadowing the real
    one is not possible since run_step resolves scripts/ relative to cwd;
    instead the stub is injected via a wrapper dir on PATH for `python`)."""
    r = tmp_path / "runs_r5"
    r.mkdir(exist_ok=True)  # tests may pre-seed artifacts
    script = tmp_path / "snippet.sh"
    script.write_text(textwrap.dedent(f"""\
        set -u
        set -o pipefail
        cd {REPO}
        R={r}
        M=$R/session_manifest.jsonl
        . {LIB}
        {body}
        """))
    env = {**os.environ}
    # hermetic: an operator's exported deadline must not leak into tests
    # (the deadline tests opt in via an explicit export in their snippet)
    env.pop("SESSION_DEADLINE", None)
    if fake_bench is not None:
        # shadow `python bench.py ...`: a wrapper `python` that execs the
        # stub when its first arg is bench.py, else the real interpreter
        bindir = tmp_path / "bin"
        bindir.mkdir()
        stub = tmp_path / "fake_bench.py"
        stub.write_text(fake_bench)
        wrapper = bindir / "python"
        wrapper.write_text(textwrap.dedent(f"""\
            #!/bin/bash
            if [ "${{1:-}}" = "bench.py" ]; then shift;
              exec {sys.executable} {stub} "$@"
            fi
            exec {sys.executable} "$@"
            """))
        wrapper.chmod(0o755)
        env["PATH"] = f"{bindir}:{env['PATH']}"
    p = subprocess.run(["bash", str(script)], capture_output=True, text=True,
                       timeout=300, env=env, cwd=REPO)
    return r, p


def manifest(r):
    path = r / "session_manifest.jsonl"
    if not path.exists():
        return []
    return [json.loads(l) for l in path.read_text().splitlines()]


def test_step_success_and_failure_rc(tmp_path):
    r, p = run_snippet(tmp_path, """
        step ok 30 python -c "print('fine')" || echo "RC_BAD_$?"
        step bad 30 python -c "import sys; sys.exit(7)" || echo "RC_GOT_$?"
        """)
    assert "RC_BAD" not in p.stdout
    assert "RC_GOT_7" in p.stdout  # the step's rc IS the command's
    recs = {m["name"]: m for m in manifest(r)}
    assert recs["ok"]["rc"] == 0 and recs["bad"]["rc"] == 7


def test_bench_line_success_writes_artifact(tmp_path):
    r, p = run_snippet(
        tmp_path,
        'bench_line t1 30 --model 45m\n',
        fake_bench='import json; print(json.dumps({"metric": "m", '
                   '"value": 1, "unit": "u", "vs_baseline": 1}))')
    art = r / "bench_t1.json"
    assert art.exists(), p.stderr
    assert json.loads(art.read_text())["value"] == 1
    assert manifest(r)[-1]["rc"] == 0


def test_bench_line_failure_removes_artifact_and_records_rc(tmp_path):
    r, p = run_snippet(
        tmp_path,
        'bench_line t2 30 --model 45m\n',
        fake_bench='import sys; print("partial garbage"); sys.exit(5)')
    assert not (r / "bench_t2.json").exists()  # no half-written artifact
    recs = {m["name"]: m for m in manifest(r)}
    assert recs["bench_t2"]["rc"] == 5  # "failed rc=0" is impossible


def test_bench_line_error_payload_is_retried(tmp_path):
    # seed an error artifact (bench rc=3 outage contract writes JSON + rc 3)
    r = tmp_path / "runs_r5"
    r.mkdir()
    (r / "bench_t3.json").write_text(
        '{"metric": "bench", "error": "backend_unavailable"}\n')
    r2, p = run_snippet(
        tmp_path,
        'bench_line t3 30 --model 45m\n',
        fake_bench='import json; print(json.dumps({"metric": "m", '
                   '"value": 2, "unit": "u", "vs_baseline": 1}))')
    assert r2 == r
    rec = json.loads((r / "bench_t3.json").read_text())
    assert "error" not in rec and rec["value"] == 2  # error line re-ran


def test_deadline_stops_new_steps_chip_stays_free(tmp_path):
    """Past SESSION_DEADLINE run_step (the chokepoint) must refuse to
    start the child — rc 18, recorded in the manifest, no bench artifact —
    so a late session can't hold the single-tenant chip into the driver's
    end-of-round bench window. The script itself continues (cheap no-op
    guards), which is fine: the chip is never touched."""
    canary = tmp_path / "CHIP_TOUCHED"
    r, p = run_snippet(
        tmp_path,
        'export SESSION_DEADLINE=200001010000\n'  # long past
        'bench_line t5 30 --model 45m\n',
        fake_bench=f'import sys; open({str(canary)!r}, "w"); sys.exit(0)')
    assert not (r / "bench_t5.json").exists()
    assert not canary.exists()  # the child must never have started
    recs = manifest(r)
    assert recs and recs[0]["rc"] == 18 and recs[0].get("deadline") is True


def test_malformed_deadline_fails_closed(tmp_path):
    r, p = run_snippet(
        tmp_path,
        'export SESSION_DEADLINE="2026-08-01T04:15"\n'  # malformed
        'step s1 30 python -c "print(1)"\n',
        fake_bench=None)
    recs = manifest(r)
    assert recs and recs[0]["rc"] == 18  # refuses to start, loudly
    # the manifest must tell the TRUTH (malformed, not "deadline passed")
    assert "malformed" in recs[0]["stderr_tail"]
    # step() routes run_step's stderr into session.log — the complaint
    # must be in the session forensics, not lost
    assert "malformed" in (r / "session.log").read_text()


def test_deadline_inert_without_deadline(tmp_path):
    r, p = run_snippet(
        tmp_path,
        'step ok 30 python -c "print(42)"\n',
        fake_bench=None)
    assert p.returncode == 0  # unset deadline -> run normally
    assert manifest(r)[0]["rc"] == 0


def test_bench_line_good_artifact_is_idempotent(tmp_path):
    r = tmp_path / "runs_r5"
    r.mkdir()
    (r / "bench_t4.json").write_text(
        '{"metric": "m", "value": 9, "unit": "u", "vs_baseline": 1}\n')
    r2, p = run_snippet(
        tmp_path,
        'bench_line t4 30 --model 45m\n',
        fake_bench='import sys; sys.exit(99)')  # must NOT be invoked
    rec = json.loads((r / "bench_t4.json").read_text())
    assert rec["value"] == 9  # untouched
    assert not manifest(r)  # no step ran
