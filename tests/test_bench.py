"""The driver-facing bench contract: `bench.py` must print exactly ONE JSON
line on stdout with the metric/value/unit/vs_baseline keys, whatever flags
are set. Runs the real harness on the virtual CPU mesh at a tiny shape."""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_backend_outage_emits_machine_readable_json():
    """VERDICT r3 #4 + ISSUE 4 satellite: an unreachable backend (the ONLY
    bench failure mode seen in three rounds — BENCH_r02/r03 rc=1) must
    yield one parseable `{"error": "backend_unavailable"}` line and exit
    ZERO, for both outage shapes: plugin init raising, and plugin init
    hanging forever. BENCH_r05 showed rc=3 losing the trajectory point:
    the driver drops nonzero-rc artifacts, which threw away exactly the
    machine-readable record this path exists to preserve."""
    script = (
        "import bench, time\n"
        "import sys\n"
        "mode = sys.argv[1]\n"
        "def raising():\n"
        "    raise RuntimeError('Unable to initialize backend: tunnel down')\n"
        "def hanging():\n"
        "    time.sleep(120)\n"
        "bench._discover_backend(probe=raising if mode == 'raise' else hanging,"
        " timeout_s=0.5)\n")
    for mode in ("raise", "hang"):
        p = subprocess.run([sys.executable, "-c", script, mode],
                           capture_output=True, text=True, timeout=120,
                           cwd=REPO_ROOT)
        assert p.returncode == 0, (mode, p.returncode, p.stderr[-1000:])
        lines = [l for l in p.stdout.strip().splitlines() if l.strip()]
        assert len(lines) == 1, (mode, p.stdout)
        rec = json.loads(lines[0])
        assert rec["error"] == "backend_unavailable", rec
        assert "detail" in rec, rec


@pytest.mark.parametrize("extra", [
    ["--steps_per_dispatch", "1", "--tp", "1"],
    ["--steps_per_dispatch", "2", "--tp", "2"],
])
def test_bench_emits_one_json_line(extra):
    p = subprocess.run(
        [sys.executable, "-c", (
            "import os;"
            "os.environ['XLA_FLAGS']=os.environ.get('XLA_FLAGS','')"
            " + ' --xla_force_host_platform_device_count=8';"
            "import jax; jax.config.update('jax_platforms','cpu');"
            "import bench;"
            "bench.main(['--model','tiny','--batch','2','--seqlen','64',"
            "'--iters','1'] + %r)" % (extra,))],
        capture_output=True, text=True, timeout=500, cwd=REPO_ROOT)
    assert p.returncode == 0, p.stderr[-2000:]
    lines = [l for l in p.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, f"stdout must be ONE JSON line, got: {p.stdout!r}"
    rec = json.loads(lines[0])
    assert set(rec) == {"metric", "value", "unit", "vs_baseline",
                        "zero_stage", "param_bytes_per_device"}
    assert rec["unit"] == "tokens/sec/chip"
    assert rec["value"] > 0
    assert rec["zero_stage"] == 0          # no --zero flag staged here
    assert rec["param_bytes_per_device"] > 0


def test_breakdown_bench_emits_one_json_line():
    """--breakdown (staged as bench line 45mbreakdown) must produce its
    JSON artifact on CPU before it ever runs on the scarce chip: one line,
    the component keys summarize_run.py renders, derived components
    consistent with the measured ones."""
    p = subprocess.run(
        [sys.executable, "-c", (
            "import os;"
            "os.environ['XLA_FLAGS']=os.environ.get('XLA_FLAGS','')"
            " + ' --xla_force_host_platform_device_count=8';"
            "import jax; jax.config.update('jax_platforms','cpu');"
            "import bench;"
            "bench.main(['--model','tiny','--breakdown','--batch','2',"
            "'--seqlen','64','--iters','2','--tp','1',"
            "'--steps_per_dispatch','4'])")],
        capture_output=True, text=True, timeout=500, cwd=REPO_ROOT)
    assert p.returncode == 0, p.stderr[-2000:]
    lines = [l for l in p.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, f"stdout must be ONE JSON line, got: {p.stdout!r}"
    rec = json.loads(lines[0])
    assert set(rec) == {"metric", "value", "unit", "vs_baseline",
                        "components", "wire_dtype", "attribution",
                        "zero_stage", "param_bytes_per_device"}
    assert rec["unit"] == "ms/step"
    assert rec["wire_dtype"] == "f32"   # default: uncompressed DP wire
    comp = rec["components"]
    for key in ("h2d_ms", "fwd_ms", "fwdbwd_ms", "step_ms", "step_ms_spd4",
                "derived_bwd_ms", "derived_adam_ms", "derived_dispatch_ms"):
        assert key in comp, comp
    assert rec["value"] == comp["step_ms"] > 0
    # derived components must be consistent with the measured ones
    assert abs(comp["derived_bwd_ms"]
               - (comp["fwdbwd_ms"] - comp["fwd_ms"])) < 0.02
    assert abs(comp["derived_dispatch_ms"]
               - (comp["step_ms"] - comp["step_ms_spd4"])) < 0.02
    # the roofline attribution rides the same artifact: ranked suspects
    # with shares of the measured amortised step
    att = rec["attribution"]
    assert att["analytic_step_ms"] > 0
    ranks = [s["rank"] for s in att["suspects"]]
    assert ranks == sorted(ranks) and ranks[0] == 1
    est = [s["est_ms"] for s in att["suspects"]]
    assert est == sorted(est, reverse=True)
    # the measured dispatch gap must appear as a suspect (spd mode ran)
    assert any(s["name"] == "dispatch overhead" for s in att["suspects"])


def test_breakdown_analytic_emits_one_json_line():
    """--breakdown --analytic: the CPU-runnable roofline attribution at the
    FLAGSHIP 45m b32xt1000 shape (no device timing — milliseconds to run),
    the exact artifact VERDICT r5 #1 asked for."""
    p = subprocess.run(
        [sys.executable, "-c", (
            "import os;"
            "os.environ['XLA_FLAGS']=os.environ.get('XLA_FLAGS','')"
            " + ' --xla_force_host_platform_device_count=8';"
            "import jax; jax.config.update('jax_platforms','cpu');"
            "import bench;"
            "bench.main(['--model','45m','--breakdown','--analytic',"
            "'--remat','dots','--tp','1'])")],
        capture_output=True, text=True, timeout=500, cwd=REPO_ROOT)
    assert p.returncode == 0, p.stderr[-2000:]
    lines = [l for l in p.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, f"stdout must be ONE JSON line, got: {p.stdout!r}"
    rec = json.loads(lines[0])
    assert set(rec) == {"metric", "value", "unit", "vs_baseline",
                        "wire_dtype", "tp_overlap", "comm", "suspects",
                        "zero_stage"}
    assert rec["unit"] == "ms/step (analytic)"
    assert rec["value"] > 0
    names = [s["name"] for s in rec["suspects"]]
    assert any("tile/pad waste" in n for n in names), names
    # single-chip config: no collectives, so no comm to hide
    assert rec["comm"] == {"total_ms": 0, "hidden_ms": 0, "exposed_ms": 0}
    # the full human table lands on stderr for the session log
    assert "step-time attribution" in p.stderr
    assert "rank" in p.stderr


def test_breakdown_analytic_overlapped_config_reports_comm_hidden():
    """ISSUE 4 acceptance: the overlapped config (tp4 + SP + ring, bucketed
    bf16 DP reduce) must report a NONZERO 'comm hidden' line — the
    measurable claim the ring decomposition exists to make. Runs the same
    CPU-only analytic path the driver can execute; --tp 4 prices a 4-chip
    mesh without needing one (no mesh is built in analytic mode)."""
    p = subprocess.run(
        [sys.executable, "-c", (
            "import os;"
            "os.environ['XLA_FLAGS']=os.environ.get('XLA_FLAGS','')"
            " + ' --xla_force_host_platform_device_count=8';"
            "import jax; jax.config.update('jax_platforms','cpu');"
            "import bench;"
            "bench.main(['--model','45m','--breakdown','--analytic',"
            "'--remat','dots','--tp','4','--dp','2','--sequence_parallel',"
            "'--tp_overlap','ring','--dp_reduce_bucket_mb','25',"
            "'--dp_reduce_dtype','bf16'])")],
        capture_output=True, text=True, timeout=500, cwd=REPO_ROOT)
    assert p.returncode == 0, p.stderr[-2000:]
    lines = [l for l in p.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, f"stdout must be ONE JSON line, got: {p.stdout!r}"
    rec = json.loads(lines[0])
    assert rec["comm"]["hidden_ms"] > 0, rec["comm"]
    assert rec["comm"]["total_ms"] >= rec["comm"]["hidden_ms"]
    # the stderr table carries the human-readable line
    assert "comm hidden / exposed" in p.stderr
    # and the overlapped config's per-record notes mention the ring
    assert "tp_overlap=ring" in p.stderr
    # exposed comm appears as a ranked suspect alongside the tile/remat ones
    names = [s["name"] for s in rec["suspects"]]
    assert any("exposed collective comm" in n for n in names), names


def test_serving_speculate_bench_emits_one_json_line():
    """ISSUE 7 acceptance criterion: `--serving --speculate K` must run on
    CPU and emit ONE JSON line carrying the speculative A/B — `vs_paged`
    (speculative / plain paged at equal HBM) plus the dispatch-economics
    fields summarize_run.py renders. With two independently random-init
    models the greedy acceptance rate is ~0, so accepted-tokens/dispatch
    must still floor at 1.0 (every verify emits at least the corrected
    token) — the equal-HBM page split must show the drafter paid for."""
    p = subprocess.run(
        [sys.executable, "-c", (
            "import jax; jax.config.update('jax_platforms','cpu');"
            "import bench;"
            "bench.main(['--model','tiny','--serving','--tp','1',"
            "'--slots','2','--serve_requests','3','--prompt_len','12',"
            "'--gen_tokens','6','--page_size','8','--prefill_chunk','16',"
            "'--speculate','2'])")],
        capture_output=True, text=True, timeout=500, cwd=REPO_ROOT)
    assert p.returncode == 0, p.stderr[-2000:]
    lines = [l for l in p.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, f"stdout must be ONE JSON line, got: {p.stdout!r}"
    rec = json.loads(lines[0])
    for key in ("vs_paged", "speculate_k", "accepted_tokens_per_dispatch",
                "acceptance_rate", "acceptance_rate_by_position",
                "spec_rounds", "drafter_ms_total", "target_ms_total",
                "target_pages", "drafter_pages", "drafter_budget_share",
                "paged_vs_slot", "vs_baseline"):
        assert key in rec, (key, sorted(rec))
    assert rec["unit"] == "tokens/sec (serving)"
    assert rec["value"] > 0
    assert rec["speculate_k"] == 2
    assert rec["vs_paged"] > 0
    assert len(rec["acceptance_rate_by_position"]) == 2
    assert rec["accepted_tokens_per_dispatch"] >= 1.0, rec
    assert rec["target_pages"] > 0 and rec["drafter_pages"] > 0


def test_decode_bench_emits_one_json_line():
    """--decode measures KV-cache generation throughput; vs_baseline is the
    speedup over the reference-semantics full-recompute per-token loop
    (`/root/reference/test.py:141-161`), which must come out > 1."""
    p = subprocess.run(
        [sys.executable, "-c", (
            "import os;"
            "os.environ['XLA_FLAGS']=os.environ.get('XLA_FLAGS','')"
            " + ' --xla_force_host_platform_device_count=8';"
            "import jax; jax.config.update('jax_platforms','cpu');"
            "import bench;"
            "bench.main(['--model','tiny','--decode','--batch','2',"
            "'--prompt_len','8','--gen_tokens','12','--tp','1'])")],
        capture_output=True, text=True, timeout=500, cwd=REPO_ROOT)
    assert p.returncode == 0, p.stderr[-2000:]
    lines = [l for l in p.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, f"stdout must be ONE JSON line, got: {p.stdout!r}"
    rec = json.loads(lines[0])
    # ADVICE r4: the decode line discloses batch size and probe coverage so
    # the batching win and the pure KV win are separable
    assert set(rec) == {"metric", "value", "unit", "vs_baseline", "batch",
                        "probe_steps", "kv_rate_per_stream",
                        "ref_recompute_rate"}
    assert rec["unit"] == "tokens/sec"
    assert rec["value"] > 0
    assert rec["batch"] == 2
    assert rec["probe_steps"] == 12  # the FULL gen budget, not a short probe
    # vs_baseline is the PER-STREAM KV-vs-recompute speedup; on the CPU toy
    # it is modest (no dispatch round-trip to amortise) but must be real
    assert rec["vs_baseline"] > 1, rec
