"""The driver-facing bench contract: `bench.py` must print exactly ONE JSON
line on stdout with the metric/value/unit/vs_baseline keys, whatever flags
are set. Runs the real harness on the virtual CPU mesh at a tiny shape."""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.parametrize("extra", [
    ["--steps_per_dispatch", "1", "--tp", "1"],
    ["--steps_per_dispatch", "2", "--tp", "2"],
])
def test_bench_emits_one_json_line(extra):
    p = subprocess.run(
        [sys.executable, "-c", (
            "import os;"
            "os.environ['XLA_FLAGS']=os.environ.get('XLA_FLAGS','')"
            " + ' --xla_force_host_platform_device_count=8';"
            "import jax; jax.config.update('jax_platforms','cpu');"
            "import bench;"
            "bench.main(['--model','tiny','--batch','2','--seqlen','64',"
            "'--iters','1'] + %r)" % (extra,))],
        capture_output=True, text=True, timeout=500, cwd=REPO_ROOT)
    assert p.returncode == 0, p.stderr[-2000:]
    lines = [l for l in p.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, f"stdout must be ONE JSON line, got: {p.stdout!r}"
    rec = json.loads(lines[0])
    assert set(rec) == {"metric", "value", "unit", "vs_baseline"}
    assert rec["unit"] == "tokens/sec/chip"
    assert rec["value"] > 0
