"""Grouped-query attention (num_kv_heads < num_heads) — beyond the
reference (its attention is plain MHA, `/root/reference/models/model.py:49`).
Checks: TP model vs unsharded oracle (which implements the group-repeat
independently), KV-cache decode parity, and construction-time validation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_from_scratch_tpu import (MeshConfig, ModelConfig,
                                                  Transformer, make_mesh)
from distributed_pytorch_from_scratch_tpu.models.decode import GreedyDecoder
from distributed_pytorch_from_scratch_tpu.models.vanilla import (
    VanillaTransformer)

CFG = ModelConfig(attn_dim=64, ffn_dim=128, num_heads=8, num_kv_heads=2,
                  num_layers=2, vocab_size=96, maxlen=64)


def make_batch(key, batch=4, t=32, vocab=96):
    k1, k2 = jax.random.split(key)
    ids = jax.random.randint(k1, (batch, t), 0, vocab)
    tgt = jax.random.randint(k2, (batch, t), 0, vocab)
    pos = jnp.tile(jnp.arange(t)[None, :], (batch, 1))
    return ids, tgt, pos


def test_kv_projection_is_narrow():
    model = Transformer(CFG, tp_size=2)
    params = model.init(jax.random.key(0))
    # wk/wv project to kv_heads*head_dim = 2*8 = 16, not attn_dim 64
    assert params["layers"]["wk"]["weight"].shape == (2, 64, 16)
    assert params["layers"]["wq"]["weight"].shape == (2, 64, 64)
    assert CFG.num_params() < ModelConfig(
        attn_dim=64, ffn_dim=128, num_heads=8, num_layers=2,
        vocab_size=96, maxlen=64).num_params()


@pytest.mark.parametrize("dp,tp", [(1, 2), (2, 1)])
def test_gqa_matches_vanilla(dp, tp):
    mesh = make_mesh(MeshConfig(dp=dp, tp=tp))
    model = Transformer(CFG, tp_size=tp)
    oracle = VanillaTransformer(CFG)
    params = model.init(jax.random.key(0))
    ids, tgt, pos = make_batch(jax.random.key(1))

    l_sh, g_sh = jax.value_and_grad(model.make_loss(mesh))(params, ids, tgt, pos)
    l_ref, g_ref = jax.value_and_grad(oracle.loss)(params, ids, tgt, pos)
    np.testing.assert_allclose(l_sh, l_ref, rtol=1e-5)
    for a, b in zip(jax.tree.leaves(g_sh), jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)

    logits_sh = model.make_forward(mesh)(params, ids, pos)
    logits_ref = oracle.forward(params, ids, pos)
    np.testing.assert_allclose(np.asarray(logits_sh), np.asarray(logits_ref),
                               rtol=1e-4, atol=1e-4)


def test_gqa_kv_decode_matches_forward_argmax():
    """The KV-cache decoder under GQA == greedy over the full forward."""
    mesh = make_mesh(MeshConfig(dp=1, tp=2))
    model = Transformer(CFG, tp_size=2)
    params = jax.device_put(model.init(jax.random.key(0)),
                            model.shardings(mesh))
    fwd = model.make_forward(mesh)

    prompt = [1, 5, 9, 13]
    buf_len = 12
    dec = GreedyDecoder(model, mesh, buf_len)
    gen = dec.decode_batch(params, [prompt], eos_id=-1,  # no EOS: run to cap
                           max_total_len=buf_len)[0]

    # oracle: repeatedly argmax the full-forward's last-position logits
    ids = list(prompt)
    while len(ids) < buf_len:
        buf = jnp.asarray([ids + [0] * (buf_len - len(ids))])
        pos = jnp.tile(jnp.arange(buf_len)[None, :], (1, 1))
        logits = fwd(params, buf, pos)[0, len(ids) - 1, : CFG.vocab_size]
        ids.append(int(jnp.argmax(logits)))
    assert gen == ids[len(prompt):], (gen, ids[len(prompt):])


def test_gqa_kv_cache_stays_at_kv_heads():
    """The decode caches hold num_kv_heads entries — the GQA memory win —
    not the group-expanded query-head count."""
    from jax.sharding import PartitionSpec as P

    from distributed_pytorch_from_scratch_tpu.models import decode as dec
    from distributed_pytorch_from_scratch_tpu.config import resolve_dtype
    from distributed_pytorch_from_scratch_tpu.ops.rope import rope_tables

    mesh = make_mesh(MeshConfig(dp=1, tp=2))
    model = Transformer(CFG, tp_size=2)
    params = model.init(jax.random.key(0))
    dtype = resolve_dtype(CFG.compute_dtype)
    buf = jnp.zeros((1, 8), jnp.int32)

    def shard_fn(params, buf):
        cos_t, sin_t = rope_tables(CFG.maxlen, CFG.head_dim, CFG.rope_theta)
        ks, vs, _ = dec._prefill(model, params, buf,
                                 jnp.asarray([4]), cos_t, sin_t, dtype)
        return ks.shape[2], vs.shape[2]  # head axis of (L, b, heads, t, hd)

    with mesh:
        kh, vh = jax.shard_map(shard_fn, mesh=mesh,
                               in_specs=(model.specs(), P(None, None)),
                               out_specs=P())(params, buf)
    assert kh == vh == CFG.kv_heads // 2  # local kv heads, NOT local q heads
    assert CFG.kv_heads // 2 < CFG.num_heads // 2


def test_gqa_validation():
    with pytest.raises(ValueError, match="multiple"):
        Transformer(ModelConfig(num_heads=8, num_kv_heads=3), tp_size=1)
    with pytest.raises(ValueError, match="num_kv_heads"):
        Transformer(ModelConfig(num_heads=8, num_kv_heads=2), tp_size=4)


@pytest.mark.parametrize("cp,impl", [(2, "ring"), (2, "ulysses")])
def test_gqa_context_parallel_matches_vanilla(cp, impl):
    """GQA k/v (no repeat) flowing through ring / ulysses context
    parallelism — the kernels/collectives route the groups themselves."""
    mesh = make_mesh(MeshConfig(cp=cp))
    model = Transformer(CFG, cp_size=cp, cp_impl=impl)
    oracle = VanillaTransformer(CFG)
    params = model.init(jax.random.key(2))
    ids, tgt, pos = make_batch(jax.random.key(3))

    l_sh, g_sh = jax.value_and_grad(model.make_loss(mesh))(params, ids, tgt,
                                                           pos)
    l_ref, g_ref = jax.value_and_grad(oracle.loss)(params, ids, tgt, pos)
    np.testing.assert_allclose(l_sh, l_ref, rtol=1e-5)
    for a, b in zip(jax.tree.leaves(g_sh), jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)
