"""Speculative decoding correctness — ISSUE 7.

The tentpole contract (serving/speculative.py):

* **greedy** — SPECULATIVE output is TOKEN-IDENTICAL to the
  non-speculative paged engine AND per-prompt `GreedyDecoder`, whatever
  the drafter proposes — across k ∈ {2, 4}, page sizes {8, 16}, tp ∈
  {1, 2}, arrival orders, and preempt-and-resume. A draft is accepted iff
  it equals the target argmax; the first rejection (or the bonus slot)
  emits the target argmax itself, so a bad drafter costs SPEED, never
  tokens. The acceptance-boundary-at-page-boundary case (a round's
  accepted run ending exactly at a page edge, the next round growing a
  fresh page mid-verify) is pinned with a self-drafting engine whose
  acceptance is ~1.0 by construction.
* **sampled** — exact rejection sampling (accept d ~ q with prob
  min(1, p/q), resample the first rejection from norm(max(p − q, 0)))
  makes the emitted stream DISTRIBUTION-identical to the plain fused
  sampler: pinned by a two-sample chi-square against the non-speculative
  paged engine at fixed seeds, plus a power control that the same test
  DOES reject a genuinely different distribution (top_k 4 vs 8).

Plus the fused-sampler satellite: `debug_host_sampler=True` (the old
host-side full-vocab sampling) draws bit-identical tokens to the fused
in-program path on BOTH non-speculative engines, greedy and sampled —
so making the fused path the only production path changed nothing but
the per-step D2H bytes.
"""

import json
import os

import jax
import numpy as np
import pytest

from distributed_pytorch_from_scratch_tpu.config import MeshConfig, ModelConfig
from distributed_pytorch_from_scratch_tpu.models.decode import GreedyDecoder
from distributed_pytorch_from_scratch_tpu.models.transformer import Transformer
from distributed_pytorch_from_scratch_tpu.runtime.mesh import make_mesh
from distributed_pytorch_from_scratch_tpu.serving.engine import (
    ContinuousBatchingEngine, PagedEngine, Request)
from distributed_pytorch_from_scratch_tpu.serving.speculative import (
    SpeculativeEngine)

CFG = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=8, num_layers=2,
                  vocab_size=96, maxlen=64)
# the drafter: cheaper than the target in every dimension, same vocab
DCFG = ModelConfig(attn_dim=16, ffn_dim=32, num_heads=2, num_layers=1,
                   vocab_size=96, maxlen=64)
BUF = 32
EOS = 1

PROMPTS = [
    [0, 5, 17, 33, 60],
    [0, 95],                        # boundary vocab id
    [0, 2, 4, 6, 8, 10, 12, 14],    # page-boundary prompt at ps=8
    [0, 7],
    [0, 9, 11],
]


def _setup(tp, seed=7):
    mesh = make_mesh(MeshConfig(dp=1, tp=tp))
    model = Transformer(CFG, tp_size=tp)
    params = jax.device_put(model.init(jax.random.key(seed)),
                            model.shardings(mesh))
    return mesh, model, params


def _drafter(mesh, tp, seed=21):
    dmodel = Transformer(DCFG, tp_size=tp)
    dparams = jax.device_put(dmodel.init(jax.random.key(seed)),
                             dmodel.shardings(mesh))
    return dmodel, dparams


def _assert_drained(eng):
    """No page leak in EITHER pool after retirement: every target AND
    drafter page back on its free list, refcounts at zero."""
    assert eng.pool.free_pages == eng.pool.num_pages
    assert (eng.pool.refcount == 0).all()
    assert eng.dpool.free_pages == eng.dpool.num_pages
    assert (eng.dpool.refcount == 0).all()
    assert (eng._dtbl == eng.dpool.scratch_page).all()


# ---- greedy token identity (the anchor) ----


@pytest.mark.parametrize("tp,k,ps", [
    (1, 2, 8), (1, 2, 16), (1, 4, 8), (1, 4, 16),
    (2, 2, 8), (2, 2, 16), (2, 4, 8), (2, 4, 16)])
def test_spec_matches_paged_and_greedy(tp, k, ps):
    """Staggered admissions + slot churn (5 requests through 2 slots),
    shuffled late arrivals, a RANDOM-INIT drafter (acceptance ~0 — the
    adversarial case: every round is mostly rejections): the speculative
    stream equals the non-speculative paged engine's AND each prompt's
    solo GreedyDecoder decode, token for token."""
    mesh, model, params = _setup(tp)
    dec = GreedyDecoder(model, mesh, BUF)
    refs = [dec.decode(params, p, EOS, max_total_len=len(p) + 10)
            for p in PROMPTS]

    def drive(eng):
        reqs = [Request(rid=i, prompt=p, max_new=10)
                for i, p in enumerate(PROMPTS)]
        eng.submit(reqs[0])
        eng.submit(reqs[1])
        for _ in range(3):              # let the first two run a few rounds
            eng.step()
        for r in reversed(reqs[2:]):    # late arrivals, reversed order
            eng.submit(r)
        eng.run_to_completion()
        return {r.rid: r.tokens for r in eng.completed}

    dmodel, dparams = _drafter(mesh, tp)
    spec_eng = SpeculativeEngine(
        model, mesh, params, dmodel, dparams, num_slots=2, buf_len=BUF,
        eos_id=EOS, speculate_k=k, page_size=ps, prefill_chunk=4)
    spec = drive(spec_eng)
    paged = drive(PagedEngine(model, mesh, params, num_slots=2, buf_len=BUF,
                              eos_id=EOS, page_size=ps, prefill_chunk=4))
    assert len(spec) == len(PROMPTS)
    for i, ref in enumerate(refs):
        assert spec[i] == ref, (tp, k, ps, i, spec[i], ref)
        assert spec[i] == paged[i], (tp, k, ps, i)
    st = spec_eng.stats()
    assert st["spec_rounds"] > 0
    # the headline normalisation: 1.0 = non-speculative (one token per
    # row per target dispatch); a random drafter can't fall below it
    assert st["accepted_tokens_per_dispatch"] >= 1.0
    _assert_drained(spec_eng)


@pytest.mark.parametrize("plen", [6, 7, 8])
def test_spec_acceptance_boundary_at_page_boundary(plen):
    """SELF-drafting (drafter == target): greedy drafts equal the target
    argmax, so every round accepts the full window and the cursor jumps
    k+1 positions — repeatedly landing ON and crossing ps=8 page edges
    (prompt lengths 6/7/8 phase the first round's accepted run to end
    just before / exactly at / just past the boundary, with page growth
    happening MID-verify). Output must still equal GreedyDecoder, and the
    acceptance telemetry must actually show the all-accept regime."""
    mesh, model, params = _setup(1, seed=5)
    prompt = [0] + [3 + (7 * i) % 90 for i in range(plen - 1)]
    ref = GreedyDecoder(model, mesh, BUF).decode(
        params, prompt, EOS, max_total_len=len(prompt) + 14)
    eng = SpeculativeEngine(
        model, mesh, params, model, params, num_slots=1, buf_len=BUF,
        eos_id=EOS, speculate_k=4, page_size=8, prefill_chunk=8)
    eng.submit(Request(rid=0, prompt=prompt, max_new=14))
    eng.run_to_completion()
    assert eng.completed[0].tokens == ref, (plen, eng.completed[0].tokens)
    st = eng.stats()
    # self-drafting greedy: chunked-vs-single-step lowerings are
    # token-identical (PR 6's pin), so every tested draft is accepted
    assert st["acceptance_rate"] >= 0.9, st
    assert st["accepted_tokens_per_dispatch"] > 2.0, st
    # it genuinely beat one-round-per-token: 14 tokens in far fewer rounds
    assert st["spec_rounds"] < 14, st
    _assert_drained(eng)


def test_spec_preempt_resume_token_identity():
    """Three requests through a 4-page target pool (~6 pages of demand):
    decode-time page exhaustion preempts victims mid-speculation — BOTH
    page lists freed, request requeued — and the resume rebuilds target
    AND drafter caches through the chunked-prefill path. Outputs stay
    identical to uninterrupted solo decodes."""
    mesh, model, params = _setup(2, seed=3)
    dec = GreedyDecoder(model, mesh, BUF)
    prompts = [[0, 5, 9, 60, 2, 8, 33], [0, 11, 4, 7, 21, 35, 2],
               [0, 44, 17, 8, 52, 3, 71]]
    refs = [dec.decode(params, p, EOS, max_total_len=len(p) + 12)
            for p in prompts]
    dmodel, dparams = _drafter(mesh, 2)
    eng = SpeculativeEngine(
        model, mesh, params, dmodel, dparams, num_slots=3, buf_len=BUF,
        eos_id=EOS, speculate_k=2, page_size=8, num_pages=4,
        prefill_chunk=8)
    for i, p in enumerate(prompts):
        eng.submit(Request(rid=i, prompt=p, max_new=12))
    eng.run_to_completion()
    got = {r.rid: r.tokens for r in eng.completed}
    for i, ref in enumerate(refs):
        assert got[i] == ref, (i, got[i], ref)
    assert eng.stats()["preemptions"] >= 1
    _assert_drained(eng)


# ---- sampled: distribution identity (exact rejection sampling) ----

# chi-square 0.999 quantiles by df: stat above this rejects at p < 0.001
_CHI2_999 = {1: 10.828, 2: 13.816, 3: 16.266, 4: 18.467, 5: 20.515,
             6: 22.458, 7: 24.322, 8: 26.125, 9: 27.877, 10: 29.588,
             11: 31.264, 12: 32.909, 13: 34.528, 14: 36.123, 15: 37.697}


def _chi2_two_sample(a_tokens, b_tokens, vocab):
    """Two-sample chi-square over token histograms, low-count bins pooled
    (combined expected >= 10 per kept bin). Returns (stat, crit)."""
    a = np.bincount(a_tokens, minlength=vocab).astype(float)
    b = np.bincount(b_tokens, minlength=vocab).astype(float)
    comb = a + b
    order = np.argsort(-comb)
    keep = [i for i in order if comb[i] >= 10]
    rest = [i for i in order if 0 < comb[i] < 10]
    bins = [(a[i], b[i]) for i in keep]
    if rest:
        bins.append((a[rest].sum(), b[rest].sum()))
    assert len(bins) >= 2, "distribution collapsed to one bin"
    A, B = a.sum(), b.sum()
    r1, r2 = np.sqrt(B / A), np.sqrt(A / B)
    stat = sum((ai * r1 - bi * r2) ** 2 / (ai + bi) for ai, bi in bins)
    df = len(bins) - 1
    return stat, _CHI2_999[min(df, max(_CHI2_999))]


def _sampled_tokens(eng, n, seed0, max_new=3):
    for i in range(n):
        eng.submit(Request(rid=i, prompt=[0, 5, 9], max_new=max_new,
                           seed=seed0 + i))
    eng.run_to_completion()
    toks = {r.rid: r.tokens for r in eng.completed}
    assert len(toks) == n
    return toks


def test_spec_sampling_distribution_identity():
    """The Leviathan guarantee, measured: 256 fixed-seed requests through
    the SPECULATIVE engine (temperature 1.0, top_k 8, a disagreeing
    random drafter so both the accept and the residual-resample paths
    fire) vs 256 through the plain paged engine. Positions 1 and 2 of
    each stream — the tokens the accept/resample rule actually produced
    (position 0 is prefill-sampled by the same fused sampler in both) —
    must pass a two-sample chi-square at p = 0.001. Power control: the
    SAME test statistic REJECTS a genuinely different distribution
    (top_k 4), so a pass is not vacuous."""
    n = 256
    mesh, model, params = _setup(1, seed=0)
    kw = dict(num_slots=8, buf_len=BUF, eos_id=EOS, page_size=8,
              prefill_chunk=16, temperature=1.0, top_k=8)
    dmodel, dparams = _drafter(mesh, 1)
    spec = _sampled_tokens(
        SpeculativeEngine(model, mesh, params, dmodel, dparams,
                          speculate_k=2, **kw), n, seed0=1000)
    plain = _sampled_tokens(
        PagedEngine(model, mesh, params, **kw), n, seed0=5000)
    # streams that sampled EOS early end before position 2; "reached this
    # position" is itself an identically-distributed event on both sides,
    # so conditioning on it keeps the two samples comparable
    for pos in (1, 2):
        s = np.array([t[pos] for t in spec.values() if len(t) > pos])
        p = np.array([t[pos] for t in plain.values() if len(t) > pos])
        assert min(len(s), len(p)) > n // 2, (pos, len(s), len(p))
        stat, crit = _chi2_two_sample(s, p, CFG.vocab_size)
        assert stat < crit, (pos, stat, crit)
    # power control: top_k=4 concentrates mass the top_k=8 run spreads —
    # the same statistic must blow past the same critical value
    kw4 = dict(kw, top_k=4)
    ctl = _sampled_tokens(PagedEngine(model, mesh, params, **kw4),
                          128, seed0=9000)
    s = np.array([t[1] for t in spec.values() if len(t) > 1])
    c = np.array([t[1] for t in ctl.values() if len(t) > 1])
    stat, crit = _chi2_two_sample(s, c, CFG.vocab_size)
    assert stat > crit, ("power control failed to reject", stat, crit)


def test_spec_sampling_reproducible_per_request_seed():
    """A sampled request's speculative stream is a pure function of ITS
    seed: every draw folds (seed, absolute_position, stream_tag), and a
    row's round windows depend only on its own accepts — so batch mix,
    slot placement, and neighbours' speculation cannot perturb it."""
    mesh, model, params = _setup(1, seed=0)
    dmodel, dparams = _drafter(mesh, 1)
    kw = dict(num_slots=3, buf_len=BUF, eos_id=EOS, speculate_k=2,
              page_size=8, prefill_chunk=8, temperature=1.0, top_k=8)

    solo = SpeculativeEngine(model, mesh, params, dmodel, dparams, **kw)
    solo.submit(Request(rid=0, prompt=[0, 5, 17], max_new=8, seed=11))
    solo.run_to_completion()
    solo_tokens = solo.completed[0].tokens

    crowd = SpeculativeEngine(model, mesh, params, dmodel, dparams, **kw)
    crowd.submit(Request(rid=90, prompt=[0, 9, 11, 13], max_new=6, seed=4))
    crowd.step()
    crowd.submit(Request(rid=91, prompt=[0, 2], max_new=6, seed=5))
    crowd.submit(Request(rid=0, prompt=[0, 5, 17], max_new=8, seed=11))
    crowd.run_to_completion()
    assert {r.rid: r.tokens for r in crowd.completed}[0] == solo_tokens
    assert all(0 <= t < CFG.vocab_size for t in solo_tokens)


# ---- the fused-sampler satellite: host ablation draws the same tokens ----


@pytest.mark.parametrize("engine_kind", ["slot", "paged"])
def test_host_sampler_matches_fused(engine_kind):
    """`debug_host_sampler=True` re-enables the pre-fused behaviour (the
    step program hands full-vocab logits to the host, which filters and
    samples there). Greedy AND sampled tokens must be bit-identical to
    the fused in-program sampler across both engines — the pin that lets
    the fused path be the ONLY production path."""
    mesh, model, params = _setup(1, seed=2)

    def build(debug, temperature, top_k):
        if engine_kind == "slot":
            return ContinuousBatchingEngine(
                model, mesh, params, num_slots=2, buf_len=BUF, eos_id=EOS,
                prefill_bucket=8, temperature=temperature, top_k=top_k,
                debug_host_sampler=debug)
        return PagedEngine(
            model, mesh, params, num_slots=2, buf_len=BUF, eos_id=EOS,
            page_size=8, prefill_chunk=8, temperature=temperature,
            top_k=top_k, debug_host_sampler=debug)

    def drive(eng):
        for i, p in enumerate(([0, 5, 17, 33], [0, 9, 2])):
            eng.submit(Request(rid=i, prompt=p, max_new=8, seed=13 + i))
        eng.run_to_completion()
        return {r.rid: r.tokens for r in eng.completed}

    for temperature, top_k in ((0.0, 0), (1.0, 8)):
        fused = drive(build(False, temperature, top_k))
        host = drive(build(True, temperature, top_k))
        assert fused == host, (engine_kind, temperature, fused, host)


# ---- validation / refusals ----


def test_spec_refuses_invalid_configs():
    mesh, model, params = _setup(1, seed=0)
    dmodel, dparams = _drafter(mesh, 1)
    kw = dict(num_slots=2, buf_len=BUF, eos_id=EOS, page_size=8)
    # the ablation knob belongs to the NON-speculative engines
    with pytest.raises(ValueError, match="debug_host_sampler"):
        SpeculativeEngine(model, mesh, params, dmodel, dparams,
                          speculate_k=2, debug_host_sampler=True, **kw)
    with pytest.raises(ValueError, match="speculate_k"):
        SpeculativeEngine(model, mesh, params, dmodel, dparams,
                          speculate_k=0, **kw)
    # vocabularies must agree: the verify step compares p and q over one
    # token space
    wrong = Transformer(ModelConfig(attn_dim=16, ffn_dim=32, num_heads=2,
                                    num_layers=1, vocab_size=64, maxlen=64),
                        tp_size=1)
    wparams = jax.device_put(wrong.init(jax.random.key(0)),
                             wrong.shardings(mesh))
    with pytest.raises(ValueError, match="vocab"):
        SpeculativeEngine(model, mesh, params, wrong, wparams,
                          speculate_k=2, **kw)
    # a request whose worst case outgrows the DRAFTER pool is refused at
    # submit (admitted, it could deadlock drafter-page preemption)
    eng = SpeculativeEngine(model, mesh, params, dmodel, dparams,
                            speculate_k=2, drafter_pages=2, **kw)
    with pytest.raises(ValueError, match="drafter"):
        eng.submit(Request(rid=0, prompt=[0] * 12, max_new=12))


def test_serve_parser_speculate_validation():
    from distributed_pytorch_from_scratch_tpu.serving.serve import (
        get_serve_args)
    with pytest.raises(SystemExit):        # --speculate needs --paged
        get_serve_args(["--dry_run", "--speculate", "2"])
    with pytest.raises(SystemExit):        # ablation knob excludes spec
        get_serve_args(["--dry_run", "--paged", "--speculate", "2",
                        "--debug_host_sampler"])
    with pytest.raises(SystemExit):        # drafter knobs need --speculate
        get_serve_args(["--dry_run", "--paged", "--drafter_pages", "4"])
    args = get_serve_args(["--dry_run", "--paged", "--speculate", "3",
                           "--drafter_pages", "8"])
    assert args.speculate == 3 and args.drafter_pages == 8


# ---- the serve CLI smoke (tier-1: the speculative surface cannot rot) ----


def test_spec_serve_dry_run_smoke(tmp_path):
    """`serve.py --dry_run --paged --speculate 2` end-to-end on CPU: the
    acceptance telemetry must reach the summary, the JSON record, the
    `spec_decode_stats` MetricsWriter event, and summarize_run.py's
    serving section."""
    from distributed_pytorch_from_scratch_tpu.serving import serve as serve_mod

    log_dir = str(tmp_path / "serve_spec")
    summary = serve_mod.main(["--dry_run", "--paged", "--speculate", "2",
                              "--num_requests", "6", "--log_dir", log_dir])
    assert summary["completed"] == summary["requests"] > 0
    assert summary["speculate_k"] == 2
    assert summary["spec_rounds"] > 0
    assert summary["accepted_tokens_per_dispatch"] >= 1.0
    assert 0 <= summary["acceptance_rate"] <= 1
    assert len(summary["acceptance_rate_by_position"]) == 2
    assert summary["drafter_ms_total"] > 0
    assert summary["target_ms_total"] > 0
    # the event pipeline
    recs = [json.loads(l)
            for l in open(os.path.join(log_dir, "metrics.jsonl"))]
    spec_ev = next(r for r in recs if r["tag"] == "spec_decode_stats")
    assert spec_ev["speculate_k"] == 2
    assert spec_ev["drafter_num_pages"] > 0
    assert spec_ev["target_page_bytes"] > spec_ev["drafter_page_bytes"]
    # and summarize_run.py renders the speculative line
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "_sr_spec", os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "scripts", "summarize_run.py"))
    sr = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(sr)
    text = "\n".join(sr.serving_lines(str(tmp_path)))
    assert "speculative: k=2" in text
    assert "tokens/target dispatch" in text
