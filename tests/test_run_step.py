"""Unit tests for scripts/run_step.py — the hardware-session step wrapper.

VERDICT r4 #4: "failed rc=0" must be impossible; a unit test over the
wrapper's failure paths is the acceptance gate. These run the wrapper as a
real subprocess (it is itself a process supervisor) but with trivial
commands, so they are fast and TPU-free.
"""

import json
import os
import subprocess
import sys


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WRAPPER = os.path.join(REPO, "scripts", "run_step.py")


def run_wrapper(tmp_path, name, cmd, timeout=None, expect_rc=0):
    manifest = tmp_path / "manifest.jsonl"
    argv = [sys.executable, WRAPPER, "--manifest", str(manifest),
            "--name", name]
    if timeout is not None:
        argv += ["--timeout", str(timeout)]
    argv += ["--"] + cmd
    # hermetic against an operator shell's exported session deadline
    env = {**os.environ}
    env.pop("SESSION_DEADLINE", None)
    proc = subprocess.run(argv, capture_output=True, text=True, env=env)
    assert proc.returncode == expect_rc, proc.stderr
    lines = manifest.read_text().strip().splitlines()
    assert len(lines) == 1
    return json.loads(lines[0]), proc


def test_success_records_rc0_and_passes_stdout_through(tmp_path):
    rec, proc = run_wrapper(
        tmp_path, "ok-step",
        [sys.executable, "-c", "print('ARTIFACT_LINE')"])
    assert rec["rc"] == 0 and rec["timed_out"] is False
    assert rec["name"] == "ok-step"
    assert "ARTIFACT_LINE" in proc.stdout  # stdout must reach redirections


def test_failure_records_real_rc_and_stderr_tail(tmp_path):
    rec, proc = run_wrapper(
        tmp_path, "bad-flag",
        [sys.executable, "-c",
         "import sys; print('boom: unrecognized arguments', file=sys.stderr);"
         "sys.exit(2)"],
        expect_rc=2)
    assert rec["rc"] == 2 and rec["timed_out"] is False
    assert "unrecognized arguments" in rec["stderr_tail"]
    # the round-4 bug class: the wrapper's own exit code IS the step's
    assert proc.returncode == 2


def test_timeout_kills_and_records_124(tmp_path):
    rec, _ = run_wrapper(
        tmp_path, "hang",
        [sys.executable, "-c", "import time; time.sleep(60)"],
        timeout=1.5, expect_rc=124)
    assert rec["rc"] == 124 and rec["timed_out"] is True
    assert rec["secs"] < 10


def test_timeout_sends_sigterm_first_for_graceful_shutdown(tmp_path):
    """A training step that hits the step timeout must get SIGTERM (so
    train.py's preemption handler can write its shutdown checkpoint) before
    any SIGKILL — the priority-pass training slice depends on this."""
    marker = tmp_path / "graceful_checkpoint"
    child = ("import signal, sys, time\n"
             f"def h(sig, frame):\n"
             f"    open({str(marker)!r}, 'w').write('saved')\n"
             f"    sys.exit(0)\n"
             "signal.signal(signal.SIGTERM, h)\n"
             "time.sleep(60)\n")
    # timeout must exceed python's startup on this image (~2s: the axon
    # sitecustomize runs at interpreter start) or SIGTERM lands before the
    # handler is installed
    rec, _ = run_wrapper(tmp_path, "train-slice",
                         [sys.executable, "-c", child],
                         timeout=8, expect_rc=124)
    assert rec["timed_out"] is True
    assert marker.exists(), "SIGTERM handler never ran (got SIGKILL?)"


def test_timeout_kills_whole_process_group(tmp_path):
    """A step that spawns its own child (bench.py's PJRT threads analogue)
    must not leave orphans holding the single-tenant chip."""
    marker = tmp_path / "orphan_alive"
    # the marker path rides argv, not a nested string literal — a tmpdir
    # containing a quote character must not produce a SyntaxError child
    inner = "import sys, time; time.sleep(5); open(sys.argv[1], 'w').write('x')"
    child = (f"import subprocess, sys, time; "
             f"subprocess.Popen([sys.executable, '-c', {inner!r}, "
             f"{str(marker)!r}]); "
             f"time.sleep(60)")
    rec, _ = run_wrapper(tmp_path, "tree-hang",
                         [sys.executable, "-c", child],
                         timeout=1.5, expect_rc=124)
    assert rec["timed_out"] is True
    import time
    time.sleep(5)  # give a surviving orphan time to write the marker
    assert not marker.exists(), "grandchild survived the group kill"


def test_stderr_tail_is_bounded(tmp_path):
    rec, _ = run_wrapper(
        tmp_path, "chatty",
        [sys.executable, "-c",
         "import sys; sys.stderr.write('x' * 100000 + 'THE_END')"])
    assert len(rec["stderr_tail"]) <= 2000
    assert rec["stderr_tail"].endswith("THE_END")


def test_usage_error_is_rc97_not_a_step_result(tmp_path):
    proc = subprocess.run(
        [sys.executable, WRAPPER, "--manifest", str(tmp_path / "m"),
         "--name", "x"],  # no `--` / command
        capture_output=True, text=True)
    assert proc.returncode == 97
    assert not (tmp_path / "m").exists()


def test_tee_duplicates_stdout_to_file(tmp_path):
    tee = tmp_path / "step.log"
    manifest = tmp_path / "manifest.jsonl"
    proc = subprocess.run(
        [sys.executable, WRAPPER, "--manifest", str(manifest),
         "--name", "teed", "--tee", str(tee), "--",
         sys.executable, "-c", "print('step 100/5000 -> avg loss 3.14')"],
        capture_output=True, text=True)
    assert proc.returncode == 0
    assert "step 100/5000" in proc.stdout  # still reaches the console
    assert "step 100/5000" in tee.read_text()  # and the artifact log


def test_manifest_appends_multiple_steps(tmp_path):
    manifest = tmp_path / "manifest.jsonl"
    for i, rc in enumerate((0, 3)):
        subprocess.run(
            [sys.executable, WRAPPER, "--manifest", str(manifest),
             "--name", f"s{i}", "--",
             sys.executable, "-c", f"import sys; sys.exit({rc})"],
            capture_output=True)
    recs = [json.loads(l) for l in manifest.read_text().splitlines()]
    assert [r["rc"] for r in recs] == [0, 3]
    assert [r["name"] for r in recs] == ["s0", "s1"]
