"""Serving v2 (paged KV cache) correctness — ISSUE 6.

The anchor contract carries over from PR 5 and tightens: PAGED greedy
decode is token-identical to the slot-granular engine AND per-prompt
`models/decode.GreedyDecoder` — across page sizes, arrival orders,
COW-shared prefixes, chunked prefill, and preempt-and-resume. The paged
lowerings (`models/decode._paged_decode_one` / `_paged_prefill_chunk`)
reuse `_decode_one`'s attend math over a gathered page view, and
per-position values depend only on the prefix, so chunking/paging/sharing
change COST and CAPACITY, never tokens.

Plus the paged-specific invariants: refcounts drain to zero after retire
(no page leak, prefix index empty), copy-on-write actually copies when a
writer hits a shared page, chunked prefill's decode stall is bounded by
one chunk (asserted via the engine's measured counter), the SLO
scheduler's deadline-class ordering / overdue rescue / tenant fairness,
and the CAPACITY win: at an equal HBM budget the paged engine admits a
mixed burst the slot engine refuses (QueueFull).
"""

import json
import os

import jax
import numpy as np
import pytest

from distributed_pytorch_from_scratch_tpu.config import MeshConfig, ModelConfig
from distributed_pytorch_from_scratch_tpu.models.decode import GreedyDecoder
from distributed_pytorch_from_scratch_tpu.models.transformer import Transformer
from distributed_pytorch_from_scratch_tpu.runtime.mesh import make_mesh
from distributed_pytorch_from_scratch_tpu.serving.engine import (
    ContinuousBatchingEngine, PagedEngine, Request)
from distributed_pytorch_from_scratch_tpu.serving.scheduler import (
    QueueFull, SLOScheduler, parse_slo_classes)

CFG = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=8, num_layers=2,
                  vocab_size=96, maxlen=64)
BUF = 32
EOS = 1

PROMPTS = [
    [0, 5, 17, 33, 60],
    [0, 95],                        # boundary vocab id
    [0, 2, 4, 6, 8, 10, 12, 14],    # page-boundary prompt at ps=8
    [0, 7],
    [0, 9, 11],
    [0, 3, 5, 7, 11, 13, 17],
]


def _setup(tp, seed=7):
    mesh = make_mesh(MeshConfig(dp=1, tp=tp))
    model = Transformer(CFG, tp_size=tp)
    params = jax.device_put(model.init(jax.random.key(seed)),
                            model.shardings(mesh))
    return mesh, model, params


def _assert_drained(eng):
    """No page leak: every page back on the free list, refcounts at zero,
    prefix index empty (deregistration followed the frees)."""
    assert eng.pool.free_pages == eng.pool.num_pages, (
        eng.pool.free_pages, eng.pool.num_pages)
    assert (eng.pool.refcount == 0).all(), eng.pool.refcount
    assert not eng.pool._children and not eng.pool._page_keys


@pytest.mark.parametrize("tp,ps", [(1, 8), (2, 8), (1, 16), (2, 16)])
def test_paged_matches_slot_and_greedy(tp, ps):
    """Staggered admissions + slot churn (6 requests through 2 slots),
    shuffled late arrivals, chunked prefill at 4 positions: every
    request's paged greedy tokens equal its solo GreedyDecoder decode AND
    the PR 5 slot engine's output."""
    mesh, model, params = _setup(tp)
    dec = GreedyDecoder(model, mesh, BUF)
    refs = [dec.decode(params, p, EOS, max_total_len=len(p) + 10)
            for p in PROMPTS]

    def drive(eng):
        reqs = [Request(rid=i, prompt=p, max_new=10)
                for i, p in enumerate(PROMPTS)]
        eng.submit(reqs[0])
        eng.submit(reqs[1])
        for _ in range(3):              # let the first two run a few tokens
            eng.step()
        for r in reversed(reqs[2:]):    # late arrivals, reversed order
            eng.submit(r)
        eng.run_to_completion()
        return {r.rid: r.tokens for r in eng.completed}

    paged = drive(PagedEngine(model, mesh, params, num_slots=2, buf_len=BUF,
                              eos_id=EOS, page_size=ps, prefill_chunk=4))
    slot = drive(ContinuousBatchingEngine(
        model, mesh, params, num_slots=2, buf_len=BUF, eos_id=EOS,
        prefill_bucket=8, max_prefill_batch=2))
    assert len(paged) == len(PROMPTS)
    for i, ref in enumerate(refs):
        assert paged[i] == ref, (tp, ps, i, paged[i], ref)
        assert paged[i] == slot[i], (tp, ps, i)


def test_paged_matches_greedy_gpt2():
    """The second model family (learned positions, LayerNorm, gelu, tied
    head) through the paged chunk/step programs."""
    from distributed_pytorch_from_scratch_tpu.models.gpt2 import (
        GPT2Transformer)
    cfg = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=4, num_layers=2,
                      vocab_size=96, maxlen=64)
    mesh = make_mesh(MeshConfig(dp=1, tp=2))
    model = GPT2Transformer(cfg, tp_size=2)
    params = jax.device_put(model.init(jax.random.key(9)),
                            model.shardings(mesh))
    prompts = [[0, 4, 8, 15], [0, 16, 23, 42, 7, 3]]
    refs = [GreedyDecoder(model, mesh, BUF).decode(
        params, p, EOS, max_total_len=len(p) + 8) for p in prompts]
    eng = PagedEngine(model, mesh, params, num_slots=2, buf_len=BUF,
                      eos_id=EOS, page_size=8, prefill_chunk=4)
    for i, p in enumerate(prompts):
        eng.submit(Request(rid=i, prompt=p, max_new=8))
    eng.run_to_completion()
    got = {r.rid: r.tokens for r in eng.completed}
    for i, ref in enumerate(refs):
        assert got[i] == ref, (i, got[i], ref)
    _assert_drained(eng)


def test_cow_shared_prefix_identity_and_drain():
    """Three requests sharing an 18-token prefix (ps=8: two full shared
    pages + a partial tail) admitted together: outputs token-identical to
    unshared solo decodes, the prefix cache actually hits, at least one
    copy-on-write materialisation happens (a writer landing in the shared
    partial tail), and after retirement every refcount drains to zero —
    no page leak."""
    mesh, model, params = _setup(2, seed=3)
    dec = GreedyDecoder(model, mesh, BUF)
    pre = [0, 7, 3, 9, 22, 41, 5, 13, 28, 31, 6, 44, 2, 19, 55, 8, 60, 12]
    assert len(pre) == 18
    prompts = [pre + [70], pre + [80], pre + [90, 33]]
    refs = [dec.decode(params, p, EOS, max_total_len=len(p) + 8)
            for p in prompts]
    eng = PagedEngine(model, mesh, params, num_slots=3, buf_len=BUF,
                      eos_id=EOS, page_size=8, prefill_chunk=32)
    for i, p in enumerate(prompts):
        eng.submit(Request(rid=i, prompt=p, max_new=8))
    eng.run_to_completion()
    got = {r.rid: r.tokens for r in eng.completed}
    for i, ref in enumerate(refs):
        assert got[i] == ref, (i, got[i], ref)
    st = eng.stats()
    assert st["prefix_hit_tokens"] >= 16, st   # >= both full shared pages
    assert st["cow_copies"] >= 1, st
    assert 0 < st["prefix_hit_rate"] < 1, st
    _assert_drained(eng)


def test_chunked_vs_whole_prefill_identity_and_stall_bound():
    """A 40-token prompt prefilled 4 positions at a time produces the same
    tokens as whole-prompt prefill (and GreedyDecoder), AND the decode
    stall bound holds: with a live stream decoding, no engine step
    dispatches more than one chunk of prefill work (the engine's measured
    `max_interleaved_prefill_positions` counter — asserted, not
    eyeballed). The live short stream finishes BEFORE the long prompt's
    first token: no head-of-line prefill."""
    mesh, model, params = _setup(1, seed=7)
    buf = 48
    rng = np.random.default_rng(5)
    long = [0] + [int(t) for t in rng.integers(3, CFG.vocab_size, size=39)]
    short = [0, 5, 9]
    dec = GreedyDecoder(model, mesh, buf)
    ref_long = dec.decode(params, long, EOS, max_total_len=len(long) + 5)
    ref_short = dec.decode(params, short, EOS, max_total_len=len(short) + 6)

    def drive(chunk):
        eng = PagedEngine(model, mesh, params, num_slots=2, buf_len=buf,
                          eos_id=EOS, page_size=8, prefill_chunk=chunk)
        eng.submit(Request(rid=0, prompt=short, max_new=6))
        eng.step()                      # short is live and decoding
        assert eng.live_requests == 1
        eng.submit(Request(rid=1, prompt=long, max_new=5))
        eng.run_to_completion()
        return eng, {r.rid: r for r in eng.completed}

    chunked, got_c = drive(chunk=4)
    whole, got_w = drive(chunk=64)      # one chunk covers the whole prompt
    for got in (got_c, got_w):
        assert got[0].tokens == ref_short, got[0].tokens
        assert got[1].tokens == ref_long, got[1].tokens
    # the stall bound: never more than one chunk of prefill between decode
    # dispatches while a stream was live
    assert 0 < chunked.max_interleaved_prefill <= 4, \
        chunked.max_interleaved_prefill
    # and the live stream was never stalled behind the 40-token prefill:
    # it finished its 6 tokens before the long prompt produced its first
    assert got_c[0].finish_t < got_c[1].first_token_t
    _assert_drained(chunked)


def test_preempt_resume_token_identity():
    """Three requests through a page pool too small for their combined
    growth (4 pages of 8 vs ~6 pages of demand): decode-time page
    exhaustion must preempt victims (pages freed, request re-queued) and
    resume them through the COW/prefill path — with outputs token-identical
    to uninterrupted solo decodes. The dropped pending token is re-derived
    by the resume prefill (same prefix -> same argmax)."""
    mesh, model, params = _setup(2, seed=3)
    dec = GreedyDecoder(model, mesh, BUF)
    prompts = [[0, 5, 9, 60, 2, 8, 33], [0, 11, 4, 7, 21, 35, 2],
               [0, 44, 17, 8, 52, 3, 71]]
    refs = [dec.decode(params, p, EOS, max_total_len=len(p) + 12)
            for p in prompts]
    eng = PagedEngine(model, mesh, params, num_slots=3, buf_len=BUF,
                      eos_id=EOS, page_size=8, num_pages=4, prefill_chunk=8)
    for i, p in enumerate(prompts):
        eng.submit(Request(rid=i, prompt=p, max_new=12))
    eng.run_to_completion()
    got = {r.rid: r.tokens for r in eng.completed}
    for i, ref in enumerate(refs):
        assert got[i] == ref, (i, got[i], ref)
    st = eng.stats()
    assert st["preemptions"] >= 1, st
    _assert_drained(eng)


def test_interleaved_prefill_no_stale_row_scribble():
    """Regression (found by ISSUE 8's capacity test): the decode dispatch
    is dense over ALL slot rows, so a slot MID-PREFILL flows through it
    with cursor 0 and whatever pending token its previous occupant left —
    and before the fix, that spurious write landed at position 0 of the
    prefilling slot's REAL page through its page table, corrupting the
    resumed/late request's prompt KV. The trigger needs (a) slot reuse (a
    fresh slot's stale token is 0, which happens to be every prompt's
    first id here, masking the bug) and (b) decode steps interleaved with
    a chunked prefill. Six requests churning through a pool sized for
    heavy preemption hit both deterministically; every output must still
    match its solo decode."""
    mesh, model, params = _setup(1, seed=3)
    dec = GreedyDecoder(model, mesh, BUF)
    prompts = [[0, i + 2, i + 3, i + 5, i + 7, 11, 13, 2] for i in range(6)]
    refs = [dec.decode(params, p, EOS, max_total_len=len(p) + 8)
            for p in prompts]
    eng = PagedEngine(model, mesh, params, num_slots=6, buf_len=BUF,
                      eos_id=EOS, page_size=8, num_pages=8, prefill_chunk=8)
    for i, p in enumerate(prompts):
        eng.submit(Request(rid=i, prompt=list(p), max_new=8))
    eng.run_to_completion()
    got = {r.rid: r.tokens for r in eng.completed}
    for i, ref in enumerate(refs):
        assert got[i] == ref, (i, got[i], ref)
    assert eng.preemptions >= 1          # the churn actually happened
    _assert_drained(eng)


def test_paged_sampling_reproducible_per_request_seed():
    """Sampled decoding through the paged path: a request's tokens are a
    pure function of ITS seed (fold_in(seed, position) draws), regardless
    of batch mix, page placement, or chunking."""
    mesh, model, params = _setup(2, seed=0)
    kw = dict(num_slots=2, buf_len=BUF, eos_id=EOS, page_size=8,
              temperature=1.0, top_k=8)

    solo = PagedEngine(model, mesh, params, prefill_chunk=64, **kw)
    solo.submit(Request(rid=0, prompt=[0, 5, 17], max_new=10, seed=11))
    solo.run_to_completion()
    solo_tokens = solo.completed[0].tokens

    crowd = PagedEngine(model, mesh, params, prefill_chunk=2, **kw)
    crowd.submit(Request(rid=90, prompt=[0, 9, 11, 13], max_new=6, seed=4))
    crowd.step()
    crowd.submit(Request(rid=91, prompt=[0, 2], max_new=6, seed=5))
    crowd.submit(Request(rid=0, prompt=[0, 5, 17], max_new=10, seed=11))
    crowd.run_to_completion()
    assert {r.rid: r.tokens for r in crowd.completed}[0] == solo_tokens
    assert all(0 <= t < CFG.vocab_size for t in solo_tokens)


def test_capacity_win_at_equal_hbm():
    """The headline: at the SAME page-pool byte budget (2 slots x 32
    tokens = 8 pages x 8 tokens), the paged engine serves a mixed burst
    the slot engine REFUSES. The slot engine's 2 rows stay leased for the
    long-runners, its queue backs up past --queue_limit and later
    submissions raise QueueFull; the paged engine admits from the queue
    into fresh slots backed by pages, so the same submissions are
    accepted and every request completes."""
    mesh, model, params = _setup(1, seed=3)
    dec = GreedyDecoder(model, mesh, BUF)
    long_reqs = [[0, 5, 9, 60, 2, 8], [0, 11, 4, 7, 21, 35]]
    shorts = [[0, 44, 17], [0, 9, 2], [0, 61, 5], [0, 3, 88]]
    prompts = long_reqs + shorts
    refs = [dec.decode(params, p, EOS, max_total_len=len(p) + 8)
            for p in prompts]

    def submit_pattern(eng):
        """2 long, drain a step, 2 short (queued), a step, 2 more short."""
        rejected = []
        eng.submit(Request(rid=0, prompt=prompts[0], max_new=8))
        eng.submit(Request(rid=1, prompt=prompts[1], max_new=8))
        eng.step()
        for rid in (2, 3):
            try:
                eng.submit(Request(rid=rid, prompt=prompts[rid], max_new=8))
            except QueueFull:
                rejected.append(rid)
        eng.step()
        for rid in (4, 5):
            try:
                eng.submit(Request(rid=rid, prompt=prompts[rid], max_new=8))
            except QueueFull:
                rejected.append(rid)
        eng.run_to_completion()
        return rejected, {r.rid: r.tokens for r in eng.completed}

    # slot engine: 2 slots x buf 32 (the whole budget pre-carved), queue
    # bounded at 2 -> the second pair of shorts is REFUSED
    slot = ContinuousBatchingEngine(model, mesh, params, num_slots=2,
                                    buf_len=BUF, eos_id=EOS,
                                    prefill_bucket=8, max_queue=2)
    slot_rejected, slot_got = submit_pattern(slot)
    assert slot_rejected, "slot engine should have refused submissions"
    assert slot.scheduler.rejected >= 1

    # paged engine: the SAME 64-token budget as 8 pages, slots past the
    # pool -> everything admits, nothing refused, all outputs exact
    paged = PagedEngine(model, mesh, params, num_slots=8, buf_len=BUF,
                        eos_id=EOS, page_size=8, num_pages=8,
                        prefill_chunk=8, max_queue=2)
    paged_rejected, paged_got = submit_pattern(paged)
    assert paged_rejected == [], paged_rejected
    assert len(paged_got) == len(prompts)
    for i, ref in enumerate(refs):
        assert paged_got[i] == ref, (i, paged_got[i], ref)
    # and it genuinely ran MORE concurrent work than the slot engine has
    # slots — the token-granular capacity win, not a scheduling accident
    assert paged.max_live > 2, paged.max_live
    _assert_drained(paged)


def test_paged_refuses_oversize_request():
    """A request whose worst-case private footprint exceeds the pool is
    refused at submit (admitted, it could deadlock preemption once it
    became the only live request)."""
    mesh, model, params = _setup(1, seed=0)
    eng = PagedEngine(model, mesh, params, num_slots=2, buf_len=BUF,
                      eos_id=EOS, page_size=8, num_pages=2)
    with pytest.raises(ValueError, match="pages"):
        eng.submit(Request(rid=0, prompt=[0] * 20, max_new=10))


# ---- SLO scheduler (pure host logic, fake clock) ----


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_slo_scheduler_class_ordering_and_fairness():
    """Deadline classes admit tighter-first; an overdue loose-class
    request is rescued by EDF (the anti-starvation bound); within a
    class, tenants are served by least accumulated service, so a flood
    interleaves with a trickle."""
    clk = _Clock()
    s = SLOScheduler(buf_len=64, classes={"interactive": 0.25, "batch": 8.0},
                     default_class="batch", clock=clk)
    # class ordering: batch arrives FIRST, interactive second -> the
    # interactive head admits first while neither is overdue
    s.submit(Request(rid=0, prompt=[0, 1], max_new=4, tenant="a",
                     slo_class="batch"))
    clk.t = 0.01
    s.submit(Request(rid=1, prompt=[0, 1], max_new=4, tenant="b",
                     slo_class="interactive"))
    assert s.peek().rid == 1
    assert s.take().rid == 1
    # overdue rescue: once the batch request blows its deadline, it beats
    # a fresh interactive arrival (EDF among the overdue)
    clk.t = 9.0
    s.submit(Request(rid=2, prompt=[0, 1], max_new=4, tenant="b",
                     slo_class="interactive"))
    assert s.take().rid == 0
    assert s.take().rid == 2
    assert s.take() is None

    # fairness: tenant a floods 3 batch requests, tenant b submits 1 —
    # b's rides second, not last (service ledger, FIFO tie-break)
    clk.t = 10.0
    for i, ten in enumerate(("a", "a", "a", "b")):
        s.submit(Request(rid=10 + i, prompt=[0, 1, 2], max_new=4,
                         tenant=ten, slo_class="batch"))
    order = [s.take().rid for _ in range(4)]
    assert order == [10, 13, 11, 12], order

    # requeue (preemption resume): front of its tenant queue, no second
    # service charge, fresh deadline
    clk.t = 11.0
    s.submit(Request(rid=20, prompt=[0, 1], max_new=4, tenant="a",
                     slo_class="batch"))
    victim = s.take()
    before = dict(s.service)
    s.requeue(victim)
    assert s.peek().rid == 20
    assert s.take().rid == 20
    assert s.service == before          # not charged twice


def test_slo_scheduler_single_tenant_class_visibility():
    """Queues are per-(tenant, class) LANES, not per-tenant: with ONE
    tenant (serve.py's default), an earlier batch arrival must not hide
    the interactive request behind it (head-only scan over per-tenant
    queues would make rule 2 inert), and a requeued fresh-deadline victim
    must not hide an overdue tighter-class request — the head-visibility
    regression that livelocked the engine's admit loop (preempt victim ->
    victim re-peeks as the only head -> re-admit -> preempt, forever)."""
    clk = _Clock()
    s = SLOScheduler(buf_len=64, classes={"interactive": 0.25, "batch": 8.0},
                     default_class="batch", clock=clk)
    # same tenant, batch FIRST: the interactive arrival must still peek
    s.submit(Request(rid=0, prompt=[0, 1], max_new=4, slo_class="batch"))
    clk.t = 0.01
    s.submit(Request(rid=1, prompt=[0, 1], max_new=4,
                     slo_class="interactive"))
    assert s.peek().rid == 1
    assert s.take().rid == 1

    # overdue-behind-victim: rid2 (interactive) blows its deadline while
    # the batch rid0 is preempt-requeued with a FRESH deadline — the
    # overdue rescue must still see rid2 through the victim
    victim = s.take()            # rid0 (only batch pending)
    assert victim.rid == 0
    clk.t = 1.0
    s.submit(Request(rid=2, prompt=[0, 1], max_new=4,
                     slo_class="interactive"))
    clk.t = 2.0                  # rid2 overdue (deadline 1.25)
    s.requeue(victim)            # fresh deadline 10.0, front of batch lane
    assert s.peek().rid == 2, "overdue interactive hidden behind victim"
    assert s.take().rid == 2
    assert s.take().rid == 0
    assert s.take() is None


def test_single_tenant_preemption_no_livelock():
    """The engine-level version of the head-visibility bug: ONE tenant,
    one slot, a batch long-runner holding it, then an interactive request
    that goes overdue. The admit loop must preempt the batch victim ONCE
    and admit the interactive request (pre-fix: the requeued victim hid
    the overdue head and admit ping-ponged forever). Both requests still
    finish token-identical to solo decodes."""
    clk = _Clock()
    mesh, model, params = _setup(1, seed=3)
    dec = GreedyDecoder(model, mesh, BUF)
    batch_p = [0, 5, 9, 60, 2, 8]
    inter_p = [0, 44, 17]
    ref_b = dec.decode(params, batch_p, EOS, max_total_len=len(batch_p) + 10)
    ref_i = dec.decode(params, inter_p, EOS, max_total_len=len(inter_p) + 6)
    eng = PagedEngine(model, mesh, params, num_slots=1, buf_len=BUF,
                      eos_id=EOS, page_size=8, num_pages=4, prefill_chunk=8,
                      slo_classes={"interactive": 0.25, "batch": 8.0},
                      default_class="batch", clock=clk)
    eng.submit(Request(rid=0, prompt=batch_p, max_new=10))
    eng.step()                       # batch admitted into the only slot
    clk.t = 1.0
    eng.submit(Request(rid=1, prompt=inter_p, max_new=6,
                       slo_class="interactive"))
    clk.t = 2.0                      # interactive now overdue
    for _ in range(200):             # bounded: a livelock would stall here
        if not eng.has_work():
            break
        eng.step()
    got = {r.rid: r.tokens for r in eng.completed}
    assert len(got) == 2, got
    assert got[0] == ref_b and got[1] == ref_i
    assert eng.stats()["preemptions"] >= 1
    _assert_drained(eng)


def test_parse_slo_classes():
    assert parse_slo_classes("interactive=0.25,batch=8") == {
        "interactive": 0.25, "batch": 8.0}
    with pytest.raises(ValueError, match="name=deadline"):
        parse_slo_classes("interactive")
    with pytest.raises(ValueError, match="> 0"):
        parse_slo_classes("x=0")
    with pytest.raises(ValueError, match="empty"):
        parse_slo_classes(" ,")


def test_slo_scheduler_validation_and_backpressure():
    s = SLOScheduler(buf_len=32, max_queue=1)
    with pytest.raises(ValueError, match="unknown SLO class"):
        s.submit(Request(rid=0, prompt=[0], max_new=1, slo_class="vip"))
    with pytest.raises(ValueError, match="leave room"):
        s.submit(Request(rid=1, prompt=[0] * 32, max_new=1))
    s.submit(Request(rid=2, prompt=[0], max_new=1))
    with pytest.raises(QueueFull, match="full"):
        s.submit(Request(rid=3, prompt=[0], max_new=1))
    assert s.rejected == 1


# ---- the paged serve CLI smoke (tier-1: the v2 surface cannot rot) ----


def test_paged_serve_dry_run_smoke(tmp_path):
    """`serve.py --dry_run --paged` end-to-end on CPU: the new
    utilization / prefix-hit / SLO-attainment metrics must reach the
    summary, the JSON record, the MetricsWriter events, and
    summarize_run.py's rendering — the acceptance criterion's full
    pipeline."""
    from distributed_pytorch_from_scratch_tpu.serving import serve as serve_mod

    log_dir = str(tmp_path / "serve_paged")
    summary = serve_mod.main(["--dry_run", "--paged", "--log_dir", log_dir])
    assert summary["completed"] == summary["requests"] > 0
    assert summary["tokens_per_sec"] > 0
    # the serving-v2 telemetry
    assert 0 < summary["kv_util_mean"] <= 1
    assert summary["prefix_hit_rate"] > 0     # dry run shares a prefix
    assert "slo_attainment" in summary
    for cls in summary["slo_attainment"].values():
        assert 0 <= cls["attained"] <= 1 and cls["completed"] > 0
    assert summary["max_interleaved_prefill_positions"] <= 8  # dry chunk
    # events reached the writer: the summary AND the page-economics event
    recs = [json.loads(l)
            for l in open(os.path.join(log_dir, "metrics.jsonl"))]
    tags = [r["tag"] for r in recs]
    assert "serving_summary" in tags
    assert "paged_kv_stats" in tags
    kv = next(r for r in recs if r["tag"] == "paged_kv_stats")
    assert kv["num_pages"] > 0 and kv["kv_util_mean"] > 0
    # per-request events carry class/tenant/preemption counts
    req_ev = next(r for r in recs if r["tag"] == "serve_request")
    assert "slo_class" in req_ev and "preemptions" in req_ev
    # chunk spans landed in the Chrome trace
    trace = json.load(open(os.path.join(log_dir, "trace.json")))
    names = {ev.get("name") for ev in trace["traceEvents"]}
    assert "prefill_chunk" in names and "decode_step" in names
    # and summarize_run.py renders the v2 line end-to-end
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "_sr_paged", os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "scripts", "summarize_run.py"))
    sr = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(sr)
    lines = sr.serving_lines(str(tmp_path))
    text = "\n".join(lines)
    assert "kv util" in text and "SLO" in text and "prefix hit" in text
