"""GOOD: early returns inside branches; every statement reachable."""


def f(x):
    if x < 0:
        return -x
    return x + 1


def g(xs):
    for x in xs:
        if x is None:
            continue
        yield x
