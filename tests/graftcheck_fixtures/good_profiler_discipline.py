"""GOOD: captures driven through the training/metrics.py owners — the
window opens/closes inside ProfilerTrace's mechanics, so stops block on
the sync value and never race another capture."""
import jax

from distributed_pytorch_from_scratch_tpu.training.metrics import (
    ProfilerTrace)


def profile_some_steps(step_fn, state, log_dir):
    trace = ProfilerTrace(log_dir, start_step=0, num_steps=4)
    for step in range(5):
        trace.maybe_start(step)
        state = step_fn(state)
        trace.maybe_stop(step, sync=state)
    jax.block_until_ready(state)
    trace.close(sync=state)
    return state
