"""GOOD: the sync happens in the HOST loop, after the program returns —
scripts/tpu_checks.py's shared-jit-wrapper idiom."""
import jax


@jax.jit
def step(x):
    return x * 2


def timed(x):
    y = step(x)
    return jax.device_get(y)          # host side: fine
