"""GOOD: the reshard path streams leaf-at-a-time.

Per-leaf device_get inside the tree-map callback keeps peak host bytes
at one leaf; NpzFile members are read lazily, one key at a time, so no
full-shard dict ever exists.
"""

import jax
import numpy as np


def reshard_to_host_streamed(tree, shard_path, write):
    jax.tree.map(lambda x: write(np.asarray(jax.device_get(x))), tree)
    with np.load(shard_path) as npz:
        for key in npz.files:
            write(npz[key])               # one member at a time
