"""GOOD: split before the second draw; fold_in per loop iteration — the
serving fold_in(seed, position) schedule in miniature."""
import jax


def sample(shape):
    key = jax.random.PRNGKey(0)
    k1, k2 = jax.random.split(key)
    a = jax.random.normal(k1, shape)
    b = jax.random.uniform(k2, shape)
    return a, b


def sample_loop(shape, n):
    key = jax.random.PRNGKey(1)
    out = []
    for i in range(n):
        step_key = jax.random.fold_in(key, i)
        out.append(jax.random.normal(step_key, shape))
    return out
