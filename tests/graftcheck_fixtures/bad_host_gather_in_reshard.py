"""BAD: a reshard path materialises the whole tree on host.

The streamed reshard discipline (reshard/apply.py): leaves cross the
host one at a time, peak host bytes bounded by the largest single leaf.
This helper gathers the ENTIRE device tree in one call, then loads
every shard member eagerly — both whole-tree materialisations.
"""

import jax
import numpy as np


def reshard_to_host(tree, shard_path):
    host = jax.device_get(tree)           # whole tree, one call
    shards = dict(np.load(shard_path))    # every member, eagerly
    return host, shards
