"""BAD: statements after an unconditional return / raise."""


def f(x):
    return x + 1
    x = x * 2          # never runs


def g(x):
    if x < 0:
        raise ValueError(x)
        return -x      # never runs
    return x
