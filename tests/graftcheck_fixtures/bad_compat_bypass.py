"""BAD: imports the experimental shard_map directly (bypasses the shim),
and uses shimmed surface names without ever loading runtime/compat.py."""
import jax
from jax.experimental.shard_map import shard_map  # noqa: F401


def size(axis):
    return jax.lax.axis_size(axis)


def smap(f, mesh, specs):
    return jax.shard_map(f, mesh=mesh, in_specs=specs, out_specs=specs)
