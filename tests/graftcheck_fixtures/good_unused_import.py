"""GOOD: every import used (including one only via an attribute chain, one
via a string annotation, and a compat-gated import in a try block)."""
import json
from typing import Optional

import numpy as np

try:
    import scipy  # noqa: F401  (optional dep, availability-gated)
except ImportError:
    scipy = None


def load(path) -> Optional[dict]:
    return json.loads(open(path).read())


def mean(xs: "np.ndarray"):
    return np.mean(xs)
