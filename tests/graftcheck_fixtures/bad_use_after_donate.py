"""BAD: the PR 3 bench bug in miniature — params read (for a FLOPs count)
AFTER being donated to the step program."""
import jax


def bench(step_raw, params, opt, batch):
    step = jax.jit(step_raw, donate_argnums=(0, 1))
    out = step(params, opt, batch)
    flops = sum(p.size for p in jax.tree.leaves(params))  # dead buffer!
    return out, flops
