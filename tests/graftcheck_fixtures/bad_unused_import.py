"""BAD: two imports nothing references."""
import json
from collections import OrderedDict

import numpy as np


def mean(xs):
    return np.mean(xs)
