"""BAD: one key consumed twice (identical draws), and a loop consuming an
outer key every iteration without fold_in."""
import jax


def sample(shape):
    key = jax.random.PRNGKey(0)
    a = jax.random.normal(key, shape)
    b = jax.random.uniform(key, shape)      # same key: correlated!
    return a, b


def sample_loop(shape, n):
    key = jax.random.PRNGKey(1)
    out = []
    for _ in range(n):
        out.append(jax.random.normal(key, shape))   # same draw, n times
    return out
