"""BAD: actuating the controller from an arbitrary call site — this
drain loop fires apply_decisions mid-batch, so a knob (prefill chunk,
pages_per_block, speculation K) moves inside the very window the
decision's evidence was measured over, tearing the attribution."""


def drain_requests(engine):
    for req in engine.pending():
        engine.step(req)
        engine.controller.apply_decisions()    # mid-window actuation!
    return engine.stats()
