"""GOOD: actuation only happens inside a registered safe point — the
`@control_safe_point` function runs on the host between capture
windows, so knobs move where no measurement is in flight and the next
window sees one consistent config."""

from distributed_pytorch_from_scratch_tpu.obs.control import (
    control_safe_point)


def drain_requests(engine):
    for req in engine.pending():
        engine.step(req)
    control_tick(engine.controller)            # the safe point, post-batch
    return engine.stats()


@control_safe_point
def control_tick(controller):
    controller.tick(0)
    controller.apply_decisions()
