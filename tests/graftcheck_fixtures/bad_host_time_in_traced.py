"""BAD: time.time() inside a scanned body — the timestamp is traced once
and baked into the program as a constant."""
import time

import jax


def run(xs):
    def body(carry, x):
        stamp = time.time()           # trace-time constant!
        return carry + x, stamp

    return jax.lax.scan(body, 0.0, xs)
