"""Known-bad / known-good snippet corpus for tests/test_graftcheck.py.

Each rule ships a `bad_<rule>.py` (must trigger exactly that rule) and a
`good_<rule>.py` (must stay clean). These files are NEVER imported — they
exist to be parsed by the linter — and the directory is excluded from the
repo sweep (`analysis/rules.EXCLUDE_DIRS`), so the deliberate violations
here never fail the clean-repo gate.
"""
