"""GOOD: wall-clock measured in the host loop around the dispatch."""
import time

import jax


def run(xs):
    def body(carry, x):
        return carry + x, carry

    t0 = time.time()
    out = jax.lax.scan(body, 0.0, xs)
    return out, time.time() - t0
