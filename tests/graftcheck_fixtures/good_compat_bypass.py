"""GOOD: the package import loads runtime/compat.py first, so the shimmed
surface is guaranteed present before any jax use."""
import jax

import distributed_pytorch_from_scratch_tpu  # noqa: F401  (loads compat)


def size(axis):
    return jax.lax.axis_size(axis)


def smap(f, mesh, specs):
    return jax.shard_map(f, mesh=mesh, in_specs=specs, out_specs=specs)
