"""BAD: raw jax.profiler.start_trace/stop_trace in a loop module — this
races the one-capture-at-a-time window mechanics ProfilerTrace owns; a
concurrent armed window's stop would truncate THIS capture (or vice
versa) into an unparseable dir."""
import jax


def profile_some_steps(step_fn, state, log_dir):
    jax.profiler.start_trace(log_dir)      # scattered start!
    for _ in range(4):
        state = step_fn(state)
    jax.block_until_ready(state)
    jax.profiler.stop_trace()              # scattered stop!
    return state
