"""BAD: numpy RNG inside a jitted function — ONE host draw frozen into the
compiled program; every step replays it."""
import numpy as np

import jax


@jax.jit
def noisy_step(x):
    noise = np.random.normal(size=x.shape)   # frozen at trace time
    return x + noise
