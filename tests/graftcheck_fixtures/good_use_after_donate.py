"""GOOD: FLOPs computed BEFORE donation, and the donated names rebound by
the call itself (the train-loop idiom) — nothing reads a dead buffer."""
import jax


def bench(step_raw, params, opt, batches):
    step = jax.jit(step_raw, donate_argnums=(0, 1))
    flops = sum(p.size for p in jax.tree.leaves(params))
    for batch in batches:
        params, opt, loss = step(params, opt, batch)
    return params, opt, loss, flops
