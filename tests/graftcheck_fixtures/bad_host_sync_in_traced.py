"""BAD: a device_get inside a jitted body — host sync at trace time, and
the r3 'honest-looking timing' lie when used around kernels."""
import jax


@jax.jit
def step(x):
    y = x * 2
    host = jax.device_get(y)          # sync inside traced code
    return y + host.sum()
