"""GOOD: randomness threaded as a jax PRNG key argument — fresh per call,
traced as data."""
import jax


@jax.jit
def noisy_step(x, key):
    noise = jax.random.normal(key, x.shape)
    return x + noise
