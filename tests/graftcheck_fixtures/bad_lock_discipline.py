"""BAD: a ring shared with host threads, appended under the lock on the
record path but drained WITHOUT it on the dump path — a torn snapshot
under exactly the anomaly the recorder exists to capture."""
import threading
from collections import deque


class Recorder:
    def __init__(self):
        self._lock = threading.Lock()
        self._ring = deque(maxlen=16)
        self.count = 0

    def record(self, ev):
        with self._lock:
            self._ring.append(ev)
            self.count += 1

    def dump(self):
        events = list(self._ring)
        self._ring.clear()            # unlocked mutation: races record()
        self.count = 0                # and so does this
        return events
