"""GOOD: every mutation of the shared ring happens under the lock
(obs/flight.py's actual discipline)."""
import threading
from collections import deque


class Recorder:
    def __init__(self):
        self._lock = threading.Lock()
        self._ring = deque(maxlen=16)
        self.count = 0

    def record(self, ev):
        with self._lock:
            self._ring.append(ev)
            self.count += 1

    def dump(self):
        with self._lock:
            events = list(self._ring)
            self._ring.clear()
            self.count = 0
        return events
