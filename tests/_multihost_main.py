"""Worker for tests/test_multihost.py: one of two cooperating processes.

Each process owns 4 virtual CPU devices; `init_multihost` wires the
jax.distributed rendezvous (the analogue of the reference's
`dist.init_process_group('nccl', 'env://')`, `/root/reference/utils.py:19-24`)
after which `jax.devices()` spans all 8 devices across both processes and
the ordinary mesh/shard_map code runs unchanged — one dp2 x tp4 train step
with per-process dp data sharding.

Usage: python tests/_multihost_main.py <process_id> <coordinator_port>
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=4")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def main() -> None:
    process_id = int(sys.argv[1])
    port = int(sys.argv[2])

    from distributed_pytorch_from_scratch_tpu.runtime.mesh import init_multihost

    init_multihost(coordinator=f"localhost:{port}", num_processes=2,
                   process_id=process_id)
    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 8, jax.device_count()
    assert jax.local_device_count() == 4, jax.local_device_count()

    from distributed_pytorch_from_scratch_tpu import (MeshConfig, ModelConfig,
                                                      Transformer, make_mesh)
    from distributed_pytorch_from_scratch_tpu.config import OptimizerConfig
    from distributed_pytorch_from_scratch_tpu.training.optim import (
        init_adam_state)
    from distributed_pytorch_from_scratch_tpu.training.train_step import (
        build_train_step)

    dp, tp = 2, 4
    mesh = make_mesh(MeshConfig(dp=dp, tp=tp))
    cfg = ModelConfig(attn_dim=64, ffn_dim=128, num_heads=4, num_layers=2,
                      vocab_size=64, maxlen=32)
    model = Transformer(cfg, tp_size=tp)

    # params: computed under jit with the global sharding — each process
    # materialises only its addressable shards (no host broadcast, unlike
    # the reference's rank-0 weight broadcast, `layers.py:38,83,116`)
    params = jax.jit(model.init,
                     out_shardings=model.shardings(mesh))(jax.random.key(0))
    opt = init_adam_state(params)

    # data: every process holds ITS dp shard only; the global array is
    # assembled from process-local data (per-process dp data sharding)
    b, t = 8, 32
    rng = np.random.RandomState(7)
    ids_global = rng.randint(0, cfg.vocab_size, size=(b, t)).astype(np.int32)
    tgt_global = np.roll(ids_global, -1, axis=1)
    pos_global = np.tile(np.arange(t, dtype=np.int32)[None, :], (b, 1))

    from jax.sharding import NamedSharding, PartitionSpec as P

    batch_sharding = NamedSharding(mesh, P(("dp", "ep"), "cp"))
    rows = b // jax.process_count()
    lo = process_id * rows

    def dist_array(global_np):
        return jax.make_array_from_process_local_data(
            batch_sharding, global_np[lo:lo + rows])

    step = build_train_step(model, mesh,
                            OptimizerConfig(lr=1e-3, warmup_steps=2,
                                            max_steps=10))
    params, opt, loss = step(params, opt, dist_array(ids_global),
                             dist_array(tgt_global), dist_array(pos_global))
    loss = float(jax.block_until_ready(loss))
    assert np.isfinite(loss), loss
    print(f"MULTIHOST-OK process={process_id} loss={loss:.6f}", flush=True)

    # Second step: ring context parallelism ACROSS the process boundary.
    # With 4 local devices per process, the cp=2 groups of a cp2 x tp4 mesh
    # place each ring peer on a different process, so the ring's
    # collective-permutes traverse the inter-process (DCN-analogue) link —
    # the reference's NCCL backend never leaves one host
    # (`/root/reference/utils.py:23`, single-host mp.spawn).
    cp_model = Transformer(cfg, tp_size=4, cp_size=2)
    cp_mesh = make_mesh(MeshConfig(cp=2, tp=4))
    cp_params = jax.jit(cp_model.init,
                       out_shardings=cp_model.shardings(cp_mesh))(
        jax.random.key(0))
    cp_batch_sh = NamedSharding(cp_mesh, P(("dp", "ep"), "cp"))
    half = t // 2
    col = half * process_id

    def dist_cols(global_np):
        # every batch row is cp-sharded over the sequence dim; this process
        # owns sequence columns [col, col+half)
        return jax.make_array_from_process_local_data(
            cp_batch_sh, global_np[:, col:col + half])

    cp_step = build_train_step(cp_model, cp_mesh,
                               OptimizerConfig(lr=1e-3, warmup_steps=2,
                                               max_steps=10))
    _, _, cp_loss = cp_step(cp_params, init_adam_state(cp_params),
                            dist_cols(ids_global), dist_cols(tgt_global),
                            dist_cols(pos_global))
    cp_loss = float(jax.block_until_ready(cp_loss))
    assert np.isfinite(cp_loss), cp_loss
    print(f"MULTIHOST-CP-OK process={process_id} loss={cp_loss:.6f}",
          flush=True)

    # Third step: the pipeline ACROSS the process boundary — stage 0 on
    # process 0's devices, stage 1 on process 1's, activations ppermuting
    # between hosts each schedule step.
    pp_model = Transformer(cfg, tp_size=4, pp_size=2, pp_microbatches=2)
    pp_mesh = make_mesh(MeshConfig(pp=2, tp=4))
    pp_params = jax.jit(pp_model.init,
                       out_shardings=pp_model.shardings(pp_mesh))(
        jax.random.key(0))
    pp_batch_sh = NamedSharding(pp_mesh, P(("dp", "ep"), "cp"))

    def dist_full(global_np):
        # batch replicated over pp: both processes provide the full array
        return jax.make_array_from_process_local_data(pp_batch_sh, global_np)

    pp_step = build_train_step(pp_model, pp_mesh,
                               OptimizerConfig(lr=1e-3, warmup_steps=2,
                                               max_steps=10))
    _, _, pp_loss = pp_step(pp_params, init_adam_state(pp_params),
                            dist_full(ids_global), dist_full(tgt_global),
                            dist_full(pos_global))
    pp_loss = float(jax.block_until_ready(pp_loss))
    assert np.isfinite(pp_loss), pp_loss
    print(f"MULTIHOST-PP-OK process={process_id} loss={pp_loss:.6f}",
          flush=True)


if __name__ == "__main__":
    main()
