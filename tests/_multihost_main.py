"""Worker for tests/test_multihost.py: one of two cooperating processes.

Each process owns 4 virtual CPU devices; `init_multihost` wires the
jax.distributed rendezvous (the analogue of the reference's
`dist.init_process_group('nccl', 'env://')`, `/root/reference/utils.py:19-24`)
after which `jax.devices()` spans all 8 devices across both processes and
the ordinary mesh/shard_map code runs unchanged — one dp2 x tp4 train step
with per-process dp data sharding.

Usage: python tests/_multihost_main.py <process_id> <coordinator_port>
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=4")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


def main() -> None:
    process_id = int(sys.argv[1])
    port = int(sys.argv[2])

    from distributed_pytorch_from_scratch_tpu.runtime.mesh import init_multihost

    init_multihost(coordinator=f"localhost:{port}", num_processes=2,
                   process_id=process_id)
    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 8, jax.device_count()
    assert jax.local_device_count() == 4, jax.local_device_count()

    from distributed_pytorch_from_scratch_tpu import (MeshConfig, ModelConfig,
                                                      Transformer, make_mesh)
    from distributed_pytorch_from_scratch_tpu.config import OptimizerConfig
    from distributed_pytorch_from_scratch_tpu.training.optim import (
        init_adam_state)
    from distributed_pytorch_from_scratch_tpu.training.train_step import (
        build_train_step)

    dp, tp = 2, 4
    mesh = make_mesh(MeshConfig(dp=dp, tp=tp))
    cfg = ModelConfig(attn_dim=64, ffn_dim=128, num_heads=4, num_layers=2,
                      vocab_size=64, maxlen=32)
    model = Transformer(cfg, tp_size=tp)

    # params: computed under jit with the global sharding — each process
    # materialises only its addressable shards (no host broadcast, unlike
    # the reference's rank-0 weight broadcast, `layers.py:38,83,116`)
    params = jax.jit(model.init,
                     out_shardings=model.shardings(mesh))(jax.random.key(0))
    opt = init_adam_state(params)

    # data: every process holds ITS dp shard only; the global array is
    # assembled from process-local data (per-process dp data sharding)
    b, t = 8, 32
    rng = np.random.RandomState(7)
    ids_global = rng.randint(0, cfg.vocab_size, size=(b, t)).astype(np.int32)
    tgt_global = np.roll(ids_global, -1, axis=1)
    pos_global = np.tile(np.arange(t, dtype=np.int32)[None, :], (b, 1))

    from jax.sharding import NamedSharding, PartitionSpec as P

    batch_sharding = NamedSharding(mesh, P(("dp", "ep"), "cp"))
    rows = b // jax.process_count()
    lo = process_id * rows

    def dist_array(global_np):
        return jax.make_array_from_process_local_data(
            batch_sharding, global_np[lo:lo + rows])

    step = build_train_step(model, mesh,
                            OptimizerConfig(lr=1e-3, warmup_steps=2,
                                            max_steps=10))
    params, opt, loss = step(params, opt, dist_array(ids_global),
                             dist_array(tgt_global), dist_array(pos_global))
    loss = float(jax.block_until_ready(loss))
    assert np.isfinite(loss), loss
    print(f"MULTIHOST-OK process={process_id} loss={loss:.6f}", flush=True)


if __name__ == "__main__":
    main()
