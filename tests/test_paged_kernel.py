"""Pallas paged-attention kernel correctness — ISSUE 14.

Two layers of pins, mirroring how the kernel is layered:

* **block-level oracle**: `ops.pallas.paged_attention.paged_attention`
  (interpret mode) against the dense attend the gather path runs — the
  gathered page view + masked softmax einsum — across page sizes,
  pages_per_block, GQA groups, chunk widths, per-row cursors/qlen, int8
  (codes, scales) pools, and the cp-adoption `pos_offset` hook. Garbage
  rows (free slots at cursor 0, pad chunk columns) must stay finite.

* **engine token identity** (the acceptance contract): a PagedEngine /
  SpeculativeEngine built with `paged_attn_impl='pallas'` (interpreter
  opt-in) emits greedy output TOKEN-IDENTICAL to the gather impl — across
  page sizes {8, 16}, kv_dtype {native, int8}, tp ∈ {1, 2}, GQA, both
  model families, speculative rounds, and preempt/COW-resume. The gather
  impl stays the oracle; a kernel bug must show up as a token diff here,
  never as a silent perf lie.

Plus the perf-attribution pins: `obs/attribution.paged_decode_hbm_bytes`
prices the pallas dispatch at exactly the gather dispatch MINUS the
gather-copy bytes (the eliminated view write+read), the bench `--serving
--paged_attn pallas` record carries the A/B with those numbers, and
`check_bench_regression` treats the bytes metric directionally (up =
fail). CLI scope refusals round it out.
"""

import importlib.util
import json
import math
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_from_scratch_tpu.config import MeshConfig, ModelConfig
from distributed_pytorch_from_scratch_tpu.models.decode import GreedyDecoder
from distributed_pytorch_from_scratch_tpu.models.transformer import Transformer
from distributed_pytorch_from_scratch_tpu.ops.pallas import paged_attention as pa
from distributed_pytorch_from_scratch_tpu.runtime.mesh import make_mesh
from distributed_pytorch_from_scratch_tpu.serving.engine import (
    PagedEngine, Request)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CFG = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=8, num_layers=2,
                  vocab_size=96, maxlen=64)
BUF, EOS = 32, 1
PROMPTS = [
    [0, 5, 17, 33, 60],
    [0, 95],
    [0, 2, 4, 6, 8, 10, 12, 14],    # page-boundary prompt at ps=8
    [0, 7],
    [0, 9, 11],
    [0, 3, 5, 7, 11, 13, 17],
]


def _load_script(name):
    path = os.path.join(REPO, "scripts", name + ".py")
    spec = importlib.util.spec_from_file_location(f"_pk_{name}", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ------------------------------------------------- block-level oracle ----


def _dense_oracle(q, k_pool, v_pool, tbl, start, ps, pos_offset=0):
    """The gather path's math: dense page view + masked f32 softmax."""
    b, h, cw, hd = q.shape
    if isinstance(k_pool, tuple):
        kc, ksc = k_pool
        vc, vsc = v_pool
        kvh = kc.shape[1]
        kview = kc[tbl].astype(jnp.float32) * ksc[tbl][..., None]
        vview = vc[tbl].astype(jnp.float32) * vsc[tbl][..., None]
    else:
        kvh = k_pool.shape[1]
        kview = k_pool[tbl].astype(jnp.float32)
        vview = v_pool[tbl].astype(jnp.float32)
    mp = tbl.shape[1]
    kview = kview.transpose(0, 2, 1, 3, 4).reshape(b, kvh, mp * ps, hd)
    vview = vview.transpose(0, 2, 1, 3, 4).reshape(b, kvh, mp * ps, hd)
    g = h // kvh
    qg = q.reshape(b, kvh, g, cw, hd).astype(jnp.float32)
    s = jnp.einsum("bkgqd,bktd->bkgqt", qg, kview) / math.sqrt(hd)
    pos = start[:, None] + jnp.arange(cw)[None, :]
    vis = (pos_offset + jnp.arange(mp * ps)[None, None, None, :, None]
           <= pos[:, None, None, None, :]).transpose(0, 1, 2, 4, 3)
    s = jnp.where(vis, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bkgqt,bktd->bkgqd", p, vview)
    return o.reshape(b, h, cw, hd)


def _pool(rng, pages, kvh, ps, hd, int8=False):
    if int8:
        kp = (jnp.asarray(rng.integers(-127, 128, (pages + 1, kvh, ps, hd)),
                          jnp.int8),
              jnp.asarray(rng.uniform(0.01, 0.05, (pages + 1, kvh, ps)),
                          jnp.float32))
        vp = (jnp.asarray(rng.integers(-127, 128, (pages + 1, kvh, ps, hd)),
                          jnp.int8),
              jnp.asarray(rng.uniform(0.01, 0.05, (pages + 1, kvh, ps)),
                          jnp.float32))
        return kp, vp
    kp = jnp.asarray(rng.normal(size=(pages + 1, kvh, ps, hd)), jnp.float32)
    vp = jnp.asarray(rng.normal(size=(pages + 1, kvh, ps, hd)), jnp.float32)
    return kp, vp


@pytest.mark.parametrize("ps,n_blk,g", [(8, 1, 1), (8, 2, 4), (16, 3, 2)])
def test_kernel_decode_matches_dense_oracle(ps, n_blk, g):
    """q_len=1 (the decode dispatch) over a scattered page walk: per-row
    cursors at page boundaries, mid-page, and 0 (the free-slot garbage
    row) — kernel == dense attend at every row, incl. odd
    pages_per_block that force a padded walk."""
    rng = np.random.default_rng(ps * 10 + n_blk + g)
    kvh, hd, mp, b = 2, 16, 4, 4
    kp, vp = _pool(rng, 10, kvh, ps, hd)
    tbl = jnp.asarray(rng.integers(0, 10, (b, mp)), jnp.int32)
    cur = jnp.asarray([ps - 1, 2 * ps, mp * ps - 1, 0], jnp.int32)
    q = jnp.asarray(rng.normal(size=(b, kvh * g, 1, hd)), jnp.float32)
    o = pa.paged_attention(q, kp, vp, tbl, cur, page_size=ps,
                           pages_per_block=n_blk, interpret=True)
    r = _dense_oracle(q, kp, vp, tbl, cur, ps)
    np.testing.assert_allclose(np.asarray(o), np.asarray(r), atol=1e-5)
    assert np.isfinite(np.asarray(o)).all()


@pytest.mark.parametrize("int8", [False, True])
def test_kernel_chunk_matches_dense_oracle(int8):
    """The chunk/verify dispatch (cw=4, per-row start + qlen): valid
    columns match the dense attend exactly; pad columns (>= qlen, whose
    page walk is skipped) stay finite garbage like the gather path."""
    rng = np.random.default_rng(7 if int8 else 3)
    ps, mp, b, kvh, g, hd, cw = 8, 4, 3, 2, 2, 16, 4
    kp, vp = _pool(rng, 10, kvh, ps, hd, int8=int8)
    tbl = jnp.asarray(rng.integers(0, 10, (b, mp)), jnp.int32)
    start = jnp.asarray([2, 9, 0], jnp.int32)
    qlen = jnp.asarray([4, 2, 1], jnp.int32)
    q = jnp.asarray(rng.normal(size=(b, kvh * g, cw, hd)), jnp.float32)
    o = np.asarray(pa.paged_attention(q, kp, vp, tbl, start, page_size=ps,
                                      qlen=qlen, pages_per_block=2,
                                      interpret=True))
    r = np.asarray(_dense_oracle(q, kp, vp, tbl, start, ps))
    for i in range(b):
        n = int(qlen[i])
        np.testing.assert_allclose(o[i, :, :n], r[i, :, :n], atol=1e-5,
                                   err_msg=f"row {i}")
    assert np.isfinite(o).all()   # pad columns: garbage, never NaN/inf


def test_kernel_pos_offset_shifts_page_positions():
    """The cp-adoption hook: `pos_offset` declares the global position of
    the LOCAL pool's first slot — a kernel over the table's SECOND half
    with pos_offset = span/2 must equal the corresponding rows of the
    whole-table attend (the exact call a cp-sharded pool makes)."""
    rng = np.random.default_rng(11)
    ps, mp, b, kvh, hd = 8, 4, 2, 2, 16
    kp, vp = _pool(rng, 10, kvh, ps, hd)
    tbl = jnp.asarray(rng.integers(0, 10, (b, mp)), jnp.int32)
    cur = jnp.asarray([mp * ps - 1, 3 * ps], jnp.int32)
    q = jnp.asarray(rng.normal(size=(b, kvh, 1, hd)), jnp.float32)
    # full attend == online-combine of the two half walks; verify the
    # SECOND half's masking uses the shifted positions by comparing its
    # standalone result against a dense oracle with the same offset
    half = tbl[:, mp // 2:]
    o = pa.paged_attention(q, kp, vp, half, cur, page_size=ps,
                           pos_offset=(mp // 2) * ps, interpret=True)
    r = _dense_oracle(q, kp, vp, half, cur, ps, pos_offset=(mp // 2) * ps)
    np.testing.assert_allclose(np.asarray(o), np.asarray(r), atol=1e-5)


# ------------------------------------------------ engine token identity --


def _setup(tp, seed=7, cfg=CFG, family="llama"):
    mesh = make_mesh(MeshConfig(dp=1, tp=tp))
    if family == "gpt2":
        from distributed_pytorch_from_scratch_tpu.models.gpt2 import (
            GPT2Transformer)
        model = GPT2Transformer(cfg, tp_size=tp)
    else:
        model = Transformer(cfg, tp_size=tp)
    params = jax.device_put(model.init(jax.random.key(seed)),
                            model.shardings(mesh))
    return mesh, model, params


def _drive(eng, prompts=PROMPTS, max_new=10, stagger=True):
    reqs = [Request(rid=i, prompt=p, max_new=max_new)
            for i, p in enumerate(prompts)]
    if stagger:
        eng.submit(reqs[0])
        eng.submit(reqs[1])
        for _ in range(3):
            eng.step()
        for r in reversed(reqs[2:]):
            eng.submit(r)
    else:
        for r in reqs:
            eng.submit(r)
    eng.run_to_completion()
    return {r.rid: r.tokens for r in eng.completed}


def _ab(mesh, model, params, **kw):
    """Gather vs pallas(interpret) through otherwise-identical engines."""
    got = {}
    for impl in ("gather", "pallas"):
        eng = PagedEngine(model, mesh, params, eos_id=EOS,
                          paged_attn_impl=impl,
                          paged_attn_interpret=impl == "pallas", **kw)
        assert eng.paged_attn_impl == impl   # interpret opt-in: no fallback
        got[impl] = _drive(eng)
    return got


@pytest.mark.parametrize("tp,ps", [(2, 8), (1, 16)])
def test_pallas_matches_gather_greedy(tp, ps):
    """The anchor: staggered admissions + slot churn + chunked prefill +
    COW sharing through 2 slots — pallas greedy tokens == gather greedy
    tokens for every request. Pairwise over tp {1,2} x ps {8,16} (the
    (2,16)/(1,8) corners add compile time, not lowering coverage: tp
    changes the collectives, ps the page walk, independently)."""
    mesh, model, params = _setup(tp)
    got = _ab(mesh, model, params, num_slots=2, buf_len=BUF,
              page_size=ps, prefill_chunk=4)
    assert len(got["pallas"]) == len(PROMPTS)
    for i in range(len(PROMPTS)):
        assert got["pallas"][i] == got["gather"][i], (tp, ps, i)


@pytest.mark.parametrize("tp", [2])
def test_pallas_matches_gather_int8_kv(tp):
    """int8 (codes, scales) pools: the kernel's FUSED dequant must emit
    the same tokens as the gather path's dequantized HBM view."""
    mesh, model, params = _setup(tp)
    got = _ab(mesh, model, params, num_slots=2, buf_len=BUF,
              page_size=8, prefill_chunk=4, kv_dtype="int8")
    for i in range(len(PROMPTS)):
        assert got["pallas"][i] == got["gather"][i], (tp, i)


def test_pallas_matches_gather_gqa():
    """Grouped-query heads (8 q heads onto 2 kv heads): the kernel's
    q-row grouping must route exactly like the gather path's reshape."""
    cfg = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=8, num_kv_heads=2,
                      num_layers=2, vocab_size=96, maxlen=64)
    mesh, model, params = _setup(2, seed=5, cfg=cfg)
    got = _ab(mesh, model, params, num_slots=2, buf_len=BUF,
              page_size=8, prefill_chunk=4)
    for i in range(len(PROMPTS)):
        assert got["pallas"][i] == got["gather"][i], i


def test_pallas_matches_gather_gpt2():
    """The second family (learned positions, LayerNorm, gelu, tied head)
    through the kernelized chunk/step programs."""
    cfg = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=4, num_layers=2,
                      vocab_size=96, maxlen=64)
    mesh, model, params = _setup(2, seed=9, cfg=cfg, family="gpt2")
    got = _ab(mesh, model, params, num_slots=2, buf_len=BUF,
              page_size=8, prefill_chunk=4)
    for i in range(len(PROMPTS)):
        assert got["pallas"][i] == got["gather"][i], i


def test_pallas_matches_gather_speculative():
    """Speculative rounds on the kernel: drafter scan, K+1 verify, and
    drafter chunk prefill all walk their page tables in place — emitted
    tokens identical to the gather-impl speculative engine (hence, by PR
    7's pin, to the plain paged engine)."""
    from distributed_pytorch_from_scratch_tpu.serving.speculative import (
        SpeculativeEngine)
    dcfg = ModelConfig(attn_dim=16, ffn_dim=32, num_heads=2, num_layers=1,
                       vocab_size=96, maxlen=64)
    mesh, model, params = _setup(2)
    dmodel = Transformer(dcfg, tp_size=2)
    dparams = jax.device_put(dmodel.init(jax.random.key(9)),
                             dmodel.shardings(mesh))
    got = {}
    for impl in ("gather", "pallas"):
        eng = SpeculativeEngine(
            model, mesh, params, dmodel, dparams, num_slots=2, buf_len=BUF,
            eos_id=EOS, speculate_k=3, page_size=8, prefill_chunk=4,
            paged_attn_impl=impl, paged_attn_interpret=impl == "pallas")
        got[impl] = _drive(eng, prompts=PROMPTS[:4], max_new=8,
                           stagger=False)
        assert eng.spec_rounds > 0
    assert got["pallas"] == got["gather"]


def test_pallas_preempt_cow_resume_identity():
    """Through page exhaustion: preempted victims resume via COW prefill
    on the kernel path with outputs token-identical to uninterrupted solo
    GreedyDecoder decodes (the PR 6 contract, now on the kernel)."""
    mesh, model, params = _setup(2, seed=3)
    dec = GreedyDecoder(model, mesh, BUF)
    prompts = [[0, 5, 9, 60, 2, 8, 33], [0, 11, 4, 7, 21, 35, 2],
               [0, 44, 17, 8, 52, 3, 71]]
    refs = [dec.decode(params, p, EOS, max_total_len=len(p) + 12)
            for p in prompts]
    eng = PagedEngine(model, mesh, params, num_slots=3, buf_len=BUF,
                      eos_id=EOS, page_size=8, num_pages=4,
                      prefill_chunk=8, paged_attn_impl="pallas",
                      paged_attn_interpret=True)
    for i, p in enumerate(prompts):
        eng.submit(Request(rid=i, prompt=p, max_new=12))
    eng.run_to_completion()
    got = {r.rid: r.tokens for r in eng.completed}
    for i, ref in enumerate(refs):
        assert got[i] == ref, (i, got[i], ref)
    assert eng.stats()["preemptions"] >= 1
    assert eng.stats()["paged_attn"] == "pallas"


# ------------------------------------------------ resolution / refusals --


def test_pallas_falls_back_to_gather_on_cpu_with_warning(capsys):
    """'pallas' without the interpreter opt-in on a non-TPU backend must
    resolve to gather — ONCE loudly, then quietly (the warning is
    per-process, the resolution per-engine)."""
    pa._warned_fallback = False
    try:
        assert pa.resolve_paged_attn_impl("pallas") == "gather"
        first = capsys.readouterr().err
        assert "falling back to the gather impl" in first
        assert pa.resolve_paged_attn_impl("pallas") == "gather"
        assert "falling back" not in capsys.readouterr().err
        assert pa.resolve_paged_attn_impl("gather") == "gather"
        assert pa.resolve_paged_attn_impl("pallas",
                                          interpret=True) == "pallas"
        with pytest.raises(ValueError, match="paged_attn impl"):
            pa.resolve_paged_attn_impl("cuda")
    finally:
        pa._warned_fallback = False


def test_serve_cli_refuses_paged_attn_without_paged():
    from distributed_pytorch_from_scratch_tpu.serving.serve import (
        get_serve_args)
    with pytest.raises(SystemExit):
        get_serve_args(["--dry_run", "--paged_attn", "pallas"])


def test_bench_cli_refuses_paged_attn_without_serving():
    import bench
    with pytest.raises(SystemExit):
        bench.parse_args(["--model", "tiny", "--paged_attn", "pallas"])


def test_paged_serve_dry_run_pallas_smoke(tmp_path):
    """--dry_run --paged --paged_attn pallas on CPU: warns, falls back to
    gather, completes, and the record says which impl actually ran."""
    p = subprocess.run(
        [sys.executable, "-m",
         "distributed_pytorch_from_scratch_tpu.serving.serve",
         "--dry_run", "--paged", "--paged_attn", "pallas",
         "--log_dir", str(tmp_path / "logs")],
        capture_output=True, text=True, timeout=500, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert p.returncode == 0, p.stderr[-2000:]
    rec = json.loads(p.stdout.strip().splitlines()[-1])
    assert rec["paged_attn"] == "gather"      # resolved, not requested
    assert "falling back to the gather impl" in p.stderr


# ------------------------------------------- pricing / A/B / gate pins ---


def test_paged_decode_hbm_bytes_drops_gather_copy():
    """The acceptance pricing: at the same dense span, pallas total ==
    gather total MINUS the gather-copy bytes (the dequantized view's HBM
    write+read); with live_tokens the kernel's block skip prices BELOW
    that. int8 pools shrink the pool-read term but the gather copy stays
    compute-dtype (the view dequantizes)."""
    from distributed_pytorch_from_scratch_tpu.obs.attribution import (
        paged_decode_hbm_bytes)
    kw = dict(slots=8, max_pages=4, page_size=16)
    g = paged_decode_hbm_bytes(CFG, paged_attn="gather", **kw)
    p = paged_decode_hbm_bytes(CFG, paged_attn="pallas", **kw)
    assert g["gather_copy_bytes"] > 0
    assert p["gather_copy_bytes"] == 0
    assert p["total_bytes"] == g["total_bytes"] - g["gather_copy_bytes"]
    # live-context skip prices strictly below the dense walk
    p_live = paged_decode_hbm_bytes(CFG, paged_attn="pallas",
                                    live_tokens=64, **kw)
    assert p_live["kv_pool_read_bytes"] < p["kv_pool_read_bytes"]
    # int8: smaller pool read, same compute-dtype gather copy
    g8 = paged_decode_hbm_bytes(CFG, paged_attn="gather", kv_dtype="int8",
                                **kw)
    assert g8["kv_pool_read_bytes"] < g["kv_pool_read_bytes"]
    assert g8["gather_copy_bytes"] == g["gather_copy_bytes"]
    # int8 weights hold the PR 8 weight-read floor
    w8 = paged_decode_hbm_bytes(CFG, paged_attn="pallas",
                                decode_weight_dtype="int8", **kw)
    assert w8["weight_bytes"] < p["weight_bytes"]
    with pytest.raises(ValueError, match="paged_attn"):
        paged_decode_hbm_bytes(CFG, paged_attn="triton", **kw)


def test_serving_bench_record_carries_kernel_ab():
    """`--serving --paged_attn pallas` must run on CPU (falling back to
    gather for BOTH arms — the record says so) and emit ONE JSON line
    whose decode-roofline fields ASSERT the gather-copy elimination:
    pallas bytes <= gather bytes - gather_copy (the ISSUE 14 acceptance
    criterion, in the record, not in prose)."""
    p = subprocess.run(
        [sys.executable, "-c", (
            "import jax; jax.config.update('jax_platforms','cpu');"
            "import bench;"
            "bench.main(['--model','tiny','--serving','--tp','1',"
            "'--slots','2','--serve_requests','3','--prompt_len','12',"
            "'--gen_tokens','6','--page_size','8','--prefill_chunk','16',"
            "'--paged_attn','pallas'])")],
        capture_output=True, text=True, timeout=500, cwd=REPO)
    assert p.returncode == 0, p.stderr[-2000:]
    lines = [ln for ln in p.stdout.strip().splitlines() if ln.strip()]
    assert len(lines) == 1, f"stdout must be ONE JSON line: {p.stdout!r}"
    rec = json.loads(lines[0])
    for key in ("paged_attn", "decode_hbm_bytes_per_step",
                "decode_hbm_bytes_gather", "decode_hbm_bytes_pallas",
                "gather_copy_bytes_per_step", "pallas_vs_gather",
                "gather_rate", "gather_ttft_ms_p95"):
        assert key in rec, (key, sorted(rec))
    assert rec["paged_attn"] == "gather"   # CPU fallback, honestly stated
    assert rec["gather_copy_bytes_per_step"] > 0
    # the asserted elimination: the kernel's priced dispatch drops AT
    # LEAST the whole gather copy (plus any dead-page skip)
    assert (rec["decode_hbm_bytes_pallas"]
            <= rec["decode_hbm_bytes_gather"]
            - rec["gather_copy_bytes_per_step"])
    # the fallen-back record prices the impl that RAN
    assert rec["decode_hbm_bytes_per_step"] == rec["decode_hbm_bytes_gather"]
    assert rec["pallas_vs_gather"] > 0


def test_gate_fails_when_decode_bytes_grow():
    """check_bench_regression treats decode_hbm_bytes_per_step
    directionally: a serving record whose per-step bytes GREW past the
    band fails even with tokens/s flat (the silent-fallback canary)."""
    gate = _load_script("check_bench_regression")
    base = {"metric": "serving tokens/sec (x)", "value": 100.0,
            "unit": "tokens/sec (serving)",
            "decode_hbm_bytes_per_step": 1_000_000}
    fresh_ok = dict(base, decode_hbm_bytes_per_step=900_000)
    fresh_bad = dict(base, decode_hbm_bytes_per_step=2_000_000)
    checks, _ = gate.metric_checks(fresh_ok, base, 10.0, 25.0)
    by = {c["field"]: c for c in checks}
    assert by["decode_hbm_bytes_per_step"]["ok"]
    assert by["decode_hbm_bytes_per_step"]["direction"] == "down"
    checks, _ = gate.metric_checks(fresh_bad, base, 10.0, 25.0)
    by = {c["field"]: c for c in checks}
    assert not by["decode_hbm_bytes_per_step"]["ok"]


def test_paged_block_config_cache_roundtrip(tmp_path, monkeypatch):
    """The autotuner table persists and reloads through the JSON cache
    (the flash BlockConfig convention, paged family): set -> save ->
    clear -> load -> same config; garbled files are ignored."""
    path = str(tmp_path / "paged_blocks.json")
    monkeypatch.setenv("PAGED_BLOCKS_CACHE", path)
    # pin the lazy once-per-process load as already-done: this test must
    # not depend on run order, and the lazy load would read the
    # developer's REAL cache (or re-read the file this test just saved)
    monkeypatch.setattr(pa, "_cache_loaded", True)
    # writer/reader key parity: the autotuner stores native entries under
    # kv_dtype=None and every float pool dtype must normalize to the SAME
    # key, else the kernel's default lookup silently misses tuned entries
    assert pa._table_key(16, 64, None) == pa._table_key(16, 64, "native")
    assert pa._table_key(16, 64, None) == pa._table_key(16, 64, jnp.float32)
    assert pa._table_key(16, 64, None) != pa._table_key(16, 64, "int8")
    key = pa._table_key(16, 64, "int8")
    try:
        pa.set_paged_block_config(16, 64, "int8", pa.PagedBlockConfig(4))
        assert pa.save_paged_block_cache() == path
        pa._PAGED_TABLE.pop(key, None)
        assert pa.get_paged_block_config(16, 64, "int8").pages_per_block == 1
        assert pa.load_paged_block_cache() >= 1
        assert pa.get_paged_block_config(16, 64, "int8").pages_per_block == 4
        # garbled cache: ignored, table keeps defaults
        with open(path, "w") as f:
            f.write("{not json")
        assert pa.load_paged_block_cache() == 0
    finally:
        pa._PAGED_TABLE.pop(key, None)


def test_autotune_paged_blocks_interpret_smoke():
    """The sweep itself runs chip-free under the interpreter (tiny shape)
    and records a winner in the table."""
    key = pa._table_key(8, 16, None)
    try:
        cfg = pa.autotune_paged_block_config(
            8, head_dim=16, slots=2, max_pages=2, kv_heads=2,
            sweep=(1, 2), iters=1, warmup=0, interpret=True)
        assert cfg.pages_per_block in (1, 2)
        assert pa.get_paged_block_config(8, 16).pages_per_block == \
            cfg.pages_per_block
    finally:
        pa._PAGED_TABLE.pop(key, None)
