"""Standalone 16-virtual-device equivalence sweep (run by test_wide_mesh.py).

A separate process because the device count is fixed at backend init: the
main suite's conftest pins 8 devices, and meshes like dp4xtp4 or dp2xcp2xtp4
need 16 to surface shape/spec bugs an 8-device mesh cannot express
(VERDICT r1 #9).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=16")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from distributed_pytorch_from_scratch_tpu.config import (  # noqa: E402
    MeshConfig, ModelConfig, OptimizerConfig)
from distributed_pytorch_from_scratch_tpu.models.transformer import (  # noqa: E402
    Transformer)
from distributed_pytorch_from_scratch_tpu.models.vanilla import (  # noqa: E402
    VanillaTransformer)
from distributed_pytorch_from_scratch_tpu.runtime.mesh import (  # noqa: E402
    make_mesh)
from distributed_pytorch_from_scratch_tpu.training.optim import (  # noqa: E402
    AdamState, init_adam_state)
from distributed_pytorch_from_scratch_tpu.training.train_step import (  # noqa: E402
    build_train_step)
from distributed_pytorch_from_scratch_tpu.training.zero import (  # noqa: E402
    zero1_moment_shardings)

CFG = ModelConfig(attn_dim=64, ffn_dim=128, num_heads=8, num_layers=2,
                  vocab_size=100, maxlen=32)  # 100: non-divisible over tp=4


def batch(key, b=8, t=16):
    ids = jax.random.randint(key, (b, t), 0, CFG.vocab_size)
    tgt = jnp.roll(ids, -1, axis=1)
    pos = jnp.tile(jnp.arange(t)[None, :], (b, 1))
    return ids, tgt, pos


def check_equivalence(dp, cp, tp, mode):
    mesh = make_mesh(MeshConfig(dp=dp, cp=cp, tp=tp))
    model = Transformer(CFG, tp_size=tp, cp_size=cp)
    oracle = VanillaTransformer(CFG)
    params = model.init(jax.random.key(0))
    ids, tgt, pos = batch(jax.random.key(1))

    loss_fn = model.make_loss(mesh, mode=mode)
    l_sh, g_sh = jax.value_and_grad(loss_fn)(params, ids, tgt, pos)
    l_ref, g_ref = jax.value_and_grad(oracle.loss)(params, ids, tgt, pos)
    np.testing.assert_allclose(float(l_sh), float(l_ref), rtol=1e-5)
    for a, b in zip(jax.tree.flatten(g_sh)[0], jax.tree.flatten(g_ref)[0]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)
    print(f"equivalence OK: dp{dp} x cp{cp} x tp{tp} mode={mode} "
          f"loss={float(l_sh):.5f}")


def check_zero1(dp, tp):
    mesh = make_mesh(MeshConfig(dp=dp, tp=tp))
    model = Transformer(CFG, tp_size=tp)
    ocfg = OptimizerConfig(lr=1e-3, warmup_steps=5, max_steps=50)
    key = jax.random.key(3)
    params_a = jax.device_put(model.init(key), model.shardings(mesh))
    params_b = jax.tree.map(jnp.copy, params_a)
    scalar = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())
    put = lambda opt, sh: jax.device_put(
        opt, AdamState(step=scalar, mu=sh, nu=sh))
    opt_a = put(init_adam_state(params_a), model.shardings(mesh))
    opt_b = put(init_adam_state(params_b), zero1_moment_shardings(model, mesh))
    step_a = build_train_step(model, mesh, ocfg)
    step_b = build_train_step(model, mesh, ocfg, zero1=True)
    for s in range(5):
        ids, tgt, pos = batch(jax.random.fold_in(key, s))
        params_a, opt_a, la = step_a(params_a, opt_a, ids, tgt, pos)
        params_b, opt_b, lb = step_b(params_b, opt_b, ids, tgt, pos)
        np.testing.assert_allclose(float(la), float(lb), rtol=1e-6, atol=1e-7)
    for a, b in zip(jax.tree.flatten(params_a)[0],
                    jax.tree.flatten(params_b)[0]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
    print(f"zero1 OK: dp{dp} x tp{tp}")


def check_moe(dp, ep, tp):
    """16-device MoE: loss/grads match the same model on a 1-device mesh."""
    cfg = ModelConfig(attn_dim=64, ffn_dim=128, num_heads=8, num_layers=2,
                      vocab_size=100, maxlen=32, num_experts=8,
                      moe_capacity_factor=8.0)
    ids, tgt, pos = batch(jax.random.key(5))
    ref = Transformer(cfg)
    params = ref.init(jax.random.key(0))
    l_ref, g_ref = jax.value_and_grad(ref.make_loss(make_mesh(MeshConfig())))(
        params, ids, tgt, pos)
    model = Transformer(cfg, tp_size=tp, ep_size=ep)
    mesh = make_mesh(MeshConfig(dp=dp, ep=ep, tp=tp))
    sp = jax.device_put(params, model.shardings(mesh))
    l_sh, g_sh = jax.value_and_grad(model.make_loss(mesh))(sp, ids, tgt, pos)
    np.testing.assert_allclose(float(l_sh), float(l_ref), rtol=1e-5)
    for a, b in zip(jax.tree.flatten(g_sh)[0], jax.tree.flatten(g_ref)[0]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)
    print(f"moe OK: dp{dp} x ep{ep} x tp{tp} loss={float(l_sh):.5f}")


def check_pipeline(dp, pp, tp, m, num_layers=2, family="llama",
                   schedule="gpipe"):
    import dataclasses

    from distributed_pytorch_from_scratch_tpu.models.gpt2 import (
        GPT2Transformer)
    from distributed_pytorch_from_scratch_tpu.models.vanilla import (
        VanillaGPT2)

    cfg = dataclasses.replace(CFG, num_layers=num_layers)
    cls = GPT2Transformer if family == "gpt2" else Transformer
    ids, tgt, pos = batch(jax.random.key(6))
    params = cls(cfg).init(jax.random.key(0))
    if family == "gpt2":
        oracle = VanillaGPT2(cfg)
        l_ref, g_ref = jax.value_and_grad(oracle.loss)(params, ids, tgt, pos)
    else:
        ref = Transformer(cfg)
        l_ref, g_ref = jax.value_and_grad(
            ref.make_loss(make_mesh(MeshConfig())))(params, ids, tgt, pos)
    model = cls(cfg, tp_size=tp, pp_size=pp, pp_microbatches=m,
                pp_schedule=schedule)
    mesh = make_mesh(MeshConfig(dp=dp, pp=pp, tp=tp))
    sp = jax.device_put(model.from_canonical(params), model.shardings(mesh))
    l_sh, g_sh = jax.value_and_grad(model.make_loss(mesh))(sp, ids, tgt, pos)
    np.testing.assert_allclose(float(l_sh), float(l_ref), rtol=1e-5)
    for a, b in zip(jax.tree.flatten(model.to_canonical(g_sh))[0],
                    jax.tree.flatten(g_ref)[0]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)
    print(f"pipeline OK: {family} dp{dp} x pp{pp} x tp{tp} m={m} "
          f"L={num_layers} schedule={schedule} loss={float(l_sh):.5f}")


def main():
    assert jax.device_count() >= 16, jax.device_count()
    check_equivalence(4, 1, 4, "vocab_parallel")
    check_equivalence(4, 1, 4, "gather")
    check_equivalence(2, 2, 4, "vocab_parallel")
    check_equivalence(1, 2, 8, "vocab_parallel")
    check_zero1(4, 4)
    check_zero1(8, 2)
    check_moe(2, 4, 2)       # 8 experts over ep=4, tp inside experts
    check_pipeline(2, 2, 4, 4)
    check_pipeline(1, 4, 4, 8, num_layers=4)       # deep pipe: 4 stages
    check_pipeline(2, 2, 4, 4, family="gpt2")      # second family, 16 dev
    # interleaved schedule at 4 stages x 2 virtual blocks, 16 devices
    check_pipeline(1, 4, 4, 8, num_layers=8, schedule="interleaved")
    print("wide-mesh sweep: ALL OK")


if __name__ == "__main__":
    main()
    sys.exit(0)
