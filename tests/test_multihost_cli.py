"""Multi-host TRAIN CLI proof: the real `python -m ..._tpu.train` entry point
runs across two cooperating processes (VERDICT r3 code-review follow-up — the
--coordinator flag must be backed by an actually multi-host-capable loop, not
just a rendezvous).

Two processes x 4 virtual CPU devices rendezvous via --coordinator and train
a dp2 x tp4 mesh for 6 steps: batches enter through
`jax.make_array_from_callback` (each process contributes the shards it owns
of the same global batch), checkpoints are all-gathered and written by
process 0 only, and resume broadcasts process 0's checkpoint to all
processes. The final average loss must match a single-process 8-device run
of the identical config bit-for-bit-close — the cross-process collectives
compute the same training trajectory the reference's NCCL world computes on
one host (`/root/reference/utils.py:19-24`, `train.py:55-151`).
"""

import json
import os
import re
import socket
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@pytest.fixture(scope="module")
def tokens_json(tmp_path_factory):
    import numpy as np
    d = tmp_path_factory.mktemp("mh_cli")
    rng = np.random.RandomState(0)
    docs = [rng.randint(3, 200, size=rng.randint(20, 60)).tolist()
            for _ in range(96)]
    path = d / "tokens.json"
    with open(path, "w") as f:
        json.dump({"train": docs[:90], "validation": docs[90:],
                   "special_ids": {"<BOS>": 0, "<EOS>": 1, "<UNK>": 2},
                   "vocab_size": 256}, f)
    return path


def _env(n_devices: int):
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONUNBUFFERED": "1",
           "XLA_FLAGS": f"--xla_force_host_platform_device_count={n_devices}"}
    # the axon sitecustomize would force the TPU platform (tests/conftest.py)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    return env


def _train_cmd(tokens, save_dir, steps, extra=()):
    return [sys.executable, "-m", "distributed_pytorch_from_scratch_tpu.train",
            "--data_path", str(tokens), "--save_dir", str(save_dir),
            "--attn_dim", "64", "--ffn_dim", "128", "--num_heads", "4",
            "--num_layers", "2", "--maxlen", "64",
            "--dp_size", "2", "--tp_size", "4",
            "--batch_size", "8", "--max_steps", str(steps),
            "--warmup_steps", "2", "--log_interval", "2",
            "--save_interval", "3", *extra]


def _final_loss(out: str) -> float:
    m = re.search(r"training finished at step \d+, avg loss ([0-9.]+)", out)
    assert m, out
    return float(m.group(1))


def _run_pair(tokens, save_dir, steps, extra=()):
    """Launch the train CLI as two rendezvousing processes; returns stdouts."""
    port = _free_port()
    mh = ["--coordinator", f"localhost:{port}", "--num_processes", "2"]
    procs = [subprocess.Popen(
        _train_cmd(tokens, save_dir, steps,
                   extra=(*extra, *mh, "--process_id", str(pid))),
        env=_env(4), cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True) for pid in (0, 1)]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=900)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        assert p.returncode == 0, f"stdout:\n{out}\nstderr:\n{err}"
        outs.append(out)
    return outs


def test_multihost_cli_matches_single_process(tokens_json, tmp_path):
    # oracle: ONE process owning all 8 devices, identical config/seed
    single = subprocess.run(
        _train_cmd(tokens_json, tmp_path / "single", 6),
        env=_env(8), cwd=REPO, capture_output=True, text=True, timeout=900)
    assert single.returncode == 0, single.stderr
    want = _final_loss(single.stdout)

    outs = _run_pair(tokens_json, tmp_path / "multi", 6)
    got = [_final_loss(o) for o in outs]
    assert got[0] == got[1], got  # both processes saw the same global loss
    assert abs(got[0] - want) < 1e-5, (got[0], want)

    # process 0 wrote the checkpoints; process 1 wrote none (same FS here,
    # so a second writer would have raced the atomic publish)
    ckpts = [f for f in os.listdir(tmp_path / "multi")
             if f.startswith("tprank-")]
    assert any("iter-6" in f for f in ckpts), ckpts

    # logs are per-process (no TB event-file clobber)
    assert (tmp_path / "multi" / "logs" / "proc0").is_dir()
    assert (tmp_path / "multi" / "logs" / "proc1").is_dir()


def test_multihost_cli_resume_broadcast(tokens_json, tmp_path):
    # 3 steps, checkpoint at 3; then resume to 6 across processes — the
    # checkpoint loads on process 0 and broadcasts (no shared-FS assumption)
    _run_pair(tokens_json, tmp_path / "mh", 3)
    outs = _run_pair(tokens_json, tmp_path / "mh", 6, extra=("--resume",))
    for out in outs:
        assert "resumed from iter 3" in out, out
    assert _final_loss(outs[0]) == _final_loss(outs[1])


def test_sigterm_to_nonzero_process_shuts_down_both(tokens_json, tmp_path):
    """ADVICE r4: the shutdown consensus must be any-of, not process-0-only.
    SIGTERM delivered ONLY to process 1 mid-run: both processes must agree,
    checkpoint, and exit 0 — under the old broadcast-of-process-0's-flag
    design process 1's signal was silently dropped and the run trained to
    completion without a shutdown checkpoint."""
    import signal
    import threading

    port = _free_port()
    mh = ["--coordinator", f"localhost:{port}", "--num_processes", "2"]
    procs = [subprocess.Popen(
        _train_cmd(tokens_json, tmp_path / "sig", 100000,
                   extra=("--log_interval", "1", *mh,
                          "--process_id", str(pid))),
        env=_env(4), cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True, bufsize=1) for pid in (0, 1)]
    outs = [[], []]
    seen_step = [threading.Event(), threading.Event()]

    def pump(i):
        for line in procs[i].stdout:
            outs[i].append(line)
            if line.startswith("step "):
                seen_step[i].set()

    threads = [threading.Thread(target=pump, args=(i,), daemon=True)
               for i in (0, 1)]
    for t in threads:
        t.start()
    try:
        for i in (0, 1):
            assert seen_step[i].wait(timeout=600), (
                f"proc {i} produced no step:\n" + "".join(outs[i]))
        procs[1].send_signal(signal.SIGTERM)  # ONLY the non-zero process
        for i in (0, 1):
            assert procs[i].wait(timeout=300) == 0, "".join(outs[i])
    finally:
        for p in procs:
            p.kill()
    for t in threads:
        t.join(timeout=10)
    for i in (0, 1):
        assert "shutdown requested: checkpointed at step" in "".join(outs[i]), (
            f"proc {i}:\n" + "".join(outs[i]))
    # the shutdown checkpoint exists (process 0 writes)
    ckpts = [f for f in os.listdir(tmp_path / "sig")
             if f.startswith("tprank-")]
    assert ckpts, os.listdir(tmp_path / "sig")


def test_multihost_eval_matches_single_process(tmp_path):
    """evaluate.py across two processes: same val-loss sweep and decodes as
    the single-process run (checkpoints broadcast from process 0, doc-mean
    losses replicated before the host fetch, process-0-only report)."""
    import json as _json
    d = tmp_path
    texts = [f"the quick brown fox jumps over the lazy dog number {i} and "
             f"great empire never falls it only sleeps" for i in range(40)]
    with open(d / "texts.json", "w") as f:
        _json.dump({"train": texts, "validation": texts[:6]}, f)
    fix = subprocess.run(
        [sys.executable, "-c", (
            "import sys; sys.path.insert(0, %r)\n"
            "from distributed_pytorch_from_scratch_tpu.data.tokenizer import "
            "pre_tokenize, train_bpe\n"
            "train_bpe(%r, %r, vocab_size=280)\n"
            "pre_tokenize(%r, %r, %r)\n" % (
                REPO, str(d / "texts.json"), str(d / "tok.json"),
                str(d / "texts.json"), str(d / "tokens.json"),
                str(d / "tok.json")))],
        env=_env(8), cwd=REPO, capture_output=True, text=True, timeout=300)
    assert fix.returncode == 0, fix.stderr

    shape = ["--attn_dim", "64", "--ffn_dim", "128", "--num_heads", "4",
             "--num_layers", "2", "--maxlen", "32"]
    tr = subprocess.run(
        [sys.executable, "-m", "distributed_pytorch_from_scratch_tpu.train",
         "--data_path", str(d / "tokens.json"), "--save_dir", str(d / "ck"),
         *shape, "--dp_size", "2", "--tp_size", "4", "--batch_size", "8",
         "--max_steps", "4", "--warmup_steps", "2", "--save_interval", "2"],
        env=_env(8), cwd=REPO, capture_output=True, text=True, timeout=900)
    assert tr.returncode == 0, tr.stderr

    eval_cmd = [sys.executable, "-m",
                "distributed_pytorch_from_scratch_tpu.evaluate",
                "--data_path", str(d / "tokens.json"),
                "--ckpt_dir", str(d / "ck"),
                "--tokenizer_path", str(d / "tok.json"), *shape,
                "--dp_size", "2", "--tp_size", "4", "--batch_size", "4",
                "--max_decode_len", "16"]
    single = subprocess.run(eval_cmd, env=_env(8), cwd=REPO,
                            capture_output=True, text=True, timeout=900)
    assert single.returncode == 0, single.stderr
    want = re.findall(r"iter (\d+): val loss ([0-9.]+)", single.stdout)
    assert want, single.stdout

    port = _free_port()
    mh = ["--coordinator", f"localhost:{port}", "--num_processes", "2"]
    procs = [subprocess.Popen(eval_cmd + mh + ["--process_id", str(pid)],
                              env=_env(4), cwd=REPO, stdout=subprocess.PIPE,
                              stderr=subprocess.PIPE, text=True)
             for pid in (0, 1)]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=900)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        assert p.returncode == 0, f"stdout:\n{out}\nstderr:\n{err}"
        outs.append(out)
    got = re.findall(r"iter (\d+): val loss ([0-9.]+)", outs[0])
    assert got == want, (got, want)
    assert "val loss" not in outs[1]  # reports are process-0-only
