"""ProfilerTrace window semantics (training/metrics.py).

The train loop's step counter can jump by steps_per_dispatch, so the
window logic must be boundary-tolerant: one trace per run, started at the
first boundary past start_step, stopped at-or-after stop_step, never
restarted. jax.profiler is monkeypatched — these are pure state-machine
tests, no real tracing."""

import jax
import jax.numpy as jnp
import pytest

from distributed_pytorch_from_scratch_tpu.training.metrics import (
    ProfilerTrace)


@pytest.fixture
def profiler_calls(monkeypatch):
    calls = []
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda d: calls.append(("start", d)))
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: calls.append(("stop",)))
    return calls


def test_window_exact_steps(tmp_path, profiler_calls):
    p = ProfilerTrace(str(tmp_path), start_step=3, num_steps=2)
    for step in range(10):
        p.maybe_start(step)
        p.maybe_stop(step + 1, sync=jnp.zeros(()))
    starts = [c for c in profiler_calls if c[0] == "start"]
    stops = [c for c in profiler_calls if c[0] == "stop"]
    assert len(starts) == 1 and len(stops) == 1
    # started at the first boundary >= start_step, stopped at stop_step
    assert profiler_calls.index(starts[0]) < profiler_calls.index(stops[0])


def test_window_cleared_in_one_dispatch_jump(tmp_path, profiler_calls):
    """steps_per_dispatch=8 can hop the whole [3, 5) window in one jump:
    the trace must still start exactly once (at step 8) and stop at the
    next boundary, covering at least num_steps."""
    p = ProfilerTrace(str(tmp_path), start_step=3, num_steps=2)
    for step in range(0, 64, 8):
        p.maybe_start(step)
        p.maybe_stop(step + 8, sync=jnp.zeros(()))
    starts = [c for c in profiler_calls if c[0] == "start"]
    stops = [c for c in profiler_calls if c[0] == "stop"]
    assert len(starts) == 1 and len(stops) == 1


def test_done_prevents_restart(tmp_path, profiler_calls):
    p = ProfilerTrace(str(tmp_path), start_step=0, num_steps=1)
    p.maybe_start(0)
    p.maybe_stop(1)
    assert p._done and not p._active
    for step in range(2, 20):
        p.maybe_start(step)  # must not re-arm
    assert len([c for c in profiler_calls if c[0] == "start"]) == 1


def test_close_mid_window_stops_cleanly(tmp_path, profiler_calls):
    p = ProfilerTrace(str(tmp_path), start_step=0, num_steps=100)
    p.maybe_start(0)
    assert p._active
    p.close(sync=jnp.zeros(()))
    assert not p._active
    assert profiler_calls == [("start", p.log_dir), ("stop",)]
    p.close()  # idempotent: no second stop
    assert profiler_calls.count(("stop",)) == 1


def test_close_without_start_is_noop(tmp_path, profiler_calls):
    p = ProfilerTrace(str(tmp_path), start_step=5, num_steps=2)
    p.maybe_stop(1)
    p.close()
    assert profiler_calls == []
