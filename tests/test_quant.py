"""Quantized wires and caches (ISSUE 8): int8 DP-reduce wire, ring_q
collective matmuls, int8 paged KV, int8 decode weights.

The pins, mirroring the PR 4 bf16-canary style:

1. Round-trip oracles for the shared quantization rule (ops/quant.py):
   per-block worst-case error amax/254, all-zero blocks EXACT, a single
   outlier poisons only its own block.
2. The int8 DP-reduce wire (`bucketed_psum(reduce_dtype=jnp.int8)` ->
   `quantized_allreduce`): grads within 2^-4 of the f32 reduce (the n
   requantizations bound), f32 OUTSIDE the wire, and a multi-step train
   run whose loss tracks the f32-wire run.
3. `tp_overlap='ring_q'` forward/backward bounds at tp in {2, 4}, kernel-
   and model-level, both families; `off`/`ring` stay exactly as before
   (their equivalence tests live in test_overlap.py and still pass).
4. int8 paged KV: greedy decode TOP-1 UNCHANGED (token-identical output)
   on a fixed prompt set with the per-step full-vocab logit deviation
   pinned, COW copies carry the scale array, refcounts drain.
5. The equal-HBM capacity win: at the SAME byte budget the int8 pool
   leases ~2x the pages — the burst the native pool PoolExhausted's on
   fits the int8 pool.
6. int8 decode weights: weight round-trip bound + engine logit deviation
   bound + outputs exact on the fixed set; CLI refusals + dry-run smoke.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributed_pytorch_from_scratch_tpu.config import (
    IGNORE_INDEX, MeshConfig, ModelConfig, OptimizerConfig)
from distributed_pytorch_from_scratch_tpu.models.decode import GreedyDecoder
from distributed_pytorch_from_scratch_tpu.models.gpt2 import GPT2Transformer
from distributed_pytorch_from_scratch_tpu.models.transformer import Transformer
from distributed_pytorch_from_scratch_tpu.ops.collectives import (
    gather_from, reduce_scatter, split_to)
from distributed_pytorch_from_scratch_tpu.ops.overlap import (
    ag_matmul, matmul_rs, quantized_allreduce)
from distributed_pytorch_from_scratch_tpu.ops.quant import (
    dequantize_decode_params, dequantize_groups, dequantize_rows,
    quantize_decode_params, quantize_groups, quantize_rows)
from distributed_pytorch_from_scratch_tpu.runtime.mesh import make_mesh
from distributed_pytorch_from_scratch_tpu.serving.engine import (
    ContinuousBatchingEngine, PagedEngine, Request)
from distributed_pytorch_from_scratch_tpu.serving.kv_manager import (
    PagedKVPool, PoolExhausted, page_bytes)
from distributed_pytorch_from_scratch_tpu.training.zero import (
    build_bucketed_grad_fn)

CFG = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=8, num_layers=2,
                  vocab_size=96, maxlen=64)
BUF, EOS = 32, 1
PROMPTS = [
    [0, 5, 17, 33, 60],
    [0, 95],
    [0, 2, 4, 6, 8, 10, 12, 14],    # page-boundary prompt at ps=8
    [0, 7],
]


def make_batch(key, batch=4, t=32, vocab=96):
    k1, k2 = jax.random.split(key)
    ids = jax.random.randint(k1, (batch, t), 0, vocab)
    tgt = jax.random.randint(k2, (batch, t), 0, vocab)
    mask = jax.random.bernoulli(jax.random.fold_in(key, 9), 0.2, (batch, t))
    tgt = jnp.where(mask, IGNORE_INDEX, tgt)
    pos = jnp.tile(jnp.arange(t)[None, :], (batch, 1))
    return ids, tgt, pos


def rel_err(a, b):
    return (float(jnp.max(jnp.abs(a - b)))
            / max(float(jnp.max(jnp.abs(b))), 1e-8))


# ------------------------------------------------- round-trip oracles ----

def test_quantize_roundtrip_oracles():
    """The shared int8 rule: per-block error <= amax/254; all-zero blocks
    exact; a single outlier inflates only its own block's error."""
    key = jax.random.key(0)
    x = jax.random.normal(key, (6, 40)) * jnp.exp(
        jax.random.normal(jax.random.fold_in(key, 1), (6, 1)))
    q, sc = quantize_rows(x)
    assert q.dtype == jnp.int8 and sc.dtype == jnp.float32
    back = dequantize_rows(q, sc, jnp.float32)
    amax = np.max(np.abs(np.asarray(x)), axis=-1)
    err = np.max(np.abs(np.asarray(back - x)), axis=-1)
    assert (err <= amax / 254 + 1e-12).all(), (err, amax / 254)

    # all-zero block: EXACT round-trip (scale falls back to 1, q = 0)
    z = jnp.zeros((3, 16))
    qz, sz = quantize_rows(z)
    assert (np.asarray(qz) == 0).all()
    assert (np.asarray(dequantize_rows(qz, sz, jnp.float32)) == 0).all()

    # grouped 1-D rule + outlier isolation: a 1e4 spike in group 0 must
    # not budge the error bound of far groups
    flat = jnp.ones((3000,)) * 0.01
    flat = flat.at[3].set(1e4)
    qg, sg = quantize_groups(flat, group=512)
    back = dequantize_groups(qg, sg, 3000, group=512)
    assert float(jnp.max(jnp.abs(back[512:] - flat[512:]))) <= 0.01 / 254
    # the spike itself round-trips within ITS block's bound
    assert abs(float(back[3]) - 1e4) <= 1e4 / 254


# ------------------------------------------------- int8 DP-reduce wire ----

def test_quantized_allreduce_matches_psum():
    """The EQuARX ring == psum within the n-requantization bound, on a
    single axis and a multi-axis product; replica-identical output (the
    optimizer contract); zeros exact."""
    mesh = make_mesh(MeshConfig(dp=4, tp=2))
    v = jax.random.normal(jax.random.key(5), (8, 3001))

    def q(z):
        return quantized_allreduce(z[0], ("dp", "tp"))

    def p(z):
        return jax.lax.psum(z[0], ("dp", "tp"))

    spec = (P(("dp", "tp")),)
    rq = jax.jit(jax.shard_map(q, mesh=mesh, in_specs=spec,
                               out_specs=P()))(v)
    rp = jax.jit(jax.shard_map(p, mesh=mesh, in_specs=spec,
                               out_specs=P()))(v)
    assert rel_err(rq, rp) < 2.0 ** -4
    # replica-identity: out_specs P() already asserts it (a diverging
    # value would fail shard_map's replication gather) — and zeros:
    r0 = jax.jit(jax.shard_map(q, mesh=mesh, in_specs=spec,
                               out_specs=P()))(jnp.zeros((8, 777)))
    assert float(jnp.max(jnp.abs(r0))) == 0.0


def test_bucketed_reduce_int8_wire_tolerance():
    """The int8-wire analogue of the bf16 2^-7 canary: grads from the
    int8-wire bucketed reducer stay f32 OUTSIDE the wire and land within
    2^-4 of the f32 reduction (n quantizations of running partials at
    dp4; tests/test_overlap.py pins the bf16 sibling)."""
    mesh = make_mesh(MeshConfig(dp=4, tp=2))
    model = Transformer(CFG, tp_size=2, sequence_parallel=True)
    params = model.init(jax.random.key(0))
    ids, tgt, pos = make_batch(jax.random.key(2), batch=8)
    _, g32 = jax.jit(build_bucketed_grad_fn(
        model, mesh, bucket_mb=1.0))(params, ids, tgt, pos)
    _, g8 = jax.jit(build_bucketed_grad_fn(
        model, mesh, bucket_mb=1.0,
        reduce_dtype=jnp.int8))(params, ids, tgt, pos)
    for a, b in zip(jax.tree.leaves(g8), jax.tree.leaves(g32)):
        assert a.dtype == jnp.float32   # wire-only compression
        scale = max(float(jnp.max(jnp.abs(b))), 1e-8)
        err = float(jnp.max(jnp.abs(a - b))) / scale
        assert err < 2.0 ** -4, f"int8 wire error {err} out of bounds"


@pytest.mark.slow
def test_int8_wire_multi_step_loss_tracks_f32():
    """A 3-step train run on the int8 wire tracks the f32-wire run's loss
    trajectory (the multi-step pin: quantization noise must not compound
    into divergence at these scales)."""
    from distributed_pytorch_from_scratch_tpu.training.optim import (
        init_adam_state)
    from distributed_pytorch_from_scratch_tpu.training.train_step import (
        build_train_step)

    mesh = make_mesh(MeshConfig(dp=4, tp=2))
    model = Transformer(CFG, tp_size=2, sequence_parallel=True)
    ocfg = OptimizerConfig()
    losses = {}
    for name, wire in (("f32", None), ("int8", jnp.int8)):
        params = jax.device_put(model.init(jax.random.key(0)),
                                model.shardings(mesh))
        opt = init_adam_state(params)
        step = build_train_step(model, mesh, ocfg,
                                dp_reduce_bucket_mb=1.0,
                                dp_reduce_dtype=wire)
        traj = []
        for i in range(3):
            ids, tgt, pos = make_batch(jax.random.key(10 + i), batch=8)
            params, opt, loss = step(params, opt, ids, tgt, pos)
            traj.append(float(loss))
        losses[name] = traj
    for a, b in zip(losses["int8"], losses["f32"]):
        assert abs(a - b) / abs(b) < 0.02, losses


# --------------------------------------------------------- ring_q bounds ----

@pytest.mark.parametrize("tp", [2, 4])
def test_ring_q_kernels_match_oracles_within_bound(tp):
    """ag_matmul/matmul_rs(quantized=True) vs the monolithic oracles:
    forward within 2^-6 relative (one rounding per gather chunk, n-1 for
    the reduce accumulator), jacrev grads within 2^-4 — and the
    UNQUANTIZED paths still match at test_overlap.py's exact tolerances
    (checked there; here we only pin the quantized deltas)."""
    mesh = make_mesh(MeshConfig(dp=1, tp=tp))
    b, t, d = 2, 8, 16
    key = jax.random.key(0)
    x = jax.random.normal(key, (b, t, d))
    w = jax.random.normal(jax.random.fold_in(key, 1), (d, 12))

    def ring_loss(x, w):
        return jnp.sum(ag_matmul(x, (w,), "tp", True)[0] ** 2)

    def mono_loss(x, w):
        return jnp.sum((gather_from(x, "tp", tiled_axis=-2) @ w) ** 2)

    specs = (P(None, "tp", None), P())
    run = lambda fn: jax.jit(jax.shard_map(fn, mesh=mesh, in_specs=specs,
                                           out_specs=P()))
    assert rel_err(run(ring_loss)(x, w), run(mono_loss)(x, w)) < 2.0 ** -6
    gq = jax.jit(jax.jacrev(jax.shard_map(
        ring_loss, mesh=mesh, in_specs=specs, out_specs=P()),
        argnums=(0, 1)))(x, w)
    gm = jax.jit(jax.jacrev(jax.shard_map(
        mono_loss, mesh=mesh, in_specs=specs, out_specs=P()),
        argnums=(0, 1)))(x, w)
    for a, bb in zip(gq, gm):
        assert rel_err(a, bb) < 2.0 ** -4

    xr = jax.random.normal(jax.random.fold_in(key, 2), (b, t, d))
    wr = jax.random.normal(jax.random.fold_in(key, 3), (d, 10))

    def rs_q(x, w):
        return matmul_rs(split_to(x, "tp"), w, "tp", True)

    def rs_m(x, w):
        return reduce_scatter(split_to(x, "tp") @ w, "tp", scatter_axis=-2)

    out = P(None, "tp", None)
    sp = (P(), P("tp", None))
    yq = jax.jit(jax.shard_map(rs_q, mesh=mesh, in_specs=sp,
                               out_specs=out))(xr, wr)
    ym = jax.jit(jax.shard_map(rs_m, mesh=mesh, in_specs=sp,
                               out_specs=out))(xr, wr)
    assert rel_err(yq, ym) < 2.0 ** -6


@pytest.mark.parametrize("family,tp", [
    ("llama", 2), ("gpt2", 2),
    pytest.param("llama", 4, marks=pytest.mark.slow),
    pytest.param("gpt2", 4, marks=pytest.mark.slow)])
def test_model_ring_q_matches_off_within_bound(family, tp):
    """tp_overlap='ring_q' loss/grads vs 'off' at the model level — the
    ISSUE 8 acceptance pin for the quantized tp wire (both families, tp
    in {2, 4}; the int8 payloads perturb the loss < 1e-4 relative and
    every grad leaf < 2^-4 at this scale)."""
    cls = GPT2Transformer if family == "gpt2" else Transformer
    cfg = CFG if family == "llama" else ModelConfig(
        attn_dim=32, ffn_dim=64, num_heads=4, num_layers=2,
        vocab_size=96, maxlen=64)
    mesh = make_mesh(MeshConfig(dp=1, tp=tp))
    mono = cls(cfg, tp_size=tp, sequence_parallel=True)
    ring = cls(cfg, tp_size=tp, sequence_parallel=True, tp_overlap="ring_q")
    params = mono.init(jax.random.key(0))
    ids, tgt, pos = make_batch(jax.random.key(2))
    l0, g0 = jax.value_and_grad(mono.make_loss(mesh))(params, ids, tgt, pos)
    l1, g1 = jax.value_and_grad(ring.make_loss(mesh))(params, ids, tgt, pos)
    assert abs(float(l1) - float(l0)) / abs(float(l0)) < 1e-4
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g0)):
        assert rel_err(a, b) < 2.0 ** -4


def test_ring_q_refusals():
    """ring_q inherits ring's scope: SP required, no MoE; unknown modes
    still refused; CLI parsers refuse the unsupported combos loudly."""
    with pytest.raises(ValueError, match="sequence_parallel"):
        Transformer(CFG, tp_size=2, tp_overlap="ring_q")
    moe_cfg = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=8,
                          num_layers=2, vocab_size=96, maxlen=64,
                          num_experts=4)
    with pytest.raises(ValueError, match="MoE"):
        Transformer(moe_cfg, tp_size=2, sequence_parallel=True,
                    tp_overlap="ring_q")
    import bench
    with pytest.raises(SystemExit):
        bench.parse_args(["--tp_overlap", "ring_q"])   # no SP
    with pytest.raises(SystemExit):
        bench.parse_args(["--dp_reduce_dtype", "int8"])  # no bucket
    with pytest.raises(SystemExit):
        bench.parse_args(["--kv_dtype", "int8"])       # no --serving
    from distributed_pytorch_from_scratch_tpu.serving.serve import (
        get_serve_args)
    with pytest.raises(SystemExit):
        get_serve_args(["--dry_run", "--kv_dtype", "int8"])  # no --paged


# ----------------------------------------------------------- int8 KV ----

def _setup(tp=1, seed=7):
    mesh = make_mesh(MeshConfig(dp=1, tp=tp))
    model = Transformer(CFG, tp_size=tp)
    params = jax.device_put(model.init(jax.random.key(seed)),
                            model.shardings(mesh))
    return mesh, model, params


def _drive(eng, prompts=PROMPTS, max_new=10):
    for i, p in enumerate(prompts):
        eng.submit(Request(rid=i, prompt=list(p), max_new=max_new))
    eng.run_to_completion()
    return {r.rid: r.tokens for r in eng.completed}


@pytest.mark.parametrize("tp", [1, 2])
def test_int8_kv_greedy_pin(tp):
    """The greedy-quality pin: int8-KV paged decode emits the SAME tokens
    as the native pool (top-1 unchanged at every step of the fixed prompt
    set) with the per-step full-vocab logit deviation bounded — captured
    through the debug-host-sampler path, which materialises the logits
    the fused sampler consumes."""
    from distributed_pytorch_from_scratch_tpu.serving import engine as em

    mesh, model, params = _setup(tp)
    dec = GreedyDecoder(model, mesh, BUF)
    refs = [dec.decode(params, p, EOS, max_total_len=len(p) + 10)
            for p in PROMPTS]

    captured = {}
    orig = em.host_sample_tokens

    def run(kv_dtype, tag):
        captured[tag] = []

        def spy(model_, logits, *a, **kw):
            captured[tag].append(np.asarray(logits))
            return orig(model_, logits, *a, **kw)

        em.host_sample_tokens = spy
        try:
            eng = PagedEngine(model, mesh, params, num_slots=2, buf_len=BUF,
                              eos_id=EOS, page_size=8, prefill_chunk=4,
                              kv_dtype=kv_dtype, debug_host_sampler=True)
            return _drive(eng)
        finally:
            em.host_sample_tokens = orig

    native = run(None, "native")
    int8 = run("int8", "int8")
    for i, ref in enumerate(refs):
        assert int8[i] == ref, (tp, i, int8[i], ref)    # top-1 unchanged
        assert native[i] == ref
    # per-step logit deviation pinned: the two runs took identical
    # trajectories, so step logits align pairwise
    assert len(captured["int8"]) == len(captured["native"])
    worst = max(float(np.max(np.abs(a - b))) for a, b in
                zip(captured["int8"], captured["native"]))
    assert worst < 0.05, worst


def test_int8_kv_cow_copies_scales_and_drains():
    """Two identical prompts with a partial tail page: the second shares
    the donor's pages, its first decode write COW-copies BOTH the codes
    and the scale array (one bucketed dispatch), outputs stay identical,
    and the pool drains to zero (scales freed through the same refcount
    path)."""
    mesh, model, params = _setup()
    eng = PagedEngine(model, mesh, params, num_slots=4, buf_len=BUF,
                      eos_id=EOS, page_size=8, prefill_chunk=16,
                      kv_dtype="int8")
    p = [0, 2, 4, 6, 8, 10, 12, 14, 3, 5]
    got = _drive(eng, [p, list(p)], max_new=6)
    assert got[0] == got[1]
    st = eng.stats()
    assert st["cow_copies"] >= 1
    assert st["prefix_hit_tokens"] > 0
    assert st["kv_dtype"] == "int8"
    assert eng.pool.free_pages == eng.pool.num_pages
    assert (eng.pool.refcount == 0).all()


def test_int8_kv_capacity_win_at_equal_hbm():
    """The ISSUE 8 capacity criterion at pool level: at the SAME byte
    budget the int8 pool leases ~2x the pages — the lease burst that
    PoolExhausted's the native pool fits the int8 pool (CFG's hd=4 f32
    pages price at exactly 2x: 16 vs 8 bytes per head-vector) — and at
    engine level the same byte budget admits the whole burst live at
    once where the native pool has to interleave."""
    mesh, model, params = _setup(seed=3)
    ps = 8
    budget = 8 * page_bytes(model.cfg, ps)            # 8 native pages
    n_native = budget // page_bytes(model.cfg, ps)
    n_int8 = budget // page_bytes(model.cfg, ps, "int8")
    assert n_int8 >= 1.8 * n_native, (n_int8, n_native)

    native = PagedKVPool(model, mesh, int(n_native), ps)
    quant = PagedKVPool(model, mesh, int(n_int8), ps, kv_dtype="int8")
    with pytest.raises(PoolExhausted):
        for _ in range(int(n_native) + 1):
            native.alloc()
    for _ in range(int(n_native) + 1):                # same burst fits
        quant.alloc()

    # engine level: 6 x 2-page requests = 12 pages live. The int8 engine
    # (16 pages at the same bytes) runs all 6 concurrently; the native
    # engine (8 pages) cannot — its max concurrent live tokens stay
    # under the burst's demand.
    prompts = [[0, i + 2, i + 3, i + 5, i + 7, 11, 13, 2] for i in range(6)]
    refs = [GreedyDecoder(model, mesh, BUF).decode(
        params, p, EOS, max_total_len=len(p) + 8) for p in prompts]

    def drive(kv_dtype, pages):
        eng = PagedEngine(model, mesh, params, num_slots=6, buf_len=BUF,
                          eos_id=EOS, page_size=ps, num_pages=int(pages),
                          prefill_chunk=8, kv_dtype=kv_dtype)
        got = _drive(eng, prompts, max_new=8)
        return eng, got

    neng, ngot = drive(None, n_native)
    qeng, qgot = drive("int8", n_int8)
    for i, ref in enumerate(refs):                    # outputs exact
        assert qgot[i] == ref, (i, qgot[i], ref)
        assert ngot[i] == ref
    assert qeng.max_live == 6                         # whole burst live
    assert qeng.max_live > neng.max_live or neng.preemptions > 0


# ---------------------------------------------------- int8 decode weights ----

def test_int8_decode_weight_roundtrip_and_specs():
    """Per-output-channel weight quantization: round-trip error bounded
    by each column's amax/254; 1-D leaves pass through untouched; the
    derived spec tree shards codes like the weight and scales like the
    weight minus its contraction dim."""
    model = Transformer(CFG, tp_size=2, sequence_parallel=True)
    params = model.init(jax.random.key(0))
    qp, qs = quantize_decode_params(params, model.specs())
    back = dequantize_decode_params(qp)
    for (pa, a), (pb, b) in zip(
            jax.tree_util.tree_leaves_with_path(params),
            jax.tree_util.tree_leaves_with_path(back)):
        if a.ndim >= 2:
            amax = np.max(np.abs(np.asarray(a)), axis=-2, keepdims=True)
            err = np.max(np.abs(np.asarray(b - a)), axis=-2, keepdims=True)
            assert (err <= amax / 254 + 1e-12).all(), pa
        else:
            assert (np.asarray(a) == np.asarray(b)).all(), pa  # untouched
    # spec shapes: lm_head weight P(None, 'tp') -> scale P(None, 'tp')
    assert qs["lm_head"]["weight"]["qweight"] == P(None, "tp")
    assert tuple(qs["lm_head"]["weight"]["scale"]) == (None, "tp")


@pytest.mark.parametrize("paged", [False, True])
def test_int8_decode_weights_engine_pin(paged):
    """Both engines serve int8 decode weights: outputs on the fixed
    prompt set stay token-identical to full-precision weights at this
    scale (logit margins dwarf the per-channel rounding), pinned so a
    quantization regression that DOES move tokens fails loudly."""
    tp = 2
    mesh, model, params = _setup(tp)
    dec = GreedyDecoder(model, mesh, BUF)
    refs = [dec.decode(params, p, EOS, max_total_len=len(p) + 10)
            for p in PROMPTS]
    if paged:
        eng = PagedEngine(model, mesh, params, num_slots=2, buf_len=BUF,
                          eos_id=EOS, page_size=8, prefill_chunk=4,
                          decode_weight_dtype="int8")
    else:
        eng = ContinuousBatchingEngine(
            model, mesh, params, num_slots=2, buf_len=BUF, eos_id=EOS,
            prefill_bucket=8, max_prefill_batch=2,
            decode_weight_dtype="int8")
    got = _drive(eng)
    for i, ref in enumerate(refs):
        assert got[i] == ref, (paged, i, got[i], ref)
    with pytest.raises(ValueError, match="decode_weight_dtype"):
        PagedEngine(model, mesh, params, num_slots=2, buf_len=BUF,
                    eos_id=EOS, decode_weight_dtype="fp4")


# ------------------------------------------------------------ CLI smoke ----

def test_quant_serve_dry_run_smoke(tmp_path):
    """`serve.py --dry_run --paged --kv_dtype int8 --decode_weight_dtype
    int8` end-to-end on CPU: the record carries both dtypes and the
    paged_kv_stats event carries kv_dtype (the rot guard for chip-less
    images, like the r9/r10 smokes)."""
    import json
    import os

    from distributed_pytorch_from_scratch_tpu.serving import serve as sm

    log_dir = str(tmp_path / "serve_quant")
    summary = sm.main(["--dry_run", "--paged", "--kv_dtype", "int8",
                       "--decode_weight_dtype", "int8",
                       "--log_dir", log_dir])
    assert summary["completed"] == summary["requests"] > 0
    assert summary["kv_dtype"] == "int8"
    recs = [json.loads(l)
            for l in open(os.path.join(log_dir, "metrics.jsonl"))]
    kv = next(r for r in recs if r["tag"] == "paged_kv_stats")
    assert kv["kv_dtype"] == "int8"
