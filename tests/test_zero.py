"""The ZeRO ladder (training/zero.py): identical math, sharded memory.

No reference counterpart (plain per-rank Adam, `/root/reference/train.py:83`;
SURVEY §2.4 "ZeRO ❌"). Invariants pinned here, on the virtual 8-device mesh:

* stage 1 — training with zero stage 1 produces bit-comparable
  params/losses to the plain path (it is a layout change, not an algorithm
  change); the moments actually live dp-sharded on device; checkpoints
  round-trip the dp-sharded state.
* stage 2 — the bucketed REDUCE-SCATTER grad path is value-parity with the
  whole-tree transpose-derived reducer at dp4 (f32 at the exact-bound
  tolerances; int8 within the PR 8 quant bound — and measurably different
  from f32, proving the quantized ring actually ran), the grads really come
  back dp-sharded, and the full train step matches plain Adam step for step.
* stage 3 — params rest dp-sharded (measured bytes/device shrink ~1/dp),
  the gather-on-demand train step's loss trajectory matches the ZeRO-1 run
  at dp2 x tp2 + SP, and the stage trains a budget the ZeRO-1 memory
  estimate refuses (the ISSUE 9 acceptance pair).
* scope — stages 2/3 refuse MoE / pp>1 / tp>1-without-SP loudly; stage 3
  refuses remat=False and a compressed --dp_reduce_dtype; --zero 2 + int8
  routes through the quantized reduce-scatter rather than silently
  falling back.
* checkpoints — dp-sharded stage-2/3 state saves through
  training/checkpoint.py + validate_checkpoint and resumes BIT-IDENTICAL
  at dp2 (feeds ROADMAP item 5's resharding story).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_from_scratch_tpu.config import (
    MeshConfig, ModelConfig, OptimizerConfig)
from distributed_pytorch_from_scratch_tpu.models.transformer import Transformer
from distributed_pytorch_from_scratch_tpu.runtime.mesh import make_mesh
from distributed_pytorch_from_scratch_tpu.training.checkpoint import (
    load_checkpoint, save_checkpoint, validate_checkpoint)
from distributed_pytorch_from_scratch_tpu.training.optim import (
    AdamState, init_adam_state)
from distributed_pytorch_from_scratch_tpu.training.train_step import (
    build_train_step)
from distributed_pytorch_from_scratch_tpu.training.zero import (
    build_bucketed_grad_fn, build_zero3_grad_fn, zero1_moment_shardings,
    zero1_specs, zero3_shardings)

CFG = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=8, num_layers=2,
                  vocab_size=96, maxlen=32)
OCFG = OptimizerConfig(lr=1e-3, warmup_steps=5, max_steps=50)
MOE_CFG = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=8, num_layers=2,
                      vocab_size=96, maxlen=64, num_experts=4)


def make_batch(key, batch=8, t=16, vocab=96):
    k1, k2 = jax.random.split(key)
    ids = jax.random.randint(k1, (batch, t), 0, vocab)
    tgt = jax.random.randint(k2, (batch, t), 0, vocab)
    pos = jnp.tile(jnp.arange(t)[None, :], (batch, 1))
    return ids, tgt, pos


def put_opt(opt, mesh, moment_sh):
    scalar = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())
    return jax.device_put(opt, AdamState(step=scalar, mu=moment_sh,
                                         nu=moment_sh))


def tree_bytes_per_device(tree) -> float:
    """Measured resident bytes per mesh device (sums addressable shards
    over the devices that hold them)."""
    leaves = jax.tree.leaves(tree)
    total = sum(sum(s.data.nbytes for s in leaf.addressable_shards)
                for leaf in leaves)
    devices = {s.device for leaf in leaves for s in leaf.addressable_shards}
    return total / max(len(devices), 1)


# ---------------------------------------------------------------- stage 1 --

@pytest.mark.parametrize("dp,tp", [(4, 2), (8, 1), (2, 4)])
def test_zero1_matches_plain_adam(dp, tp):
    mesh = make_mesh(MeshConfig(dp=dp, tp=tp))
    model = Transformer(CFG, tp_size=tp)
    key = jax.random.key(0)
    params_a = jax.device_put(model.init(key), model.shardings(mesh))
    params_b = jax.tree.map(jnp.copy, params_a)

    step_plain = build_train_step(model, mesh, OCFG)
    step_zero = build_train_step(model, mesh, OCFG, zero1=True)
    opt_a = put_opt(init_adam_state(params_a), mesh, model.shardings(mesh))
    opt_b = put_opt(init_adam_state(params_b), mesh,
                    zero1_moment_shardings(model, mesh))

    for s in range(10):
        ids, tgt, pos = make_batch(jax.random.fold_in(key, s))
        params_a, opt_a, loss_a = step_plain(params_a, opt_a, ids, tgt, pos)
        params_b, opt_b, loss_b = step_zero(params_b, opt_b, ids, tgt, pos)
        np.testing.assert_allclose(float(loss_a), float(loss_b),
                                   rtol=1e-6, atol=1e-7)

    for a, b in zip(jax.tree.flatten(params_a)[0], jax.tree.flatten(params_b)[0]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_moments_are_dp_sharded():
    dp, tp = 4, 2
    mesh = make_mesh(MeshConfig(dp=dp, tp=tp))
    model = Transformer(CFG, tp_size=tp)
    params = jax.device_put(model.init(jax.random.key(0)),
                            model.shardings(mesh))
    opt = put_opt(init_adam_state(params), mesh,
                  zero1_moment_shardings(model, mesh))
    step = build_train_step(model, mesh, OCFG, zero1=True)
    ids, tgt, pos = make_batch(jax.random.key(1))
    params, opt, _ = step(params, opt, ids, tgt, pos)

    # the big moment leaves must be dp-sharded on device after the step
    big = opt.mu["layers"]["wq"]["weight"]          # (L, d, d/tp)
    local = big.addressable_shards[0].data.size
    assert local * dp * tp == big.size, (
        f"wq moment not dp-sharded: local={local}, global={big.size}")
    # and params stay replicated over dp (sharded only over tp)
    pw = params["layers"]["wq"]["weight"]
    assert pw.addressable_shards[0].data.size * tp == pw.size


def test_zero1_specs_fallback_replicated():
    """Leaves with no free dp-divisible dim keep their param spec."""
    mesh = make_mesh(MeshConfig(dp=8, tp=1))
    import jax.sharding as shd
    P = shd.PartitionSpec
    specs = {"w": P(None, None)}
    shapes = {"w": jax.ShapeDtypeStruct((3, 5), jnp.float32)}  # nothing divides by 8
    out = zero1_specs(specs, shapes, mesh)
    assert out["w"] == P(None, None)


def test_zero1_checkpoint_roundtrip(tmp_path):
    dp, tp = 2, 2
    mesh = make_mesh(MeshConfig(dp=dp, tp=tp))
    model = Transformer(CFG, tp_size=tp)
    params = jax.device_put(model.init(jax.random.key(0)),
                            model.shardings(mesh))
    opt = put_opt(init_adam_state(params), mesh,
                  zero1_moment_shardings(model, mesh))
    step = build_train_step(model, mesh, OCFG, zero1=True)
    ids, tgt, pos = make_batch(jax.random.key(2))
    for s in range(3):
        params, opt, _ = step(params, opt, ids, tgt, pos)

    save_checkpoint(str(tmp_path), 3, 1.0, params, model.specs(), tp,
                    opt_state=opt)
    p2, opt2, it = load_checkpoint(str(tmp_path), 3, params, model.specs(),
                                   with_opt=True)
    assert it == 3
    for a, b in zip(jax.tree.flatten(opt.mu)[0], jax.tree.flatten(opt2.mu)[0]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-7)


# ---------------------------------------------------------------- stage 2 --

def test_zero2_grads_match_whole_tree_reducer():
    """ISSUE 9 acceptance: the bucketed reduce-scatter grad path at dp4 is
    value-parity with the whole-tree transpose-derived reducer (f32, exact
    bound — same tolerances as the stage-1 bucketed parity pin), AND the
    grads really come back dp-sharded (half the wire would be no win if
    every rank still materialised the full tree)."""
    dp, tp = 4, 2
    mesh = make_mesh(MeshConfig(dp=dp, tp=tp))
    model = Transformer(CFG, tp_size=tp, sequence_parallel=True)
    params = model.init(jax.random.key(0))
    ids, tgt, pos = make_batch(jax.random.key(2), t=32)
    l0, g0 = jax.jit(jax.value_and_grad(
        model.make_loss(mesh)))(params, ids, tgt, pos)
    # tiny buckets force many reduce-scatters: the schedule is exercised
    l2, g2 = jax.jit(build_bucketed_grad_fn(
        model, mesh, bucket_mb=0.001, zero_stage=2))(params, ids, tgt, pos)
    np.testing.assert_allclose(float(l2), float(l0), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(g2), jax.tree.leaves(g0)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
    # sharding: a big grad leaf holds only 1/(dp*tp) locally
    big = g2["layers"]["wq"]["weight"]
    assert big.addressable_shards[0].data.size * dp * tp == big.size, (
        "zero-2 grads must be dp-sharded, not replicated")


def test_zero2_int8_wire_within_quant_bound():
    """--zero 2 --dp_reduce_dtype int8: the bucket routes through the
    quantized reduce-scatter (PR 8's ring stopped at its RS half). Pinned
    BOTH ways: within the PR 8 bound of the f32 reduction, and NOT
    bit-identical to it — a silent f32 fallback would pass a pure
    closeness check."""
    dp = 4
    mesh = make_mesh(MeshConfig(dp=dp, tp=1))
    model = Transformer(CFG)
    params = model.init(jax.random.key(0))
    ids, tgt, pos = make_batch(jax.random.key(2), t=32)
    _, g32 = jax.jit(build_bucketed_grad_fn(
        model, mesh, bucket_mb=0.001, zero_stage=2))(params, ids, tgt, pos)
    _, g8 = jax.jit(build_bucketed_grad_fn(
        model, mesh, bucket_mb=0.001, reduce_dtype=jnp.int8,
        zero_stage=2))(params, ids, tgt, pos)
    worst, bitwise_same = 0.0, True
    for a, b in zip(jax.tree.leaves(g8), jax.tree.leaves(g32)):
        assert a.dtype == jnp.float32  # wire-only compression
        scale = max(float(jnp.max(jnp.abs(b))), 1e-8)
        err = float(jnp.max(jnp.abs(a - b))) / scale
        worst = max(worst, err)
        bitwise_same &= bool(jnp.array_equal(a, b))
    assert worst < 2.0 ** -4, f"int8 RS wire error {worst} out of bounds"
    assert not bitwise_same, (
        "int8 grads bit-identical to f32: the quantized reduce-scatter "
        "silently did not run")


def test_zero2_matches_plain_adam():
    """Full stage-2 train step (reduce-scattered grads + dp-sharded
    moments + param all-gather) is step-for-step parity with plain Adam."""
    dp, tp = 4, 2
    mesh = make_mesh(MeshConfig(dp=dp, tp=tp))
    model = Transformer(CFG, tp_size=tp, sequence_parallel=True)
    key = jax.random.key(0)
    params_a = jax.device_put(model.init(key), model.shardings(mesh))
    params_b = jax.tree.map(jnp.copy, params_a)
    step_plain = build_train_step(model, mesh, OCFG)
    step_z2 = build_train_step(model, mesh, OCFG, zero=2)
    opt_a = put_opt(init_adam_state(params_a), mesh, model.shardings(mesh))
    opt_b = put_opt(init_adam_state(params_b), mesh,
                    zero1_moment_shardings(model, mesh))
    for s in range(6):
        ids, tgt, pos = make_batch(jax.random.fold_in(key, s), t=32)
        params_a, opt_a, loss_a = step_plain(params_a, opt_a, ids, tgt, pos)
        params_b, opt_b, loss_b = step_z2(params_b, opt_b, ids, tgt, pos)
        np.testing.assert_allclose(float(loss_a), float(loss_b),
                                   rtol=1e-5, atol=1e-6)
    for a, b in zip(jax.tree.leaves(params_a), jax.tree.leaves(params_b)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------- stage 3 --

def test_zero3_param_bytes_shrink():
    """ZeRO-3's memory claim, MEASURED: params device_put at
    zero3_shardings occupy ~1/dp the per-device bytes of the replicated
    layout (slack for the few indivisible leaves)."""
    dp = 4
    mesh = make_mesh(MeshConfig(dp=dp, tp=2))
    model = Transformer(CFG, tp_size=2, sequence_parallel=True,
                        remat="dots")
    params = model.init(jax.random.key(0))
    full = tree_bytes_per_device(
        jax.device_put(params, model.shardings(mesh)))
    shard = tree_bytes_per_device(
        jax.device_put(params, zero3_shardings(model, mesh)))
    assert shard <= full / dp * 1.35, (
        f"zero-3 params not ~1/dp per device: {shard} vs full {full}")


def test_zero3_loss_trajectory_matches_zero1():
    """ISSUE 9 acceptance: 3-step loss trajectory of the gather-on-demand
    ZeRO-3 step within tolerance of the ZeRO-1 run at dp2 x tp2 + SP
    (different float summation orders — the ring gathers and the scattered
    update — so allclose, not bitwise)."""
    mesh = make_mesh(MeshConfig(dp=2, tp=2))
    model = Transformer(CFG, tp_size=2, sequence_parallel=True,
                        remat="dots")
    key = jax.random.key(0)
    init = model.init(key)
    params_1 = jax.device_put(init, model.shardings(mesh))
    params_3 = jax.device_put(init, zero3_shardings(model, mesh))
    step_1 = build_train_step(model, mesh, OCFG, zero=1)
    step_3 = build_train_step(model, mesh, OCFG, zero=3)
    opt_1 = put_opt(init_adam_state(init), mesh,
                    zero1_moment_shardings(model, mesh))
    opt_3 = put_opt(init_adam_state(init), mesh, zero3_shardings(model, mesh))
    for s in range(3):
        ids, tgt, pos = make_batch(jax.random.fold_in(key, s), t=32)
        params_1, opt_1, loss_1 = step_1(params_1, opt_1, ids, tgt, pos)
        params_3, opt_3, loss_3 = step_3(params_3, opt_3, ids, tgt, pos)
        np.testing.assert_allclose(float(loss_3), float(loss_1),
                                   rtol=1e-4, atol=1e-5)
    # params stay dp-sharded at rest after the donated step
    big = params_3["layers"]["wq"]["weight"]
    assert big.addressable_shards[0].data.size * 2 * 2 == big.size


@pytest.mark.parametrize("family", ["llama", "gpt2"])
def test_zero3_grads_match_whole_tree_reducer(family):
    """The gather-transpose grad path (no explicit dp reduction at all)
    equals the whole-tree reducer on every leaf — the stage-3 sibling of
    the stage-2 parity pin, dp2 x tp2 + SP, BOTH families (the per-layer
    gather hook lives in each family's _layer_body)."""
    from distributed_pytorch_from_scratch_tpu.models.gpt2 import (
        GPT2Transformer)
    cls = GPT2Transformer if family == "gpt2" else Transformer
    mesh = make_mesh(MeshConfig(dp=2, tp=2))
    model = cls(CFG, tp_size=2, sequence_parallel=True, remat="dots")
    params = model.init(jax.random.key(0))
    ids, tgt, pos = make_batch(jax.random.key(2), t=32)
    l0, g0 = jax.jit(jax.value_and_grad(
        model.make_loss(mesh)))(params, ids, tgt, pos)
    p3 = jax.device_put(params, zero3_shardings(model, mesh))
    l3, g3 = jax.jit(build_zero3_grad_fn(
        model, mesh, bucket_mb=0.001))(p3, ids, tgt, pos)
    np.testing.assert_allclose(float(l3), float(l0), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(g3), jax.tree.leaves(g0)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=1e-5)


def test_zero3_trains_past_zero1_budget():
    """The unlock, pinned with the estimator's own numbers: a budget that
    REFUSES the flagship shape under ZeRO-1 (even full remat exceeds it)
    fits comfortably under ZeRO-3 at dp8 — the config class item 4 exists
    for (params bigger than HBM x tp). The trajectory-parity half of the
    criterion is test_zero3_loss_trajectory_matches_zero1."""
    from distributed_pytorch_from_scratch_tpu.config import model_preset
    from distributed_pytorch_from_scratch_tpu.training.memory import (
        estimate_step_gib)
    cfg = model_preset("gpt2-355m")
    kw = dict(batch=8, seqlen=1024, tp=1, world=8, dp=8)
    z1_best = min(estimate_step_gib(cfg, remat=r, zero_stage=1, **kw)
                  for r in ("false", "dots", "true"))
    z3_dots = estimate_step_gib(cfg, remat="dots", zero_stage=3, **kw)
    budget = z1_best * 0.9  # a chip ZeRO-1 cannot fit even at full remat
    assert z1_best > budget
    assert z3_dots < budget, (
        f"zero-3 estimate {z3_dots:.2f} GiB must fit the {budget:.2f} GiB "
        f"budget zero-1 refuses (zero-1 best {z1_best:.2f})")
    # and the estimator ladder is monotone at fixed remat
    stages = [estimate_step_gib(cfg, remat="dots", zero_stage=z, **kw)
              for z in (0, 1, 2, 3)]
    assert stages == sorted(stages, reverse=True), stages


# ------------------------------------------------ scope refusals + resume --

def test_zero_scope_refusals():
    """Stages 2/3 refuse the configurations whose cotangent bookkeeping
    the static spec cannot express — loudly, at build time."""
    mesh_ep = make_mesh(MeshConfig(dp=2, ep=2, tp=2))
    with pytest.raises(ValueError, match="MoE"):
        build_bucketed_grad_fn(Transformer(MOE_CFG, tp_size=2, ep_size=2),
                               mesh_ep, zero_stage=2)
    with pytest.raises(ValueError, match="MoE"):
        build_zero3_grad_fn(Transformer(MOE_CFG, tp_size=2, ep_size=2),
                            mesh_ep)
    mesh_pp = make_mesh(MeshConfig(pp=2, tp=2))
    with pytest.raises(ValueError, match="pp_size"):
        build_zero3_grad_fn(
            Transformer(CFG, tp_size=2, pp_size=2, sequence_parallel=True),
            mesh_pp)
    mesh = make_mesh(MeshConfig(dp=2, tp=2))
    with pytest.raises(ValueError, match="sequence_parallel"):
        build_zero3_grad_fn(Transformer(CFG, tp_size=2), mesh)
    # stage 3 without remat would re-materialise the full replica as
    # backward residuals — refused, not silently absorbed
    with pytest.raises(ValueError, match="remat"):
        build_zero3_grad_fn(
            Transformer(CFG, tp_size=2, sequence_parallel=True, remat=False),
            mesh)
    # and build_bucketed_grad_fn only speaks stages 1/2
    with pytest.raises(ValueError, match="zero_stage"):
        build_bucketed_grad_fn(Transformer(CFG), mesh, zero_stage=3)


def test_zero_cli_refusals():
    """bench.py's argparse mirrors the loud scope refusals (the staged r12
    sweep parses through the same code): zero 3 never silently degrades
    the wire or drops remat, zero 2 + int8 is accepted WITHOUT an explicit
    bucket (stage 2 implies the bucketed reducer)."""
    import bench
    with pytest.raises(SystemExit) as e:
        bench.parse_args(["--zero", "3", "--dp_reduce_dtype", "int8",
                          "--dp_reduce_bucket_mb", "25"])
    assert e.value.code != 0
    with pytest.raises(SystemExit) as e:
        bench.parse_args(["--zero", "3", "--remat", "false"])
    assert e.value.code != 0
    with pytest.raises(SystemExit) as e:
        bench.parse_args(["--zero", "2", "--model", "45m-moe8"])
    assert e.value.code != 0
    # accepted: int8 wire under zero 2 with the implied default bucket
    args = bench.parse_args(["--zero", "2", "--dp_reduce_dtype", "int8",
                             "--dp", "2"])
    assert args.zero == 2 and args.dp_reduce_dtype == "int8"
    # zero 3 defaults remat to dots (never 'false')
    assert bench.parse_args(["--zero", "3", "--dp", "2"]).remat == "dots"


@pytest.mark.parametrize("stage", [2, 3])
def test_zero_checkpoint_bit_identical_resume(stage, tmp_path):
    """Save -> validate -> load -> resume is BIT-identical to the
    uninterrupted run at dp2, for dp-sharded stage-2 moments and stage-3
    params+moments alike: the checkpoint stores global arrays (no
    host-side full-tree gather — leaves stream one at a time), so
    device_put back onto the ZeRO layouts reconstructs the exact state."""
    dp, tp = 2, 2
    mesh = make_mesh(MeshConfig(dp=dp, tp=tp))
    model = Transformer(CFG, tp_size=tp, sequence_parallel=True,
                        remat="dots")
    key = jax.random.key(0)
    init = model.init(key)
    param_sh = (zero3_shardings(model, mesh) if stage == 3
                else model.shardings(mesh))
    moment_sh = (param_sh if stage == 3
                 else zero1_moment_shardings(model, mesh))
    step = build_train_step(model, mesh, OCFG, zero=stage)

    def run(params, opt, lo, hi):
        for s in range(lo, hi):
            ids, tgt, pos = make_batch(jax.random.fold_in(key, s), t=32)
            params, opt, _ = step(params, opt, ids, tgt, pos)
        return params, opt

    params = jax.device_put(init, param_sh)
    opt = put_opt(init_adam_state(init), mesh, moment_sh)
    params, opt = run(params, opt, 0, 2)
    save_checkpoint(str(tmp_path), 2, 1.0, params, model.specs(), tp,
                    opt_state=opt, zero_stage=stage)
    # uninterrupted continuation
    params_a, _ = run(jax.tree.map(jnp.copy, params),
                      jax.tree.map(jnp.copy, opt), 2, 4)
    # resumed continuation: validate -> load -> device_put at ZeRO layouts
    tp_found, _ = validate_checkpoint(str(tmp_path), 2)
    assert tp_found == tp
    p2, opt2, it = load_checkpoint(str(tmp_path), 2, init, model.specs(),
                                   with_opt=True)
    assert it == 2
    p2 = jax.device_put(p2, param_sh)
    opt2 = put_opt(AdamState(step=jnp.asarray(opt2.step), mu=opt2.mu,
                             nu=opt2.nu), mesh, moment_sh)
    params_b, _ = run(p2, opt2, 2, 4)
    for a, b in zip(jax.tree.leaves(params_a), jax.tree.leaves(params_b)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
