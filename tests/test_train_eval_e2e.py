"""End-to-end smoke: the full CLI pipeline on a tiny corpus.

The cluster-free analogue of the reference's `recipe.sh` integration flow
(SURVEY §3.3): texts -> tokenizer -> token JSON -> `train.main` (TP=2, DP=2,
checkpoints, resume) -> `evaluate.main` (per-ckpt val loss + greedy decode),
all on the virtual CPU mesh.
"""

import json
import os
import re

import numpy as np
import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from distributed_pytorch_from_scratch_tpu import evaluate as eval_mod
from distributed_pytorch_from_scratch_tpu import train as train_mod
from distributed_pytorch_from_scratch_tpu.data.tokenizer import (
    pre_tokenize, train_bpe)
from distributed_pytorch_from_scratch_tpu.training.checkpoint import (
    latest_step, list_checkpoints)

TEXTS = [
    "the king rode out at dawn with his men",
    "a quiet morning on the river bank",
    "she sold sea shells by the sea shore",
    "to be or not to be that is the question",
    "all the world is a stage and we are players",
    "the lazy dog slept while the fox jumped",
    # cover the bytes (capitals, punctuation) of evaluate.DECODE_PROMPTS so
    # the tiny tokenizer can round-trip them (byte-level BPE only includes
    # bytes seen in training)
    "Nice to meet you, it's a Great day; Your majesty, I shall be glad",
    "What a glory to see; Shame for the weak, The brave man ne, Poor old man",
] * 6


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    d = tmp_path_factory.mktemp("e2e")
    text_json = d / "texts.json"
    with open(text_json, "w") as f:
        json.dump({"train": TEXTS, "validation": TEXTS[:6]}, f)
    tok = d / "tokenizer.json"
    train_bpe(str(text_json), str(tok), vocab_size=280)
    tokens = d / "tokens.json"
    pre_tokenize(str(text_json), str(tokens), str(tok))
    return {"dir": d, "tokens": tokens, "tok": tok}


MODEL_FLAGS = ["--attn_dim", "32", "--ffn_dim", "64", "--num_heads", "8",
               "--num_layers", "2", "--maxlen", "32"]


def test_train_eval_resume_e2e(corpus):
    save_dir = str(corpus["dir"] / "ckpts")
    base = ["--tp_size", "2", "--dp_size", "2",
            "--data_path", str(corpus["tokens"]),
            "--save_dir", save_dir,
            "--batch_size", "4", "--log_interval", "2",
            "--save_interval", "4", "--warmup_steps", "2",
            *MODEL_FLAGS]

    # train 8 steps, checkpoints at 4 and 8
    train_mod.main(base + ["--max_steps", "8"])
    assert latest_step(save_dir) == 8
    assert len(list_checkpoints(save_dir, rank=0)) == 2
    assert len(list_checkpoints(save_dir, rank=1)) == 2

    # resume to 12: must continue from 8, not restart
    train_mod.main(base + ["--max_steps", "12", "--resume"])
    assert latest_step(save_dir) == 12

    # evaluate all checkpoints + greedy decode
    result = eval_mod.evaluate(eval_mod.get_eval_args([
        "--tp_size", "2",
        "--ckpt_dir", save_dir,
        "--data_path", str(corpus["tokens"]),
        "--tokenizer_path", str(corpus["tok"]),
        "--max_decode_len", "16",
        "--no-bf16",
        "--batch_size", "2",
        *MODEL_FLAGS]))
    assert set(result["val_losses"]) == {4, 8, 12}
    assert all(np.isfinite(v) for v in result["val_losses"].values())
    assert len(result["decoded"]) == len(eval_mod.DECODE_PROMPTS)
    report = os.path.join(save_dir, "val", "val.txt")
    assert os.path.exists(report)
    text = open(report).read()
    assert "Validation loss" in text and "Decoded texts" in text

    # the same evaluation on the full 3-D mesh (dp2 x cp2 x tp2, VERDICT
    # weak #5): val losses must agree with the tp-only run — dp shards the
    # batch (ragged final batch padded with IGNORE_INDEX rows), cp runs ring
    # attention over sequence chunks
    # --no_kv_cache: the full-recompute decode must also run on the 3-D
    # mesh (its buffer is replicated over dp/cp, not sharded); zigzag
    # exercises the balanced ring layout through the eval CLI
    result3d = eval_mod.evaluate(eval_mod.get_eval_args([
        "--tp_size", "2", "--dp_size", "2", "--cp_size", "2",
        "--cp_layout", "zigzag",
        "--ckpt_dir", save_dir,
        "--data_path", str(corpus["tokens"]),
        "--tokenizer_path", str(corpus["tok"]),
        "--max_decode_len", "16",
        "--no-bf16",
        "--batch_size", "2",
        "--no_kv_cache",
        *MODEL_FLAGS]))
    for it, v in result["val_losses"].items():
        np.testing.assert_allclose(result3d["val_losses"][it], v,
                                   rtol=0, atol=1e-5)


def test_train_rejects_oversized_mesh(corpus):
    with pytest.raises(SystemExit, match="devices"):
        train_mod.train(train_mod.get_train_args([
            "--tp_size", "64", "--data_path", str(corpus["tokens"]),
            *MODEL_FLAGS, "--max_steps", "1"]))


def test_pp_train_then_eval_on_dp_tp_mesh(corpus):
    """VERDICT r3 #6: the pp-train -> eval flow, end to end. Train on a
    pp2 x tp2 mesh (4 layers / 2 stages, microbatched GPipe), checkpoint,
    then evaluate on a pp-LESS dp x tp mesh — the mesh-independent
    checkpoint reload is what makes the handoff work (the reference's
    train->test handoff is same-mesh only, `/root/reference/test.py:94-98`;
    here the eval mesh is a different shape entirely). doc_loss refuses pp
    meshes at the API level (`Transformer.doc_loss_shard`), so the eval CLI
    deliberately has no --pp_size flag."""
    save_dir = str(corpus["dir"] / "ckpts_pp")
    pp_model_flags = ["--attn_dim", "32", "--ffn_dim", "64",
                      "--num_heads", "8", "--num_layers", "4",
                      "--maxlen", "32"]
    train_mod.main(["--pp_size", "2", "--tp_size", "2",
                    "--pp_microbatches", "4",
                    "--data_path", str(corpus["tokens"]),
                    "--save_dir", save_dir,
                    "--batch_size", "4", "--log_interval", "2",
                    "--save_interval", "3", "--warmup_steps", "2",
                    "--max_steps", "6", *pp_model_flags])
    assert latest_step(save_dir) == 6

    # reload on tp2 (pp=1) and on dp2 x tp2: val losses must agree exactly
    results = {}
    for name, mesh_flags in [("tp2", ["--tp_size", "2"]),
                             ("dp2tp2", ["--tp_size", "2",
                                         "--dp_size", "2"])]:
        results[name] = eval_mod.evaluate(eval_mod.get_eval_args([
            *mesh_flags,
            "--ckpt_dir", save_dir,
            "--data_path", str(corpus["tokens"]),
            "--tokenizer_path", str(corpus["tok"]),
            "--max_decode_len", "12",
            "--no-bf16",
            "--batch_size", "2",
            *pp_model_flags]))
    for r in results.values():
        assert set(r["val_losses"]) == {3, 6}
        assert all(np.isfinite(v) for v in r["val_losses"].values())
        assert len(r["decoded"]) == len(eval_mod.DECODE_PROMPTS)
    for it, v in results["tp2"]["val_losses"].items():
        np.testing.assert_allclose(results["dp2tp2"]["val_losses"][it], v,
                                   rtol=0, atol=1e-5)


def test_pp_ring_cp_train_cli_smoke(corpus):
    """pp x ring-CP through the train CLI: the live-gated ring schedule
    (unconditional ppermutes, cond-gated dense segments — VERDICT r3 #3)
    compiles and trains finite losses end to end."""
    r = train_mod.train(train_mod.get_train_args([
        "--pp_size", "2", "--cp_size", "2", "--pp_microbatches", "2",
        "--data_path", str(corpus["tokens"]),
        "--save_dir", str(corpus["dir"] / "ckpts_ppcp"),
        "--batch_size", "4", "--log_interval", "2", "--warmup_steps", "2",
        "--max_steps", "2", "--save_interval", "2",
        "--attn_dim", "32", "--ffn_dim", "64", "--num_heads", "8",
        "--num_layers", "4", "--maxlen", "32"]))
    assert r["steps"] == 2 and np.isfinite(r["avg_loss"])


@pytest.mark.slow  # heaviest of its family; shorter siblings stay fast
def test_interleaved_train_resume_eval(corpus):
    """The interleaved schedule through the train CLI: checkpoints are
    saved CANONICAL (layers flattened back to the (L, ...) stack), resume
    reloads them through canonical_specs + from_canonical (params AND Adam
    moments), and the eval CLI — which knows nothing about schedules —
    reads the same artifacts. A direct canonical-round-trip assertion pins
    the save-side layout: the saved checkpoint loaded into a plain pp=1
    template must reproduce the interleaved model's own loss."""
    import jax

    from distributed_pytorch_from_scratch_tpu import MeshConfig, make_mesh
    from distributed_pytorch_from_scratch_tpu.config import ModelConfig
    from distributed_pytorch_from_scratch_tpu.models.transformer import (
        Transformer)
    from distributed_pytorch_from_scratch_tpu.training.checkpoint import (
        load_checkpoint)

    save_dir = str(corpus["dir"] / "ckpts_interleaved")
    flags = ["--attn_dim", "32", "--ffn_dim", "64", "--num_heads", "4",
             "--num_layers", "4", "--maxlen", "32"]
    base = ["--pp_size", "2", "--tp_size", "2",
            "--pp_schedule", "interleaved", "--pp_microbatches", "2",
            "--data_path", str(corpus["tokens"]),
            "--save_dir", save_dir,
            "--batch_size", "4", "--log_interval", "2",
            "--save_interval", "2", "--warmup_steps", "2", *flags]
    train_mod.main(base + ["--max_steps", "4"])
    assert latest_step(save_dir) == 4
    # resume exercises canonical_specs load + from_canonical on params/moments
    train_mod.main(base + ["--max_steps", "6", "--resume"])
    assert latest_step(save_dir) == 6

    # canonical round-trip: checkpoint -> pp=1 template -> loss must equal
    # the interleaved model's loss on the same (from_canonical'd) params
    import jax.numpy as jnp
    vocab = json.load(open(corpus["tokens"]))["vocab_size"]
    cfg = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=4, num_layers=4,
                      vocab_size=vocab, maxlen=32)
    flat = Transformer(cfg)
    template = flat.init(jax.random.key(0))
    loaded, _, st = load_checkpoint(save_dir, 6, template, flat.specs())
    assert st == 6
    ids = jnp.zeros((4, 8), jnp.int32)
    tgt = jnp.ones((4, 8), jnp.int32)
    pos = jnp.tile(jnp.arange(8)[None, :], (4, 1))
    l_flat = flat.make_loss(make_mesh(MeshConfig()))(loaded, ids, tgt, pos)

    iv = Transformer(cfg, pp_size=2, tp_size=2, pp_schedule="interleaved",
                     pp_microbatches=2)
    mesh = make_mesh(MeshConfig(pp=2, tp=2))
    sp = jax.device_put(iv.from_canonical(loaded), iv.shardings(mesh))
    l_iv = iv.make_loss(mesh)(sp, ids, tgt, pos)
    np.testing.assert_allclose(float(l_iv), float(l_flat), rtol=1e-5)

    result = eval_mod.evaluate(eval_mod.get_eval_args([
        "--tp_size", "2",
        "--ckpt_dir", save_dir,
        "--data_path", str(corpus["tokens"]),
        "--tokenizer_path", str(corpus["tok"]),
        "--max_decode_len", "8",
        "--no-bf16",
        "--batch_size", "2",
        *flags]))
    assert set(result["val_losses"]) == {2, 4, 6}
    assert all(np.isfinite(v) for v in result["val_losses"].values())


def test_generate_cli(corpus):
    """The generation CLI: prompt in -> extended text out, batched prompts
    in one dispatch, greedy and sampled modes (the reference has no
    generation entry point at all — its decode lives inside test.py)."""
    from distributed_pytorch_from_scratch_tpu import generate as gen_mod

    save_dir = str(corpus["dir"] / "ckpts_gen")
    train_mod.main(["--tp_size", "2",
                    "--data_path", str(corpus["tokens"]),
                    "--save_dir", save_dir,
                    "--batch_size", "4", "--log_interval", "2",
                    "--save_interval", "4", "--warmup_steps", "2",
                    "--max_steps", "4", *MODEL_FLAGS])

    base = ["--ckpt_dir", save_dir,
            "--tokenizer_path", str(corpus["tok"]),
            "--tp_size", "2", "--max_new_tokens", "8", "--no-bf16",
            *MODEL_FLAGS]
    outs = gen_mod.main(base + ["--prompt", "the king",
                                "--prompt", "a quiet morning"])
    assert len(outs) == 2
    assert outs[0].startswith("the king")
    assert outs[1].startswith("a quiet morning")

    sampled = gen_mod.main(base + ["--prompt", "the king",
                                   "--temperature", "1.0",
                                   "--decode_top_p", "0.9",
                                   "--seed", "3"])
    again = gen_mod.main(base + ["--prompt", "the king",
                                 "--temperature", "1.0",
                                 "--decode_top_p", "0.9",
                                 "--seed", "3"])
    assert sampled == again  # same seed reproduces


@pytest.mark.slow
def test_adamw_cosine_train_then_cp_decode_eval(corpus):
    """Round-4 additions through the REAL CLIs: train with AdamW decoupled
    decay + the cosine schedule, then evaluate with --cp_size 2 — the val
    forward shards the sequence over 'cp' (ring attention) and decoding
    routes through the paged engine's cp-sharded page pool (ISSUE 18)."""
    import subprocess
    import sys
    save = str(corpus["dir"] / "wd_ck")
    env = {**__import__("os").environ, "JAX_PLATFORMS": "cpu"}
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["XLA_FLAGS"] = env.get("XLA_FLAGS", "") + \
        " --xla_force_host_platform_device_count=8"
    tr = subprocess.run(
        [sys.executable, "-m", "distributed_pytorch_from_scratch_tpu.train",
         "--data_path", str(corpus["tokens"]), "--save_dir", save,
         "--attn_dim", "64", "--ffn_dim", "128", "--num_heads", "4",
         "--num_layers", "2", "--maxlen", "32",
         "--dp_size", "2", "--tp_size", "2", "--batch_size", "8",
         "--max_steps", "4", "--warmup_steps", "2", "--save_interval", "2",
         "--weight_decay", "0.1", "--lr_schedule", "cosine"],
        capture_output=True, text=True, timeout=600, env=env,
        cwd=REPO_ROOT)
    assert tr.returncode == 0, tr.stderr
    assert "training finished" in tr.stdout

    ev = subprocess.run(
        [sys.executable, "-m",
         "distributed_pytorch_from_scratch_tpu.evaluate",
         "--data_path", str(corpus["tokens"]), "--ckpt_dir", save,
         "--tokenizer_path", str(corpus["tok"]),
         "--attn_dim", "64", "--ffn_dim", "128", "--num_heads", "4",
         "--num_layers", "2", "--maxlen", "32",
         "--cp_size", "2", "--tp_size", "2", "--batch_size", "4",
         "--max_decode_len", "16"],
        capture_output=True, text=True, timeout=900, env=env,
        cwd=REPO_ROOT)
    assert ev.returncode == 0, ev.stderr
    assert len(re.findall(r"val loss [0-9.]+", ev.stdout)) >= 2, ev.stdout
    assert "->" in ev.stdout  # decodes printed (cp-sharded prefill path)
