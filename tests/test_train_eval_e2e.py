"""End-to-end smoke: the full CLI pipeline on a tiny corpus.

The cluster-free analogue of the reference's `recipe.sh` integration flow
(SURVEY §3.3): texts -> tokenizer -> token JSON -> `train.main` (TP=2, DP=2,
checkpoints, resume) -> `evaluate.main` (per-ckpt val loss + greedy decode),
all on the virtual CPU mesh.
"""

import json
import os

import numpy as np
import pytest

from distributed_pytorch_from_scratch_tpu import evaluate as eval_mod
from distributed_pytorch_from_scratch_tpu import train as train_mod
from distributed_pytorch_from_scratch_tpu.data.tokenizer import (
    pre_tokenize, train_bpe)
from distributed_pytorch_from_scratch_tpu.training.checkpoint import (
    latest_step, list_checkpoints)

TEXTS = [
    "the king rode out at dawn with his men",
    "a quiet morning on the river bank",
    "she sold sea shells by the sea shore",
    "to be or not to be that is the question",
    "all the world is a stage and we are players",
    "the lazy dog slept while the fox jumped",
    # cover the bytes (capitals, punctuation) of evaluate.DECODE_PROMPTS so
    # the tiny tokenizer can round-trip them (byte-level BPE only includes
    # bytes seen in training)
    "Nice to meet you, it's a Great day; Your majesty, I shall be glad",
    "What a glory to see; Shame for the weak, The brave man ne, Poor old man",
] * 6


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    d = tmp_path_factory.mktemp("e2e")
    text_json = d / "texts.json"
    with open(text_json, "w") as f:
        json.dump({"train": TEXTS, "validation": TEXTS[:6]}, f)
    tok = d / "tokenizer.json"
    train_bpe(str(text_json), str(tok), vocab_size=280)
    tokens = d / "tokens.json"
    pre_tokenize(str(text_json), str(tokens), str(tok))
    return {"dir": d, "tokens": tokens, "tok": tok}


MODEL_FLAGS = ["--attn_dim", "32", "--ffn_dim", "64", "--num_heads", "8",
               "--num_layers", "2", "--maxlen", "32"]


def test_train_eval_resume_e2e(corpus):
    save_dir = str(corpus["dir"] / "ckpts")
    base = ["--tp_size", "2", "--dp_size", "2",
            "--data_path", str(corpus["tokens"]),
            "--save_dir", save_dir,
            "--batch_size", "4", "--log_interval", "2",
            "--save_interval", "4", "--warmup_steps", "2",
            *MODEL_FLAGS]

    # train 8 steps, checkpoints at 4 and 8
    train_mod.main(base + ["--max_steps", "8"])
    assert latest_step(save_dir) == 8
    assert len(list_checkpoints(save_dir, rank=0)) == 2
    assert len(list_checkpoints(save_dir, rank=1)) == 2

    # resume to 12: must continue from 8, not restart
    train_mod.main(base + ["--max_steps", "12", "--resume"])
    assert latest_step(save_dir) == 12

    # evaluate all checkpoints + greedy decode
    result = eval_mod.evaluate(eval_mod.get_eval_args([
        "--tp_size", "2",
        "--ckpt_dir", save_dir,
        "--data_path", str(corpus["tokens"]),
        "--tokenizer_path", str(corpus["tok"]),
        "--max_decode_len", "16",
        "--no-bf16",
        "--batch_size", "2",
        *MODEL_FLAGS]))
    assert set(result["val_losses"]) == {4, 8, 12}
    assert all(np.isfinite(v) for v in result["val_losses"].values())
    assert len(result["decoded"]) == len(eval_mod.DECODE_PROMPTS)
    report = os.path.join(save_dir, "val", "val.txt")
    assert os.path.exists(report)
    text = open(report).read()
    assert "Validation loss" in text and "Decoded texts" in text

    # the same evaluation on the full 3-D mesh (dp2 x cp2 x tp2, VERDICT
    # weak #5): val losses must agree with the tp-only run — dp shards the
    # batch (ragged final batch padded with IGNORE_INDEX rows), cp runs ring
    # attention over sequence chunks
    # --no_kv_cache: the full-recompute decode must also run on the 3-D
    # mesh (its buffer is replicated over dp/cp, not sharded); zigzag
    # exercises the balanced ring layout through the eval CLI
    result3d = eval_mod.evaluate(eval_mod.get_eval_args([
        "--tp_size", "2", "--dp_size", "2", "--cp_size", "2",
        "--cp_layout", "zigzag",
        "--ckpt_dir", save_dir,
        "--data_path", str(corpus["tokens"]),
        "--tokenizer_path", str(corpus["tok"]),
        "--max_decode_len", "16",
        "--no-bf16",
        "--batch_size", "2",
        "--no_kv_cache",
        *MODEL_FLAGS]))
    for it, v in result["val_losses"].items():
        np.testing.assert_allclose(result3d["val_losses"][it], v,
                                   rtol=0, atol=1e-5)


def test_train_rejects_oversized_mesh(corpus):
    with pytest.raises(SystemExit, match="devices"):
        train_mod.train(train_mod.get_train_args([
            "--tp_size", "64", "--data_path", str(corpus["tokens"]),
            *MODEL_FLAGS, "--max_steps", "1"]))
