"""Preflight validation of the staged hardware session (VERDICT r4 #1).

Round 4 lost part of its only 4-minute chip window to flag rot: the staged
t=8k bench line invoked `bench.py --maxlen 8192 --batch_size 2` — flags
bench.py does not have — and round 3's staged kernel-check script had a
sys.path bug. Nothing validated the staged scripts against the real CLIs
before the scarce window opened.

This test extracts EVERY python invocation from runs/r5/*.sh (including
those wrapped in scripts/run_step.py and the bench_line/step shell helpers)
and validates it against the REAL argparser of the target program, on CPU,
in CI. A staged command that would die on argparse now fails the suite
instead of the chip window.
"""

import os
import re
import shlex

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
R5 = os.path.join(REPO, "runs", "r5")

# every staged session dir gets preflighted (r6 stages the fast-45m pass,
# r7 the comm-overlap A/B, r8 the serving loadgen sweep, r9 the paged
# serving-v2 sweep + slot-vs-paged A/B, r10 the speculative k-sweep +
# fused-sampler ablation, r11 the int8 wire sweep + int8-KV serving arms,
# r12 the ZeRO stage x wire ladder + RS/AG breakdown arm, r13 the
# regression-gated trajectory point + traced/flight-recorded serving,
# r14 the live telemetry plane: exported serving + collector rollup +
# the SLO-collapse anomaly arm with cross-linked device profiling,
# r15 the paged-attention kernel: pages_per_block autotune + the
# gather-vs-pallas A/B sweep with int8 and speculative arms,
# r16 measured attribution: duty-cycled profiled train window, the
# measured breakdown + profiled serving bench arms, the anomaly capture
# that parses, and the measured-ms regression gate,
# r17 the control plane: advise-mode train window, act-mode serving
# loadgen with a burst traffic shift, the off-mode zero-cost arm, and
# the check_bench_regression --controller window gate,
# r18 run forensics: the archive index over the real runs, two
# profiled serving arms one knob apart + their pairwise diff, the
# --explain gate on a forced regression, and the triage/trajectory
# passes,
# r19 long-context cp serving: traced cp-contract preflight, the
# cp{1,2} A/B one knob apart, the 32k-token-prompt capacity arm, the
# int8-KV cp arm, and the cp2-vs-cp1 regression-gate line,
# r20 the serving fleet: the live 2-replica router arm + its
# single-replica baseline, the disaggregated prefill->decode arms
# (native + int8 wire), the four-arm bench --fleet A/B, and the
# int8-vs-native fleet regression-gate line,
# r21 elastic reshard: the tp4 training artifact, the offline
# plan-then-reshard to tp2 + serving it, the elastic dp2xtp2 --resume
# arm off the tp4 checkpoint, the fleet width-restart arm, and the
# bench --reshard pair with its regression-gate line)
SESSION_DIRS = [d for d in (R5, os.path.join(REPO, "runs", "r6"),
                            os.path.join(REPO, "runs", "r7"),
                            os.path.join(REPO, "runs", "r8"),
                            os.path.join(REPO, "runs", "r9"),
                            os.path.join(REPO, "runs", "r10"),
                            os.path.join(REPO, "runs", "r11"),
                            os.path.join(REPO, "runs", "r12"),
                            os.path.join(REPO, "runs", "r13"),
                            os.path.join(REPO, "runs", "r14"),
                            os.path.join(REPO, "runs", "r15"),
                            os.path.join(REPO, "runs", "r16"),
                            os.path.join(REPO, "runs", "r17"),
                            os.path.join(REPO, "runs", "r18"),
                            os.path.join(REPO, "runs", "r19"),
                            os.path.join(REPO, "runs", "r20"),
                            os.path.join(REPO, "runs", "r21"))
                if os.path.isdir(d)]
SESSION_SCRIPTS = [os.path.join(d, n)
                   for d in SESSION_DIRS
                   for n in sorted(os.listdir(d)) if n.endswith(".sh")]

# shell variables the session scripts define; substituted before lexing.
# $R/$M are per-script (the sourcing script's runs dir).
SHELL_VARS = {
    "TOKENS": "/tmp/corpus_tokens.json",
    "LOG": "/tmp/tpu_status_r5.txt",
}
REDIRECT = re.compile(r"^\d*(>>?|\|)|^\|\|?$|^&&$|^2>>?$")


def _sub_vars(line: str, rdir: str) -> str:
    subs = dict(SHELL_VARS, R=rdir, M=f"{rdir}/session_manifest.jsonl")
    for k, v in subs.items():
        line = line.replace("${%s}" % k, v).replace("$%s" % k, v)
    return line


def _strip_shell_tail(tokens):
    """Drop everything from the first redirection/pipe onward."""
    out = []
    for i, t in enumerate(tokens):
        if REDIRECT.match(t):
            break
        if t in (">", ">>", "<", "|", "||", "&&", ";"):
            break
        out.append(t)
    return out


def extract_commands(path):
    """Yield (lineno, argv) for every staged python command in a script."""
    text = open(path).read()
    rdir = "runs/" + os.path.basename(os.path.dirname(path))
    # join backslash continuations
    text = re.sub(r"\\\n\s*", " ", text)
    cmds = []
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = _sub_vars(raw.strip(), rdir)
        if not line or line.startswith("#"):
            continue
        # bench_line TAG TIMEOUT flags...  =>  python bench.py flags...
        m = re.match(r"bench_line\s+(\S+)\s+(\S+)\s+(.*)$", line)
        if m:
            toks = _strip_shell_tail(shlex.split(m.group(3)))
            cmds.append((lineno, ["python", "bench.py"] + toks))
            continue
        # step NAME TIMEOUT cmd...  =>  cmd...
        m = re.match(r"step\s+(\S+)\s+(\S+)\s+(python\s.*)$", line)
        if m:
            line = m.group(3)
        if "python" not in line:
            continue
        try:
            toks = shlex.split(line)
        except ValueError:
            continue
        # find EVERY python command on the line (a `summarize && refresh`
        # chain stages two commands; stopping at the first would leave the
        # second unvalidated)
        while "python" in toks:
            i = toks.index("python")
            toks = toks[i:]
            argv = _strip_shell_tail(toks)
            # `python scripts/run_step.py <wrapper flags> -- cmd...`:
            # record the WRAPPER invocation too (its flags must parse — a
            # `--time-out` typo would exit 97 on the chip), then unwrap
            if len(argv) >= 2 and argv[1].endswith("run_step.py"):
                if not any("$" in a for a in argv):
                    cmds.append((lineno, argv))
                if "--" in toks:
                    toks = toks[toks.index("--") + 1:]
                    continue
                break
            if len(argv) >= 2:
                cmds.append((lineno, argv))
            # resume scanning past this command for a chained `&& python ...`
            toks = toks[max(len(argv), 1):]
    # drop function-template lines (contain unexpanded "$@")
    return [(ln, argv) for ln, argv in cmds
            if not any("$" in a for a in argv)]


ALL_COMMANDS = [(os.path.basename(p), ln, argv)
                for p in SESSION_SCRIPTS
                for ln, argv in extract_commands(p)]


def _load_script(name):
    """Import a scripts/*.py file by path (scripts/ is not a package)."""
    import importlib.util
    path = os.path.join(REPO, "scripts", name + ".py")
    spec = importlib.util.spec_from_file_location(f"_staged_{name}", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _parse_with(parse_fn, argv):
    try:
        parse_fn(argv)
    except SystemExit as e:
        if e.code not in (0, None):
            pytest.fail(f"argparse rejected staged flags: {argv}")


def validate(argv):
    """Dispatch one extracted command to the matching real parser."""
    prog = argv[1]
    rest = argv[2:]
    if prog == "-c":
        return  # inline probe snippets: syntax-checked below
    if prog == "-m":
        mod, rest = argv[2], argv[3:]
        if mod == "distributed_pytorch_from_scratch_tpu.train":
            from distributed_pytorch_from_scratch_tpu.train import (
                get_train_args)
            return _parse_with(get_train_args, rest)
        if mod == "distributed_pytorch_from_scratch_tpu.evaluate":
            from distributed_pytorch_from_scratch_tpu.evaluate import (
                get_eval_args)
            return _parse_with(get_eval_args, rest)
        if mod == "distributed_pytorch_from_scratch_tpu.data.tokenizer":
            from distributed_pytorch_from_scratch_tpu.data.tokenizer import (
                parse_args)
            return _parse_with(parse_args, rest)
        if mod == "distributed_pytorch_from_scratch_tpu.serving.serve":
            from distributed_pytorch_from_scratch_tpu.serving.serve import (
                get_serve_args)
            return _parse_with(get_serve_args, rest)
        pytest.fail(f"staged module has no registered parser: {mod}")
    # script path
    path = os.path.join(REPO, prog)
    assert os.path.exists(path), f"staged script missing: {prog}"
    if prog == "bench.py":
        import bench
        return _parse_with(bench.parse_args, rest)
    if prog.startswith("scripts/") and prog.endswith(".py"):
        name = os.path.basename(prog)[:-3]
        if name in ("tpu_checks", "make_image_corpus", "tune_flash_blocks",
                    "check_bench_regression", "graftcheck", "obs_top",
                    "obs_diff", "serve_fleet", "reshard_ckpt"):
            mod = _load_script(name)
            return _parse_with(mod.parse_args, rest)
        if name == "run_step":
            return _load_script(name).parse_argv(rest)
    if prog.endswith("scripts/summarize_run.py"):
        assert rest and rest[0].startswith("runs/"), rest
        return
    if prog.endswith("scripts/refresh_baseline.py"):
        assert rest and re.fullmatch(r"runs/r\d+", rest[0]), rest
        return
    pytest.fail(f"staged script has no registered parser: {prog}")


def test_session_scripts_exist():
    assert SESSION_SCRIPTS, "no staged session scripts under runs/r5/"
    names = [os.path.basename(p) for p in SESSION_SCRIPTS]
    assert "run_experiment.sh" in names
    assert any(n.startswith("watch") for n in names)


def test_commands_were_extracted():
    """The extractor must actually see the session's heavy hitters — an
    extraction regression would otherwise silently validate nothing."""
    flat = [" ".join(argv) for _, _, argv in ALL_COMMANDS]
    assert any("bench.py" in c for c in flat)
    assert any("distributed_pytorch_from_scratch_tpu.train" in c for c in flat)
    assert any("distributed_pytorch_from_scratch_tpu.evaluate" in c
               for c in flat)
    assert any("tpu_checks.py" in c for c in flat)
    assert len(flat) >= 15, flat


@pytest.mark.parametrize(
    "script,lineno,argv", ALL_COMMANDS,
    ids=[f"{s}:{ln}:{' '.join(a[1:3])}" for s, ln, a in ALL_COMMANDS])
def test_staged_command_parses(script, lineno, argv):
    validate(argv)


def test_inline_snippets_compile():
    """`python -c '...'` probe snippets must at least be valid python."""
    for script, lineno, argv in ALL_COMMANDS:
        if argv[1] == "-c" and len(argv) > 2:
            compile(argv[2], f"{script}:{lineno}", "exec")


def test_staged_paths_exist():
    """Every runs/ or scripts/ path mentioned in a staged command must
    exist NOW (the r3 failure: staged runs/r3/tpu_checks.py referenced a
    file whose bug was only discovered on the chip)."""
    for script, lineno, argv in ALL_COMMANDS:
        for tok in argv:
            if tok.startswith(("scripts/", "runs/")) and "." in tok:
                if tok.endswith((".py", ".sh")):
                    assert os.path.exists(os.path.join(REPO, tok)), (
                        f"{script}:{lineno} references missing {tok}")


def test_watcher_tag_list_matches_staged_bench_lines():
    """watch_r5.sh's complete() enumerates the bench artifacts it waits
    for; run_experiment.sh's bench_line calls produce them. A rename on
    either side would make the watcher wait forever (or declare victory
    while a line is missing) — the two lists must be identical, and
    run_priority.sh's subset must exist in the full session."""
    text = open(os.path.join(R5, "run_experiment.sh")).read()
    exp_tags = set(re.findall(r"^bench_line\s+(\S+)", text, re.M))
    text = open(os.path.join(R5, "run_priority.sh")).read()
    pri_tags = set(re.findall(r"^bench_line\s+(\S+)", text, re.M))
    watcher = open(os.path.join(R5, "watch_r5.sh")).read()
    m = re.search(r"for t in ([^;]+); do", watcher)
    assert m, "watcher bench-tag loop not found"
    watch_tags = set(m.group(1).replace("\\", " ").split())
    assert exp_tags, "no bench_line calls extracted from run_experiment.sh"
    assert watch_tags == exp_tags, (
        f"watcher waits for {sorted(watch_tags - exp_tags)} that the "
        f"session never produces / misses {sorted(exp_tags - watch_tags)}")
    assert pri_tags <= exp_tags, (
        f"priority-pass tags not in the full session: "
        f"{sorted(pri_tags - exp_tags)}")


def test_train_and_priority_train_flags_agree():
    """run_priority.sh's training slice must resume the SAME run as
    run_experiment.sh: same save_dir, model shape flags, and optimizer
    schedule, else a short-window slice would corrupt the long run."""
    full = priority = None
    for script, lineno, argv in ALL_COMMANDS:
        if "distributed_pytorch_from_scratch_tpu.train" in argv and \
                "runs/r5/ckpt" in argv:
            if script == "run_experiment.sh":
                full = argv
            elif script == "run_priority.sh":
                priority = argv
    assert full and priority
    from distributed_pytorch_from_scratch_tpu.train import get_train_args
    a = get_train_args(full[3:])
    b = get_train_args(priority[3:])
    for field in ("save_dir", "data_path", "batch_size", "maxlen",
                  "max_steps", "warmup_steps", "lr", "steps_per_dispatch",
                  "remat", "save_interval", "lr_schedule", "bf16"):
        assert getattr(a, field) == getattr(b, field), field
