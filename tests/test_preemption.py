"""Preemption-safe training: SIGTERM mid-run -> final checkpoint + clean
exit + --resume continues.

The reference has no failure-detection/recovery story at all
(`mp.spawn(join=True)`, SURVEY §5.3): a signal kills the job and any
progress since the last periodic save is lost. Here the train loop polls a
signal flag each step (train.py `_ShutdownFlag`) — the TPU-idiomatic
equivalent, since preemptible TPU VM evictions arrive as SIGTERM.

Runs the real CLI in a subprocess (signals can't be exercised in-process:
pytest owns the main thread's handlers).
"""

import json
import os
import signal
import subprocess
import sys
import threading

import pytest

pytestmark = pytest.mark.slow

from distributed_pytorch_from_scratch_tpu.data.tokenizer import (pre_tokenize,
                                                                 train_bpe)
from distributed_pytorch_from_scratch_tpu.training.checkpoint import (
    latest_step)

TEXTS = ["the king rode out at dawn with his men",
         "a quiet morning on the river bank",
         "she sold sea shells by the sea shore",
         "to be or not to be that is the question"] * 4


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    d = tmp_path_factory.mktemp("preempt")
    text_json = d / "texts.json"
    with open(text_json, "w") as f:
        json.dump({"train": TEXTS, "validation": TEXTS[:2]}, f)
    tok = d / "tokenizer.json"
    train_bpe(str(text_json), str(tok), vocab_size=270)
    tokens = d / "tokens.json"
    pre_tokenize(str(text_json), str(tokens), str(tok))
    return tokens


def test_sigterm_checkpoints_and_resumes(corpus, tmp_path):
    save_dir = str(tmp_path / "ckpts")
    # PYTHONUNBUFFERED: the child block-buffers stdout into a pipe, so the
    # "step N" marker would otherwise never arrive before the signal.
    # PALLAS_AXON_POOL_IPS must be dropped: with it set, this image's
    # sitecustomize registers the axon TPU plugin and forces the platform,
    # overriding JAX_PLATFORMS=cpu (see tests/conftest.py NOTE).
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONUNBUFFERED": "1"}
    env.pop("PALLAS_AXON_POOL_IPS", None)
    args = [sys.executable, "-m", "distributed_pytorch_from_scratch_tpu.train",
            "--data_path", str(corpus), "--save_dir", save_dir,
            "--attn_dim", "32", "--ffn_dim", "64", "--num_heads", "4",
            "--num_layers", "2", "--maxlen", "32",
            "--batch_size", "2", "--log_interval", "1",
            "--save_interval", "100000", "--warmup_steps", "2"]
    proc = subprocess.Popen(args + ["--max_steps", "100000"],
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True, bufsize=1,
                            env=env)
    lines = []
    seen_step = threading.Event()

    def pump():
        for line in proc.stdout:
            lines.append(line)
            if line.startswith("step "):
                seen_step.set()

    t = threading.Thread(target=pump, daemon=True)
    t.start()
    try:
        assert seen_step.wait(timeout=300), (
            "no training step within 300s:\n" + "".join(lines))
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=120) == 0, "".join(lines)
    finally:
        proc.kill()
    t.join(timeout=10)
    out = "".join(lines)
    assert "shutdown requested: checkpointed at step" in out, out

    stopped_at = latest_step(save_dir)
    assert stopped_at is not None and stopped_at >= 1

    # the saved state must actually resume
    resumed = subprocess.run(
        args + ["--max_steps", str(stopped_at + 2), "--resume"],
        capture_output=True, text=True, timeout=300, env=env)
    assert resumed.returncode == 0, resumed.stdout + resumed.stderr
    assert f"resumed from iter {stopped_at}" in resumed.stdout
    assert f"training finished at step {stopped_at + 2}" in resumed.stdout
