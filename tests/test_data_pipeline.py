"""Data pipeline tests: preprocess -> tokenizer -> pre-tokenize -> batches.

Covers the reference's offline pipeline (`preprocess_data.py`,
`train_tokenizer.py`, `pre_tokenize.py`, `dataset.py`) including schema
compatibility with the reference's shipped tokenizer and collate semantics
(`dataset.py:40-55`).
"""

import json
import os

import numpy as np
import pytest

from distributed_pytorch_from_scratch_tpu.config import (
    BOS_TOKEN, EOS_TOKEN, IGNORE_INDEX, UNK_TOKEN)
from distributed_pytorch_from_scratch_tpu.data.dataset import (
    TokenDataset, collate, get_dataloader)
from distributed_pytorch_from_scratch_tpu.data.tokenizer import (
    pre_tokenize, train_bpe)

TEXTS = [
    "the quick brown fox jumps over the lazy dog",
    "hello world this is a test of the tokenizer",
    "distributed training from scratch on tpu hardware",
    "megatron style tensor parallelism with jax",
] * 8


@pytest.fixture(scope="module")
def pipeline(tmp_path_factory):
    d = tmp_path_factory.mktemp("data")
    text_json = d / "texts.json"
    with open(text_json, "w") as f:
        json.dump({"train": TEXTS, "validation": TEXTS[:4]}, f)
    tok_path = d / "tokenizer.json"
    train_bpe(str(text_json), str(tok_path), vocab_size=300)
    tokens_json = d / "tokens.json"
    pre_tokenize(str(text_json), str(tokens_json), str(tok_path))
    return {"dir": d, "text_json": text_json, "tok": tok_path,
            "tokens": tokens_json}


def test_token_json_schema(pipeline):
    with open(pipeline["tokens"]) as f:
        data = json.load(f)
    # byte-compatible with the reference's pre_tokenize.py:43-48 output
    assert set(data) == {"train", "validation", "special_ids", "vocab_size"}
    assert set(data["special_ids"]) == {BOS_TOKEN, EOS_TOKEN, UNK_TOKEN}
    assert all(isinstance(x, list) for x in data["train"])
    assert data["special_ids"][BOS_TOKEN] == 0
    assert data["special_ids"][EOS_TOKEN] == 1
    assert data["special_ids"][UNK_TOKEN] == 2


def test_reference_shipped_tokenizer_loads():
    """The reference ships a trained tokenizer.json; our pipeline must accept
    it directly (same library, same format)."""
    ref_tok = "/root/reference/tokenizer/tokenizer.json"
    if not os.path.exists(ref_tok):
        pytest.skip("reference tokenizer not present")
    from tokenizers import Tokenizer
    tok = Tokenizer.from_file(ref_tok)
    assert tok.get_vocab_size() == 1024
    assert tok.token_to_id(BOS_TOKEN) == 0
    ids = tok.encode("hello world").ids
    assert tok.decode(ids).strip() == "hello world"


def test_collate_semantics():
    """input = [BOS]+tokens padded EOS; target = tokens+[EOS] padded IGNORE
    (reference dataset.py:40-55)."""
    bos, eos = 0, 1
    batch = [[5, 6, 7], [8]]
    out = collate(batch, bos, eos, IGNORE_INDEX, pad_to=6)
    np.testing.assert_array_equal(out["input_ids"],
                                  [[0, 5, 6, 7, 1, 1], [0, 8, 1, 1, 1, 1]])
    np.testing.assert_array_equal(out["target_ids"],
                                  [[5, 6, 7, 1, -1, -1], [8, 1, -1, -1, -1, -1]])
    np.testing.assert_array_equal(out["position_ids"][0], np.arange(6))


def test_collate_per_batch_max_matches_reference_shape():
    """without pad_to, width is batch max + 1 like the reference."""
    out = collate([[5, 6, 7], [8]], 0, 1, IGNORE_INDEX)
    assert out["input_ids"].shape == (2, 4)


def test_dataset_truncation(pipeline):
    ds = TokenDataset(str(pipeline["tokens"]), "train", maxlen=4)
    for i in range(len(ds)):
        assert len(ds[i]) <= 3  # maxlen - 1


def test_dataloader_fixed_shapes_and_epochs(pipeline):
    dl = get_dataloader(str(pipeline["tokens"]), batch_size=8,
                        split="train", maxlen=32, seed=1)
    shapes = set()
    b0 = None
    for batch in dl.epoch(0):
        shapes.add(batch["input_ids"].shape)
        if b0 is None:
            b0 = batch["input_ids"].copy()
    assert len(shapes) == 1, f"recompile hazard: varying shapes {shapes}"
    assert shapes.pop() == (8, 32)
    # different epoch -> different order; same epoch -> same order (seeded)
    b0_again = next(iter(dl.epoch(0)))["input_ids"]
    np.testing.assert_array_equal(b0, b0_again)
    b1 = next(iter(dl.epoch(1)))["input_ids"]
    assert not np.array_equal(b0, b1)


def test_dataloader_validation_keeps_tail(pipeline):
    dl = get_dataloader(str(pipeline["tokens"]), batch_size=3,
                        split="validation", maxlen=32, shuffle=False)
    total = sum(b["input_ids"].shape[0] for b in dl.epoch(0))
    assert total == 4  # drop_last defaults off for validation


def test_preprocess(tmp_path):
    pd = pytest.importorskip("pandas")
    pq = tmp_path / "raw.parquet"
    texts = [f"document number {i} " + "x" * (i * 10) for i in range(50)]
    pd.DataFrame({"text": texts}).to_parquet(pq)
    from distributed_pytorch_from_scratch_tpu.data.preprocess import preprocess
    out = tmp_path / "texts.json"
    data = preprocess(str(pq), str(out), max_chars=200, val_ratio=0.1, seed=0)
    assert set(data) == {"train", "validation"}
    assert all(len(t) <= 200 for t in data["train"] + data["validation"])
    assert len(data["validation"]) >= 1


# ---- packed-stream data mode (beyond the reference) ----


def test_packed_loader_shapes_and_shift(pipeline):
    """Every packed batch is exactly (batch, maxlen) with the shift-by-one
    target across the whole stream — including row boundaries."""
    dl = get_dataloader(str(pipeline["tokens"]), batch_size=2, maxlen=16,
                        data_mode="packed", seed=3)
    batches = list(dl.epoch(0))
    assert len(batches) == len(dl) and len(batches) > 0
    for b in batches:
        assert b["input_ids"].shape == (2, 16)
        assert b["target_ids"].shape == (2, 16)
        assert (b["position_ids"] == np.arange(16)[None, :]).all()
        flat_in = b["input_ids"].reshape(-1)
        flat_tgt = b["target_ids"].reshape(-1)
        # within the batch, target is input shifted by one (incl. across rows)
        np.testing.assert_array_equal(flat_tgt[:-1], flat_in[1:])
        assert (b["target_ids"] != IGNORE_INDEX).all()  # zero padding


def test_packed_loader_covers_corpus_exactly_once(pipeline):
    """The concatenation of an epoch's inputs reproduces the BOS/EOS-framed
    shuffled corpus prefix — no token lost, duplicated, or padded."""
    ds = TokenDataset(str(pipeline["tokens"]), "train", 16)
    dl = get_dataloader(str(pipeline["tokens"]), batch_size=2, maxlen=16,
                        data_mode="packed", seed=7)
    seqs = ds.data["train"]
    order = np.random.RandomState(7 + 0).permutation(len(seqs))
    expect = []
    for i in order:
        expect.extend([ds.bos] + list(seqs[int(i)]) + [ds.eos])
    got = np.concatenate([b["input_ids"].reshape(-1)
                          for b in dl.epoch(0)])
    np.testing.assert_array_equal(got, np.asarray(expect[: len(got)]))
    # the drop is at most one chunk + the shift token
    assert len(expect) - len(got) <= 2 * 16 + 1


def test_packed_loader_epochs_differ_and_are_deterministic(pipeline):
    dl = get_dataloader(str(pipeline["tokens"]), batch_size=2, maxlen=16,
                        data_mode="packed", seed=5)
    e0a = next(iter(dl.epoch(0)))["input_ids"]
    e0b = next(iter(dl.epoch(0)))["input_ids"]
    e1 = next(iter(dl.epoch(1)))["input_ids"]
    np.testing.assert_array_equal(e0a, e0b)
    assert not np.array_equal(e0a, e1)


def test_packed_loader_rejects_tiny_corpus(tmp_path):
    j = tmp_path / "tiny.json"
    json.dump({"train": [[5, 6]], "validation": [[5]],
               "special_ids": {BOS_TOKEN: 0, EOS_TOKEN: 1, UNK_TOKEN: 2},
               "vocab_size": 16}, open(j, "w"))
    with pytest.raises(ValueError, match="packed mode needs"):
        get_dataloader(str(j), batch_size=4, maxlen=64, data_mode="packed")


def test_cli_train_packed_mode(pipeline, tmp_path):
    """--data_mode packed end to end through the train CLI (with prefetch +
    steps_per_dispatch riding the same batch interface)."""
    from distributed_pytorch_from_scratch_tpu import train as train_mod

    r = train_mod.train(train_mod.get_train_args(
        ["--data_path", str(pipeline["tokens"]),
         "--save_dir", str(tmp_path / "ck"),
         "--data_mode", "packed", "--tp_size", "2", "--dp_size", "2",
         "--batch_size", "4", "--maxlen", "16",
         "--attn_dim", "32", "--ffn_dim", "64", "--num_heads", "4",
         "--num_layers", "2",
         "--max_steps", "4", "--steps_per_dispatch", "2",
         "--save_interval", "4", "--log_interval", "2",
         "--warmup_steps", "2"]))
    assert r["steps"] == 4 and np.isfinite(r["avg_loss"])


def test_packed_loader_rejects_docs_only_knobs(pipeline):
    with pytest.raises(ValueError, match="TRAINING data mode"):
        get_dataloader(str(pipeline["tokens"]), 2, maxlen=16,
                       split="validation", data_mode="packed")
    with pytest.raises(ValueError, match="ignores"):
        get_dataloader(str(pipeline["tokens"]), 2, maxlen=16,
                       backend="native", data_mode="packed")
