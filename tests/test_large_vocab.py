"""Large-vocab stress: vocab-parallel embedding + CE at production scale.

BASELINE config 4 (50k-vocab vocab-parallel embedding stress). The reference
stress-tests its ParallelVocabularyEmbedding up to vocab 65,536
(`/root/reference/tests/test_parallel_vocab_embedding.py:80`); this suite
matches that bound for the embedding and additionally exercises the full
model's cross-entropy at GPT-2's vocab 50,257 (non-divisible over tp=8 ->
padded to 50,264) in both loss modes — the vocab-parallel CE path was built
precisely for this regime, where the full (B, T, V) logits tensor stops
being affordable.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributed_pytorch_from_scratch_tpu.config import (IGNORE_INDEX,
                                                         MeshConfig,
                                                         ModelConfig)
from distributed_pytorch_from_scratch_tpu.models.transformer import Transformer
from distributed_pytorch_from_scratch_tpu.models.vanilla import (
    VanillaTransformer)
from distributed_pytorch_from_scratch_tpu.parallel.embedding import (
    VocabParallelEmbedding)
from distributed_pytorch_from_scratch_tpu.runtime.mesh import make_mesh

TP = 8


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(MeshConfig(dp=1, tp=TP))


@pytest.mark.parametrize("vocab", [50_000, 65_536])
def test_embedding_forward_and_grads_large_vocab(mesh, vocab):
    """Reference check at its largest grid point (vocab 65,536), plus the
    BASELINE 50k point: forward lookup and weight grads vs a plain take."""
    hdim = 32
    layer = VocabParallelEmbedding(vocab, hdim, tp_size=TP)
    params = layer.init(jax.random.key(0))
    assert params["weight"].shape == (layer.vocab_padded, hdim)
    # ids deliberately cover both extremes of the table
    ids = jnp.concatenate([
        jax.random.randint(jax.random.key(1), (2, 14), 0, vocab),
        jnp.array([[0, vocab - 1]] * 2, jnp.int32)], axis=1)

    def sharded_loss(params, ids):
        out = layer.apply(params, ids)
        return jnp.sum(out * out)

    def oracle_loss(params, ids):
        return jnp.sum(jnp.take(params["weight"], ids, axis=0) ** 2)

    loss = jax.jit(jax.shard_map(
        sharded_loss, mesh=mesh, in_specs=(layer.specs(), P()),
        out_specs=P()))(params, ids)
    np.testing.assert_allclose(loss, oracle_loss(params, ids), rtol=1e-5)

    g_sh = jax.jit(jax.grad(jax.shard_map(
        sharded_loss, mesh=mesh, in_specs=(layer.specs(), P()),
        out_specs=P())))(params, ids)
    g_ref = jax.grad(oracle_loss)(params, ids)
    np.testing.assert_allclose(np.asarray(g_sh["weight"]),
                               np.asarray(g_ref["weight"]),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("mode", ["vocab_parallel", "gather"])
def test_full_model_ce_at_gpt2_vocab(mesh, mode):
    """Full-model loss + grads vs the oracle at vocab 50,257 (GPT-2 / the
    BASELINE config-3 tokenizer scale; non-divisible: padded to 50,264)."""
    cfg = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=8, num_layers=1,
                      vocab_size=50_257, maxlen=16)
    model = Transformer(cfg, tp_size=TP)
    assert model.vocab_padded == 50_264
    oracle = VanillaTransformer(cfg)
    params = model.init(jax.random.key(2))

    b, t = 2, 8
    ids = jax.random.randint(jax.random.key(3), (b, t), 0, cfg.vocab_size)
    # targets hit the top of the vocab range too, plus ignored positions
    tgt = jax.random.randint(jax.random.key(4), (b, t), 0, cfg.vocab_size)
    tgt = tgt.at[0, 0].set(cfg.vocab_size - 1).at[1, -1].set(IGNORE_INDEX)
    pos = jnp.tile(jnp.arange(t)[None, :], (b, 1))

    loss_fn = model.make_loss(mesh, mode=mode)
    l_sh, g_sh = jax.value_and_grad(loss_fn)(params, ids, tgt, pos)
    l_ref, g_ref = jax.value_and_grad(oracle.loss)(params, ids, tgt, pos)

    np.testing.assert_allclose(float(l_sh), float(l_ref), rtol=1e-5)
    flat_sh, _ = jax.tree.flatten(g_sh)
    flat_ref, _ = jax.tree.flatten(g_ref)
    for a, b_ in zip(flat_sh, flat_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-4, atol=1e-5)


def test_vocab_parallel_ce_never_materialises_full_logits(mesh):
    """The point of the vocab-parallel CE (BASELINE config 4): the compiled
    program's live logits tensor is the LOCAL shard (B, T, V/tp), not the
    full (B, T, V). Asserted on the jitted HLO rather than by timing."""
    cfg = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=8, num_layers=1,
                      vocab_size=50_257, maxlen=16)
    model = Transformer(cfg, tp_size=TP)
    params = model.init(jax.random.key(5))
    b, t = 2, 8
    ids = jax.random.randint(jax.random.key(6), (b, t), 0, cfg.vocab_size)
    tgt = jnp.roll(ids, -1, 1)
    pos = jnp.tile(jnp.arange(t)[None, :], (b, 1))

    def hlo_for(mode):
        fn = model.make_loss(mesh, mode=mode)
        return jax.jit(fn).lower(params, ids, tgt, pos).compile().as_text()

    # full-logits shape (per shard after stitching), HLO spells shapes
    # as f32[b,t,vocab]
    full = f"{b},{t},{model.vocab_padded}]"
    assert full not in hlo_for("vocab_parallel"), (
        "vocab_parallel CE materialised the full logits tensor")
    assert full in hlo_for("gather"), (
        "sanity: the gather mode is expected to materialise full logits")

    saved_mib = (b * t * model.vocab_padded * 4 * (TP - 1) / TP) / 2 ** 20
    print(f"\nvocab-parallel CE avoids a {b}x{t}x{model.vocab_padded} f32 "
          f"logits gather: ~{saved_mib:.1f} MiB saved per step at this toy "
          f"shape (scales as B*T*V*(tp-1)/tp; at the gpt2-124m bench shape "
          f"b8xt1024, tp=8 that is "
          f"{8 * 1024 * 50264 * 4 * 7 / 8 / 2**30:.2f} GiB)")
