"""Multi-host (multi-process) training proof — VERDICT r2 #7.

Spawns TWO local processes that rendezvous through
`runtime.mesh.init_multihost` (`jax.distributed.initialize` underneath — the
DCN analogue of the reference's NCCL env:// rendezvous,
`/root/reference/utils.py:19-24`), each owning 4 virtual CPU devices, and
runs ONE dp2 x tp4 train step with per-process dp data sharding
(`jax.make_array_from_process_local_data`). Both processes must report the
identical finite loss: the cross-process psum really ran.
"""

import os
import re
import socket
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_two_process_train_step():
    script = os.path.join(os.path.dirname(__file__), "_multihost_main.py")
    repo = os.path.dirname(os.path.dirname(script))
    port = _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # workers set their own device count
    procs = [subprocess.Popen(
        [sys.executable, script, str(pid), str(port)],
        env=env, cwd=repo, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True) for pid in (0, 1)]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=600)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        assert p.returncode == 0, f"stdout:\n{out}\nstderr:\n{err}"
        outs.append(out)

    # three cross-process configs: dp x tp train step, ring CP with its
    # collective-permutes crossing the process boundary, and a 2-stage
    # pipeline with one stage per process — both processes must report
    # identical finite losses for each (the cross-process collectives ran)
    for tag in ("MULTIHOST-OK", "MULTIHOST-CP-OK", "MULTIHOST-PP-OK"):
        losses = []
        for pid, out in enumerate(outs):
            m = re.search(rf"{tag} process={pid} loss=([0-9.]+)", out)
            assert m, (tag, out)
            losses.append(float(m.group(1)))
        assert losses[0] == losses[1], (tag, losses)
