"""BASELINE config 3 proof: the 'gpt2-124m' preset builds, shards, and
trains a step on a 2-D mesh — the test-proven entry for the config the
single real chip can't bench at full shape without remat tradeoffs.

~90 s on the CPU mesh (one 124M-param fwd+bwd+Adam compile + step); kept
because it is the only coverage of the preset's real dims (12 heads, 50257
vocab -> padded vocab-parallel CE over tp=4).
"""

import jax
import jax.numpy as jnp
import pytest

from distributed_pytorch_from_scratch_tpu import (MeshConfig, Transformer,
                                                  make_mesh)
from distributed_pytorch_from_scratch_tpu.config import (OptimizerConfig,
                                                         model_preset)
from distributed_pytorch_from_scratch_tpu.training.optim import (
    init_adam_state)
from distributed_pytorch_from_scratch_tpu.training.train_step import (
    build_train_step)


@pytest.mark.slow  # heaviest of its family; shorter siblings stay fast
def test_gpt2_124m_preset_trains_on_2d_mesh():
    cfg = model_preset("gpt2-124m")
    # GPT-2-small DIMS (768/3072/12x12/50257/1024); the LLaMA-style arch
    # (untied lm_head + SwiGLU gate) lands at ~190M params, not 124M
    assert (cfg.attn_dim, cfg.ffn_dim, cfg.num_layers) == (768, 3072, 12)
    assert cfg.vocab_size == 50257 and cfg.num_heads == 12

    mesh = make_mesh(MeshConfig(dp=2, tp=4))
    model = Transformer(cfg, tp_size=4)
    params = jax.device_put(model.init(jax.random.key(0)),
                            model.shardings(mesh))
    opt = init_adam_state(params)
    step = build_train_step(model, mesh, OptimizerConfig())

    b, t = 2, 64
    ids = jax.random.randint(jax.random.key(1), (b, t), 0, cfg.vocab_size)
    pos = jnp.tile(jnp.arange(t, dtype=jnp.int32)[None, :], (b, 1))
    params, opt, loss = step(params, opt, ids, jnp.roll(ids, -1, 1), pos)

    # untrained CE over a 50257-way softmax must sit at ~ln(V)
    assert abs(float(loss) - float(jnp.log(cfg.vocab_size))) < 0.5
    assert int(opt.step) == 1
