"""BASELINE config 3 proof: the 'gpt2-124m' preset builds, shards, and
trains a step on a 2-D mesh — the test-proven entry for the config the
single real chip can't bench at full shape without remat tradeoffs.

~90 s on the CPU mesh (one 124M-param fwd+bwd+Adam compile + step); kept
because it is the only coverage of the preset's real dims (12 heads, 50257
vocab -> padded vocab-parallel CE over tp=4).
"""

import jax
import jax.numpy as jnp
import pytest

from distributed_pytorch_from_scratch_tpu import (MeshConfig, Transformer,
                                                  make_mesh)
from distributed_pytorch_from_scratch_tpu.config import (OptimizerConfig,
                                                         model_preset)
from distributed_pytorch_from_scratch_tpu.training.optim import (
    init_adam_state)
from distributed_pytorch_from_scratch_tpu.training.train_step import (
    build_train_step)


def test_gpt2_355m_preset_dims():
    """Fast contract check: GPT-2 Medium dims on the gpt2-355m preset."""
    cfg = model_preset("gpt2-355m")
    assert (cfg.attn_dim, cfg.ffn_dim, cfg.num_layers,
            cfg.num_heads, cfg.vocab_size) == (1024, 4096, 24, 16, 50257)


@pytest.mark.slow  # 355M-param threefry init + 1.4 GiB device_put
def test_gpt2_355m_preset_init_and_param_count():
    """The gpt2-355m preset must actually build: sharded init covers the
    whole tree and lands at GPT-2 Medium's ~354.8M params in the gpt2
    family (tied embedding/head; the padded vocab adds <0.1%). The full
    fwd+bwd compile is too heavy for CPU CI — the 124m sibling covers
    the train step."""
    from distributed_pytorch_from_scratch_tpu.models.gpt2 import (
        GPT2Transformer)
    cfg = model_preset("gpt2-355m")
    mesh = make_mesh(MeshConfig(dp=2, tp=4))
    model = GPT2Transformer(cfg, tp_size=4)
    params = model.init(jax.random.key(0))
    n = sum(x.size for x in jax.tree.leaves(params))
    assert 350e6 < n < 365e6, n
    jax.device_put(params, model.shardings(mesh))  # shardings cover tree


@pytest.mark.slow  # heaviest of its family; shorter siblings stay fast
def test_gpt2_124m_preset_trains_on_2d_mesh():
    cfg = model_preset("gpt2-124m")
    # GPT-2-small DIMS (768/3072/12x12/50257/1024); the LLaMA-style arch
    # (untied lm_head + SwiGLU gate) lands at ~190M params, not 124M
    assert (cfg.attn_dim, cfg.ffn_dim, cfg.num_layers) == (768, 3072, 12)
    assert cfg.vocab_size == 50257 and cfg.num_heads == 12

    mesh = make_mesh(MeshConfig(dp=2, tp=4))
    model = Transformer(cfg, tp_size=4)
    params = jax.device_put(model.init(jax.random.key(0)),
                            model.shardings(mesh))
    opt = init_adam_state(params)
    step = build_train_step(model, mesh, OptimizerConfig())

    b, t = 2, 64
    ids = jax.random.randint(jax.random.key(1), (b, t), 0, cfg.vocab_size)
    pos = jnp.tile(jnp.arange(t, dtype=jnp.int32)[None, :], (b, 1))
    params, opt, loss = step(params, opt, ids, jnp.roll(ids, -1, 1), pos)

    # untrained CE over a 50257-way softmax must sit at ~ln(V)
    assert abs(float(loss) - float(jnp.log(cfg.vocab_size))) < 0.5
    assert int(opt.step) == 1
