"""The control plane (obs v5, ISSUE 16): drift-driven retuning, the
online SLO controller, and the auditable decision ledger.

Laws pinned here, all on CPU:

* a committed reconcile fixture produces a PINNED RetuneAdvisor decision
  (the rules are a contract, not a heuristic that may drift);
* the --control ladder: `advise` computes + ledgers but NEVER mutates;
  `act` mutates ONLY inside `apply_decisions()` called from a
  `@control_safe_point` function (the graftcheck rule's dynamic twin);
* the SLO controller demonstrably adapts under a loadgen traffic shift,
  every actuation cross-links its triggering telemetry snapshot, and
  the ledger alone reconstructs the knob trajectory;
* the off state is ZERO-cost: no events, no record fields — a
  `--control off` run is byte-shaped like a pre-v5 run;
* schema v5: both ledger event tags validate, and their required-field
  contracts cannot drift silently.
"""

import glob
import importlib.util
import json
import os

import pytest

from distributed_pytorch_from_scratch_tpu.obs.control import (
    CONTROL_MODES, RetuneAdvisor, control_safe_point)
from distributed_pytorch_from_scratch_tpu.obs.schema import (
    EVENT_REQUIRED, EVENT_SCHEMA_VERSION, validate_record)
from distributed_pytorch_from_scratch_tpu.obs.telemetry import (
    TelemetryExporter)
from distributed_pytorch_from_scratch_tpu.serving.controller import (
    SLOController)
from distributed_pytorch_from_scratch_tpu.training.metrics import (
    MetricsWriter)

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
FIXTURE = os.path.join(HERE, "data", "reconcile_drift.json")


def _load_fixture():
    with open(FIXTURE) as f:
        return json.load(f)


@control_safe_point
def _actuate(ctl):
    """The tests' one registered safe point (controller-discipline: an
    undecorated apply_decisions() call would fail the repo sweep)."""
    return ctl.apply_decisions()


def _events(log_dir, *tags):
    out = []
    for p in sorted(glob.glob(os.path.join(log_dir, "**",
                                           "metrics*.jsonl"),
                              recursive=True)):
        for line in open(p):
            rec = json.loads(line)
            if not tags or rec.get("tag") in tags:
                out.append(rec)
    return out


def _load_script(name):
    path = os.path.join(REPO, "scripts", name + ".py")
    spec = importlib.util.spec_from_file_location(f"_ctl_{name}", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# --------------------------------------------- the RetuneAdvisor rules --

def test_reconcile_fixture_pins_retune_decision(tmp_path):
    """The committed drift fixture (66.7% all-reduce drift, copy and
    host_gap both under their thresholds, compute on-model) produces
    EXACTLY one decision: dp_bucket_mb 0 -> 4.0 (unbucketed -> seeded),
    evidenced by the capture id and the drifted phase — and the ledger
    event validates under schema v5."""
    with MetricsWriter(str(tmp_path), process_index=0) as w:
        adv = RetuneAdvisor("advise", writer=w)
        adv.register_knob("dp_bucket_mb", lambda: 0, integer=False)
        # knobs the fixture must NOT move (their signals are sub-threshold)
        state = {"pages": 1, "chunk": 32}
        adv.register_knob("pages_per_block", lambda: state["pages"], lo=1)
        adv.register_knob("prefill_chunk", lambda: state["chunk"], lo=1)
        out = adv.observe_attribution(_load_fixture())
    assert len(out) == 1 and adv.decisions == out
    d = out[0]
    assert d["knob"] == "dp_bucket_mb"
    assert d["old"] == 0 and d["new"] == 4.0
    assert d["applied"] is False and d["mode"] == "advise"
    assert d["evidence"]["trigger"] == "comm_drift"
    assert d["evidence"]["capture"] == "/tmp/profiles/duty_000123"
    assert d["evidence"]["phases"]["all-reduce"]["drift_pct"] == 66.7
    events = _events(str(tmp_path), "tuning_decision")
    assert len(events) == 1
    assert validate_record(events[0]) == []
    # a REPEATED identical signal must not spam the ledger
    with MetricsWriter(str(tmp_path / "again"), process_index=0) as w:
        adv2 = RetuneAdvisor("advise", writer=w)
        adv2.register_knob("dp_bucket_mb", lambda: 0, integer=False)
        assert len(adv2.observe_attribution(_load_fixture())) == 1
        assert adv2.observe_attribution(_load_fixture()) == []


def test_advise_never_mutates(tmp_path):
    """The advise rung: decisions land in the ledger applied=false and
    no setter ever runs — even through an explicit safe point."""
    fields = {"capture": "c1", "reconcile": {
        "measured_step_ms": 100.0, "rows": [
            {"phase": "host_gap", "measured_ms": 30.0,
             "analytic_ms": None, "drift_pct": None}]}}
    state = {"chunk": 16}

    def setter(v):                        # must never fire in advise
        raise AssertionError("advise mutated a knob")

    with MetricsWriter(str(tmp_path), process_index=0) as w:
        adv = RetuneAdvisor("advise", writer=w)
        adv.register_knob("prefill_chunk", lambda: state["chunk"], setter,
                          lo=1)
        out = adv.observe_attribution(fields)
        assert [d["knob"] for d in out] == ["prefill_chunk"]
        assert out[0]["applied"] is False
        assert _actuate(adv) == 0         # nothing queued in advise
        adv.close()
    assert state["chunk"] == 16
    events = _events(str(tmp_path), "tuning_decision")
    assert [e["applied"] for e in events] == [False]


def test_act_applies_only_at_safe_points(tmp_path):
    """The act rung: a proposal QUEUES (no mutation, no ledger event) at
    observation time and lands only when apply_decisions() runs from a
    @control_safe_point function; an init-boundary knob (no setter) and
    a refusing setter both ledger applied=false with the reason; close()
    flushes anything that never reached a safe point."""
    assert getattr(_actuate, "__control_safe_point__", False) is True
    fields = {"capture": "c2", "reconcile": {
        "measured_step_ms": 100.0, "rows": [
            {"phase": "all-reduce", "measured_ms": 40.0,
             "analytic_ms": 20.0, "drift_pct": 100.0},
            {"phase": "host_gap", "measured_ms": 30.0,
             "analytic_ms": None, "drift_pct": None},
            {"phase": "copy", "measured_ms": 20.0, "analytic_ms": 10.0,
             "drift_pct": 100.0}]}}
    state = {"chunk": 16}

    def refuse(v):
        raise ValueError("online config would shadow a sweep result")

    with MetricsWriter(str(tmp_path), process_index=0) as w:
        adv = RetuneAdvisor("act", writer=w)
        adv.register_knob("dp_bucket_mb", lambda: 0, integer=False)
        adv.register_knob("prefill_chunk", lambda: state["chunk"],
                          lambda v: state.__setitem__("chunk", int(v)),
                          lo=1)
        adv.register_knob("pages_per_block", lambda: 1, refuse, lo=1)
        out = adv.observe_attribution(fields)
        assert len(out) == 3
        # proposed but NOT actuated, NOT yet ledgered
        assert state["chunk"] == 16 and adv.decisions == []
        assert _events(str(tmp_path), "tuning_decision") == []
        assert _actuate(adv) == 1         # only prefill_chunk could move
        assert state["chunk"] == 32
        by_knob = {d["knob"]: d for d in adv.decisions}
        assert by_knob["prefill_chunk"]["applied"] is True
        assert by_knob["dp_bucket_mb"]["applied"] is False
        assert "init-boundary" in by_knob["dp_bucket_mb"]["note"]
        assert by_knob["pages_per_block"]["applied"] is False
        assert "shadow" in by_knob["pages_per_block"]["error"]
        # a queued proposal that never reaches a safe point still ledgers
        adv.observe_hbm({"available": True, "devices": [
            {"bytes_in_use": 95, "limit_bytes": 100}]})
        adv.close()
    flushed = [e for e in _events(str(tmp_path), "tuning_decision")
               if e.get("note", "").startswith("unapplied")]
    assert flushed and all(e["applied"] is False for e in flushed)
    # and the static rule agrees: an undecorated call site violates
    from distributed_pytorch_from_scratch_tpu.analysis.rules import (
        lint_file)
    vios = lint_file("snippet.py",
                     text="def f(c):\n    c.apply_decisions()\n")
    assert any(v.rule == "controller-discipline" for v in vios)
    vios = lint_file("snippet.py", text=(
        "from distributed_pytorch_from_scratch_tpu.obs.control import "
        "control_safe_point\n"
        "@control_safe_point\n"
        "def f(c):\n    c.apply_decisions()\n"))
    assert not any(v.rule == "controller-discipline" for v in vios)


# ------------------------------------------------- the SLO controller --

class _FakeReq:
    def __init__(self, ttft_s, finish_t, slo_class="interactive",
                 ntok=8, tpot_s=0.01):
        self.slo_class = slo_class
        self.ttft_s = ttft_s
        self.tpot_s = tpot_s
        self.finish_t = finish_t
        self.tokens = [0] * ntok


class _FakeSched:
    def __init__(self, classes, max_queue):
        self.classes = classes
        self.max_queue = max_queue
        self.pending = 0


class _FakeEngine:
    def __init__(self, max_queue=16):
        self.scheduler = _FakeSched({"interactive": 0.05}, max_queue)
        self.completed = []
        self._slot_req = {}
        self.prefill_chunk = 32

    def stats(self):
        return {}


def test_slo_controller_adapts_and_ledger_reconstructs(tmp_path):
    """A traffic shift (SLO collapse with a deep queue, then recovery)
    drives the controller: clamp admission, then restore — each
    actuation cross-linked (snapshot_seq) to a telemetry snapshot that
    is IN the stream, and the ledger events alone reconstruct the knob
    trajectory from init to final value."""
    t = {"now": 0.0}
    eng = _FakeEngine(max_queue=16)
    with MetricsWriter(str(tmp_path), process_index=0) as w:
        tele = TelemetryExporter(writer=w)   # headless: registry only
        ctl = SLOController(eng, "act", writer=w, telemetry=tele,
                            interval=8, cooldown=1,
                            clock=lambda: t["now"])
        # window 1: every interactive TTFT misses 50ms, queue is deep
        t["now"] = 1.0
        eng.completed += [_FakeReq(0.2, finish_t=0.5 + 0.05 * i)
                          for i in range(6)]
        eng.scheduler.pending, eng._slot_req = 20, {0: 1, 1: 1}
        ctl.tick(8)
        assert eng.scheduler.max_queue == 16        # queued, not acted
        assert ctl.decisions == []
        assert _actuate(ctl) == 1
        assert eng.scheduler.max_queue == 8
        d1 = ctl.decisions[0]
        assert (d1["knob"], d1["trigger"]) == ("max_queue",
                                               "slo_miss_queue")
        assert d1["applied"] is True and d1["snapshot_seq"] == 1
        # window 2: attainment recovers -> the clamp relaxes to init
        t["now"] = 2.0
        eng.completed += [_FakeReq(0.01, finish_t=1.5 + 0.05 * i)
                          for i in range(6)]
        eng.scheduler.pending = 2
        ctl.tick(16)
        _actuate(ctl)
        assert eng.scheduler.max_queue == 16
        d2 = ctl.decisions[1]
        assert (d2["knob"], d2["trigger"]) == ("max_queue", "recovered")
        assert d2["snapshot_seq"] == 2
        # pre/post windows split at the FIRST actuation
        wds = ctl.windows()
        assert wds["pre"]["completed"] == 6
        assert wds["post"]["completed"] == 6
        assert ctl.summary()["windows"] == wds
        ctl.close()
    # the ledger reconstructs the trajectory: old chains to new, and the
    # cross-linked snapshots exist in the stream BEFORE their decisions
    stream = _events(str(tmp_path), "controller_decision",
                     "telemetry_snapshot")
    value, snaps_seen = 16, 0
    for rec in stream:
        if rec["tag"] == "telemetry_snapshot":
            snaps_seen += 1
            continue
        assert validate_record(rec) == []
        assert rec["snapshot_seq"] <= snaps_seen
        if rec["applied"]:
            assert rec["old"] == value
            value = rec["new"]
    assert value == eng.scheduler.max_queue == 16


def test_loadgen_replay_traffic_shift_end_to_end(tmp_path, capsys):
    """The acceptance path: serve.py --control act over a REPLAYED trace
    whose traffic shifts mid-run (4 easy arrivals, then a 20-request
    flood against an impossible interactive deadline) must produce >= 1
    applied controller_decision cross-linked to its telemetry snapshot,
    carry the pre/post windows in the record, and render in
    summarize_run's control-plane timeline."""
    from distributed_pytorch_from_scratch_tpu.serving import (
        serve as serve_mod)

    rng_ids = [3, 5, 7, 9, 11, 13]
    trace = str(tmp_path / "trace.jsonl")
    with open(trace, "w") as f:
        for i in range(24):
            f.write(json.dumps({
                "rid": i, "prompt": [rng_ids[(i + j) % 6]
                                     for j in range(6)],
                "max_new": 8, "seed": i,
                "arrival": 0.0 if i < 4 else 0.3}) + "\n")
    log_dir = str(tmp_path / "logs")
    serve_mod.main([
        "--random_init", "--paged", "--no-bf16",
        "--attn_dim", "32", "--ffn_dim", "64", "--num_heads", "4",
        "--num_layers", "2", "--maxlen", "64", "--vocab_size", "64",
        "--slots", "4", "--page_size", "8", "--max_new_tokens", "8",
        "--arrival", "replay", "--replay", trace,
        "--slo_classes", "interactive=0.0001",
        "--default_class", "interactive",
        "--control", "act", "--control_interval", "16",
        "--log_dir", log_dir])
    # the control fields ride the stdout JSON record (the gate's food),
    # not run_loadgen's summary dict
    rec = json.loads([l for l in capsys.readouterr().out.splitlines()
                      if l.startswith("{")][-1])
    assert rec["control"] == "act"
    ctl = rec["controller"]
    assert ctl["mode"] == "act" and ctl["decisions"] >= 1
    assert ctl["applied"] >= 1
    assert "windows" in ctl
    assert ctl["windows"]["pre"]["completed"] >= 1
    assert ctl["windows"]["post"]["completed"] >= 1
    # ledger: >= 1 applied decision whose snapshot cross-link resolves
    stream = _events(log_dir, "controller_decision",
                     "telemetry_snapshot")
    snaps_seen, applied = 0, []
    for r in stream:
        if r["tag"] == "telemetry_snapshot":
            snaps_seen += 1
            continue
        assert validate_record(r) == []
        assert 1 <= r["snapshot_seq"] <= snaps_seen
        if r["applied"]:
            applied.append(r)
    assert applied
    # and the post-hoc timeline renders trigger -> action -> effect
    sr = _load_script("summarize_run")
    text = "\n".join(sr.control_lines(str(tmp_path)))
    assert "controller_decision" in text or applied[0]["knob"] in text
    assert "=>" in text


# ------------------------------------------------- the zero-cost off --

def test_off_state_is_zero_cost(tmp_path):
    """--control off (the default) must look EXACTLY like a pre-v5 run:
    no ledger events, no control/controller/tuning record fields, no
    ctl/* gauges — and the off-mode advisor/controller are inert."""
    from distributed_pytorch_from_scratch_tpu.serving import (
        serve as serve_mod)

    log_dir = str(tmp_path / "off_logs")
    rec = serve_mod.main(["--dry_run", "--paged", "--log_dir", log_dir])
    for field in ("control", "controller", "tuning",
                  "telemetry_snapshots", "metrics_port"):
        assert field not in rec, field
    assert _events(log_dir, "tuning_decision", "controller_decision",
                   "telemetry_snapshot") == []
    # the off-mode objects observe nothing and emit nothing
    adv = RetuneAdvisor("off")
    assert adv.observe_attribution(_load_fixture()) == []
    assert adv.observe_hbm({"available": True, "devices": [
        {"bytes_in_use": 99, "limit_bytes": 100}]}) == []
    assert adv.decisions == [] and adv.summary()["decisions"] == 0
    eng = _FakeEngine()
    ctl = SLOController(eng, "off", interval=1)
    eng.completed += [_FakeReq(0.2, finish_t=0.5) for _ in range(8)]
    eng.scheduler.pending = 50
    ctl.tick(8)
    assert _actuate(ctl) == 0 and ctl.decisions == []
    assert eng.scheduler.max_queue == 16


# ----------------------------------------------------- schema v5 pins --

def test_schema_v5_ledger_contracts():
    """The version and both ledger tags' required fields are pinned —
    a consumer keyed on snapshot_seq must notice if it ever drifts."""
    # the ledger family landed in v5; the exact current version is
    # pinned in tests/test_forensics.py (v6 added run_card/run_diff)
    assert EVENT_SCHEMA_VERSION >= 5
    assert CONTROL_MODES == ("off", "advise", "act")
    assert EVENT_REQUIRED["tuning_decision"] == (
        "knob", "old", "new", "evidence", "mode", "applied")
    assert EVENT_REQUIRED["controller_decision"] == (
        "knob", "old", "new", "trigger", "mode", "applied",
        "snapshot_seq")
    ok = {"tag": "controller_decision", "schema_version": 5,
          "knob": "max_queue", "old": 16, "new": 8,
          "trigger": "slo_miss_queue", "mode": "act", "applied": True,
          "snapshot_seq": 1}
    assert validate_record(ok) == []
    bad = dict(ok)
    del bad["snapshot_seq"]
    assert any("snapshot_seq" in p for p in validate_record(bad))
    futur = dict(ok, schema_version=EVENT_SCHEMA_VERSION + 1)
    assert any("NEWER" in p for p in validate_record(futur))


# -------------------------------------- the continuous gate (--controller) --

@pytest.mark.parametrize("post,expect_rc", [
    ({"completed": 6, "tokens_per_sec": 120.0, "ttft_ms_p95": 40.0,
      "tpot_ms_p95": 9.0}, 0),            # improved -> pass
    ({"completed": 6, "tokens_per_sec": 60.0, "ttft_ms_p95": 90.0,
      "tpot_ms_p95": 9.0}, 1),            # degraded -> fail
])
def test_controller_gate_pass_and_fail(tmp_path, capsys, post, expect_rc):
    rec = {"metric": "serve", "controller": {
        "mode": "act", "decisions": 2, "applied": 1,
        "windows": {"pre": {"completed": 5, "tokens_per_sec": 100.0,
                            "ttft_ms_p95": 50.0, "tpot_ms_p95": 10.0},
                    "post": post}}}
    path = str(tmp_path / "rec.json")
    json.dump(rec, open(path, "w"))
    gate = _load_script("check_bench_regression")
    rc = gate.main(["--fresh", path, "--controller"])
    assert rc == expect_rc
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["gate"] == "controller_window"
    assert out["status"] == ("ok" if expect_rc == 0 else "regression")


def test_controller_gate_skips_visibly(tmp_path, capsys):
    """No controller block / zero decisions / nothing applied: the gate
    SKIPS (exit 0) with the reason on stderr — absence of a decision is
    not a regression."""
    gate = _load_script("check_bench_regression")
    cases = [
        ({"metric": "serve"}, "no controller"),
        ({"metric": "serve",
          "controller": {"mode": "act", "decisions": 0, "applied": 0}},
         "no decisions"),
        ({"metric": "serve",
          "controller": {"mode": "advise", "decisions": 3, "applied": 0}},
         "APPLIED"),
    ]
    for i, (rec, needle) in enumerate(cases):
        path = str(tmp_path / f"rec{i}.json")
        json.dump(rec, open(path, "w"))
        assert gate.main(["--fresh", path, "--controller"]) == 0
        cap = capsys.readouterr()
        assert json.loads(cap.out.strip())["status"] == "skip"
        assert "SKIP" in cap.err and needle in cap.err
