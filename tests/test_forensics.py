"""Run forensics (ISSUE 17, obs v6): the RunCard index, the cross-run
diff engine, and trajectory changepoint triage.

What is pinned here, against the two committed fixture run dirs under
tests/forensics_fixtures/ (run_a: pages_per_block=4, run_b:
pages_per_block=8 with a degraded copy phase) and the repo's REAL
BENCH_r02 outage record:

* RunCard fields for both fixture runs (fingerprint, headline metrics,
  ledger/capture tallies, HBM watermark, graftcheck contracts);
* the ranked-suspect diff: the pages_per_block config delta JOINED to
  the copy-phase delta, above a noise floor derived from the fixtures'
  duty-cycle capture variance;
* changepoint detection over the committed synthetic trajectory flags
  the pinned step (t5) while outage points are listed, never points;
* outage records can NEVER become baselines, and the gate and the index
  share literally the same classifier function;
* schema v6: run_card / run_diff contracts + JSON roundtrip;
* `check_bench_regression --explain` attaches the forensic report on
  failure and stays silent on pass.
"""

import importlib.util
import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIX = os.path.join(REPO, "tests", "forensics_fixtures")
RUN_A = os.path.join(FIX, "run_a")
RUN_B = os.path.join(FIX, "run_b")

# the standalone import path scripts use (obs dir on sys.path, no jax) —
# the SAME modules check_bench_regression._forensics loads, so identity
# assertions below are meaningful
OBS_DIR = os.path.join(REPO, "distributed_pytorch_from_scratch_tpu", "obs")
if OBS_DIR not in sys.path:
    sys.path.insert(0, OBS_DIR)
import rundiff  # noqa: E402
import runindex  # noqa: E402
from schema import (EVENT_REQUIRED, EVENT_SCHEMA_VERSION,  # noqa: E402
                    validate_record)


def _load_script(name):
    path = os.path.join(REPO, "scripts", name + ".py")
    spec = importlib.util.spec_from_file_location(f"_fx_{name}", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ------------------------------------------------------------- RunCard pins --

def test_run_card_pins_fixture_run_a():
    card = runindex.card_from_run_dir(RUN_A)
    assert card["tag"] == "run_card"
    assert card["run"] == "run_a"
    assert card["kind"] == "session"
    assert card["outage"] is False
    assert card["baseline_eligible"] is True
    assert card["legacy"] is False
    # the committed fingerprint IS what the live function computes —
    # the stamp round-trips through the record
    assert card["config_fingerprint"] == "0e6bbad84b3c"
    assert card["config_fingerprint"] == \
        runindex.config_fingerprint(card["config"])
    assert card["git_rev"] == "aaaa111"
    assert card["metrics"]["value"] == 5214.0
    assert card["metrics"]["unit"] == "tokens/sec (serving)"
    assert card["metrics"]["ttft_ms_p95"] == 85.0
    assert card["measured_vs_analytic"]["phases"]["copy"] == 2.01
    # 3 duty captures tallied, with per-step phase samples kept for the
    # noise floor
    assert card["captures"]["count"] == 3
    assert card["captures"]["triggers"] == {"duty": 3}
    assert len(card["profile_phases"]) == 3
    assert card["hbm"] == {"available": True, "devices": 1,
                           "peak_bytes": 9120256}
    assert card["collectives"]["ok"] is True
    assert card["collectives"]["contracts"][
        "expected_collectives:train_step"] is True
    assert card["ledger"]["decisions"] == 0
    assert runindex.validate_card(card) == []


def test_run_card_run_b_ledger():
    card = runindex.card_from_run_dir(RUN_B)
    assert card["config_fingerprint"] == "8961e903d0d6"
    assert card["metrics"]["value"] == 4288.0
    led = card["ledger"]
    assert led["decisions"] == 1 and led["applied"] == 0
    assert led["knobs"]["pages_per_block"]["last"] == [4, 8]
    assert card["hbm"]["peak_bytes"] == 9830400


def test_run_card_legacy_note_not_silent_none():
    """A pre-stamp record (the real BENCH_r01) indexes with the loud
    legacy note, and the diff engine refuses to call two fingerprint-less
    configs equal."""
    card = runindex.card_from_bench_path(
        os.path.join(REPO, "BENCH_r01.json"))
    assert card["legacy"] is True
    assert runindex.LEGACY_NOTE in card["notes"]
    assert card["config_fingerprint"] is None
    delta = rundiff.config_delta(card, card)
    assert delta["available"] is False
    assert any("fingerprint unavailable" in n for n in delta["notes"])


# ------------------------------------------------- one outage classifier --

def test_outage_classifier_is_shared_with_gate():
    """The gate's pick_baseline and the index must use literally the
    same classifier function — the ISSUE 17 no-divergence satellite."""
    gate = _load_script("check_bench_regression")
    gate_runindex, gate_rundiff = gate._forensics()
    assert gate_runindex.outage_reason is runindex.outage_reason
    assert gate_rundiff.diff_runs is rundiff.diff_runs


def test_bench_r02_outage_never_baseline():
    """BENCH_r02 (rc=1, traceback tail, parsed=null) is the real pinned
    outage fixture: classified as outage, never selected as baseline."""
    r02 = os.path.join(REPO, "BENCH_r02.json")
    cls = runindex.classify_path(r02)
    assert cls["outage"] is not None
    assert "rc=1" in cls["outage"]
    card = runindex.card_from_bench_path(r02)
    assert card["outage"] is True and card["baseline_eligible"] is False
    assert runindex.validate_card(card) == []
    # the gate skips it even when it is the ONLY candidate
    gate = _load_script("check_bench_regression")
    fresh = gate.load_record(os.path.join(RUN_A, "bench_paged.json"))
    assert gate.pick_baseline(fresh, [r02]) == (None, None)
    # and a healthy record still wins when both are offered
    fresh_chip = {"metric": "tokens/sec/chip (x)",
                  "unit": "tokens/sec/chip", "value": 1.0}
    rec, path = gate.pick_baseline(
        fresh_chip, [os.path.join(REPO, "BENCH_r01.json"), r02])
    assert path.endswith("BENCH_r01.json")
    assert rec["unit"] == "tokens/sec/chip"


def test_outage_reason_taxonomy():
    assert runindex.outage_reason(None) == "no parseable record"
    assert runindex.outage_reason(None, rc=3) == \
        "no parseable record (rc=3)"
    assert "backend_unavailable" in runindex.outage_reason(
        {"error": "backend_unavailable", "detail": "tunnel"})
    assert runindex.outage_reason({"metric": "x", "value": 1}, rc=1) \
        == "rc=1"
    assert runindex.outage_reason({"value": 1}) == \
        "record carries no metric"
    assert runindex.outage_reason({"metric": "x", "value": 1}) is None
    assert runindex.outage_reason({"metric": "x"}, rc=0) is None


# ------------------------------------------------------ pinned suspect diff --

def test_pinned_ranked_suspect_pages_per_block_to_copy():
    """THE acceptance pin: the pages_per_block config delta is joined to
    the copy-phase delta as the #1 ranked suspect."""
    doc = rundiff.diff_runs(runindex.card_from_run_dir(RUN_A),
                            runindex.card_from_run_dir(RUN_B))
    assert doc["tag"] == "run_diff"
    assert doc["config_delta"]["changed"] == {"pages_per_block": [4, 8]}
    assert len(doc["suspects"]) == 1
    top = doc["suspects"][0]
    assert top["knob"] == "pages_per_block"
    assert top["phase"] == "copy"
    assert top["delta_ms"] == pytest.approx(2.11, abs=1e-6)
    assert top["score"] > 1.0
    assert "copy paid" in top["verdict"]
    # the insignificant compute/host_gap jitters stayed below the
    # capture-variance noise floor — visible in phase_deltas, not suspects
    by_phase = {r["phase"]: r for r in doc["phase_deltas"]}
    assert by_phase["copy"]["significant"] is True
    assert by_phase["compute"]["significant"] is False
    assert by_phase["host_gap"]["significant"] is False
    # measured consequences ride along
    assert doc["hbm"]["delta_bytes"] == 9830400 - 9120256
    assert doc["ledger"]["decisions_b"] == 1
    # human rendering names the suspect
    text = "\n".join(rundiff.format_diff(doc))
    assert "pages_per_block" in text and "suspects (ranked)" in text


def test_unclaimed_phase_delta_blames_code_delta():
    """A significant phase move with NO changed knob is attributed to
    the code/environment delta (git a -> b), not silently dropped."""
    card_a = runindex.card_from_run_dir(RUN_A)
    card_b = runindex.card_from_run_dir(RUN_B)
    # same config on both sides -> no knob can claim the copy delta
    card_b = dict(card_b, config=card_a["config"],
                  config_fingerprint=card_a["config_fingerprint"])
    doc = rundiff.diff_runs(card_a, card_b)
    assert doc["config_delta"]["changed"] == {}
    tops = [s for s in doc["suspects"] if s["phase"] == "copy"]
    assert len(tops) == 1 and tops[0]["knob"] is None
    assert "git aaaa111 -> bbbb222" in tops[0]["verdict"]


def test_noise_floor_from_capture_variance():
    card = runindex.card_from_run_dir(RUN_A)
    floors = rundiff.noise_floor(card)
    # three captures with +/-0.02 ms/step jitter -> a real (clamped)
    # per-phase floor for every phase the duty cycle measured
    assert set(floors) == {"copy", "compute", "host_gap"}
    for v in floors.values():
        assert rundiff.MIN_FLOOR_MS <= v < 0.1


# ----------------------------------------------------- trajectory triage --

def _trajectory_cards():
    doc = json.load(open(os.path.join(FIX, "trajectory.json")))
    cards = []
    for pt in doc["points"]:
        if "outage" in pt:
            cards.append({"run": pt["run"], "outage": True,
                          "outage_reason": pt["outage"],
                          "metrics": {"unit": doc["unit"]}})
        else:
            cards.append({"run": pt["run"], "outage": False,
                          "metrics": {"metric": doc["metric"],
                                      "unit": doc["unit"],
                                      "value": pt["value"]}})
    return doc, cards


def test_changepoint_flags_pinned_trajectory_step():
    doc, cards = _trajectory_cards()
    reports = rundiff.trajectory_report(cards)
    assert len(reports) == 1
    rep = reports[0]
    # outage points are LISTED but never series points
    assert [o["run"] for o in rep["outages"]] == ["t2b", "t5b"]
    assert [p["run"] for p in rep["series"]] == \
        ["t1", "t2", "t3", "t4", "t5", "t6", "t7"]
    cp = rep["changepoint"]
    assert cp is not None
    assert cp["run"] == doc["expected_changepoint_run"] == "t5"
    assert cp["direction"] == "down"
    assert cp["before_mean"] == pytest.approx(100325.0)
    assert cp["after_mean"] == pytest.approx(86066.67, abs=0.01)


def test_changepoint_quiet_on_flat_and_short_series():
    assert rundiff.changepoint([100.0, 100.4, 99.7, 100.1, 99.9,
                                100.2]) is None
    assert rundiff.changepoint([100.0, 50.0]) is None  # < 2*min_seg
    assert rundiff.changepoint([]) is None


# ----------------------------------------------------------- schema v6 pins --

def test_schema_v6_forensics_contracts():
    """The version and both forensics tags' required fields are pinned,
    and real index/diff output round-trips through JSON + validates."""
    assert EVENT_SCHEMA_VERSION == 7  # v7 added the reshard_event family
    assert EVENT_REQUIRED["run_card"] == \
        ("run", "kind", "outage", "baseline_eligible")
    assert EVENT_REQUIRED["run_diff"] == \
        ("run_a", "run_b", "config_delta", "suspects")
    card = runindex.card_from_run_dir(RUN_A)
    doc = rundiff.diff_runs(card, runindex.card_from_run_dir(RUN_B))
    for rec in (card, doc):
        rt = json.loads(json.dumps(rec))
        assert rt == rec  # JSON roundtrip is lossless
        assert validate_record(rt) == []
    bad = {k: v for k, v in doc.items() if k != "suspects"}
    assert any("suspects" in p for p in validate_record(bad))
    bad_card = dict(card, outage=True, baseline_eligible=True)
    assert any("never" in p for p in runindex.validate_card(bad_card))


def test_run_stamp_deterministic():
    cfg = {"model": "45m", "batch": 32, "paged": True}
    s1, s2 = runindex.run_stamp(cfg), runindex.run_stamp(dict(cfg))
    assert s1["config_fingerprint"] == s2["config_fingerprint"]
    assert s1["config"] == s2["config"]
    assert runindex.config_fingerprint(dict(cfg, batch=64)) != \
        s1["config_fingerprint"]


# --------------------------------------------------------- --explain gate --

def test_gate_explain_attaches_forensics_on_failure(capsys):
    gate = _load_script("check_bench_regression")
    rc = gate.main(["--fresh", os.path.join(RUN_B, "bench_paged.json"),
                    "--baseline", os.path.join(RUN_A, "bench_paged.json"),
                    "--tol_pct", "0", "--tol_latency_pct", "0",
                    "--explain"])
    cap = capsys.readouterr()
    assert rc == 1
    out = json.loads(cap.out.splitlines()[0])
    assert out["status"] == "regression"
    forensics = out["forensics"]
    assert forensics["diff"]["suspects"][0]["knob"] == "pages_per_block"
    assert forensics["diff"]["suspects"][0]["phase"] == "copy"
    # the stderr report names the suspect — a red gate ships its triage
    assert "pages_per_block" in cap.err and "suspects" in cap.err


def test_gate_explain_silent_on_pass(capsys):
    gate = _load_script("check_bench_regression")
    rc = gate.main(["--fresh", os.path.join(RUN_A, "bench_paged.json"),
                    "--baseline", os.path.join(RUN_A, "bench_paged.json"),
                    "--explain"])
    cap = capsys.readouterr()
    assert rc == 0
    out = json.loads(cap.out.splitlines()[0])
    assert out["status"] == "ok"
    assert "forensics" not in out


def test_gate_explain_refused_with_controller():
    gate = _load_script("check_bench_regression")
    with pytest.raises(SystemExit) as e:
        gate.parse_args(["--fresh", "x.json", "--controller",
                         "--explain"])
    assert e.value.code not in (0, None)


# ------------------------------------------------------------ obs_diff CLI --

def test_obs_diff_pairwise_cli(capsys):
    od = _load_script("obs_diff")
    rc = od.main([RUN_A, RUN_B])
    cap = capsys.readouterr()
    assert rc == 0
    doc = json.loads(cap.out.strip())
    assert doc["tag"] == "run_diff"
    assert doc["run_a"] == "run_a" and doc["run_b"] == "run_b"
    assert doc["suspects"][0]["knob"] == "pages_per_block"
    assert "suspects (ranked)" in cap.err


def test_obs_diff_card_and_bare_name_resolution(capsys):
    od = _load_script("obs_diff")
    assert od.main(["--card", RUN_A]) == 0
    card = json.loads(capsys.readouterr().out.strip())
    assert card["tag"] == "run_card" and card["run"] == "run_a"
    # bare round names resolve against the repo (r02 -> BENCH_r02.json)
    assert od.main(["r02", "r01"]) == 0
    doc = json.loads(capsys.readouterr().out.strip())
    assert doc["run_a"] == "BENCH_r02" and doc["run_b"] == "BENCH_r01"
    assert doc["outage_a"] is not None  # r02's outage is carried along
    assert od.main(["--card", "nonexistent_run_xyz"]) == 2
    capsys.readouterr()


def test_obs_diff_triage_picks_comparable_baseline(tmp_path, capsys):
    """--triage auto-picks the best comparable baseline: same unit,
    outages excluded, matching fingerprint preferred."""
    od = _load_script("obs_diff")
    repo = tmp_path / "repo"
    (repo / "runs").mkdir(parents=True)
    # trajectory: r01 healthy (different fingerprint), r02 an outage,
    # r03 healthy with run_b's fingerprint -> triage must pick r03
    a = json.load(open(os.path.join(RUN_A, "bench_paged.json")))
    b = json.load(open(os.path.join(RUN_B, "bench_paged.json")))
    (repo / "BENCH_r01.json").write_text(json.dumps(a))
    (repo / "BENCH_r02.json").write_text(json.dumps(
        {"n": 2, "rc": 1, "tail": "Traceback ...", "parsed": None}))
    (repo / "BENCH_r03.json").write_text(json.dumps(b))
    fresh = tmp_path / "fresh.json"
    fresh.write_text(json.dumps(dict(b, value=3000.0)))
    rc = od.main(["--triage", str(fresh), "--repo", str(repo)])
    cap = capsys.readouterr()
    assert rc == 0
    doc = json.loads(cap.out.strip())
    assert doc["run_a"] == "BENCH_r03"  # fingerprint match beats r01
    assert "baseline BENCH_r03" in cap.err
    # no comparable unit at all -> an answer, not an error
    lonely = tmp_path / "lonely.json"
    lonely.write_text(json.dumps({"metric": "weird", "value": 1.0,
                                  "unit": "furlongs"}))
    assert od.main(["--triage", str(lonely), "--repo", str(repo)]) == 0
    doc = json.loads(capsys.readouterr().out.strip())
    assert doc["note"] == "no comparable baseline"


def test_obs_diff_index_counts_real_repo(capsys):
    """--index over the real repo: every committed BENCH round + every
    runs/ dir gets a card, r02-r05 classified as outages, and no outage
    is baseline-eligible."""
    od = _load_script("obs_diff")
    assert od.main(["--index"]) == 0
    cards = json.loads(capsys.readouterr().out.strip())["cards"]
    by_run = {c["run"]: c for c in cards}
    assert by_run["BENCH_r01"]["baseline_eligible"] is True
    for r in ("BENCH_r02", "BENCH_r03", "BENCH_r04", "BENCH_r05"):
        assert by_run[r]["outage"] is True, r
    assert all(not (c["outage"] and c["baseline_eligible"])
               for c in cards)
    assert all(runindex.validate_card(c) == [] for c in cards)


# --------------------------------------------------- record stamping (e2e) --

def test_serve_record_carries_provenance_stamp(tmp_path, capsys):
    """The serving summary record uniformly stamps config_fingerprint +
    git_rev, and the fingerprint is recomputable from the stamped
    config — the stamp round-trips into a card the index can join on."""
    from distributed_pytorch_from_scratch_tpu.serving import serve as sv
    sv.main(["--dry_run", "--log_dir", str(tmp_path / "logs")])
    rec = None
    for line in capsys.readouterr().out.splitlines():
        if line.startswith("{"):
            obj = json.loads(line)
            if "metric" in obj:
                rec = obj
    assert rec is not None
    assert rec["config_fingerprint"] == \
        runindex.config_fingerprint(rec["config"])
    assert "git_rev" in rec
    card = runindex.card_from_record(rec, run="dry", source="stdout")
    assert card["legacy"] is False
    assert card["baseline_eligible"] is True
    assert card["config_fingerprint"] == rec["config_fingerprint"]
