"""Numerical-equivalence tests for the parallel layers.

Ports the reference's testing idiom (SURVEY §4;
`/root/reference/tests/test_column_parallel_linear.py`,
`test_row_parallel_linear.py`, `test_parallel_vocab_embedding.py`) to JAX:

1. init equality — the sharded layer's global param IS the full init (one
   PRNG key; the reference needed an RNG save/restore + broadcast dance);
2. forward allclose against a plain jnp oracle across a grid of shapes;
3. gradient equality (input grads full, weight grads slice-vs-slice);
4. multi-step training equivalence — hundreds of SGD steps on sharded vs
   vanilla, asserting the full loss history matches (the reference runs 1000
   steps on 2 GPUs; under jit determinism we get tighter tolerances with
   fewer steps).

All tests run on the virtual 8-device CPU mesh from conftest.

EQUIV_STEPS env var overrides the multi-step history length (default 200;
set 1000 to reproduce the reference's exact bar — run recorded in
BASELINE.md).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributed_pytorch_from_scratch_tpu.config import MeshConfig
from distributed_pytorch_from_scratch_tpu.parallel.embedding import VocabParallelEmbedding
from distributed_pytorch_from_scratch_tpu.parallel.linear import (
    ColumnParallelLinear, RowParallelLinear)
from distributed_pytorch_from_scratch_tpu.parallel.norm import RMSNorm
from distributed_pytorch_from_scratch_tpu.runtime.mesh import make_mesh

EQUIV_STEPS = int(os.environ.get("EQUIV_STEPS", "200"))

TP = 4


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(MeshConfig(dp=1, tp=TP))


def run_sharded(mesh, fn, in_specs, out_specs, *args):
    return jax.jit(jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs))(*args)


# ---------------------------------------------------------------- column ----

DIM_GRID = [(16, 32), (64, 16), (32, 32)]
SHAPE_GRID = [(2, 8), (4, 1), (1, 16)]


@pytest.mark.parametrize("idim,odim", DIM_GRID)
@pytest.mark.parametrize("bias", [True, False])
def test_column_parallel_forward_and_grads(mesh, idim, odim, bias):
    layer = ColumnParallelLinear(idim, odim, add_bias=bias, gather_output=False)
    key = jax.random.key(42)
    params = layer.init(key)

    for b, t in SHAPE_GRID:
        x = jax.random.normal(jax.random.fold_in(key, b * 100 + t), (b, t, idim))

        def sharded_loss(params, x):
            y = layer.apply(params, x)                    # local (b,t,odim/n)
            coef = jnp.arange(1.0, odim + 1.0)
            local = jax.lax.dynamic_slice_in_dim(
                coef, jax.lax.axis_index("tp") * (odim // TP), odim // TP)
            s = jnp.sum(y * local)                        # distinct per column
            return jax.lax.psum(s, "tp")

        def oracle_loss(params, x):
            y = x @ params["weight"]
            if bias:
                y = y + params["bias"]
            return jnp.sum(y * jnp.arange(1.0, odim + 1.0))

        in_specs = (layer.specs(), P())
        loss = run_sharded(mesh, sharded_loss, in_specs, P(), params, x)
        ref = oracle_loss(params, x)
        np.testing.assert_allclose(loss, ref, rtol=1e-5)

        g_sh = jax.jit(jax.grad(jax.shard_map(
            sharded_loss, mesh=mesh, in_specs=in_specs, out_specs=P()),
            argnums=(0, 1)))(params, x)
        g_ref = jax.grad(oracle_loss, argnums=(0, 1))(params, x)
        jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5),
                     g_sh, g_ref)


def test_column_parallel_gather_output(mesh):
    idim, odim = 16, 32
    layer = ColumnParallelLinear(idim, odim, gather_output=True)
    params = layer.init(jax.random.key(0))
    x = jax.random.normal(jax.random.key(1), (2, 4, idim))

    out = run_sharded(
        mesh,
        lambda p, x: jax.lax.psum(jnp.sum(layer.apply(p, x), axis=-1).mean(), "tp") / TP,
        (layer.specs(), P()), P(), params, x)
    # gathered output summed over full odim must be tp-invariant; compare to oracle
    ref = jnp.sum(x @ params["weight"] + params["bias"], axis=-1).mean()
    np.testing.assert_allclose(out, ref, rtol=1e-5)


# ------------------------------------------------------------------- row ----

@pytest.mark.parametrize("idim,odim", DIM_GRID)
@pytest.mark.parametrize("bias", [True, False])
@pytest.mark.parametrize("split_input", [True, False])
def test_row_parallel_forward_and_grads(mesh, idim, odim, bias, split_input):
    layer = RowParallelLinear(idim, odim, add_bias=bias, split_input=split_input)
    key = jax.random.key(7)
    params = layer.init(key)
    x = jax.random.normal(jax.random.fold_in(key, 1), (2, 4, idim))

    def sharded_loss(params, x):
        if not split_input:
            # caller supplies pre-sharded input: slice it here to simulate
            from distributed_pytorch_from_scratch_tpu.ops.collectives import split_to
            x = split_to(x, "tp")
        y = layer.apply(params, x)
        return jnp.sum(y * y) / y.size

    def oracle_loss(params, x):
        y = x @ params["weight"]
        if bias:
            y = y + params["bias"]
        return jnp.sum(y * y) / y.size

    in_specs = (layer.specs(), P())
    loss = run_sharded(mesh, sharded_loss, in_specs, P(), params, x)
    np.testing.assert_allclose(loss, oracle_loss(params, x), rtol=1e-5)

    g_sh = jax.jit(jax.grad(jax.shard_map(
        sharded_loss, mesh=mesh, in_specs=in_specs, out_specs=P()),
        argnums=(0, 1)))(params, x)
    g_ref = jax.grad(oracle_loss, argnums=(0, 1))(params, x)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5),
                 g_sh, g_ref)


# ------------------------------------------------------------- embedding ----

@pytest.mark.parametrize("vocab", [64, 100, 1024])  # 100: non-divisible -> padded
def test_vocab_parallel_embedding_forward(mesh, vocab):
    hdim = 16
    layer = VocabParallelEmbedding(vocab, hdim, tp_size=TP)
    params = layer.init(jax.random.key(3))
    ids = jax.random.randint(jax.random.key(4), (2, 10), 0, vocab)

    out = run_sharded(mesh, layer.apply, (layer.specs(), P()), P(None, None, "tp"),
                      params, ids)
    # out stitched over a fake last-dim sharding of identical copies -> tile;
    # take the first hdim block and compare with a plain take.
    out = out[..., :hdim]
    ref = jnp.take(params["weight"], ids, axis=0)
    np.testing.assert_allclose(out, ref, rtol=1e-6, atol=1e-6)


def test_vocab_parallel_embedding_grads(mesh):
    vocab, hdim = 64, 8
    layer = VocabParallelEmbedding(vocab, hdim, tp_size=TP)
    params = layer.init(jax.random.key(5))
    ids = jax.random.randint(jax.random.key(6), (4, 6), 0, vocab)

    def sharded_loss(params, ids):
        out = layer.apply(params, ids)
        return jnp.sum(out * out)

    def oracle_loss(params, ids):
        out = jnp.take(params["weight"], ids, axis=0)
        return jnp.sum(out * out)

    loss = run_sharded(mesh, sharded_loss, (layer.specs(), P()), P(), params, ids)
    np.testing.assert_allclose(loss, oracle_loss(params, ids), rtol=1e-5)

    g_sh = jax.jit(jax.grad(jax.shard_map(
        sharded_loss, mesh=mesh, in_specs=(layer.specs(), P()), out_specs=P())))(params, ids)
    g_ref = jax.grad(oracle_loss)(params, ids)
    np.testing.assert_allclose(g_sh["weight"], g_ref["weight"], rtol=1e-5, atol=1e-6)


def test_embedding_does_not_mutate_input(mesh):
    """The reference mutates ids in place (`layers.py:138`, SURVEY quirk #4).
    JAX arrays are immutable, but assert the contract anyway."""
    vocab, hdim = 64, 8
    layer = VocabParallelEmbedding(vocab, hdim, tp_size=TP)
    params = layer.init(jax.random.key(5))
    ids = jax.random.randint(jax.random.key(6), (2, 5), 0, vocab)
    before = np.asarray(ids).copy()
    run_sharded(mesh, layer.apply, (layer.specs(), P()), P(None, None, "tp"), params, ids)
    np.testing.assert_array_equal(np.asarray(ids), before)


# -------------------------------------------------- multi-step training -----

def _column_parallel_history(mesh, steps):
    """Shared body of the column-parallel multi-step check — the default
    lane runs it at EQUIV_STEPS, the slow lane at the reference's full
    1000 steps (see below)."""
    idim, odim, lr = 16, 32, 1e-2
    layer = ColumnParallelLinear(idim, odim, gather_output=False)
    key = jax.random.key(11)
    params_sh = layer.init(key)
    params_ref = jax.tree.map(jnp.copy, params_sh)

    def sharded_loss(params, x, y_tgt):
        y = layer.apply(params, x)                       # local shard
        from distributed_pytorch_from_scratch_tpu.ops.collectives import split_to
        tgt = split_to(y_tgt, "tp")
        local = jnp.sum((y - tgt) ** 2)
        return jax.lax.psum(local, "tp") / y_tgt.size

    def oracle_loss(params, x, y_tgt):
        y = x @ params["weight"] + params["bias"]
        return jnp.sum((y - y_tgt) ** 2) / y_tgt.size

    sh_loss_fn = jax.jit(jax.value_and_grad(jax.shard_map(
        sharded_loss, mesh=mesh, in_specs=(layer.specs(), P(), P()), out_specs=P())))
    ref_loss_fn = jax.jit(jax.value_and_grad(oracle_loss))

    hist_sh, hist_ref = [], []
    for step in range(steps):
        k = jax.random.fold_in(key, 1000 + step)
        x = jax.random.normal(k, (4, idim))
        y_tgt = jax.random.normal(jax.random.fold_in(k, 1), (4, odim))
        l_sh, g_sh = sh_loss_fn(params_sh, x, y_tgt)
        l_ref, g_ref = ref_loss_fn(params_ref, x, y_tgt)
        params_sh = jax.tree.map(lambda p, g: p - lr * g, params_sh, g_sh)
        params_ref = jax.tree.map(lambda p, g: p - lr * g, params_ref, g_ref)
        hist_sh.append(float(l_sh))
        hist_ref.append(float(l_ref))

    np.testing.assert_allclose(hist_sh, hist_ref, atol=1e-5)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, atol=1e-5),
                 params_sh, params_ref)


def test_column_parallel_multi_step_training(mesh):
    """Reference check #3 (`test_column_parallel_linear.py:111-135`): many
    SGD steps on parallel vs vanilla; final weights AND the whole loss
    history must match."""
    _column_parallel_history(mesh, EQUIV_STEPS)


@pytest.mark.slow
def test_column_parallel_multi_step_training_full_reference_bar(mesh):
    """VERDICT r5 #6: the reference asserts its equivalence over 1000
    optimizer steps (`test_column_parallel_linear.py:111-135`). The
    default lane runs EQUIV_STEPS (200) for speed; this slow-lane pin
    runs the FULL 1000 unconditionally, so the reference's bar stays
    continuously green in CI instead of only via the EQUIV_STEPS env
    override once per round."""
    _column_parallel_history(mesh, 1000)


def test_row_parallel_multi_step_training(mesh):
    idim, odim, steps, lr = 32, 16, EQUIV_STEPS, 1e-2
    layer = RowParallelLinear(idim, odim, split_input=True)
    key = jax.random.key(13)
    params_sh = layer.init(key)
    params_ref = jax.tree.map(jnp.copy, params_sh)

    def sharded_loss(params, x, y_tgt):
        y = layer.apply(params, x)
        return jnp.sum((y - y_tgt) ** 2) / y_tgt.size

    def oracle_loss(params, x, y_tgt):
        y = x @ params["weight"] + params["bias"]
        return jnp.sum((y - y_tgt) ** 2) / y_tgt.size

    sh_loss_fn = jax.jit(jax.value_and_grad(jax.shard_map(
        sharded_loss, mesh=mesh, in_specs=(layer.specs(), P(), P()), out_specs=P())))
    ref_loss_fn = jax.jit(jax.value_and_grad(oracle_loss))

    for step in range(steps):
        k = jax.random.fold_in(key, 2000 + step)
        x = jax.random.normal(k, (4, idim))
        y_tgt = jax.random.normal(jax.random.fold_in(k, 1), (4, odim))
        l_sh, g_sh = sh_loss_fn(params_sh, x, y_tgt)
        l_ref, g_ref = ref_loss_fn(params_ref, x, y_tgt)
        np.testing.assert_allclose(l_sh, l_ref, atol=1e-5)
        params_sh = jax.tree.map(lambda p, g: p - lr * g, params_sh, g_sh)
        params_ref = jax.tree.map(lambda p, g: p - lr * g, params_ref, g_ref)

    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, atol=1e-5),
                 params_sh, params_ref)


def test_embedding_multi_step_training(mesh):
    """Reference `test_parallel_vocab_embedding.py:114-134`: toy model
    (vocab-parallel embedding -> column-parallel linear), Adam-free SGD."""
    vocab, hdim, odim, steps, lr = 64, 8, 12, max(100, EQUIV_STEPS // 2), 1e-2
    emb = VocabParallelEmbedding(vocab, hdim, tp_size=TP)
    lin = ColumnParallelLinear(hdim, odim, gather_output=False)
    key = jax.random.key(17)
    params_sh = {"emb": emb.init(key), "lin": lin.init(jax.random.fold_in(key, 1))}
    params_ref = jax.tree.map(jnp.copy, params_sh)
    specs = {"emb": emb.specs(), "lin": lin.specs()}

    def sharded_loss(params, ids, tgt):
        x = emb.apply(params["emb"], ids)
        y = lin.apply(params["lin"], x)                  # local (b,t,odim/n)
        from distributed_pytorch_from_scratch_tpu.ops.collectives import split_to
        t_local = split_to(tgt, "tp")
        return jax.lax.psum(jnp.sum((y - t_local) ** 2), "tp") / tgt.size

    def oracle_loss(params, ids, tgt):
        x = jnp.take(params["emb"]["weight"], ids, axis=0)
        y = x @ params["lin"]["weight"] + params["lin"]["bias"]
        return jnp.sum((y - tgt) ** 2) / tgt.size

    sh_fn = jax.jit(jax.value_and_grad(jax.shard_map(
        sharded_loss, mesh=mesh, in_specs=(specs, P(), P()), out_specs=P())))
    ref_fn = jax.jit(jax.value_and_grad(oracle_loss))

    for step in range(steps):
        k = jax.random.fold_in(key, 3000 + step)
        ids = jax.random.randint(k, (4, 6), 0, vocab)
        tgt = jax.random.normal(jax.random.fold_in(k, 1), (4, 6, odim))
        l_sh, g_sh = sh_fn(params_sh, ids, tgt)
        l_ref, g_ref = ref_fn(params_ref, ids, tgt)
        np.testing.assert_allclose(l_sh, l_ref, atol=1e-5)
        params_sh = jax.tree.map(lambda p, g: p - lr * g, params_sh, g_sh)
        params_ref = jax.tree.map(lambda p, g: p - lr * g, params_ref, g_ref)

    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, atol=1e-5),
                 params_sh, params_ref)


def test_rmsnorm_matches_formula():
    layer = RMSNorm(16)
    params = layer.init(jax.random.key(0))
    x = jax.random.normal(jax.random.key(1), (2, 3, 16))
    out = layer.apply(params, x)
    ref = x * (1.0 / np.sqrt(np.mean(np.asarray(x) ** 2, axis=-1, keepdims=True) + 1e-5))
    np.testing.assert_allclose(out, ref, rtol=1e-5)
