"""Continuous-batching serving engine (serving/) correctness.

The anchor contract: continuous-batched GREEDY decode is token-identical
to per-prompt `models/decode.GreedyDecoder` output — for every request,
across arrival orders, slot reuse, prefill length-bucketing, and tp
sharding. Both drivers share one lowering (`_prefill` / `_decode_one` /
the sampler filters), and each row's math is row-independent, so the
equality is exact, not approximate.

Plus: slot refill must not leak the prior occupant's cache rows, sampled
decoding must reproduce per request seed regardless of batch mix, the
FIFO scheduler's bucket grouping and backpressure bound, and the serve.py
--dry_run CPU smoke (the CLI surface cannot rot on chip-less images).
"""

import json
import os

import jax
import pytest

from distributed_pytorch_from_scratch_tpu.config import MeshConfig, ModelConfig
from distributed_pytorch_from_scratch_tpu.models.decode import GreedyDecoder
from distributed_pytorch_from_scratch_tpu.models.transformer import Transformer
from distributed_pytorch_from_scratch_tpu.runtime.mesh import make_mesh
from distributed_pytorch_from_scratch_tpu.serving.engine import (
    ContinuousBatchingEngine, Request)
from distributed_pytorch_from_scratch_tpu.serving.scheduler import (
    FIFOScheduler, QueueFull, bucket_width)

CFG = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=8, num_layers=2,
                  vocab_size=96, maxlen=64)
BUF = 32
EOS = 1

PROMPTS = [
    [0, 5, 17, 33, 60],
    [0, 95],                        # boundary vocab id
    [0, 2, 4, 6, 8, 10, 12, 14],    # longer prompt (different bucket)
    [0, 7],
    [0, 9, 11],
    [0, 3, 5, 7, 11, 13, 17],
]


def _setup(tp, seed=7):
    mesh = make_mesh(MeshConfig(dp=1, tp=tp))
    model = Transformer(CFG, tp_size=tp)
    params = jax.device_put(model.init(jax.random.key(seed)),
                            model.shardings(mesh))
    return mesh, model, params


@pytest.mark.parametrize("tp", [1, 2])
def test_engine_matches_greedy_decoder(tp):
    """Staggered admissions + forced slot reuse (6 requests through 2
    slots), submissions in a shuffled order mid-flight: every request's
    greedy tokens equal its solo GreedyDecoder decode."""
    mesh, model, params = _setup(tp)
    dec = GreedyDecoder(model, mesh, BUF)
    refs = [dec.decode(params, p, EOS, max_total_len=len(p) + 10)
            for p in PROMPTS]
    eng = ContinuousBatchingEngine(model, mesh, params, num_slots=2,
                                   buf_len=BUF, eos_id=EOS,
                                   prefill_bucket=8, max_prefill_batch=2)
    reqs = [Request(rid=i, prompt=p, max_new=10)
            for i, p in enumerate(PROMPTS)]
    eng.submit(reqs[0])
    eng.submit(reqs[1])
    for _ in range(3):              # let the first two run a few tokens
        eng.step()
    for r in reversed(reqs[2:]):    # late arrivals, reversed order
        eng.submit(r)
    eng.run_to_completion()
    got = {r.rid: r.tokens for r in eng.completed}
    assert len(got) == len(PROMPTS)
    for i, ref in enumerate(refs):
        assert got[i] == ref, (tp, i, got[i], ref)
    # 6 requests through 2 slots: slots were reused, not just filled once
    assert eng.stats()["completed"] == 6


def test_slot_refill_does_not_leak_prior_occupant():
    """A refilled slot must behave exactly like a fresh one: decode a
    long-prompt request through a 1-slot engine (filling many cache rows),
    then a short-prompt request into the SAME slot — its tokens must equal
    a fresh engine's (and GreedyDecoder's) output. A leak of the prior
    occupant's K/V rows would perturb the attention sums."""
    mesh, model, params = _setup(2, seed=3)
    long_req = [0] + list(range(3, 25))      # fills rows 0..22+
    short = [0, 5, 9]
    ref = GreedyDecoder(model, mesh, BUF).decode(
        params, short, EOS, max_total_len=len(short) + 8)

    eng = ContinuousBatchingEngine(model, mesh, params, num_slots=1,
                                   buf_len=BUF, eos_id=EOS,
                                   prefill_bucket=8)
    eng.submit(Request(rid=0, prompt=long_req, max_new=6))
    eng.run_to_completion()
    eng.submit(Request(rid=1, prompt=short, max_new=8))
    eng.run_to_completion()
    got = {r.rid: r.tokens for r in eng.completed}
    assert got[1] == ref, (got[1], ref)


def test_engine_matches_greedy_decoder_gpt2():
    """The second model family (learned positions, LayerNorm, gelu, tied
    head) through the same engine programs."""
    from distributed_pytorch_from_scratch_tpu.models.gpt2 import (
        GPT2Transformer)
    cfg = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=4, num_layers=2,
                      vocab_size=96, maxlen=64)
    mesh = make_mesh(MeshConfig(dp=1, tp=2))
    model = GPT2Transformer(cfg, tp_size=2)
    params = jax.device_put(model.init(jax.random.key(9)),
                            model.shardings(mesh))
    prompts = [[0, 4, 8, 15], [0, 16, 23, 42, 7, 3]]
    refs = [GreedyDecoder(model, mesh, BUF).decode(
        params, p, EOS, max_total_len=len(p) + 8) for p in prompts]
    eng = ContinuousBatchingEngine(model, mesh, params, num_slots=2,
                                   buf_len=BUF, eos_id=EOS, prefill_bucket=8)
    for i, p in enumerate(prompts):
        eng.submit(Request(rid=i, prompt=p, max_new=8))
    eng.run_to_completion()
    got = {r.rid: r.tokens for r in eng.completed}
    for i, ref in enumerate(refs):
        assert got[i] == ref, (i, got[i], ref)


def test_sampling_reproducible_per_request_seed():
    """A sampled request's tokens are a pure function of ITS seed (and the
    engine's sampling knobs) — independent of arrival order, slot
    placement, and what shares the batch."""
    mesh, model, params = _setup(2, seed=0)
    kw = dict(num_slots=2, buf_len=BUF, eos_id=EOS, prefill_bucket=8,
              temperature=1.0, top_k=8)

    solo = ContinuousBatchingEngine(model, mesh, params, **kw)
    solo.submit(Request(rid=0, prompt=[0, 5, 17], max_new=10, seed=11))
    solo.run_to_completion()
    solo_tokens = solo.completed[0].tokens

    # same request, different batch mix and arrival position
    crowd = ContinuousBatchingEngine(model, mesh, params, **kw)
    crowd.submit(Request(rid=90, prompt=[0, 9, 11, 13], max_new=6, seed=4))
    crowd.step()
    crowd.submit(Request(rid=91, prompt=[0, 2], max_new=6, seed=5))
    crowd.submit(Request(rid=0, prompt=[0, 5, 17], max_new=10, seed=11))
    crowd.run_to_completion()
    crowd_tokens = {r.rid: r.tokens for r in crowd.completed}[0]
    assert crowd_tokens == solo_tokens

    # a different seed should (overwhelmingly) diverge
    other = ContinuousBatchingEngine(model, mesh, params, **kw)
    other.submit(Request(rid=0, prompt=[0, 5, 17], max_new=10, seed=12))
    other.run_to_completion()
    assert (other.completed[0].tokens != solo_tokens
            or len(solo_tokens) <= 2)
    # all draws stay in the real vocab (padded columns masked)
    assert all(0 <= t < CFG.vocab_size for t in solo_tokens)


def test_max_new_budgets():
    """max_new is a per-request budget: 0 completes instantly with no
    tokens (and no slot), n caps the generation exactly like
    GreedyDecoder's total-length limit."""
    mesh, model, params = _setup(1, seed=5)
    eng = ContinuousBatchingEngine(model, mesh, params, num_slots=2,
                                   buf_len=BUF, eos_id=EOS, prefill_bucket=8)
    eng.submit(Request(rid=0, prompt=[0, 5, 9], max_new=0))
    eng.submit(Request(rid=1, prompt=[0, 5, 9], max_new=4, seed=0))
    eng.run_to_completion()
    got = {r.rid: r.tokens for r in eng.completed}
    assert got[0] == []
    ref = GreedyDecoder(model, mesh, BUF).decode(
        params, [0, 5, 9], EOS, max_total_len=3 + 4)
    assert got[1] == ref
    assert len(got[1]) <= 4


# ---- scheduler (pure host logic) ----


def test_scheduler_fifo_bucket_groups():
    """take_batch peels same-bucket PREFIXES off the queue head — strict
    FIFO admission with bucket-grouped prefill batching."""
    s = FIFOScheduler(buf_len=64, prefill_bucket=16)
    lens = [5, 9, 30, 7, 40]     # buckets: 16,16,32,16,48
    for i, n in enumerate(lens):
        s.submit(Request(rid=i, prompt=[0] * n, max_new=4))
    g1 = s.take_batch(8)
    assert [r.rid for r in g1] == [0, 1]       # stop at first width change
    assert s.group_width(g1) == 16
    g2 = s.take_batch(8)
    assert [r.rid for r in g2] == [2]
    g3 = s.take_batch(8)
    assert [r.rid for r in g3] == [3]          # rid 3 never jumped ahead
    assert [r.rid for r in s.take_batch(8)] == [4]
    assert s.take_batch(8) == []
    # max_requests caps the group
    for i, n in enumerate((4, 4, 4)):
        s.submit(Request(rid=10 + i, prompt=[0] * n, max_new=4))
    assert [r.rid for r in s.take_batch(2)] == [10, 11]


def test_scheduler_backpressure_and_validation():
    s = FIFOScheduler(buf_len=32, prefill_bucket=8, max_queue=2)
    s.submit(Request(rid=0, prompt=[0, 1, 2], max_new=4))
    s.submit(Request(rid=1, prompt=[0, 1, 2], max_new=4))
    with pytest.raises(QueueFull, match="full"):
        s.submit(Request(rid=2, prompt=[0, 1, 2], max_new=4))
    assert s.rejected == 1
    with pytest.raises(ValueError, match="leave room"):
        s.submit(Request(rid=3, prompt=[0] * 32, max_new=4))
    with pytest.raises(ValueError, match="max_new"):
        s.submit(Request(rid=4, prompt=[0], max_new=-1))
    with pytest.raises(ValueError, match="non-empty"):
        s.submit(Request(rid=5, prompt=[], max_new=4))


def test_bucket_width():
    assert bucket_width(5, 16, 64) == 16
    assert bucket_width(16, 16, 64) == 16
    assert bucket_width(17, 16, 64) == 32
    assert bucket_width(60, 16, 64) == 64      # clamped to the buffer
    assert bucket_width(5, 0, 64) == 64        # bucketing off = full buffer


def test_engine_refuses_cp_models():
    mesh = make_mesh(MeshConfig(cp=2, tp=2))
    model = Transformer(CFG, tp_size=2, cp_size=2)
    with pytest.raises(ValueError, match="cp=1"):
        ContinuousBatchingEngine(model, mesh, params=None, num_slots=2,
                                 buf_len=BUF, eos_id=EOS)


# ---- the serve CLI smoke (tier-1: the surface cannot rot on CPU) ----


def test_serve_dry_run_smoke(tmp_path):
    from distributed_pytorch_from_scratch_tpu.serving import serve as serve_mod

    log_dir = str(tmp_path / "serve")
    summary = serve_mod.main(["--dry_run", "--log_dir", log_dir])
    assert summary["completed"] == summary["requests"] > 0
    assert summary["tokens_per_sec"] > 0
    assert summary["ttft_ms_p50"] is not None
    # metrics events reached the writer (summarize_run.py's input)
    tags = [json.loads(l)["tag"]
            for l in open(os.path.join(log_dir, "metrics.jsonl"))]
    assert "serving_summary" in tags
    assert "serve_request" in tags
    # the Chrome trace finalised with prefill/decode spans
    trace = json.load(open(os.path.join(log_dir, "trace.json")))
    names = {ev.get("name") for ev in trace["traceEvents"]}
    assert "prefill" in names and "decode_step" in names
    # and summarize_run.py renders the serving section from it
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "_sr", os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "scripts", "summarize_run.py"))
    sr = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(sr)
    lines = sr.serving_lines(str(tmp_path))
    assert len(lines) == 1 and "TTFT p50/p95" in lines[0]
