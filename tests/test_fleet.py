"""Serving fleet v1 correctness — ISSUE 19.

The anchor contract extends across PROCESS boundaries: a 2-replica
router fleet is greedy token-identical to a single PagedEngine (across
shared-prefix batches and a replica restart), and disaggregated
prefill/decode joined by the KV page stream is token-identical to the
same engine colocated — at tp 1->1 and 2->1 (the export/import path
reshards heads), native and int8 pages. Page values depend only on the
prefix, so WHERE a request runs and HOW its pages travel change cost,
never tokens.

Plus the fleet-specific laws: `export_pages`/`import_pages` round-trip
bit-identical across tp widths (and map cp pages through the scratch-
aware array index), the router's shadow prefix index predicts the
replica's ACTUAL prefix_hit_tokens exactly in the concurrently-live
regime, ties break least-loaded, session affinity spills LOUDLY (a
`session_spill` event, never a silent drop), dispatch overhead stays
under 1 ms p50 on CPU, and the PR 12 cross-process waterfall pin
extends to THREE hops (router -> prefill -> transfer -> decode) with
span sum == cross-process wall.
"""

import json
import types

import jax
import numpy as np
import pytest

from distributed_pytorch_from_scratch_tpu.config import MeshConfig, ModelConfig
from distributed_pytorch_from_scratch_tpu.models.transformer import Transformer
from distributed_pytorch_from_scratch_tpu.obs.reqtrace import (
    RequestTracer, TraceContext, merge_traces)
from distributed_pytorch_from_scratch_tpu.runtime.mesh import make_mesh
from distributed_pytorch_from_scratch_tpu.serving.engine import (
    PagedEngine, Request)
from distributed_pytorch_from_scratch_tpu.serving.kv_manager import (
    PagedKVPool)
from distributed_pytorch_from_scratch_tpu.serving.router import FleetRouter
from distributed_pytorch_from_scratch_tpu.serving.scheduler import QueueFull
from distributed_pytorch_from_scratch_tpu.serving.transfer import (
    run_disaggregated)
from distributed_pytorch_from_scratch_tpu.training.metrics import (
    MetricsWriter)

CFG = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=8, num_layers=2,
                  vocab_size=96, maxlen=64)
BUF = 32
EOS = 1
PS = 8

# one full shared page (PS tokens) + distinct tails
_BASE = [0, 5, 17, 33, 60, 2, 4, 6]
PROMPTS = [
    _BASE + [7],
    _BASE + [9, 11],
    _BASE + [3, 5, 7, 11],
    _BASE + [13],
    _BASE + [21, 23],
    _BASE + [25],
]


def _setup(tp, seed=7, cp=1):
    mesh = make_mesh(MeshConfig(dp=1, tp=tp, cp=cp))
    model = Transformer(CFG, tp_size=tp, cp_size=cp)
    params = jax.device_put(model.init(jax.random.key(seed)),
                            model.shardings(mesh))
    return mesh, model, params


def _engine(tp=1, seed=7, **kw):
    mesh, model, params = _setup(tp, seed=seed)
    kw.setdefault("num_slots", 4)
    kw.setdefault("page_size", PS)
    kw.setdefault("prefill_chunk", PS)
    return PagedEngine(model, mesh, params, buf_len=BUF, eos_id=EOS, **kw)


def _reqs(max_new=8, rid0=0):
    return [Request(rid=rid0 + i, prompt=list(p), max_new=max_new)
            for i, p in enumerate(PROMPTS)]


def _assert_drained(eng):
    assert eng.pool.free_pages == eng.pool.num_pages, (
        eng.pool.free_pages, eng.pool.num_pages)
    assert (eng.pool.refcount == 0).all()
    assert not eng.pool._children and not eng.pool._page_keys


# ------------------------------------------- page export/import round-trip

def _pool(tp=1, cp=1, kv_dtype=None, num_pages=8):
    mesh = make_mesh(MeshConfig(dp=1, tp=tp, cp=cp))
    model = types.SimpleNamespace(cfg=CFG, cp_size=cp)
    return PagedKVPool(model, mesh, num_pages, PS, kv_dtype=kv_dtype)


def _rand_like(a, n, rng):
    shape = (a.shape[0], n) + tuple(a.shape[2:])
    if np.issubdtype(np.dtype(a.dtype), np.integer):
        return rng.integers(-100, 100, shape).astype(a.dtype)
    return rng.standard_normal(shape).astype(a.dtype)


def _tree_eq(a, b):
    jax.tree.map(np.testing.assert_array_equal, a, b)


@pytest.mark.parametrize("tp_a,tp_b", [(1, 1), (1, 2), (2, 1)])
@pytest.mark.parametrize("kv_dtype", [None, "int8"])
def test_export_import_roundtrip_across_tp(tp_a, tp_b, kv_dtype):
    """Host KV pages import -> export bit-identical, then survive a
    SECOND pool at a different tp width unchanged: export is global head
    layout, so the tp reshard is implicit in the device put."""
    rng = np.random.default_rng(3)
    pa = _pool(tp=tp_a, kv_dtype=kv_dtype)
    k = jax.tree.map(lambda a: _rand_like(a, 3, rng), pa.ks)
    v = jax.tree.map(lambda a: _rand_like(a, 3, rng), pa.vs)
    pages = pa.import_pages(k, v)
    assert len(pages) == 3
    assert pa.free_pages == pa.num_pages - 3
    k1, v1 = pa.export_pages(pages)
    _tree_eq(k, k1)
    _tree_eq(v, v1)
    pb = _pool(tp=tp_b, kv_dtype=kv_dtype)
    pages_b = pb.import_pages(k1, v1)
    k2, v2 = pb.export_pages(pages_b)
    _tree_eq(k, k2)
    _tree_eq(v, v2)
    for pool, pgs in ((pa, pages), (pb, pages_b)):
        for p in pgs:
            pool.unref(p)
        assert pool.free_pages == pool.num_pages


def test_import_pages_cp_mapping_and_rollback():
    """cp=2: owners map pages through the scratch-aware array index
    (rank r's pages live past r's scratch row), and a pool too dry for
    the batch rolls back EVERY lease before raising."""
    from distributed_pytorch_from_scratch_tpu.serving.kv_manager import (
        PoolExhausted)
    rng = np.random.default_rng(4)
    pool = _pool(cp=2, num_pages=16)          # 8 per rank
    k = jax.tree.map(lambda a: _rand_like(a, 3, rng), pool.ks)
    v = jax.tree.map(lambda a: _rand_like(a, 3, rng), pool.vs)
    pages = pool.import_pages(k, v, owners=[0, 1, 1])
    assert pages == [0, 8, 9]                 # rank 0 page 0; rank 1 pages
    k1, v1 = pool.export_pages(pages)
    _tree_eq(k, k1)
    _tree_eq(v, v1)
    free_before = pool.free_pages
    big_k = jax.tree.map(lambda a: _rand_like(a, 14, rng), pool.ks)
    big_v = jax.tree.map(lambda a: _rand_like(a, 14, rng), pool.vs)
    with pytest.raises(PoolExhausted):
        pool.import_pages(big_k, big_v)       # 14 > 13 remaining
    assert pool.free_pages == free_before     # full rollback
    for p in pages:
        pool.unref(p)
    assert pool.free_pages == pool.num_pages


# ------------------------------------------------- router token identity

def test_fleet_token_identity_with_restart():
    """2-replica router fleet == single PagedEngine on a shared-prefix
    batch; then r0 is REPLACED (restart) and a second batch still
    matches. Pools drain on every engine."""
    single = _engine(num_slots=4)
    for r in _reqs():
        single.submit(r)
    single.run_to_completion()
    refs = {r.rid: list(r.tokens) for r in single.completed}
    assert len(refs) == len(PROMPTS) and any(refs.values())

    # prefix_weight dialed DOWN so the load term actually spreads the
    # shared-prefix burst across replicas — the identity claim is only
    # interesting when both replicas serve (default weights correctly
    # concentrate a fully-shared burst on the replica holding the page)
    replicas = [_engine(num_slots=2), _engine(num_slots=2)]
    router = FleetRouter(replicas, prefix_weight=0.5)
    done = {}
    for r in _reqs():
        router.submit(r)
        done.update({d.rid: list(d.tokens) for d in router.step()})
    done.update({r.rid: list(r.tokens) for r in router.run_to_completion()})
    assert done == refs
    # the load term spread the burst: both replicas took work
    assert min(router.dispatched.values()) >= 1, router.dispatched

    fresh = _engine(num_slots=2)
    router.replace_replica("r0", fresh)
    for r in _reqs(rid0=100):
        router.submit(r)
    done2 = {r.rid - 100: list(r.tokens)
             for r in router.run_to_completion()}
    assert done2 == refs
    for _, e in router.replicas:
        _assert_drained(e)
    _assert_drained(single)


# --------------------------------------------------------- dispatch laws

def test_shadow_prediction_equals_actual_prefix_hits():
    """The dispatch law: in the concurrently-live regime (slots >=
    burst) the router-side shadow predicts each replica's ACTUAL
    prefix_hit_tokens counter exactly. Plus the CPU overhead pin:
    dispatch p50 under 1 ms."""
    replicas = [_engine(num_slots=8), _engine(num_slots=8)]
    router = FleetRouter(replicas)
    for r in _reqs():
        router.submit(r)
    router.run_to_completion()
    predicted = {}
    for rid, (name, hit) in router.predicted.items():
        predicted[name] = predicted.get(name, 0) + hit
    for name, eng in router.replicas:
        assert predicted.get(name, 0) == eng.prefix_hit_tokens, (
            name, predicted, eng.prefix_hit_tokens)
    # the shared page was predicted at least once (the law isn't 0 == 0)
    assert sum(predicted.values()) >= PS
    st = router.stats()
    assert st["dispatch_ms_p50"] < 1.0, st


def test_router_least_loaded_tiebreak():
    """No prefix signal anywhere -> equal scores break by replica order;
    a queued request then tips the load term toward the idle replica."""
    router = FleetRouter([_engine(num_slots=2), _engine(num_slots=2)])
    # fully distinct prompts (no common lead token): zero prefix signal
    a = Request(rid=0, prompt=[2, 9, 21], max_new=2)
    b = Request(rid=1, prompt=[5, 13, 37], max_new=2)
    assert router.submit(a) == "r0"           # tie -> first replica
    assert router.submit(b) == "r1"           # r0 now loaded
    router.run_to_completion()
    for _, e in router.replicas:
        _assert_drained(e)


def test_session_affinity_and_loud_spill(tmp_path):
    """A session sticks to its replica; when that replica refuses
    (QueueFull) the request SPILLS to the next best with a
    `session_spill` writer event — and only a fleet-wide refusal
    reaches the caller."""
    w = MetricsWriter(str(tmp_path), process_index=0)
    router = FleetRouter([_engine(num_slots=1, max_queue=1),
                          _engine(num_slots=1, max_queue=1)],
                         writer=w)
    a = Request(rid=0, prompt=[0, 9, 21], max_new=2)
    first = router.submit(a, session="s1")
    # same session, pinned replica full -> loud spill to the other
    b = Request(rid=1, prompt=[0, 13, 37], max_new=2)
    spilled = router.submit(b, session="s1")
    assert spilled != first
    assert router.spills == 1
    # both replicas full -> fleet-wide refusal propagates
    with pytest.raises(QueueFull):
        router.submit(Request(rid=2, prompt=[0, 2, 4], max_new=2))
    assert router.rejected == 1
    router.run_to_completion()
    w.close()
    evs = [json.loads(l) for l in open(tmp_path / "metrics.jsonl")]
    spill = [e for e in evs if e.get("tag") == "session_spill"]
    assert len(spill) == 1
    assert spill[0]["session"] == "s1" and spill[0]["pinned"] == first


# ------------------------------------------- disaggregated prefill/decode

@pytest.mark.parametrize("tp_pre,tp_dec,kv_dtype",
                         [(1, 1, None), (2, 1, None), (1, 1, "int8")])
def test_disagg_token_identity(tp_pre, tp_dec, kv_dtype):
    """Prefill-engine -> KV page stream -> decode-engine output equals
    the same engine colocated — including across a tp reshard (2->1)
    and int8 pages (codes+scales travel, dequant math unchanged)."""
    coloc = _engine(tp=tp_dec, kv_dtype=kv_dtype)
    for r in _reqs():
        coloc.submit(r)
    coloc.run_to_completion()
    refs = {r.rid: list(r.tokens) for r in coloc.completed}

    pre = _engine(tp=tp_pre, kv_dtype=kv_dtype, prefill_only=True)
    dec = _engine(tp=tp_dec, kv_dtype=kv_dtype)
    out = run_disaggregated(pre, dec, _reqs())
    done = {r.rid: list(r.tokens) for r in out["completed"]}
    assert done == refs
    # every request's pages crossed the wire and were accounted
    assert len(out["transfers"]) == len(PROMPTS)
    assert out["transferred_pages"] == sum(t["pages"]
                                           for t in out["transfers"])
    assert out["transferred_bytes"] > 0
    assert pre.pages_exported == out["transferred_pages"]
    assert dec.pages_imported == out["transferred_pages"]
    _assert_drained(pre)
    _assert_drained(dec)
    _assert_drained(coloc)


def test_disagg_refuses_mismatched_wire():
    pre = _engine(prefill_only=True)
    dec8 = _engine(kv_dtype="int8")
    with pytest.raises(ValueError, match="kv_dtype mismatch"):
        run_disaggregated(pre, dec8, _reqs())
    dec_ps = _engine(page_size=16, prefill_chunk=16)
    with pytest.raises(ValueError, match="page_size mismatch"):
        run_disaggregated(pre, dec_ps, _reqs())


# ------------------------------------- three-hop cross-process waterfall

class _FakeReq:
    def __init__(self, rid):
        self.rid = rid
        self.trace_id = None
        self.prompt = [3, 4, 5]
        self.prompt_len = 3
        self.tokens = []
        self.submit_t = None
        self.first_token_t = None
        self.finish_t = None
        self.ttft_s = None
        self.tpot_s = None
        self.preemptions = 0
        self.tenant = "t0"
        self.slo_class = None


def test_three_hop_waterfall_span_sum_equals_wall():
    """The PR 12 two-hop pin extended to THREE processes with two
    deliberate clock skews: router (p0) -> prefill (p1, +500s) ->
    decode (p2, -312s). One contiguous waterfall, span sum == total ==
    the cross-process wall in the root timebase."""
    skew1, skew2 = 500.0, -312.0
    c0, c1, c2 = [0.0], [0.0], [0.0]
    rt0 = RequestTracer(clock=lambda: c0[0],
                        wall=lambda: 1000.0 + c0[0], process_index=0)
    rt1 = RequestTracer(clock=lambda: c1[0],
                        wall=lambda: 1000.0 + skew1 + c1[0],
                        process_index=1)
    rt2 = RequestTracer(clock=lambda: c2[0],
                        wall=lambda: 1000.0 + skew2 + c2[0],
                        process_index=2)
    # hop 0: the router scores + dispatches in 10ms
    r0 = _FakeReq(9)
    r0.submit_t = 0.0
    rt0.begin(r0)
    c0[0] = 0.010
    ctx0 = rt0.export_context(r0, "route")
    rec0 = rt0.retire(r0, t=c0[0])
    # hop 1 adopts 5ms later (root time 15ms): 30ms of chunked prefill
    c1[0] = 0.0
    r1 = _FakeReq(9)
    rt1.begin(r1, ctx=TraceContext.from_wire(
        json.loads(json.dumps(ctx0.to_wire()))))
    assert r1.trace_id == r0.trace_id
    c1[0] = 0.030
    rt1.mark(r1, "prefill_chunk", positions=3)
    ctx1 = rt1.export_context(r1, "handoff")
    rec1 = rt1.retire(r1, t=c1[0])
    # hop 2 adopts after 20ms on the wire (root 65ms): 40ms of decode
    c2[0] = 0.0
    r2 = _FakeReq(9)
    rt2.begin(r2, ctx=TraceContext.from_wire(ctx1.to_wire()))
    c2[0] = 0.040
    rt2.mark(r2, "decode")
    r2.finish_t = 0.040
    r2.tokens = [7, 8]
    rec2 = rt2.retire(r2)
    # the handoff handshake anchors each hop's adoption AT the previous
    # hop's export wall (both skews cancel exactly, like the 2-hop pin),
    # so the merged waterfall is contiguous with span sum == total ==
    # the cross-process wall: 10ms route + 30ms prefill + 40ms decode.
    m = merge_traces([rec0, rec1, rec2])
    assert m["processes"] == [0, 1, 2]
    cursor = 0.0
    for s in m["spans"]:
        assert s["start_ms"] == pytest.approx(cursor, abs=0.01), (
            s, m["spans"])
        cursor += s["dur_ms"]
    assert cursor == pytest.approx(m["total_ms"], abs=0.01)
    assert m["total_ms"] == pytest.approx(80.0, abs=0.5)
    names = [s["name"] for s in m["spans"]]
    assert "route" in names                   # hop 0's dispatch span
    assert "handoff" in names                 # hop 1's export span
    assert "prefill_chunk" in names and "decode" in names


def test_three_hop_waterfall_real_path():
    """The real wiring: router tracer exports `route`, the prefill
    engine adopts + exports `handoff` (transfer.py), the decode engine
    adopts at admit — three records, one merged contiguous waterfall."""
    rt0 = RequestTracer(process_index=0)
    rt1 = RequestTracer(process_index=1)
    rt2 = RequestTracer(process_index=2)
    pre = _engine(prefill_only=True, request_tracer=rt1)
    dec = _engine(request_tracer=rt2)
    reqs = _reqs(max_new=4)
    for r in reqs:
        rt0.begin(r)
        ctx = rt0.export_context(r, "route")
        r.trace_ctx = ctx.to_wire()
        rt0.retire(r)
    out = run_disaggregated(pre, dec, reqs)
    assert len(out["completed"]) == len(reqs)
    for r in reqs:
        recs = [rt0.timeline(r.rid), rt1.timeline(r.rid),
                rt2.timeline(r.rid)]
        assert all(rec is not None for rec in recs), r.rid
        assert {rec["trace_id"] for rec in recs} == {r.trace_id}
        m = merge_traces(recs)
        assert m["processes"] == [0, 1, 2]
        cursor = 0.0
        for s in m["spans"]:
            assert s["start_ms"] == pytest.approx(cursor, abs=0.01)
            cursor += s["dur_ms"]
        assert cursor == pytest.approx(m["total_ms"], abs=0.01)
        assert "kv_import" in [s["name"] for s in m["spans"]]
    _assert_drained(pre)
    _assert_drained(dec)
