"""Native C++ data path (csrc/dataloader.cpp): parity with the Python/HF path.

The reference's data path rides HF tokenizers (Rust) and torch's collate;
our framework owns a C++ equivalent. These tests pin:

* BPE encode parity with HF `tokenizers` on the SHIPPED reference
  tokenizer.json (`/root/reference/tokenizer/tokenizer.json`) across
  structured probes and randomized strings (incl. whitespace runs,
  contractions, unicode, unknown-byte -> UNK emission);
* collate parity with data.dataset.collate byte for byte;
* the pre_tokenize 'native' backend produces the identical token JSON.
"""

import json
import os
import random

import numpy as np
import pytest

from distributed_pytorch_from_scratch_tpu.data.dataset import collate
from distributed_pytorch_from_scratch_tpu.data.native import (
    PROBE_TEXTS, NativeBPE, native_available, native_collate)

# The SHIPPED reference tokenizer; containers without the reference repo
# checked out use the in-repo copy (tokenizer/tokenizer.json — the same
# 1024-token BPE), so the native-vs-HF parity sweep still runs everywhere.
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REF_TOK = "/root/reference/tokenizer/tokenizer.json"
if not os.path.exists(REF_TOK):
    REF_TOK = os.path.join(_REPO, "tokenizer", "tokenizer.json")

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native library unavailable (no g++?)")


@pytest.fixture(scope="module")
def hf():
    from tokenizers import Tokenizer
    return Tokenizer.from_file(REF_TOK)


@pytest.fixture(scope="module")
def native():
    return NativeBPE(REF_TOK)


def test_probe_texts_match(native, hf):
    for t in PROBE_TEXTS:
        assert native.encode(t) == hf.encode(t).ids, repr(t)


def test_unknown_bytes_emit_unk(native, hf):
    # tab's byte-alphabet char is not in the 1024-token trained vocab;
    # HF emits UNK (id 2) per unknown symbol and so must we
    assert native.encode("\t") == hf.encode("\t").ids
    assert 2 in native.encode("a\tb")


def test_randomized_parity(native, hf):
    rng = random.Random(42)
    alphabet = " abcdefgh  ij.,!?'0123456789\n\tABC (—)é 中文"
    for _ in range(300):
        s = "".join(rng.choice(alphabet) for _ in range(rng.randrange(0, 100)))
        assert native.encode(s) == hf.encode(s).ids, repr(s)


def test_long_document_parity(native, hf):
    readme = "/root/reference/README.md"
    if not os.path.exists(readme):  # reference repo absent
        readme = os.path.join(_REPO, "README.md")
    text = open(readme).read() * 20
    assert native.encode(text) == hf.encode(text).ids


def test_nul_bytes_not_truncated(native, hf):
    s = "before\x00after and more"
    assert native.encode(s) == hf.encode(s).ids


def test_output_buffer_regrows(native, hf):
    big = "word " * 90000  # > the initial 64k-id output buffer
    a = native.encode(big)
    assert len(a) > 1 << 16
    assert a == hf.encode(big).ids


def test_collate_parity():
    rng = random.Random(0)
    batch = [[rng.randrange(3, 1000) for _ in range(rng.randrange(0, 30))]
             for _ in range(8)]
    width = 32
    ref = collate(batch, bos=0, eos=1, ignore_idx=-1, pad_to=width)
    got = native_collate(batch, bos=0, eos=1, ignore_idx=-1, width=width)
    for k in ("input_ids", "target_ids", "position_ids"):
        np.testing.assert_array_equal(got[k], ref[k], err_msg=k)


def test_collate_rejects_overlong_rows():
    """ADVICE r1 (medium): a row longer than width-1 used to heap-overflow
    in C++. native_collate must now refuse it up front (and the C++ clamp
    is a second line of defence)."""
    with pytest.raises(AssertionError, match="pad width"):
        native_collate([[5] * 40], bos=0, eos=1, ignore_idx=-1, width=16)


def test_dataloader_native_backend_byte_equal(tmp_path):
    """DataLoader(backend='native') (the product path under 'auto') yields
    byte-identical batches to the numpy backend."""
    from distributed_pytorch_from_scratch_tpu.data.dataset import (
        get_dataloader)
    rng = random.Random(1)
    data = {"train": [[rng.randrange(3, 1000)
                       for _ in range(rng.randrange(1, 30))]
                      for _ in range(32)],
            "validation": [[4, 5, 6]],
            "special_ids": {"<BOS>": 0, "<EOS>": 1, "<UNK>": 2},
            "vocab_size": 1024}
    p = tmp_path / "tokens.json"
    p.write_text(json.dumps(data))
    mk = lambda backend: get_dataloader(str(p), batch_size=4, maxlen=32,
                                        seed=7, backend=backend)
    for a, b in zip(mk("native").epoch(0), mk("numpy").epoch(0)):
        for k in ("input_ids", "target_ids", "position_ids"):
            np.testing.assert_array_equal(a[k], b[k], err_msg=k)


def test_native_collate_speed():
    """Record the native-vs-numpy collate timing (VERDICT r1 asked for a
    measured number or an honest no-win note; printed with -s)."""
    import time
    rng = random.Random(2)
    batch = [[rng.randrange(3, 1000) for _ in range(rng.randrange(200, 999))]
             for _ in range(32)]
    width = 1000
    n = 100

    def timed(fn):
        fn()  # warmup (lib load / allocator)
        t0 = time.perf_counter()
        for _ in range(n):
            fn()
        return (time.perf_counter() - t0) / n

    t_py = timed(lambda: collate(batch, bos=0, eos=1, ignore_idx=-1,
                                 pad_to=width))
    t_c = timed(lambda: native_collate(batch, bos=0, eos=1, ignore_idx=-1,
                                       width=width))
    print(f"\ncollate b32xw1000: numpy {t_py*1e6:.0f}us, "
          f"native {t_c*1e6:.0f}us ({t_py/t_c:.1f}x)")
    # measured ~2x on this image; no strict assert (environment-dependent),
    # the parity tests above are the correctness gate


def test_pre_tokenize_native_backend(tmp_path):
    from distributed_pytorch_from_scratch_tpu.data.tokenizer import pre_tokenize
    data = {"train": ["hello world", "it's a test  of runs"],
            "validation": ["good morning"]}
    inp = tmp_path / "texts.json"
    inp.write_text(json.dumps(data))
    out_n = pre_tokenize(str(inp), str(tmp_path / "n.json"), REF_TOK,
                         backend="native")
    out_h = pre_tokenize(str(inp), str(tmp_path / "h.json"), REF_TOK,
                         backend="hf")
    assert out_n == out_h


def test_pre_tokenize_added_token_text_falls_back(tmp_path):
    """ADVICE r1: HF matches a literal '<EOS>' in raw text, the native
    scanner never does. A corpus containing one anywhere (beyond the old
    64-sample probe window) must route to HF under 'auto' — and the outputs
    must match HF exactly — while backend='native' must refuse."""
    from distributed_pytorch_from_scratch_tpu.data.tokenizer import pre_tokenize
    filler = [f"plain text number {i}" for i in range(80)]
    data = {"train": filler + ["sneaky <EOS> in late text"],
            "validation": ["good morning"]}
    inp = tmp_path / "texts.json"
    inp.write_text(json.dumps(data))
    out_a = pre_tokenize(str(inp), str(tmp_path / "a.json"), REF_TOK,
                         backend="auto")
    out_h = pre_tokenize(str(inp), str(tmp_path / "h.json"), REF_TOK,
                         backend="hf")
    assert out_a == out_h
    # the HF path really does emit the special id for the literal string
    assert 1 in out_a["train"][80]
    with pytest.raises(ValueError, match="added-token"):
        pre_tokenize(str(inp), str(tmp_path / "n.json"), REF_TOK,
                     backend="native")


def test_dataloader_native_overlong_rows_and_dynamic_width(tmp_path):
    """ADVICE r2: cover the indexed fast path's cap-truncation branch (rows
    LONGER than maxlen-1) and the pad_to=None dynamic-width branch, against
    the numpy backend byte-for-byte."""
    from distributed_pytorch_from_scratch_tpu.data.dataset import (DataLoader,
                                                                   TokenDataset)
    rng = random.Random(3)
    # rows straddle the cap: maxlen=16 -> cap 15, rows up to 40 tokens
    data = {"train": [[rng.randrange(3, 1000)
                       for _ in range(rng.randrange(1, 41))]
                      for _ in range(24)],
            "validation": [[4, 5, 6]],
            "special_ids": {"<BOS>": 0, "<EOS>": 1, "<UNK>": 2},
            "vocab_size": 1024}
    p = tmp_path / "tokens.json"
    p.write_text(json.dumps(data))

    def mk(backend, pad_to):
        # direct DataLoader construction (get_dataloader always sets pad_to)
        return DataLoader(TokenDataset(str(p), "train", maxlen=16),
                          batch_size=4, shuffle=True, seed=5,
                          pad_to=pad_to, backend=backend)

    for pad_to in (None, 16):
        batches = list(zip(mk("native", pad_to).epoch(0),
                           mk("numpy", pad_to).epoch(0)))
        assert batches
        saw_truncated = False
        for a, b in batches:
            for k in ("input_ids", "target_ids", "position_ids"):
                np.testing.assert_array_equal(a[k], b[k],
                                              err_msg=f"{k} pad_to={pad_to}")
            assert a["input_ids"].shape[1] <= 16
            # a truncated row carries cap tokens + EOS = cap+1 live targets
            saw_truncated |= bool(
                (np.sum(a["target_ids"] != -1, axis=1) == 16).any())
        assert saw_truncated, "test data should exercise the cap branch"


def test_dataloader_native_undersized_pad_raises(tmp_path):
    """An undersized pad_to must raise on BOTH backends (the C++ clamp would
    otherwise silently truncate — ADVICE r2)."""
    from distributed_pytorch_from_scratch_tpu.data.dataset import (DataLoader,
                                                                   TokenDataset)
    data = {"train": [[5] * 20 for _ in range(8)],
            "validation": [[4, 5, 6]],
            "special_ids": {"<BOS>": 0, "<EOS>": 1, "<UNK>": 2},
            "vocab_size": 1024}
    p = tmp_path / "tokens.json"
    p.write_text(json.dumps(data))
    for backend in ("native", "numpy"):
        dl = DataLoader(TokenDataset(str(p), "train", maxlen=64),
                        batch_size=4, shuffle=False, pad_to=10,
                        backend=backend)
        with pytest.raises(AssertionError):
            next(iter(dl.epoch(0)))
