"""Native C++ data path (csrc/dataloader.cpp): parity with the Python/HF path.

The reference's data path rides HF tokenizers (Rust) and torch's collate;
our framework owns a C++ equivalent. These tests pin:

* BPE encode parity with HF `tokenizers` on the SHIPPED reference
  tokenizer.json (`/root/reference/tokenizer/tokenizer.json`) across
  structured probes and randomized strings (incl. whitespace runs,
  contractions, unicode, unknown-byte -> UNK emission);
* collate parity with data.dataset.collate byte for byte;
* the pre_tokenize 'native' backend produces the identical token JSON.
"""

import json
import random

import numpy as np
import pytest

from distributed_pytorch_from_scratch_tpu.data.dataset import collate
from distributed_pytorch_from_scratch_tpu.data.native import (
    PROBE_TEXTS, NativeBPE, native_available, native_collate)

REF_TOK = "/root/reference/tokenizer/tokenizer.json"

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native library unavailable (no g++?)")


@pytest.fixture(scope="module")
def hf():
    from tokenizers import Tokenizer
    return Tokenizer.from_file(REF_TOK)


@pytest.fixture(scope="module")
def native():
    return NativeBPE(REF_TOK)


def test_probe_texts_match(native, hf):
    for t in PROBE_TEXTS:
        assert native.encode(t) == hf.encode(t).ids, repr(t)


def test_unknown_bytes_emit_unk(native, hf):
    # tab's byte-alphabet char is not in the 1024-token trained vocab;
    # HF emits UNK (id 2) per unknown symbol and so must we
    assert native.encode("\t") == hf.encode("\t").ids
    assert 2 in native.encode("a\tb")


def test_randomized_parity(native, hf):
    rng = random.Random(42)
    alphabet = " abcdefgh  ij.,!?'0123456789\n\tABC (—)é 中文"
    for _ in range(300):
        s = "".join(rng.choice(alphabet) for _ in range(rng.randrange(0, 100)))
        assert native.encode(s) == hf.encode(s).ids, repr(s)


def test_long_document_parity(native, hf):
    text = open("/root/reference/README.md").read() * 20
    assert native.encode(text) == hf.encode(text).ids


def test_nul_bytes_not_truncated(native, hf):
    s = "before\x00after and more"
    assert native.encode(s) == hf.encode(s).ids


def test_output_buffer_regrows(native, hf):
    big = "word " * 90000  # > the initial 64k-id output buffer
    a = native.encode(big)
    assert len(a) > 1 << 16
    assert a == hf.encode(big).ids


def test_collate_parity():
    rng = random.Random(0)
    batch = [[rng.randrange(3, 1000) for _ in range(rng.randrange(0, 30))]
             for _ in range(8)]
    width = 32
    ref = collate(batch, bos=0, eos=1, ignore_idx=-1, pad_to=width)
    got = native_collate(batch, bos=0, eos=1, ignore_idx=-1, width=width)
    for k in ("input_ids", "target_ids", "position_ids"):
        np.testing.assert_array_equal(got[k], ref[k], err_msg=k)


def test_pre_tokenize_native_backend(tmp_path):
    from distributed_pytorch_from_scratch_tpu.data.tokenizer import pre_tokenize
    data = {"train": ["hello world", "it's a test  of runs"],
            "validation": ["good morning"]}
    inp = tmp_path / "texts.json"
    inp.write_text(json.dumps(data))
    out_n = pre_tokenize(str(inp), str(tmp_path / "n.json"), REF_TOK,
                         backend="native")
    out_h = pre_tokenize(str(inp), str(tmp_path / "h.json"), REF_TOK,
                         backend="hf")
    assert out_n == out_h
