"""Multi-step-per-dispatch training == N single-step dispatches.

`build_train_step_multi` scans the SAME step body (grad + Adam/OneCycle)
over a stacked megabatch, so the resulting params/opt-state/losses must
match the single-step program on the same batch stream. Also checks the
train.py CLI path end-to-end with --steps_per_dispatch.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_from_scratch_tpu import (MeshConfig, ModelConfig,
                                                  Transformer, make_mesh)
from distributed_pytorch_from_scratch_tpu.config import OptimizerConfig
from distributed_pytorch_from_scratch_tpu.training.optim import (
    init_adam_state)
from distributed_pytorch_from_scratch_tpu.training.train_step import (
    build_train_step, build_train_step_multi)

CFG = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=8, num_layers=2,
                  vocab_size=64, maxlen=16)


def _batches(key, n, b=4, t=16):
    ids = jax.random.randint(key, (n, b, t), 0, CFG.vocab_size)
    tgt = jnp.roll(ids, -1, axis=2)
    pos = jnp.tile(jnp.arange(t, dtype=jnp.int32)[None, None, :], (n, b, 1))
    return ids, tgt, pos


@pytest.mark.parametrize("tp,dp", [(1, 1), (4, 2)])
def test_multi_step_matches_single(tp, dp):
    mesh = make_mesh(MeshConfig(dp=dp, tp=tp))
    model = Transformer(CFG, tp_size=tp)
    ocfg = OptimizerConfig(lr=1e-3, warmup_steps=2, max_steps=8)
    sh = model.shardings(mesh)
    # fresh init per run: device_put aliases when the sharding already
    # matches, and the donated step would delete a shared pytree
    init = lambda: model.init(jax.random.key(0))
    N = 4
    ids, tgt, pos = _batches(jax.random.key(1), N)

    # N single-step dispatches
    p1 = jax.device_put(init(), sh)
    o1 = init_adam_state(p1)
    step = build_train_step(model, mesh, ocfg)
    losses1 = []
    for s in range(N):
        p1, o1, loss = step(p1, o1, ids[s], tgt[s], pos[s])
        losses1.append(float(loss))

    # one scanned dispatch over the same stream
    p2 = jax.device_put(init(), sh)
    o2 = init_adam_state(p2)
    multi = build_train_step_multi(model, mesh, ocfg)
    p2, o2, losses2 = multi(p2, o2, ids, tgt, pos)

    np.testing.assert_allclose(np.asarray(losses2), losses1, atol=1e-6)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), atol=1e-6), p1, p2)
    assert int(o2.step) == N


def test_multi_step_zero1_matches_single_zero1():
    """The scanned program under ZeRO-1 (dp-sharded Adam moments) matches
    single-step ZeRO-1 — the out_shardings plumbing differs, the math must
    not."""
    from distributed_pytorch_from_scratch_tpu.training.zero import (
        zero1_moment_shardings)
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = make_mesh(MeshConfig(dp=4, tp=2))
    model = Transformer(CFG, tp_size=2)
    ocfg = OptimizerConfig(lr=1e-3, warmup_steps=2, max_steps=8)
    sh = model.shardings(mesh)
    moment_sh = zero1_moment_shardings(model, mesh)
    scalar = NamedSharding(mesh, P())
    N = 3
    ids, tgt, pos = _batches(jax.random.key(2), N)

    def fresh():
        p = jax.device_put(model.init(jax.random.key(0)), sh)
        o = init_adam_state(p)
        o = jax.device_put(o, o.__class__(step=scalar, mu=moment_sh,
                                          nu=moment_sh))
        return p, o

    p1, o1 = fresh()
    step = build_train_step(model, mesh, ocfg, zero1=True,
                            moment_shardings=moment_sh)
    for s in range(N):
        p1, o1, _ = step(p1, o1, ids[s], tgt[s], pos[s])

    p2, o2 = fresh()
    multi = build_train_step_multi(model, mesh, ocfg, zero1=True,
                                   moment_shardings=moment_sh)
    p2, o2, losses = multi(p2, o2, ids, tgt, pos)

    assert losses.shape == (N,)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), atol=1e-6), p1, p2)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), atol=1e-6), o1.mu, o2.mu)


def test_cli_steps_per_dispatch_matches(tmp_path):
    """train.py --steps_per_dispatch 2 reproduces the plain run: same final
    avg loss, same checkpoint steps (saves land on dispatch boundaries)."""
    import json

    from distributed_pytorch_from_scratch_tpu import train as train_mod
    from distributed_pytorch_from_scratch_tpu.data.tokenizer import (
        pre_tokenize, train_bpe)
    from distributed_pytorch_from_scratch_tpu.training.checkpoint import (
        list_checkpoints)

    texts = ["the king rode out at dawn with his men",
             "a quiet morning on the river bank",
             "she sold sea shells by the sea shore",
             "to be or not to be that is the question"] * 4
    tj = tmp_path / "texts.json"
    json.dump({"train": texts, "validation": texts[:2]}, open(tj, "w"))
    train_bpe(str(tj), str(tmp_path / "tok.json"), vocab_size=270)
    pre_tokenize(str(tj), str(tmp_path / "tokens.json"),
                 str(tmp_path / "tok.json"))

    base = ["--data_path", str(tmp_path / "tokens.json"),
            "--attn_dim", "32", "--ffn_dim", "64", "--num_heads", "4",
            "--num_layers", "2", "--maxlen", "32", "--batch_size", "4",
            "--max_steps", "6", "--save_interval", "3",
            "--log_interval", "2", "--warmup_steps", "2"]
    r1 = train_mod.train(train_mod.get_train_args(
        base + ["--save_dir", str(tmp_path / "ck1")]))
    r2 = train_mod.train(train_mod.get_train_args(
        base + ["--save_dir", str(tmp_path / "ck2"),
                "--steps_per_dispatch", "2"]))
    assert r1["steps"] == r2["steps"] == 6
    np.testing.assert_allclose(r2["avg_loss"], r1["avg_loss"], atol=1e-5)
    # saves at 3 fall between dispatch boundaries (2,4,6): the multi run
    # checkpoints at the crossing (4) and at 6
    assert [it for it, _ in list_checkpoints(str(tmp_path / "ck1"))] == [3, 6]
    assert [it for it, _ in list_checkpoints(str(tmp_path / "ck2"))] == [4, 6]


def test_cli_spd_tail_shrinks_to_max_steps(tmp_path):
    """max_steps not divisible by steps_per_dispatch: the final window must
    shrink (round-3 prefetch loop slices it) so the run ends EXACTLY on
    max_steps."""
    import json

    from distributed_pytorch_from_scratch_tpu import train as train_mod
    from distributed_pytorch_from_scratch_tpu.data.tokenizer import (
        pre_tokenize, train_bpe)

    texts = ["the king rode out at dawn with his men",
             "a quiet morning on the river bank",
             "she sold sea shells by the sea shore",
             "to be or not to be that is the question"] * 4
    tj = tmp_path / "texts.json"
    json.dump({"train": texts, "validation": texts[:2]}, open(tj, "w"))
    train_bpe(str(tj), str(tmp_path / "tok.json"), vocab_size=270)
    pre_tokenize(str(tj), str(tmp_path / "tokens.json"),
                 str(tmp_path / "tok.json"))

    r = train_mod.train(train_mod.get_train_args(
        ["--data_path", str(tmp_path / "tokens.json"),
         "--save_dir", str(tmp_path / "ck"),
         "--attn_dim", "32", "--ffn_dim", "64", "--num_heads", "4",
         "--num_layers", "2", "--maxlen", "32", "--batch_size", "4",
         "--max_steps", "5", "--steps_per_dispatch", "3",
         "--save_interval", "5", "--log_interval", "5",
         "--warmup_steps", "2"]))
    assert r["steps"] == 5, r
