"""Gradient accumulation: one optimizer step from the mean of A microbatch
gradients == one step on the concatenated A*B batch whenever the microbatch
valid-token counts match (mean-of-means == global mean then). Also covers
the CLI integration and the mutual exclusion with --steps_per_dispatch."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_from_scratch_tpu import (MeshConfig, ModelConfig,
                                                  Transformer, make_mesh)
from distributed_pytorch_from_scratch_tpu.config import OptimizerConfig
from distributed_pytorch_from_scratch_tpu.training.optim import (
    init_adam_state)
from distributed_pytorch_from_scratch_tpu.training.train_step import (
    build_grad_accum_step, build_train_step)

CFG = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=8, num_layers=2,
                  vocab_size=64, maxlen=16)


@pytest.mark.parametrize("dp,tp", [(1, 1), (2, 2)])
def test_accum_matches_concatenated_batch(dp, tp):
    mesh = make_mesh(MeshConfig(dp=dp, tp=tp))
    model = Transformer(CFG, tp_size=tp)
    ocfg = OptimizerConfig(lr=1e-3, warmup_steps=2, max_steps=8)
    sh = model.shardings(mesh)
    A, B, T = 4, 4, 16
    # fully-valid targets: every microbatch then weighs B*T tokens, so
    # mean-of-means equals the concatenated batch's global mean exactly
    ids = jax.random.randint(jax.random.key(1), (A, B, T), 0, CFG.vocab_size)
    tgt = jnp.roll(ids, -1, axis=2)
    pos = jnp.tile(jnp.arange(T, dtype=jnp.int32)[None, None, :], (A, B, 1))

    p1 = jax.device_put(model.init(jax.random.key(0)), sh)
    o1 = init_adam_state(p1)
    accum = build_grad_accum_step(model, mesh, ocfg)
    p1, o1, l1 = accum(p1, o1, ids, tgt, pos)

    p2 = jax.device_put(model.init(jax.random.key(0)), sh)
    o2 = init_adam_state(p2)
    step = build_train_step(model, mesh, ocfg)
    big = lambda x: x.reshape(A * B, T)
    p2, o2, l2 = step(p2, o2, big(ids), big(tgt), big(pos))

    # atol 5e-6: the accum scan and the concatenated batch reduce the same
    # CE sum in different XLA fusion orders; f32 rounding on a ~4.3 loss
    # wobbles a little over 1e-6 on some CPU XLA builds
    np.testing.assert_allclose(float(l1), float(l2), atol=5e-6)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), atol=1e-6), p1, p2)
    assert int(o1.step) == int(o2.step) == 1


def test_cli_grad_accum(tmp_path):
    import json

    from distributed_pytorch_from_scratch_tpu import train as train_mod
    from distributed_pytorch_from_scratch_tpu.data.tokenizer import (
        pre_tokenize, train_bpe)
    from distributed_pytorch_from_scratch_tpu.training.checkpoint import (
        list_checkpoints)

    texts = ["the king rode out at dawn with his men",
             "a quiet morning on the river bank",
             "she sold sea shells by the sea shore",
             "to be or not to be that is the question"] * 4
    tj = tmp_path / "texts.json"
    json.dump({"train": texts, "validation": texts[:2]}, open(tj, "w"))
    train_bpe(str(tj), str(tmp_path / "tok.json"), vocab_size=270)
    pre_tokenize(str(tj), str(tmp_path / "tokens.json"),
                 str(tmp_path / "tok.json"))

    base = ["--data_path", str(tmp_path / "tokens.json"),
            "--save_dir", str(tmp_path / "ck"),
            "--attn_dim", "32", "--ffn_dim", "64", "--num_heads", "4",
            "--num_layers", "2", "--maxlen", "32", "--batch_size", "2",
            "--max_steps", "4", "--save_interval", "2",
            "--log_interval", "1", "--warmup_steps", "2"]
    r = train_mod.train(train_mod.get_train_args(base + ["--grad_accum", "2"]))
    # 4 optimizer steps, each from 2 microbatches (16 sequences / 2 per
    # microbatch / 2 accum = 4 steps/epoch: exactly one epoch)
    assert r["steps"] == 4 and np.isfinite(r["avg_loss"])
    assert [it for it, _ in list_checkpoints(str(tmp_path / "ck"))] == [2, 4]

    with pytest.raises(SystemExit, match="mutually exclusive"):
        train_mod.train(train_mod.get_train_args(
            base + ["--grad_accum", "2", "--steps_per_dispatch", "2"]))
