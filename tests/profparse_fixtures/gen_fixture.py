"""Regenerate the committed fixture capture for tests/test_measured_attribution.py.

The fixture is a synthetic `jax.profiler` capture dir — the real
`plugins/profile/<ts>/*.trace.json.gz` layout with a HAND-AUTHORED event
set whose per-phase totals are pinned exactly by the tests:

    fusion   10.0 ms (8 + 2)          dot        2.0 ms
    all-reduce 3.0 ms                 collective-permute 1.0 ms
    copy      0.5 ms                  transpose  0.5 ms
    convert   1.0 ms
    busy = 18.0 ms, lane span 0..20 ms  ->  host_gap = 2.0 ms

One python host-callstack event (no hlo args) rides along and must be
ignored. Written with a deterministic gzip (mtime=0) so regeneration is
byte-stable. Run from the repo root:

    python tests/profparse_fixtures/gen_fixture.py
"""

import gzip
import json
import os

HERE = os.path.dirname(os.path.abspath(__file__))
OUT = os.path.join(HERE, "capture", "plugins", "profile",
                   "2026_01_01_00_00_00")

PID, TID = 7, 42


def ev(name, ts, dur, hlo=True):
    e = {"ph": "X", "pid": PID, "tid": TID, "ts": float(ts),
         "dur": float(dur), "name": name}
    if hlo:
        e["args"] = {"hlo_module": "jit_step", "hlo_op": name}
    return e


DOC = {
    "displayTimeUnit": "ns",
    "traceEvents": [
        {"ph": "M", "pid": PID, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "pid": PID, "tid": TID, "name": "thread_name",
         "args": {"name": "XLA Ops"}},
        ev("fusion.1", 0, 8000),
        ev("fusion.2", 8000, 2000),
        ev("dot.3", 10000, 2000),
        ev("all-reduce.1", 12000, 3000),
        ev("collective-permute.2", 15000, 1000),
        ev("copy.5", 16000, 500),
        ev("transpose.1", 16500, 500),
        ev("convert.9", 19000, 1000),
        # host event without hlo args: the parser must skip it
        ev("$train.py:100 run_step", 0, 20000, hlo=False),
    ],
}


def main():
    os.makedirs(OUT, exist_ok=True)
    path = os.path.join(OUT, "fixture.trace.json.gz")
    payload = json.dumps(DOC, sort_keys=True).encode()
    with open(path, "wb") as f:
        f.write(gzip.compress(payload, mtime=0))
    print(f"wrote {path} ({len(payload)} bytes uncompressed)")


if __name__ == "__main__":
    main()
