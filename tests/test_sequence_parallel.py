"""Megatron-style sequence parallelism: equivalence with the replicated path.

The reference has no SP (norms replicated, full-size inter-block activations
on every rank — SURVEY §2.4). Here activations between sublayers are
sequence-sharded over 'tp': the per-sublayer all-reduce becomes a
reduce-scatter (row-linear output) + all-gather (next column-linear input)
conjugate pair. These tests pin the invariant that SP is a pure layout
optimisation: identical math, identical gradients.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributed_pytorch_from_scratch_tpu.config import (
    IGNORE_INDEX, MeshConfig, ModelConfig)
from distributed_pytorch_from_scratch_tpu.models.transformer import Transformer
from distributed_pytorch_from_scratch_tpu.models.vanilla import VanillaTransformer
from distributed_pytorch_from_scratch_tpu.parallel.linear import (
    ColumnParallelLinear, RowParallelLinear)
from distributed_pytorch_from_scratch_tpu.runtime.mesh import make_mesh

CFG = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=8, num_layers=2,
                  vocab_size=96, maxlen=64)


def make_batch(key, batch=4, t=32, vocab=96):
    k1, k2 = jax.random.split(key)
    input_ids = jax.random.randint(k1, (batch, t), 0, vocab)
    target_ids = jax.random.randint(k2, (batch, t), 0, vocab)
    mask = jax.random.bernoulli(jax.random.fold_in(key, 9), 0.2, (batch, t))
    target_ids = jnp.where(mask, IGNORE_INDEX, target_ids)
    position_ids = jnp.tile(jnp.arange(t)[None, :], (batch, 1))
    return input_ids, target_ids, position_ids


# ---- layer level: seq_sharded layouts are exact round-trips ----

def test_column_row_seq_layouts_match_replicated():
    """column(gather-seq input) o row(scatter-seq output) must equal the
    replicated pipeline on both values and gradients."""
    mesh = make_mesh(MeshConfig(dp=1, tp=4))
    tp = 4
    col = ColumnParallelLinear(16, 32, gather_output=False)
    row = RowParallelLinear(32, 16, split_input=False)
    pc = col.init(jax.random.key(0))
    pr = row.init(jax.random.key(1))
    x = jax.random.normal(jax.random.key(2), (2, 8, 16))
    w = jax.random.normal(jax.random.key(3), (2, 8, 16))

    def block(layout, pc, pr, x):
        if layout == "sp":
            y = col.apply(pc, x, input_layout="seq_sharded")
            y = row.apply(pr, y, output_layout="seq_sharded")
        else:
            y = col.apply(pc, x)
            y = row.apply(pr, y)
        return y

    def run(layout):
        spec_x = P(None, "tp", None) if layout == "sp" else P(None, None, None)
        fn = jax.shard_map(
            lambda pc, pr, x: block(layout, pc, pr, x), mesh=mesh,
            in_specs=(col.specs(), row.specs(), spec_x), out_specs=spec_x)
        loss = lambda pc, pr, x: jnp.sum(fn(pc, pr, x) * w)
        val, grads = jax.value_and_grad(loss, argnums=(0, 1, 2))(pc, pr, x)
        return val, grads

    v_sp, g_sp = run("sp")
    v_re, g_re = run("replicated")
    np.testing.assert_allclose(float(v_sp), float(v_re), rtol=1e-6)
    for a, b in zip(jax.tree.flatten(g_sp)[0], jax.tree.flatten(g_re)[0]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


# ---- model level ----

@pytest.mark.parametrize("dp,cp,tp", [(1, 1, 4), (2, 1, 4), (1, 2, 4), (2, 2, 2)])
def test_model_sp_matches_vanilla(dp, cp, tp):
    mesh = make_mesh(MeshConfig(dp=dp, cp=cp, tp=tp))
    model = Transformer(CFG, tp_size=tp, cp_size=cp, sequence_parallel=True)
    oracle = VanillaTransformer(CFG)
    params = model.init(jax.random.key(0))
    ids, tgt, pos = make_batch(jax.random.key(2))

    l_sh, g_sh = jax.value_and_grad(model.make_loss(mesh))(params, ids, tgt, pos)
    l_ref, g_ref = jax.value_and_grad(oracle.loss)(params, ids, tgt, pos)

    np.testing.assert_allclose(l_sh, l_ref, rtol=1e-5)
    for a, b in zip(jax.tree.flatten(g_sh)[0], jax.tree.flatten(g_ref)[0]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_model_sp_forward_logits():
    mesh = make_mesh(MeshConfig(dp=1, cp=1, tp=8))
    model = Transformer(CFG, tp_size=8, sequence_parallel=True)
    oracle = VanillaTransformer(CFG)
    params = model.init(jax.random.key(0))
    ids, _, pos = make_batch(jax.random.key(1))
    logits_sh = model.make_forward(mesh)(params, ids, pos)
    logits_ref = oracle.forward(params, ids, pos)
    np.testing.assert_allclose(np.asarray(logits_sh), np.asarray(logits_ref),
                               rtol=1e-4, atol=1e-4)


def test_sp_rejects_indivisible_seq():
    mesh = make_mesh(MeshConfig(dp=1, tp=8))
    model = Transformer(CFG, tp_size=8, sequence_parallel=True)
    params = model.init(jax.random.key(0))
    ids, tgt, pos = make_batch(jax.random.key(2), t=28)  # 28 % 8 != 0
    with pytest.raises(ValueError, match="sequence_parallel"):
        model.make_loss(mesh)(params, ids, tgt, pos)


def test_sp_bf16_runs():
    cfg = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=8, num_layers=2,
                      vocab_size=96, maxlen=64, compute_dtype="bfloat16")
    mesh = make_mesh(MeshConfig(dp=1, tp=4))
    model = Transformer(cfg, tp_size=4, sequence_parallel=True)
    params = model.init(jax.random.key(0))
    ids, tgt, pos = make_batch(jax.random.key(6))
    loss = model.make_loss(mesh)(params, ids, tgt, pos)
    assert np.isfinite(float(loss))
