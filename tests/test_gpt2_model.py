"""GPT-2 model family vs its unsharded oracle — same three-check idiom as
the LLaMA-family equivalence suite (SURVEY §4): shared init pytree, forward
allclose, grads allclose, and a multi-step training-history check. The tied
embedding head is the interesting part: the embedding weight's gradient must
carry BOTH the lookup and lm-head contributions across the vocab-parallel
shards."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_from_scratch_tpu import MeshConfig, make_mesh
from distributed_pytorch_from_scratch_tpu.config import (IGNORE_INDEX,
                                                         ModelConfig,
                                                         OptimizerConfig)
from distributed_pytorch_from_scratch_tpu.models.gpt2 import GPT2Transformer
from distributed_pytorch_from_scratch_tpu.models.vanilla import VanillaGPT2
from distributed_pytorch_from_scratch_tpu.training.optim import (
    adam_update, init_adam_state)
from distributed_pytorch_from_scratch_tpu.training.train_step import (
    build_train_step)

CFG = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=8, num_layers=2,
                  vocab_size=96, maxlen=64)


def make_batch(key, batch=4, t=32, vocab=96):
    k1, k2 = jax.random.split(key)
    ids = jax.random.randint(k1, (batch, t), 0, vocab)
    tgt = jax.random.randint(k2, (batch, t), 0, vocab)
    mask = jax.random.bernoulli(jax.random.fold_in(key, 9), 0.2, (batch, t))
    tgt = jnp.where(mask, IGNORE_INDEX, tgt)
    pos = jnp.tile(jnp.arange(t)[None, :], (batch, 1))
    return ids, tgt, pos


def test_param_tree_is_tied():
    """No separate lm_head params — the head IS the embedding table."""
    model = GPT2Transformer(CFG, tp_size=4)
    params = model.init(jax.random.key(0))
    assert set(params) == {"embedding", "pos_embedding", "layers", "norm"}
    assert params["embedding"]["weight"].shape == (96, 32)
    assert set(params["layers"]) == {"ln1", "wq", "wk", "wv", "wo",
                                     "ln2", "fc", "proj"}
    # specs tree mirrors the param tree exactly
    jax.tree.map(lambda *_: None, params, model.specs())


@pytest.mark.parametrize("dp,tp", [(1, 4), (2, 2), (1, 1)])
def test_loss_and_grads_match_vanilla(dp, tp):
    mesh = make_mesh(MeshConfig(dp=dp, tp=tp))
    model = GPT2Transformer(CFG, tp_size=tp)
    oracle = VanillaGPT2(CFG)
    params = model.init(jax.random.key(0))
    ids, tgt, pos = make_batch(jax.random.key(2))

    l_sh, g_sh = jax.value_and_grad(model.make_loss(mesh))(params, ids, tgt, pos)
    l_ref, g_ref = jax.value_and_grad(oracle.loss)(params, ids, tgt, pos)

    np.testing.assert_allclose(l_sh, l_ref, rtol=1e-5)
    for a, b in zip(jax.tree.leaves(g_sh), jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_forward_logits_match_vanilla():
    mesh = make_mesh(MeshConfig(dp=1, tp=4))
    model = GPT2Transformer(CFG, tp_size=4)
    oracle = VanillaGPT2(CFG)
    params = model.init(jax.random.key(0))
    ids, _, pos = make_batch(jax.random.key(1))
    logits_sh = model.make_forward(mesh)(params, ids, pos)
    logits_ref = oracle.forward(params, ids, pos)
    np.testing.assert_allclose(np.asarray(logits_sh), np.asarray(logits_ref),
                               rtol=1e-4, atol=1e-4)


def test_nondivisible_vocab_padding():
    """vocab 90 over tp=4 -> padded to 92; padded logits masked, loss equal
    to the oracle's."""
    cfg = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=4, num_layers=1,
                      vocab_size=90, maxlen=32)
    mesh = make_mesh(MeshConfig(dp=1, tp=4))
    model = GPT2Transformer(cfg, tp_size=4)
    assert model.vocab_padded == 92
    oracle = VanillaGPT2(cfg)
    params = model.init(jax.random.key(3))
    assert params["embedding"]["weight"].shape == (92, 32)
    ids, tgt, pos = make_batch(jax.random.key(4), vocab=90)
    l_sh = model.make_loss(mesh)(params, ids, tgt, pos)
    l_ref = oracle.loss(params, ids, tgt, pos)
    np.testing.assert_allclose(l_sh, l_ref, rtol=1e-5)


def test_multi_step_training_history_matches_vanilla():
    """20 Adam steps: parallel and oracle losses track each other — the
    reference's strongest equivalence check (1000-step history,
    `/root/reference/tests/*:111-135`), shortened for CI."""
    mesh = make_mesh(MeshConfig(dp=2, tp=2))
    model = GPT2Transformer(CFG, tp_size=2)
    oracle = VanillaGPT2(CFG)
    ocfg = OptimizerConfig(lr=1e-3, warmup_steps=2, max_steps=30)

    p_sh = jax.device_put(model.init(jax.random.key(0)),
                          model.shardings(mesh))
    o_sh = init_adam_state(p_sh)
    step_sh = build_train_step(model, mesh, ocfg)

    p_v = model.init(jax.random.key(0))
    o_v = init_adam_state(p_v)
    grad_v = jax.jit(jax.value_and_grad(oracle.loss))

    @jax.jit
    def step_v(p, o, ids, tgt, pos):
        loss, g = grad_v(p, ids, tgt, pos)
        p, o = adam_update(ocfg, p, g, o)
        return p, o, loss

    # one FIXED batch: repeated optimization must drive its loss down,
    # giving the histories real dynamics to diverge on if the tied-head
    # gradients were wrong anywhere
    ids, tgt, pos = make_batch(jax.random.key(100))
    hist_sh, hist_v = [], []
    for s in range(20):
        p_sh, o_sh, l1 = step_sh(p_sh, o_sh, ids, tgt, pos)
        p_v, o_v, l2 = step_v(p_v, o_v, ids, tgt, pos)
        hist_sh.append(float(l1))
        hist_v.append(float(l2))
    np.testing.assert_allclose(hist_sh, hist_v, rtol=0, atol=1e-4)
    assert hist_sh[-1] < hist_sh[0] - 0.1, hist_sh


def test_cli_family_gpt2_train_eval(tmp_path):
    """--family gpt2 end to end: train with checkpoints, evaluate val loss +
    greedy decode (full-recompute path — the KV decoder is llama-only)."""
    import json

    from distributed_pytorch_from_scratch_tpu import evaluate as eval_mod
    from distributed_pytorch_from_scratch_tpu import train as train_mod
    from distributed_pytorch_from_scratch_tpu.data.tokenizer import (
        pre_tokenize, train_bpe)

    texts = ["the king rode out at dawn with his men",
             "a quiet morning on the river bank",
             "Nice to meet you, it's a Great day; Your majesty, I shall be glad",
             "What a glory to see; Shame for the weak, The brave man ne, "
             "Poor old man"] * 6
    tj = tmp_path / "texts.json"
    json.dump({"train": texts, "validation": texts[:4]}, open(tj, "w"))
    train_bpe(str(tj), str(tmp_path / "tok.json"), vocab_size=300)
    pre_tokenize(str(tj), str(tmp_path / "tokens.json"),
                 str(tmp_path / "tok.json"))

    flags = ["--family", "gpt2", "--attn_dim", "32", "--ffn_dim", "64",
             "--num_heads", "4", "--num_layers", "2", "--maxlen", "32"]
    r = train_mod.train(train_mod.get_train_args(
        ["--data_path", str(tmp_path / "tokens.json"),
         "--save_dir", str(tmp_path / "ck"),
         "--tp_size", "2", "--dp_size", "2",
         "--batch_size", "4", "--max_steps", "6", "--save_interval", "3",
         "--log_interval", "3", "--warmup_steps", "2", *flags]))
    assert r["steps"] == 6 and np.isfinite(r["avg_loss"])

    result = eval_mod.evaluate(eval_mod.get_eval_args(
        ["--ckpt_dir", str(tmp_path / "ck"),
         "--data_path", str(tmp_path / "tokens.json"),
         "--tokenizer_path", str(tmp_path / "tok.json"),
         "--tp_size", "2", "--max_decode_len", "8", "--no-bf16", *flags]))
    assert set(result["val_losses"]) == {3, 6}
    assert all(np.isfinite(v) for v in result["val_losses"].values())
    assert len(result["decoded"]) == len(eval_mod.DECODE_PROMPTS)


MOE_CFG = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=4, num_layers=2,
                      vocab_size=96, maxlen=64, num_experts=4, moe_top_k=2,
                      moe_capacity_factor=8.0)  # ample: zero drops -> exact


@pytest.mark.parametrize("name,axes,kw", [
    ("ep2", dict(ep=2), dict(ep_size=2)),
    ("ep2tp2", dict(ep=2, tp=2), dict(ep_size=2, tp_size=2)),
    pytest.param("dp2ep2tp2", dict(dp=2, ep=2, tp=2),
                 dict(ep_size=2, tp_size=2), marks=pytest.mark.slow),
    pytest.param("ep2tp2_sp", dict(ep=2, tp=2),
                 dict(ep_size=2, tp_size=2, sequence_parallel=True),
                 marks=pytest.mark.slow),
    ("pp2ep2", dict(pp=2, ep=2), dict(pp_size=2, ep_size=2)),
])
def test_gpt2_moe_matches_single_device(name, axes, kw):
    """gpt2 + MoE (VERDICT r3 #5 — the family matrix's last hole): loss,
    logits and every gradient leaf match the SAME model on a 1-device mesh,
    including the router aux losses riding the (family-agnostic) pipeline
    carry on the pp2 x ep2 shape."""
    key = jax.random.key(0)
    ids, tgt, pos = make_batch(jax.random.key(2), batch=8)

    ref_model = GPT2Transformer(MOE_CFG)
    ref_mesh = make_mesh(MeshConfig())
    params = ref_model.init(key)
    l_ref, g_ref = jax.value_and_grad(ref_model.make_loss(ref_mesh))(
        params, ids, tgt, pos)
    logits_ref = ref_model.make_forward(ref_mesh)(params, ids, pos)

    model = GPT2Transformer(MOE_CFG, **kw)
    mesh = make_mesh(MeshConfig(**axes))
    sh_params = jax.device_put(params, model.shardings(mesh))
    l_sh, g_sh = jax.value_and_grad(model.make_loss(mesh))(
        sh_params, ids, tgt, pos)
    logits_sh = model.make_forward(mesh)(sh_params, ids, pos)

    np.testing.assert_allclose(float(l_sh), float(l_ref), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(logits_sh), np.asarray(logits_ref),
                               rtol=1e-4, atol=1e-4)
    for a, b in zip(jax.tree.leaves(g_sh), jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_gpt2_moe_param_tree():
    model = GPT2Transformer(MOE_CFG, tp_size=2, ep_size=2)
    params = model.init(jax.random.key(0))
    assert set(params["layers"]) == {"ln1", "wq", "wk", "wv", "wo",
                                     "ln2", "moe"}
    jax.tree.map(lambda *_: None, params, model.specs())


def test_gpt2_moe_kv_decode_matches_forward_argmax():
    """The generic KV decoder through a gpt2 MoE: greedy decode == argmax
    over the full forward (same check as the dense-family decode test)."""
    from distributed_pytorch_from_scratch_tpu.models.decode import (
        GreedyDecoder)

    mesh = make_mesh(MeshConfig(ep=2, tp=2))
    model = GPT2Transformer(MOE_CFG, ep_size=2, tp_size=2)
    params = jax.device_put(model.init(jax.random.key(0)),
                            model.shardings(mesh))
    fwd = model.make_forward(mesh)

    prompt = [1, 5, 9, 13]
    buf_len = 12
    dec = GreedyDecoder(model, mesh, buf_len)
    gen = dec.decode_batch(params, [prompt], eos_id=-1,
                           max_total_len=buf_len)[0]

    ids = list(prompt)
    while len(ids) < buf_len:
        buf = jnp.asarray([(ids + [0] * (buf_len - len(ids)))] * 2)
        pos = jnp.tile(jnp.arange(buf_len)[None, :], (2, 1))
        logits = fwd(params, buf, pos)[0, len(ids) - 1, : MOE_CFG.vocab_size]
        ids.append(int(jnp.argmax(logits)))
    assert gen == ids[len(prompt):], (gen, ids[len(prompt):])


def test_gpt2_moe_validation():
    with pytest.raises(ValueError, match="requires cfg.num_experts"):
        GPT2Transformer(CFG, ep_size=2)


def test_gpt2_kv_decode_matches_forward_argmax():
    """The generic KV-cache decoder on the gpt2 family (learned positions,
    LayerNorm, gelu MLP, tied head) == greedy over the full forward
    (VERDICT r2 #6: gpt2 used to be forced onto the O(t^2) recompute
    path)."""
    from distributed_pytorch_from_scratch_tpu.models.decode import (
        GreedyDecoder)

    mesh = make_mesh(MeshConfig(dp=1, tp=2))
    model = GPT2Transformer(CFG, tp_size=2)
    params = jax.device_put(model.init(jax.random.key(0)),
                            model.shardings(mesh))
    fwd = model.make_forward(mesh)

    prompt = [1, 5, 9, 13]
    buf_len = 12
    dec = GreedyDecoder(model, mesh, buf_len)
    gen = dec.decode_batch(params, [prompt], eos_id=-1,  # no EOS: run to cap
                           max_total_len=buf_len)[0]

    ids = list(prompt)
    while len(ids) < buf_len:
        buf = jnp.asarray([ids + [0] * (buf_len - len(ids))])
        pos = jnp.tile(jnp.arange(buf_len)[None, :], (1, 1))
        logits = fwd(params, buf, pos)[0, len(ids) - 1, : CFG.vocab_size]
        ids.append(int(jnp.argmax(logits)))
    assert gen == ids[len(prompt):], (gen, ids[len(prompt):])


def test_gpt2_decoder_rejects_overlong_buffer():
    from distributed_pytorch_from_scratch_tpu.models.decode import (
        GreedyDecoder)

    mesh = make_mesh(MeshConfig(dp=1, tp=2))
    model = GPT2Transformer(CFG, tp_size=2)
    with pytest.raises(ValueError, match="learned position table"):
        GreedyDecoder(model, mesh, buf_len=CFG.maxlen + 1)


@pytest.mark.parametrize("name,axes,kw", [
    ("cp2_ring", dict(cp=2), dict(cp_size=2)),
    ("cp2_ulysses", dict(cp=2), dict(cp_size=2, cp_impl="ulysses")),
    ("cp2_zigzag", dict(cp=2), dict(cp_size=2, cp_layout="zigzag")),
    ("tp2_sp", dict(tp=2), dict(tp_size=2, sequence_parallel=True)),
    pytest.param("dp2cp2tp2_sp", dict(dp=2, cp=2, tp=2),
                 dict(tp_size=2, cp_size=2, sequence_parallel=True),
                 marks=pytest.mark.slow),
])
def test_gpt2_context_sequence_parallel_matches_vanilla(name, axes, kw):
    """gpt2 on cp (ring/ulysses/zigzag) and Megatron SP meshes — round 3
    closes the family's dp x tp-only restriction (VERDICT r2 missing #3)."""
    mesh = make_mesh(MeshConfig(**axes))
    model = GPT2Transformer(CFG, **kw)
    oracle = VanillaGPT2(CFG)
    params = model.init(jax.random.key(0))
    ids, tgt, pos = make_batch(jax.random.key(4))

    l_sh, g_sh = jax.value_and_grad(model.make_loss(mesh))(params, ids, tgt,
                                                           pos)
    l_ref, g_ref = jax.value_and_grad(oracle.loss)(params, ids, tgt, pos)
    np.testing.assert_allclose(float(l_sh), float(l_ref), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(g_sh), jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("name,axes,kw", [
    ("pp2", dict(pp=2), dict(pp_size=2)),
    ("pp2tp2_m4", dict(pp=2, tp=2),
     dict(pp_size=2, tp_size=2, pp_microbatches=4)),
    ("pp2tp2_sp_remat", dict(pp=2, tp=2),
     dict(pp_size=2, tp_size=2, sequence_parallel=True,
          pp_remat_steps=True)),
])
def test_gpt2_pipeline_matches_vanilla(name, axes, kw):
    """gpt2 through the (family-agnostic) GPipe schedule: loss + every
    gradient leaf — including the tied embedding's double contribution
    routed through stage-0 inject AND the pp-scattered head — match the
    unsharded oracle."""
    mesh = make_mesh(MeshConfig(**axes))
    model = GPT2Transformer(CFG, **kw)
    oracle = VanillaGPT2(CFG)
    params = model.init(jax.random.key(0))
    ids, tgt, pos = make_batch(jax.random.key(6), batch=8)

    sp = jax.device_put(params, model.shardings(mesh))
    l_sh, g_sh = jax.value_and_grad(model.make_loss(mesh))(sp, ids, tgt, pos)
    l_ref, g_ref = jax.value_and_grad(oracle.loss)(params, ids, tgt, pos)
    np.testing.assert_allclose(float(l_sh), float(l_ref), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(g_sh), jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)

    logits_sh = model.make_forward(mesh)(sp, ids, pos)
    logits_ref = oracle.forward(params, ids, pos)
    np.testing.assert_allclose(np.asarray(logits_sh),
                               np.asarray(logits_ref), rtol=1e-4, atol=1e-4)
