"""VERDICT r5 #5: a ulysses-trained config meeting `--cp_size > 1` at
generation time must refuse LOUDLY with a pointer to the ring path (the KV
decoder's cp prefill is ring-only, models/decode.py::_prefill_cp) instead
of silently requiring it. Both CLIs validate before touching any file, so
these run with dummy paths."""

import pytest

from distributed_pytorch_from_scratch_tpu import evaluate as eval_mod
from distributed_pytorch_from_scratch_tpu import generate as gen_mod


def test_generate_refuses_ulysses_cp():
    args = gen_mod.get_generate_args(
        ["--ckpt_dir", "/nonexistent", "--tokenizer_path", "/nonexistent",
         "--prompt", "hi", "--cp_size", "2", "--cp_impl", "ulysses"])
    with pytest.raises(SystemExit, match="ring-only"):
        gen_mod.generate(args)


def test_generate_ring_passes_the_gate():
    """The same flags with --cp_impl ring must get PAST the refusal (and
    fail later on the dummy tokenizer path instead)."""
    args = gen_mod.get_generate_args(
        ["--ckpt_dir", "/nonexistent", "--tokenizer_path", "/nonexistent",
         "--prompt", "hi", "--cp_size", "2"])
    with pytest.raises(Exception) as e:
        gen_mod.generate(args)
    assert "ring-only" not in str(e.value)


def test_evaluate_refuses_ulysses_cp_decode():
    args = eval_mod.get_eval_args(
        ["--data_path", "/nonexistent", "--tokenizer_path", "/nonexistent",
         "--ckpt_dir", "/nonexistent", "--cp_size", "2",
         "--cp_impl", "ulysses"])
    with pytest.raises(SystemExit, match="ring-only"):
        eval_mod.evaluate(args)


def test_evaluate_ulysses_allowed_without_kv_decode():
    """--no_kv_cache decodes on the cp=1 dense path, so ulysses val loss
    is fine there: the gate must NOT fire (the dummy data path fails
    later instead)."""
    args = eval_mod.get_eval_args(
        ["--data_path", "/nonexistent", "--tokenizer_path", "/nonexistent",
         "--ckpt_dir", "/nonexistent", "--cp_size", "2",
         "--cp_impl", "ulysses", "--no_kv_cache"])
    with pytest.raises(Exception) as e:
        eval_mod.evaluate(args)
    assert "ring-only" not in str(e.value)
