"""obs v2 (ISSUE 10): per-request tracing, the anomaly flight recorder,
cross-rank skew attribution, the event-schema contract, and the
bench-regression gate.

The acceptance criteria pinned here:
* every completed request of a traced loadgen run has a CONTIGUOUS span
  timeline whose span sum equals its measured submit->finish wall
  (TTFT + decode wall) within tolerance — including through preemption +
  COW resume and speculative drafter rounds (no orphan spans);
* an induced sentinel non-finite halt and a forced PoolExhausted
  preemption each produce a flight dump containing the triggering event
  plus the preceding ring contents;
* `check_bench_regression.py` exits 0 on the committed trajectory vs
  itself, nonzero on a synthetically degraded record, and 0-with-skip on
  a backend_unavailable record;
* the k-worst exemplar waterfalls render in `summarize_run.py` output.
"""

import glob
import importlib.util
import json
import os
import time

import jax
import pytest

from distributed_pytorch_from_scratch_tpu.config import MeshConfig, ModelConfig
from distributed_pytorch_from_scratch_tpu.obs import (
    EVENT_SCHEMA_VERSION, FlightRecorder, HealthSentinel, HangWatchdog,
    RequestTracer, SpanTracer, TrainingHealthError, rank_skew,
    validate_jsonl, validate_record)
from distributed_pytorch_from_scratch_tpu.models.transformer import Transformer
from distributed_pytorch_from_scratch_tpu.runtime.mesh import make_mesh
from distributed_pytorch_from_scratch_tpu.serving.engine import (
    ContinuousBatchingEngine, PagedEngine, Request)
from distributed_pytorch_from_scratch_tpu.serving.loadgen import (
    run_loadgen, synthetic_requests)
from distributed_pytorch_from_scratch_tpu.training.metrics import MetricsWriter

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CFG = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=8, num_layers=2,
                  vocab_size=96, maxlen=64)
DRAFTER_CFG = ModelConfig(attn_dim=16, ffn_dim=32, num_heads=2,
                          num_layers=1, vocab_size=96, maxlen=64)
BUF = 32
EOS = 1


def _setup(tp=1, seed=3):
    mesh = make_mesh(MeshConfig(dp=1, tp=tp))
    model = Transformer(CFG, tp_size=tp)
    params = jax.device_put(model.init(jax.random.key(seed)),
                            model.shardings(mesh))
    return mesh, model, params


def _load_script(name):
    path = os.path.join(REPO, "scripts", name + ".py")
    spec = importlib.util.spec_from_file_location(f"_obs2_{name}", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _assert_contiguous_and_sums(rec, req, tol_ms=0.1):
    """The pinned timeline contract: spans chain end-to-start with no gap
    or overlap, and their sum equals the request's measured wall
    (finish - submit = TTFT + decode wall)."""
    spans = rec["spans"]
    assert spans, rec
    cursor = 0.0
    for s in spans:
        assert abs(s["start_ms"] - cursor) <= 0.01, (s, cursor, spans)
        assert s["dur_ms"] >= 0.0, s
        cursor = s["start_ms"] + s["dur_ms"]
    assert abs(cursor - rec["total_ms"]) <= tol_ms, (cursor, rec["total_ms"])
    wall_ms = (req.finish_t - req.submit_t) * 1e3
    assert abs(rec["total_ms"] - wall_ms) <= tol_ms, (rec["total_ms"],
                                                      wall_ms)
    # wall == TTFT + decode wall, by the Request clock identities
    ttft_ms = (req.first_token_t - req.submit_t) * 1e3
    decode_ms = (req.finish_t - req.first_token_t) * 1e3
    assert abs(rec["total_ms"] - (ttft_ms + decode_ms)) <= tol_ms


# ------------------------------------------------- per-request timelines

def test_paged_request_timelines_contiguous_and_sum_to_wall(tmp_path):
    """Every completed request of a paged run (chunked prefill + COW
    shared prefixes + forced preemption/resume) gets a contiguous
    timeline summing to its wall time; the preempted request's timeline
    shows the `preempted` span and a second `queued` stretch (the COW
    re-admission) — no orphan spans, live set drains to zero."""
    mesh, model, params = _setup(seed=3)
    writer = MetricsWriter(str(tmp_path), process_index=0)
    rt = RequestTracer(writer=writer)
    # the preempt-resume recipe: pool too small for combined growth
    eng = PagedEngine(model, mesh, params, num_slots=3, buf_len=BUF,
                      eos_id=EOS, page_size=8, num_pages=4, prefill_chunk=8,
                      request_tracer=rt, writer=writer)
    shared = [0, 5, 9, 60]
    prompts = [shared + [2, 8, 33], shared + [4, 7, 21],
               shared + [17, 8, 52]]
    for i, p in enumerate(prompts):
        eng.submit(Request(rid=i, prompt=list(p), max_new=12))
    eng.run_to_completion()
    writer.close()
    assert eng.preemptions >= 1            # the churn actually happened
    assert rt.live == 0                    # no orphan timelines
    preempted_seen = False
    for req in eng.completed:
        rec = rt.timeline(req.rid)
        assert rec is not None and rec["trace_id"] == req.trace_id
        _assert_contiguous_and_sums(rec, req)
        names = [s["name"] for s in rec["spans"]]
        assert names[0] == "queued", names
        assert "prefill_chunk" in names and "decode" in names, names
        if req.preemptions:
            preempted_seen = True
            assert "preempted" in names, names
            # resume = a second queued stretch after the preemption
            assert "queued" in names[names.index("preempted"):], names
            assert rec["preemptions"] == req.preemptions
    assert preempted_seen
    # the jsonl mirror: one versioned request_trace event per request
    recs = [json.loads(l) for l in open(tmp_path / "metrics.jsonl")]
    traces = [r for r in recs if r["tag"] == "request_trace"]
    assert len(traces) == len(eng.completed)
    assert all(r["schema_version"] == EVENT_SCHEMA_VERSION for r in traces)
    assert not any(validate_record(r) for r in traces)


def test_slot_engine_request_timelines(tmp_path):
    """The PR 5 slot engine gets the same contract (queued -> prefill ->
    decode), so traced loadgen runs are engine-agnostic."""
    mesh, model, params = _setup(seed=5)
    rt = RequestTracer()
    eng = ContinuousBatchingEngine(model, mesh, params, num_slots=2,
                                   buf_len=BUF, eos_id=EOS,
                                   prefill_bucket=8, request_tracer=rt)
    prompts = [[0, 5, 17, 33], [0, 9, 11], [0, 3, 5, 7, 11]]
    for i, p in enumerate(prompts):
        eng.submit(Request(rid=i, prompt=p, max_new=6))
    eng.run_to_completion()
    assert rt.live == 0
    for req in eng.completed:
        rec = rt.timeline(req.rid)
        _assert_contiguous_and_sums(rec, req)
        names = [s["name"] for s in rec["spans"]]
        assert names[0] == "queued" and "prefill" in names, names


def test_speculative_request_timelines():
    """Trace-ID propagation through drafter rounds: spec_round spans
    (with accepted counts) + drafter_prefill, still contiguous."""
    from distributed_pytorch_from_scratch_tpu.serving.speculative import (
        SpeculativeEngine)
    mesh, model, params = _setup(seed=2)
    dmodel = Transformer(DRAFTER_CFG, tp_size=1)
    dparams = jax.device_put(dmodel.init(jax.random.key(9)),
                             dmodel.shardings(mesh))
    rt = RequestTracer()
    eng = SpeculativeEngine(model, mesh, params, dmodel, dparams,
                            num_slots=2, buf_len=BUF, eos_id=EOS,
                            speculate_k=2, page_size=8, prefill_chunk=8,
                            request_tracer=rt)
    prompts = [[0, 5, 17, 33, 60], [0, 9, 11, 4]]
    for i, p in enumerate(prompts):
        eng.submit(Request(rid=i, prompt=p, max_new=8))
    eng.run_to_completion()
    assert rt.live == 0
    for req in eng.completed:
        rec = rt.timeline(req.rid)
        _assert_contiguous_and_sums(rec, req)
        names = [s["name"] for s in rec["spans"]]
        assert "spec_round" in names and "drafter_prefill" in names, names
        rounds = [s for s in rec["spans"] if s["name"] == "spec_round"]
        # accepted counts ride the coalesced spans
        assert all("accepted" in s for s in rounds)


def test_request_tracer_chrome_track(tmp_path):
    """Retired timelines land in the SpanTracer file as complete events
    on a synthetic per-request track plus a flow s/f pair."""
    tracer = SpanTracer(str(tmp_path), process_name="unit")
    clock = time.monotonic
    rt = RequestTracer(tracer=tracer, clock=clock)
    req = Request(rid=7, prompt=[0, 1, 2], max_new=4)
    req.submit_t = clock()
    rt.begin(req)
    rt.mark(req, "queued")
    rt.mark(req, "decode")
    rt.mark(req, "decode")
    req.prompt_len, req.first_token_t = 3, clock()
    req.finish_t = clock()
    rt.retire(req)
    path = tracer.close()
    evs = json.load(open(path))["traceEvents"]
    req_evs = [e for e in evs if e.get("cat") == "request"]
    assert {e["ph"] for e in req_evs} == {"X", "s", "f"}
    xs = [e for e in req_evs if e["ph"] == "X"]
    assert any(e["name"] == "req7:decode" and e["args"]["count"] == 2
               for e in xs)
    # synthetic track, not a host thread id
    assert all(e["tid"] >= 1_000_000 for e in req_evs)


# ---------------------------------------------------- the flight recorder

def test_flight_ring_bound_holds_under_sustained_load(tmp_path):
    fl = FlightRecorder(str(tmp_path), maxlen=64)
    for i in range(10_000):
        fl.record("ev", i=i)
    assert len(fl) == 64 and fl.recorded == 10_000
    path = fl.dump({"kind": "unit"}, tag="unit")
    doc = json.load(open(path))
    assert len(doc["ring"]) == 64
    # the ring holds the MOST RECENT events, oldest first
    assert doc["ring"][0]["i"] == 10_000 - 64
    assert doc["ring"][-1]["i"] == 9_999
    assert doc["trigger"]["kind"] == "unit"
    assert doc["recorded_total"] == 10_000


def test_flight_dump_cap(tmp_path):
    fl = FlightRecorder(str(tmp_path), maxlen=8, max_dumps=2)
    fl.record("ev")
    assert fl.dump({"kind": "a"}) and fl.dump({"kind": "b"})
    assert fl.dump({"kind": "c"}) is None        # capped
    assert fl.dumps_skipped == 1
    assert len(glob.glob(str(tmp_path / "flightdump_*.json"))) == 2


def test_flight_dump_write_failure_is_contained(tmp_path):
    """A diagnostic artifact must never kill the run it diagnoses: a
    dump whose write fails (dump dir's parent is a FILE — robust as
    root) returns None, counts a failure, and does not occupy a
    max_dumps slot or report a phantom path."""
    blocker = tmp_path / "blocker"
    blocker.write_text("i am a file")
    fl = FlightRecorder(str(blocker / "dumps"), maxlen=8, max_dumps=2)
    fl.record("ev")
    assert fl.dump({"kind": "a"}) is None
    assert fl.dump_failures == 1 and fl.dumps == []
    assert fl.dumps_skipped == 0           # a failure is not a cap skip


def test_pool_exhausted_preemption_dumps_flight(tmp_path):
    """The acceptance pin: a forced PoolExhausted preemption produces a
    flight dump whose trigger names the victim and whose ring holds the
    preceding scheduler/pool history."""
    mesh, model, params = _setup(seed=3)
    fl = FlightRecorder(str(tmp_path), maxlen=128)
    eng = PagedEngine(model, mesh, params, num_slots=3, buf_len=BUF,
                      eos_id=EOS, page_size=8, num_pages=4, prefill_chunk=8,
                      flight=fl)
    for i, p in enumerate([[0, 5, 9, 60, 2, 8, 33], [0, 11, 4, 7, 21, 35, 2],
                           [0, 44, 17, 8, 52, 3, 71]]):
        eng.submit(Request(rid=i, prompt=p, max_new=12))
    eng.run_to_completion()
    assert eng.preemptions >= 1
    dumps = sorted(glob.glob(str(tmp_path / "flightdump_pool_exhausted_*")))
    assert dumps, "PoolExhausted preemption produced no flight dump"
    doc = json.load(open(dumps[0]))
    assert doc["trigger"]["kind"] == "pool_exhausted_preempt"
    assert "victim_rid" in doc["trigger"]
    kinds = {ev["kind"] for ev in doc["ring"]}
    # the preceding ring context: admissions AND the preemption decision
    assert "sched_submit" in kinds and "preempt" in kinds, kinds
    assert "pool_exhausted" in kinds, kinds


def test_sentinel_halt_dumps_and_cross_links_flight(tmp_path):
    fl = FlightRecorder(str(tmp_path), maxlen=32)
    fl.record("heartbeat", step=1)
    fl.record("span", bucket="step")
    s = HealthSentinel(str(tmp_path), flight=fl)
    s.check(0, 2.0)
    with pytest.raises(TrainingHealthError) as ei:
        s.check(5, float("nan"))
    sent = json.load(open(ei.value.dump_path))
    flight_path = sent["flight_dump"]
    assert flight_path and os.path.exists(flight_path)
    doc = json.load(open(flight_path))
    assert doc["trigger"]["kind"] == "sentinel_nonfinite"
    assert doc["trigger"]["sentinel_dump"] == ei.value.dump_path
    assert {"heartbeat", "span"} <= {ev["kind"] for ev in doc["ring"]}


def test_watchdog_stall_dumps_and_cross_links_flight(tmp_path):
    fl = FlightRecorder(str(tmp_path), maxlen=32)
    fl.record("heartbeat", step=7)
    stalls = []
    wd = HangWatchdog(timeout_s=0.08, poll_s=0.02, flight=fl,
                      on_stall=lambda rec: stalls.append(rec))
    try:
        wd.beat(step=7)
        deadline = time.monotonic() + 5.0
        while not stalls and time.monotonic() < deadline:
            time.sleep(0.02)
        assert stalls
        flight_path = stalls[0]["flight_dump"]
        assert flight_path and os.path.exists(flight_path)
        doc = json.load(open(flight_path))
        assert doc["trigger"]["kind"] == "watchdog_stall"
        assert doc["trigger"]["last_step"] == 7
    finally:
        wd.close()


# --------------------------------------------- loadgen exemplars + summary

def test_loadgen_exemplars_and_summarize_waterfall(tmp_path):
    """The e2e acceptance pin: a traced loadgen run surfaces the k-worst
    TTFT/TPOT requests WITH timelines, and summarize_run.py renders the
    waterfall (plus flight-dump pointers when one exists)."""
    mesh, model, params = _setup(seed=4)
    writer = MetricsWriter(str(tmp_path), process_index=0)
    fl = FlightRecorder(str(tmp_path), maxlen=64)
    rt = RequestTracer(writer=writer, flight=fl)
    eng = PagedEngine(model, mesh, params, num_slots=3, buf_len=BUF,
                      eos_id=EOS, page_size=8, num_pages=4, prefill_chunk=8,
                      request_tracer=rt, flight=fl, writer=writer)
    reqs = synthetic_requests(5, 4, 10, 10, CFG.vocab_size, seed=2,
                              arrival="burst")
    summary = run_loadgen(eng, reqs, sleep=lambda s: None)
    writer.close()
    assert summary["completed"] == 5
    assert len(summary["worst_ttft_rids"]) == 3
    recs = [json.loads(l) for l in open(tmp_path / "metrics.jsonl")]
    (ex,) = [r for r in recs if r["tag"] == "request_exemplars"]
    assert not validate_record(ex)
    worst = ex["worst_ttft"]
    assert worst[0]["timeline"], worst
    # worst-first ordering
    ttfts = [w["ttft_ms"] for w in worst]
    assert ttfts == sorted(ttfts, reverse=True)
    sr = _load_script("summarize_run")
    text = sr.summarize(str(tmp_path))
    assert "Slowest requests" in text
    assert f"worst TTFT rid {worst[0]['rid']}" in text
    if fl.dumps:
        assert "flight dump" in text.lower()


def test_summarize_renders_flight_and_skew_sections(tmp_path):
    """Synthetic metrics + a flight dump: the summary grows the flight
    pointer and per-rank skew table sections, and schema drift is LOUD."""
    fl = FlightRecorder(str(tmp_path), maxlen=8)
    fl.record("pool_stats", live=3)
    fl.dump({"kind": "slo_attainment_collapse", "slo_class": "interactive"},
            tag="slo_collapse")
    # two ranks' phase stats; p1 is a data_wait straggler
    with MetricsWriter(str(tmp_path), process_index=0) as w:
        w.event("rank_phase_stats", process=0,
                phases_s={"data_wait": 1.0, "step": 10.0}, steps=100,
                tokens=1000, wall_s=12.0)
    with MetricsWriter(str(tmp_path), process_index=1) as w:
        w.event("rank_phase_stats", process=1,
                phases_s={"data_wait": 5.0, "step": 10.2}, steps=100,
                tokens=1000, wall_s=16.0)
    # a drifted record: missing required field + no schema_version
    with open(tmp_path / "metrics.proc9.jsonl", "w") as f:
        f.write(json.dumps({"tag": "request_trace", "ts": 0.0}) + "\n")
    sr = _load_script("summarize_run")
    text = sr.summarize(str(tmp_path))
    assert "slo_attainment_collapse" in text
    assert "Cross-rank phase skew" in text
    assert "straggler suspect: p1" in text and "data_wait" in text
    assert "SCHEMA DRIFT" in text and "missing schema_version" in text


# ------------------------------------------------- cross-rank attribution

def test_rank_skew_ranks_stragglers():
    recs = [
        {"process": 0, "phases_s": {"data_wait": 1.0, "h2d": 0.5,
                                    "step": 10.0}, "steps": 100},
        {"process": 1, "phases_s": {"data_wait": 4.0, "h2d": 0.5,
                                    "step": 10.1}, "steps": 100},
        {"process": 2, "phases_s": {"data_wait": 1.1, "h2d": 0.5,
                                    "step": 9.9}, "steps": 100},
    ]
    rep = rank_skew(recs, tol=0.2)
    assert rep["ranks"] == 3
    assert rep["suspects"][0] == {"process": 1, "phase": "data_wait",
                                  "excess_s": pytest.approx(1.9667,
                                                            abs=1e-3),
                                  "ratio": pytest.approx(1.9672, abs=1e-3)}
    assert rep["phases"]["data_wait"]["max_process"] == 1
    # one skewed phase only -> not persistent
    assert rep["persistent"] == []
    # a rank slow in TWO phases IS persistent
    recs[1]["phases_s"]["h2d"] = 2.0
    rep = rank_skew(recs, tol=0.2)
    assert rep["persistent"] == [1]
    # nothing to compare with one record — or with two records from the
    # SAME process (a re-run staged script's duplicate events must not
    # render a fake one-rank "cross-rank" table)
    assert rank_skew(recs[:1]) is None
    assert rank_skew([recs[0], dict(recs[0])]) is None


# ------------------------------------------------------ schema validation

def test_metrics_events_carry_schema_version_and_validate(tmp_path):
    with MetricsWriter(str(tmp_path), process_index=0) as w:
        w.scalar("train/x", 1.0, 1)  # scalars stay unversioned
        w.event("goodput_summary", wall_s=1.0, buckets_s={}, goodput=0.5,
                steps=10)
    assert validate_jsonl(str(tmp_path / "metrics.jsonl")) == []
    recs = [json.loads(l) for l in open(tmp_path / "metrics.jsonl")]
    assert "schema_version" not in recs[0]
    assert recs[1]["schema_version"] == EVENT_SCHEMA_VERSION


def test_schema_validator_fails_loudly_on_drift(tmp_path):
    bad = tmp_path / "metrics.jsonl"
    with open(bad, "w") as f:
        f.write(json.dumps({"tag": "serving_summary", "ts": 0.0,
                            "schema_version": EVENT_SCHEMA_VERSION,
                            "requests": 4}) + "\n")      # missing fields
        f.write(json.dumps({"tag": "goodput_summary", "ts": 0.0,
                            "wall_s": 1.0, "buckets_s": {}, "goodput": 1.0,
                            "steps": 1}) + "\n")         # pre-versioned
        f.write(json.dumps({"tag": "cost_analysis", "ts": 0.0, "flops": 1,
                            "schema_version": EVENT_SCHEMA_VERSION + 5})
                + "\n")                                  # future version
        f.write("{torn json\n")
    problems = "\n".join(validate_jsonl(str(bad)))
    assert "missing required field 'completed'" in problems
    assert "missing schema_version" in problems
    assert "NEWER than this reader" in problems
    assert "unparseable JSON" in problems


# ------------------------------------------------- the regression gate

GATE = None


def _gate():
    global GATE
    if GATE is None:
        GATE = _load_script("check_bench_regression")
    return GATE


def test_gate_passes_on_committed_trajectory_vs_itself(capsys):
    rc = _gate().main(["--fresh", os.path.join(REPO, "BENCH_r01.json")])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["status"] == "ok" and out["checks"]


def test_gate_fails_on_degraded_record(tmp_path, capsys):
    base = json.load(open(os.path.join(REPO, "BENCH_r01.json")))["parsed"]
    degraded = dict(base, value=base["value"] * 0.7,
                    vs_baseline=base["vs_baseline"] * 0.7)
    p = tmp_path / "degraded.json"
    p.write_text(json.dumps(degraded))
    rc = _gate().main(["--fresh", str(p)])
    assert rc == 1
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["status"] == "regression"
    assert any(not c["ok"] for c in out["checks"])
    # within-tolerance wobble still passes
    ok = dict(base, value=base["value"] * 0.95)
    p.write_text(json.dumps(ok))
    assert _gate().main(["--fresh", str(p)]) == 0


def test_gate_skips_on_backend_unavailable(tmp_path, capsys):
    p = tmp_path / "outage.json"
    p.write_text(json.dumps({"metric": "bench",
                             "error": "backend_unavailable",
                             "detail": "tunnel down"}))
    rc = _gate().main(["--fresh", str(p)])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["status"] == "skip" and out["reason"] == "backend_unavailable"
    # a NON-outage error is a real failure, not a skip
    p.write_text(json.dumps({"metric": "bench", "error": "oom"}))
    assert _gate().main(["--fresh", str(p)]) == 1


def test_gate_serving_latency_direction(tmp_path, capsys):
    """Serving records gate BOTH ways: throughput down OR p95 up past
    tolerance fails; no comparable baseline passes with a note."""
    base = {"metric": "serving x", "value": 1000.0,
            "unit": "tokens/sec (serving)", "vs_baseline": 2.0,
            "ttft_ms_p95": 100.0, "tpot_ms_p95": 10.0}
    bp = tmp_path / "base.json"
    bp.write_text(json.dumps(base))
    worse = dict(base, ttft_ms_p95=200.0)   # latency doubled, rate held
    fp = tmp_path / "fresh.json"
    fp.write_text(json.dumps(worse))
    assert _gate().main(["--fresh", str(fp), "--baseline", str(bp)]) == 1
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    bad = [c for c in out["checks"] if not c["ok"]]
    assert bad and bad[0]["field"] == "ttft_ms_p95"
    # no same-unit baseline at all -> pass with status no_baseline
    assert _gate().main(["--fresh", str(fp), "--baseline"]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["status"] == "no_baseline"


# --------------------------------------------------------- CLI coverage

def test_serve_dry_run_with_tracing_and_flight(tmp_path, capsys):
    """--dry_run --paged --trace_requests --flight_records: the CLI smoke
    that keeps the flags from rotting on chip-less images. Every request
    gets a versioned request_trace event; exemplars land in the summary
    record."""
    from distributed_pytorch_from_scratch_tpu.serving import serve as srv
    log_dir = str(tmp_path / "logs")
    srv.main(["--dry_run", "--paged", "--trace_requests",
              "--flight_records", "--log_dir", log_dir])
    out = capsys.readouterr().out.strip().splitlines()
    rec = json.loads(out[-1])
    assert rec["trace_requests"] is True
    assert len(rec["worst_ttft_rids"]) >= 1
    recs = [json.loads(l)
            for l in open(os.path.join(log_dir, "metrics.jsonl"))]
    traces = [r for r in recs if r["tag"] == "request_trace"]
    assert len(traces) == rec["completed"]
    assert not any(p for r in traces for p in validate_record(r))
    assert any(r["tag"] == "request_exemplars" for r in recs)


def test_serve_flight_ring_zero_disables(tmp_path, capsys):
    """--flight_ring 0 disables the recorder (train.py semantics) —
    not a ValueError at engine construction."""
    from distributed_pytorch_from_scratch_tpu.serving import serve as srv
    srv.main(["--dry_run", "--paged", "--flight_records", "--flight_ring",
              "0", "--log_dir", str(tmp_path / "logs")])
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert "flight_dumps" not in rec       # recorder was off


def test_serve_refuses_unwritable_trace_dir(tmp_path):
    """Loud refusal, not a silent traceless run: a log_dir that cannot be
    created (parent is a FILE — robust even when running as root, which
    ignores permission bits) dies before any engine work."""
    blocker = tmp_path / "blocker"
    blocker.write_text("i am a file")
    from distributed_pytorch_from_scratch_tpu.serving import serve as srv
    with pytest.raises(SystemExit) as ei:
        srv.main(["--dry_run", "--paged", "--trace_requests",
                  "--log_dir", str(blocker / "logs")])
    assert "not writable" in str(ei.value)


def test_bench_serving_flags_refused_without_serving():
    import bench
    with pytest.raises(SystemExit):
        bench.parse_args(["--trace_requests"])
    with pytest.raises(SystemExit):
        bench.parse_args(["--flight_records"])
    args = bench.parse_args(["--serving", "--trace_requests",
                             "--flight_records", "--obs_dir", "/tmp/x"])
    assert args.trace_requests and args.flight_records
