"""ZeRO-1 (dp-sharded Adam moments): identical math, sharded memory.

No reference counterpart (plain per-rank Adam, `/root/reference/train.py:83`;
SURVEY §2.4 "ZeRO ❌"). Invariants pinned here:

* training with zero1=True produces bit-comparable params/losses to the
  plain path (it is a layout change, not an algorithm change);
* the moments actually live dp-sharded on device (per-device bytes shrink);
* checkpoint save/load round-trips the dp-sharded state.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_from_scratch_tpu.config import (
    IGNORE_INDEX, MeshConfig, ModelConfig, OptimizerConfig)
from distributed_pytorch_from_scratch_tpu.models.transformer import Transformer
from distributed_pytorch_from_scratch_tpu.runtime.mesh import make_mesh
from distributed_pytorch_from_scratch_tpu.training.checkpoint import (
    load_checkpoint, save_checkpoint)
from distributed_pytorch_from_scratch_tpu.training.optim import (
    AdamState, init_adam_state)
from distributed_pytorch_from_scratch_tpu.training.train_step import (
    build_train_step)
from distributed_pytorch_from_scratch_tpu.training.zero import (
    zero1_moment_shardings, zero1_specs)

CFG = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=8, num_layers=2,
                  vocab_size=96, maxlen=32)
OCFG = OptimizerConfig(lr=1e-3, warmup_steps=5, max_steps=50)


def make_batch(key, batch=8, t=16, vocab=96):
    k1, k2 = jax.random.split(key)
    ids = jax.random.randint(k1, (batch, t), 0, vocab)
    tgt = jax.random.randint(k2, (batch, t), 0, vocab)
    pos = jnp.tile(jnp.arange(t)[None, :], (batch, 1))
    return ids, tgt, pos


def put_opt(opt, mesh, moment_sh):
    scalar = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())
    return jax.device_put(opt, AdamState(step=scalar, mu=moment_sh,
                                         nu=moment_sh))


@pytest.mark.parametrize("dp,tp", [(4, 2), (8, 1), (2, 4)])
def test_zero1_matches_plain_adam(dp, tp):
    mesh = make_mesh(MeshConfig(dp=dp, tp=tp))
    model = Transformer(CFG, tp_size=tp)
    key = jax.random.key(0)
    params_a = jax.device_put(model.init(key), model.shardings(mesh))
    params_b = jax.tree.map(jnp.copy, params_a)

    step_plain = build_train_step(model, mesh, OCFG)
    step_zero = build_train_step(model, mesh, OCFG, zero1=True)
    opt_a = put_opt(init_adam_state(params_a), mesh, model.shardings(mesh))
    opt_b = put_opt(init_adam_state(params_b), mesh,
                    zero1_moment_shardings(model, mesh))

    for s in range(10):
        ids, tgt, pos = make_batch(jax.random.fold_in(key, s))
        params_a, opt_a, loss_a = step_plain(params_a, opt_a, ids, tgt, pos)
        params_b, opt_b, loss_b = step_zero(params_b, opt_b, ids, tgt, pos)
        np.testing.assert_allclose(float(loss_a), float(loss_b),
                                   rtol=1e-6, atol=1e-7)

    for a, b in zip(jax.tree.flatten(params_a)[0], jax.tree.flatten(params_b)[0]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_moments_are_dp_sharded():
    dp, tp = 4, 2
    mesh = make_mesh(MeshConfig(dp=dp, tp=tp))
    model = Transformer(CFG, tp_size=tp)
    params = jax.device_put(model.init(jax.random.key(0)),
                            model.shardings(mesh))
    opt = put_opt(init_adam_state(params), mesh,
                  zero1_moment_shardings(model, mesh))
    step = build_train_step(model, mesh, OCFG, zero1=True)
    ids, tgt, pos = make_batch(jax.random.key(1))
    params, opt, _ = step(params, opt, ids, tgt, pos)

    # the big moment leaves must be dp-sharded on device after the step
    big = opt.mu["layers"]["wq"]["weight"]          # (L, d, d/tp)
    local = big.addressable_shards[0].data.size
    assert local * dp * tp == big.size, (
        f"wq moment not dp-sharded: local={local}, global={big.size}")
    # and params stay replicated over dp (sharded only over tp)
    pw = params["layers"]["wq"]["weight"]
    assert pw.addressable_shards[0].data.size * tp == pw.size


def test_zero1_specs_fallback_replicated():
    """Leaves with no free dp-divisible dim keep their param spec."""
    mesh = make_mesh(MeshConfig(dp=8, tp=1))
    import jax.sharding as shd
    P = shd.PartitionSpec
    specs = {"w": P(None, None)}
    shapes = {"w": jax.ShapeDtypeStruct((3, 5), jnp.float32)}  # nothing divides by 8
    out = zero1_specs(specs, shapes, mesh)
    assert out["w"] == P(None, None)


def test_zero1_checkpoint_roundtrip(tmp_path):
    dp, tp = 2, 2
    mesh = make_mesh(MeshConfig(dp=dp, tp=tp))
    model = Transformer(CFG, tp_size=tp)
    params = jax.device_put(model.init(jax.random.key(0)),
                            model.shardings(mesh))
    opt = put_opt(init_adam_state(params), mesh,
                  zero1_moment_shardings(model, mesh))
    step = build_train_step(model, mesh, OCFG, zero1=True)
    ids, tgt, pos = make_batch(jax.random.key(2))
    for s in range(3):
        params, opt, _ = step(params, opt, ids, tgt, pos)

    save_checkpoint(str(tmp_path), 3, 1.0, params, model.specs(), tp,
                    opt_state=opt)
    p2, opt2, it = load_checkpoint(str(tmp_path), 3, params, model.specs(),
                                   with_opt=True)
    assert it == 3
    for a, b in zip(jax.tree.flatten(opt.mu)[0], jax.tree.flatten(opt2.mu)[0]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-7)
