"""data.prefetch: the background input-assembly thread (VERDICT r2 weak #6).

Covers ordering, the window/stack transforms, exception propagation, resume
skip, and prompt producer shutdown on close()/abandonment.
"""

import threading
import time

import numpy as np
import pytest

from distributed_pytorch_from_scratch_tpu.data.prefetch import (
    Prefetcher, stack_window, window_stream)


def _batch(i, rows=2, width=4):
    return {k: np.full((rows, width), i + off, np.int32)
            for off, k in enumerate(("input_ids", "target_ids",
                                     "position_ids"))}


def test_window_stream_groups_and_skips():
    wins = list(window_stream((_batch(i) for i in range(7)), 3, skip=1))
    assert [len(w) for w in wins] == [3, 3]  # 6 after skip -> 2 full windows
    assert wins[0][0]["input_ids"][0, 0] == 1  # batch 0 skipped


def test_window_stream_yields_final_partial():
    wins = list(window_stream((_batch(i) for i in range(5)), 3))
    assert [len(w) for w in wins] == [3, 2]


def test_stack_window_shapes():
    stacked = stack_window([_batch(0), _batch(1)])
    assert stacked["input_ids"].shape == (2, 2, 4)
    np.testing.assert_array_equal(stacked["input_ids"][1], _batch(1)["input_ids"])


def test_prefetcher_preserves_order_and_counts_waits():
    src = [_batch(i) for i in range(9)]
    pf = Prefetcher(iter(src), depth=2)
    got = list(pf)
    assert len(got) == 9
    for i, b in enumerate(got):
        np.testing.assert_array_equal(b["input_ids"], src[i]["input_ids"])
    assert pf.pulls == 10  # 9 items + the DONE sentinel
    assert pf.wait_time >= 0.0


def test_prefetcher_applies_transform_on_thread():
    tids = set()

    def tf(item):
        tids.add(threading.get_ident())
        return item

    list(Prefetcher(iter([_batch(0), _batch(1)]), transform=tf))
    assert tids and threading.get_ident() not in tids


def test_prefetcher_propagates_exceptions():
    def gen():
        yield _batch(0)
        raise RuntimeError("boom in producer")

    pf = Prefetcher(gen())
    assert next(iter(pf))["input_ids"][0, 0] == 0
    with pytest.raises(RuntimeError, match="boom in producer"):
        next(iter(pf))


def test_prefetcher_close_stops_abandoned_producer():
    started = threading.Event()

    def endless():
        started.set()
        i = 0
        while True:
            yield _batch(i % 100)
            i += 1

    pf = Prefetcher(endless(), depth=2)
    started.wait(5)
    next(iter(pf))
    pf.close()
    pf._thread.join(timeout=5)
    assert not pf._thread.is_alive(), "producer must exit after close()"


def test_prefetcher_overlaps_slow_producer():
    """While the consumer processes item N, the producer assembles N+1: the
    consumer's second pull must not pay the full production cost."""
    delay = 0.15

    def slow():
        for i in range(3):
            time.sleep(delay)
            yield _batch(i)

    pf = Prefetcher(slow(), depth=2)
    it = iter(pf)
    next(it)                      # producer starts on item 1 immediately
    time.sleep(delay * 1.5)       # consumer "works"; item 1 lands meanwhile
    w0 = pf.wait_time
    next(it)
    assert pf.wait_time - w0 < delay / 2, (
        f"second pull waited {pf.wait_time - w0:.3f}s — no overlap")
