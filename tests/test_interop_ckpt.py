"""Reference-checkpoint import (interop.py): torch per-rank .pth shards ->
this framework's param tree and checkpoint format.

The fixtures build state_dicts with the reference's EXACT naming and
shard layouts (`/root/reference/models/layers.py` — column shards
(odim/tp, idim), row shards (odim, idim/tp), replicated row bias and
norms, vocab-row-sharded embedding/lm_head) from known full tensors, so
the converter's concat/transpose/pad logic is verified against ground
truth without executing any reference code. A forward/loss drive on the
imported params proves the result is a usable model, and the CLI path
round-trips through the normal checkpoint machinery onto a tp=2 mesh.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")  # host-side only; not a package dep

from distributed_pytorch_from_scratch_tpu import MeshConfig, make_mesh
from distributed_pytorch_from_scratch_tpu.config import ModelConfig
from distributed_pytorch_from_scratch_tpu.interop import (
    convert_state_dicts, load_reference_checkpoint, main as interop_main)
from distributed_pytorch_from_scratch_tpu.models.transformer import Transformer

CFG = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=4, num_layers=2,
                  vocab_size=96, maxlen=64)


def make_full_tensors(cfg, rng):
    d, f, L, V = cfg.attn_dim, cfg.ffn_dim, cfg.num_layers, cfg.vocab_size
    t = lambda *shape: rng.standard_normal(shape).astype(np.float32)
    full = {"embedding.weight": t(V, d), "norm.scale": t(d),
            "lm_head.weight": t(V, d), "lm_head.bias": t(V)}
    for i in range(L):
        p = f"layers.{i}"
        for name in ("wq", "wk", "wv"):
            full[f"{p}.attn.{name}.weight"] = t(d, d)   # torch (odim, idim)
            full[f"{p}.attn.{name}.bias"] = t(d)
        full[f"{p}.attn.wo.weight"] = t(d, d)
        full[f"{p}.attn.wo.bias"] = t(d)
        full[f"{p}.ffn.gate_proj.weight"] = t(f, d)
        full[f"{p}.ffn.gate_proj.bias"] = t(f)
        full[f"{p}.ffn.up_proj.weight"] = t(f, d)
        full[f"{p}.ffn.up_proj.bias"] = t(f)
        full[f"{p}.ffn.down_proj.weight"] = t(d, f)
        full[f"{p}.ffn.down_proj.bias"] = t(d)
        full[f"{p}.norm1.scale"] = t(d)
        full[f"{p}.norm2.scale"] = t(d)
    return full


def shard_reference(full, cfg, tp):
    """Split full tensors into per-rank state_dicts exactly the way the
    reference's parallel layers hold them."""
    col_w = lambda w, r: np.split(w, tp, axis=0)[r]     # (odim/tp, idim)
    row_w = lambda w, r: np.split(w, tp, axis=1)[r]     # (odim, idim/tp)
    shards = []
    for r in range(tp):
        s = {}
        for k, v in full.items():
            if k == "embedding.weight" or k.startswith("lm_head"):
                s[k] = np.split(v, tp, axis=0)[r]       # vocab shards
            elif k.endswith(("norm1.scale", "norm2.scale")) or k == "norm.scale":
                s[k] = v                                  # replicated
            elif ".wo." in k or ".down_proj." in k:
                s[k] = row_w(v, r) if k.endswith("weight") else v  # row: full bias
            elif k.endswith("weight"):
                s[k] = col_w(v, r)
            else:
                s[k] = np.split(v, tp, axis=0)[r]       # column bias shards
        shards.append(s)
    return shards


@pytest.mark.parametrize("tp", [1, 2, 4])
def test_convert_reassembles_ground_truth(tp):
    rng = np.random.default_rng(0)
    full = make_full_tensors(CFG, rng)
    params = convert_state_dicts(shard_reference(full, CFG, tp), CFG)

    np.testing.assert_array_equal(params["embedding"]["weight"],
                                  full["embedding.weight"])
    np.testing.assert_array_equal(params["norm"]["scale"], full["norm.scale"])
    # linears transpose into the (idim, odim) layout
    np.testing.assert_array_equal(params["lm_head"]["weight"],
                                  full["lm_head.weight"].T)
    np.testing.assert_array_equal(params["lm_head"]["bias"],
                                  full["lm_head.bias"])
    for i in range(CFG.num_layers):
        p = f"layers.{i}"
        for mod, ref in [("wq", "attn.wq"), ("wo", "attn.wo"),
                         ("gate_proj", "ffn.gate_proj"),
                         ("down_proj", "ffn.down_proj")]:
            np.testing.assert_array_equal(
                params["layers"][mod]["weight"][i],
                full[f"{p}.{ref}.weight"].T, err_msg=f"{p}.{ref}")
            np.testing.assert_array_equal(
                params["layers"][mod]["bias"][i], full[f"{p}.{ref}.bias"])
        np.testing.assert_array_equal(params["layers"]["norm1"]["scale"][i],
                                      full[f"{p}.norm1.scale"])


def test_convert_is_tp_invariant():
    """The same full tensors imported from tp=1 and tp=4 shardings must
    produce identical trees (shard reassembly is lossless)."""
    rng = np.random.default_rng(1)
    full = make_full_tensors(CFG, rng)
    p1 = convert_state_dicts(shard_reference(full, CFG, 1), CFG)
    p4 = convert_state_dicts(shard_reference(full, CFG, 4), CFG)
    import jax
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p4)):
        np.testing.assert_array_equal(a, b)


def test_padded_vocab_import():
    """vocab 90 imported with pad_vocab_multiple=4 -> 92 rows/cols of
    which the last 2 are REAL zero padding (the layout a tp=4 target model
    expects — padded_vocab_size(4) == 92)."""
    cfg = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=4, num_layers=1,
                      vocab_size=90, maxlen=32)
    rng = np.random.default_rng(2)
    full = make_full_tensors(cfg, rng)
    params = convert_state_dicts(shard_reference(full, cfg, 2), cfg,
                                 pad_vocab_multiple=4)
    assert cfg.padded_vocab_size(4) == 92
    assert params["embedding"]["weight"].shape == (92, 32)
    assert params["lm_head"]["weight"].shape == (32, 92)
    assert params["lm_head"]["bias"].shape == (92,)
    np.testing.assert_array_equal(params["embedding"]["weight"][:90],
                                  full["embedding.weight"])
    assert (params["embedding"]["weight"][90:] == 0).all()
    assert (params["lm_head"]["weight"][:, 90:] == 0).all()
    assert (params["lm_head"]["bias"][90:] == 0).all()

    # and the padded import actually drives a tp=4 model
    import jax
    import jax.numpy as jnp
    model = Transformer(cfg, tp_size=4)
    mesh = make_mesh(MeshConfig(tp=4))
    sp = jax.device_put(jax.tree.map(jnp.asarray, params),
                        model.shardings(mesh))
    ids = jax.random.randint(jax.random.key(1), (2, 8), 0, 90)
    pos = jnp.tile(jnp.arange(8)[None, :], (2, 1))
    loss = model.make_loss(mesh)(sp, ids, ids, pos)
    assert np.isfinite(float(loss))


def test_imported_params_drive_the_model():
    """Imported params run a forward + loss on a tp=2 mesh — shape-exact
    and finite (the end-to-end 'switch frameworks' check)."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(3)
    full = make_full_tensors(CFG, rng)
    params = convert_state_dicts(shard_reference(full, CFG, 2), CFG)
    params = jax.tree.map(jnp.asarray, params)

    model = Transformer(CFG, tp_size=2)
    mesh = make_mesh(MeshConfig(tp=2))
    sp = jax.device_put(params, model.shardings(mesh))
    ids = jax.random.randint(jax.random.key(0), (2, 16), 0, CFG.vocab_size)
    pos = jnp.tile(jnp.arange(16)[None, :], (2, 1))
    logits = model.make_forward(mesh)(sp, ids, pos)
    assert logits.shape == (2, 16, model.vocab_padded)
    assert bool(jnp.isfinite(logits).all())
    loss = model.make_loss(mesh)(sp, ids, ids, pos)
    assert np.isfinite(float(loss))


def test_cli_import_roundtrip(tmp_path):
    """torch .pth rank files -> interop CLI -> our checkpoint -> reload on
    a tp=2 mesh; values identical to the direct conversion."""
    import jax
    import jax.numpy as jnp

    from distributed_pytorch_from_scratch_tpu.training.checkpoint import (
        load_checkpoint)

    rng = np.random.default_rng(4)
    full = make_full_tensors(CFG, rng)
    ref_dir = tmp_path / "ref"
    ref_dir.mkdir()
    for r, sd in enumerate(shard_reference(full, CFG, 2)):
        torch.save({k: torch.from_numpy(v) for k, v in sd.items()},
                   ref_dir / f"tprank-{r}_iter-500_loss-3.1400.pth")

    out_dir = tmp_path / "ours"
    interop_main(["--ref_ckpt_dir", str(ref_dir), "--out_dir", str(out_dir),
                  "--attn_dim", "32", "--ffn_dim", "64", "--num_heads", "4",
                  "--num_layers", "2", "--vocab_size", "96",
                  "--maxlen", "64"])

    model = Transformer(CFG)
    template = model.init(jax.random.key(9))
    loaded, _, step = load_checkpoint(str(out_dir), 500, template,
                                      model.specs())
    assert step == 500
    direct = load_reference_checkpoint(str(ref_dir), 500, CFG)
    for a, b in zip(jax.tree.leaves(loaded), jax.tree.leaves(direct)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_import_rejects_mismatched_vocab():
    """An over- or under-declared --vocab_size must fail with a diagnostic,
    never silently zero-fill 'real' vocab rows."""
    import dataclasses

    rng = np.random.default_rng(5)
    full = make_full_tensors(CFG, rng)
    shards = shard_reference(full, CFG, 2)
    for wrong_vocab in (128, 64):
        wrong = dataclasses.replace(CFG, vocab_size=wrong_vocab)
        with pytest.raises(ValueError, match="flags match"):
            convert_state_dicts(shards, wrong)


# ---- export direction: our checkpoints -> reference .pth ----


def test_export_inverts_import():
    """export_state_dicts is the exact inverse of convert_state_dicts:
    full tensors -> reference shards -> our tree -> reference shards again
    reproduces the original shard values bit-for-bit, at matching AND
    different TP degrees."""
    from distributed_pytorch_from_scratch_tpu.interop import (
        export_state_dicts)

    rng = np.random.default_rng(6)
    full = make_full_tensors(CFG, rng)
    orig = shard_reference(full, CFG, 2)
    params = convert_state_dicts(orig, CFG)

    again = export_state_dicts(params, CFG, 2)
    assert [set(s) for s in again] == [set(s) for s in orig]
    for a, b in zip(again, orig):
        for k in b:
            np.testing.assert_array_equal(a[k], b[k], err_msg=k)

    # resharded export (tp=4) concatenates back to the same full tensors
    tp4 = export_state_dicts(params, CFG, 4)
    w = np.concatenate([s["layers.0.attn.wq.weight"] for s in tp4], axis=0)
    np.testing.assert_array_equal(w, full["layers.0.attn.wq.weight"])


def test_export_drops_vocab_padding():
    from distributed_pytorch_from_scratch_tpu.interop import (
        export_state_dicts)

    cfg = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=4, num_layers=1,
                      vocab_size=90, maxlen=32)
    rng = np.random.default_rng(7)
    full = make_full_tensors(cfg, rng)
    params = convert_state_dicts(shard_reference(full, cfg, 2), cfg,
                                 pad_vocab_multiple=4)  # padded to 92
    out = export_state_dicts(params, cfg, 1)[0]
    assert out["embedding.weight"].shape == (90, 32)
    assert out["lm_head.weight"].shape == (90, 32)
    assert out["lm_head.bias"].shape == (90,)
    np.testing.assert_array_equal(out["embedding.weight"],
                                  full["embedding.weight"])
    np.testing.assert_array_equal(out["lm_head.weight"],
                                  full["lm_head.weight"])


def test_export_rejects_unexportable_features():
    from distributed_pytorch_from_scratch_tpu.interop import (
        export_state_dicts)

    moe = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=4, num_layers=2,
                      vocab_size=96, maxlen=64, num_experts=4)
    with pytest.raises(ValueError, match="MoE"):
        export_state_dicts({}, moe, 1)
    gqa = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=4, num_kv_heads=2,
                      vocab_size=96, maxlen=64, num_layers=2)
    with pytest.raises(ValueError, match="GQA"):
        export_state_dicts({}, gqa, 1)


def test_cli_export_roundtrip(tmp_path):
    """Train-free CLI round-trip: our checkpoint (from a real model init)
    -> export at tp=2 -> import back -> identical param tree."""
    import jax

    from distributed_pytorch_from_scratch_tpu.training.checkpoint import (
        save_checkpoint)

    model = Transformer(CFG)
    params = model.init(jax.random.key(42))
    ours = tmp_path / "ours"
    save_checkpoint(str(ours), 7, 1.23, params, model.specs(), tp_size=1)

    exported = tmp_path / "ref"
    interop_main(["--direction", "export", "--our_ckpt_dir", str(ours),
                  "--out_dir", str(exported), "--export_tp", "2",
                  "--attn_dim", "32", "--ffn_dim", "64", "--num_heads", "4",
                  "--num_layers", "2", "--vocab_size", "96",
                  "--maxlen", "64"])
    pths = sorted(exported.glob("tprank-*_iter-7_loss-*.pth"))
    assert len(pths) == 2
    # the real loss metadata (1.23 from our filename) carries over
    assert all("loss-1.2300" in p.name for p in pths), pths

    from distributed_pytorch_from_scratch_tpu.interop import (
        load_reference_checkpoint)
    back = load_reference_checkpoint(str(exported), 7, CFG)
    for a, b in zip(jax.tree.leaves(back), jax.tree.leaves(params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=0, atol=0)


def test_export_rejects_understated_flags():
    """Shape flags that understate the trained model must fail loudly —
    export slices vocab padding and loops range(num_layers), so a silent
    pass would truncate the model."""
    import dataclasses

    from distributed_pytorch_from_scratch_tpu.interop import (
        export_state_dicts)

    rng = np.random.default_rng(8)
    full = make_full_tensors(CFG, rng)
    params = convert_state_dicts(shard_reference(full, CFG, 1), CFG)
    small_vocab = dataclasses.replace(CFG, vocab_size=32)
    with pytest.raises(ValueError, match="drop"):
        export_state_dicts(params, small_vocab, 1)
    few_layers = dataclasses.replace(CFG, num_layers=1)
    with pytest.raises(ValueError, match="does not match"):
        export_state_dicts(params, few_layers, 1)
