// Native data-path library: byte-level BPE encoder + LM batch collate.
//
// The reference's data path rides two native subsystems it does not own:
// the HF `tokenizers` Rust BPE (`/root/reference/train_tokenizer.py:5-9`,
// `pre_tokenize.py:7`) and torch's C++ DataLoader/collate machinery
// (`dataset.py:58-68`). This file is the framework-owned C++ equivalent:
//
//  * GPT-2-style byte-level BPE encoding compatible with the shipped
//    `tokenizer/tokenizer.json` (ByteLevel pretokenizer with
//    add_prefix_space + the GPT-2 split regex, bytes->unicode alphabet,
//    rank-ordered greedy pair merging). Unicode letter/number classification
//    covers ASCII + the common alphabetic/digit ranges; codepoints outside
//    the table classify as "other", which can only move pretoken boundaries
//    (byte-level coverage keeps every input losslessly encodable) — the
//    Python binding verifies parity against HF on load and falls back if
//    the host corpus disagrees.
//
//  * Batch collate with the reference's exact semantics
//    (`/root/reference/dataset.py:40-55`): input = [BOS]+tokens padded with
//    EOS, target = tokens+[EOS] padded with IGNORE, positions = arange.
//
// Exposed as a C ABI for ctypes (no pybind11 in this image).

#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

// ---------- GPT-2 bytes->unicode alphabet ----------
// Printable bytes map to themselves; the rest map to 256+n in order.
// (Mirrors openai/gpt-2 encoder.py bytes_to_unicode.)
void bytes_to_unicode(uint32_t out[256]) {
    std::vector<int> bs;
    for (int b = '!'; b <= '~'; ++b) bs.push_back(b);
    for (int b = 0xA1; b <= 0xAC; ++b) bs.push_back(b);
    for (int b = 0xAE; b <= 0xFF; ++b) bs.push_back(b);
    std::vector<bool> present(256, false);
    for (int b : bs) present[b] = true;
    int n = 0;
    std::vector<uint32_t> cs(256);
    for (int b = 0; b < 256; ++b) {
        if (present[b]) { cs[b] = (uint32_t)b; }
        else { cs[b] = 256 + n; ++n; }
    }
    for (int b = 0; b < 256; ++b) out[b] = cs[b];
}

void append_utf8(std::string& s, uint32_t cp) {
    if (cp < 0x80) { s += (char)cp; }
    else if (cp < 0x800) {
        s += (char)(0xC0 | (cp >> 6));
        s += (char)(0x80 | (cp & 0x3F));
    } else if (cp < 0x10000) {
        s += (char)(0xE0 | (cp >> 12));
        s += (char)(0x80 | ((cp >> 6) & 0x3F));
        s += (char)(0x80 | (cp & 0x3F));
    } else {
        s += (char)(0xF0 | (cp >> 18));
        s += (char)(0x80 | ((cp >> 12) & 0x3F));
        s += (char)(0x80 | ((cp >> 6) & 0x3F));
        s += (char)(0x80 | (cp & 0x3F));
    }
}

// ---------- unicode classification (compact table) ----------
bool is_letter(uint32_t c) {
    if ((c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z')) return true;
    if (c < 0x80) return false;
    // Latin-1 letters (exclude x D7 / xF7 signs)
    if (c >= 0xC0 && c <= 0xFF && c != 0xD7 && c != 0xF7) return true;
    if (c == 0xAA || c == 0xB5 || c == 0xBA) return true;
    if (c >= 0x100 && c <= 0x2AF) return true;   // Latin extended A/B, IPA
    if (c >= 0x370 && c <= 0x3FF && c != 0x37E) return true;   // Greek
    if (c >= 0x400 && c <= 0x52F) return true;   // Cyrillic (+supplement)
    if (c >= 0x531 && c <= 0x58F) return true;   // Armenian
    if (c >= 0x5D0 && c <= 0x5EA) return true;   // Hebrew
    if (c >= 0x620 && c <= 0x64A) return true;   // Arabic letters
    if (c >= 0x4E00 && c <= 0x9FFF) return true; // CJK unified
    if (c >= 0x3040 && c <= 0x30FF && c != 0x3097 && c != 0x3098) return true; // kana
    if (c >= 0xAC00 && c <= 0xD7A3) return true; // Hangul syllables
    return false;
}

bool is_number(uint32_t c) {
    if (c >= '0' && c <= '9') return true;
    if (c == 0xB2 || c == 0xB3 || c == 0xB9) return true;  // ^2 ^3 ^1
    if (c == 0xBC || c == 0xBD || c == 0xBE) return true;  // 1/4 1/2 3/4
    if (c >= 0x660 && c <= 0x669) return true;   // Arabic-Indic digits
    if (c >= 0x966 && c <= 0x96F) return true;   // Devanagari digits
    return false;
}

bool is_space(uint32_t c) {
    return c == ' ' || c == '\t' || c == '\n' || c == '\r' || c == '\f' ||
           c == '\v' || c == 0x85 || c == 0xA0 ||
           (c >= 0x2000 && c <= 0x200A) || c == 0x1680 || c == 0x2028 ||
           c == 0x2029 || c == 0x202F || c == 0x205F || c == 0x3000;
}

// decode UTF-8 at i, advance i; invalid bytes yield the byte value itself
uint32_t next_cp(const std::string& s, size_t& i) {
    unsigned char c = s[i];
    if (c < 0x80) { ++i; return c; }
    uint32_t cp; int extra;
    if ((c >> 5) == 0x6) { cp = c & 0x1F; extra = 1; }
    else if ((c >> 4) == 0xE) { cp = c & 0x0F; extra = 2; }
    else if ((c >> 3) == 0x1E) { cp = c & 0x07; extra = 3; }
    else { ++i; return c; }
    if (i + extra >= s.size()) { ++i; return c; }
    for (int k = 1; k <= extra; ++k) {
        unsigned char cc = s[i + k];
        if ((cc >> 6) != 0x2) { ++i; return c; }
        cp = (cp << 6) | (cc & 0x3F);
    }
    i += extra + 1;
    return cp;
}

struct CP { uint32_t cp; size_t byte_off, byte_len; };

// ---------- the GPT-2 split regex, hand-compiled ----------
//   's|'t|'re|'ve|'m|'ll|'d | ?\p{L}+ | ?\p{N}+ | ?[^\s\p{L}\p{N}]+
//   | \s+(?!\S) | \s+
std::vector<std::pair<size_t, size_t>> gpt2_split(const std::string& text) {
    std::vector<CP> cps;
    size_t i = 0;
    while (i < text.size()) {
        size_t st = i;
        uint32_t cp = next_cp(text, i);
        cps.push_back({cp, st, i - st});
    }
    std::vector<std::pair<size_t, size_t>> out;  // byte ranges
    size_t n = cps.size(), p = 0;
    auto emit = [&](size_t a, size_t b) {  // [a, b) in cp indices
        size_t lo = cps[a].byte_off;
        size_t hi = cps[b - 1].byte_off + cps[b - 1].byte_len;
        out.emplace_back(lo, hi - lo);
    };
    while (p < n) {
        // contractions: '(s|t|m|d) and '(re|ve|ll)
        if (cps[p].cp == '\'' && p + 1 < n) {
            uint32_t a = cps[p + 1].cp;
            uint32_t b = (p + 2 < n) ? cps[p + 2].cp : 0;
            if (a == 's' || a == 't' || a == 'm' || a == 'd') {
                emit(p, p + 2); p += 2; continue;
            }
            if ((a == 'r' && b == 'e') || (a == 'v' && b == 'e') ||
                (a == 'l' && b == 'l')) {
                emit(p, p + 3); p += 3; continue;
            }
        }
        // ` ?\p{L}+` / ` ?\p{N}+` / ` ?[^\s L N]+`
        size_t q = p;
        bool led_space = (cps[q].cp == ' ');
        size_t body = led_space ? q + 1 : q;
        if (body < n) {
            uint32_t c0 = cps[body].cp;
            if (is_letter(c0)) {
                size_t e = body;
                while (e < n && is_letter(cps[e].cp)) ++e;
                emit(p, e); p = e; continue;
            }
            if (is_number(c0)) {
                size_t e = body;
                while (e < n && is_number(cps[e].cp)) ++e;
                emit(p, e); p = e; continue;
            }
            if (!is_space(c0)) {
                size_t e = body;
                while (e < n && !is_space(cps[e].cp) && !is_letter(cps[e].cp)
                       && !is_number(cps[e].cp)) ++e;
                emit(p, e); p = e; continue;
            }
        }
        // whitespace: \s+(?!\S) else \s+ — a run of spaces followed by a
        // non-space keeps its LAST space for the next token
        size_t e = p;
        while (e < n && is_space(cps[e].cp)) ++e;
        if (e > p) {
            size_t stop = e;
            if (e < n && e - p > 1) stop = e - 1;       // leave one for next
            else if (e < n && e - p == 1) {              // single space: glue
                // the ` ?` of the following class consumes it (handled above
                // when led_space), so only reachable when next is space-led
                // handled; fall through emitting the single space
            }
            if (stop > p) { emit(p, stop); p = stop; continue; }
        }
        // single leftover space directly before a word: handled by led_space
        // above next iteration; emit it alone only if nothing else matched
        emit(p, p + 1);
        ++p;
    }
    return out;
}

// ---------- BPE ----------
struct Tok {
    std::unordered_map<std::string, int> vocab;
    std::unordered_map<std::string, int> ranks;  // "l\x01r" -> rank
    uint32_t byte_map[256];
    int unk_id;
    std::unordered_map<std::string, std::vector<int>> cache;
};

std::string pair_key(const std::string& l, const std::string& r) {
    return l + '\x01' + r;
}

void bpe_word(Tok* t, const std::string& mapped,
              std::vector<int>& out) {
    // split mapped (utf-8 of byte-alphabet chars) into single-cp symbols
    std::vector<std::string> sym;
    size_t i = 0;
    while (i < mapped.size()) {
        size_t st = i;
        next_cp(mapped, i);
        sym.emplace_back(mapped.substr(st, i - st));
    }
    while (sym.size() > 1) {
        int best = INT32_MAX, bi = -1;
        for (size_t k = 0; k + 1 < sym.size(); ++k) {
            auto it = t->ranks.find(pair_key(sym[k], sym[k + 1]));
            if (it != t->ranks.end() && it->second < best) {
                best = it->second; bi = (int)k;
            }
        }
        if (bi < 0) break;
        // merge every occurrence of that pair, left to right
        const std::string l = sym[bi], r = sym[bi + 1];
        std::vector<std::string> ns;
        for (size_t k = 0; k < sym.size();) {
            if (k + 1 < sym.size() && sym[k] == l && sym[k + 1] == r) {
                ns.push_back(l + r); k += 2;
            } else { ns.push_back(sym[k]); ++k; }
        }
        sym.swap(ns);
    }
    for (auto& s : sym) {
        auto it = t->vocab.find(s);
        if (it != t->vocab.end()) out.push_back(it->second);
        // symbol outside the trained vocab (e.g. a byte-char the training
        // corpus never contained): HF BPE emits the UNK token per symbol
        else if (t->unk_id >= 0) out.push_back(t->unk_id);
    }
}

}  // namespace

extern "C" {

void* tok_create(const char** tokens, const int32_t* ids, int32_t vocab_n,
                 const char** merge_l, const char** merge_r,
                 int32_t merge_n, int32_t unk_id) {
    Tok* t = new Tok();
    t->unk_id = unk_id;
    for (int32_t i = 0; i < vocab_n; ++i) t->vocab[tokens[i]] = ids[i];
    for (int32_t i = 0; i < merge_n; ++i)
        t->ranks[pair_key(merge_l[i], merge_r[i])] = i;
    bytes_to_unicode(t->byte_map);
    return t;
}

void tok_free(void* p) { delete (Tok*)p; }

// Returns the TOTAL id count for the text (which may exceed max_out; only
// the first max_out ids are written — the caller grows its buffer and
// retries on overflow). `text_len` is an explicit byte count so embedded
// NULs survive. add_prefix_space semantics of the shipped tokenizer.json
// are applied here.
int32_t tok_encode(void* p, const char* text_c, int32_t text_len,
                   int32_t add_prefix_space, int32_t* out, int32_t max_out) {
    Tok* t = (Tok*)p;
    std::string text(text_c, (size_t)text_len);
    if (add_prefix_space && !text.empty() && text[0] != ' ')
        text = " " + text;
    int32_t n = 0;
    for (auto [off, len] : gpt2_split(text)) {
        std::string piece = text.substr(off, len);
        auto cit = t->cache.find(piece);
        const std::vector<int>* ids;
        std::vector<int> tmp;
        if (cit != t->cache.end()) {
            ids = &cit->second;
        } else {
            std::string mapped;
            for (unsigned char c : piece) append_utf8(mapped, t->byte_map[c]);
            bpe_word(t, mapped, tmp);
            if (t->cache.size() < (1u << 20)) {
                ids = &(t->cache[piece] = tmp);
            } else {
                ids = &tmp;
            }
        }
        for (int id : *ids) {
            if (n < max_out) out[n] = id;
            ++n;  // keep counting so the caller learns the required size
        }
    }
    return n;
}

// Reference collate semantics (`/root/reference/dataset.py:40-55`):
//   input_ids[i]  = [BOS] + toks, padded to width with EOS
//   target_ids[i] = toks + [EOS], padded to width with IGNORE
//   position_ids  = arange(width) per row
// `flat` holds the batch's token ids back to back; `lens[i]` each row's count.
void collate_batch(const int32_t* flat, const int32_t* lens, int32_t batch,
                   int32_t width, int32_t bos, int32_t eos, int32_t ignore,
                   int32_t* input_ids, int32_t* target_ids,
                   int32_t* position_ids) {
    int64_t off = 0;
    for (int32_t i = 0; i < batch; ++i) {
        int32_t L = lens[i];
        // Defensive clamp: a row longer than width-1 must not write past the
        // row (callers validate width >= max(len)+1, but an unchecked width
        // would otherwise be a heap overflow, not a wrong answer).
        int32_t Lc = L < width - 1 ? L : width - 1;
        int32_t* in = input_ids + (int64_t)i * width;
        int32_t* tg = target_ids + (int64_t)i * width;
        int32_t* ps = position_ids + (int64_t)i * width;
        in[0] = bos;
        for (int32_t j = 0; j < Lc; ++j) {
            in[j + 1] = flat[off + j];
            tg[j] = flat[off + j];
        }
        for (int32_t j = Lc + 1; j < width; ++j) in[j] = eos;
        tg[Lc] = eos;
        for (int32_t j = Lc + 1; j < width; ++j) tg[j] = ignore;
        for (int32_t j = 0; j < width; ++j) ps[j] = j;
        off += L;
    }
}

// Indexed collate over a PACKED corpus: `packed` holds every sequence of the
// dataset back to back, `offsets[i]..offsets[i+1]` delimiting sequence i
// (offsets has n_seq+1 entries). `idxs` selects the batch's rows in order.
// Each row is truncated to min(len, cap) tokens first — the same
// maxlen-1 truncation TokenDataset.__getitem__ applies — then collated with
// the reference semantics above. One call replaces the per-batch Python
// gather + flatten + collate, so a prefetch thread spends its time in this
// GIL-released loop instead of the interpreter.
void collate_indexed(const int32_t* packed, const int64_t* offsets,
                     const int32_t* idxs, int32_t batch, int32_t cap,
                     int32_t width, int32_t bos, int32_t eos, int32_t ignore,
                     int32_t* input_ids, int32_t* target_ids,
                     int32_t* position_ids) {
    for (int32_t i = 0; i < batch; ++i) {
        int64_t st = offsets[idxs[i]];
        int64_t L64 = offsets[idxs[i] + 1] - st;
        int32_t L = L64 > cap ? cap : (int32_t)L64;     // maxlen-1 truncation
        int32_t Lc = L < width - 1 ? L : width - 1;     // defensive clamp
        const int32_t* src = packed + st;
        int32_t* in = input_ids + (int64_t)i * width;
        int32_t* tg = target_ids + (int64_t)i * width;
        int32_t* ps = position_ids + (int64_t)i * width;
        in[0] = bos;
        for (int32_t j = 0; j < Lc; ++j) {
            in[j + 1] = src[j];
            tg[j] = src[j];
        }
        for (int32_t j = Lc + 1; j < width; ++j) in[j] = eos;
        tg[Lc] = eos;
        for (int32_t j = Lc + 1; j < width; ++j) tg[j] = ignore;
        for (int32_t j = 0; j < width; ++j) ps[j] = j;
    }
}

}  // extern "C"
