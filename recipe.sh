#!/usr/bin/env bash
# End-to-end recipe: download -> preprocess -> tokenizer -> tokenize ->
# train (TP matrix) -> eval (TP matrix).
#
# TPU-native equivalent of /root/reference/recipe.sh (9 idempotent steps,
# recipe.sh:11-125). Differences: no CUDA_VISIBLE_DEVICES/port juggling —
# one process per host drives all chips via the ('dp','tp') mesh; the TP
# matrix is a loop; set TP_SIZES / DP_SIZE to match your slice (e.g.
# TP_SIZES="1 2 4 8" on a v4-8). Steps are skipped when their output exists,
# like the reference.
set -euo pipefail

WORK=${WORK:-./work}
VOCAB_SIZE=${VOCAB_SIZE:-1024}          # reference recipe.sh:6
TP_SIZES=${TP_SIZES:-"1"}
DP_SIZE=${DP_SIZE:-1}
MAX_STEPS=${MAX_STEPS:-20000}
BATCH_SIZE=${BATCH_SIZE:-32}
SAVE_INTERVAL=${SAVE_INTERVAL:-1000}
LOG_INTERVAL=${LOG_INTERVAL:-100}
FINEWEB_URL=${FINEWEB_URL:-"https://huggingface.co/datasets/HuggingFaceFW/fineweb/resolve/main/sample/10BT/000_00000.parquet"}

mkdir -p "$WORK"

# Step 0: static contract check (ISSUE 11) — the graftcheck sweep + trace
# contracts must be clean before burning accelerator time on a run whose
# programs violate the priced comm schedule or silently drop a donation.
echo "== Step 0: graftcheck static contracts"
python scripts/graftcheck.py --json "$WORK/graftcheck.json"

PARQUET="$WORK/fineweb.parquet"
TEXTS="$WORK/texts.json"
TOKENIZER="$WORK/tokenizer/tokenizer.json"
TOKENS="$WORK/tokens.json"

# Step 1: download a FineWeb shard (reference recipe.sh:13-19). With no
# network egress, fall back to the in-image docstring corpus
# (scripts/make_image_corpus.py) — same filter/split/schema, so every later
# step is identical.
if [ -f "$TEXTS" ]; then
    echo "== Step 1: $TEXTS exists, skipping download"
elif [ ! -f "$PARQUET" ]; then
    echo "== Step 1: downloading FineWeb shard"
    if ! curl -fL --max-time 300 "$FINEWEB_URL" -o "$PARQUET"; then
        echo "   download failed (no egress?) — harvesting the in-image corpus instead"
        rm -f "$PARQUET"
        python scripts/make_image_corpus.py "$TEXTS" \
            --root "$(python -c 'import numpy, os; print(os.path.dirname(os.path.dirname(numpy.__file__)))')"
    fi
else
    echo "== Step 1: $PARQUET exists, skipping"
fi

# Step 2: preprocess parquet -> text JSON (reference recipe.sh:22-29)
if [ ! -f "$TEXTS" ]; then
    echo "== Step 2: preprocessing"
    python -m distributed_pytorch_from_scratch_tpu.data.preprocess -i "$PARQUET" -o "$TEXTS"
else
    echo "== Step 2: $TEXTS exists, skipping"
fi

# Step 3: train BPE tokenizer (reference recipe.sh:32-39)
if [ ! -f "$TOKENIZER" ]; then
    echo "== Step 3: training tokenizer (vocab $VOCAB_SIZE)"
    python -m distributed_pytorch_from_scratch_tpu.data.tokenizer train \
        -d "$TEXTS" -v "$VOCAB_SIZE" -o "$TOKENIZER"
else
    echo "== Step 3: $TOKENIZER exists, skipping"
fi

# Step 4: pre-tokenize (reference recipe.sh:41-48)
if [ ! -f "$TOKENS" ]; then
    echo "== Step 4: pre-tokenizing"
    python -m distributed_pytorch_from_scratch_tpu.data.tokenizer encode \
        -i "$TEXTS" -o "$TOKENS" -t "$TOKENIZER"
else
    echo "== Step 4: $TOKENS exists, skipping"
fi

# Steps 5..: train + eval per TP size (reference recipe.sh:51-125)
for TP in $TP_SIZES; do
    CKPT="$WORK/checkpoints_tp${TP}"
    if [ ! -d "$CKPT" ] || [ -z "$(ls -A "$CKPT" 2>/dev/null | grep -v logs || true)" ]; then
        echo "== Train: TP=$TP DP=$DP_SIZE"
        python -m distributed_pytorch_from_scratch_tpu.train \
            --tp_size "$TP" --dp_size "$DP_SIZE" \
            --data_path "$TOKENS" --save_dir "$CKPT" \
            --batch_size "$BATCH_SIZE" --max_steps "$MAX_STEPS" \
            --save_interval "$SAVE_INTERVAL" --log_interval "$LOG_INTERVAL" --bf16
    else
        echo "== Train TP=$TP: checkpoints exist, skipping"
    fi
    echo "== Eval: TP=$TP"
    python -m distributed_pytorch_from_scratch_tpu.evaluate \
        --tp_size "$TP" --ckpt_dir "$CKPT" \
        --data_path "$TOKENS" --tokenizer_path "$TOKENIZER"
done

# Final step (obs v6): stamp the work dir with its RunCard so this recipe
# run is indexable/diffable like any bench session (ISSUE 17). Best-effort:
# a forensics hiccup must not fail a completed recipe.
echo "== RunCard: $WORK/run_card.json"
python scripts/obs_diff.py --card "$WORK" > "$WORK/run_card.json" \
    || echo "== RunCard emission failed (non-fatal)"
echo "== recipe complete"
