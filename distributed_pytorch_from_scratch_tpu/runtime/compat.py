"""Shims for older jax releases — imported before anything else in the
package (`__init__.py`).

The codebase targets the current jax API surface (`jax.shard_map`,
`jax.typeof`, `jax.sharding.AxisType`); some deployment images pin an older
jax (0.4.x) where those names live elsewhere or do not exist. Each shim is
applied only when the attribute is missing, so on a current jax this module
is a no-op. Centralised here instead of per-call-site guards so the rest of
the code reads as plain current-jax.
"""

from __future__ import annotations

import jax

# The dotted names this module guarantees exist (the "shimmed surface").
# KEEP THIS A PURE LITERAL: analysis/lints_source.py reads it out of this
# file's AST (never importing jax) to drive the compat-bypass lint — a
# call site using one of these names from a module that never loads the
# shim breaks on 0.4.x images. Extend this tuple whenever a new shim is
# added below.
SHIMMED_SURFACE = (
    "jax.shard_map",
    "jax.typeof",
    "jax.lax.axis_size",
    "jax.lax.pvary",
)

if not hasattr(jax, "shard_map"):
    from jax.experimental.shard_map import shard_map as _shard_map

    def _compat_shard_map(f=None, *, mesh, in_specs, out_specs, **kw):
        # the modern kwarg is check_vma; the experimental one was check_rep
        if "check_vma" in kw:
            kw.setdefault("check_rep", kw.pop("check_vma"))
        # The old rep-checker is a static debugging aid with no rules for
        # primitives current code uses freely inside shard_map (`while`
        # loops, live-gated `cond` branches, and their transposes — the
        # transpose-time bails cannot even be caught at the call layer).
        # Its own error message recommends check_rep=False; values and
        # gradients are identical without it, only the efficient-psum-
        # transpose rewrite and the static check are lost. Default it off
        # on legacy jax; explicit caller values still win.
        kw.setdefault("check_rep", False)
        if f is None:
            return lambda g: _compat_shard_map(g, mesh=mesh,
                                               in_specs=in_specs,
                                               out_specs=out_specs, **kw)
        return _shard_map(f, mesh, in_specs, out_specs, **kw)

    jax.shard_map = _compat_shard_map

if not hasattr(jax, "typeof"):
    # jax.typeof(x) -> aval; callers only getattr() optional fields (vma),
    # so the old get_aval is a faithful stand-in
    jax.typeof = jax.core.get_aval

if not hasattr(jax.lax, "axis_size"):
    # psum of the constant 1 constant-folds to the axis size without any
    # communication — the standard pre-axis_size spelling
    jax.lax.axis_size = lambda axis_name: jax.lax.psum(1, axis_name)

try:  # pallas-TPU params class was renamed TPUCompilerParams -> CompilerParams
    from jax.experimental.pallas import tpu as _pltpu

    if (not hasattr(_pltpu, "CompilerParams")
            and hasattr(_pltpu, "TPUCompilerParams")):
        _pltpu.CompilerParams = _pltpu.TPUCompilerParams
except Exception:  # pallas entirely absent: the kernels gate on import
    pass

if not hasattr(jax.lax, "pvary"):
    # pvary is a TYPE-level replicated->varying cast for the new vma
    # system; value-wise it is the identity, and old shard_map's check_rep
    # rewriter tracks replication itself — identity is the faithful shim
    jax.lax.pvary = lambda x, axis_name=None, *a, **k: x
