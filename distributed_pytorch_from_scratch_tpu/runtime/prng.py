"""Deterministic PRNG plumbing.

Replaces the reference's seed-everything + broadcast-from-rank-0 init dance:
`set_seed` (`/root/reference/utils.py:12-16`) seeds four RNGs identically on
every rank, then each parallel layer materialises a FULL weight, broadcasts
rank 0's copy and slices (`/root/reference/models/layers.py:78-87`). With an
explicit JAX PRNG key the whole dance collapses — every host derives the same
init from the same key, and `NamedSharding` does the slicing. The *property*
the reference's tests assert (a shard equals the slice of one full init) holds
by construction.
"""

from __future__ import annotations

from typing import Iterator

import jax


def root_key(seed: int) -> jax.Array:
    return jax.random.key(seed)


def fold(key: jax.Array, name: str) -> jax.Array:
    """Derive a named subkey. Stable: depends only on (key, name)."""
    # Fold in a stable hash of the name (Python's hash() is salted per
    # process, which would break cross-host determinism).
    h = 0
    for ch in name.encode():
        h = (h * 131 + ch) % (2**31 - 1)
    return jax.random.fold_in(key, h)


def split_iter(key: jax.Array) -> Iterator[jax.Array]:
    while True:
        key, sub = jax.random.split(key)
        yield sub
