"""Device-mesh runtime: the TPU-native replacement for the reference's
process-group machinery.

The reference binds one OS process per GPU via `mp.spawn`
(`/root/reference/train.py:151`), rendezvouses over TCP
(`/root/reference/utils.py:19-24`) and keeps a module-global
`ProcessGroupManager` singleton with the TP topology
(`/root/reference/process_manager.py:8-25`). On TPU one process drives all
local chips, topology is a `jax.sharding.Mesh` with named axes, and
collectives are XLA ops over ICI — so this module is mostly a thin, typed
factory plus multi-host init.

Axis names: 'dp' (data parallel) and 'tp' (tensor parallel). The reference
only has 'tp' (`process_manager.py:13` asserts tp_size == world_size); the
2-D mesh is the BASELINE.json config-5 extension.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config import MeshConfig

DP_AXIS = "dp"
PP_AXIS = "pp"
CP_AXIS = "cp"
EP_AXIS = "ep"
TP_AXIS = "tp"
AXIS_NAMES = (DP_AXIS, PP_AXIS, CP_AXIS, EP_AXIS, TP_AXIS)


def make_mesh(cfg: MeshConfig, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build the ('dp', 'pp', 'cp', 'ep', 'tp') mesh.

    Replaces `init_pgm` (`/root/reference/process_manager.py:23-25`): where the
    reference carved a 1-D `torch.arange(world).view(tp_size)` grid into one
    NCCL group (`process_manager.py:16-17`), here the mesh itself is the
    topology and XLA lowers named-axis collectives onto ICI rings.

    The 'tp' axis is innermost (fastest-varying over devices) so TP
    collectives — the per-layer latency-critical ops, see SURVEY §3.1 —
    ride neighbouring ICI links. 'ep' (MoE all-to-all, twice per MoE layer)
    and 'cp' (ring-attention KV hops, once per ring step) sit between;
    'pp' (one activation ppermute per microbatch per stage boundary) and
    'dp' (one gradient all-reduce per step) are outermost.
    """
    if devices is None:
        devices = jax.devices()
    n = cfg.world_size
    if n > len(devices):
        raise ValueError(
            f"Mesh {cfg.dp}x{cfg.pp}x{cfg.cp}x{cfg.ep}x{cfg.tp} needs {n} "
            f"devices but only {len(devices)} are visible"
        )
    grid = np.asarray(devices[:n]).reshape(cfg.dp, cfg.pp, cfg.cp, cfg.ep,
                                           cfg.tp)
    # axis_types landed after jax 0.4.x; Auto is that default anyway, so on
    # older releases plain Mesh(devices, names) is the same mesh
    if hasattr(jax.sharding, "AxisType"):
        return Mesh(grid, AXIS_NAMES,
                    axis_types=(jax.sharding.AxisType.Auto,) * len(AXIS_NAMES))
    return Mesh(grid, AXIS_NAMES)


def single_device_mesh() -> Mesh:
    """1x1 mesh: the TP=1 degenerate case (the reference's de-facto 'vanilla'
    path, where every comm op no-ops — `/root/reference/models/comm_ops.py:13-14`)."""
    return make_mesh(MeshConfig(dp=1, tp=1))


def tp_mesh(tp: int) -> Mesh:
    return make_mesh(MeshConfig(dp=1, tp=tp))


def mesh_shape(mesh: Mesh) -> MeshConfig:
    return MeshConfig(dp=mesh.shape[DP_AXIS], tp=mesh.shape[TP_AXIS],
                      cp=mesh.shape.get(CP_AXIS, 1),
                      ep=mesh.shape.get(EP_AXIS, 1),
                      pp=mesh.shape.get(PP_AXIS, 1))


def named(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))


def batch_feeder(mesh: Mesh):
    """Host-batch -> device-array function for (b, t)-shaped (or leading-
    stacked) token batches, multi-host-aware.

    Single process: `jnp.asarray` (jit reshards per the step's in-specs).
    Multi-process: a host-local full batch cannot be passed to a jit whose
    shardings span non-addressable devices, so the global array is
    assembled via `jax.make_array_from_callback` — every process holds the
    identical (same-seed) host batch and contributes the shards it owns.
    The leading dims beyond (b, t) (steps_per_dispatch / grad-accum
    stacking) stay unsharded, matching the jnp.asarray path."""
    import jax.numpy as jnp
    if jax.process_count() == 1:
        return jnp.asarray

    def feed(x):
        spec = P(*([None] * (x.ndim - 2)), (DP_AXIS, EP_AXIS), CP_AXIS)
        return jax.make_array_from_callback(
            x.shape, NamedSharding(mesh, spec), lambda idx: x[idx])

    return feed


def process_info() -> "tuple[int, int]":
    """(process_index, process_count) — safe to call before (or without)
    `init_multihost`: backendless failures degrade to a single-process view.
    Shared by MetricsWriter (per-process file tagging) and the obs layer
    (trace pid, watchdog messages)."""
    try:
        return jax.process_index(), jax.process_count()
    except Exception:
        return 0, 1


def init_multihost(coordinator: Optional[str] = None,
                   num_processes: Optional[int] = None,
                   process_id: Optional[int] = None) -> None:
    """Multi-host (DCN) initialisation.

    The reference's analogue is `dist.init_process_group('nccl', 'env://')`
    (`/root/reference/utils.py:23`). For a single host this is a no-op: one
    process sees all local chips. Across hosts, `jax.distributed.initialize`
    wires the DCN rendezvous; afterwards `jax.devices()` spans the slice and
    the same mesh code works unchanged.
    """
    if coordinator is None and "COORDINATOR_ADDRESS" in os.environ:
        coordinator = os.environ["COORDINATOR_ADDRESS"]
    if coordinator is None:
        return  # single host
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
