"""graftcheck report: one versioned JSON document + the human rendering.

Follows obs/schema.py's discipline: a `schema_version` stamp, a required-
field contract consumers can key on, and validation that fails loudly
instead of silently dropping sections. `scripts/summarize_run.py` renders a
"graftcheck" section from this document when one is present in a run dir.
Stdlib-only (see rules.py).
"""

from __future__ import annotations

import json
import time
from typing import Dict, List, Optional

from .rules import GRAFTCHECK_SCHEMA_VERSION, RULES, Violation

#: fields a consumer may key on (presence contract, obs/schema.py style)
REPORT_REQUIRED = ("schema_version", "tool", "ok", "violations",
                   "files_scanned", "rules", "contracts")


def build_report(violations: List[Violation], files_scanned: int,
                 contracts: Optional[List[dict]] = None,
                 duration_s: Optional[float] = None) -> dict:
    """The versioned JSON document. `contracts` is layer 2's result list
    (each: {name, ok, detail, program?}); None means the trace layer was
    skipped (--no-trace), which is recorded distinctly from "ran clean"."""
    contracts = contracts if contracts is not None else []
    failed = [c for c in contracts if not c.get("ok")]
    return {
        "schema_version": GRAFTCHECK_SCHEMA_VERSION,
        "tool": "graftcheck",
        "wall_time": time.time(),
        "duration_s": round(duration_s, 3) if duration_s else None,
        "ok": not violations and not failed,
        "files_scanned": files_scanned,
        "rules": {rid: {"summary": r.summary} for rid, r in RULES.items()},
        "violations": [v.asdict() for v in violations],
        "violation_counts": _counts(violations),
        "contracts": contracts,
    }


def _counts(violations: List[Violation]) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for v in violations:
        out[v.rule] = out.get(v.rule, 0) + 1
    return out


def validate_report(doc: dict) -> List[str]:
    """Problems with a parsed report (obs/schema.validate_record style):
    missing required fields, a version newer than this reader."""
    problems = []
    if not isinstance(doc, dict):
        return ["report is not a JSON object"]
    for field in REPORT_REQUIRED:
        if field not in doc:
            problems.append(f"graftcheck report missing field {field!r}")
    v = doc.get("schema_version")
    if isinstance(v, int) and v > GRAFTCHECK_SCHEMA_VERSION:
        problems.append(
            f"graftcheck report schema_version {v} is NEWER than this "
            f"reader ({GRAFTCHECK_SCHEMA_VERSION}) — update the consumer")
    return problems


def format_report(doc: dict, verbose: bool = False) -> str:
    """Human text: violations grouped by rule, then the contract table."""
    lines = []
    vios = doc.get("violations", [])
    contracts = doc.get("contracts", [])
    for v in vios:
        lines.append(f"{v['path']}:{v['line']}: [{v['rule']}] "
                     f"{v['message']}")
    if vios:
        by = doc.get("violation_counts", {})
        lines.append("")
        lines.append("violations by rule: "
                     + ", ".join(f"{k} x{n}" for k, n in sorted(by.items())))
    for c in contracts:
        mark = "ok " if c.get("ok") else "FAIL"
        prog = f" [{c['program']}]" if c.get("program") else ""
        if c.get("ok") and not verbose:
            lines.append(f"  [{mark}] {c['name']}{prog}")
        else:
            lines.append(f"  [{mark}] {c['name']}{prog}: "
                         f"{c.get('detail', '')}")
    n_fail = sum(1 for c in contracts if not c.get("ok"))
    status = "clean" if doc.get("ok") else "VIOLATIONS"
    lines.append(
        f"graftcheck: {status} — {len(vios)} lint violation(s) over "
        f"{doc.get('files_scanned', 0)} file(s), "
        f"{len(contracts) - n_fail}/{len(contracts)} trace contract(s) ok"
        + (f" in {doc['duration_s']}s" if doc.get("duration_s") else ""))
    return "\n".join(lines)


def write_report(doc: dict, path: str) -> None:
    with open(path, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
