"""Canonical-program builders for the graftcheck trace contracts (layer 2).

Each builder lowers + compiles ONE of the programs whose collective
schedule `obs/attribution.py` prices, on the virtual-CPU test mesh, at a
tiny model shape (the schedule depends on parallelism topology, not
parameter count). The result is a `Program` record carrying both text
forms plus the donation bookkeeping `contracts.py` asserts over:

* train step at zero ∈ {0,1,2,3} × wire ∈ {f32, int8} on dp2 x tp2 + SP
  (the ZeRO ladder's canonical mesh, tests/test_zero.py's shape);
* the paged decode step, a prefill chunk, and the speculative K+1 verify
  dispatch on tp2 (serving's canonical programs, engine-built so the
  contract covers what production actually compiles).

jax is imported lazily: importing this module costs nothing, and layer 1
never triggers it. Builders are cached — the CLI and several contracts
share one compile.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, Optional, Tuple


@dataclasses.dataclass
class Program:
    name: str
    lowered_text: str
    compiled_text: str
    mesh: object                      # jax Mesh (axis classification)
    donated_leaves: int               # leaves of the donated argnums
    donated_flat_start: int           # first flat input index donated
    donated_flat_stop: int            # one past the last
    config: Dict                      # kwargs for expected_collectives


def _tiny_model_cfg(maxlen: int = 32):
    from ..config import ModelConfig
    return ModelConfig(attn_dim=32, ffn_dim=64, num_heads=8, num_layers=2,
                       vocab_size=96, maxlen=maxlen)


def _batch(key, batch=8, t=16, vocab=96):
    import jax
    import jax.numpy as jnp
    k1, k2 = jax.random.split(key)
    ids = jax.random.randint(k1, (batch, t), 0, vocab)
    tgt = jax.random.randint(k2, (batch, t), 0, vocab)
    pos = jnp.tile(jnp.arange(t)[None, :], (batch, 1))
    return ids, tgt, pos


def _donation_span(args, donate_argnums) -> Tuple[int, int, int]:
    """(leaves, flat_start, flat_stop) for contiguous donated argnums —
    the flat input indices the compiled input_output_alias map refers to."""
    import jax
    donate = sorted(donate_argnums)
    assert donate == list(range(donate[0], donate[-1] + 1)), donate
    start = sum(len(jax.tree.leaves(args[i])) for i in range(donate[0]))
    n = sum(len(jax.tree.leaves(args[i])) for i in donate)
    return n, start, start + n


@functools.lru_cache(maxsize=16)
def train_step_program(zero_stage: int = 1, wire: str = "f32",
                       dp: int = 2, tp: int = 2) -> Program:
    """Lower+compile one train step at the given ZeRO stage and DP wire
    dtype on a dp x tp + SP mesh. wire='int8' implies the bucketed reducer
    (the stage-0/1/2 int8 path; stage 3 REFUSES int8 — callers assert that
    refusal separately via `train_step_refuses`)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..config import MeshConfig, OptimizerConfig
    from ..models.transformer import Transformer
    from ..runtime.mesh import make_mesh
    from ..training.optim import AdamState, init_adam_state
    from ..training.train_step import build_train_step
    from ..training.zero import zero1_moment_shardings, zero3_shardings

    cfg = _tiny_model_cfg()
    ocfg = OptimizerConfig(lr=1e-3, warmup_steps=5, max_steps=50)
    mesh = make_mesh(MeshConfig(dp=dp, tp=tp))
    model = Transformer(cfg, tp_size=tp, sequence_parallel=(tp > 1),
                        remat="dots")
    kw: Dict = dict(zero=zero_stage)
    bucketed = wire == "int8" or zero_stage >= 2
    if wire == "int8":
        kw.update(dp_reduce_bucket_mb=25.0, dp_reduce_dtype=jnp.int8)
    elif zero_stage >= 2:
        kw.update(dp_reduce_bucket_mb=25.0)
    step = build_train_step(model, mesh, ocfg, **kw)

    if zero_stage >= 3:
        param_sh = zero3_shardings(model, mesh)
        moment_sh = param_sh
    else:
        param_sh = model.shardings(mesh)
        moment_sh = (zero1_moment_shardings(model, mesh)
                     if zero_stage >= 1 else param_sh)
    params = jax.device_put(model.init(jax.random.key(0)), param_sh)
    scalar = NamedSharding(mesh, P())
    opt = jax.device_put(init_adam_state(params),
                         AdamState(step=scalar, mu=moment_sh, nu=moment_sh))
    ids, tgt, pos = _batch(jax.random.key(1), vocab=cfg.vocab_size)
    args = (params, opt, ids, tgt, pos)
    lowered = step.lower(*args)
    compiled = lowered.compile()
    leaves, start, stop = _donation_span(args, (0, 1))
    return Program(
        name=f"train_step_zero{zero_stage}_{wire}",
        lowered_text=lowered.as_text(),
        compiled_text=compiled.as_text(),
        mesh=mesh,
        donated_leaves=leaves,
        donated_flat_start=start,
        donated_flat_stop=stop,
        config=dict(tp=tp, sp=tp > 1, tp_overlap="off", dp=dp,
                    dp_bucket_mb=25.0 if bucketed else 0.0,
                    dp_reduce_dtype=wire if wire != "f32" else "f32",
                    zero_stage=zero_stage))


def train_step_refuses(zero_stage: int, wire: str,
                       dp: int = 2, tp: int = 2) -> Optional[str]:
    """The error message a refused (stage, wire) combination raises at
    build time, or None if the build is accepted. The loud-refusal
    contract: zero-3 + int8 must refuse (the compressed wire would
    silently not apply), never fall back."""
    try:
        train_step_program(zero_stage, wire, dp, tp)
    except ValueError as e:
        # lru_cache never caches a raising call, so the refusal is
        # re-evaluated (and re-raised) on every probe — nothing to evict
        return str(e)
    return None


@functools.lru_cache(maxsize=8)
def _paged_engine(tp: int = 2, speculative: bool = False,
                  paged_attn: str = "gather", cp: int = 1):
    import jax

    from ..config import MeshConfig
    from ..models.transformer import Transformer
    from ..runtime.mesh import make_mesh
    from ..serving.engine import PagedEngine

    cfg = _tiny_model_cfg(maxlen=64)
    mesh = make_mesh(MeshConfig(dp=1, cp=cp, tp=tp))
    model = Transformer(cfg, tp_size=tp, cp_size=cp)
    params = jax.device_put(model.init(jax.random.key(7)),
                            model.shardings(mesh))
    # the pallas variant lowers through the Pallas interpreter on the
    # CPU contract mesh (the engines' explicit opt-in), so the kernel
    # path's wire and donation facts are checkable chip-free
    kw = dict(paged_attn_impl=paged_attn,
              paged_attn_interpret=paged_attn == "pallas")
    if speculative:
        from ..serving.speculative import SpeculativeEngine
        # the drafter stays cp=1 by contract (its pool replicates over
        # the cp axis) — only the TARGET's pages shard
        dmodel = Transformer(cfg, tp_size=tp)
        dparams = jax.device_put(dmodel.init(jax.random.key(9)),
                                 dmodel.shardings(mesh))
        return SpeculativeEngine(model, mesh, params, dmodel, dparams,
                                 num_slots=2, buf_len=32, eos_id=1,
                                 speculate_k=2, page_size=8,
                                 prefill_chunk=4, **kw)
    return PagedEngine(model, mesh, params, num_slots=2, buf_len=32,
                       eos_id=1, page_size=8, prefill_chunk=4, **kw)


def _pool_bytes_per_rank(eng) -> int:
    """One cp rank's slab of the KV pool in bytes — the scale the
    cp-no-page-gather canary thresholds against."""
    import jax
    total = sum(x.nbytes
                for x in jax.tree.leaves((eng.pool.ks, eng.pool.vs)))
    return total // max(1, eng.pool.cp)


def _engine_step_args(eng):
    import jax.numpy as jnp
    return (eng._params_in, eng.pool.ks, eng.pool.vs,
            jnp.asarray(eng._tokens), jnp.asarray(eng._pos),
            jnp.asarray(eng._seeds), jnp.asarray(eng._tbl))


def _finish(name, eng, fn, args, donate_argnums, config) -> Program:
    lowered = fn.lower(*args)
    compiled = lowered.compile()
    leaves, start, stop = _donation_span(args, donate_argnums)
    return Program(name=name, lowered_text=lowered.as_text(),
                   compiled_text=compiled.as_text(), mesh=eng.mesh,
                   donated_leaves=leaves, donated_flat_start=start,
                   donated_flat_stop=stop, config=config)


@functools.lru_cache(maxsize=8)
def paged_decode_program(tp: int = 2, paged_attn: str = "gather",
                         cp: int = 1) -> Program:
    """The paged decode step exactly as PagedEngine compiles it (donated
    KV pool halves, per-row cursors over the page table). `paged_attn`
    selects the attend impl — the 'pallas' variant must satisfy the SAME
    collective schedule (the kernel changes HBM traffic, never the wire).
    `cp` > 1 shards the page pool over the cp axis (ISSUE 18): the config
    carries `pool_bytes_per_rank` so the page-locality canary can
    threshold against the slab size."""
    eng = _paged_engine(tp, paged_attn=paged_attn, cp=cp)
    cfg = dict(serving=True, tp=tp, dp=1, cp=cp, kind="decode")
    if cp > 1:
        cfg["pool_bytes_per_rank"] = _pool_bytes_per_rank(eng)
    suffix = "" if paged_attn == "gather" else f"_{paged_attn}"
    suffix += f"_cp{cp}" if cp > 1 else ""
    return _finish(f"paged_decode_tp{tp}{suffix}", eng, eng._step_fn,
                   _engine_step_args(eng), (1, 2), cfg)


@functools.lru_cache(maxsize=8)
def prefill_chunk_program(tp: int = 2, cw: int = 4,
                          paged_attn: str = "gather",
                          cp: int = 1) -> Program:
    """One chunked-prefill dispatch (width cw) from the paged engine. At
    cp > 1 the dispatch rings the query chunk around the cp axis (cw must
    divide by cp, as the engine guarantees)."""
    import jax.numpy as jnp
    eng = _paged_engine(tp, paged_attn=paged_attn, cp=cp)
    fn = eng._build_chunk(cw)
    n = eng.num_slots
    args = (eng._params_in, eng.pool.ks, eng.pool.vs,
            jnp.zeros((n, cw), jnp.int32), jnp.zeros((n,), jnp.int32),
            jnp.zeros((n,), jnp.int32), jnp.asarray(eng._tbl),
            jnp.zeros((n, cw), jnp.int32), jnp.zeros((n, cw), jnp.int32),
            jnp.asarray(eng._seeds))
    cfg = dict(serving=True, tp=tp, dp=1, cp=cp, kind="prefill_chunk")
    if cp > 1:
        cfg["pool_bytes_per_rank"] = _pool_bytes_per_rank(eng)
    suffix = "" if paged_attn == "gather" else f"_{paged_attn}"
    suffix += f"_cp{cp}" if cp > 1 else ""
    return _finish(f"prefill_chunk_tp{tp}_w{cw}{suffix}", eng, fn, args,
                   (1, 2), cfg)


@functools.lru_cache(maxsize=8)
def speculative_verify_program(tp: int = 2, k: int = 2,
                               paged_attn: str = "gather",
                               cp: int = 1) -> Program:
    """The speculative engine's K+1 verify dispatch (target scores k+1
    positions through the page table in one program). At cp > 1 the
    verify window pads to a cp multiple and rides the prefill ring
    (target pages cp-sharded, drafter cp=1 by contract)."""
    import jax.numpy as jnp
    eng = _paged_engine(tp, speculative=True, paged_attn=paged_attn,
                        cp=cp)
    fn = eng._verify_fn
    n = eng.num_slots
    w = k + 1
    # greedy verify signature (speculative.py's round loop): params, pool
    # halves, pending tokens, the k drafts, cursors, window lengths, page
    # table, per-position dest page/offset, seeds. The dest vectors span
    # the engine's (cp-padded) verify width.
    vw = getattr(eng, "_vw", w)
    args = (eng._params_in, eng.pool.ks, eng.pool.vs,
            jnp.zeros((n,), jnp.int32),             # pending token
            jnp.zeros((n, k), jnp.int32),           # drafted tokens
            jnp.zeros((n,), jnp.int32),             # pos
            jnp.ones((n,), jnp.int32),              # qlen
            jnp.asarray(eng._tbl),
            jnp.zeros((n, vw), jnp.int32), jnp.zeros((n, vw), jnp.int32),
            jnp.asarray(eng._seeds))
    cfg = dict(serving=True, tp=tp, dp=1, cp=cp, kind="spec_verify")
    if cp > 1:
        cfg["pool_bytes_per_rank"] = _pool_bytes_per_rank(eng)
    suffix = "" if paged_attn == "gather" else f"_{paged_attn}"
    suffix += f"_cp{cp}" if cp > 1 else ""
    return _finish(f"spec_verify_tp{tp}_k{k}{suffix}", eng, fn, args,
                   (1, 2), cfg)


@functools.lru_cache(maxsize=4)
def reshard_program(dp: int = 2, tp: int = 2) -> Program:
    """The live-mesh redistribution pass reshard/ lowers when source and
    target layouts coexist on one device set: an identity jit from the
    ZeRO-3 training layout (params dp-sharded leaf-wise) onto the
    serving layout (dp-replicated, tp kept). XLA lowers this to one dp
    all-gather PER LEAF — the fragment-wise schedule reshard/plan.py
    plans — and the config carries the planner's own numbers for the
    same leaf set (`plan_gather_leaves`, `max_leaf_bytes`) so
    `check_reshard_fragmentwise` can pin lowered reality against the
    planned schedule: same gather count, no payload beyond one leaf. A
    whole-tree gather (the host path's forbidden materialisation,
    transplanted to devices) would collapse the count and blow the
    payload bound."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    from ..config import MeshConfig
    from ..models.transformer import Transformer
    from ..reshard import make_layout
    from ..reshard.plan import plan_reshard
    from ..runtime.mesh import make_mesh
    from ..training.checkpoint import _flatten
    from ..training.zero import zero3_shardings

    cfg = _tiny_model_cfg()
    mesh = make_mesh(MeshConfig(dp=dp, tp=tp))
    model = Transformer(cfg, tp_size=tp, sequence_parallel=(tp > 1),
                        remat="dots")
    params = jax.device_put(model.init(jax.random.key(3)),
                            zero3_shardings(model, mesh))
    dst_sh = jax.tree.map(lambda s: NamedSharding(mesh, s), model.specs(),
                          is_leaf=lambda x: isinstance(x, PartitionSpec))
    fn = jax.jit(lambda t: t, out_shardings=dst_sh)
    lowered = fn.lower(params)
    compiled = lowered.compile()
    # the planner's schedule for the SAME leaf set: src = stamped zero-3
    # layout, dst = the serving layout (zero 0, same specs, same mesh)
    flat = _flatten(params, "param")
    shapes = {k: tuple(v.shape) for k, v in flat.items()}
    items = {k: v.dtype.itemsize for k, v in flat.items()}
    specs = model.canonical_specs()
    plan = plan_reshard(sorted(flat), shapes, items,
                        make_layout(mesh, specs, zero_stage=3),
                        make_layout(mesh, specs, zero_stage=0))
    gathers = sum(1 for lp in plan.leaves.values() if lp.op == "gather")
    return Program(
        name=f"reshard_dp{dp}tp{tp}_zero3_to_serving",
        lowered_text=lowered.as_text(),
        compiled_text=compiled.as_text(),
        mesh=mesh, donated_leaves=0,
        donated_flat_start=0, donated_flat_stop=0,
        config=dict(reshard=True, plan_gather_leaves=gathers,
                    max_leaf_bytes=plan.summary()["max_leaf_bytes"]))


def clear_caches() -> None:
    for fn in (train_step_program, _paged_engine, paged_decode_program,
               prefill_chunk_program, speculative_verify_program,
               reshard_program):
        fn.cache_clear()
