"""graftcheck core: rule registry, violations, pragmas, and the file runner.

Layer 1 of the static checker (ISSUE 11). Everything in this module — and
in the lint modules it drives (`lints_source.py`, `lints_traced.py`) — is
STDLIB-ONLY: no jax, no package imports. The rules must be runnable on an
image where jax is broken or absent (the exact situation `runtime/compat.py`
exists for), and from a standalone `scripts/graftcheck.py` invocation that
never pays the jax import. Layer 2 (the trace contracts in `contracts.py`)
is the only part that imports jax, and only lazily.

Every rule is the static form of a bug this repo actually shipped or
narrowly caught — the catalog (with the historical incident per rule) lives
in docs/ANALYSIS.md. Suppression is per-line via an inline pragma:

    x = legacy_call()  # graftcheck: disable=use-after-donate

or for a whole file (first 10 lines):

    # graftcheck: disable-file=unused-import
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Callable, Dict, List, Optional, Sequence

#: bump when the report's field contract changes incompatibly
#: (obs/schema.py-style versioning; consumers check before rendering)
GRAFTCHECK_SCHEMA_VERSION = 1


@dataclasses.dataclass
class Rule:
    id: str                 # kebab-case, the pragma/CLI name
    summary: str            # one line: what it catches
    history: str            # the historical bug it would have caught


@dataclasses.dataclass
class Violation:
    rule: str
    path: str
    line: int
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def asdict(self) -> dict:
        return dataclasses.asdict(self)


RULES: Dict[str, Rule] = {}
_CHECKERS: List[Callable] = []


def rule(id: str, summary: str, history: str):
    """Register a rule id (decorating the checker that emits it). A checker
    may own several rule ids; registration is what the report's rule
    catalog and the pragma validator enumerate."""
    RULES[id] = Rule(id, summary, history)

    def deco(fn):
        if fn not in _CHECKERS:
            _CHECKERS.append(fn)
        return fn

    return deco


# ----------------------------------------------------------------- pragmas --

_PRAGMA = re.compile(r"#\s*graftcheck:\s*disable=([\w,\-]+)")
_FILE_PRAGMA = re.compile(r"#\s*graftcheck:\s*disable-file=([\w,\-]+)")


def _line_pragmas(text: str) -> Dict[int, set]:
    """lineno -> set of rule ids disabled on that line ('all' wildcards)."""
    out: Dict[int, set] = {}
    for i, line in enumerate(text.splitlines(), 1):
        m = _PRAGMA.search(line)
        if m:
            out[i] = set(m.group(1).split(","))
    return out


def _file_pragmas(text: str) -> set:
    out: set = set()
    for line in text.splitlines()[:10]:
        m = _FILE_PRAGMA.search(line)
        if m:
            out |= set(m.group(1).split(","))
    return out


@dataclasses.dataclass
class SourceFile:
    """One parsed file handed to every checker (parse once, lint many)."""

    path: str               # as reported in violations (repo-relative)
    text: str
    tree: ast.AST
    in_package: bool        # under distributed_pytorch_from_scratch_tpu/
    _nodes: Optional[list] = None

    @property
    def nodes(self) -> list:
        """`ast.walk(tree)` materialised ONCE — every checker iterates
        this instead of re-walking (the sweep's hot path)."""
        if self._nodes is None:
            self._nodes = list(ast.walk(self.tree))
        return self._nodes


def parse_source(path: str, text: Optional[str] = None,
                 in_package: Optional[bool] = None) -> SourceFile:
    if text is None:
        with open(path, encoding="utf-8", errors="replace") as f:
            text = f.read()
    if in_package is None:
        in_package = "distributed_pytorch_from_scratch_tpu" in \
            path.replace(os.sep, "/")
    tree = ast.parse(text, filename=path)
    return SourceFile(path=path, text=text, tree=tree, in_package=in_package)


def lint_source(src: SourceFile,
                only: Optional[Sequence[str]] = None) -> List[Violation]:
    """Run every registered checker over one parsed file, honouring
    pragmas. `only` filters to a subset of rule ids (CLI --rules)."""
    disabled_file = _file_pragmas(src.text)
    disabled_line = _line_pragmas(src.text)
    out: List[Violation] = []
    for checker in _CHECKERS:
        for v in checker(src):
            if only and v.rule not in only:
                continue
            if v.rule in disabled_file or "all" in disabled_file:
                continue
            on_line = disabled_line.get(v.line, ())
            if v.rule in on_line or "all" in on_line:
                continue
            out.append(v)
    out.sort(key=lambda v: (v.path, v.line, v.rule))
    return out


def lint_file(path: str, text: Optional[str] = None,
              only: Optional[Sequence[str]] = None,
              report_path: Optional[str] = None) -> List[Violation]:
    """Lint one file; `report_path` overrides the path stamped into
    violations (fixture tests lint snippets under synthetic names)."""
    src = parse_source(path, text)
    if report_path is not None:
        src = dataclasses.replace(src, path=report_path)
    return lint_source(src, only=only)


# ------------------------------------------------------------- file walker --

#: directories never swept: caches, VCS, run artifacts, the deliberately-
#: violating fixture corpus, and data/work dirs recipe.sh creates
EXCLUDE_DIRS = {"__pycache__", ".git", "runs", "work", "serve_logs",
                "graftcheck_fixtures", "csrc", "tokenizer", ".claude"}


def iter_python_files(root: str) -> List[str]:
    out = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d not in EXCLUDE_DIRS)
        for name in sorted(filenames):
            if name.endswith(".py"):
                out.append(os.path.join(dirpath, name))
    return out


def lint_paths(paths: Sequence[str],
               only: Optional[Sequence[str]] = None,
               root: Optional[str] = None) -> "tuple[List[Violation], int]":
    """Lint files and/or directory trees. Returns (violations, files
    scanned). Paths in violations are relative to `root` when given (the
    stable form the JSON report and the clean-repo test pin)."""
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            files.extend(iter_python_files(p))
        else:
            files.append(p)
    out: List[Violation] = []
    for f in files:
        rel = os.path.relpath(f, root) if root else f
        try:
            src = parse_source(f)
        except SyntaxError as e:
            out.append(Violation("syntax-error", rel, e.lineno or 0,
                                 f"unparseable python: {e.msg}"))
            continue
        src = dataclasses.replace(src, path=rel)
        out.extend(lint_source(src, only=only))
    out.sort(key=lambda v: (v.path, v.line, v.rule))
    return out, len(files)


# the checkers self-register on import; import order fixes report order
from . import lints_source  # noqa: E402,F401  (registration side effect)
from . import lints_traced  # noqa: E402,F401  (registration side effect)
