"""Source-discipline lints: compat-API bypass, dead imports, unreachable
statements, and host-thread lock discipline (graftcheck layer 1).

Stdlib-only — see `rules.py`. The compat-bypass rule reads the shimmed
surface out of `runtime/compat.py`'s own source (an AST literal-eval of its
`SHIMMED_SURFACE` assignment), so the shim module stays the single owner of
that list without this module ever importing jax.
"""

from __future__ import annotations

import ast
import os
from typing import List, Optional, Set

from .rules import SourceFile, Violation, rule

PACKAGE = "distributed_pytorch_from_scratch_tpu"


def dotted(node: ast.AST) -> Optional[str]:
    """`jax.lax.psum` -> "jax.lax.psum" for Name/Attribute chains."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


# ------------------------------------------------------------ compat-bypass --

_FALLBACK_SURFACE = ("jax.shard_map", "jax.typeof", "jax.lax.axis_size",
                     "jax.lax.pvary")
_surface_cache: Optional[tuple] = None


def shimmed_surface() -> tuple:
    """The dotted names `runtime/compat.py` shims, read from its
    `SHIMMED_SURFACE` literal by AST (no import, no jax). Falls back to the
    names known at this rule's writing if the assignment ever goes missing
    — the lint degrading is better than the lint crashing."""
    global _surface_cache
    if _surface_cache is not None:
        return _surface_cache
    compat = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "runtime", "compat.py")
    surface = _FALLBACK_SURFACE
    try:
        tree = ast.parse(open(compat, encoding="utf-8").read())
        for node in ast.walk(tree):
            if (isinstance(node, ast.Assign)
                    and any(isinstance(t, ast.Name)
                            and t.id == "SHIMMED_SURFACE"
                            for t in node.targets)):
                surface = tuple(ast.literal_eval(node.value))
    except (OSError, SyntaxError, ValueError):
        pass
    _surface_cache = surface
    return surface


@rule("compat-bypass",
      "raw jax API use that bypasses the runtime/compat.py shim layer",
      "the 0.4.x image breakage PR 2's compat shims fixed: direct "
      "jax.experimental.shard_map imports and shimmed-surface calls from "
      "modules that never load the shim break on old-jax images")
def check_compat_bypass(src: SourceFile) -> List[Violation]:
    if src.path.replace(os.sep, "/").endswith("runtime/compat.py"):
        return []
    out: List[Violation] = []
    imports_package = False
    imports_jax = False
    for node in src.nodes:
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name.split(".")[0] == PACKAGE:
                    imports_package = True
                if a.name.split(".")[0] == "jax":
                    imports_jax = True
                if a.name.startswith("jax.experimental.shard_map"):
                    out.append(Violation(
                        "compat-bypass", src.path, node.lineno,
                        "import of jax.experimental.shard_map bypasses "
                        "runtime/compat.py — use jax.shard_map (the shim "
                        "guarantees it exists and defaults check_rep off "
                        "on legacy jax)"))
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod.split(".")[0] == PACKAGE or node.level:
                imports_package = True
            if mod.startswith("jax.experimental.shard_map") or (
                    mod == "jax.experimental"
                    and any(a.name == "shard_map" for a in node.names)):
                out.append(Violation(
                    "compat-bypass", src.path, node.lineno,
                    "import of jax.experimental.shard_map bypasses "
                    "runtime/compat.py — use jax.shard_map"))
    # shimmed-surface attribute uses are only safe when the compat shim is
    # guaranteed loaded first: package modules get it from the package
    # __init__; anything else must import the package (or the shim) itself
    if src.in_package or imports_package or not imports_jax:
        return out
    surface = set(shimmed_surface())
    for node in src.nodes:
        name = dotted(node) if isinstance(node, ast.Attribute) else None
        if name in surface:
            out.append(Violation(
                "compat-bypass", src.path, node.lineno,
                f"{name} is shimmed by runtime/compat.py but this module "
                f"never loads the shim (import the package, or "
                f"runtime.compat, before first jax use) — on a 0.4.x "
                f"image this call does not exist"))
    return out


# ------------------------------------------------------------ unused-import --

@rule("unused-import",
      "imported name never referenced in the module",
      "dead imports accumulated across PR 1-10 sweeps; each one is a "
      "startup cost and a false dependency edge the next refactor trips on")
def check_unused_import(src: SourceFile) -> List[Violation]:
    if os.path.basename(src.path) == "__init__.py":
        return []        # __init__ imports are the re-export surface
    imported: dict = {}  # local name -> (lineno, display)
    # honour the ecosystem convention for side-effect imports: a line
    # carrying `# noqa` (bare, or naming F401) is deliberate
    noqa_lines = set()
    for i, line in enumerate(src.text.splitlines(), 1):
        if "# noqa" in line:
            tail = line.split("# noqa", 1)[1]
            if not tail.strip().startswith(":") or "F401" in tail:
                noqa_lines.add(i)
    in_try: Set[int] = set()
    for node in src.nodes:
        if isinstance(node, ast.Try):
            for sub in ast.walk(node):
                in_try.add(id(sub))
    for node in src.nodes:
        if id(node) in in_try:
            continue     # compat-style gated imports are deliberate
        if isinstance(node, ast.Import):
            for a in node.names:
                local = a.asname or a.name.split(".")[0]
                imported[local] = (node.lineno, a.name)
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue
            for a in node.names:
                if a.name == "*":
                    continue
                local = a.asname or a.name
                imported[local] = (node.lineno, a.name)
    if not imported:
        return []
    used: Set[str] = set()
    for node in src.nodes:
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            d = dotted(node)
            if d:
                used.add(d.split(".")[0])
    # names in __all__ are exports, not uses-in-module, but keep them
    for node in src.nodes:
        if (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == "__all__"
                        for t in node.targets)):
            try:
                used |= set(ast.literal_eval(node.value))
            except ValueError:
                pass
    # string annotations ("Model") reference names invisibly to the walk
    for node in src.nodes:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            used |= {w for w in imported
                     if w in node.value and len(w) > 2}
    out = []
    for local, (lineno, display) in sorted(imported.items(),
                                           key=lambda kv: kv[1][0]):
        if lineno in noqa_lines:
            continue
        if local not in used and not local.startswith("_"):
            out.append(Violation(
                "unused-import", src.path, lineno,
                f"'{display}' imported but never used"))
    return out


# ---------------------------------------------------------- unreachable-code --

_TERMINAL = (ast.Return, ast.Raise, ast.Break, ast.Continue)


@rule("unreachable-code",
      "statements after an unconditional return/raise/break/continue",
      "dead branches left by the PR 5-9 engine refactors: unreachable "
      "code reads as load-bearing and rots silently")
def check_unreachable(src: SourceFile) -> List[Violation]:
    out = []
    for node in src.nodes:
        for field in ("body", "orelse", "finalbody"):
            stmts = getattr(node, field, None)
            if not isinstance(stmts, list):
                continue
            for i, stmt in enumerate(stmts[:-1]):
                if isinstance(stmt, _TERMINAL):
                    nxt = stmts[i + 1]
                    out.append(Violation(
                        "unreachable-code", src.path, nxt.lineno,
                        f"statement is unreachable (follows "
                        f"{type(stmt).__name__.lower()} on line "
                        f"{stmt.lineno})"))
                    break
    return out


# ------------------------------------------------------ profiler-discipline --

#: the one module allowed to start/stop jax.profiler traces: it owns the
#: window mechanics (ProfilerTrace / AnomalyProfiler / DutyCycleProfiler)
_PROFILER_OWNER = "training/metrics.py"
_PROFILER_CALLS = {"jax.profiler.start_trace", "jax.profiler.stop_trace"}


@rule("profiler-discipline",
      "jax.profiler.start_trace/stop_trace outside training/metrics.py",
      "the device profiler is one-capture-at-a-time: a scattered "
      "start/stop races the ProfilerTrace/AnomalyProfiler/"
      "DutyCycleProfiler window mechanics (training/metrics.py), so a "
      "stop fires mid-window and the capture truncates unreadably — the "
      "exact failure the obs-v4 measured plane cannot tolerate, since "
      "every capture now parses into a profile_attribution event")
def check_profiler_discipline(src: SourceFile) -> List[Violation]:
    if src.path.replace(os.sep, "/").endswith(_PROFILER_OWNER):
        return []
    out: List[Violation] = []
    for node in src.nodes:
        if isinstance(node, ast.Attribute):
            name = dotted(node)
            if name in _PROFILER_CALLS:
                out.append(Violation(
                    "profiler-discipline", src.path, node.lineno,
                    f"{name} outside training/metrics.py breaks the "
                    f"one-capture-at-a-time window mechanics — drive "
                    f"captures through ProfilerTrace / AnomalyProfiler / "
                    f"DutyCycleProfiler instead"))
        elif isinstance(node, ast.ImportFrom):
            if (node.module or "") == "jax.profiler" and any(
                    a.name in ("start_trace", "stop_trace")
                    for a in node.names):
                out.append(Violation(
                    "profiler-discipline", src.path, node.lineno,
                    "importing start_trace/stop_trace from jax.profiler "
                    "outside training/metrics.py — drive captures "
                    "through the ProfilerTrace owners"))
    return out


# ---------------------------------------------------- controller-discipline --

#: the control plane's owner modules: the advisor/controller INTERNALS may
#: touch actuation freely (they are the mechanism the rule protects)
_CONTROL_OWNERS = ("obs/control.py", "serving/controller.py")
_ACTUATION_CALLS = {"apply_decisions", "actuate"}
_SAFE_POINT_DECO = "control_safe_point"


def _deco_tail(d: ast.AST) -> str:
    """`@control_safe_point` / `@control.control_safe_point` -> the bare
    decorator name (calls unwrap to their func)."""
    if isinstance(d, ast.Call):
        d = d.func
    return (dotted(d) or "").split(".")[-1]


@rule("controller-discipline",
      "controller/advisor actuation outside a control_safe_point function",
      "the obs-v5 control plane mutates live engine knobs "
      "(pages_per_block, prefill chunk, speculation K); an actuation "
      "from an arbitrary call site lands mid-capture-window or inside a "
      "traced function, which tears the measurement the decision was "
      "based on — actuation is only legal at registered safe points "
      "(engine init boundaries, the host-side control tick, between "
      "duty-cycle capture windows)")
def check_controller_discipline(src: SourceFile) -> List[Violation]:
    path = src.path.replace(os.sep, "/")
    if any(path.endswith(owner) for owner in _CONTROL_OWNERS):
        return []
    # every node living inside a @control_safe_point function is blessed
    safe_ids: set = set()
    for node in src.nodes:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_deco_tail(d) == _SAFE_POINT_DECO
                   for d in node.decorator_list):
                for sub in ast.walk(node):
                    safe_ids.add(id(sub))
    out: List[Violation] = []
    for node in src.nodes:
        if not isinstance(node, ast.Call) or id(node) in safe_ids:
            continue
        f = node.func
        name = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None)
        if name in _ACTUATION_CALLS:
            out.append(Violation(
                "controller-discipline", src.path, node.lineno,
                f"{name}() outside a @control_safe_point function — "
                f"knob actuation from an arbitrary call site can land "
                f"mid-capture-window or inside a traced function; move "
                f"the call into a registered safe point (the engine's "
                f"control tick, a duty-profiler on_attribution hook, or "
                f"an init boundary)"))
    return out


# ----------------------------------------------- host-gather-in-reshard --

@rule("host-gather-in-reshard",
      "whole-tree host materialisation on a reshard path",
      "the reshard subsystem's (ISSUE 20) one law: leaves cross the host "
      "ONE AT A TIME, peak host bytes bounded by the largest single leaf "
      "— a 45M-param tree that fits sharded on 8 chips does not fit "
      "unsharded in one host buffer. A whole-tree jax.device_get or an "
      "eager dict(np.load(...)) on a reshard path is exactly the "
      "one-shot materialisation reshard/apply.py's streaming executors "
      "exist to eliminate")
def check_host_gather_in_reshard(src: SourceFile) -> List[Violation]:
    path = src.path.replace(os.sep, "/")
    if "/reshard/" in path or path.startswith("reshard/"):
        scoped = list(src.nodes)
    else:
        # outside the subsystem the rule guards functions that CLAIM to
        # reshard (serve_fleet's restart, train's elastic resume, bench)
        scoped, seen = [], set()
        for node in src.nodes:
            if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and "reshard" in node.name):
                for sub in ast.walk(node):
                    if id(sub) not in seen:
                        seen.add(id(sub))
                        scoped.append(sub)
    if not scoped:
        return []
    # device_get inside a Lambda is the streamed per-leaf idiom (a
    # jax.tree.map leaf callback) — the tree-at-once call is the hazard
    in_lambda = set()
    for node in scoped:
        if isinstance(node, ast.Lambda):
            for sub in ast.walk(node):
                in_lambda.add(id(sub))
    out: List[Violation] = []
    for node in scoped:
        if not isinstance(node, ast.Call):
            continue
        name = dotted(node.func) or ""
        if (name.split(".")[-1] == "device_get"
                and id(node) not in in_lambda):
            out.append(Violation(
                "host-gather-in-reshard", src.path, node.lineno,
                "whole-tree jax.device_get on a reshard path — stream "
                "leaves one at a time (a per-leaf tree.map callback, or "
                "reshard/apply.py's executors); peak host bytes must "
                "stay bounded by the largest single leaf"))
        if (isinstance(node.func, ast.Name) and node.func.id == "dict"
                and len(node.args) == 1
                and isinstance(node.args[0], ast.Call)):
            inner = dotted(node.args[0].func) or ""
            if (inner.split(".")[-1] == "load"
                    and inner.split(".")[0] in ("np", "numpy")):
                out.append(Violation(
                    "host-gather-in-reshard", src.path, node.lineno,
                    "dict(np.load(...)) materialises every shard member "
                    "at once on a reshard path — read members lazily "
                    "(NpzFile is lazy per key; reshard/apply.py streams "
                    "payload bytes member-by-member)"))
    return out


# ---------------------------------------------------------- lock-discipline --

_LOCK_CTORS = {"threading.Lock", "threading.RLock", "threading.Condition",
               "Lock", "RLock", "Condition"}
_MUTATORS = {"append", "appendleft", "extend", "pop", "popleft", "add",
             "remove", "discard", "insert", "clear", "update", "setdefault",
             "popitem"}


def _self_attr(node: ast.AST) -> Optional[str]:
    """self.<attr> -> attr (only depth-1: self.x, not self.x.y)."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _stmt_mutations(stmt, holding: bool, sink, lock_attrs):
    """Collect (attr, lineno, holding_lock) for every `self.<attr>`
    mutation under `stmt`, tracking `with self.<lock>:` context (only
    attrs in `lock_attrs` count as locks — `with self._span(...)` is a
    tracer, not a guard)."""
    if isinstance(stmt, (ast.Assign, ast.AugAssign)):
        targets = stmt.targets if isinstance(stmt, ast.Assign) else [
            stmt.target]
        for t in targets:
            attr = _self_attr(t)
            if attr is None and isinstance(t, ast.Subscript):
                attr = _self_attr(t.value)
            if attr is None and isinstance(t, ast.Tuple):
                for el in t.elts:
                    a = _self_attr(el)
                    if a:
                        sink.append((a, stmt.lineno, holding))
            if attr:
                sink.append((attr, stmt.lineno, holding))
    if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
        f = stmt.value.func
        if isinstance(f, ast.Attribute) and f.attr in _MUTATORS:
            attr = _self_attr(f.value)
            if attr:
                sink.append((attr, stmt.lineno, holding))
    # recurse into compound statements, preserving lock context
    inner = holding
    if isinstance(stmt, ast.With):
        for item in stmt.items:
            ctx = item.context_expr
            if _self_attr(ctx) in lock_attrs:
                inner = True
    for field in ("body", "orelse", "finalbody", "handlers"):
        sub = getattr(stmt, field, None)
        if isinstance(sub, list):
            for s in sub:
                if isinstance(s, ast.ExceptHandler):
                    for ss in s.body:
                        _stmt_mutations(ss, inner, sink, lock_attrs)
                else:
                    _stmt_mutations(s, inner, sink, lock_attrs)


@rule("lock-discipline",
      "attribute guarded by the class lock mutated without holding it",
      "the obs/flight + prefetch + ckpt-writer host threads share state "
      "with the main loop; an unlocked mutation is a torn dump / lost "
      "heartbeat under exactly the anomaly the recorder exists to capture")
def check_lock_discipline(src: SourceFile) -> List[Violation]:
    out = []
    for cls in src.nodes:
        if not isinstance(cls, ast.ClassDef):
            continue
        # does this class own a lock? (self._lock = threading.Lock() ...)
        lock_attrs = set()
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign) and isinstance(node.value,
                                                           ast.Call):
                ctor = dotted(node.value.func)
                if ctor in _LOCK_CTORS:
                    for t in node.targets:
                        a = _self_attr(t)
                        if a:
                            lock_attrs.add(a)
        if not lock_attrs:
            continue
        # first pass: which attrs are EVER mutated under the lock
        per_method: dict = {}
        for fn in cls.body:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            sink: list = []
            for stmt in fn.body:
                _stmt_mutations(stmt, False, sink, lock_attrs)
            per_method[fn.name] = sink
        guarded = {attr for sink in per_method.values()
                   for attr, _, locked in sink if locked}
        guarded -= lock_attrs
        if not guarded:
            continue
        # second pass: mutations of guarded attrs with the lock NOT held
        for name, sink in per_method.items():
            if name == "__init__":
                continue   # construction precedes sharing
            for attr, lineno, locked in sink:
                if attr in guarded and not locked:
                    out.append(Violation(
                        "lock-discipline", src.path, lineno,
                        f"self.{attr} is mutated under the class lock "
                        f"elsewhere but written here without holding it "
                        f"({cls.name}.{name}) — a host thread racing this "
                        f"write tears the shared state"))
    return out
