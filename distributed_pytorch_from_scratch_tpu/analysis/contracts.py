"""graftcheck layer 2: trace contracts over compiled programs.

Parses the optimized HLO of each canonical program (`programs.py`) into a
per-axis collective inventory — op kind, element dtype, payload bytes, and
the MESH AXIS each collective runs over (classified from replica_groups /
source_target_pairs against the mesh's device grid) — then asserts:

* the inventory matches `obs/attribution.expected_collectives` for the
  program's config (require/allow/forbid sets over (axis, op) pairs);
* int8-wire programs carry no wide-dtype payload on the dp axis beyond
  the scale sidecars (the "int8 silently falls back to f32" hazard);
* ZeRO-3 programs contain no dp-axis all-gather at all — the per-layer
  ring is collective-permute; a dp all-gather would be the whole-tree
  param gather the stage exists to eliminate;
* declared donations actually alias in the compiled executable (the
  input_output_alias map covers every donated leaf — a dtype/shape change
  that silently un-donates shows up here, not as a quiet 2x footprint);
* knobs that shouldn't recompile don't: lowering the same program from
  different host-side values must produce byte-identical HLO.

Pure text analysis over `Program` records — jax is only reached through
`programs.py`'s lazy builders.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, FrozenSet, List, Optional, Tuple

from ..obs.introspect import _DTYPE_BYTES

_COLL_RE = re.compile(
    r"=\s+(?P<shape>[^=\n]*?)\s*"
    r"(?P<op>all-reduce|all-gather|reduce-scatter|all-to-all|"
    r"collective-permute|collective-broadcast)(?P<start>-start)?"
    r"\((?P<rest>[^\n]*)")

_GROUPS_RE = re.compile(
    r"replica_groups=(\{\{.*?\}\}|\[[\d,]+\]<=\[[\d,]+\](?:T\([\d,]+\))?)")
_PAIRS_RE = re.compile(r"source_target_pairs=(\{\{.*?\}\})")
_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([\d,]*)\]")


def _parse_braced_groups(text: str) -> List[Tuple[int, ...]]:
    """'{{0,2},{1,3}}' -> [(0,2),(1,3)]"""
    return [tuple(int(x) for x in grp.split(",") if x != "")
            for grp in re.findall(r"\{([\d,]*)\}", text[1:-1])]


def _parse_iota_groups(text: str) -> List[Tuple[int, ...]]:
    """XLA's v2 format: '[G,S]<=[dims]T(perm)' — reshape(transpose(iota))."""
    m = re.match(r"\[([\d,]+)\]<=\[([\d,]+)\](?:T\(([\d,]+)\))?", text)
    shape = [int(x) for x in m.group(1).split(",")]
    src = [int(x) for x in m.group(2).split(",")]
    n = 1
    for d in src:
        n *= d
    ids = list(range(n))
    if m.group(3):
        perm = [int(x) for x in m.group(3).split(",")]
        # index math without numpy: transpose the src-shaped iota
        strides = [0] * len(src)
        acc = 1
        for i in range(len(src) - 1, -1, -1):
            strides[i] = acc
            acc *= src[i]
        dims = [src[p] for p in perm]
        out = []

        def rec(prefix):
            if len(prefix) == len(dims):
                flat = sum(prefix[i] * strides[perm[i]]
                           for i in range(len(dims)))
                out.append(flat)
                return
            for j in range(dims[len(prefix)]):
                rec(prefix + [j])

        rec([])
        ids = out
    g, s = shape[0], shape[1] if len(shape) > 1 else 1
    return [tuple(ids[i * s:(i + 1) * s]) for i in range(g)]


@dataclasses.dataclass
class Collective:
    op: str
    axis: str          # mesh axis name, 'all', 'mixed', or 'local'
    dtype: str         # widest member dtype ('f32', 's8', ...)
    bytes: int         # payload bytes (largest member for -start tuples)
    line: str


def _axis_groups(mesh) -> Dict[str, FrozenSet[FrozenSet[int]]]:
    """axis name -> the set of device-id groups a collective over exactly
    that axis uses (only axes of size > 1)."""
    import numpy as np
    ids = np.vectorize(lambda d: d.id)(mesh.devices)
    names = list(mesh.axis_names)
    out = {}
    for i, name in enumerate(names):
        if ids.shape[i] <= 1:
            continue
        moved = np.moveaxis(ids, i, -1).reshape(-1, ids.shape[i])
        out[name] = frozenset(frozenset(int(x) for x in row)
                              for row in moved)
    out["all"] = frozenset({frozenset(int(x) for x in ids.flatten())})
    return out


def _classify(groups: List[Tuple[int, ...]],
              axis_groups: Dict[str, FrozenSet[FrozenSet[int]]]) -> str:
    sizes = {len(g) for g in groups}
    if sizes <= {1}:
        return "local"      # singleton groups: no wire traffic at all
    gset = frozenset(frozenset(g) for g in groups if len(g) > 1)
    for name, ref in axis_groups.items():
        if gset <= ref:
            return name
    return "mixed"


def _classify_pairs(pairs: List[Tuple[int, ...]],
                    axis_groups: Dict[str, FrozenSet[FrozenSet[int]]]
                    ) -> str:
    """A permute's axis: every (src, dst) pair must sit inside one of the
    axis's groups."""
    for name, ref in axis_groups.items():
        if name == "all":
            continue
        if all(any({s, t} <= g for g in ref) for s, t in pairs):
            return name
    if all(any(set(p) <= g for g in axis_groups["all"]) for p in pairs):
        return "all"
    return "mixed"


def _shape_members(shape: str) -> List[Tuple[str, int]]:
    out = []
    for dtype, dims in _SHAPE_RE.findall(shape):
        size = _DTYPE_BYTES.get(dtype)
        if size is None:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        out.append((dtype, n * size))
    return out


def parse_collectives_by_axis(hlo_text: str, mesh) -> List[Collective]:
    ag = _axis_groups(mesh)
    out = []
    for m in _COLL_RE.finditer(hlo_text):
        rest = m.group("rest")
        gm = _GROUPS_RE.search(rest)
        pm = _PAIRS_RE.search(rest)
        if gm:
            text = gm.group(1)
            groups = (_parse_braced_groups(text) if text.startswith("{")
                      else _parse_iota_groups(text))
            axis = _classify(groups, ag)
        elif pm:
            pairs = _parse_braced_groups(pm.group(1))
            pairs = [p for p in pairs if len(p) == 2 and p[0] != p[1]]
            axis = _classify_pairs(pairs, ag) if pairs else "local"
        else:
            axis = "all"    # no groups attr = one group of every device
        members = _shape_members(m.group("shape"))
        if not members:
            continue
        # async -start forms carry (operands..., result, context) tuple
        # shapes; the largest member is the payload either way
        dtype, nbytes = max(members, key=lambda kv: kv[1])
        out.append(Collective(op=m.group("op"), axis=axis, dtype=dtype,
                              bytes=nbytes, line=m.group(0)[:160]))
    return out


def inventory(colls: List[Collective]) -> Dict[Tuple[str, str], Dict]:
    """(axis, op) -> {count, bytes, max_bytes, dtypes} — wire-carrying
    collectives only ('local' singleton groups move no bytes)."""
    out: Dict[Tuple[str, str], Dict] = {}
    for c in colls:
        if c.axis == "local":
            continue
        rec = out.setdefault((c.axis, c.op),
                             {"count": 0, "bytes": 0, "max_bytes": 0,
                              "dtypes": set()})
        rec["count"] += 1
        rec["bytes"] += c.bytes
        rec["max_bytes"] = max(rec["max_bytes"], c.bytes)
        rec["dtypes"].add(c.dtype)
    return out


def _result(name: str, ok: bool, detail: str,
            program: Optional[str] = None) -> dict:
    return {"name": name, "ok": bool(ok), "detail": detail,
            "program": program}


# ------------------------------------------------------------- contracts --

#: payloads at or below this are bookkeeping (loss scalars, quant scales,
#: cursor fields), not wire schedules — the inventory contract ignores
#: their dtype, the int8-width contract exempts them
SCALE_SIDECAR_BYTES = 256


def check_collective_inventory(prog, expected: Dict) -> dict:
    """Observed (axis, op) inventory vs the expected_collectives schedule:
    every REQUIRED pair present, nothing outside REQUIRED|ALLOWED, no
    FORBIDDEN pair, and per-pair wire dtypes within the declared set."""
    colls = parse_collectives_by_axis(prog.compiled_text, prog.mesh)
    inv = inventory(colls)
    problems = []
    req = {tuple(k): v for k, v in expected["require"].items()}
    allow = {tuple(k) for k in expected["allow"]}
    forbid = {tuple(k) for k in expected["forbid"]}
    for key, spec in req.items():
        if key not in inv:
            problems.append(f"missing required collective {key}: "
                            f"{spec.get('note', '')}")
            continue
        want = spec.get("dtypes")
        if want:
            got = {d for c in colls
                   if (c.axis, c.op) == key
                   and c.bytes > SCALE_SIDECAR_BYTES
                   for d in (c.dtype,)}
            extra = got - set(want)
            if extra:
                problems.append(
                    f"{key} carries {sorted(extra)} payloads; schedule "
                    f"prices {sorted(want)} ({spec.get('note', '')})")
    for key in inv:
        if key in forbid:
            problems.append(
                f"forbidden collective {key} present "
                f"({inv[key]['count']}x, {inv[key]['max_bytes']}B max): "
                f"{expected['forbid'][key]}")
        elif key not in req and key not in allow:
            if inv[key]["max_bytes"] > SCALE_SIDECAR_BYTES:
                problems.append(
                    f"unexpected collective {key} "
                    f"({inv[key]['count']}x, {inv[key]['max_bytes']}B "
                    f"max) — not in the priced schedule; either the "
                    f"program grew a wire the attribution doesn't price "
                    f"or expected_collectives needs updating WITH the "
                    f"pricing")
    detail = ("; ".join(problems) if problems else
              "inventory == priced schedule: " + ", ".join(
                  f"{a}/{o} x{v['count']}"
                  for (a, o), v in sorted(inv.items())))
    return _result("collective-inventory", not problems, detail, prog.name)


def check_no_wide_dp_wire(prog, axis: str = "dp",
                          allowed_ops: Tuple[str, ...] = ()) -> dict:
    """int8-wire contract: every collective on `axis` carrying more than
    the scale sidecar must be 8-bit. `allowed_ops` exempts ops the
    schedule prices as f32 by design (e.g. the ZeRO-2 param all-gather)."""
    colls = parse_collectives_by_axis(prog.compiled_text, prog.mesh)
    wide = [c for c in colls
            if c.axis == axis and c.op not in allowed_ops
            and c.bytes > SCALE_SIDECAR_BYTES
            and not c.dtype.endswith("8")]
    narrow = [c for c in colls
              if c.axis == axis and c.dtype.endswith("8")]
    if wide:
        worst = max(wide, key=lambda c: c.bytes)
        return _result(
            "int8-wire-width", False,
            f"{len(wide)} wide collective(s) on the {axis} axis — e.g. "
            f"{worst.op} {worst.dtype} {worst.bytes}B: the int8 wire "
            f"silently fell back", prog.name)
    if not narrow:
        return _result(
            "int8-wire-width", False,
            f"no 8-bit collective found on the {axis} axis at all — the "
            f"quantized ring never ran", prog.name)
    return _result(
        "int8-wire-width", True,
        f"{len(narrow)} s8 collective(s) on {axis}, widest non-sidecar "
        f"payload is 8-bit", prog.name)


def check_cp_no_page_gather(prog) -> dict:
    """cp-sharded paged serving (ISSUE 18): no cp-axis collective may
    carry a pool-slab-scale payload — page DATA stays rank-local by
    construction; the wire moves only the prefill query carry
    (collective-permute) and the small (out, lse) combine psums. A
    slab-scale cp gather would be the whole-pool materialisation the
    shard exists to eliminate — the ZeRO-3 whole-tree-gather rule,
    transplanted to pages. Threshold: half one rank's slab bytes
    (`pool_bytes_per_rank` from the program config)."""
    threshold = max(prog.config.get("pool_bytes_per_rank", 0) // 2,
                    SCALE_SIDECAR_BYTES)
    colls = parse_collectives_by_axis(prog.compiled_text, prog.mesh)
    cp_colls = [c for c in colls if c.axis == "cp"]
    big = [c for c in cp_colls if c.bytes >= threshold]
    if big:
        worst = max(big, key=lambda c: c.bytes)
        return _result(
            "cp-no-page-gather", False,
            f"{len(big)} slab-scale cp collective(s) — largest {worst.op} "
            f"{worst.dtype} {worst.bytes}B >= {threshold}B (half the "
            f"local pool slab): page data is crossing the cp wire instead "
            f"of staying rank-local", prog.name)
    return _result(
        "cp-no-page-gather", True,
        f"largest cp payload {max((c.bytes for c in cp_colls), default=0)}"
        f"B < {threshold}B (half the local pool slab): the cp wire "
        f"carries query-carry/combine traffic only "
        f"({len(cp_colls)} cp collective(s))", prog.name)


def check_zero3_no_whole_tree_gather(prog) -> dict:
    """ZeRO-3: no dp-axis all-gather at all — the per-layer ring is
    collective-permute inside the scan; a dp all-gather is the whole-tree
    param materialisation the stage exists to eliminate."""
    colls = parse_collectives_by_axis(prog.compiled_text, prog.mesh)
    bad = [c for c in colls if c.axis == "dp" and c.op == "all-gather"]
    rings = [c for c in colls
             if c.axis == "dp" and c.op == "collective-permute"]
    if bad:
        worst = max(bad, key=lambda c: c.bytes)
        return _result(
            "zero3-no-whole-tree-gather", False,
            f"{len(bad)} dp-axis all-gather(s) in a ZeRO-3 program "
            f"(largest {worst.bytes}B) — params are materialising "
            f"whole-tree instead of ringing per layer", prog.name)
    if not rings:
        return _result(
            "zero3-no-whole-tree-gather", False,
            "no dp-axis collective-permute found — the per-layer gather "
            "ring is missing entirely", prog.name)
    return _result(
        "zero3-no-whole-tree-gather", True,
        f"no dp all-gather; {len(rings)} dp ring permute(s) (the "
        f"per-layer gathers + their reduce-scatter transposes)", prog.name)


def check_reshard_fragmentwise(prog) -> dict:
    """Reshard redistribution (ISSUE 20): the lowered live-mesh reshard
    must move leaves FRAGMENT-WISE, matching the planner's schedule.
    Pins: (a) every wire-carrying collective is a dp all-gather (the
    per-leaf un-ZeRO gather — nothing else belongs on this wire); (b)
    the gather COUNT equals the planner's gather-leaf count (XLA fusing
    leaves into one whole-tree gather collapses the count); (c) no
    single payload exceeds one leaf's bytes — the device-side mirror of
    the streamed host path's peak-one-leaf bound."""
    colls = parse_collectives_by_axis(prog.compiled_text, prog.mesh)
    wire = [c for c in colls if c.axis != "local"]
    gathers = [c for c in wire if (c.axis, c.op) == ("dp", "all-gather")]
    alien = [c for c in wire if (c.axis, c.op) != ("dp", "all-gather")]
    want = int(prog.config["plan_gather_leaves"])
    cap = int(prog.config["max_leaf_bytes"])
    problems = []
    if alien:
        worst = max(alien, key=lambda c: c.bytes)
        problems.append(
            f"{len(alien)} collective(s) besides the per-leaf dp "
            f"all-gather (largest: {worst.op} on {worst.axis}, "
            f"{worst.bytes}B) — the redistribution wire must carry "
            f"nothing else")
    if len(gathers) != want:
        problems.append(
            f"{len(gathers)} dp all-gather(s) vs {want} gather leaves "
            f"in the planned schedule — the lowered pass no longer "
            f"matches reshard/plan.py fragment-wise")
    big = [c for c in gathers if c.bytes > cap]
    if big:
        worst = max(big, key=lambda c: c.bytes)
        problems.append(
            f"gather payload {worst.bytes}B exceeds the largest leaf "
            f"({cap}B) — leaves are fusing into a whole-tree gather")
    detail = ("; ".join(problems) if problems else
              f"{len(gathers)} per-leaf dp all-gather(s) == planned "
              f"gather leaves; largest payload "
              f"{max((c.bytes for c in gathers), default=0)}B <= one "
              f"leaf ({cap}B); no other wire collective")
    return _result("reshard-fragmentwise", not problems, detail,
                   prog.name)


_ALIAS_ENTRY = re.compile(r"\{[\d,\s]*\}:\s*\((\d+),")


def donated_param_indices(compiled_text: str) -> List[int]:
    """Flat parameter indices the compiled executable aliases in place."""
    m = re.search(r"input_output_alias=\{", compiled_text)
    if not m:
        return []
    # brace-match from the opening '{'
    i = m.end() - 1
    depth = 0
    for j in range(i, min(len(compiled_text), i + 200000)):
        ch = compiled_text[j]
        if ch == "{":
            depth += 1
        elif ch == "}":
            depth -= 1
            if depth == 0:
                body = compiled_text[i:j + 1]
                return sorted(int(x) for x in
                              _ALIAS_ENTRY.findall(body))
    return []


def check_donation_aliased(prog) -> dict:
    """Every donated leaf must appear in the executable's
    input_output_alias map — XLA silently DROPS a donation whose aval
    matches no output (the quiet 2x-footprint failure)."""
    aliased = set(donated_param_indices(prog.compiled_text))
    want = set(range(prog.donated_flat_start, prog.donated_flat_stop))
    missing = want - aliased
    if missing:
        return _result(
            "donation-aliased", False,
            f"{len(missing)}/{len(want)} donated input leaf(s) not "
            f"aliased in the executable (flat indices "
            f"{sorted(missing)[:8]}...) — the donation silently became "
            f"a copy", prog.name)
    return _result(
        "donation-aliased", True,
        f"all {len(want)} donated leaves alias outputs in-place "
        f"({len(aliased)} aliased inputs total)", prog.name)


def check_stable_lowering(name: str, texts: List[str]) -> dict:
    """Recompile-hazard probe: the same program lowered from different
    host-side values (same shapes/dtypes) must produce byte-identical
    StableHLO — a difference means a host value was baked in as a
    constant, and the serving loop would recompile per step."""
    distinct = len(set(texts))
    if distinct != 1:
        return _result(
            "recompile-hazard", False,
            f"{name}: {distinct} distinct lowerings from {len(texts)} "
            f"same-shaped argument sets — a host value is baked into the "
            f"program and will force recompiles", name)
    return _result(
        "recompile-hazard", True,
        f"{name}: 1 lowering across {len(texts)} same-shaped argument "
        f"sets", name)


# ------------------------------------------------------------ the runner --

#: Program.config keys that parameterise CONTRACTS (thresholds), not the
#: expected_collectives schedule — stripped before the schedule call
_NON_SCHEDULE_KEYS = ("pool_bytes_per_rank",)


def _expected(prog) -> Dict:
    from ..obs.attribution import expected_collectives
    return expected_collectives(**{k: v for k, v in prog.config.items()
                                   if k not in _NON_SCHEDULE_KEYS})


def run_trace_contracts(full: bool = False) -> List[dict]:
    """Build the canonical programs and run every contract. `full` adds
    the slower sweep (all zero stages x wires, spec verify, the pallas
    cp variants); the default set covers the acceptance contracts —
    including the cp=2 serving ring inventory + page-locality canary
    (ISSUE 18)."""
    from . import programs as P
    from ..obs.attribution import expected_collectives

    results: List[dict] = []

    # stage 0 rides in the DEFAULT set: its donation contract is the one
    # that caught the un-pinned out_shardings bug (train_step.py), so the
    # regression pin must run everywhere the default gate runs
    train_matrix = [(0, "f32"), (1, "f32"), (2, "int8"), (3, "f32")]
    if full:
        train_matrix = [(0, "f32"), (0, "int8"), (1, "f32"), (1, "int8"),
                        (2, "f32"), (2, "int8"), (3, "f32")]
    for stage, wire in train_matrix:
        prog = P.train_step_program(stage, wire)
        exp = expected_collectives(**prog.config)
        results.append(check_collective_inventory(prog, exp))
        results.append(check_donation_aliased(prog))
        if wire == "int8":
            allowed = ("all-gather",) if stage >= 1 else ()
            results.append(check_no_wide_dp_wire(prog,
                                                 allowed_ops=allowed))
        if stage == 3:
            results.append(check_zero3_no_whole_tree_gather(prog))

    # zero-3 must REFUSE a compressed wire, loudly, at build time
    msg = P.train_step_refuses(3, "int8")
    results.append(_result(
        "zero3-int8-refusal", msg is not None and "stage 2" in msg,
        msg or "zero stage 3 + int8 wire BUILT instead of refusing — "
               "the compressed wire silently does not apply",
        "train_step_zero3_int8"))

    # serving: paged decode donation + inventory-free checks, for BOTH
    # attend impls — the pallas kernel (ISSUE 14) must add NO collective
    # the priced schedule doesn't name, and its donation must survive
    for impl in ("gather", "pallas"):
        dec = P.paged_decode_program(paged_attn=impl)
        results.append(check_donation_aliased(dec))
        exp = expected_collectives(**dec.config)
        results.append(check_collective_inventory(dec, exp))
        # recompile probe: decode step lowered from different host states
        # (the pallas page walk reads the table through scalar prefetch —
        # a table VALUE baked into the kernel would recompile per step)
        results.append(check_stable_lowering(
            "paged_decode" + ("" if impl == "gather" else f"_{impl}"),
            _decode_lowerings(paged_attn=impl)))

    # cp-sharded serving (ISSUE 18) rides the DEFAULT set — the ring
    # inventory (decode combine psums; prefill ring permutes + reassembly)
    # and the page-locality canary are acceptance contracts
    for prog in (P.paged_decode_program(cp=2),
                 P.prefill_chunk_program(cp=2)):
        results.append(check_collective_inventory(prog, _expected(prog)))
        results.append(check_donation_aliased(prog))
        results.append(check_cp_no_page_gather(prog))

    # the reshard redistribution pass (ISSUE 20) rides the DEFAULT set:
    # the lowered live-mesh reshard must match reshard/plan.py's
    # fragment-wise schedule (per-leaf gathers, one-leaf payload bound)
    results.append(check_reshard_fragmentwise(P.reshard_program()))

    if full:
        for impl in ("gather", "pallas"):
            chunk = P.prefill_chunk_program(paged_attn=impl)
            results.append(check_donation_aliased(chunk))
            results.append(check_collective_inventory(
                chunk, expected_collectives(**chunk.config)))
            ver = P.speculative_verify_program(paged_attn=impl)
            results.append(check_donation_aliased(ver))
            results.append(check_collective_inventory(
                ver, expected_collectives(**ver.config)))
        # the pallas cp variants + the cp spec verify (target sharded,
        # drafter cp=1) must satisfy the same cp schedule and canary
        for prog in (P.paged_decode_program(paged_attn="pallas", cp=2),
                     P.prefill_chunk_program(paged_attn="pallas", cp=2),
                     P.speculative_verify_program(cp=2)):
            results.append(check_collective_inventory(prog,
                                                      _expected(prog)))
            results.append(check_donation_aliased(prog))
            results.append(check_cp_no_page_gather(prog))
    return results


def _decode_lowerings(paged_attn: str = "gather") -> List[str]:
    """The paged decode step lowered from 3 different host states (step
    index, cursor positions, table contents) — shapes identical."""
    import jax.numpy as jnp

    from . import programs as P
    eng = P._paged_engine(2, paged_attn=paged_attn)
    texts = []
    for bump in (0, 1, 3):
        tokens = jnp.asarray(eng._tokens) + bump
        pos = jnp.asarray(eng._pos) + bump
        tbl = jnp.asarray(eng._tbl)
        if bump:
            tbl = tbl.at[0, 0].set(bump % eng.pool.num_pages)
        lo = eng._step_fn.lower(eng._params_in, eng.pool.ks, eng.pool.vs,
                                tokens, pos, jnp.asarray(eng._seeds), tbl)
        texts.append(lo.as_text())
    return texts
