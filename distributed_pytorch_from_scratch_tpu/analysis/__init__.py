"""graftcheck — static contract checker for this repo (ISSUE 11).

Two layers:

* **Layer 1 — source lints** (`rules.py`, `lints_source.py`,
  `lints_traced.py`, `report.py`): pure-AST rules for this codebase's
  known failure classes — compat-shim bypass, use-after-donate, host
  calls inside traced code, PRNG key reuse, lock discipline, dead
  imports/unreachable code. Stdlib-only: importing these modules never
  imports jax, so `scripts/graftcheck.py` can sweep the repo on a box
  where jax is broken (the situation runtime/compat.py exists for).

* **Layer 2 — trace contracts** (`programs.py`, `contracts.py`): lower
  the canonical programs (train step across the ZeRO × wire matrix,
  paged decode, prefill chunk, speculative verify) on the CPU test mesh
  and assert invariants on the compiled HLO — the collective inventory
  matches what `obs/attribution.expected_collectives` prices, int8 wires
  carry no f32 dp-axis payloads, declared donations actually alias, and
  knobs that shouldn't recompile don't. These modules import jax lazily
  and only when asked.

This package deliberately avoids importing its own parent package at
module scope; layer 2 does so inside functions. That keeps layer 1 loadable
standalone (scripts/graftcheck.py loads it by path for the no-jax sweep).
"""

from .rules import (GRAFTCHECK_SCHEMA_VERSION, RULES, Violation, lint_file,
                    lint_paths)
from .report import build_report, format_report, validate_report

__all__ = ["GRAFTCHECK_SCHEMA_VERSION", "RULES", "Violation", "lint_file",
           "lint_paths", "build_report", "format_report", "validate_report"]
