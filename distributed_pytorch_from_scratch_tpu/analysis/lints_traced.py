"""Trace-hygiene lints: host calls inside traced code, use-after-donate,
and PRNG key reuse (graftcheck layer 1).

Stdlib-only — see `rules.py`. "Traced" is decided structurally, never by
running jax: a function is traced when it is (a) decorated with a jit/
shard_map/checkpoint-family decorator, (b) passed by name into a trace
entrypoint (`jax.jit(f, ...)`, `jax.lax.scan(body, ...)`, ...), or (c)
defined inside a traced function. Host-side effects inside such a function
run at TRACE time, not step time — `time.time()` timestamps the compile,
`np.random` freezes one draw into the program, `device_get` forces a sync
that defeats async dispatch.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .lints_source import dotted
from .rules import SourceFile, Violation, rule

# callables whose function-valued arguments become traced code
_TRACE_ENTRYPOINTS = {
    "jax.jit", "jit", "jax.pmap", "jax.shard_map", "shard_map",
    "jax.checkpoint", "jax.remat", "checkpoint", "remat",
    "jax.vmap", "jax.grad", "jax.value_and_grad", "jax.jacrev",
    "jax.jacfwd", "jax.linearize", "jax.vjp", "jax.jvp",
    "jax.eval_shape", "jax.make_jaxpr",
    "jax.lax.scan", "jax.lax.while_loop", "jax.lax.fori_loop",
    "jax.lax.cond", "jax.lax.switch", "jax.lax.map",
    "jax.lax.associative_scan", "jax.custom_vjp", "jax.custom_jvp",
    "lax.scan", "lax.while_loop", "lax.fori_loop", "lax.cond",
    "lax.switch", "lax.map",
}

_TRACE_DECORATORS = {
    "jax.jit", "jit", "jax.shard_map", "shard_map", "jax.checkpoint",
    "jax.remat", "jax.vmap", "jax.custom_vjp", "jax.custom_jvp",
    "jax.pmap",
}


def _called_name(call: ast.Call) -> Optional[str]:
    name = dotted(call.func)
    if name is not None:
        return name
    # functools.partial(jax.jit, ...) used as a decorator/entrypoint
    if isinstance(call.func, ast.Call):
        inner = dotted(call.func.func)
        if inner in ("functools.partial", "partial"):
            if call.func.args:
                return dotted(call.func.args[0])
    return None


def _traced_function_nodes(tree: ast.AST) -> List[ast.AST]:
    """FunctionDef/Lambda nodes whose bodies are traced (see module doc)."""
    traced_names: Set[str] = set()
    inline_fns: List[ast.AST] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _called_name(node)
        if name is None:
            continue
        target = name
        if isinstance(node.func, ast.Call):
            inner = dotted(node.func.func)
            if inner in ("functools.partial", "partial") and node.func.args:
                target = dotted(node.func.args[0]) or name
        if target not in _TRACE_ENTRYPOINTS:
            continue
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(arg, ast.Name):
                traced_names.add(arg.id)
            elif isinstance(arg, ast.Lambda):
                inline_fns.append(arg)
    out: List[ast.AST] = list(inline_fns)
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if node.name in traced_names:
            out.append(node)
            continue
        for deco in node.decorator_list:
            dname = None
            if isinstance(deco, ast.Call):
                dname = _called_name(deco)
            else:
                dname = dotted(deco)
            if dname in _TRACE_DECORATORS:
                out.append(node)
                break
    return out


def _module_imports(tree: ast.AST) -> Set[str]:
    """Top-level module names imported (un-aliased root names)."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out.add(a.asname or a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            for a in node.names:
                out.add(a.asname or a.name)
    return out


# one checker walks the traced bodies once and emits all three rule ids

@rule("host-sync-in-traced",
      "device_get / block_until_ready / .item() inside traced code",
      "the obs round-3 'lse timing' lie: a block_until_ready inside the "
      "jitted wrapper made the kernel look synchronous and the timing "
      "honest-looking but wrong (fixed by scripts/tpu_checks.py's shared "
      "jit wrapper, PR 3)")
@rule("host-time-in-traced",
      "time.* / datetime.now inside traced code",
      "a time.time() inside a jitted body stamps TRACE time into the "
      "program as a constant — the per-step 'timing' never changes again")
@rule("host-rng-in-traced",
      "numpy/stdlib RNG inside traced code",
      "np.random inside a traced function freezes ONE host draw into the "
      "compiled program: every step reuses it, silently — the class of "
      "bug the per-request fold_in schedule (PR 5/7) exists to prevent")
def check_host_calls_in_traced(src: SourceFile) -> List[Violation]:
    imports = _module_imports(src.tree)
    out: List[Violation] = []
    seen: Set[int] = set()
    for fn in _traced_function_nodes(src.tree):
        for node in ast.walk(fn):
            if id(node) in seen or not isinstance(node, ast.Call):
                continue
            seen.add(id(node))
            name = dotted(node.func) or ""
            if name in ("jax.device_get", "jax.block_until_ready"):
                out.append(Violation(
                    "host-sync-in-traced", src.path, node.lineno,
                    f"{name} inside traced code forces a host sync at "
                    f"trace time (and fails on tracers at step time)"))
            elif (isinstance(node.func, ast.Attribute)
                  and node.func.attr in ("block_until_ready", "item")):
                out.append(Violation(
                    "host-sync-in-traced", src.path, node.lineno,
                    f".{node.func.attr}() inside traced code — tracers "
                    f"have no device buffer to sync; this is host logic "
                    f"leaking into the program"))
            elif (name.startswith("time.") and "time" in imports) or \
                    name in ("datetime.now", "datetime.datetime.now"):
                out.append(Violation(
                    "host-time-in-traced", src.path, node.lineno,
                    f"{name}() inside traced code runs at TRACE time — "
                    f"the value is baked into the program as a constant"))
            elif ((name.startswith("np.random.")
                   or name.startswith("numpy.random."))
                  or (name.startswith("random.") and "random" in imports)):
                out.append(Violation(
                    "host-rng-in-traced", src.path, node.lineno,
                    f"{name}() is host RNG inside traced code — one draw "
                    f"is frozen into the compiled program; thread a "
                    f"jax.random key (fold_in per step) instead"))
    return out


# --------------------------------------------------------- use-after-donate --

def _donating_assigns(tree: ast.AST) -> Dict[str, Tuple[int, ...]]:
    """name (possibly dotted, e.g. 'self._step_fn') -> donate_argnums for
    assignments of the form `name = jax.jit(f, donate_argnums=...)` (the
    argnums must be a literal int/tuple to be tracked)."""
    out: Dict[str, Tuple[int, ...]] = {}
    for node in ast.walk(tree):
        if not isinstance(node, (ast.Assign, ast.Return)):
            continue
        value = node.value
        if not isinstance(value, ast.Call):
            continue
        if dotted(value.func) not in ("jax.jit", "jit"):
            continue
        argnums = None
        for kw in value.keywords:
            if kw.arg == "donate_argnums":
                try:
                    lit = ast.literal_eval(kw.value)
                except ValueError:
                    lit = None
                if isinstance(lit, int):
                    argnums = (lit,)
                elif isinstance(lit, (tuple, list)):
                    argnums = tuple(int(i) for i in lit)
        if argnums is None:
            continue
        if isinstance(node, ast.Assign):
            for t in node.targets:
                name = dotted(t)
                if name:
                    out[name] = argnums
    return out


def _scan_donation_scope(stmts, donating, out, src, dead=None):
    """Linear statement walk: after `f(a, b)` where f donates argnum i,
    a Load of the donated name before its next Store is a use-after-donate.
    Loop bodies are walked twice so a donation in iteration N flags the
    un-rebound read in iteration N+1."""
    dead = dead if dead is not None else {}

    def names_loaded(node):
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load):
                yield sub.id, sub.lineno
            elif isinstance(sub, ast.Attribute) and isinstance(
                    sub.ctx, ast.Load):
                d = dotted(sub)
                if d:
                    yield d, sub.lineno

    def names_stored(node):
        for sub in ast.walk(node):
            if isinstance(sub, (ast.Name, ast.Attribute)) and isinstance(
                    sub.ctx, ast.Store):
                d = sub.id if isinstance(sub, ast.Name) else dotted(sub)
                if d:
                    yield d

    for stmt in stmts:
        # within one statement the order of effects is: argument reads,
        # then the donating call, then the statement's own stores — so
        # `params, opt, _ = step(params, opt, ...)` donates AND rebinds
        for name, lineno in names_loaded(stmt):
            if name in dead:
                don_line, fn_name = dead[name]
                out.append(Violation(
                    "use-after-donate", src.path, lineno,
                    f"'{name}' was donated to {fn_name}() on line "
                    f"{don_line} (donate_argnums) — its buffer is dead; "
                    f"reading it returns garbage on hardware that honours "
                    f"donation"))
                # report once per donation
                del dead[name]
        for sub in ast.walk(stmt):
            if not isinstance(sub, ast.Call):
                continue
            fname = dotted(sub.func)
            if fname in donating:
                for i in donating[fname]:
                    if i < len(sub.args):
                        arg = sub.args[i]
                        aname = dotted(arg)
                        if aname:
                            dead[aname] = (sub.lineno, fname)
        for name in names_stored(stmt):
            dead.pop(name, None)
        # recurse into compound statements in order; loops twice
        for field in ("body", "orelse", "finalbody"):
            sub_stmts = getattr(stmt, field, None)
            if isinstance(sub_stmts, list) and sub_stmts:
                reps = 2 if isinstance(stmt, (ast.For, ast.While)) \
                    and field == "body" else 1
                for _ in range(reps):
                    _scan_donation_scope(sub_stmts, donating, out, src,
                                         dead)


@rule("use-after-donate",
      "argument read after being passed to a donate_argnums program",
      "the PR 3 bench bug: run_breakdown computed FLOPs from params AFTER "
      "donating them to the step — garbage math on chip, invisible on CPU "
      "where donation is a no-op")
def check_use_after_donate(src: SourceFile) -> List[Violation]:
    donating = _donating_assigns(src.tree)
    if not donating:
        return []
    out: List[Violation] = []
    # module level + each function scope, statements in order
    _scan_donation_scope(src.tree.body, donating, out, src)
    for node in src.nodes:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _scan_donation_scope(node.body, donating, out, src)
    # de-duplicate (module walk visits nested defs' statements too)
    uniq = {(v.line, v.message): v for v in out}
    return list(uniq.values())


# ----------------------------------------------------------- prng-key-reuse --

_KEY_SOURCES = {"jax.random.PRNGKey", "jax.random.key", "jax.random.split",
                "jax.random.fold_in", "jax.random.wrap_key_data",
                "jax.random.clone", "random.PRNGKey", "random.split",
                "random.fold_in"}
_NON_CONSUMING = {"split", "fold_in", "key_data", "wrap_key_data", "clone",
                  "key_impl", "PRNGKey", "key"}


def _is_key_source(call: ast.Call) -> bool:
    return dotted(call.func) in _KEY_SOURCES


def _consumer_name(call: ast.Call) -> Optional[str]:
    """jax.random.<fn> consuming its key argument -> <fn>, else None."""
    name = dotted(call.func) or ""
    if not name.startswith(("jax.random.", "jrandom.", "jr.")):
        return None
    fn = name.rsplit(".", 1)[1]
    if fn in _NON_CONSUMING:
        return None
    return fn


@rule("prng-key-reuse",
      "a PRNG key consumed twice without split/fold_in between",
      "two draws from one key are IDENTICAL draws: the correlated-sampling "
      "bug class the per-request (seed, position, stream) fold_in schedule "
      "in serving/ was built to rule out")
def check_prng_key_reuse(src: SourceFile) -> List[Violation]:
    out: List[Violation] = []
    _FN = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)

    def shallow_exprs(stmt):
        """Expression nodes the statement itself evaluates (not nested
        statement lists, not nested function bodies)."""
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            yield stmt.iter
        elif isinstance(stmt, (ast.While, ast.If)):
            yield stmt.test
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                yield item.context_expr
        elif isinstance(stmt, _FN + (ast.ClassDef, ast.Try)):
            return
        else:
            yield stmt

    def scan_scope(fn_node):
        keys: Dict[str, int] = {}          # name -> consumption count
        born_line: Dict[str, int] = {}
        loop_depth_of: Dict[str, int] = {}

        def handle_expr(node, loop_depth):
            for sub in ast.walk(node):
                if isinstance(sub, _FN):
                    continue  # nested scopes are scanned separately
                if isinstance(sub, ast.Assign) and isinstance(
                        sub.value, ast.Call) and _is_key_source(sub.value):
                    for t in sub.targets:
                        targets = t.elts if isinstance(t, ast.Tuple) \
                            else [t]
                        for el in targets:
                            if isinstance(el, ast.Name):
                                keys[el.id] = 0
                                born_line[el.id] = sub.lineno
                                loop_depth_of[el.id] = loop_depth
                elif isinstance(sub, ast.Assign):
                    for t in sub.targets:  # any other rebind forgets it
                        targets = t.elts if isinstance(t, ast.Tuple) \
                            else [t]
                        for el in targets:
                            if isinstance(el, ast.Name):
                                keys.pop(el.id, None)
                if isinstance(sub, ast.Call):
                    fn = _consumer_name(sub)
                    if fn and sub.args and isinstance(sub.args[0],
                                                      ast.Name):
                        kname = sub.args[0].id
                        if kname not in keys:
                            continue
                        keys[kname] += 1
                        if keys[kname] == 2:
                            out.append(Violation(
                                "prng-key-reuse", src.path, sub.lineno,
                                f"key '{kname}' (from line "
                                f"{born_line[kname]}) consumed a second "
                                f"time by jax.random.{fn} — identical "
                                f"randomness; split or fold_in first"))
                        elif (keys[kname] == 1 and loop_depth
                                > loop_depth_of.get(kname, loop_depth)):
                            keys[kname] += 1  # report once
                            out.append(Violation(
                                "prng-key-reuse", src.path, sub.lineno,
                                f"key '{kname}' defined outside this "
                                f"loop is consumed by jax.random.{fn} "
                                f"every iteration without fold_in — "
                                f"every iteration draws the SAME "
                                f"randomness"))

        def walk(stmts, loop_depth):
            for stmt in stmts:
                for expr in shallow_exprs(stmt):
                    handle_expr(expr, loop_depth)
                deeper = loop_depth + (
                    1 if isinstance(stmt, (ast.For, ast.AsyncFor,
                                           ast.While)) else 0)
                for field in ("body", "orelse", "finalbody"):
                    subs = getattr(stmt, field, None)
                    if isinstance(subs, list) and not isinstance(
                            stmt, _FN + (ast.ClassDef,)):
                        walk(subs, deeper if field == "body"
                             else loop_depth)
                for h in getattr(stmt, "handlers", []):
                    walk(h.body, loop_depth)

        walk(fn_node.body, 0)

    for node in src.nodes:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scan_scope(node)
    return out
