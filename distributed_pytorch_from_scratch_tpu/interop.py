"""Reference-checkpoint interop: import the PyTorch framework's trained
checkpoints into this framework's format.

The reference saves one torch `state_dict` per TP rank as
`tprank-{r}_iter-{n}_loss-{x}.pth` (`/root/reference/train.py:121-126`),
holding that rank's SHARDS of the Megatron-partitioned weights
(`/root/reference/models/layers.py`):

    embedding.weight                 (vocab/tp, d)   row shard
    layers.{i}.attn.{wq,wk,wv}.weight  (d/tp, d)     column shard (+ bias (d/tp,))
    layers.{i}.attn.wo.weight        (d, d/tp)       row shard    (+ bias (d,) replicated)
    layers.{i}.ffn.{gate,up}_proj.weight (f/tp, d)   column shard (+ bias (f/tp,))
    layers.{i}.ffn.down_proj.weight  (d, f/tp)       row shard    (+ bias (d,) replicated)
    layers.{i}.norm{1,2}.scale       (d,)            replicated
    norm.scale                       (d,)            replicated
    lm_head.weight                   (vocab/tp, d)   column shard (+ bias (vocab/tp,))

This module reassembles the global tensors from all rank files and maps
them into this framework's param tree (stacked layers, (idim, odim)
weight layout — torch's `F.linear` computes `x @ W.T`, ours `x @ W`, so
every linear weight is transposed; vocab rows/cols are zero-padded to
`padded_vocab_size`). The result can be saved as a normal checkpoint and
then trained/evaluated/decoded on ANY mesh — a reference user switches
frameworks without losing their training run.

CLI:
    python -m distributed_pytorch_from_scratch_tpu.interop \
        --ref_ckpt_dir <dir with tprank-*.pth> --iter 16000 \
        --out_dir <our checkpoint dir> \
        --attn_dim 512 --ffn_dim 2048 --num_heads 8 --num_layers 12 \
        --vocab_size 1024 --maxlen 1000
"""

from __future__ import annotations

import argparse
import glob
import os
import re
from typing import Dict, List

import numpy as np

from .config import ModelConfig
from .training.checkpoint import find_rank_shards


def find_reference_shards(ckpt_dir: str, step: int) -> List[str]:
    """Per-rank .pth paths for iteration `step`, ordered by rank."""
    by_rank = find_rank_shards(ckpt_dir, step, ext="pth")
    if not by_rank:
        raise FileNotFoundError(
            f"no reference checkpoint files for iter {step} in {ckpt_dir}")
    ranks = sorted(by_rank)
    if ranks != list(range(len(ranks))):
        raise FileNotFoundError(
            f"reference checkpoint iter {step} has ranks {ranks}; "
            f"expected contiguous 0..{len(ranks) - 1}")
    return [by_rank[r] for r in ranks]


def reference_iters(ckpt_dir: str) -> List[int]:
    pat = re.compile(r"tprank-(\d+)_iter-(\d+)_loss-(.+?)\.pth$")
    its = set()
    for p in glob.glob(os.path.join(ckpt_dir, "tprank-*_iter-*_loss-*.pth")):
        m = pat.search(os.path.basename(p))
        if m:
            its.add(int(m.group(2)))
    return sorted(its)


def convert_state_dicts(shards: List[Dict[str, np.ndarray]],
                        cfg: ModelConfig,
                        pad_vocab_multiple: int = 1) -> Dict:
    """Per-rank reference state_dicts (numpy values) -> this framework's
    global param tree.

    `pad_vocab_multiple`: zero-pad the vocab rows/cols of the embedding
    and lm_head up to a multiple of this value. Checkpoints reload onto a
    tp mesh only when the stored vocab dim equals the target model's
    `padded_vocab_size(tp)`, so for a NON-divisible vocab pass the target
    tp degree here (a divisible vocab — e.g. the reference's 1024 — needs
    no padding for any practical tp)."""
    L = cfg.num_layers
    m = max(1, pad_vocab_multiple)
    vp = ((cfg.vocab_size + m - 1) // m) * m

    def cat(key: str, dim: int) -> np.ndarray:
        return np.concatenate([s[key] for s in shards], axis=dim)

    def col_linear(prefix: str) -> Dict[str, np.ndarray]:
        # torch column shards (odim/tp, idim) -> global (odim, idim) -> ours
        # (idim, odim); bias shards (odim/tp,) -> (odim,)
        out = {"weight": np.ascontiguousarray(cat(f"{prefix}.weight", 0).T)}
        if f"{prefix}.bias" in shards[0]:
            out["bias"] = cat(f"{prefix}.bias", 0)
        return out

    def row_linear(prefix: str) -> Dict[str, np.ndarray]:
        # torch row shards (odim, idim/tp) -> global (odim, idim) -> ours
        # (idim, odim); bias replicated -> rank 0's copy
        out = {"weight": np.ascontiguousarray(cat(f"{prefix}.weight", 1).T)}
        if f"{prefix}.bias" in shards[0]:
            out["bias"] = shards[0][f"{prefix}.bias"]
        return out

    def pad_rows(w: np.ndarray) -> np.ndarray:
        if w.shape[0] == vp:
            return w
        return np.concatenate(
            [w, np.zeros((vp - w.shape[0],) + w.shape[1:], w.dtype)], axis=0)

    raw = cat("embedding.weight", 0)
    # exact-match BEFORE padding: an over-declared --vocab_size would
    # otherwise be silently zero-filled into "real" vocab rows, and an
    # under-declared one would crash with an opaque negative-dim error
    if raw.shape != (cfg.vocab_size, cfg.attn_dim):
        raise ValueError(f"embedding reassembled to {raw.shape}; expected "
                         f"({cfg.vocab_size}, {cfg.attn_dim}) — do the "
                         f"--attn_dim/--vocab_size flags match the trained "
                         f"model?")
    emb = pad_rows(raw)

    def one_layer(i: int) -> Dict:
        p = f"layers.{i}"
        return {
            "wq": col_linear(f"{p}.attn.wq"),
            "wk": col_linear(f"{p}.attn.wk"),
            "wv": col_linear(f"{p}.attn.wv"),
            "wo": row_linear(f"{p}.attn.wo"),
            "gate_proj": col_linear(f"{p}.ffn.gate_proj"),
            "up_proj": col_linear(f"{p}.ffn.up_proj"),
            "down_proj": row_linear(f"{p}.ffn.down_proj"),
            "norm1": {"scale": shards[0][f"{p}.norm1.scale"]},
            "norm2": {"scale": shards[0][f"{p}.norm2.scale"]},
        }

    layers = [one_layer(i) for i in range(L)]
    # stack per-leaf along the new leading layer dim (lax.scan layout)
    stacked = {}
    for mod in layers[0]:
        stacked[mod] = {k: np.stack([lyr[mod][k] for lyr in layers])
                        for k in layers[0][mod]}

    lm = col_linear("lm_head")
    if lm["weight"].shape != (cfg.attn_dim, cfg.vocab_size):
        raise ValueError(f"lm_head reassembled to {lm['weight'].shape}; "
                         f"expected ({cfg.attn_dim}, {cfg.vocab_size})")
    lm["weight"] = np.concatenate(
        [lm["weight"],
         np.zeros((cfg.attn_dim, vp - lm["weight"].shape[1]),
                  lm["weight"].dtype)], axis=1)
    if "bias" in lm:
        lm["bias"] = pad_rows(lm["bias"])

    return {
        "embedding": {"weight": emb},
        "layers": stacked,
        "norm": {"scale": shards[0]["norm.scale"]},
        "lm_head": lm,
    }


def load_reference_checkpoint(ckpt_dir: str, step: int, cfg: ModelConfig,
                              pad_vocab_multiple: int = 1) -> Dict:
    """torch .pth rank shards -> this framework's param tree (f32 numpy)."""
    import torch  # CPU-only use; torch is host-side here

    shards = []
    for path in find_reference_shards(ckpt_dir, step):
        sd = torch.load(path, map_location="cpu", weights_only=True)
        shards.append({k: v.float().numpy() for k, v in sd.items()})
    return convert_state_dicts(shards, cfg, pad_vocab_multiple)


def main(argv=None) -> Dict:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--ref_ckpt_dir", required=True,
                   help="directory holding the reference's tprank-*.pth files")
    p.add_argument("--iter", type=int, default=None,
                   help="iteration to import (default: latest found)")
    p.add_argument("--out_dir", required=True,
                   help="output directory for this framework's checkpoint")
    p.add_argument("--attn_dim", type=int, default=512)
    p.add_argument("--ffn_dim", type=int, default=2048)
    p.add_argument("--num_heads", type=int, default=8)
    p.add_argument("--num_layers", type=int, default=12)
    p.add_argument("--vocab_size", type=int, default=1024)
    p.add_argument("--maxlen", type=int, default=1000)
    p.add_argument("--pad_vocab_multiple", type=int, default=1,
                   help="zero-pad the vocab dim to a multiple of this (set "
                        "to your target tp degree when vocab_size does not "
                        "divide it; irrelevant for divisible vocabs)")
    args = p.parse_args(argv)

    from .models.transformer import Transformer
    from .training.checkpoint import save_checkpoint

    step = args.iter
    if step is None:
        its = reference_iters(args.ref_ckpt_dir)
        if not its:
            raise SystemExit(f"no reference checkpoints in "
                             f"{args.ref_ckpt_dir}")
        step = its[-1]
    cfg = ModelConfig(attn_dim=args.attn_dim, ffn_dim=args.ffn_dim,
                      num_heads=args.num_heads, num_layers=args.num_layers,
                      vocab_size=args.vocab_size, maxlen=args.maxlen)
    params = load_reference_checkpoint(args.ref_ckpt_dir, step, cfg,
                                       args.pad_vocab_multiple)
    # The template model pads vocab exactly like the converter (tp_size is
    # only used for the padding arithmetic here; the checkpoint itself is
    # written as one tp=1 shard file).
    model = Transformer(cfg, tp_size=max(1, args.pad_vocab_multiple))
    # shape-check against a real init before writing anything
    import jax

    template = jax.eval_shape(lambda: model.init(jax.random.key(0)))
    flat_t = {"/".join(map(str, path)): leaf for path, leaf in
              _walk(template)}
    flat_p = {"/".join(map(str, path)): leaf for path, leaf in _walk(params)}
    if set(flat_t) != set(flat_p):
        raise SystemExit(f"converted tree mismatch: missing "
                         f"{sorted(set(flat_t) - set(flat_p))}, extra "
                         f"{sorted(set(flat_p) - set(flat_t))}")
    for k in flat_t:
        if tuple(flat_t[k].shape) != tuple(flat_p[k].shape):
            raise SystemExit(f"shape mismatch at {k}: reference gives "
                             f"{flat_p[k].shape}, model expects "
                             f"{flat_t[k].shape}")
    paths = save_checkpoint(args.out_dir, step, float("nan"), params,
                            model.specs(), tp_size=1)
    print(f"imported reference iter {step} "
          f"({len(find_reference_shards(args.ref_ckpt_dir, step))} rank "
          f"shard(s)) -> {paths[0]}")
    return params


def _walk(tree, path=()):
    if isinstance(tree, dict):
        for k in sorted(tree):
            yield from _walk(tree[k], path + (k,))
    else:
        yield path, tree


if __name__ == "__main__":
    main()
