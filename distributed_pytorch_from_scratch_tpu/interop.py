"""Reference-checkpoint interop, BOTH directions: import the PyTorch
framework's trained checkpoints into this framework's format, or export
ours back into the reference's per-rank `.pth` layout (which its
`test.py`/`train.py` consume unchanged).

The reference saves one torch `state_dict` per TP rank as
`tprank-{r}_iter-{n}_loss-{x}.pth` (`/root/reference/train.py:121-126`),
holding that rank's SHARDS of the Megatron-partitioned weights
(`/root/reference/models/layers.py`):

    embedding.weight                 (vocab/tp, d)   row shard
    layers.{i}.attn.{wq,wk,wv}.weight  (d/tp, d)     column shard (+ bias (d/tp,))
    layers.{i}.attn.wo.weight        (d, d/tp)       row shard    (+ bias (d,) replicated)
    layers.{i}.ffn.{gate,up}_proj.weight (f/tp, d)   column shard (+ bias (f/tp,))
    layers.{i}.ffn.down_proj.weight  (d, f/tp)       row shard    (+ bias (d,) replicated)
    layers.{i}.norm{1,2}.scale       (d,)            replicated
    norm.scale                       (d,)            replicated
    lm_head.weight                   (vocab/tp, d)   column shard (+ bias (vocab/tp,))

This module reassembles the global tensors from all rank files and maps
them into this framework's param tree (stacked layers, (idim, odim)
weight layout — torch's `F.linear` computes `x @ W.T`, ours `x @ W`, so
every linear weight is transposed; vocab rows/cols are zero-padded to
`padded_vocab_size`). The result can be saved as a normal checkpoint and
then trained/evaluated/decoded on ANY mesh — a reference user switches
frameworks without losing their training run.

CLI (model-shape flags shared by both directions):
    # reference -> ours
    python -m distributed_pytorch_from_scratch_tpu.interop \
        --ref_ckpt_dir <dir with tprank-*.pth> --iter 16000 \
        --out_dir <our checkpoint dir> \
        --attn_dim 512 --ffn_dim 2048 --num_heads 8 --num_layers 12 \
        --vocab_size 1024 --maxlen 1000
    # ours -> reference (any reference TP degree)
    python -m distributed_pytorch_from_scratch_tpu.interop --direction export \
        --our_ckpt_dir <dir with tprank-*.npz> --export_tp 4 \
        --out_dir <reference checkpoint dir> [same shape flags]
"""

from __future__ import annotations

import argparse
import glob
import os
import re
from typing import Dict, List

import numpy as np

from .config import ModelConfig
from .training.checkpoint import (CKPT_RE, find_rank_shards,
                                  validate_checkpoint)


def find_reference_shards(ckpt_dir: str, step: int) -> List[str]:
    """Per-rank .pth paths for iteration `step`, ordered by rank.
    Completeness is validated up front (training/checkpoint.py) so a hole
    in the rank set names the missing ranks instead of mis-assembling."""
    tp_size, by_rank = validate_checkpoint(ckpt_dir, step, ext="pth")
    return [by_rank[r] for r in range(tp_size)]


def reference_iters(ckpt_dir: str) -> List[int]:
    pat = re.compile(r"tprank-(\d+)_iter-(\d+)_loss-(.+?)\.pth$")
    its = set()
    for p in glob.glob(os.path.join(ckpt_dir, "tprank-*_iter-*_loss-*.pth")):
        m = pat.search(os.path.basename(p))
        if m:
            its.add(int(m.group(2)))
    return sorted(its)


def convert_state_dicts(shards: List[Dict[str, np.ndarray]],
                        cfg: ModelConfig,
                        pad_vocab_multiple: int = 1) -> Dict:
    """Per-rank reference state_dicts (numpy values) -> this framework's
    global param tree.

    `pad_vocab_multiple`: zero-pad the vocab rows/cols of the embedding
    and lm_head up to a multiple of this value. Checkpoints reload onto a
    tp mesh only when the stored vocab dim equals the target model's
    `padded_vocab_size(tp)`, so for a NON-divisible vocab pass the target
    tp degree here (a divisible vocab — e.g. the reference's 1024 — needs
    no padding for any practical tp)."""
    L = cfg.num_layers
    m = max(1, pad_vocab_multiple)
    vp = ((cfg.vocab_size + m - 1) // m) * m

    def cat(key: str, dim: int) -> np.ndarray:
        return np.concatenate([s[key] for s in shards], axis=dim)

    def col_linear(prefix: str) -> Dict[str, np.ndarray]:
        # torch column shards (odim/tp, idim) -> global (odim, idim) -> ours
        # (idim, odim); bias shards (odim/tp,) -> (odim,)
        out = {"weight": np.ascontiguousarray(cat(f"{prefix}.weight", 0).T)}
        if f"{prefix}.bias" in shards[0]:
            out["bias"] = cat(f"{prefix}.bias", 0)
        return out

    def row_linear(prefix: str) -> Dict[str, np.ndarray]:
        # torch row shards (odim, idim/tp) -> global (odim, idim) -> ours
        # (idim, odim); bias replicated -> rank 0's copy
        out = {"weight": np.ascontiguousarray(cat(f"{prefix}.weight", 1).T)}
        if f"{prefix}.bias" in shards[0]:
            out["bias"] = shards[0][f"{prefix}.bias"]
        return out

    def pad_rows(w: np.ndarray) -> np.ndarray:
        if w.shape[0] == vp:
            return w
        return np.concatenate(
            [w, np.zeros((vp - w.shape[0],) + w.shape[1:], w.dtype)], axis=0)

    raw = cat("embedding.weight", 0)
    # exact-match BEFORE padding: an over-declared --vocab_size would
    # otherwise be silently zero-filled into "real" vocab rows, and an
    # under-declared one would crash with an opaque negative-dim error
    if raw.shape != (cfg.vocab_size, cfg.attn_dim):
        raise ValueError(f"embedding reassembled to {raw.shape}; expected "
                         f"({cfg.vocab_size}, {cfg.attn_dim}) — do the "
                         f"--attn_dim/--vocab_size flags match the trained "
                         f"model?")
    emb = pad_rows(raw)

    def one_layer(i: int) -> Dict:
        p = f"layers.{i}"
        return {
            "wq": col_linear(f"{p}.attn.wq"),
            "wk": col_linear(f"{p}.attn.wk"),
            "wv": col_linear(f"{p}.attn.wv"),
            "wo": row_linear(f"{p}.attn.wo"),
            "gate_proj": col_linear(f"{p}.ffn.gate_proj"),
            "up_proj": col_linear(f"{p}.ffn.up_proj"),
            "down_proj": row_linear(f"{p}.ffn.down_proj"),
            "norm1": {"scale": shards[0][f"{p}.norm1.scale"]},
            "norm2": {"scale": shards[0][f"{p}.norm2.scale"]},
        }

    layers = [one_layer(i) for i in range(L)]
    # stack per-leaf along the new leading layer dim (lax.scan layout)
    stacked = {}
    for mod in layers[0]:
        stacked[mod] = {k: np.stack([lyr[mod][k] for lyr in layers])
                        for k in layers[0][mod]}

    lm = col_linear("lm_head")
    if lm["weight"].shape != (cfg.attn_dim, cfg.vocab_size):
        raise ValueError(f"lm_head reassembled to {lm['weight'].shape}; "
                         f"expected ({cfg.attn_dim}, {cfg.vocab_size})")
    lm["weight"] = np.concatenate(
        [lm["weight"],
         np.zeros((cfg.attn_dim, vp - lm["weight"].shape[1]),
                  lm["weight"].dtype)], axis=1)
    if "bias" in lm:
        lm["bias"] = pad_rows(lm["bias"])

    return {
        "embedding": {"weight": emb},
        "layers": stacked,
        "norm": {"scale": shards[0]["norm.scale"]},
        "lm_head": lm,
    }


def load_reference_checkpoint(ckpt_dir: str, step: int, cfg: ModelConfig,
                              pad_vocab_multiple: int = 1) -> Dict:
    """torch .pth rank shards -> this framework's param tree (f32 numpy)."""
    import torch  # CPU-only use; torch is host-side here

    shards = []
    for path in find_reference_shards(ckpt_dir, step):
        sd = torch.load(path, map_location="cpu", weights_only=True)
        shards.append({k: v.float().numpy() for k, v in sd.items()})
    return convert_state_dicts(shards, cfg, pad_vocab_multiple)


def export_state_dicts(params: Dict, cfg: ModelConfig,
                       tp: int) -> List[Dict[str, np.ndarray]]:
    """This framework's param tree -> per-rank reference state_dicts — the
    exact inverse of `convert_state_dicts`, so a model trained here can be
    evaluated (or trained further) by the reference's `test.py`/`train.py`.

    Only the reference-expressible feature set exports: the llama family,
    MHA (no GQA), dense FFN (no MoE). Vocab padding rows/cols are dropped
    (they carry no probability mass); the vocab must divide `tp` like the
    reference requires (`/root/reference/models/layers.py:117`)."""
    L, V, d = cfg.num_layers, cfg.vocab_size, cfg.attn_dim
    if cfg.num_experts:
        raise ValueError("MoE checkpoints cannot export: the reference's "
                         "FFN is dense (no router/experts)")
    if cfg.kv_heads != cfg.num_heads:
        raise ValueError("GQA checkpoints cannot export: the reference is "
                         "MHA-only (num_kv_heads == num_heads)")
    # mirror the reference's own construction asserts so a bad tp fails
    # HERE with the offending flag, not in np.split or on the reference's
    # side after the files shipped (`/root/reference/models/model.py:55`,
    # `layers.py:69,25,117`)
    if tp < 1:
        raise ValueError(f"export tp must be >= 1, got {tp}")
    for what, size in [("vocab_size", V), ("num_heads", cfg.num_heads),
                       ("attn_dim", d), ("ffn_dim", cfg.ffn_dim)]:
        if size % tp != 0:
            raise ValueError(f"tp {tp} must divide {what} {size} for the "
                             f"reference's partitioning")
    np_ = lambda a: np.asarray(a, np.float32)

    # Validate the tree against the declared shape BEFORE slicing: export
    # trims vocab padding and loops `range(L)`, so understated flags would
    # otherwise silently truncate the model (the import direction already
    # fails loudly on this mistake).
    emb_rows = np.shape(params["embedding"]["weight"])[0]
    got_L = np.shape(params["layers"]["wq"]["weight"])[0]
    got_d = np.shape(params["norm"]["scale"])[0]
    got_f = np.shape(params["layers"]["down_proj"]["weight"])[1]
    if got_L != L or got_d != d or got_f != cfg.ffn_dim:
        raise ValueError(
            f"checkpoint shape (layers={got_L}, attn_dim={got_d}, "
            f"ffn_dim={got_f}) does not match the declared flags "
            f"(layers={L}, attn_dim={d}, ffn_dim={cfg.ffn_dim})")
    if emb_rows < V:
        raise ValueError(
            f"checkpoint embedding has only {emb_rows} vocab rows but "
            f"--vocab_size is {V} — the flag overstates the trained vocab")
    if emb_rows >= V + 64:
        # padding is < the training tp degree (<= 64 in practice); a larger
        # gap means --vocab_size understates the trained vocab
        raise ValueError(
            f"checkpoint embedding has {emb_rows} vocab rows but "
            f"--vocab_size is {V}; exporting would silently drop "
            f"{emb_rows - V} real rows — do the flags match the trained "
            f"model?")

    def col_shards(w, b, r, unpad_to=None):
        # ours (idim, odim[+pad]) -> torch (odim, idim) shard r over dim 0;
        # `unpad_to` drops trailing padded output rows (lm_head only —
        # never inferred from sizes: ffn_dim may exceed vocab_size)
        wt = np_(w).T
        if unpad_to is not None:
            wt = wt[:unpad_to]
        out = {"weight": np.ascontiguousarray(np.split(wt, tp, axis=0)[r])}
        if b is not None:
            bb = np_(b)
            if unpad_to is not None:
                bb = bb[:unpad_to]
            out["bias"] = np.split(bb, tp, axis=0)[r]
        return out

    def row_shards(w, b, r):
        wt = np_(w).T
        out = {"weight": np.ascontiguousarray(np.split(wt, tp, axis=1)[r])}
        if b is not None:
            out["bias"] = np_(b)  # replicated full bias
        return out

    lyr = params["layers"]
    get = lambda mod, k, i: lyr[mod][k][i] if k in lyr[mod] else None
    shards = []
    for r in range(tp):
        sd: Dict[str, np.ndarray] = {
            "embedding.weight": np.split(
                np_(params["embedding"]["weight"])[:V], tp, axis=0)[r],
            "norm.scale": np_(params["norm"]["scale"]),
        }
        sd.update({f"lm_head.{k}": v for k, v in col_shards(
            params["lm_head"]["weight"],
            params["lm_head"].get("bias"), r, unpad_to=V).items()})
        for i in range(L):
            p = f"layers.{i}"
            for mod, ref, kind in [("wq", "attn.wq", "col"),
                                   ("wk", "attn.wk", "col"),
                                   ("wv", "attn.wv", "col"),
                                   ("wo", "attn.wo", "row"),
                                   ("gate_proj", "ffn.gate_proj", "col"),
                                   ("up_proj", "ffn.up_proj", "col"),
                                   ("down_proj", "ffn.down_proj", "row")]:
                fn = col_shards if kind == "col" else row_shards
                for k, v in fn(lyr[mod]["weight"][i],
                               get(mod, "bias", i), r).items():
                    sd[f"{p}.{ref}.{k}"] = v
            sd[f"{p}.norm1.scale"] = np_(lyr["norm1"]["scale"][i])
            sd[f"{p}.norm2.scale"] = np_(lyr["norm2"]["scale"][i])
        shards.append(sd)
    return shards


def export_reference_checkpoint(params: Dict, cfg: ModelConfig, tp: int,
                                out_dir: str, step: int,
                                loss: float = 0.0) -> List[str]:
    """Write per-rank `tprank-{r}_iter-{step}_loss-{loss:.4f}.pth` files
    the reference's `test.py` discovers by its filename regex
    (`/root/reference/test.py:94-98`)."""
    import torch

    os.makedirs(out_dir, exist_ok=True)
    paths = []
    for r, sd in enumerate(export_state_dicts(params, cfg, tp)):
        path = os.path.join(out_dir,
                            f"tprank-{r}_iter-{step}_loss-{loss:.4f}.pth")
        torch.save({k: torch.from_numpy(np.ascontiguousarray(v))
                    for k, v in sd.items()}, path)
        paths.append(path)
    return paths


def main(argv=None) -> Dict:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--direction", choices=["import", "export"],
                   default="import",
                   help="'import' = reference .pth -> our checkpoint; "
                        "'export' = our checkpoint -> reference .pth (the "
                        "reference's test.py/train.py can then consume it)")
    p.add_argument("--ref_ckpt_dir", default=None,
                   help="import: directory holding the reference's "
                        "tprank-*.pth files")
    p.add_argument("--our_ckpt_dir", default=None,
                   help="export: directory holding this framework's "
                        "tprank-*.npz checkpoint")
    p.add_argument("--export_tp", type=int, default=1,
                   help="export: how many reference TP rank files to write")
    p.add_argument("--iter", type=int, default=None,
                   help="iteration to convert (default: latest found)")
    p.add_argument("--out_dir", required=True,
                   help="output directory for the converted checkpoint")
    p.add_argument("--attn_dim", type=int, default=512)
    p.add_argument("--ffn_dim", type=int, default=2048)
    p.add_argument("--num_heads", type=int, default=8)
    p.add_argument("--num_layers", type=int, default=12)
    p.add_argument("--vocab_size", type=int, default=1024)
    p.add_argument("--maxlen", type=int, default=1000)
    p.add_argument("--pad_vocab_multiple", type=int, default=1,
                   help="zero-pad the vocab dim to a multiple of this (set "
                        "to your target tp degree when vocab_size does not "
                        "divide it; irrelevant for divisible vocabs)")
    args = p.parse_args(argv)

    from .models.transformer import Transformer
    from .training.checkpoint import (latest_step, load_checkpoint,
                                      save_checkpoint)

    cfg = ModelConfig(attn_dim=args.attn_dim, ffn_dim=args.ffn_dim,
                      num_heads=args.num_heads, num_layers=args.num_layers,
                      vocab_size=args.vocab_size, maxlen=args.maxlen)

    if args.direction == "export":
        import jax

        if not args.our_ckpt_dir:
            raise SystemExit("--direction export needs --our_ckpt_dir")
        step = args.iter
        if step is None:
            step = latest_step(args.our_ckpt_dir)
            if step is None:
                raise SystemExit(f"no checkpoints in {args.our_ckpt_dir}")
        model = Transformer(cfg)
        # shape-only template: load_checkpoint uses it for tree structure
        template = jax.eval_shape(lambda: model.init(jax.random.key(0)))
        params, _, _ = load_checkpoint(args.our_ckpt_dir, step, template,
                                       model.specs())
        params = jax.tree.map(np.asarray, params)
        # carry the real loss metadata from our filename into the exported
        # names (the reference's convention encodes it there)
        import math

        src = find_rank_shards(args.our_ckpt_dir, step)
        m = CKPT_RE.search(os.path.basename(src[min(src)]))
        try:
            loss = float(m.group(3)) if m else 0.0
        except ValueError:
            loss = 0.0
        if math.isnan(loss):  # e.g. an imported checkpoint's 'loss-nan'
            loss = 0.0
        paths = export_reference_checkpoint(params, cfg, args.export_tp,
                                            args.out_dir, step, loss=loss)
        print(f"exported iter {step} -> {len(paths)} reference rank "
              f"file(s), first: {paths[0]}")
        return params

    if not args.ref_ckpt_dir:
        raise SystemExit("--direction import needs --ref_ckpt_dir")
    step = args.iter
    if step is None:
        its = reference_iters(args.ref_ckpt_dir)
        if not its:
            raise SystemExit(f"no reference checkpoints in "
                             f"{args.ref_ckpt_dir}")
        step = its[-1]
    params = load_reference_checkpoint(args.ref_ckpt_dir, step, cfg,
                                       args.pad_vocab_multiple)
    # The template model pads vocab exactly like the converter (tp_size is
    # only used for the padding arithmetic here; the checkpoint itself is
    # written as one tp=1 shard file).
    model = Transformer(cfg, tp_size=max(1, args.pad_vocab_multiple))
    # shape-check against a real init before writing anything
    import jax

    template = jax.eval_shape(lambda: model.init(jax.random.key(0)))
    flat_t = {"/".join(map(str, path)): leaf for path, leaf in
              _walk(template)}
    flat_p = {"/".join(map(str, path)): leaf for path, leaf in _walk(params)}
    if set(flat_t) != set(flat_p):
        raise SystemExit(f"converted tree mismatch: missing "
                         f"{sorted(set(flat_t) - set(flat_p))}, extra "
                         f"{sorted(set(flat_p) - set(flat_t))}")
    for k in flat_t:
        if tuple(flat_t[k].shape) != tuple(flat_p[k].shape):
            raise SystemExit(f"shape mismatch at {k}: reference gives "
                             f"{flat_p[k].shape}, model expects "
                             f"{flat_t[k].shape}")
    paths = save_checkpoint(args.out_dir, step, float("nan"), params,
                            model.specs(), tp_size=1)
    print(f"imported reference iter {step} "
          f"({len(find_reference_shards(args.ref_ckpt_dir, step))} rank "
          f"shard(s)) -> {paths[0]}")
    return params


def _walk(tree, path=()):
    if isinstance(tree, dict):
        for k in sorted(tree):
            yield from _walk(tree[k], path + (k,))
    else:
        yield path, tree


if __name__ == "__main__":
    main()
