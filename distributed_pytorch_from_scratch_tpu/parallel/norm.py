"""RMSNorm — replicated (not parallel), computed in f32.

Reference: `/root/reference/models/layers.py:145-155` ("Borrowed from LLama"):
`scale * x * rsqrt(mean(x^2) + eps)`, with the normalisation in f32 and the
result cast back to the input dtype. eps=1e-5.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

Params = Dict[str, Any]


@dataclass(frozen=True)
class RMSNorm:
    hdim: int
    eps: float = 1e-5

    def init(self, key: jax.Array) -> Params:
        del key
        return {"scale": jnp.ones((self.hdim,), jnp.float32)}

    def specs(self) -> Params:
        return {"scale": P(None)}

    def apply(self, params: Params, x: jax.Array) -> jax.Array:
        xf = x.astype(jnp.float32)
        normed = xf * jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + self.eps)
        return (params["scale"].astype(x.dtype) * normed.astype(x.dtype))
