"""RMSNorm + LayerNorm — replicated (not parallel), computed in f32.

RMSNorm mirrors `/root/reference/models/layers.py:145-155` ("Borrowed from
LLama"): `scale * x * rsqrt(mean(x^2) + eps)`, f32 compute, cast back.
LayerNorm (scale + bias, mean-centered) serves the GPT-2 model family
(`models/gpt2.py`) — the reference has no GPT-2 family; this is a framework
extension built on the same functional-module pattern. eps=1e-5 for both.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

Params = Dict[str, Any]


@dataclass(frozen=True)
class RMSNorm:
    hdim: int
    eps: float = 1e-5

    def init(self, key: jax.Array) -> Params:
        del key
        return {"scale": jnp.ones((self.hdim,), jnp.float32)}

    def specs(self) -> Params:
        return {"scale": P(None)}

    def apply(self, params: Params, x: jax.Array) -> jax.Array:
        xf = x.astype(jnp.float32)
        normed = xf * jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + self.eps)
        return (params["scale"].astype(x.dtype) * normed.astype(x.dtype))


@dataclass(frozen=True)
class LayerNorm:
    hdim: int
    eps: float = 1e-5

    def init(self, key: jax.Array) -> Params:
        del key
        return {"scale": jnp.ones((self.hdim,), jnp.float32),
                "bias": jnp.zeros((self.hdim,), jnp.float32)}

    def specs(self) -> Params:
        return {"scale": P(None), "bias": P(None)}

    def apply(self, params: Params, x: jax.Array) -> jax.Array:
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
        normed = ((xf - mean) * jax.lax.rsqrt(var + self.eps)).astype(x.dtype)
        return (params["scale"].astype(x.dtype) * normed
                + params["bias"].astype(x.dtype))
