"""Mixture-of-Experts FFN with expert parallelism (EP), TPU-native.

No reference counterpart: the reference's FFN is dense SwiGLU and it has no
router or expert sharding of any kind (SURVEY §2.4 "EP ❌",
`/root/reference/models/model.py:81-95`). This module is the framework
extension that turns the dense SwiGLU sublayer into a top-k routed MoE, with

* **Expert parallelism over the mesh axis 'ep'**: each ep shard owns
  `num_experts / ep` experts (leading expert dim of every expert weight is
  sharded with `P('ep', ...)`). Tokens are exchanged with ONE
  `lax.all_to_all` before and one after expert compute — the GShard/Switch
  dispatch pattern, riding ICI like every other collective here.

* **Tensor parallelism inside each expert over 'tp'**: gate/up are
  column-sharded, down is row-sharded — the same Megatron pattern as the
  dense FFN (`parallel/linear.py`), expressed as batched-over-experts
  einsums so the MXU sees one big (E_local, tokens, d) x (E_local, d, f)
  contraction instead of a Python loop over experts.

* **Static shapes throughout** (XLA requirement): routing uses the
  capacity-factor formulation — each expert accepts at most C tokens per ep
  shard; overflow tokens fall through the residual connection (standard
  Switch behaviour). With a generous `capacity_factor` nothing drops and
  the layer is exactly `sum_k gate_k * expert_k(x)`, which the equivalence
  tests exploit (routing is sharding-invariant in expectation AND in value
  when no token drops).

* **Dispatch/combine as static-shape scatter/gather**: each (token, k)
  routing resolves to a flat slot id `e * C + c`; dispatch is one
  scatter-add into the (E*C, d) expert buffer and combine is one gather
  back, weighted by the top-k gate values. Memory is O(S*k + E*C*d) —
  the earlier dense one-hot formulation built (S, E, C) masks, which is
  O(cf*k*S^2) and could not fit HBM at bench scale (ADVICE r2: ~4.1e9
  mask elements at b32 x t1000 x E8). Each expert slot receives at most
  one token (slot positions are a per-expert cumsum), so the scatter has
  no duplicate-index accumulation and stays bit-deterministic; dropped
  tokens route to one trash row that is sliced off. The transpose
  (backward) of scatter-add is a gather and vice versa — no sorts, no
  dynamic shapes.

Auxiliary losses follow Switch/ST-MoE: load-balance loss
`E * sum_e(frac_tokens_e * mean_prob_e)` and router z-loss
`mean(logsumexp(router_logits)^2)`. `apply` returns LOCAL sums; the model's
loss_shard psums them over the batch axes so the totals are independent of
how tokens are sharded (tests assert this).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..ops.collectives import copy_to, reduce_from
from ..runtime.prng import fold

Params = Dict[str, Any]


@dataclass(frozen=True)
class MoEFFN:
    """Top-k routed SwiGLU experts; drop-in for the dense FFN sublayer."""

    d: int                 # model dim
    f: int                 # per-expert hidden dim
    num_experts: int
    top_k: int = 2
    # Per-expert slots per ep shard: C = ceil(capacity_factor * S * k / E)
    # where S = local tokens. >= E/k guarantees zero drops for any routing;
    # 2.0 is a training-friendly default with rare drops.
    capacity_factor: float = 2.0
    # Renormalise the top-k gate weights to sum to 1 (Mixtral style). False
    # keeps raw softmax mass (Switch style).
    renormalize: bool = True
    ep_size: int = 1
    tp_size: int = 1
    ep_axis: str = "ep"
    tp_axis: str = "tp"

    def __post_init__(self):
        if self.num_experts % self.ep_size != 0:
            raise ValueError(f"num_experts {self.num_experts} not divisible "
                             f"by ep_size {self.ep_size}")
        if self.f % self.tp_size != 0:
            raise ValueError(f"expert ffn dim {self.f} not divisible by "
                             f"tp_size {self.tp_size}")
        if not (1 <= self.top_k <= self.num_experts):
            raise ValueError(f"top_k {self.top_k} out of range for "
                             f"{self.num_experts} experts")

    # ---- init / specs ----

    def init(self, key: jax.Array) -> Params:
        E, d, f = self.num_experts, self.d, self.f

        def expert_w(k, idim, odim):
            bound = 1.0 / math.sqrt(idim)
            return jax.random.uniform(k, (E, idim, odim), jnp.float32,
                                      -bound, bound)

        return {
            # router kept tiny + f32; zero-init (standard: uniform routing at
            # step 0, so early training matches the dense layer's scale)
            "router": jnp.zeros((d, E), jnp.float32),
            "gate": expert_w(fold(key, "gate"), d, f),
            "up": expert_w(fold(key, "up"), d, f),
            "down": expert_w(fold(key, "down"), f, d),
        }

    def specs(self) -> Params:
        ep, tp = self.ep_axis, self.tp_axis
        return {
            "router": P(None, None),
            "gate": P(ep, None, tp),
            "up": P(ep, None, tp),
            "down": P(ep, tp, None),
        }

    # ---- routing (static-shape, per ep shard) ----

    def _capacity(self, tokens: int) -> int:
        c = math.ceil(self.capacity_factor * tokens * self.top_k
                      / self.num_experts)
        return max(4, c)

    def _route(self, logits: jax.Array) -> Tuple[jax.Array, jax.Array, Params]:
        """(S, E) router logits -> flat slot ids (S, k) into the (E*C) expert
        buffer (E*C = trash for dropped tokens), combine weights (S, k), aux
        local sums."""
        S, E = logits.shape
        C = self._capacity(S)
        probs = jax.nn.softmax(logits, axis=-1)            # (S, E) f32
        topv, topi = lax.top_k(probs, self.top_k)          # (S, k)
        if self.renormalize:
            topv = topv / jnp.sum(topv, axis=-1, keepdims=True)

        # Position of each (slot, token) routing within its expert. Slot-major
        # priority (all slot-0 picks beat slot-1 picks), token order within a
        # slot — the Switch convention.
        onehot = jax.nn.one_hot(topi, E, dtype=jnp.int32)  # (S, k, E)
        flat = onehot.transpose(1, 0, 2).reshape(self.top_k * S, E)
        pos_flat = jnp.cumsum(flat, axis=0) - flat          # (k*S, E)
        pos = (pos_flat.reshape(self.top_k, S, E)
               .transpose(1, 0, 2))                         # (S, k, E)
        pos_tok = jnp.sum(pos * onehot, axis=-1)            # (S, k)
        keep = (pos_tok < C) & (topv > 0)                   # (S, k)

        # Flat slot id per (token, k): expert-major, trash slot E*C for drops.
        slots = jnp.where(keep, topi * C + pos_tok, E * C)  # (S, k)
        weights = jnp.where(keep, topv, 0.0)                # (S, k)

        aux = {
            # routed (pre-drop) assignment counts, the Switch f_e numerator
            "tokens_per_expert": jnp.sum(onehot, axis=(0, 1)).astype(jnp.float32),
            "prob_sum": jnp.sum(probs, axis=0),             # (E,)
            "z_sum": jnp.sum(jax.nn.logsumexp(logits, axis=-1) ** 2),
            "tokens": jnp.asarray(S, jnp.float32),
            "dropped": jnp.sum(1.0 - keep.astype(jnp.float32)),
        }
        return slots, weights, aux

    # ---- forward (per-shard, inside shard_map) ----

    def apply(self, params: Params, x: jax.Array,
              compute_dtype: jnp.dtype = jnp.float32
              ) -> Tuple[jax.Array, Params]:
        """x (b, t, d) -> (y (b, t, d), aux local sums).

        Must run inside shard_map over ('ep', 'tp'); x is the ep shard's
        local tokens, replicated over tp.
        """
        b, t, d = x.shape
        S = b * t
        xf = x.reshape(S, d)

        # Router in f32 for a stable softmax; stop-gradient-free (the router
        # trains through the combine weights).
        logits = xf.astype(jnp.float32) @ params["router"]
        slots, weights, aux = self._route(logits)
        E, C = self.num_experts, self._capacity(S)

        xd = xf.astype(compute_dtype)
        # Dispatch: scatter each kept (token, k) copy into its expert slot.
        # Every slot receives at most one token, plus the trash row E*C that
        # absorbs drops and is sliced off — deterministic, O(S*k*d) work.
        xk = jnp.broadcast_to(xd[:, None, :], (S, self.top_k, d))
        expert_in = (jnp.zeros((E * C + 1, d), compute_dtype)
                     .at[slots.reshape(-1)]
                     .add(xk.reshape(S * self.top_k, d), mode="drop")
                     [: E * C].reshape(E, C, d))

        if self.ep_size > 1:
            # (E, C, d) -> (E/ep, ep*C, d): each ep shard receives its own
            # experts' slots from every peer.
            expert_in = lax.all_to_all(expert_in, self.ep_axis,
                                       split_axis=0, concat_axis=1,
                                       tiled=True)

        # Batched Megatron FFN over the local experts: gate/up column-sharded
        # over tp (copy_to installs the psum of input grads), down
        # row-sharded (reduce_from sums the partial products).
        h_in = copy_to(expert_in, self.tp_axis)
        gate = jnp.einsum("ecd,edf->ecf", h_in,
                          params["gate"].astype(compute_dtype))
        up = jnp.einsum("ecd,edf->ecf", h_in,
                        params["up"].astype(compute_dtype))
        h = jax.nn.silu(gate) * up
        out = jnp.einsum("ecf,efd->ecd", h,
                         params["down"].astype(compute_dtype))
        out = reduce_from(out, self.tp_axis)

        if self.ep_size > 1:
            out = lax.all_to_all(out, self.ep_axis,
                                 split_axis=1, concat_axis=0, tiled=True)

        # Combine: gather each (token, k)'s expert output back (trash row ->
        # zeros) and sum weighted by the top-k gate values.
        out_flat = jnp.concatenate(
            [out.reshape(E * C, d), jnp.zeros((1, d), out.dtype)])
        picked = out_flat[slots.reshape(-1)].reshape(S, self.top_k, d)
        y = jnp.sum(picked * weights[..., None].astype(compute_dtype), axis=1)
        return y.reshape(b, t, d), aux


def aux_zeros(num_experts: int) -> Params:
    """Zero aux sums with the same structure `MoEFFN.apply` returns — used
    as the scan unit for dense layers so MoE and dense bodies scan alike."""
    z = jnp.zeros((), jnp.float32)
    return {"tokens_per_expert": jnp.zeros((num_experts,), jnp.float32),
            "prob_sum": jnp.zeros((num_experts,), jnp.float32),
            "z_sum": z, "tokens": z, "dropped": z}


def aux_losses(aux: Params, num_experts: int, top_k: int
               ) -> Tuple[jax.Array, jax.Array]:
    """(load_balance_loss, z_loss) from GLOBALLY-summed aux stats.

    Switch load balance: E * sum_e(f_e * P_e) with f_e the fraction of
    routed assignments to expert e and P_e the mean router prob — minimised
    (== 1) by uniform routing. Callers psum the aux sums over the batch axes
    first so the value is sharding-invariant.
    """
    tokens = jnp.maximum(aux["tokens"], 1.0)
    f = aux["tokens_per_expert"] / (tokens * top_k)
    p = aux["prob_sum"] / tokens
    lb = num_experts * jnp.sum(f * p)
    z = aux["z_sum"] / tokens
    return lb, z
