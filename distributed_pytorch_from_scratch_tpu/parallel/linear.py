"""Column- and row-parallel linear layers.

TPU-native re-expression of `/root/reference/models/layers.py:14-100`.
Design differences from the reference (all deliberate, all idiomatic JAX):

* **Functional modules.** A layer is a frozen dataclass of static shape info
  with `init(key) -> params`, `specs() -> PartitionSpec pytree` and
  `apply(params, x)`. No mutable state, no ambient process-group singleton.

* **Global params + NamedSharding.** `init` materialises the FULL weight from
  an explicit PRNG key; `specs` says how it shards over the mesh. This
  replaces the reference's init-full/broadcast-from-rank-0/slice dance
  (`layers.py:78-87`) — the property its tests assert (every shard is a slice
  of one consistent full init) holds by construction.

* **(idim, odim) weight layout**, `y = x @ W`, instead of torch's
  (odim, idim) `F.linear` layout — row-major friendly for the MXU.

* `apply` is written per-shard and must run inside `shard_map`; the comm ops
  (`ops/collectives.py`) carry the Megatron conjugate-gradient semantics.

Bias placement matches the reference exactly: column-parallel bias is SHARDED
and added before the gather (`layers.py:74,94-96`); row-parallel bias is FULL
and added after the reduce (`layers.py:29,53-54`).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops.collectives import (copy_to, gather_from, reduce_from,
                               reduce_scatter, split_to)
from ..ops.overlap import ag_matmul, matmul_rs

Params = Dict[str, Any]

OVERLAP_MODES = ("off", "ring", "ring_q")


def _check_overlap(overlap: str) -> None:
    if overlap not in OVERLAP_MODES:
        raise ValueError(f"overlap must be one of {OVERLAP_MODES}, "
                         f"got {overlap!r}")


def _torch_linear_init(key: jax.Array, idim: int, odim: int) -> jax.Array:
    """Uniform(-1/sqrt(idim), 1/sqrt(idim)) — identical distribution to the
    reference's `kaiming_uniform_(a=sqrt(5))` on a (odim, idim) weight
    (`/root/reference/models/layers.py:36,81`), which reduces to exactly this
    bound. Returned in (idim, odim) layout."""
    bound = 1.0 / math.sqrt(idim)
    return jax.random.uniform(key, (idim, odim), jnp.float32, -bound, bound)


@dataclass(frozen=True)
class ColumnParallelLinear:
    """Y = X @ W + b with W's output dim sharded over `axis`.

    Reference: `/root/reference/models/layers.py:58-100`.
    forward: copy -> local matmul -> + sharded bias -> optional gather.
    """

    idim: int
    odim: int
    add_bias: bool = True
    gather_output: bool = True
    axis: str = "tp"
    # 'ring' decomposes the sequence-parallel input all-gather into a ring
    # collective matmul (ops/overlap.ag_matmul): each ppermute hop overlaps
    # with the partial dot of the chunk already in hand. 'ring_q' is the
    # same ring with int8 codes + per-row scales on every hop (half the
    # bf16 wire bytes; bounds pinned in tests/test_quant.py). Only the
    # input_layout='seq_sharded' path changes; 'off' stays bit-identical.
    overlap: str = "off"

    def __post_init__(self):
        _check_overlap(self.overlap)

    def init(self, key: jax.Array) -> Params:
        p: Params = {"weight": _torch_linear_init(key, self.idim, self.odim)}
        if self.add_bias:
            p["bias"] = jnp.zeros((self.odim,), jnp.float32)  # zeros: layers.py:87
        return p

    def specs(self) -> Params:
        s: Params = {"weight": P(None, self.axis)}
        if self.add_bias:
            s["bias"] = P(self.axis)
        return s

    def apply(self, params: Params, x: jax.Array,
              compute_dtype: jnp.dtype = jnp.float32,
              input_layout: str = "replicated") -> jax.Array:
        w = params["weight"].astype(compute_dtype)      # local (idim, odim/n)
        if input_layout == "seq_sharded" and self.overlap != "off":
            # ring collective matmul: the gather's ppermute hops hide under
            # the per-chunk partial dots; the custom VJP rings the backward
            # too (matmul_rs for dx, a re-gather ring for dw). 'ring_q'
            # quantizes every hop's payload (ops/overlap.py).
            y = ag_matmul(x.astype(compute_dtype), (w,), self.axis,
                          self.overlap == "ring_q")[0]
            return self._epilogue(params, y, compute_dtype)
        if input_layout == "replicated":
            x = copy_to(x, self.axis)                   # bwd: all-reduce input grads
        elif input_layout == "seq_sharded":
            # Megatron sequence parallelism: x arrives (b, t/n, d); all-gather
            # the sequence dim. The transpose (psum_scatter over seq) is the
            # conjugate reduce-scatter, replacing copy_to's all-reduce — same
            # bytes on the wire, but activations upstream are 1/n-sized.
            x = gather_from(x, self.axis, tiled_axis=-2)
        elif input_layout == "gathered":
            # caller already all-gathered x (e.g. once per sublayer, shared by
            # wq/wk/wv): use as-is; fan-out cotangents sum at the caller's
            # single gather, whose transpose is one psum_scatter.
            pass
        else:
            raise ValueError(f"unknown input_layout {input_layout!r}")
        y = x.astype(compute_dtype) @ w
        return self._epilogue(params, y, compute_dtype)

    def _epilogue(self, params: Params, y: jax.Array,
                  compute_dtype) -> jax.Array:
        if self.add_bias:
            y = y + params["bias"].astype(compute_dtype)
        if self.gather_output:
            y = gather_from(y, self.axis)               # (.., odim/n) -> (.., odim)
        return y


@dataclass(frozen=True)
class RowParallelLinear:
    """Y = X @ W + b with W's input dim sharded over `axis`.

    Reference: `/root/reference/models/layers.py:14-55`.
    forward: optional split -> local matmul -> reduce (all-reduce) -> + full bias.
    `split_input=False` is the Megatron fused pattern: the input is already
    sharded (it came from a gather_output=False column-parallel layer).
    """

    idim: int
    odim: int
    add_bias: bool = True
    split_input: bool = True
    axis: str = "tp"
    # 'ring' decomposes the sequence-parallel output reduce-scatter into a
    # ring collective matmul (ops/overlap.matmul_rs): partial dots feed the
    # reduce ring chunk by chunk instead of blocking on one psum_scatter.
    # 'ring_q' additionally requantizes the circulating accumulator to
    # int8 before each hop. Only the output_layout='seq_sharded' path
    # changes; 'off' is today's.
    overlap: str = "off"

    def __post_init__(self):
        _check_overlap(self.overlap)

    def init(self, key: jax.Array) -> Params:
        p: Params = {"weight": _torch_linear_init(key, self.idim, self.odim)}
        if self.add_bias:
            p["bias"] = jnp.zeros((self.odim,), jnp.float32)
        return p

    def specs(self) -> Params:
        s: Params = {"weight": P(self.axis, None)}
        if self.add_bias:
            s["bias"] = P(None)  # replicated, added after the reduce
        return s

    def apply(self, params: Params, x: jax.Array,
              compute_dtype: jnp.dtype = jnp.float32,
              output_layout: str = "replicated") -> jax.Array:
        if self.split_input:
            x = split_to(x, self.axis)                  # (.., idim) -> (.., idim/n)
        w = params["weight"].astype(compute_dtype)      # local (idim/n, odim)
        if output_layout == "seq_sharded" and self.overlap != "off":
            # ring collective matmul: per-chunk partial dots interleave with
            # the reduce ring's hops instead of one blocking psum_scatter
            y = matmul_rs(x.astype(compute_dtype), w, self.axis,
                          self.overlap == "ring_q")
        elif output_layout == "replicated":
            y = reduce_from(x.astype(compute_dtype) @ w, self.axis)
        elif output_layout == "seq_sharded":
            # Megatron sequence parallelism: reduce-scatter the partial sums
            # over the sequence dim — each shard keeps summed (b, t/n, odim).
            # Bias (full over odim) still applies per token, after the reduce
            # like the reference (`layers.py:53-54`).
            y = reduce_scatter(x.astype(compute_dtype) @ w, self.axis,
                               scatter_axis=-2)
        else:
            raise ValueError(f"unknown output_layout {output_layout!r}")
        if self.add_bias:
            y = y + params["bias"].astype(compute_dtype)
        return y


def apply_column_ring_fused(params_list, x: jax.Array, compute_dtype,
                            axis: str = "tp", quantized: bool = False):
    """Several column-parallel projections of ONE seq-sharded input on ONE
    shared ring (wq/wk/wv, gate/up): the fused ag_matmul moves exactly the
    bytes of the single shared all-gather the monolithic path uses, and the
    custom VJP sums the fan-out cotangents on one reverse ring — the same
    one-psum_scatter-per-sublayer traffic as the shared-gather transpose.

    `params_list` is a sequence of ColumnParallelLinear param dicts (the
    layers must all be gather_output=False, which the model pattern
    guarantees). Returns one local (.., t, odim/n) output per entry.
    `quantized` (tp_overlap='ring_q') puts int8 payloads on the shared
    ring — still one quantization per chunk, however many weights ride it.
    """
    ws = tuple(p["weight"].astype(compute_dtype) for p in params_list)
    ys = ag_matmul(x.astype(compute_dtype), ws, axis, quantized)
    out = []
    for p, y in zip(params_list, ys):
        if "bias" in p:
            y = y + p["bias"].astype(compute_dtype)
        out.append(y)
    return out
