"""Vocabulary-parallel embedding.

Reference: `ParallelVocabularyEmbedding`
(`/root/reference/models/layers.py:103-141`): each rank owns a contiguous row
range of the embedding table, masks out-of-range ids, embeds, zeroes
out-of-range outputs and all-reduces the partial embeddings.

Two reference defects fixed here:

* the reference mutates its input ids in place (`layers.py:138`, callers must
  clone — SURVEY quirk #4). JAX is functional; we use `jnp.where`.
* non-divisible vocabs got a ragged last-rank partition with a printed
  warning (`layers.py:126-131`). Ragged shards break SPMD, so the table is
  padded to `vocab_padded = ceil(vocab/n)*n` rows; padded rows are zero-init
  and can never be indexed by a valid token id, so the math is unchanged.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..ops.collectives import reduce_from, reduce_scatter

Params = Dict[str, Any]


@dataclass(frozen=True)
class VocabParallelEmbedding:
    vocab_size: int
    hdim: int
    axis: str = "tp"
    tp_size: int = 1  # static: needed to size the padded table at init time
    # 1.0 = the reference's nn.Embedding-default normal(0, 1)
    # (`layers.py:114`); the GPT-2 family uses its own 0.02 (models/gpt2.py)
    init_std: float = 1.0

    @property
    def vocab_padded(self) -> int:
        n = self.tp_size
        return ((self.vocab_size + n - 1) // n) * n

    def init(self, key: jax.Array) -> Params:
        # normal(0, init_std); 1.0 matches the reference (`layers.py:114`,
        # "the same as pytorch default" for nn.Embedding).
        w = self.init_std * jax.random.normal(
            key, (self.vocab_size, self.hdim), jnp.float32)
        if self.vocab_padded != self.vocab_size:
            pad = jnp.zeros((self.vocab_padded - self.vocab_size, self.hdim), jnp.float32)
            w = jnp.concatenate([w, pad], axis=0)
        return {"weight": w}

    def specs(self) -> Params:
        return {"weight": P(self.axis, None)}

    def apply(self, params: Params, ids: jax.Array,
              output_layout: str = "replicated") -> jax.Array:
        """ids: (b, t) int32 -> (b, t, hdim) float32 ('replicated' layout) or
        (b, t/n, hdim) ('seq_sharded' — Megatron sequence parallelism)."""
        w = params["weight"]                      # local (vocab_padded/n, hdim)
        rows = w.shape[0]
        start = lax.axis_index(self.axis) * rows
        in_range = (ids >= start) & (ids < start + rows)
        local_ids = jnp.where(in_range, ids - start, 0)
        out = jnp.take(w, local_ids, axis=0, mode="clip")
        out = jnp.where(in_range[..., None], out, 0.0)
        if output_layout == "seq_sharded":
            return reduce_scatter(out, self.axis, scatter_axis=-2)
        return reduce_from(out, self.axis)        # sum partials across shards
