"""Vanilla (unsharded) transformer twin — the numerical-equivalence oracle.

The reference's full-model test imports a `VallinaTransformer` that does not
exist in its snapshot (`/root/reference/tests/test_transformers.py:14`,
SURVEY quirk #1); this module is that missing twin, done right: a completely
independent single-device implementation (no parallel layers, no collectives,
no shard_map) that consumes the SAME parameter pytree `Transformer.init`
produces. Equivalence tests train both on identical params/batches and assert
matching losses/gradients over many steps.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax import lax

from ..config import IGNORE_INDEX, ModelConfig, resolve_dtype
from ..ops.rope import apply_rotary, rope_tables

Params = Dict[str, Any]


def _rms_norm(scale: jax.Array, x: jax.Array, eps: float = 1e-5) -> jax.Array:
    xf = x.astype(jnp.float32)
    normed = xf * lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return scale.astype(x.dtype) * normed.astype(x.dtype)


def _linear(p: Params, x: jax.Array, dtype) -> jax.Array:
    y = x.astype(dtype) @ p["weight"].astype(dtype)
    if "bias" in p:
        y = y + p["bias"].astype(dtype)
    return y


@dataclass(frozen=True)
class VanillaTransformer:
    cfg: ModelConfig

    def forward(self, params: Params, input_ids: jax.Array,
                position_ids: jax.Array) -> jax.Array:
        cfg = self.cfg
        dtype = resolve_dtype(cfg.compute_dtype)
        h = cfg.head_dim

        emb = params["embedding"]["weight"]  # (vocab_padded, d); padded rows unused
        x = jnp.take(emb, input_ids, axis=0).astype(dtype)

        cos_t, sin_t = rope_tables(cfg.maxlen, h, cfg.rope_theta)
        cos = jnp.take(cos_t, position_ids, axis=0, mode="clip")
        sin = jnp.take(sin_t, position_ids, axis=0, mode="clip")

        def body(x, lp):
            b, t, d = x.shape
            y = _rms_norm(lp["norm1"]["scale"], x)
            q = _linear(lp["wq"], y, dtype)
            k = _linear(lp["wk"], y, dtype)
            v = _linear(lp["wv"], y, dtype)
            split = lambda z, nh: z.reshape(b, t, nh, h).transpose(0, 2, 1, 3)
            q = split(q, cfg.num_heads)
            k, v = split(k, cfg.kv_heads), split(v, cfg.kv_heads)
            q, k = apply_rotary(q, k, cos, sin)
            if cfg.kv_heads != cfg.num_heads:  # grouped-query attention
                rep = cfg.num_heads // cfg.kv_heads
                k, v = jnp.repeat(k, rep, axis=1), jnp.repeat(v, rep, axis=1)
            scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(h)
            mask = jnp.triu(jnp.ones((t, t), dtype=bool), k=1)
            scores = jnp.where(mask[None, None], jnp.asarray(-10000.0, scores.dtype), scores)
            probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(dtype)
            o = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
            o = o.transpose(0, 2, 1, 3).reshape(b, t, d)
            x = x + _linear(lp["wo"], o, dtype)

            y = _rms_norm(lp["norm2"]["scale"], x)
            g = _linear(lp["gate_proj"], y, dtype)
            u = _linear(lp["up_proj"], y, dtype)
            x = x + _linear(lp["down_proj"], jax.nn.silu(g) * u, dtype)
            return x, None

        x, _ = lax.scan(body, x, params["layers"])
        x = _rms_norm(params["norm"]["scale"], x)
        logits = _linear(params["lm_head"], x, dtype)
        vocab_padded = logits.shape[-1]
        if vocab_padded != cfg.vocab_size:
            col = jnp.arange(vocab_padded)
            logits = jnp.where(col[None, None, :] < cfg.vocab_size, logits,
                               jnp.asarray(-1e9, logits.dtype))
        return logits

    def loss(self, params: Params, input_ids: jax.Array, target_ids: jax.Array,
             position_ids: jax.Array) -> jax.Array:
        logits = self.forward(params, input_ids, position_ids).astype(jnp.float32)
        return _masked_ce(logits, target_ids)


def _masked_ce(logits: jax.Array, target_ids: jax.Array) -> jax.Array:
    valid = target_ids != IGNORE_INDEX
    tgt = jnp.where(valid, target_ids, 0)
    lse = jax.nn.logsumexp(logits, axis=-1)
    tgt_logit = jnp.take_along_axis(logits, tgt[..., None], axis=-1)[..., 0]
    token_loss = lse - tgt_logit
    loss_sum = jnp.sum(jnp.where(valid, token_loss, 0.0))
    count = jnp.sum(valid.astype(jnp.float32))
    return loss_sum / jnp.maximum(count, 1.0)


def _layer_norm(p: Params, x: jax.Array, eps: float = 1e-5) -> jax.Array:
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
    normed = ((xf - mean) * lax.rsqrt(var + eps)).astype(x.dtype)
    return p["scale"].astype(x.dtype) * normed + p["bias"].astype(x.dtype)


@dataclass(frozen=True)
class VanillaGPT2:
    """Unsharded oracle twin for the GPT-2 family (`models/gpt2.py`):
    LayerNorm + GELU(tanh) MLP + learned positions + tied embedding head.
    Independent implementation consuming the same parameter pytree
    `GPT2Transformer.init` produces."""

    cfg: ModelConfig

    def forward(self, params: Params, input_ids: jax.Array,
                position_ids: jax.Array) -> jax.Array:
        cfg = self.cfg
        dtype = resolve_dtype(cfg.compute_dtype)
        h = cfg.head_dim

        emb = params["embedding"]["weight"]      # (vocab_padded, d)
        x = jnp.take(emb, input_ids, axis=0)
        pos = jnp.take(params["pos_embedding"]["weight"], position_ids,
                       axis=0, mode="clip")
        x = (x + pos).astype(dtype)

        def body(x, lp):
            b, t, d = x.shape
            y = _layer_norm(lp["ln1"], x)
            q = _linear(lp["wq"], y, dtype)
            k = _linear(lp["wk"], y, dtype)
            v = _linear(lp["wv"], y, dtype)
            split = lambda z: z.reshape(b, t, cfg.num_heads, h).transpose(0, 2, 1, 3)
            q, k, v = split(q), split(k), split(v)
            scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(h)
            mask = jnp.triu(jnp.ones((t, t), dtype=bool), k=1)
            scores = jnp.where(mask[None, None],
                               jnp.asarray(-10000.0, scores.dtype), scores)
            probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(dtype)
            o = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
            o = o.transpose(0, 2, 1, 3).reshape(b, t, d)
            x = x + _linear(lp["wo"], o, dtype)

            y = _layer_norm(lp["ln2"], x)
            x = x + _linear(lp["proj"],
                            jax.nn.gelu(_linear(lp["fc"], y, dtype),
                                        approximate=True), dtype)
            return x, None

        x, _ = lax.scan(body, x, params["layers"])
        x = _layer_norm(params["norm"], x)
        logits = x @ emb.astype(dtype).T          # tied head
        vocab_padded = logits.shape[-1]
        if vocab_padded != cfg.vocab_size:
            col = jnp.arange(vocab_padded)
            logits = jnp.where(col[None, None, :] < cfg.vocab_size, logits,
                               jnp.asarray(-1e9, logits.dtype))
        return logits

    def loss(self, params: Params, input_ids: jax.Array, target_ids: jax.Array,
             position_ids: jax.Array) -> jax.Array:
        logits = self.forward(params, input_ids, position_ids).astype(jnp.float32)
        return _masked_ce(logits, target_ids)
