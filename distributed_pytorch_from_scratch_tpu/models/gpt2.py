"""GPT-2 model family: LayerNorm + GELU MLP + learned positions + TIED
vocab-parallel embeddings, on the same parallel primitives as the LLaMA
family.

The reference implements exactly one family (RoPE/RMSNorm/SwiGLU,
`/root/reference/models/model.py`); this module is a framework extension
demonstrating that the parallel layer/comm stack generalises: a second
architecture drops in with ~150 lines and inherits the whole loss / train /
checkpoint / mesh machinery unchanged.

Design notes:

* **Tied head, vocab-parallel both ways.** GPT-2 ties lm_head to the token
  embedding. The embedding is already row-sharded over 'tp'
  (`parallel/embedding.py`), so the tied head is simply
  `logits_local = x @ tok_emb_localᵀ` — the per-shard logits land in
  exactly the layout the vocab-parallel CE consumes. No extra collective,
  and the embedding weight receives BOTH gradient contributions (lookup and
  head) through plain autodiff.

* **Shared infrastructure by duck-typing.** `loss_shard`, `make_loss`,
  `make_forward` and `shardings` are borrowed directly from `Transformer`
  — they only touch `forward_shard`, `specs`, and a handful of static
  attributes, all of which this class provides. The train step builders,
  checkpointing, ZeRO-1 and the CLIs therefore work for this family with
  zero changes.

* **Megatron TP pattern identical to the LLaMA family**: wq/wk/wv + fc are
  column-parallel (`gather_output=False`), wo + proj row-parallel
  (`split_input=False`) — one all-reduce per sublayer per direction.

* Context/sequence parallelism are not wired for this family (cp_size is
  fixed at 1); attention runs the same flash/XLA kernels.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax import lax

from ..config import ModelConfig, resolve_dtype
from ..ops.attention import causal_attention
from ..parallel.embedding import VocabParallelEmbedding
from ..parallel.linear import ColumnParallelLinear, RowParallelLinear
from ..parallel.norm import LayerNorm
from ..runtime.prng import fold
from .transformer import NEG_INF, Transformer, remat_wrap

Params = Dict[str, Any]

INIT_STD = 0.02  # GPT-2's embedding/projection init scale


@dataclass(frozen=True)
class GPT2Transformer:
    """Static GPT-2 definition; params live in an explicit pytree."""

    cfg: ModelConfig
    tp_size: int = 1
    attn_impl: str = "auto"
    remat: "bool | str" = True
    # static attrs Transformer's borrowed methods consult; this family is
    # dp x tp only
    cp_size: int = 1
    cp_layout: str = "contiguous"
    sequence_parallel: bool = False
    pp_size: int = 1

    def __post_init__(self):
        cfg, tp = self.cfg, self.tp_size
        if self.remat not in (True, False, "dots"):
            raise ValueError(
                f"remat must be True, False or 'dots', got {self.remat!r}")
        if cfg.num_heads % tp != 0:
            raise ValueError(
                f"num_heads {cfg.num_heads} not divisible by tp_size {tp}")
        if cfg.attn_dim % tp != 0 or cfg.ffn_dim % tp != 0:
            raise ValueError(
                f"attn_dim {cfg.attn_dim} and ffn_dim {cfg.ffn_dim} must be "
                f"divisible by tp_size {tp}")
        if cfg.kv_heads != cfg.num_heads:
            raise ValueError("grouped-query attention (num_kv_heads) is a "
                             "llama-family feature; the gpt2 family is MHA")

    # ---- static properties ----

    # family hooks for the generic KV decoder (models/decode.py): learned
    # position embeddings instead of RoPE, LayerNorm module keys, MHA
    uses_rope = False
    attn_norm_key = "ln1"
    ffn_norm_key = "ln2"
    is_moe = False  # dense family; loss_shard and the decoder consult this

    @property
    def d(self) -> int:
        return self.cfg.attn_dim

    @property
    def max_decode_positions(self) -> int:
        """Learned position embeddings hard-cap the sequence at maxlen —
        unlike RoPE, there is no table to extend (decode callers clamp
        their buffers; see evaluate.greedy_decode)."""
        return self.cfg.maxlen

    @property
    def vocab_padded(self) -> int:
        return self.cfg.padded_vocab_size(self.tp_size)

    @property
    def num_local_heads(self) -> int:
        return self.cfg.num_heads // self.tp_size

    @functools.cached_property
    def embedding(self) -> VocabParallelEmbedding:
        return VocabParallelEmbedding(self.cfg.vocab_size, self.d,
                                      tp_size=self.tp_size,
                                      init_std=INIT_STD)

    @functools.cached_property
    def _mods(self) -> Dict[str, Any]:
        d, f = self.d, self.cfg.ffn_dim
        return {
            "ln1": LayerNorm(d),
            "wq": ColumnParallelLinear(d, d, gather_output=False),
            "wk": ColumnParallelLinear(d, d, gather_output=False),
            "wv": ColumnParallelLinear(d, d, gather_output=False),
            "wo": RowParallelLinear(d, d, split_input=False),
            "ln2": LayerNorm(d),
            "fc": ColumnParallelLinear(d, f, gather_output=False),
            "proj": RowParallelLinear(f, d, split_input=False),
        }

    @functools.cached_property
    def final_norm(self) -> LayerNorm:
        return LayerNorm(self.d)

    # ---- init / specs ----

    def init(self, key: jax.Array) -> Params:
        L = self.cfg.num_layers
        layer_keys = jax.random.split(fold(key, "layers"), L)

        def one_layer(k: jax.Array) -> Params:
            return {name: mod.init(fold(k, name))
                    for name, mod in self._mods.items()}

        return {
            "embedding": self.embedding.init(fold(key, "embedding")),
            "pos_embedding": {"weight": INIT_STD * jax.random.normal(
                fold(key, "pos"), (self.cfg.maxlen, self.d), jnp.float32)},
            "layers": jax.vmap(one_layer)(layer_keys),
            "norm": self.final_norm.init(fold(key, "norm")),
        }

    def specs(self) -> Params:
        from jax.sharding import PartitionSpec as P

        def stack(spec_dict: Params) -> Params:
            return jax.tree.map(lambda s: P(None, *s), spec_dict,
                                is_leaf=lambda x: isinstance(x, P))

        return {
            "embedding": self.embedding.specs(),
            "pos_embedding": {"weight": P(None, None)},
            "layers": {name: stack(mod.specs())
                       for name, mod in self._mods.items()},
            "norm": self.final_norm.specs(),
        }

    # ---- per-shard forward (inside shard_map) ----

    def _layer_body(self, x: jax.Array, lp: Params, dtype) -> jax.Array:
        m = self._mods
        h = self.cfg.head_dim
        b, t, _ = x.shape

        y = m["ln1"].apply(lp["ln1"], x)
        q = m["wq"].apply(lp["wq"], y, dtype)
        k = m["wk"].apply(lp["wk"], y, dtype)
        v = m["wv"].apply(lp["wv"], y, dtype)
        split = lambda z: z.reshape(b, t, self.num_local_heads, h).transpose(0, 2, 1, 3)
        o = causal_attention(split(q), split(k), split(v), impl=self.attn_impl)
        o = o.transpose(0, 2, 1, 3).reshape(b, t, self.num_local_heads * h)
        x = x + m["wo"].apply(lp["wo"], o, dtype)

        y = m["ln2"].apply(lp["ln2"], x)
        # gelu_new (tanh approximation), like GPT-2
        x = x + m["proj"].apply(lp["proj"],
                                jax.nn.gelu(m["fc"].apply(lp["fc"], y, dtype),
                                            approximate=True), dtype)
        return x

    def forward_shard(self, params: Params, input_ids: jax.Array,
                      position_ids: jax.Array) -> jax.Array:
        """(b_local, t) ids -> (b_local, t, vocab_padded / tp) LOCAL logits —
        the same per-shard contract as `Transformer.forward_shard`."""
        dtype = resolve_dtype(self.cfg.compute_dtype)
        x = self.embedding.apply(params["embedding"], input_ids)
        pos = jnp.take(params["pos_embedding"]["weight"], position_ids,
                       axis=0, mode="clip")
        x = (x + pos).astype(dtype)

        layer_fn = remat_wrap(self._layer_body, self.remat, static_argnums=(2,))

        def body(carry, lp):
            return layer_fn(carry, lp, dtype), None

        x, _ = lax.scan(body, x, params["layers"])
        x = self.final_norm.apply(params["norm"], x)

        # tied head: local logits against this shard's embedding rows
        w = params["embedding"]["weight"].astype(dtype)  # (vp/tp, d)
        logits = x @ w.T                                  # (b, t, vp/tp)

        if self.vocab_padded != self.cfg.vocab_size:
            local_v = self.vocab_padded // self.tp_size
            col = lax.axis_index("tp") * local_v + jnp.arange(local_v)
            logits = jnp.where(col[None, None, :] < self.cfg.vocab_size,
                               logits, jnp.asarray(NEG_INF, logits.dtype))
        return logits

    # ---- everything else is the shared machinery (see module docstring) ----

    @property
    def num_local_kv_heads(self) -> int:
        return self.num_local_heads  # MHA: the decoder's caches are full-size

    def _forward_with_aux(self, params: Params, input_ids: jax.Array,
                          position_ids: jax.Array,
                          head_layout: str = "replicated"):
        # head_layout is a pipeline concern; this family is pp_size == 1
        return self.forward_shard(params, input_ids, position_ids), None

    _zigzag = Transformer._zigzag
    loss_shard = Transformer.loss_shard
    make_forward = Transformer.make_forward
    make_loss = Transformer.make_loss
    shardings = Transformer.shardings
