"""GPT-2 model family: LayerNorm + GELU MLP + learned positions + TIED
vocab-parallel embeddings, on the same parallel primitives as the LLaMA
family.

The reference implements exactly one family (RoPE/RMSNorm/SwiGLU,
`/root/reference/models/model.py`); this module is a framework extension
demonstrating that the parallel layer/comm stack generalises: a second
architecture drops in with ~150 lines and inherits the whole loss / train /
checkpoint / mesh machinery unchanged.

Design notes:

* **Tied head, vocab-parallel both ways.** GPT-2 ties lm_head to the token
  embedding. The embedding is already row-sharded over 'tp'
  (`parallel/embedding.py`), so the tied head is simply
  `logits_local = x @ tok_emb_localᵀ` — the per-shard logits land in
  exactly the layout the vocab-parallel CE consumes. No extra collective,
  and the embedding weight receives BOTH gradient contributions (lookup and
  head) through plain autodiff.

* **Shared infrastructure by duck-typing.** `loss_shard`, `make_loss`,
  `make_forward` and `shardings` are borrowed directly from `Transformer`
  — they only touch `forward_shard`, `specs`, and a handful of static
  attributes, all of which this class provides. The train step builders,
  checkpointing, ZeRO-1 and the CLIs therefore work for this family with
  zero changes.

* **Megatron TP pattern identical to the LLaMA family**: wq/wk/wv + fc are
  column-parallel (`gather_output=False`), wo + proj row-parallel
  (`split_input=False`) — one all-reduce per sublayer per direction.

* Context parallelism (ring / Ulysses over 'cp'), Megatron sequence
  parallelism over 'tp' and the GPipe pipeline over 'pp' compose with this
  family exactly like the llama one — same collectives and the same
  (family-agnostic) microbatch schedule, no RoPE (positions are learned
  and enter at the embedding, so the cp shards just index their position
  slice).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax import lax

from ..config import ModelConfig, resolve_dtype
from ..ops.attention import causal_attention
from ..ops.collectives import gather_from
from ..ops.ring_attention import ring_attention, ulysses_attention
from ..parallel.embedding import VocabParallelEmbedding
from ..parallel.linear import ColumnParallelLinear, RowParallelLinear
from ..parallel.moe import MoEFFN
from ..parallel.norm import LayerNorm
from ..runtime.prng import fold
from ..ops.overlap import ag_matmul
from ..parallel.linear import apply_column_ring_fused
from .transformer import (NEG_INF, Transformer, remat_wrap, validate_cp,
                          validate_pp, validate_t_real, validate_tp_overlap)

Params = Dict[str, Any]

INIT_STD = 0.02  # GPT-2's embedding/projection init scale


@dataclass(frozen=True)
class GPT2Transformer:
    """Static GPT-2 definition; params live in an explicit pytree."""

    cfg: ModelConfig
    tp_size: int = 1
    attn_impl: str = "auto"
    remat: "bool | str" = True
    # context parallelism over 'cp', Megatron SP over 'tp', and the GPipe
    # pipeline over 'pp' — all borrowed from the llama family's machinery
    # (the microbatch schedule is Transformer._pipeline_layers, family-
    # agnostic via stage_fn)
    cp_size: int = 1
    cp_impl: str = "ring"
    cp_layout: str = "contiguous"
    sequence_parallel: bool = False
    # 'ring' = ring-decomposed collective matmuls for the SP tp collectives
    # — same contract as Transformer.tp_overlap (requires
    # sequence_parallel; the tied head rings too)
    tp_overlap: str = "off"
    pp_size: int = 1
    pp_microbatches: int = 0
    pp_remat_steps: bool = False
    pp_schedule: str = "gpipe"   # or 'interleaved' (virtual stages);
    pp_virtual: int = 2          # see Transformer.pp_schedule
    # Expert parallelism (with cfg.num_experts > 0): the gelu MLP swaps for
    # the same routed-expert sublayer the llama family uses
    # (parallel/moe.py — SwiGLU experts; documented design choice, see
    # _mods). VERDICT r3 #5.
    ep_size: int = 1
    # Pad-aware sequence bucketing — same contract as
    # Transformer.attn_t_real (real token count inside a bucket-padded
    # batch; attention skips the pad tiles, CE masks the pad targets).
    attn_t_real: "int | None" = None
    # ZeRO-3 per-layer param gather — same contract as
    # Transformer.zero3_axis (set only by training/zero.build_zero3_grad_fn
    # on its private model copy; every other path leaves it None).
    zero3_axis: "str | None" = None

    def __post_init__(self):
        cfg, tp = self.cfg, self.tp_size
        if self.remat not in (True, False, "dots"):
            raise ValueError(
                f"remat must be True, False or 'dots', got {self.remat!r}")
        if cfg.num_heads % tp != 0:
            raise ValueError(
                f"num_heads {cfg.num_heads} not divisible by tp_size {tp}")
        if cfg.attn_dim % tp != 0 or cfg.ffn_dim % tp != 0:
            raise ValueError(
                f"attn_dim {cfg.attn_dim} and ffn_dim {cfg.ffn_dim} must be "
                f"divisible by tp_size {tp}")
        if cfg.kv_heads != cfg.num_heads:
            raise ValueError("grouped-query attention (num_kv_heads) is a "
                             "llama-family feature; the gpt2 family is MHA "
                             "(real GPT-2 has none — documented choice)")
        if not cfg.num_experts and self.ep_size > 1:
            raise ValueError("ep_size > 1 requires cfg.num_experts > 0 "
                             "(a dense model has nothing to shard over 'ep'; "
                             "use dp for a pure data axis)")
        validate_cp(cfg, tp, self.cp_size, self.cp_impl, self.cp_layout)
        validate_tp_overlap(self.tp_overlap, self.sequence_parallel,
                            cfg.num_experts)
        validate_pp(cfg.num_layers, self.pp_size, self.pp_microbatches,
                    self.pp_schedule, self.pp_virtual)
        validate_t_real(self.attn_t_real, self.cp_size, cfg.num_experts)

    # ---- static properties ----

    # family hooks for the generic KV decoder (models/decode.py): learned
    # position embeddings instead of RoPE, LayerNorm module keys, MHA
    uses_rope = False
    attn_norm_key = "ln1"
    ffn_norm_key = "ln2"

    @property
    def is_moe(self) -> bool:
        # loss_shard, _pipeline_layers and the decoder consult this
        return self.cfg.num_experts > 0

    @property
    def d(self) -> int:
        return self.cfg.attn_dim

    @property
    def max_decode_positions(self) -> int:
        """Learned position embeddings hard-cap the sequence at maxlen —
        unlike RoPE, there is no table to extend (decode callers clamp
        their buffers; see evaluate.greedy_decode)."""
        return self.cfg.maxlen

    @property
    def vocab_padded(self) -> int:
        return self.cfg.padded_vocab_size(self.tp_size)

    @property
    def num_local_heads(self) -> int:
        return self.cfg.num_heads // self.tp_size

    @functools.cached_property
    def embedding(self) -> VocabParallelEmbedding:
        return VocabParallelEmbedding(self.cfg.vocab_size, self.d,
                                      tp_size=self.tp_size,
                                      init_std=INIT_STD)

    @functools.cached_property
    def _mods(self) -> Dict[str, Any]:
        d, f = self.d, self.cfg.ffn_dim
        ov = self.tp_overlap
        mods = {
            "ln1": LayerNorm(d),
            # wq/wk/wv stay overlap='off': the fused ring in _layer_body
            # covers them on ONE shared ring (shared-gather byte parity)
            "wq": ColumnParallelLinear(d, d, gather_output=False),
            "wk": ColumnParallelLinear(d, d, gather_output=False),
            "wv": ColumnParallelLinear(d, d, gather_output=False),
            "wo": RowParallelLinear(d, d, split_input=False, overlap=ov),
            "ln2": LayerNorm(d),
        }
        if self.is_moe:
            # The SAME routed-expert sublayer as the llama family
            # (parallel/moe.py). The experts are SwiGLU internally — a
            # deliberate reuse: the MoE machinery (router, capacity
            # dispatch, ep all_to_all, tp-sharded expert einsums, aux
            # losses) is activation-agnostic, and the trunk stays pure
            # GPT-2 (LayerNorm, learned positions, tied head).
            mods["moe"] = MoEFFN(
                d, f, self.cfg.num_experts, top_k=self.cfg.moe_top_k,
                capacity_factor=self.cfg.moe_capacity_factor,
                ep_size=self.ep_size, tp_size=self.tp_size)
        else:
            mods.update({
                "fc": ColumnParallelLinear(d, f, gather_output=False,
                                           overlap=ov),
                "proj": RowParallelLinear(f, d, split_input=False,
                                          overlap=ov),
            })
        return mods

    @functools.cached_property
    def final_norm(self) -> LayerNorm:
        return LayerNorm(self.d)

    # ---- init / specs ----

    def init(self, key: jax.Array) -> Params:
        L = self.cfg.num_layers
        layer_keys = jax.random.split(fold(key, "layers"), L)

        def one_layer(k: jax.Array) -> Params:
            return {name: mod.init(fold(k, name))
                    for name, mod in self._mods.items()}

        layers = jax.vmap(one_layer)(layer_keys)
        if self._interleaved:
            layers = self._layers_to_schedule(layers)
        return {
            "embedding": self.embedding.init(fold(key, "embedding")),
            "pos_embedding": {"weight": INIT_STD * jax.random.normal(
                fold(key, "pos"), (self.cfg.maxlen, self.d), jnp.float32)},
            "layers": layers,
            "norm": self.final_norm.init(fold(key, "norm")),
        }

    def specs(self) -> Params:
        from jax.sharding import PartitionSpec as P

        lead = "pp" if self.pp_size > 1 else None

        def stack(spec_dict: Params) -> Params:
            # stacked num_layers axis: sharded over 'pp' when pipelining
            # ((V, pp, Lv) dim-1 for the interleaved schedule)
            if self._interleaved:
                return jax.tree.map(lambda s: P(None, "pp", None, *s),
                                    spec_dict,
                                    is_leaf=lambda x: isinstance(x, P))
            return jax.tree.map(lambda s: P(lead, *s), spec_dict,
                                is_leaf=lambda x: isinstance(x, P))

        return {
            "embedding": self.embedding.specs(),
            "pos_embedding": {"weight": P(None, None)},
            "layers": {name: stack(mod.specs())
                       for name, mod in self._mods.items()},
            "norm": self.final_norm.specs(),
        }

    # ---- per-shard forward (inside shard_map) ----

    def _layer_body(self, x: jax.Array, lp: Params, pos: jax.Array,
                    dtype, live=None) -> jax.Array:
        """One GPT-2 block. `live` is the pp x ring-CP bubble gate — same
        contract as `Transformer._layer_body` (the shared
        `_live_gated_ring` wraps the dense segments in lax.cond while the
        ring's ppermutes run unconditionally)."""
        if self.zero3_axis:
            # ZeRO-3 per-layer gather — same contract as
            # Transformer._layer_body (inside remat; transpose
            # reduce-scatters the weight grads to this rank's shard)
            from ..training.zero import zero3_layer_gather
            lp = zero3_layer_gather(self, lp, self.zero3_axis)
        m = self._mods
        h = self.cfg.head_dim
        # sequence parallelism: x is (b, t/tp, d) between sublayers; the
        # norm output is gathered ONCE per sublayer and shared by the
        # projections, row-linear outputs reduce-scatter back (the same
        # Megatron SP pattern as Transformer._layer_body)
        sp = self.sequence_parallel
        # ring overlap: the sublayer gather never materialises — the fused
        # ring collective matmul consumes the seq-sharded activation (same
        # contract as Transformer._layer_body)
        ring_ov = sp and self.tp_overlap in ("ring", "ring_q")
        maybe_gather = ((lambda z: gather_from(z, "tp", tiled_axis=-2))
                        if sp and not ring_ov else (lambda z: z))
        in_layout = ("seq_sharded" if ring_ov
                     else "gathered" if sp else "replicated")
        out_layout = "seq_sharded" if sp else "replicated"
        b = x.shape[0]
        t = pos.shape[1]  # full (cp-local) sequence length, not x.shape[1]

        def qkv(x):
            y = maybe_gather(m["ln1"].apply(lp["ln1"], x))
            if ring_ov:
                q, k, v = apply_column_ring_fused(
                    (lp["wq"], lp["wk"], lp["wv"]), y, dtype,
                    quantized=self.tp_overlap == "ring_q")
            else:
                q = m["wq"].apply(lp["wq"], y, dtype, input_layout=in_layout)
                k = m["wk"].apply(lp["wk"], y, dtype, input_layout=in_layout)
                v = m["wv"].apply(lp["wv"], y, dtype, input_layout=in_layout)
            split = lambda z: z.reshape(
                b, t, self.num_local_heads, h).transpose(0, 2, 1, 3)
            return split(q), split(k), split(v)

        def attn_out(args):
            x, o = args
            o = o.transpose(0, 2, 1, 3).reshape(b, t,
                                                self.num_local_heads * h)
            x = x + m["wo"].apply(lp["wo"], o, dtype,
                                  output_layout=out_layout)

            y = maybe_gather(m["ln2"].apply(lp["ln2"], x))
            if self.is_moe:
                ff, aux = m["moe"].apply(lp["moe"], y, dtype)
                if sp:
                    # Same SP composition as the llama body: the router saw
                    # the tp-gathered tokens, ff is full-value on every
                    # rank — keep this rank's sequence slice so the
                    # residual stays seq-sharded.
                    tl = ff.shape[1] // self.tp_size
                    ff = lax.dynamic_slice_in_dim(
                        ff, lax.axis_index("tp") * tl, tl, axis=1)
                return x + ff, aux
            # gelu_new (tanh approximation), like GPT-2
            x = x + m["proj"].apply(lp["proj"],
                                    jax.nn.gelu(m["fc"].apply(
                                        lp["fc"], y, dtype,
                                        input_layout=in_layout),
                                        approximate=True), dtype,
                                    output_layout=out_layout)
            return x, None

        # ring overlap: dense segments run even on bubble steps (their tp
        # ppermutes cannot hide in a stage-divergent cond — see
        # Transformer._layer_body)
        if live is None or ring_ov:
            q, k, v = qkv(x)
            if self.cp_size > 1:
                if self.cp_impl == "ring":
                    o = ring_attention(q, k, v, pos, axis="cp",
                                       impl=self.attn_impl, live=live)
                else:
                    o = ulysses_attention(q, k, v, axis="cp",
                                          impl=self.attn_impl)
            else:
                o = causal_attention(q, k, v, impl=self.attn_impl,
                                     t_real=self._t_real(t))
            return attn_out((x, o))
        return self._live_gated_ring(x, qkv, attn_out, pos, live)

    def forward_shard(self, params: Params, input_ids: jax.Array,
                      position_ids: jax.Array,
                      head_layout: str = "replicated") -> jax.Array:
        """(b_local, t) ids -> (b_local, t, vocab_padded / tp) LOCAL logits —
        the same per-shard contract as `Transformer.forward_shard`
        (`head_layout` follows the same pipeline semantics)."""
        logits, _ = self._forward_with_aux(params, input_ids, position_ids,
                                           head_layout=head_layout)
        return logits

    def _forward_with_aux(self, params: Params, input_ids: jax.Array,
                          position_ids: jax.Array,
                          head_layout: str = "replicated"):
        """forward_shard + MoE aux-stat sums (None for dense) — the same
        contract as `Transformer._forward_with_aux`, which the borrowed
        `loss_shard` consumes."""
        dtype = resolve_dtype(self.cfg.compute_dtype)
        sp = self.sequence_parallel
        if sp and input_ids.shape[1] % self.tp_size != 0:
            raise ValueError(
                f"sequence_parallel needs the (cp-local) sequence length "
                f"{input_ids.shape[1]} divisible by tp_size {self.tp_size}")
        x = self.embedding.apply(params["embedding"], input_ids,
                                 output_layout="seq_sharded" if sp
                                 else "replicated")
        pos_emb = jnp.take(params["pos_embedding"]["weight"], position_ids,
                           axis=0, mode="clip")
        if sp:
            # embedding output is seq-sharded; slice the position rows the
            # same way before the add
            tl = pos_emb.shape[1] // self.tp_size
            pos_emb = lax.dynamic_slice_in_dim(
                pos_emb, lax.axis_index("tp") * tl, tl, axis=1)
        x = (x + pos_emb).astype(dtype)

        layer_fn = remat_wrap(self._layer_body, self.remat, static_argnums=(3,))

        if self.pp_size > 1:
            def stage_fn(z, layers, pos_m, live=None):
                def body(carry, lp):
                    return layer_fn(carry, lp, pos_m, dtype, live)
                z, auxs = lax.scan(body, z, layers)
                aux = (jax.tree.map(lambda a: jnp.sum(a, axis=0), auxs)
                       if self.is_moe else None)
                return z, aux

            x, aux = self._pipeline_layers(stage_fn, x, params["layers"],
                                           (position_ids,),
                                           head_layout=head_layout)
        else:
            def body(carry, lp):
                return layer_fn(carry, lp, position_ids, dtype)

            x, auxs = lax.scan(body, x, params["layers"])
            aux = (jax.tree.map(lambda a: jnp.sum(a, axis=0), auxs)
                   if self.is_moe else None)
        x = self.final_norm.apply(params["norm"], x)
        # tied head: local logits against this shard's embedding rows
        w = params["embedding"]["weight"].astype(dtype)  # (vp/tp, d)
        if sp and self.tp_overlap in ("ring", "ring_q"):
            # ring collective matmul for the tied head too: the gather's
            # hops hide under the per-chunk logits dots, and the VJP's
            # reverse ring reduce-scatters the head's input cotangent
            logits = ag_matmul(x.astype(dtype), (w.T,), "tp",
                               self.tp_overlap == "ring_q")[0]
        else:
            if sp:
                # the tied head consumes full-sequence activations; the
                # gather's transpose reduce-scatters the input cotangent
                x = gather_from(x, "tp", tiled_axis=-2)
            logits = x.astype(dtype) @ w.T                # (b, t, vp/tp)

        if self.vocab_padded != self.cfg.vocab_size:
            local_v = self.vocab_padded // self.tp_size
            col = lax.axis_index("tp") * local_v + jnp.arange(local_v)
            logits = jnp.where(col[None, None, :] < self.cfg.vocab_size,
                               logits, jnp.asarray(NEG_INF, logits.dtype))
        return logits, aux

    # ---- everything else is the shared machinery (see module docstring) ----

    @property
    def num_local_kv_heads(self) -> int:
        return self.num_local_heads  # MHA: the decoder's caches are full-size

    _t_real = Transformer._t_real
    _pipeline_layers = Transformer._pipeline_layers
    _pipeline_interleaved = Transformer._pipeline_interleaved
    _pp_vary_axes = Transformer._pp_vary_axes
    _live_gated_ring = Transformer._live_gated_ring
    _interleaved = Transformer._interleaved
    _layers_to_schedule = Transformer._layers_to_schedule
    _layers_to_canonical = Transformer._layers_to_canonical
    to_canonical = Transformer.to_canonical
    from_canonical = Transformer.from_canonical
    canonical_specs = Transformer.canonical_specs

    _zigzag = Transformer._zigzag
    _token_ce = Transformer._token_ce
    loss_shard = Transformer.loss_shard
    doc_loss_shard = Transformer.doc_loss_shard
    make_forward = Transformer.make_forward
    make_loss = Transformer.make_loss
    make_doc_loss = Transformer.make_doc_loss
    shardings = Transformer.shardings
